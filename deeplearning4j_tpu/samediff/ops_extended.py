"""Extended SameDiff op families.

Reference: the long tail of libnd4j declarable ops / nd4j op classes
(SURVEY.md §2.1 "Declarable ops (~500)", §2.2 "op class hierarchy") beyond
the core closure in ops.py: special functions, extended reductions and
index accumulations, segment ops, sorting/top-k, spatial rearrangement,
conv1d/3d + transpose conv + pooling variants, cell-level RNN primitives,
color-space transforms, the full loss family, extended linalg, random
distributions, and numeric hygiene ops (clip-by-norm family, moments).

Same registration contract as ops.py: jnp-thin pure functions in SD_OPS —
XLA fuses; nothing here owns a kernel. Ops whose reference semantics need
dynamic output shapes (unique, where-without-branches) take the XLA-honest
form: static ``k``/``num_segments``/size attrs, as the TPU compilation
model requires (SURVEY.md §7).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .ops import sd_op

# ---- special functions -----------------------------------------------------
sd_op("erfinv")(jax.scipy.special.erfinv)
sd_op("lgamma")(jax.scipy.special.gammaln)
sd_op("digamma")(jax.scipy.special.digamma)
sd_op("betainc")(jax.scipy.special.betainc)
sd_op("igamma")(jax.scipy.special.gammainc)
sd_op("igammac")(jax.scipy.special.gammaincc)
sd_op("log_sigmoid")(jax.nn.log_sigmoid)
sd_op("exp2")(jnp.exp2)
sd_op("log10")(jnp.log10)
sd_op("rint")(jnp.rint)
sd_op("trunc")(jnp.trunc)
sd_op("frac")(lambda x: x - jnp.trunc(x))
sd_op("fmod")(jnp.fmod)
sd_op("hypot")(jnp.hypot)
sd_op("logaddexp")(jnp.logaddexp)
sd_op("xlogy")(lambda x, y: jnp.where(x == 0.0, 0.0, x * jnp.log(y)))
sd_op("xdivy")(lambda x, y: jnp.where(x == 0.0, 0.0, x / y))
sd_op("lerp")(lambda a, b, w=0.5: a + w * (b - a))
sd_op("logit")(lambda x, eps=1e-7: jnp.log(jnp.clip(x, eps, 1 - eps)
                                           / (1 - jnp.clip(x, eps, 1 - eps))))
sd_op("safe_divide")(lambda a, b: jnp.where(b == 0.0, 0.0, a / b))
sd_op("nan_to_num")(lambda x, nan=0.0, posinf=None, neginf=None:
                    jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf))
sd_op("replace_nans")(lambda x, value=0.0: jnp.nan_to_num(x, nan=value))

# ---- extended reductions / index accumulations -----------------------------
sd_op("logsumexp")(lambda x, axis=None, keepdims=False:
                   jax.scipy.special.logsumexp(
                       x, axis=None if axis is None else tuple(
                           int(a) for a in np.atleast_1d(axis)),
                       keepdims=bool(keepdims)))
sd_op("reduce_median")(lambda x, axis=None, keepdims=False:
                       jnp.median(x, axis=None if axis is None else tuple(
                           int(a) for a in np.atleast_1d(axis)),
                           keepdims=bool(keepdims)))
sd_op("percentile")(lambda x, q=50.0, axis=None:
                    jnp.percentile(x, q, axis=None if axis is None else tuple(
                        int(a) for a in np.atleast_1d(axis))))
sd_op("count_nonzero")(lambda x, axis=None:
                       jnp.count_nonzero(x, axis=None if axis is None else
                                         tuple(int(a) for a in np.atleast_1d(axis))))
sd_op("count_zero")(lambda x, axis=None:
                    jnp.sum(x == 0, axis=None if axis is None else
                            tuple(int(a) for a in np.atleast_1d(axis))))
sd_op("iamax")(lambda x, axis=-1: jnp.argmax(jnp.abs(x), axis=int(axis)))
sd_op("iamin")(lambda x, axis=-1: jnp.argmin(jnp.abs(x), axis=int(axis)))
sd_op("amax")(lambda x, axis=None, keepdims=False:
              jnp.max(jnp.abs(x), axis=None if axis is None else tuple(
                  int(a) for a in np.atleast_1d(axis)), keepdims=keepdims))
sd_op("amin")(lambda x, axis=None, keepdims=False:
              jnp.min(jnp.abs(x), axis=None if axis is None else tuple(
                  int(a) for a in np.atleast_1d(axis)), keepdims=keepdims))
sd_op("amean")(lambda x, axis=None, keepdims=False:
               jnp.mean(jnp.abs(x), axis=None if axis is None else tuple(
                   int(a) for a in np.atleast_1d(axis)), keepdims=keepdims))
sd_op("asum")(lambda x, axis=None, keepdims=False:
              jnp.sum(jnp.abs(x), axis=None if axis is None else tuple(
                  int(a) for a in np.atleast_1d(axis)), keepdims=keepdims))


@sd_op("entropy")
def _entropy(x, axis=None):
    ax = None if axis is None else tuple(int(a) for a in np.atleast_1d(axis))
    return -jnp.sum(x * jnp.log(jnp.clip(x, 1e-12, None)), axis=ax)


@sd_op("shannon_entropy")
def _shannon_entropy(x, axis=None):
    ax = None if axis is None else tuple(int(a) for a in np.atleast_1d(axis))
    return -jnp.sum(x * jnp.log2(jnp.clip(x, 1e-12, None)), axis=ax)


sd_op("log_entropy")(lambda x, axis=None: jnp.log(_entropy(x, axis)))
sd_op("squared_norm")(lambda x, axis=None, keepdims=False:
                      jnp.sum(jnp.square(x), axis=None if axis is None else
                              tuple(int(a) for a in np.atleast_1d(axis)),
                              keepdims=keepdims))


@sd_op("moments")
def _moments(x, axis=None, keepdims=False):
    ax = None if axis is None else tuple(int(a) for a in np.atleast_1d(axis))
    mean = jnp.mean(x, axis=ax, keepdims=keepdims)
    var = jnp.var(x, axis=ax, keepdims=keepdims)
    return mean, var


@sd_op("normalize_moments")
def _normalize_moments(counts, mean_ss, variance_ss, shift=0.0):
    mean = mean_ss / counts + shift
    variance = variance_ss / counts - jnp.square(mean_ss / counts)
    return mean, variance


@sd_op("standardize")
def _standardize(x, axis=-1, eps=1e-8):
    ax = tuple(int(a) for a in np.atleast_1d(axis))
    mean = jnp.mean(x, axis=ax, keepdims=True)
    std = jnp.std(x, axis=ax, keepdims=True)
    return (x - mean) / (std + eps)


@sd_op("confusion_matrix")
def _confusion_matrix(labels, predictions, num_classes=None, weights=None):
    n = int(num_classes)
    idx = labels.astype(jnp.int32) * n + predictions.astype(jnp.int32)
    w = jnp.ones_like(idx, jnp.float32) if weights is None else weights
    return jnp.zeros(n * n, w.dtype).at[idx].add(w).reshape(n, n)


# ---- segment ops -----------------------------------------------------------
def _seg(reducer):
    def op(data, segment_ids, num_segments=None):
        return reducer(data, segment_ids.astype(jnp.int32),
                       num_segments=int(num_segments))

    return op


sd_op("segment_sum")(_seg(jax.ops.segment_sum))
sd_op("segment_prod")(_seg(jax.ops.segment_prod))
sd_op("segment_max")(_seg(jax.ops.segment_max))
sd_op("segment_min")(_seg(jax.ops.segment_min))
sd_op("unsorted_segment_sum")(_seg(jax.ops.segment_sum))
sd_op("unsorted_segment_prod")(_seg(jax.ops.segment_prod))
sd_op("unsorted_segment_max")(_seg(jax.ops.segment_max))
sd_op("unsorted_segment_min")(_seg(jax.ops.segment_min))


@sd_op("segment_mean")
def _segment_mean(data, segment_ids, num_segments=None):
    ids = segment_ids.astype(jnp.int32)
    n = int(num_segments)
    s = jax.ops.segment_sum(data, ids, num_segments=n)
    c = jax.ops.segment_sum(jnp.ones_like(data), ids, num_segments=n)
    return s / jnp.maximum(c, 1.0)


sd_op("unsorted_segment_mean")(_segment_mean)


# ---- scatter family (completing update/add from ops.py) --------------------
sd_op("scatter_sub")(lambda ref, indices, updates:
                     ref.at[indices.astype(jnp.int32)].add(-updates))
sd_op("scatter_mul")(lambda ref, indices, updates:
                     ref.at[indices.astype(jnp.int32)].multiply(updates))
sd_op("scatter_div")(lambda ref, indices, updates:
                     ref.at[indices.astype(jnp.int32)].divide(updates))
sd_op("scatter_max")(lambda ref, indices, updates:
                     ref.at[indices.astype(jnp.int32)].max(updates))
sd_op("scatter_min")(lambda ref, indices, updates:
                     ref.at[indices.astype(jnp.int32)].min(updates))


# ---- sorting / top-k -------------------------------------------------------
sd_op("sort")(lambda x, axis=-1, descending=False:
              -jnp.sort(-x, axis=int(axis)) if descending
              else jnp.sort(x, axis=int(axis)))
sd_op("argsort")(lambda x, axis=-1, descending=False:
                 jnp.argsort(-x, axis=int(axis)) if descending
                 else jnp.argsort(x, axis=int(axis)))


@sd_op("top_k")
def _top_k(x, k=1, sorted=True):
    values, indices = lax.top_k(x, int(k))
    return values, indices


@sd_op("in_top_k")
def _in_top_k(predictions, targets, k=1):
    _, idx = lax.top_k(predictions, int(k))
    return jnp.any(idx == targets.astype(idx.dtype)[:, None], axis=-1)


@sd_op("unique_with_counts_padded")
def _unique_padded(x, size=None):
    """XLA-honest unique: fixed ``size`` output padded with the first value
    (the reference's dynamic-shape unique cannot compile on TPU)."""
    vals, counts = jnp.unique(x, return_counts=True, size=int(size))
    return vals, counts


# ---- spatial rearrangement -------------------------------------------------
@sd_op("space_to_depth")
def _space_to_depth(x, block_size=2, data_format="NHWC"):
    b = int(block_size)
    if str(data_format).upper() == "NHWC":
        n, h, w, c = x.shape
        x = x.reshape(n, h // b, b, w // b, b, c)
        return x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // b, w // b, c * b * b)
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // b, b, w // b, b)
    return x.transpose(0, 3, 5, 1, 2, 4).reshape(n, c * b * b, h // b, w // b)


@sd_op("depth_to_space")
def _depth_to_space(x, block_size=2, data_format="NHWC"):
    b = int(block_size)
    if str(data_format).upper() == "NHWC":
        n, h, w, c = x.shape
        x = x.reshape(n, h, w, b, b, c // (b * b))
        return x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h * b, w * b, c // (b * b))
    n, c, h, w = x.shape
    x = x.reshape(n, b, b, c // (b * b), h, w)
    return x.transpose(0, 3, 4, 1, 5, 2).reshape(n, c // (b * b), h * b, w * b)


@sd_op("batch_to_space")
def _batch_to_space(x, block_shape=None, crops=None):
    bs = [int(v) for v in block_shape]
    crops = [(int(a), int(b)) for a, b in (crops or [(0, 0)] * len(bs))]
    n = x.shape[0] // int(np.prod(bs))
    spatial = x.shape[1:1 + len(bs)]
    rest = x.shape[1 + len(bs):]
    t = x.reshape(tuple(bs) + (n,) + spatial + rest)
    perm = [len(bs)]
    for i in range(len(bs)):
        perm += [len(bs) + 1 + i, i]
    perm += list(range(2 * len(bs) + 1, t.ndim))
    t = t.transpose(perm)
    out_spatial = tuple(s * b for s, b in zip(spatial, bs))
    t = t.reshape((n,) + out_spatial + rest)
    slices = [slice(None)] + [slice(c0, dim - c1) for (c0, c1), dim in
                              zip(crops, out_spatial)] + [slice(None)] * len(rest)
    return t[tuple(slices)]


@sd_op("space_to_batch")
def _space_to_batch(x, block_shape=None, paddings=None):
    bs = [int(v) for v in block_shape]
    pads = [(int(a), int(b)) for a, b in (paddings or [(0, 0)] * len(bs))]
    full_pads = [(0, 0)] + pads + [(0, 0)] * (x.ndim - 1 - len(bs))
    x = jnp.pad(x, full_pads)
    n = x.shape[0]
    spatial = x.shape[1:1 + len(bs)]
    rest = x.shape[1 + len(bs):]
    shape = (n,)
    for s, b in zip(spatial, bs):
        shape += (s // b, b)
    shape += rest
    t = x.reshape(shape)
    perm = []
    for i in range(len(bs)):
        perm.append(2 + 2 * i)
    perm.append(0)
    for i in range(len(bs)):
        perm.append(1 + 2 * i)
    perm += list(range(1 + 2 * len(bs), t.ndim))
    t = t.transpose(perm)
    return t.reshape((n * int(np.prod(bs)),) +
                     tuple(s // b for s, b in zip(spatial, bs)) + rest)


sd_op("repeat")(lambda x, repeats=1, axis=0:
                jnp.repeat(x, int(repeats), axis=int(axis)))
sd_op("roll")(lambda x, shift=1, axis=None:
              jnp.roll(x, int(shift), None if axis is None else int(axis)))
sd_op("meshgrid")(lambda *xs, indexing="xy": jnp.meshgrid(*xs, indexing=indexing))
sd_op("linspace")(lambda start=0.0, stop=1.0, num=50:
                  jnp.linspace(float(start), float(stop), int(num)))
sd_op("triu")(lambda x, k=0: jnp.triu(x, int(k)))
sd_op("tril")(lambda x, k=0: jnp.tril(x, int(k)))
sd_op("dynamic_partition_padded")(
    lambda data, partitions, num_partitions=2: tuple(
        jnp.where((partitions == i)[(...,) + (None,) * (data.ndim - partitions.ndim)],
                  data, 0)
        for i in range(int(num_partitions))))


@sd_op("histogram_fixed_width")
def _histogram_fixed_width(x, value_range=None, nbins=100):
    lo, hi = float(value_range[0]), float(value_range[1])
    return jnp.histogram(jnp.clip(x, lo, hi), bins=int(nbins),
                         range=(lo, hi))[0]


@sd_op("bincount")
def _bincount(x, minlength=0, maxlength=None, weights=None,
              binary_output=False):
    """XLA-honest bincount: output length must be static, so a positive
    ``minlength``/``maxlength`` is REQUIRED (values >= length are dropped,
    jnp semantics). The reference's grow-to-max(x)+1 behavior is a dynamic
    shape and cannot compile. ``binary_output`` gives 0/1 presence
    indicators (TF DenseBincount semantics)."""
    length = int(maxlength if maxlength else minlength)
    if length <= 0:
        raise ValueError(
            "bincount needs minlength or maxlength > 0 (static output "
            "shape); values >= length are dropped")
    if x.ndim == 2:  # TF DenseBincount per-row semantics: [B, N] -> [B, size]
        ids = x.astype(jnp.int32)
        valid = (ids >= 0) & (ids < length)
        w = jnp.where(valid, jnp.ones_like(ids, jnp.float32)
                      if weights is None else weights, 0)
        b = x.shape[0]
        off = jnp.arange(b, dtype=jnp.int32)[:, None] * length
        flat_ids = jnp.clip(ids, 0, length - 1) + off
        counts = jnp.zeros(b * length, w.dtype).at[
            flat_ids.reshape(-1)].add(w.reshape(-1)).reshape(b, length)
    else:
        counts = jnp.bincount(
            x.astype(jnp.int32).reshape(-1),
            weights=None if weights is None else weights.reshape(-1),
            length=length)
    return jnp.minimum(counts, 1) if binary_output else counts


# ---- conv/pool variants ----------------------------------------------------
@sd_op("conv1d")
def _conv1d(x, w, bias=None, stride=1, padding="SAME"):
    """x [N, W, C], w [kW, C, out] (TF conv1d convention)."""
    y = lax.conv_general_dilated(
        x, w, window_strides=(int(stride),), padding=str(padding).upper(),
        dimension_numbers=("NWC", "WIO", "NWC"))
    return y if bias is None else y + bias


@sd_op("conv3d")
def _conv3d(x, w, bias=None, strides=(1, 1, 1), padding="SAME",
            dilations=(1, 1, 1)):
    """x [N, D, H, W, C], w [kD, kH, kW, C, out] (TF conv3d NDHWC)."""
    y = lax.conv_general_dilated(
        x, w, window_strides=tuple(int(s) for s in strides),
        padding=str(padding).upper(),
        rhs_dilation=tuple(int(d) for d in dilations),
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
    return y if bias is None else y + bias


@sd_op("deconv2d")
def _deconv2d(x, w, bias=None, strides=(1, 1), padding="SAME",
              data_format="NHWC"):
    """Transpose conv (reference: deconv2d). ``w`` is the FORWARD-conv
    kernel [kH, kW, out, in] (TF deconv convention): the op is the
    gradient of that conv, matching torch.conv_transpose2d semantics
    (spatial flip included via transpose_kernel)."""
    df = str(data_format).upper()
    spec = "HWIO"  # I slot holds out-channels, O slot in-channels (gradient)
    y = lax.conv_transpose(
        x, w, strides=tuple(int(s) for s in strides),
        padding=str(padding).upper(),
        dimension_numbers=(df, spec, df), transpose_kernel=True)
    return y if bias is None else (
        y + (bias if df == "NHWC" else bias[:, None, None]))


def _pool_nd(x, kernel, strides, padding, reducer, init, spatial_dims):
    window = [1] * x.ndim
    strd = [1] * x.ndim
    for d, k, s in zip(spatial_dims, kernel, strides):
        window[d] = int(k)
        strd[d] = int(s)
    return lax.reduce_window(x, init, reducer, tuple(window), tuple(strd),
                             str(padding).upper())


@sd_op("max_pool1d")
def _max_pool1d(x, kernel=2, strides=2, padding="VALID"):
    return _pool_nd(x, [kernel], [strides], padding, lax.max, -jnp.inf, [1])


@sd_op("avg_pool1d")
def _avg_pool1d(x, kernel=2, strides=2, padding="VALID"):
    s = _pool_nd(x, [kernel], [strides], padding, lax.add, 0.0, [1])
    c = _pool_nd(jnp.ones_like(x), [kernel], [strides], padding, lax.add, 0.0, [1])
    return s / c


@sd_op("max_pool3d")
def _max_pool3d(x, kernel=(2, 2, 2), strides=(2, 2, 2), padding="VALID"):
    return _pool_nd(x, kernel, strides, padding, lax.max, -jnp.inf, [1, 2, 3])


@sd_op("avg_pool3d")
def _avg_pool3d(x, kernel=(2, 2, 2), strides=(2, 2, 2), padding="VALID"):
    s = _pool_nd(x, kernel, strides, padding, lax.add, 0.0, [1, 2, 3])
    c = _pool_nd(jnp.ones_like(x), kernel, strides, padding, lax.add, 0.0,
                 [1, 2, 3])
    return s / c


@sd_op("upsampling2d")
def _upsampling2d(x, scale=2, data_format="NCHW"):
    s = int(scale)
    if str(data_format).upper() == "NCHW":
        return jnp.repeat(jnp.repeat(x, s, axis=2), s, axis=3)
    return jnp.repeat(jnp.repeat(x, s, axis=1), s, axis=2)


@sd_op("local_response_normalization")
def _lrn(x, depth=5, bias=1.0, alpha=1.0, beta=0.5):
    """NHWC LRN (reference: LocalResponseNormalization)."""
    half = int(depth) // 2
    sq = jnp.square(x)
    padded = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, half)])
    windows = sum(padded[..., i:i + x.shape[-1]] for i in range(2 * half + 1))
    return x / jnp.power(bias + alpha * windows, beta)


sd_op("l2_normalize")(lambda x, axis=-1, eps=1e-12:
                      x / jnp.sqrt(jnp.maximum(
                          jnp.sum(jnp.square(x), axis=int(axis),
                                  keepdims=True), eps)))
sd_op("prelu")(lambda x, alpha: jnp.where(x >= 0, x, alpha * x))
sd_op("thresholded_relu")(lambda x, theta=1.0: jnp.where(x > theta, x, 0.0))
sd_op("hard_tanh")(lambda x: jnp.clip(x, -1.0, 1.0))
sd_op("rational_tanh")(lambda x: 1.7159 * jnp.tanh(2.0 / 3.0 * x))
sd_op("rectified_tanh")(lambda x: jnp.maximum(0.0, jnp.tanh(x)))


# ---- cell-level RNN primitives (reference: lstmCell/gruCell ops) ----------
@sd_op("lstm_cell")
def _lstm_cell(x, h_prev, c_prev, W, R, b=None):
    """One LSTM step: gates [i, f, o, g] (the framework's column order).
    x [B, in], h/c [B, units], W [in, 4u], R [u, 4u], b [4u]."""
    z = x @ W + h_prev @ R
    if b is not None:
        z = z + b
    u = h_prev.shape[-1]
    i, f, o, g = (z[:, :u], z[:, u:2 * u], z[:, 2 * u:3 * u], z[:, 3 * u:])
    c = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return h, c


@sd_op("gru_cell")
def _gru_cell(x, h_prev, W, R, b=None):
    """One GRU step: gates [r, z, n]. W [in, 3u], R [u, 3u], b [3u]."""
    u = h_prev.shape[-1]
    zx = x @ W
    zh = h_prev @ R
    if b is not None:
        zx = zx + b
    r = jax.nn.sigmoid(zx[:, :u] + zh[:, :u])
    z = jax.nn.sigmoid(zx[:, u:2 * u] + zh[:, u:2 * u])
    n = jnp.tanh(zx[:, 2 * u:] + r * zh[:, 2 * u:])
    return (1 - z) * n + z * h_prev


# ---- color space -----------------------------------------------------------
@sd_op("rgb_to_hsv")
def _rgb_to_hsv(x):
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx = jnp.maximum(jnp.maximum(r, g), b)
    mn = jnp.minimum(jnp.minimum(r, g), b)
    d = mx - mn
    safe = jnp.where(d == 0, 1.0, d)
    h = jnp.where(
        mx == r, (g - b) / safe % 6.0,
        jnp.where(mx == g, (b - r) / safe + 2.0, (r - g) / safe + 4.0)) / 6.0
    h = jnp.where(d == 0, 0.0, h)
    s = jnp.where(mx == 0, 0.0, d / jnp.where(mx == 0, 1.0, mx))
    return jnp.stack([h, s, mx], axis=-1)


@sd_op("hsv_to_rgb")
def _hsv_to_rgb(x):
    h, s, v = x[..., 0] * 6.0, x[..., 1], x[..., 2]
    c = v * s
    xx = c * (1 - jnp.abs(h % 2.0 - 1))
    m = v - c
    z = jnp.zeros_like(c)
    idx = jnp.floor(h).astype(jnp.int32) % 6
    rs = jnp.stack([c, xx, z, z, xx, c], -1)
    gs = jnp.stack([xx, c, c, xx, z, z], -1)
    bs = jnp.stack([z, z, xx, c, c, xx], -1)
    pick = jax.nn.one_hot(idx, 6, dtype=x.dtype)
    return jnp.stack([jnp.sum(rs * pick, -1) + m,
                      jnp.sum(gs * pick, -1) + m,
                      jnp.sum(bs * pick, -1) + m], axis=-1)


sd_op("rgb_to_grs")(lambda x: (0.2989 * x[..., 0] + 0.587 * x[..., 1]
                               + 0.114 * x[..., 2])[..., None])
sd_op("rgb_to_yuv")(lambda x: jnp.stack([
    0.299 * x[..., 0] + 0.587 * x[..., 1] + 0.114 * x[..., 2],
    -0.14714119 * x[..., 0] - 0.28886916 * x[..., 1] + 0.43601035 * x[..., 2],
    0.61497538 * x[..., 0] - 0.51496512 * x[..., 1] - 0.10001026 * x[..., 2],
], axis=-1))
@sd_op("adjust_saturation")
def _adjust_saturation(x, factor=1.0):
    hsv = _rgb_to_hsv(x)
    return _hsv_to_rgb(hsv.at[..., 1].set(
        jnp.clip(hsv[..., 1] * factor, 0.0, 1.0)))


@sd_op("adjust_hue")
def _adjust_hue(x, delta=0.0):
    hsv = _rgb_to_hsv(x)
    return _hsv_to_rgb(hsv.at[..., 0].set((hsv[..., 0] + delta) % 1.0))


# ---- loss family -----------------------------------------------------------
sd_op("hinge_loss")(lambda labels, logits:
                    jnp.mean(jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits)))
sd_op("squared_hinge_loss")(lambda labels, logits: jnp.mean(
    jnp.square(jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits))))
sd_op("poisson_loss")(lambda labels, predictions: jnp.mean(
    predictions - labels * jnp.log(jnp.clip(predictions, 1e-12, None))))
sd_op("kl_divergence")(lambda labels, predictions: jnp.sum(
    labels * jnp.log(jnp.clip(labels, 1e-12, None)
                     / jnp.clip(predictions, 1e-12, None)), axis=-1))
sd_op("mean_pairwise_squared_error")(
    lambda labels, predictions: jnp.mean(jnp.square(
        (predictions[:, :, None] - predictions[:, None, :])
        - (labels[:, :, None] - labels[:, None, :]))))
sd_op("weighted_cross_entropy_with_logits")(
    lambda labels, logits, pos_weight=1.0: jnp.mean(
        (1 - labels) * logits
        + (1 + (pos_weight - 1) * labels)
        * jnp.log1p(jnp.exp(-jnp.abs(logits)))
        + (1 + (pos_weight - 1) * labels) * jnp.maximum(-logits, 0.0)))


@sd_op("ctc_loss")
def _ctc_loss(log_probs, labels, logit_lengths, label_lengths, blank_id=0):
    """CTC (reference: ctc_loss). log_probs [B, T, C]."""
    import optax

    logit_pads = (jnp.arange(log_probs.shape[1])[None, :]
                  >= logit_lengths[:, None]).astype(jnp.float32)
    label_pads = (jnp.arange(labels.shape[1])[None, :]
                  >= label_lengths[:, None]).astype(jnp.float32)
    return optax.ctc_loss(log_probs, logit_pads, labels, label_pads,
                          blank_id=int(blank_id))


# ---- linalg extensions -----------------------------------------------------
sd_op("slogdet")(lambda x: jnp.linalg.slogdet(x))
sd_op("pinv")(jnp.linalg.pinv)
sd_op("matrix_rank")(lambda x, tol=None: jnp.linalg.matrix_rank(x, tol))
sd_op("kron")(jnp.kron)
sd_op("cross")(lambda a, b, axis=-1: jnp.cross(a, b, axis=int(axis)))
sd_op("matrix_set_diag")(lambda x, diag: x.at[
    ..., jnp.arange(min(x.shape[-2], x.shape[-1])),
    jnp.arange(min(x.shape[-2], x.shape[-1]))].set(diag))
sd_op("lu")(lambda x: jax.scipy.linalg.lu(x))
sd_op("triangular_solve")(
    lambda a, b, lower=True: jax.scipy.linalg.solve_triangular(
        a, b, lower=bool(lower)))


# ---- random distributions --------------------------------------------------
sd_op("random_gamma")(lambda shape=None, alpha=1.0, beta=1.0, rng=None:
                      jax.random.gamma(rng, alpha,
                                       [int(s) for s in shape]) / beta)
sd_op("random_poisson")(lambda shape=None, lam=1.0, rng=None:
                        jax.random.poisson(rng, lam, [int(s) for s in shape]))
sd_op("random_exponential")(lambda shape=None, rate=1.0, rng=None:
                            jax.random.exponential(
                                rng, [int(s) for s in shape]) / rate)
sd_op("random_shuffle")(lambda x, rng=None: jax.random.permutation(rng, x))
sd_op("random_truncated_normal")(
    lambda shape=None, mean=0.0, stddev=1.0, rng=None:
    mean + stddev * jax.random.truncated_normal(
        rng, -2.0, 2.0, [int(s) for s in shape]))


# ---- clipping family -------------------------------------------------------
@sd_op("clip_by_norm")
def _clip_by_norm(x, clip_norm=1.0, axis=None):
    ax = None if axis is None else tuple(int(a) for a in np.atleast_1d(axis))
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=True))
    return jnp.where(norm > clip_norm, x * clip_norm / norm, x)


@sd_op("clip_by_avg_norm")
def _clip_by_avg_norm(x, clip_norm=1.0):
    avg = jnp.sqrt(jnp.mean(jnp.square(x)))
    return jnp.where(avg > clip_norm, x * clip_norm / avg, x)


@sd_op("clip_by_global_norm")
def _clip_by_global_norm(*xs, clip_norm=1.0):
    gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(x)) for x in xs))
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(gnorm, 1e-12))
    out = tuple(x * scale for x in xs)
    return out if len(out) > 1 else out[0]


# ---- comparison utilities --------------------------------------------------
sd_op("isclose")(lambda a, b, rtol=1e-5, atol=1e-8:
                 jnp.isclose(a, b, rtol=rtol, atol=atol))
sd_op("is_non_decreasing")(lambda x: jnp.all(x[1:] >= x[:-1]))
sd_op("is_strictly_increasing")(lambda x: jnp.all(x[1:] > x[:-1]))
sd_op("is_numeric_tensor")(lambda x: jnp.asarray(
    jnp.issubdtype(x.dtype, jnp.number)))


@sd_op("assert_equals")
def _assert_equals(a, b):
    """Value-level equality checked via checkify-style select: returns a
    which equals b; under jit the check is best-effort (NaN poison)."""
    return jnp.where(jnp.all(a == b), a, jnp.full_like(a, jnp.nan))


# ---- tranche 2: image/sequence/norm utilities ------------------------------
sd_op("polygamma")(lambda n, x: jax.scipy.special.polygamma(n.astype(jnp.int32), x))
sd_op("zeta")(jax.scipy.special.zeta)
sd_op("log_matrix_determinant")(lambda x: jnp.linalg.slogdet(x)[1])


@sd_op("sequence_mask")
def _sequence_mask(lengths, maxlen=None, dtype=jnp.float32):
    """[b] lengths -> [b, maxlen] 1/0 mask (reference: sequence_mask)."""
    m = int(maxlen) if maxlen is not None else None
    if m is None:
        raise ValueError("sequence_mask needs static maxlen (XLA shapes)")
    return (jnp.arange(m)[None, :] < lengths[:, None]).astype(dtype)


@sd_op("extract_image_patches")
def _extract_image_patches(x, ksizes=(3, 3), strides=(1, 1), rates=(1, 1),
                           padding="VALID"):
    """NHWC patch extraction (reference: extract_image_patches). Output
    [n, oh, ow, kh*kw*c] with TF's channel-fastest patch layout."""
    n, h, w, c = x.shape
    kh, kw = int(ksizes[0]), int(ksizes[1])
    patches = lax.conv_general_dilated_patches(
        jnp.moveaxis(x, 3, 1), (kh, kw),
        tuple(int(s) for s in strides), str(padding).upper(),
        rhs_dilation=tuple(int(r) for r in rates),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))  # [n, c*kh*kw, oh, ow]
    _, f, oh, ow = patches.shape
    # conv patches come channel-major [c, kh, kw]; TF wants [kh, kw, c]
    patches = patches.reshape(n, c, kh * kw, oh, ow).transpose(0, 3, 4, 2, 1)
    return patches.reshape(n, oh, ow, kh * kw * c)


@sd_op("crop_and_resize")
def _crop_and_resize(image, boxes, box_indices, crop_size=(14, 14),
                     extrapolation_value=0.0):
    """NHWC crop-and-resize with normalized boxes [y1, x1, y2, x2]
    (reference: CropAndResize). TF semantics: a crop dimension of 1
    samples the box CENTER, and sample points outside the image take
    ``extrapolation_value``. Static crop_size; bilinear."""
    ch, cw = int(crop_size[0]), int(crop_size[1])
    n, h, w, c = image.shape

    def sample_coords(lo, hi, count, extent):
        if count > 1:
            return (lo * (extent - 1)
                    + jnp.arange(count) * (hi - lo) * (extent - 1)
                    / (count - 1))
        return jnp.asarray([0.5 * (lo + hi) * (extent - 1)])

    def one(box, idx):
        y1, x1, y2, x2 = box[0], box[1], box[2], box[3]
        ys = sample_coords(y1, y2, ch, h)
        xs = sample_coords(x1, x2, cw, w)
        in_y = (ys >= 0) & (ys <= h - 1)
        in_x = (xs >= 0) & (xs <= w - 1)
        img = image[idx]
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        tl = img[y0][:, x0]
        tr = img[y0][:, x1i]
        bl = img[y1i][:, x0]
        br = img[y1i][:, x1i]
        top = tl * (1 - wx) + tr * wx
        bot = bl * (1 - wx) + br * wx
        out = top * (1 - wy) + bot * wy
        inside = (in_y[:, None] & in_x[None, :])[..., None]
        return jnp.where(inside, out, extrapolation_value)

    return jax.vmap(one)(boxes, box_indices.astype(jnp.int32))


@sd_op("non_max_suppression_padded")
def _nms_padded(boxes, scores, max_output_size=10, iou_threshold=0.5):
    """Greedy NMS with a STATIC output count (XLA-honest form of the
    reference's non_max_suppression): returns (indices [k], valid [k])."""
    k = int(max_output_size)
    n = boxes.shape[0]
    y1, x1, y2, x2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(y2 - y1, 0) * jnp.maximum(x2 - x1, 0)

    def iou(i, j):
        yy1 = jnp.maximum(y1[i], y1[j])
        xx1 = jnp.maximum(x1[i], x1[j])
        yy2 = jnp.minimum(y2[i], y2[j])
        xx2 = jnp.minimum(x2[i], x2[j])
        inter = jnp.maximum(yy2 - yy1, 0) * jnp.maximum(xx2 - xx1, 0)
        return inter / jnp.maximum(area[i] + area[j] - inter, 1e-9)

    def body(alive, _):
        masked = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(masked)
        valid = masked[best] > -jnp.inf
        ious = jax.vmap(lambda j: iou(best, j))(jnp.arange(n))
        alive = alive & (ious <= iou_threshold)
        alive = alive.at[best].set(False)
        return alive, (best, valid)

    _, (idx, valid) = lax.scan(body, jnp.ones(n, bool), None, length=k)
    return idx, valid


@sd_op("instance_norm")
def _instance_norm(x, gamma=None, beta=None, eps=1e-5):
    """NCHW instance norm (reference: instance_norm custom op)."""
    mean = jnp.mean(x, axis=(2, 3), keepdims=True)
    var = jnp.var(x, axis=(2, 3), keepdims=True)
    y = (x - mean) / jnp.sqrt(var + eps)
    if gamma is not None:
        y = y * gamma[None, :, None, None]
    if beta is not None:
        y = y + beta[None, :, None, None]
    return y


@sd_op("group_norm")
def _group_norm(x, gamma=None, beta=None, groups=2, eps=1e-5):
    """NCHW group norm."""
    n, c, h, w = x.shape
    g = int(groups)
    xg = x.reshape(n, g, c // g, h, w)
    mean = jnp.mean(xg, axis=(2, 3, 4), keepdims=True)
    var = jnp.var(xg, axis=(2, 3, 4), keepdims=True)
    y = ((xg - mean) / jnp.sqrt(var + eps)).reshape(n, c, h, w)
    if gamma is not None:
        y = y * gamma[None, :, None, None]
    if beta is not None:
        y = y + beta[None, :, None, None]
    return y


@sd_op("alpha_dropout")
def _alpha_dropout(x, rate=0.5, rng=None, deterministic=True):
    """SELU-preserving dropout (reference: AlphaDropout)."""
    if deterministic or rng is None or rate <= 0.0:
        return x
    alpha_p = -1.7580993408473766
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
    b = -a * alpha_p * (1 - keep)
    return a * jnp.where(mask, x, alpha_p) + b


sd_op("embedding_lookup")(
    lambda params, ids: jnp.take(params, ids.astype(jnp.int32), axis=0))
sd_op("matrix_diag")(lambda d: jnp.zeros(
    d.shape + (d.shape[-1],), d.dtype).at[
        ..., jnp.arange(d.shape[-1]), jnp.arange(d.shape[-1])].set(d))
sd_op("reverse")(lambda x, axis=None: jnp.flip(
    x, None if axis is None else tuple(int(a) for a in np.atleast_1d(axis))))
sd_op("swapaxes")(lambda x, a=0, b=1: jnp.swapaxes(x, int(a), int(b)))
sd_op("moveaxis")(lambda x, src=0, dst=1: jnp.moveaxis(x, int(src), int(dst)))
sd_op("atleast_2d")(jnp.atleast_2d)
sd_op("squeeze_all")(lambda x: jnp.squeeze(x))
sd_op("full_like")(lambda x, value=0.0: jnp.full_like(x, value))
sd_op("digitize")(lambda x, bins: jnp.digitize(x, bins))
sd_op("searchsorted")(lambda a, v, side="left": jnp.searchsorted(a, v, side=side))
sd_op("interp")(lambda x, xp, fp: jnp.interp(x, xp, fp))
sd_op("unravel_index")(lambda idx, shape=None: jnp.stack(
    jnp.unravel_index(idx, tuple(int(s) for s in shape)), axis=-1))
sd_op("ravel_multi_index")(lambda idx, shape=None: jnp.ravel_multi_index(
    tuple(idx[..., i] for i in range(idx.shape[-1])),
    tuple(int(s) for s in shape)))
