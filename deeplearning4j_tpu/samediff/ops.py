"""SameDiff op implementations.

Reference: the nd4j op hierarchy + SameDiff op factories (sd.math()/nn()/
cnn()/rnn()/loss()/bitwise()/image()/linalg(), SURVEY.md §2.2). Each op is a
pure jnp function registered in the core OpRegistry under a stable name; the
SameDiff graph stores op names, so serialization and the TF importer resolve
through this table.

Ops are deliberately jnp-thin: XLA fuses them; there is nothing like the
reference's per-op native kernel to manage.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# name -> callable(*arrays, **attrs)
SD_OPS: dict = {}


def sd_op(name: str):
    def deco(fn):
        if name in SD_OPS:
            raise ValueError(f"duplicate samediff op {name}")
        SD_OPS[name] = fn
        return fn

    return deco


def get_sd_op(name: str):
    try:
        return SD_OPS[name]
    except KeyError:
        raise KeyError(f"Unknown samediff op {name!r}") from None


# ---- elementwise arithmetic ------------------------------------------------
for _name, _fn in {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide, "pow": jnp.power, "mod": jnp.mod,
    "floordiv": jnp.floor_divide, "squareddifference": lambda a, b: (a - b) ** 2,
    "maximum": jnp.maximum, "minimum": jnp.minimum, "atan2": jnp.arctan2,
}.items():
    sd_op(_name)(_fn)

for _name, _fn in {
    "neg": jnp.negative, "abs": jnp.abs, "sign": jnp.sign,
    "exp": jnp.exp, "expm1": jnp.expm1, "log": jnp.log, "log1p": jnp.log1p,
    "log2": jnp.log2, "sqrt": jnp.sqrt, "rsqrt": lambda x: lax.rsqrt(x),
    "square": jnp.square, "reciprocal": jnp.reciprocal, "cube": lambda x: x * x * x,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "asinh": jnp.arcsinh, "acosh": jnp.arccosh, "atanh": jnp.arctanh,
    "erf": jax.scipy.special.erf, "erfc": jax.scipy.special.erfc,
    "floor": jnp.floor, "ceil": jnp.ceil, "round": jnp.round,
    "isnan": jnp.isnan, "isinf": jnp.isinf, "isfinite": jnp.isfinite,
}.items():
    sd_op(_name)(_fn)


@sd_op("clip_by_value")
def _clip(x, clip_value_min=None, clip_value_max=None):
    return jnp.clip(x, clip_value_min, clip_value_max)


# ---- comparisons / logical -------------------------------------------------
for _name, _fn in {
    "eq": jnp.equal, "neq": jnp.not_equal, "gt": jnp.greater,
    "gte": jnp.greater_equal, "lt": jnp.less, "lte": jnp.less_equal,
    "logical_and": jnp.logical_and, "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
}.items():
    sd_op(_name)(_fn)

sd_op("logical_not")(jnp.logical_not)


@sd_op("where")
def _where(cond, x=None, y=None):
    if x is None:
        return jnp.argwhere(cond)
    return jnp.where(cond, x, y)


sd_op("select")(lambda cond, x, y: jnp.where(cond, x, y))


# ---- bitwise ---------------------------------------------------------------
for _name, _fn in {
    "bitwise_and": jnp.bitwise_and, "bitwise_or": jnp.bitwise_or,
    "bitwise_xor": jnp.bitwise_xor, "bitwise_not": jnp.bitwise_not,
    "left_shift": jnp.left_shift, "right_shift": jnp.right_shift,
}.items():
    sd_op(_name)(_fn)


# ---- reductions ------------------------------------------------------------
def _axis_tuple(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return (int(axis),)


for _name, _fn in {
    "sum": jnp.sum, "mean": jnp.mean, "max": jnp.max, "min": jnp.min,
    "prod": jnp.prod, "std": jnp.std, "var": jnp.var,
    "any": jnp.any, "all": jnp.all,
}.items():
    def _make(fn):
        def red(x, axis=None, keepdims=False):
            return fn(x, axis=_axis_tuple(axis), keepdims=bool(keepdims))

        return red

    sd_op(f"reduce_{_name}")(_make(_fn))

sd_op("argmax")(lambda x, axis=-1, keepdims=False: jnp.argmax(x, axis=int(axis), keepdims=keepdims))
sd_op("argmin")(lambda x, axis=-1, keepdims=False: jnp.argmin(x, axis=int(axis), keepdims=keepdims))


@sd_op("norm2")
def _norm2(x, axis=None, keepdims=False):
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=_axis_tuple(axis), keepdims=keepdims))


@sd_op("norm1")
def _norm1(x, axis=None, keepdims=False):
    return jnp.sum(jnp.abs(x), axis=_axis_tuple(axis), keepdims=keepdims)


@sd_op("normmax")
def _normmax(x, axis=None, keepdims=False):
    return jnp.max(jnp.abs(x), axis=_axis_tuple(axis), keepdims=keepdims)


@sd_op("cumsum")
def _cumsum(x, axis=0, exclusive=False, reverse=False):
    axis = int(axis)
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, axis)
    return out


@sd_op("cumprod")
def _cumprod(x, axis=0, exclusive=False, reverse=False):
    axis = int(axis)
    if reverse:
        x = jnp.flip(x, axis)
    if exclusive:  # prod of strict predecessors: shift in a leading 1
        ones = jnp.ones_like(lax.slice_in_dim(x, 0, 1, axis=axis))
        x = jnp.concatenate(
            [ones, lax.slice_in_dim(x, 0, x.shape[axis] - 1, axis=axis)],
            axis=axis)
    out = jnp.cumprod(x, axis=axis)
    if reverse:
        out = jnp.flip(out, axis)
    return out


# ---- shape ops -------------------------------------------------------------
sd_op("reshape")(lambda x, shape=None: jnp.reshape(x, [int(s) for s in shape]))
sd_op("transpose")(lambda x, perm=None: jnp.transpose(x, None if perm is None else [int(p) for p in perm]))
sd_op("expand_dims")(lambda x, axis=0: jnp.expand_dims(x, int(axis)))


@sd_op("squeeze")
def _squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = tuple(int(a) for a in axes if x.shape[int(a)] == 1)
    return jnp.squeeze(x, axes) if axes else x


sd_op("shape_of")(lambda x: jnp.asarray(x.shape, jnp.int32))
sd_op("size")(lambda x: jnp.asarray(x.size, jnp.int32))
sd_op("rank")(lambda x: jnp.asarray(x.ndim, jnp.int32))
sd_op("concat")(lambda *xs, axis=0: jnp.concatenate(xs, axis=int(axis)))
sd_op("stack")(lambda *xs, axis=0: jnp.stack(xs, axis=int(axis)))


@sd_op("unstack")
def _unstack(x, axis=0, num=None):
    n = num if num is not None else x.shape[int(axis)]
    return tuple(jnp.squeeze(s, int(axis)) for s in jnp.split(x, int(n), axis=int(axis)))


@sd_op("split")
def _split(x, num_splits=2, axis=0):
    return tuple(jnp.split(x, int(num_splits), axis=int(axis)))


@sd_op("split_v")
def _split_v(x, size_splits=None, axis=0):
    idx = list(jnp.cumsum(jnp.asarray(size_splits))[:-1])
    return tuple(jnp.split(x, [int(i) for i in idx], axis=int(axis)))


sd_op("tile")(lambda x, reps=None: jnp.tile(x, [int(r) for r in reps]))
sd_op("flip")(lambda x, axis=0: jnp.flip(x, int(axis)))
sd_op("broadcast_to")(
    lambda x, shape=None: jnp.broadcast_to(x, tuple(int(s) for s in shape)))
sd_op("flatten2d")(lambda x: jnp.reshape(x, (x.shape[0], -1)))


@sd_op("reshape_onnx")
def _reshape_onnx(x, shape=None):
    """ONNX Reshape semantics: 0 copies the input dim, -1 infers."""
    out = [x.shape[i] if s == 0 else int(s) for i, s in enumerate(shape)]
    return jnp.reshape(x, tuple(out))


@sd_op("slice_onnx")
def _slice_onnx(x, starts=None, ends=None, axes=None, steps=None):
    """ONNX Slice semantics: per-axis [start:end:step] with negative
    indices and INT64_MAX/INT64_MIN sentinels clamped to the dim."""
    idx = [slice(None)] * x.ndim
    for start, end, ax, st in zip(starts, ends, axes, steps):
        ax = int(ax) % x.ndim
        dim = x.shape[ax]
        start, end, st = int(start), int(end), int(st)
        if start > dim:
            start = dim
        if end > dim:
            end = dim
        if end < -dim:
            end = None if st < 0 else -dim
        idx[ax] = slice(start, end, st)
    return x[tuple(idx)]


@sd_op("slice")
def _slice(x, begin=None, size=None):
    begin = [int(b) for b in begin]
    size = [int(s) for s in size]
    size = [x.shape[i] - begin[i] if s == -1 else s for i, s in enumerate(size)]
    return lax.slice(x, begin, [b + s for b, s in zip(begin, size)])


@sd_op("strided_slice")
def _strided_slice(x, begin=None, end=None, strides=None,
                   begin_mask=0, end_mask=0, shrink_axis_mask=0,
                   new_axis_mask=0, ellipsis_mask=0):
    """TF StridedSlice semantics (subset: no ellipsis)."""
    ndim = x.ndim
    begin = list(begin)
    end = list(end)
    strides = list(strides) if strides is not None else [1] * len(begin)
    idx = []
    for i in range(len(begin)):
        if new_axis_mask & (1 << i):
            idx.append(None)
            continue
        b = None if (begin_mask & (1 << i)) else int(begin[i])
        e = None if (end_mask & (1 << i)) else int(end[i])
        s = int(strides[i])
        if shrink_axis_mask & (1 << i):
            idx.append(int(begin[i]))
        else:
            idx.append(slice(b, e, s))
    return x[tuple(idx)]


sd_op("gather")(lambda params, indices, axis=0: jnp.take(params, indices.astype(jnp.int32), axis=int(axis)))


@sd_op("gather_nd")
def _gather_nd(params, indices):
    idx = tuple(jnp.moveaxis(indices.astype(jnp.int32), -1, 0))
    return params[idx]


@sd_op("scatter_update")
def _scatter_update(ref, indices, updates):
    return ref.at[indices.astype(jnp.int32)].set(updates)


@sd_op("scatter_add")
def _scatter_add(ref, indices, updates):
    return ref.at[indices.astype(jnp.int32)].add(updates)


@sd_op("one_hot")
def _one_hot(indices, depth=None, on_value=1.0, off_value=0.0, axis=-1, dtype=None):
    out = jax.nn.one_hot(indices.astype(jnp.int32), int(depth), axis=int(axis),
                         dtype=dtype or jnp.float32)
    if on_value != 1.0 or off_value != 0.0:
        out = out * (on_value - off_value) + off_value
    return out


sd_op("zeros_like")(jnp.zeros_like)
sd_op("ones_like")(jnp.ones_like)
sd_op("fill")(lambda shape, value=0.0, dtype=None: jnp.full([int(s) for s in shape], value, dtype))
sd_op("range")(lambda start=0, limit=None, delta=1, dtype=None: jnp.arange(start, limit, delta, dtype))
sd_op("cast")(lambda x, dtype=None: x.astype(jnp.dtype(dtype)))
sd_op("identity")(lambda x: x)
sd_op("stop_gradient")(lax.stop_gradient)
sd_op("pad")(lambda x, paddings=None, mode="CONSTANT", constant_value=0.0: jnp.pad(
    x, [(int(a), int(b)) for a, b in paddings],
    mode={"CONSTANT": "constant", "REFLECT": "reflect", "SYMMETRIC": "symmetric"}[str(mode).upper()],
    **({"constant_values": constant_value} if str(mode).upper() == "CONSTANT" else {}),
))
sd_op("reverse_sequence")(
    lambda x, seq_lengths, seq_axis=1, batch_axis=0: _reverse_sequence(x, seq_lengths, seq_axis, batch_axis)
)


def _reverse_sequence(x, seq_lengths, seq_axis, batch_axis):
    seq_axis, batch_axis = int(seq_axis), int(batch_axis)
    if batch_axis != 0:
        raise NotImplementedError("reverse_sequence: batch_axis must be 0")
    t = x.shape[seq_axis]
    ar = jnp.arange(t)
    idx = jnp.where(
        ar[None, :] < seq_lengths[:, None],
        seq_lengths[:, None] - 1 - ar[None, :],
        ar[None, :],
    )  # [batch, t]
    shape = [1] * x.ndim
    shape[0] = x.shape[0]
    shape[seq_axis] = t
    return jnp.take_along_axis(x, idx.astype(jnp.int32).reshape(shape), axis=seq_axis)


# ---- linalg ----------------------------------------------------------------
@sd_op("matmul")
def _matmul(a, b, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return a @ b


sd_op("batch_matmul")(lambda a, b, adj_x=False, adj_y=False: _matmul(a, b, adj_x, adj_y))
sd_op("einsum")(lambda *xs, equation=None: jnp.einsum(equation, *xs))
sd_op("tensordot")(lambda a, b, axes=2: jnp.tensordot(a, b, axes))
sd_op("dot")(lambda a, b: jnp.dot(a, b))
sd_op("outer")(lambda a, b: jnp.outer(a, b))
sd_op("diag")(jnp.diag)
sd_op("diag_part")(jnp.diagonal)
sd_op("trace")(jnp.trace)
sd_op("eye")(lambda n, m=None, dtype=None: jnp.eye(int(n), None if m is None else int(m), dtype=dtype))
sd_op("cholesky")(jnp.linalg.cholesky)
sd_op("matrix_inverse")(jnp.linalg.inv)
sd_op("matrix_determinant")(jnp.linalg.det)
sd_op("svd")(lambda x, full_matrices=False: jnp.linalg.svd(x, full_matrices=full_matrices))
sd_op("qr")(lambda x, full_matrices=False: jnp.linalg.qr(
    x, mode="complete" if full_matrices else "reduced"))
sd_op("solve")(jnp.linalg.solve)
sd_op("lstsq")(lambda a, b: jnp.linalg.lstsq(a, b)[0])
sd_op("matrix_band_part")(
    lambda x, num_lower=-1, num_upper=-1: _band_part(x, int(num_lower), int(num_upper))
)


def _band_part(x, lower, upper):
    m, n = x.shape[-2], x.shape[-1]
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    keep = jnp.ones((m, n), bool)
    if lower >= 0:
        keep = keep & (i - j <= lower)
    if upper >= 0:
        keep = keep & (j - i <= upper)
    return jnp.where(keep, x, 0)


# ---- nn --------------------------------------------------------------------
sd_op("relu")(jax.nn.relu)
sd_op("relu6")(jax.nn.relu6)
sd_op("leaky_relu")(lambda x, alpha=0.01: jax.nn.leaky_relu(x, alpha))
sd_op("elu")(jax.nn.elu)
sd_op("selu")(jax.nn.selu)
sd_op("gelu")(lambda x, approximate=False: jax.nn.gelu(x, approximate=bool(approximate)))
sd_op("sigmoid")(jax.nn.sigmoid)
sd_op("hard_sigmoid")(lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0))
sd_op("softplus")(jax.nn.softplus)
sd_op("softsign")(jax.nn.soft_sign)
sd_op("swish")(jax.nn.swish)
sd_op("mish")(jax.nn.mish)
sd_op("softmax")(lambda x, axis=-1: jax.nn.softmax(x, axis=int(axis)))
sd_op("log_softmax")(lambda x, axis=-1: jax.nn.log_softmax(x, axis=int(axis)))


@sd_op("bias_add")
def _bias_add(x, bias, data_format="NHWC"):
    if str(data_format).upper().startswith("NC") and x.ndim > 2:
        shape = [1, bias.shape[0]] + [1] * (x.ndim - 2)
        return x + bias.reshape(shape)
    return x + bias


@sd_op("layer_norm")
def _layer_norm(x, gamma=None, beta=None, axis=-1, eps=1e-5):
    mean = jnp.mean(x, axis=int(axis), keepdims=True)
    var = jnp.var(x, axis=int(axis), keepdims=True)
    out = (x - mean) * lax.rsqrt(var + eps)
    if gamma is not None:
        out = out * gamma
    if beta is not None:
        out = out + beta
    return out


@sd_op("batch_norm")
def _batch_norm(x, mean, variance, gamma=None, beta=None, eps=1e-3, axis=1):
    axis = int(axis)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    out = (x - mean.reshape(shape)) * lax.rsqrt(variance.reshape(shape) + eps)
    if gamma is not None:
        out = out * gamma.reshape(shape)
    if beta is not None:
        out = out + beta.reshape(shape)
    return out


@sd_op("dropout")
def _dropout(x, rate=0.5, rng=None, deterministic=True):
    if deterministic or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0)


@sd_op("conv2d")
def _conv2d(x, w, bias=None, strides=(1, 1), padding="SAME", data_format="NCHW",
            dilations=(1, 1), groups=1):
    """w layout: [kH, kW, inC/groups, outC] (TF HWIO) — converted internally.
    ``groups`` maps to XLA feature_group_count (grouped/depthwise conv)."""
    df = str(data_format).upper()
    dn = (df, "HWIO", df)
    strides = tuple(int(s) for s in strides)
    dilations = tuple(int(d) for d in dilations)
    if isinstance(padding, (list, tuple)) and not isinstance(padding, str):
        padding = [(int(a), int(b)) for a, b in padding]
    y = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding, rhs_dilation=dilations,
        dimension_numbers=lax.conv_dimension_numbers(x.shape, w.shape, dn),
        feature_group_count=int(groups),
    )
    if bias is not None:
        y = _bias_add(y, bias, data_format=df)
    return y


def _pool_geometry(kernel, strides, padding, data_format):
    """Window/stride/padding in full-rank form. ``padding`` is either a lax
    string or explicit per-spatial-dim (lo, hi) pairs (the ONNX pads form)."""
    df = str(data_format).upper()
    if df == "NCHW":
        window = (1, 1) + tuple(int(k) for k in kernel)
        str_ = (1, 1) + tuple(int(s) for s in strides)
    else:
        window = (1,) + tuple(int(k) for k in kernel) + (1,)
        str_ = (1,) + tuple(int(s) for s in strides) + (1,)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        spatial = [(int(a), int(b)) for a, b in padding]
        pad = ([(0, 0), (0, 0)] + spatial) if df == "NCHW" \
            else ([(0, 0)] + spatial + [(0, 0)])
    return window, str_, pad


@sd_op("max_pool2d")
def _max_pool2d(x, kernel=(2, 2), strides=(2, 2), padding="VALID", data_format="NCHW"):
    window, str_, pad = _pool_geometry(kernel, strides, padding, data_format)
    return lax.reduce_window(x, -jnp.inf, lax.max, window, str_, pad)


@sd_op("avg_pool2d")
def _avg_pool2d(x, kernel=(2, 2), strides=(2, 2), padding="VALID", data_format="NCHW",
                count_include_pad=False):
    window, str_, pad = _pool_geometry(kernel, strides, padding, data_format)
    summed = lax.reduce_window(x, 0.0, lax.add, window, str_, pad)
    if count_include_pad:
        return summed / float(np.prod([int(k) for k in kernel]))
    # exclude-pad: divide by the true (unpadded) window population
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(ones, 0.0, lax.add, window, str_, pad)
    return summed / counts


@sd_op("multi_head_dot_product_attention")
def _mhdpa(q, k, v, wq=None, wk=None, wv=None, wo=None, n_heads=1, mask=None, scaled=True):
    """SameDiff multiHeadDotProductAttention (reference: sd.nn namespace).

    Semantics note: rows whose key mask is entirely zero output 0 (this
    framework's defined behavior across all attention impls), where the
    reference's softmax-of-constant would output mean(v). Reachable only
    for degenerate all-padding batch entries."""
    from ..nn.layers.attention import dot_product_attention, _merge_heads, _split_heads

    if wq is not None:
        q, k, v = q @ wq, k @ wk, v @ wv
    qh, kh, vh = (_split_heads(t, int(n_heads)) for t in (q, k, v))
    o = _merge_heads(dot_product_attention(qh, kh, vh, mask=mask, scaled=scaled))
    if wo is not None:
        o = o @ wo
    return o


# ---- losses ----------------------------------------------------------------
@sd_op("softmax_cross_entropy")
def _sce(labels, logits, axis=-1):
    return -jnp.sum(labels * jax.nn.log_softmax(logits, axis=int(axis)), axis=int(axis))


@sd_op("sparse_softmax_cross_entropy")
def _ssce(labels, logits):
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels.astype(jnp.int32)[..., None], axis=-1).squeeze(-1)


@sd_op("sigmoid_cross_entropy")
def _bce(labels, logits):
    return jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))


@sd_op("mean_squared_error")
def _mse_loss(labels, predictions):
    return jnp.mean(jnp.square(labels - predictions))


@sd_op("huber_loss")
def _huber(labels, predictions, delta=1.0):
    err = jnp.abs(labels - predictions)
    quad = jnp.minimum(err, delta)
    return jnp.mean(0.5 * quad**2 + delta * (err - quad))


@sd_op("log_loss")
def _log_loss(labels, predictions, eps=1e-7):
    p = jnp.clip(predictions, eps, 1 - eps)
    return -jnp.mean(labels * jnp.log(p) + (1 - labels) * jnp.log1p(-p))


@sd_op("cosine_distance")
def _cos_dist(labels, predictions, axis=-1):
    ln = labels / jnp.clip(jnp.linalg.norm(labels, axis=axis, keepdims=True), 1e-8)
    pn = predictions / jnp.clip(jnp.linalg.norm(predictions, axis=axis, keepdims=True), 1e-8)
    return 1.0 - jnp.sum(ln * pn, axis=axis)


# ---- image -----------------------------------------------------------------
@sd_op("resize_nearest")
def _resize_nearest(x, size=None, data_format="NHWC"):
    h, w = int(size[0]), int(size[1])
    if str(data_format).upper() == "NHWC":
        return jax.image.resize(x, (x.shape[0], h, w, x.shape[3]), method="nearest")
    return jax.image.resize(x, (x.shape[0], x.shape[1], h, w), method="nearest")


@sd_op("resize_bilinear")
def _resize_bilinear(x, size=None, data_format="NHWC"):
    h, w = int(size[0]), int(size[1])
    if str(data_format).upper() == "NHWC":
        return jax.image.resize(x, (x.shape[0], h, w, x.shape[3]), method="bilinear")
    return jax.image.resize(x, (x.shape[0], x.shape[1], h, w), method="bilinear")


@sd_op("adjust_contrast")
def _adjust_contrast(x, factor=1.0):
    mean = jnp.mean(x, axis=(-3, -2), keepdims=True)
    return (x - mean) * factor + mean


# ---- random (keyed) --------------------------------------------------------
@sd_op("random_normal")
def _random_normal(shape=None, mean=0.0, stddev=1.0, rng=None, dtype=jnp.float32):
    return mean + stddev * jax.random.normal(rng, [int(s) for s in shape], dtype)


@sd_op("random_uniform")
def _random_uniform(shape=None, minval=0.0, maxval=1.0, rng=None, dtype=jnp.float32):
    return jax.random.uniform(rng, [int(s) for s in shape], dtype, minval, maxval)


@sd_op("random_bernoulli")
def _random_bernoulli(shape=None, p=0.5, rng=None):
    return jax.random.bernoulli(rng, p, [int(s) for s in shape]).astype(jnp.float32)


# the extended op families register themselves on import
from . import ops_extended  # noqa: E402,F401  (SURVEY §2.1 op breadth)
from . import ops_tranche3  # noqa: E402,F401  (SURVEY §2.1 op breadth)
