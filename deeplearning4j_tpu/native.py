"""ctypes bindings for libdl4jtpu (native/dl4jtpu_native.cpp).

The reference reaches its native core through JavaCPP-generated JNI
(SURVEY.md §1 L1); here the binding layer is ctypes over the same kind of
flat C ABI. Every function has a pure-NumPy fallback so the framework works
without the native build — :func:`available` reports which path is active,
and ``DL4J_TPU_DISABLE_NATIVE=1`` forces the fallback (the reference's
"helpers allowed" environment knob, SURVEY.md §5.6).

Build: ``sh native/build.sh`` (cmake/ninja or direct g++). The loader also
attempts a one-shot build on first use when a compiler is present, so a
fresh checkout self-provisions like the reference's bundled binaries.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_log = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LIB_PATHS = [
    os.path.join(_REPO_ROOT, "native", "build", "libdl4jtpu.so"),
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "libdl4jtpu.so"),
]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _try_build() -> None:
    script = os.path.join(_REPO_ROOT, "native", "build.sh")
    if not os.path.exists(script):
        return
    if os.environ.get("DL4J_TPU_AUTOBUILD", "1") == "0":
        _log.info("libdl4jtpu not built and DL4J_TPU_AUTOBUILD=0; "
                  "using NumPy fallbacks")
        return
    # warning level so the first-use stall (up to ~2 min of cmake/g++) is
    # attributable in serving/test logs; disable via DL4J_TPU_AUTOBUILD=0
    _log.warning("libdl4jtpu not found; building via %s (may take up to "
                 "120s; set DL4J_TPU_AUTOBUILD=0 to skip)", script)
    try:
        proc = subprocess.run(["sh", script], capture_output=True,
                              timeout=120, check=False, text=True)
        if proc.returncode != 0:
            _log.warning("native build failed (rc=%d), using NumPy "
                         "fallbacks:\n%s", proc.returncode,
                         (proc.stderr or "")[-2000:])
    except Exception as e:
        _log.warning("native build errored (%s), using NumPy fallbacks", e)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    # Lock-free fast path once the load decision is final — codec calls run
    # per gradient-sync step / per image and must not serialize on a mutex.
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        try:
            if os.environ.get("DL4J_TPU_DISABLE_NATIVE") == "1":
                return None
            for attempt in range(2):
                for p in _LIB_PATHS:
                    if os.path.exists(p):
                        try:
                            lib = ctypes.CDLL(p)
                        except OSError:
                            continue
                        _declare(lib)
                        _lib = lib
                        return _lib
                if attempt == 0:
                    _try_build()
            return None
        finally:
            # only now is the decision final — setting _tried earlier would
            # let lock-free readers fall back mid-load/build
            _tried = True


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.dl4j_threshold_encode.restype = c.c_int64
    lib.dl4j_threshold_encode.argtypes = [
        c.POINTER(c.c_float), c.c_int64, c.c_float, c.POINTER(c.c_int32),
        c.c_int64]
    lib.dl4j_threshold_decode.restype = None
    lib.dl4j_threshold_decode.argtypes = [
        c.POINTER(c.c_int32), c.c_int64, c.c_float, c.POINTER(c.c_float),
        c.c_int64]
    lib.dl4j_bitmap_encode.restype = c.c_int64
    lib.dl4j_bitmap_encode.argtypes = [
        c.POINTER(c.c_float), c.c_int64, c.c_float, c.POINTER(c.c_uint8)]
    lib.dl4j_bitmap_decode.restype = None
    lib.dl4j_bitmap_decode.argtypes = [
        c.POINTER(c.c_uint8), c.c_int64, c.c_float, c.POINTER(c.c_float)]
    lib.dl4j_parse_csv_f32.restype = c.c_int32
    lib.dl4j_parse_csv_f32.argtypes = [
        c.c_char_p, c.c_int64, c.c_char, c.c_int32, c.POINTER(c.c_float),
        c.c_int64, c.POINTER(c.c_int64), c.POINTER(c.c_int64)]
    lib.dl4j_parse_idx.restype = c.c_int32
    lib.dl4j_parse_idx.argtypes = [
        c.POINTER(c.c_uint8), c.c_int64, c.c_float, c.POINTER(c.c_float),
        c.c_int64, c.POINTER(c.c_int64)]
    lib.dl4j_decode_netpbm.restype = c.c_int32
    lib.dl4j_decode_netpbm.argtypes = [
        c.POINTER(c.c_uint8), c.c_int64, c.POINTER(c.c_float), c.c_int64,
        c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.POINTER(c.c_int64)]
    lib.dl4j_resize_bilinear_f32.restype = None
    lib.dl4j_resize_bilinear_f32.argtypes = [
        c.POINTER(c.c_float), c.c_int64, c.c_int64, c.c_int64,
        c.POINTER(c.c_float), c.c_int64, c.c_int64]
    lib.dl4j_normalize_hwc_f32.restype = None
    lib.dl4j_normalize_hwc_f32.argtypes = [
        c.POINTER(c.c_float), c.c_int64, c.c_int64, c.c_int64,
        c.POINTER(c.c_float), c.POINTER(c.c_float)]
    lib.dl4j_native_version.restype = c.c_int32
    lib.dl4j_native_version.argtypes = []


def available() -> bool:
    return _load() is not None


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _flat_f32_view(a: np.ndarray, what: str) -> np.ndarray:
    """A mutation-safe flat view. reshape(-1) on a non-contiguous array
    would COPY, making the in-place residual/decode semantics silently
    no-ops on the caller's array — reject instead."""
    if a.dtype != np.float32:
        raise ValueError(f"{what} must be float32, got {a.dtype}")
    if not a.flags.c_contiguous or not a.flags.writeable:
        raise ValueError(f"{what} must be a writeable C-contiguous array "
                         "(in-place semantics)")
    return a.reshape(-1)  # guaranteed view for contiguous arrays


# ---------------------------------------------------------------------------
# Threshold / bitmap codecs (reference: encodeThresholdP1-P3, encodeBitmap —
# the gradient-sharing wire format, SURVEY.md §2.4)
# ---------------------------------------------------------------------------


def threshold_encode(grad: np.ndarray, threshold: float,
                     max_elements: Optional[int] = None
                     ) -> Optional[np.ndarray]:
    """Encode |g|>threshold entries as a sparse int32 stream, subtracting
    the threshold in place (residual / error feedback). Returns None when
    the encoding would exceed ``max_elements`` (fall back to bitmap) or when
    the buffer is too large for the int32 +/-(index+1) wire format
    (>= 2^31-1 elements; the gradient is left untouched either way)."""
    flat = _flat_f32_view(grad, "grad")
    if flat.size >= 2**31 - 1:
        return None  # mirrors the C guard (returns -2)
    cap = int(max_elements) if max_elements is not None else flat.size
    lib = _load()
    if lib is not None:
        out = np.empty(cap, np.int32)
        n = lib.dl4j_threshold_encode(
            _fptr(flat), flat.size, ctypes.c_float(threshold),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), cap)
        return None if n < 0 else out[:n].copy()
    idx = np.nonzero(np.abs(flat) > threshold)[0]
    if idx.size > cap:
        return None
    signs = np.sign(flat[idx])
    enc = ((idx + 1) * signs).astype(np.int32)
    flat[idx] -= signs.astype(np.float32) * threshold
    return enc


def threshold_decode(encoded: np.ndarray, threshold: float,
                     target: np.ndarray) -> None:
    """target[|e|-1] += sign(e) * threshold for each encoded entry."""
    flat = _flat_f32_view(target, "target")
    lib = _load()
    if lib is not None:
        enc = np.ascontiguousarray(encoded, np.int32)
        lib.dl4j_threshold_decode(
            enc.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), enc.size,
            ctypes.c_float(threshold), _fptr(flat), flat.size)
        return
    idx = np.abs(encoded).astype(np.int64) - 1
    valid = (idx >= 0) & (idx < flat.size)  # skip corrupt entries (as in C)
    np.add.at(flat, idx[valid],
              np.sign(encoded[valid]).astype(np.float32) * threshold)


def bitmap_encode(grad: np.ndarray, threshold: float
                  ) -> Tuple[np.ndarray, int]:
    """Dense 2-bit codec (00 zero / 01 +thr / 10 -thr), residual in place.
    Returns (bitmap bytes, count of non-zero codes). The count is
    informational (compression-ratio accounting) — bitmap_decode takes the
    TOTAL element count of the tensor, not this value."""
    flat = _flat_f32_view(grad, "grad")
    bitmap = np.zeros((flat.size + 3) // 4, np.uint8)
    lib = _load()
    if lib is not None:
        n = lib.dl4j_bitmap_encode(
            _fptr(flat), flat.size, ctypes.c_float(threshold),
            bitmap.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        return bitmap, int(n)
    pos = flat > threshold
    neg = flat < -threshold
    codes = np.zeros(flat.size, np.uint8)
    codes[pos] = 1
    codes[neg] = 2
    flat[pos] -= threshold
    flat[neg] += threshold
    pad = (-codes.size) % 4
    c4 = np.pad(codes, (0, pad)).reshape(-1, 4)
    bitmap[:] = (c4[:, 0] | (c4[:, 1] << 2) | (c4[:, 2] << 4)
                 | (c4[:, 3] << 6)).astype(np.uint8)
    return bitmap, int(pos.sum() + neg.sum())


def bitmap_decode(bitmap: np.ndarray, n: int, threshold: float,
                  target: np.ndarray) -> None:
    """Apply a bitmap-encoded update to ``target``. ``n`` is the TOTAL
    element count of the encoded tensor (4 codes per bitmap byte, the last
    byte may be padding) — NOT the non-zero count bitmap_encode returns;
    passing that would silently decode only a prefix."""
    flat = _flat_f32_view(target, "target")
    lib = _load()
    if lib is not None:
        bm = np.ascontiguousarray(bitmap, np.uint8)
        lib.dl4j_bitmap_decode(
            bm.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n,
            ctypes.c_float(threshold), _fptr(flat))
        return
    codes = np.repeat(bitmap, 4)
    shifts = np.tile(np.arange(4) * 2, bitmap.size)
    codes = (codes >> shifts) & 3
    codes = codes[:n]
    flat[:n][codes == 1] += threshold
    flat[:n][codes == 2] -= threshold


# ---------------------------------------------------------------------------
# Data pipeline primitives (reference: DataVec native loaders)
# ---------------------------------------------------------------------------


def parse_csv(text: bytes, delimiter: str = ",", skip_rows: int = 0
              ) -> np.ndarray:
    """Parse a delimited byte buffer into a float32 [rows, cols] matrix."""
    if isinstance(text, str):
        text = text.encode()
    lib = _load()
    if lib is not None:
        rows = ctypes.c_int64()
        cols = ctypes.c_int64()
        rc = lib.dl4j_parse_csv_f32(text, len(text), delimiter.encode(),
                                    skip_rows, None, 0,
                                    ctypes.byref(rows), ctypes.byref(cols))
        if rc != 0:
            raise ValueError(f"CSV probe failed (code {rc})")
        out = np.empty(rows.value * cols.value, np.float32)
        rc = lib.dl4j_parse_csv_f32(text, len(text), delimiter.encode(),
                                    skip_rows, _fptr(out), out.size,
                                    ctypes.byref(rows), ctypes.byref(cols))
        if rc != 0:
            raise ValueError(f"CSV parse failed (code {rc})")
        return out.reshape(rows.value, cols.value)
    lines = [ln for ln in text.decode().splitlines() if ln.strip()]
    lines = lines[skip_rows:]
    data = [[float(x) for x in ln.split(delimiter)] for ln in lines]
    if data and any(len(r) != len(data[0]) for r in data):
        raise ValueError("CSV probe failed (code -1)")
    return np.asarray(data, np.float32)


def parse_idx(buf: bytes, scale: float = 1.0) -> np.ndarray:
    """Parse an IDX (MNIST ubyte) buffer into float32 * scale."""
    raw = np.frombuffer(buf, np.uint8)
    lib = _load()
    if lib is not None:
        shape = np.zeros(8, np.int64)
        rank = lib.dl4j_parse_idx(
            raw.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), raw.size,
            ctypes.c_float(scale), None, 0,
            shape.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if rank < 0:
            raise ValueError(f"bad IDX buffer (code {rank})")
        dims = tuple(int(d) for d in shape[:rank])
        out = np.empty(int(np.prod(dims)), np.float32)
        lib.dl4j_parse_idx(
            raw.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), raw.size,
            ctypes.c_float(scale), _fptr(out), out.size,
            shape.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return out.reshape(dims)
    if raw.size < 4 or raw[0] != 0 or raw[1] != 0 or raw[2] != 0x08:
        raise ValueError("bad IDX buffer (code -1)")
    rank = int(raw[3])
    if rank < 1 or rank > 8 or raw.size < 4 + 4 * rank:
        raise ValueError("bad IDX buffer (code -1)")
    dims = tuple(int.from_bytes(buf[4 + 4 * d:8 + 4 * d], "big")
                 for d in range(rank))
    total = int(np.prod(dims))
    if raw.size < 4 + 4 * rank + total:
        raise ValueError("bad IDX buffer (code -1)")
    data = raw[4 + 4 * rank:4 + 4 * rank + total]
    return (data.astype(np.float32) * scale).reshape(dims)


def parse_netpbm_header(buf: bytes):
    """Front-anchored P5/P6 header parse shared by the float decoder's
    numpy fallback and the uint8 fast path (data.records): returns
    (width, height, channels, maxval, raster_offset). Handles '#'
    comments (to LF or CR) and enforces the single whitespace byte
    between maxval and the raster."""
    if not buf.startswith(b"P5") and not buf.startswith(b"P6"):
        raise ValueError("bad netpbm data (code -1)")
    channels = 1 if buf[:2] == b"P5" else 3
    pos = 2
    fields = []
    while len(fields) < 3:
        while pos < len(buf) and buf[pos:pos + 1].isspace():
            pos += 1
        if buf[pos:pos + 1] == b"#":
            while pos < len(buf) and buf[pos] not in (0x0A, 0x0D):
                pos += 1
            continue
        start = pos
        while pos < len(buf) and not buf[pos:pos + 1].isspace():
            pos += 1
        fields.append(int(buf[start:pos]))
    pos += 1  # single whitespace after maxval
    w, h, maxval = fields
    return w, h, channels, maxval, pos


def decode_netpbm(buf: bytes) -> np.ndarray:
    """Decode P5 (gray) / P6 (RGB) netpbm into float32 HWC in [0, 1]."""
    raw = np.frombuffer(buf, np.uint8)
    lib = _load()
    if lib is not None:
        h = ctypes.c_int64()
        w = ctypes.c_int64()
        c = ctypes.c_int64()
        ptr = raw.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        rc = lib.dl4j_decode_netpbm(ptr, raw.size, None, 0, ctypes.byref(h),
                                    ctypes.byref(w), ctypes.byref(c))
        if rc != 0:
            raise ValueError(f"bad netpbm data (code {rc})")
        out = np.empty(h.value * w.value * c.value, np.float32)
        lib.dl4j_decode_netpbm(ptr, raw.size, _fptr(out), out.size,
                               ctypes.byref(h), ctypes.byref(w),
                               ctypes.byref(c))
        return out.reshape(h.value, w.value, c.value)
    # numpy fallback
    w, h, channels, maxval, pos = parse_netpbm_header(buf)
    if maxval <= 0 or maxval > 255:  # 16-bit netpbm unsupported (as in C)
        raise ValueError("bad netpbm data (code -1)")
    total = h * w * channels
    data = np.frombuffer(buf, np.uint8, count=total, offset=pos)
    return (data.astype(np.float32) / maxval).reshape(h, w, channels)


def resize_bilinear(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Bilinear resize of float32 HWC (half-pixel centers)."""
    img = np.ascontiguousarray(img, np.float32)
    h, w, c = img.shape
    lib = _load()
    if lib is not None:
        out = np.empty((out_h, out_w, c), np.float32)
        lib.dl4j_resize_bilinear_f32(_fptr(img), h, w, c, _fptr(out),
                                     out_h, out_w)
        return out
    sy = ((np.arange(out_h) + 0.5) * h / out_h - 0.5)
    sx = ((np.arange(out_w) + 0.5) * w / out_w - 0.5)
    y0u = np.floor(sy).astype(np.int64)
    x0u = np.floor(sx).astype(np.int64)
    y0 = np.clip(y0u, 0, h - 1)
    x0 = np.clip(x0u, 0, w - 1)
    y1 = np.clip(y0u + 1, 0, h - 1)  # from the UNCLAMPED floor (as in C)
    x1 = np.clip(x0u + 1, 0, w - 1)
    # fractional parts use the unclamped floor, matching the C loop
    fy = (sy - np.floor(sy))[:, None, None]
    fx = (sx - np.floor(sx))[None, :, None]
    v00 = img[y0][:, x0]
    v01 = img[y0][:, x1]
    v10 = img[y1][:, x0]
    v11 = img[y1][:, x1]
    top = v00 + (v01 - v00) * fx
    bot = v10 + (v11 - v10) * fx
    return (top + (bot - top) * fy).astype(np.float32)


def normalize_hwc(img: np.ndarray, mean, std) -> np.ndarray:
    """(x - mean[c]) / std[c] in place; returns the array."""
    img = np.ascontiguousarray(img, np.float32)
    h, w, c = img.shape
    mean = np.ascontiguousarray(np.broadcast_to(mean, (c,)), np.float32)
    std = np.ascontiguousarray(np.broadcast_to(std, (c,)), np.float32)
    lib = _load()
    if lib is not None:
        lib.dl4j_normalize_hwc_f32(_fptr(img), h, w, c, _fptr(mean),
                                   _fptr(std))
        return img
    img -= mean
    img /= std
    return img
