"""Image augmentation transforms.

Reference: datavec-data-image's ImageTransform hierarchy (FlipImageTransform,
CropImageTransform, RandomCropTransform, RotateImageTransform,
ResizeImageTransform, PipelineImageTransform — SURVEY.md §2.2 "DataVec
image", "the ImageNet input path"). Host-side numpy/PIL on [h, w, c] float32
arrays, composable via PipelineImageTransform, pluggable into
ImageRecordReader(transform=...).

TPU-first note: the heavy lifting (normalize, random flip/crop at batch
granularity) can also run ON DEVICE via ``batch_random_flip`` /
``batch_random_crop`` — jitted, batched augmentation is the right answer
when the host is one slow core and the accelerator is idle between steps
(the reference leans on OpenCV + host thread pools instead).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np


class ImageTransform:
    """Base: ``call(image, rng)`` -> image, both [h, w, c] float32.

    ``uint8_safe`` marks transforms whose math is dtype-agnostic (pure
    index shuffles: flip/crop) — the only ones ImageRecordReader's uint8
    fast path may run before the on-device float cast."""

    uint8_safe = False

    def call(self, image: np.ndarray, rng: np.random.RandomState) -> np.ndarray:
        raise NotImplementedError

    def __call__(self, image: np.ndarray,
                 rng: Optional[np.random.RandomState] = None) -> np.ndarray:
        return self.call(np.asarray(image, np.float32),
                         rng or np.random.RandomState())


@dataclasses.dataclass
class FlipImageTransform(ImageTransform):
    """Reference: FlipImageTransform(flipMode). mode: 0 = vertical,
    1 = horizontal, -1 = both, None = random choice per call."""

    mode: Optional[int] = 1
    uint8_safe = True

    def call(self, image, rng):
        mode = self.mode
        if mode is None:
            mode = rng.choice([-1, 0, 1])
        if mode in (0, -1):
            image = image[::-1]
        if mode in (1, -1):
            image = image[:, ::-1]
        # a VIEW, not a copy: downstream consumers (reader _load, resize)
        # make one contiguous copy at the end of the whole pipeline
        return image


@dataclasses.dataclass
class CropImageTransform(ImageTransform):
    """Deterministic border crop (reference: CropImageTransform)."""

    top: int = 0
    left: int = 0
    bottom: int = 0
    right: int = 0
    uint8_safe = True

    def call(self, image, rng):
        h, w = image.shape[:2]
        return image[self.top: h - self.bottom or h,
                     self.left: w - self.right or w]


@dataclasses.dataclass
class RandomCropTransform(ImageTransform):
    """Random crop to (height, width) (reference: RandomCropTransform)."""

    height: int = 0
    width: int = 0
    uint8_safe = True

    def call(self, image, rng):
        h, w = image.shape[:2]
        if h < self.height or w < self.width:
            raise ValueError(f"image {h}x{w} smaller than crop "
                             f"{self.height}x{self.width}")
        top = rng.randint(0, h - self.height + 1)
        left = rng.randint(0, w - self.width + 1)
        return image[top: top + self.height, left: left + self.width]


@dataclasses.dataclass
class RotateImageTransform(ImageTransform):
    """Rotate by ``angle`` degrees, or uniformly in [-angle, angle] when
    ``random`` (reference: RotateImageTransform). Right-angle rotations are
    exact (np.rot90); others resample bilinearly via PIL."""

    angle: float = 0.0
    random: bool = False

    def call(self, image, rng):
        angle = float(self.angle)
        if self.random:
            angle = float(rng.uniform(-self.angle, self.angle))
        if angle % 90.0 == 0.0:
            return np.ascontiguousarray(np.rot90(image, int(angle // 90) % 4))
        from PIL import Image

        chans = []
        for c in range(image.shape[2]):
            im = Image.fromarray(image[:, :, c].astype(np.float32), mode="F")
            chans.append(np.asarray(im.rotate(angle, resample=Image.BILINEAR)))
        return np.stack(chans, axis=2)


@dataclasses.dataclass
class ResizeImageTransform(ImageTransform):
    """Bilinear resize (reference: ResizeImageTransform)."""

    height: int = 0
    width: int = 0

    def call(self, image, rng):
        from .. import native

        return native.resize_bilinear(image, self.height, self.width)


@dataclasses.dataclass
class BrightnessTransform(ImageTransform):
    """Additive brightness jitter in [-delta, delta] (for [0, 255] or
    [0, 1] ranged images alike — delta is in image units)."""

    delta: float = 0.0

    def call(self, image, rng):
        return image + rng.uniform(-self.delta, self.delta)


class PipelineImageTransform(ImageTransform):
    """Chain transforms, each applied with a probability (reference:
    PipelineImageTransform with (transform, probability) pairs)."""

    def __init__(self, *steps, shuffle: bool = False) -> None:
        self.steps: List[Tuple[ImageTransform, float]] = [
            s if isinstance(s, tuple) else (s, 1.0) for s in steps
        ]
        self.shuffle = shuffle
        self.uint8_safe = all(t.uint8_safe for t, _ in self.steps)

    def call(self, image, rng):
        order = list(range(len(self.steps)))
        if self.shuffle:
            rng.shuffle(order)
        for i in order:
            t, p = self.steps[i]
            if p >= 1.0 or rng.rand() < p:
                image = t.call(image, rng)
        return image


# ---------------------------------------------------------------------------
# device-side batched augmentation (jit-friendly; [n, c, h, w])
# ---------------------------------------------------------------------------

def batch_random_flip(x, key):
    """Per-image random horizontal flip on device. x: [n, c, h, w]."""
    import jax
    import jax.numpy as jnp

    flip = jax.random.bernoulli(key, 0.5, (x.shape[0],))
    return jnp.where(flip[:, None, None, None], x[..., ::-1], x)


def batch_random_crop(x, key, height: int, width: int):
    """Per-image random crop on device via one dynamic_slice per image
    under vmap. x: [n, c, h, w] -> [n, c, height, width]."""
    import jax
    import jax.numpy as jnp

    n, c, h, w = x.shape
    k1, k2 = jax.random.split(key)
    tops = jax.random.randint(k1, (n,), 0, h - height + 1)
    lefts = jax.random.randint(k2, (n,), 0, w - width + 1)

    def crop(img, top, left):
        return jax.lax.dynamic_slice(img, (0, top, left), (c, height, width))

    return jax.vmap(crop)(x, tops, lefts)
