"""Dataset iterators with async prefetch.

Reference: org.nd4j.linalg.dataset.api.iterator.DataSetIterator and
AsyncDataSetIterator (background prefetch thread + bounded queue — the
I/O↔compute overlap boundary in SURVEY.md §3.1).

TPU design: the async wrapper prefetches AND device_puts ahead of compute, so
the jitted train step never waits on host→HBM transfer (double buffering).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from .dataset import DataSet
from ..obs.metrics import MetricsRegistry, get_registry

_prefetch_seq = itertools.count()


class DataSetIterator:
    """Base iterator protocol (reference: DataSetIterator).

    **Iterator-state protocol** (exact mid-epoch resume;
    train/checkpoint.py captures it in the checkpoint sidecar): a
    stateful iterator implements :meth:`state_dict` — a small JSON-able
    dict with at least ``{"epoch": int, "batches": int}`` describing the
    CONSUMER position (batches handed out this epoch, NOT any prefetch
    run-ahead) — and :meth:`load_state_dict`, which repositions a freshly
    built identical iterator so the next ``next()`` yields exactly the
    first batch the snapshotted consumer had not yet received. Wrappers
    delegate; iterators without a deterministic position (plain
    generators) keep the base behavior and raise."""

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        return self.next()

    def has_next(self) -> bool:
        raise NotImplementedError

    def next(self) -> DataSet:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def batch_size(self) -> int:
        raise NotImplementedError

    def state_dict(self) -> dict:
        raise NotImplementedError(
            f"{type(self).__name__} does not support iterator-state "
            "checkpointing (state_dict)")

    def load_state_dict(self, state: dict) -> None:
        raise NotImplementedError(
            f"{type(self).__name__} does not support iterator-state "
            "checkpointing (load_state_dict)")


class ListDataSetIterator(DataSetIterator):
    """Iterate over an in-memory DataSet in minibatches (reference:
    ListDataSetIterator / IteratorDataSetIterator)."""

    def __init__(self, data: DataSet, batch: int, shuffle: bool = False, seed: int = 0) -> None:
        self.data = data
        self.batch = batch
        self.shuffle = shuffle
        self.seed = seed
        self._epoch = 0
        self._order = np.arange(data.num_examples())
        self._pos = 0
        self.reset()

    def reset(self) -> None:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            self._order = rng.permutation(self.data.num_examples())
            self._epoch += 1
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < self.data.num_examples()

    def next(self) -> DataSet:
        idx = self._order[self._pos : self._pos + self.batch]
        self._pos += self.batch
        d = self.data
        return DataSet(
            d.features[idx], d.labels[idx],
            None if d.features_mask is None else d.features_mask[idx],
            None if d.labels_mask is None else d.labels_mask[idx],
        )

    def batch_size(self) -> int:
        return self.batch

    def state_dict(self) -> dict:
        return {"epoch": self._epoch, "batches": self._pos // self.batch}

    def load_state_dict(self, state: dict) -> None:
        # the active epoch's order was drawn with seed + (_epoch - 1)
        # (reset() draws, THEN increments _epoch) — regenerate it rather
        # than storing the permutation itself
        self._epoch = int(state["epoch"])
        self._pos = int(state["batches"]) * self.batch
        if self.shuffle and self._epoch > 0:
            rng = np.random.default_rng(self.seed + self._epoch - 1)
            self._order = rng.permutation(self.data.num_examples())
        else:
            self._order = np.arange(self.data.num_examples())


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch with a bounded queue (reference:
    AsyncDataSetIterator; queue_size = reference's default 8). Optionally
    applies ``device_put_fn`` on the worker thread so batches land on device
    before the consumer asks for them.

    Observability (obs/): ``dl4j_tpu_data_*`` series with an ``instance``
    label — prefetch queue depth + high-water mark, producer blocked time
    (queue full: compute is the bottleneck, good) and consumer starvation
    time (queue empty: INPUT is the bottleneck — the I/O↔compute overlap
    signal the TPU-pod reports scrape fleet-wide). :meth:`stats` is the
    per-instance view over the same children.

    Shutdown: a consumer abandoning iteration mid-epoch calls
    :meth:`close` (``reset`` does it implicitly) which stops and JOINS the
    prefetch thread instead of leaking it behind a full queue. ``close``
    is idempotent and safe to call concurrently (including while the
    producer is parked on a full queue).

    Device buffer ring: with ``device_put_fn`` set, each batch's H2D
    transfer is dispatched on the prefetch thread AT ENQUEUE TIME (JAX
    transfers are async, so the copy for step N+1 overlaps compute for
    step N — true double buffering). ``device_buffers=N`` bounds the
    ring: at most N batches may be resident/in-flight in device memory
    beyond the one the consumer holds, independent of the (host-side)
    ``queue_size`` — deep host prefetch without unbounded HBM. A slot is
    acquired before the transfer starts and released when the consumer
    dequeues the batch.
    """

    _SENTINEL = object()

    def __init__(
        self,
        underlying: DataSetIterator,
        queue_size: int = 8,
        device_put_fn: Optional[Callable[[DataSet], DataSet]] = None,
        registry: Optional[MetricsRegistry] = None,
        name: Optional[str] = None,
        device_buffers: Optional[int] = None,
    ) -> None:
        if device_buffers is not None and device_buffers < 1:
            raise ValueError(
                f"device_buffers must be >= 1, got {device_buffers}")
        self.underlying = underlying
        self.queue_size = queue_size
        self.device_put_fn = device_put_fn
        self.device_buffers = device_buffers
        self.name = name or f"prefetch-{next(_prefetch_seq)}"
        self._queue: queue.Queue = queue.Queue(maxsize=queue_size)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._next_item = None
        self._started = False
        self._stop = threading.Event()
        self._close_lock = threading.Lock()
        self._hits = 0  # dequeues served without waiting
        self._consumed = 0  # batches handed to the consumer this epoch
        self._dev_slots = self._make_ring()
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        inst = self.name
        self._g_depth = reg.gauge(
            "dl4j_tpu_data_prefetch_queue_depth",
            "Prefetched batches waiting for the consumer",
            ("instance",)).labels(inst)
        self._g_hwm = reg.gauge(
            "dl4j_tpu_data_prefetch_queue_high_water",
            "Prefetch queue depth high-water mark", ("instance",)).labels(inst)
        self._c_batches = reg.counter(
            "dl4j_tpu_data_prefetch_batches_total",
            "Batches produced by the prefetch thread", ("instance",)).labels(inst)
        self._c_blocked = reg.counter(
            "dl4j_tpu_data_producer_blocked_seconds_total",
            "Time the prefetch thread waited on a full queue "
            "(compute-bound — the healthy direction)", ("instance",)).labels(inst)
        self._c_starved = reg.counter(
            "dl4j_tpu_data_consumer_starvation_seconds_total",
            "Time the consumer waited on an empty queue "
            "(input-bound — the I/O bottleneck signal)", ("instance",)).labels(inst)
        # prefetch-starvation open item (ROADMAP): depth means nothing
        # without capacity, and the StepProfiler's data-wait story needs
        # the per-dequeue wait distribution, not just its total
        self._g_capacity = reg.gauge(
            "dl4j_tpu_data_prefetch_queue_capacity",
            "Prefetch queue capacity (bounded queue size)",
            ("instance",)).labels(inst)
        self._g_capacity.set(queue_size)
        self._h_wait = reg.histogram(
            "dl4j_tpu_data_fetch_wait_seconds",
            "Consumer-visible wait per dequeue (0 when a batch was "
            "already prefetched)", ("instance",)).labels(inst)

    def _make_ring(self) -> Optional[threading.Semaphore]:
        if self.device_buffers is None or self.device_put_fn is None:
            return None
        return threading.Semaphore(self.device_buffers)

    def _acquire_slot(self, stop: threading.Event) -> bool:
        """Take a device-ring slot; gives up when ``stop`` is set so an
        abandoned consumer never parks the thread on a full ring."""
        sem = self._dev_slots
        while not stop.is_set():
            if sem.acquire(timeout=0.05):
                return True
        return False

    def _put(self, item, stop: threading.Event) -> bool:
        """Bounded put that gives up when ``stop`` is set (an abandoned
        consumer never drains the queue, so a plain put() would park the
        thread forever). Returns False when aborted."""
        q = self._queue
        try:
            q.put_nowait(item)
            return True
        except queue.Full:
            pass
        t0 = time.perf_counter()
        try:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False
        finally:
            self._c_blocked.inc(time.perf_counter() - t0)

    def _worker(self, stop: threading.Event) -> None:
        try:
            while not stop.is_set() and self.underlying.has_next():
                item = self.underlying.next()
                if self.device_put_fn is not None:
                    if (self._dev_slots is not None
                            and not self._acquire_slot(stop)):
                        return
                    # async dispatch: the H2D copy starts NOW, on this
                    # thread, and overlaps the consumer's compute
                    item = self.device_put_fn(item)
                if not self._put(item, stop):
                    return
                self._c_batches.inc()
                depth = self._queue.qsize()
                self._g_depth.set(depth)
                self._g_hwm.set_max(depth)
        except BaseException as e:  # propagate to consumer
            self._error = e
        finally:
            self._put(self._SENTINEL, stop)

    def _ensure_started(self) -> None:
        if not self._started:
            self._thread = threading.Thread(
                target=self._worker, args=(self._stop,),
                name=f"dsi-{self.name}", daemon=True)
            self._thread.start()
            self._started = True
            self._advance()

    def _advance(self) -> None:
        q = self._queue
        try:
            item = q.get_nowait()
            self._hits += 1
            self._h_wait.observe(0.0)
        except queue.Empty:
            t0 = time.perf_counter()
            item = q.get()
            waited = time.perf_counter() - t0
            self._c_starved.inc(waited)
            self._h_wait.observe(waited)
        self._g_depth.set(q.qsize())
        if item is not self._SENTINEL and self._dev_slots is not None:
            self._dev_slots.release()  # consumer owns the batch now
        if item is self._SENTINEL:
            if self._error is not None:
                raise self._error
            self._next_item = None
        else:
            self._next_item = item

    def has_next(self) -> bool:
        self._ensure_started()
        return self._next_item is not None

    def next(self) -> DataSet:
        self._ensure_started()
        if self._next_item is None:
            raise StopIteration
        item = self._next_item
        self._advance()
        self._consumed += 1
        return item

    def close(self, timeout: float = 5.0) -> None:
        """Stop and join the prefetch thread WITHOUT consuming the rest of
        the epoch. Safe to call any time, idempotent, and safe to call
        CONCURRENTLY — including while the producer is parked on a full
        queue or a full device ring (both park-points poll ``_stop``).
        The old behavior (drain-to-exhaustion on reset) both leaked the
        thread behind a full queue and forced the whole underlying epoch
        to be produced."""
        self._stop.set()
        with self._close_lock:
            t = self._thread
            if t is not None:
                deadline = time.monotonic() + timeout
                while t.is_alive() and time.monotonic() < deadline:
                    try:
                        self._queue.get_nowait()  # unblock a parked put
                    except queue.Empty:
                        pass
                    t.join(timeout=0.05)
            self._thread = None
            self._started = False
            self._next_item = None
            self._g_depth.set(0)

    def reset(self) -> None:
        self.close()
        with self._close_lock:
            self.underlying.reset()
            self._reinit_pipeline()

    def _reinit_pipeline(self) -> None:
        self._queue = queue.Queue(maxsize=self.queue_size)
        self._stop = threading.Event()
        self._error = None
        self._started = False
        self._next_item = None
        self._consumed = 0
        self._dev_slots = self._make_ring()

    def state_dict(self) -> dict:
        """Consumer-position snapshot: the underlying iterator's epoch
        identity with ``batches`` overridden by the batches actually
        HANDED OUT — the prefetch thread's run-ahead (queued batches and
        the lookahead item) is deliberately not counted, so a resume
        re-produces exactly the batches the consumer never saw. Requires
        an underlying whose epoch only advances via ``reset()`` (the
        whole iterator family here; do not stack the async wrapper ON
        TOP of :class:`MultipleEpochsIterator` if you need resume)."""
        st = dict(self.underlying.state_dict())
        st["batches"] = self._consumed
        return st

    def load_state_dict(self, state: dict) -> None:
        self.close()
        with self._close_lock:
            self.underlying.load_state_dict(state)
            self._reinit_pipeline()
            self._consumed = int(state["batches"])

    def stats(self) -> dict:
        """Per-instance view over the registry children (one source of
        truth; see README "Observability"). All derived ratios are
        guarded against the zero-fetch case (stats() before any next())."""
        waits = int(self._h_wait.count)
        return {
            "queue_depth": self._queue.qsize(),
            "queue_capacity": self.queue_size,
            "queue_high_water": int(self._g_hwm.value),
            "device_buffers": self.device_buffers,
            "batches": int(self._c_batches.value),
            "producer_blocked_s": float(self._c_blocked.value),
            "consumer_starvation_s": float(self._c_starved.value),
            "fetches": waits,
            "mean_fetch_wait_s": (float(self._h_wait.sum) / waits
                                  if waits > 0 else 0.0),
            # share of dequeues served without blocking: 1.0 means the
            # prefetcher fully hid the input pipeline
            "prefetch_hit_rate": (self._hits / waits if waits > 0 else None),
        }

    def batch_size(self) -> int:
        return self.underlying.batch_size()


def device_put_dataset(ds: DataSet) -> DataSet:
    """Standard device_put_fn for AsyncDataSetIterator: moves features/labels
    to the default device on the prefetch thread so the train step's inputs
    are already in HBM."""
    import jax

    return DataSet(
        jax.device_put(ds.features),
        jax.device_put(ds.labels),
        None if ds.features_mask is None else jax.device_put(ds.features_mask),
        None if ds.labels_mask is None else jax.device_put(ds.labels_mask),
    )


class MultipleEpochsIterator(DataSetIterator):
    """Repeats an iterator for N epochs (reference: MultipleEpochsIterator)."""

    def __init__(self, underlying: DataSetIterator, epochs: int) -> None:
        self.underlying = underlying
        self.epochs = epochs
        self._epoch = 0

    def reset(self) -> None:
        self.underlying.reset()
        self._epoch = 0

    def has_next(self) -> bool:
        if self.underlying.has_next():
            return True
        if self._epoch + 1 < self.epochs:
            self._epoch += 1
            self.underlying.reset()
            return self.underlying.has_next()
        return False

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        return self.underlying.next()

    def batch_size(self) -> int:
        return self.underlying.batch_size()

    def state_dict(self) -> dict:
        st = dict(self.underlying.state_dict())
        st["multi_epoch"] = self._epoch
        return st

    def load_state_dict(self, state: dict) -> None:
        self._epoch = int(state.get("multi_epoch", 0))
        self.underlying.load_state_dict(state)


class MappedDataSetIterator(DataSetIterator):
    """Applies ``feature_fn`` (and optionally ``label_fn``) to each batch —
    the composition point for ON-DEVICE preprocessing: pass a jitted fn
    (cast/normalize/augment) and wrap an AsyncDataSetIterator whose
    device_put already landed the raw (e.g. uint8) batch in HBM. The
    augment program queues on the device stream ahead of the train step,
    so the host stays on the cheap byte path end to end."""

    def __init__(self, underlying: DataSetIterator, feature_fn,
                 label_fn=None) -> None:
        self.underlying = underlying
        self.feature_fn = feature_fn
        self.label_fn = label_fn

    def has_next(self) -> bool:
        return self.underlying.has_next()

    def next(self) -> DataSet:
        ds = self.underlying.next()
        return DataSet(
            self.feature_fn(ds.features),
            ds.labels if self.label_fn is None else self.label_fn(ds.labels),
            ds.features_mask, ds.labels_mask,
        )

    def reset(self) -> None:
        self.underlying.reset()

    def batch_size(self) -> int:
        return self.underlying.batch_size()

    def state_dict(self) -> dict:
        return self.underlying.state_dict()

    def load_state_dict(self, state: dict) -> None:
        self.underlying.load_state_dict(state)
