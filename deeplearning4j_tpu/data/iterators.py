"""Dataset iterators with async prefetch.

Reference: org.nd4j.linalg.dataset.api.iterator.DataSetIterator and
AsyncDataSetIterator (background prefetch thread + bounded queue — the
I/O↔compute overlap boundary in SURVEY.md §3.1).

TPU design: the async wrapper prefetches AND device_puts ahead of compute, so
the jitted train step never waits on host→HBM transfer (double buffering).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from .dataset import DataSet


class DataSetIterator:
    """Base iterator protocol (reference: DataSetIterator)."""

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        return self.next()

    def has_next(self) -> bool:
        raise NotImplementedError

    def next(self) -> DataSet:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def batch_size(self) -> int:
        raise NotImplementedError


class ListDataSetIterator(DataSetIterator):
    """Iterate over an in-memory DataSet in minibatches (reference:
    ListDataSetIterator / IteratorDataSetIterator)."""

    def __init__(self, data: DataSet, batch: int, shuffle: bool = False, seed: int = 0) -> None:
        self.data = data
        self.batch = batch
        self.shuffle = shuffle
        self.seed = seed
        self._epoch = 0
        self._order = np.arange(data.num_examples())
        self._pos = 0
        self.reset()

    def reset(self) -> None:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            self._order = rng.permutation(self.data.num_examples())
            self._epoch += 1
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < self.data.num_examples()

    def next(self) -> DataSet:
        idx = self._order[self._pos : self._pos + self.batch]
        self._pos += self.batch
        d = self.data
        return DataSet(
            d.features[idx], d.labels[idx],
            None if d.features_mask is None else d.features_mask[idx],
            None if d.labels_mask is None else d.labels_mask[idx],
        )

    def batch_size(self) -> int:
        return self.batch


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch with a bounded queue (reference:
    AsyncDataSetIterator; queue_size = reference's default 8). Optionally
    applies ``device_put_fn`` on the worker thread so batches land on device
    before the consumer asks for them."""

    _SENTINEL = object()

    def __init__(
        self,
        underlying: DataSetIterator,
        queue_size: int = 8,
        device_put_fn: Optional[Callable[[DataSet], DataSet]] = None,
    ) -> None:
        self.underlying = underlying
        self.queue_size = queue_size
        self.device_put_fn = device_put_fn
        self._queue: queue.Queue = queue.Queue(maxsize=queue_size)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._next_item = None
        self._started = False

    def _worker(self) -> None:
        try:
            while self.underlying.has_next():
                item = self.underlying.next()
                if self.device_put_fn is not None:
                    item = self.device_put_fn(item)
                self._queue.put(item)
        except BaseException as e:  # propagate to consumer
            self._error = e
        finally:
            self._queue.put(self._SENTINEL)

    def _ensure_started(self) -> None:
        if not self._started:
            self._thread = threading.Thread(target=self._worker, daemon=True)
            self._thread.start()
            self._started = True
            self._advance()

    def _advance(self) -> None:
        item = self._queue.get()
        if item is self._SENTINEL:
            if self._error is not None:
                raise self._error
            self._next_item = None
        else:
            self._next_item = item

    def has_next(self) -> bool:
        self._ensure_started()
        return self._next_item is not None

    def next(self) -> DataSet:
        self._ensure_started()
        if self._next_item is None:
            raise StopIteration
        item = self._next_item
        self._advance()
        return item

    def reset(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            # drain so the worker can exit
            while self._next_item is not None:
                self._advance()
            self._thread.join(timeout=5)
        self.underlying.reset()
        self._queue = queue.Queue(maxsize=self.queue_size)
        self._error = None
        self._started = False
        self._next_item = None

    def batch_size(self) -> int:
        return self.underlying.batch_size()


def device_put_dataset(ds: DataSet) -> DataSet:
    """Standard device_put_fn for AsyncDataSetIterator: moves features/labels
    to the default device on the prefetch thread so the train step's inputs
    are already in HBM."""
    import jax

    return DataSet(
        jax.device_put(ds.features),
        jax.device_put(ds.labels),
        None if ds.features_mask is None else jax.device_put(ds.features_mask),
        None if ds.labels_mask is None else jax.device_put(ds.labels_mask),
    )


class MultipleEpochsIterator(DataSetIterator):
    """Repeats an iterator for N epochs (reference: MultipleEpochsIterator)."""

    def __init__(self, underlying: DataSetIterator, epochs: int) -> None:
        self.underlying = underlying
        self.epochs = epochs
        self._epoch = 0

    def reset(self) -> None:
        self.underlying.reset()
        self._epoch = 0

    def has_next(self) -> bool:
        if self.underlying.has_next():
            return True
        if self._epoch + 1 < self.epochs:
            self._epoch += 1
            self.underlying.reset()
            return self.underlying.has_next()
        return False

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        return self.underlying.next()

    def batch_size(self) -> int:
        return self.underlying.batch_size()


class MappedDataSetIterator(DataSetIterator):
    """Applies ``feature_fn`` (and optionally ``label_fn``) to each batch —
    the composition point for ON-DEVICE preprocessing: pass a jitted fn
    (cast/normalize/augment) and wrap an AsyncDataSetIterator whose
    device_put already landed the raw (e.g. uint8) batch in HBM. The
    augment program queues on the device stream ahead of the train step,
    so the host stays on the cheap byte path end to end."""

    def __init__(self, underlying: DataSetIterator, feature_fn,
                 label_fn=None) -> None:
        self.underlying = underlying
        self.feature_fn = feature_fn
        self.label_fn = label_fn

    def has_next(self) -> bool:
        return self.underlying.has_next()

    def next(self) -> DataSet:
        ds = self.underlying.next()
        return DataSet(
            self.feature_fn(ds.features),
            ds.labels if self.label_fn is None else self.label_fn(ds.labels),
            ds.features_mask, ds.labels_mask,
        )

    def reset(self) -> None:
        self.underlying.reset()

    def batch_size(self) -> int:
        return self.underlying.batch_size()
