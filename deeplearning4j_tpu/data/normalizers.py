"""Data normalizers.

Reference: org.nd4j.linalg.dataset.api.preprocessor.{NormalizerStandardize,
NormalizerMinMaxScaler, ImagePreProcessingScaler, VGG16ImagePreProcessor}.
Same fit/transform protocol; serializable state for the ModelSerializer's
normalizer entry (SURVEY.md §5.4).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .dataset import DataSet


class DataNormalization:
    fit_labels: bool = False

    def fit(self, dataset_or_iterator) -> None:
        raise NotImplementedError

    def transform(self, dataset: DataSet) -> None:
        raise NotImplementedError

    def pre_process(self, dataset: DataSet) -> None:  # reference spelling
        self.transform(dataset)

    def revert(self, dataset: DataSet) -> None:
        raise NotImplementedError

    def state_dict(self) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def load_state_dict(self, d: Dict[str, np.ndarray]) -> None:
        raise NotImplementedError


def _iter_features(data) -> np.ndarray:
    if isinstance(data, DataSet):
        return data.features
    return np.concatenate([d.features for d in data])


class NormalizerStandardize(DataNormalization):
    """Zero-mean unit-variance per feature column."""

    def __init__(self) -> None:
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    def fit(self, data) -> None:
        feats = _iter_features(data)
        axes = tuple(i for i in range(feats.ndim) if i != 1) if feats.ndim > 2 else (0,)
        self.mean = feats.mean(axis=axes)
        self.std = feats.std(axis=axes) + 1e-8

    def _bshape(self, feats: np.ndarray):
        if feats.ndim > 2:
            return (1, -1) + (1,) * (feats.ndim - 2)
        return (1, -1)

    def transform(self, dataset: DataSet) -> None:
        s = self._bshape(dataset.features)
        dataset.features = (dataset.features - self.mean.reshape(s)) / self.std.reshape(s)

    def revert(self, dataset: DataSet) -> None:
        s = self._bshape(dataset.features)
        dataset.features = dataset.features * self.std.reshape(s) + self.mean.reshape(s)

    def state_dict(self):
        return {"kind": np.array("standardize"), "mean": self.mean, "std": self.std}

    def load_state_dict(self, d) -> None:
        self.mean, self.std = d["mean"], d["std"]


class NormalizerMinMaxScaler(DataNormalization):
    """Scale features to [min_range, max_range] (default [0,1])."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0) -> None:
        self.min_range = min_range
        self.max_range = max_range
        self.data_min: Optional[np.ndarray] = None
        self.data_max: Optional[np.ndarray] = None

    def fit(self, data) -> None:
        feats = _iter_features(data)
        self.data_min = feats.min(axis=0)
        self.data_max = feats.max(axis=0)

    def transform(self, dataset: DataSet) -> None:
        span = np.maximum(self.data_max - self.data_min, 1e-8)
        scaled = (dataset.features - self.data_min) / span
        dataset.features = scaled * (self.max_range - self.min_range) + self.min_range

    def revert(self, dataset: DataSet) -> None:
        span = np.maximum(self.data_max - self.data_min, 1e-8)
        unscaled = (dataset.features - self.min_range) / (self.max_range - self.min_range)
        dataset.features = unscaled * span + self.data_min

    def state_dict(self):
        return {
            "kind": np.array("minmax"),
            "min": self.data_min, "max": self.data_max,
            "range": np.array([self.min_range, self.max_range]),
        }

    def load_state_dict(self, d) -> None:
        self.data_min, self.data_max = d["min"], d["max"]
        self.min_range, self.max_range = float(d["range"][0]), float(d["range"][1])


class ImagePreProcessingScaler(DataNormalization):
    """Scale pixel values from [0, maxPixel] to [min, max] (reference:
    ImagePreProcessingScaler, default 0-255 -> 0-1). Stateless."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0, max_pixel: float = 255.0) -> None:
        self.min_range = min_range
        self.max_range = max_range
        self.max_pixel = max_pixel

    def fit(self, data) -> None:
        pass

    def transform(self, dataset: DataSet) -> None:
        dataset.features = (
            dataset.features / self.max_pixel * (self.max_range - self.min_range) + self.min_range
        )

    def revert(self, dataset: DataSet) -> None:
        dataset.features = (
            (dataset.features - self.min_range) / (self.max_range - self.min_range) * self.max_pixel
        )

    def state_dict(self):
        return {
            "kind": np.array("image"),
            "range": np.array([self.min_range, self.max_range, self.max_pixel]),
        }

    def load_state_dict(self, d) -> None:
        self.min_range, self.max_range, self.max_pixel = (float(v) for v in d["range"])


class VGG16ImagePreProcessor(DataNormalization):
    """Subtract ImageNet channel means (reference: VGG16ImagePreProcessor)."""

    MEANS = np.array([123.68, 116.779, 103.939], dtype=np.float32)

    def fit(self, data) -> None:
        pass

    def transform(self, dataset: DataSet) -> None:
        dataset.features = dataset.features - self.MEANS.reshape(1, 3, 1, 1)

    def revert(self, dataset: DataSet) -> None:
        dataset.features = dataset.features + self.MEANS.reshape(1, 3, 1, 1)

    def state_dict(self):
        return {"kind": np.array("vgg16")}

    def load_state_dict(self, d) -> None:
        pass
