from .dataset import DataSet, MultiDataSet

__all__ = ["DataSet", "MultiDataSet"]
