from .dataset import DataSet, MultiDataSet
from .fetchers import (Cifar10DataSetIterator, EmnistDataSetIterator,
                       SvhnDataSetIterator, TinyImageNetDataSetIterator)
from .iterators import (AsyncDataSetIterator, DataSetIterator,
                        ListDataSetIterator, MappedDataSetIterator,
                        MultipleEpochsIterator, device_put_dataset)
from .sharded import ShardedDataSetIterator, shard_paths
from .image_transform import (
    BrightnessTransform,
    CropImageTransform,
    FlipImageTransform,
    ImageTransform,
    PipelineImageTransform,
    RandomCropTransform,
    ResizeImageTransform,
    RotateImageTransform,
)
from .records import (
    CollectionRecordReader,
    CSVRecordReader,
    CSVSequenceRecordReader,
    ImageRecordReader,
    LineRecordReader,
    RecordReader,
    RecordReaderDataSetIterator,
    resolve_data_workers,
)
from .transform import (
    Schema,
    TransformProcess,
    TransformProcessRecordReader,
)

__all__ = [
    "AsyncDataSetIterator",
    "BrightnessTransform",
    "DataSetIterator",
    "ListDataSetIterator",
    "MappedDataSetIterator",
    "MultipleEpochsIterator",
    "ShardedDataSetIterator",
    "device_put_dataset",
    "resolve_data_workers",
    "shard_paths",
    "Cifar10DataSetIterator",
    "CollectionRecordReader",
    "CSVRecordReader",
    "CSVSequenceRecordReader",
    "CropImageTransform",
    "DataSet",
    "EmnistDataSetIterator",
    "SvhnDataSetIterator",
    "TinyImageNetDataSetIterator",
    "FlipImageTransform",
    "ImageRecordReader",
    "ImageTransform",
    "PipelineImageTransform",
    "RandomCropTransform",
    "ResizeImageTransform",
    "RotateImageTransform",
    "LineRecordReader",
    "MultiDataSet",
    "RecordReader",
    "RecordReaderDataSetIterator",
    "Schema",
    "TransformProcess",
    "TransformProcessRecordReader",
]
