from .dataset import DataSet, MultiDataSet
from .records import (
    CollectionRecordReader,
    CSVRecordReader,
    CSVSequenceRecordReader,
    ImageRecordReader,
    LineRecordReader,
    RecordReader,
    RecordReaderDataSetIterator,
)
from .transform import (
    Schema,
    TransformProcess,
    TransformProcessRecordReader,
)

__all__ = [
    "CollectionRecordReader",
    "CSVRecordReader",
    "CSVSequenceRecordReader",
    "DataSet",
    "ImageRecordReader",
    "LineRecordReader",
    "MultiDataSet",
    "RecordReader",
    "RecordReaderDataSetIterator",
    "Schema",
    "TransformProcess",
    "TransformProcessRecordReader",
]
