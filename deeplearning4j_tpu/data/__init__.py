from .dataset import DataSet, MultiDataSet
from .fetchers import (Cifar10DataSetIterator, EmnistDataSetIterator,
                       SvhnDataSetIterator, TinyImageNetDataSetIterator)
from .image_transform import (
    BrightnessTransform,
    CropImageTransform,
    FlipImageTransform,
    ImageTransform,
    PipelineImageTransform,
    RandomCropTransform,
    ResizeImageTransform,
    RotateImageTransform,
)
from .records import (
    CollectionRecordReader,
    CSVRecordReader,
    CSVSequenceRecordReader,
    ImageRecordReader,
    LineRecordReader,
    RecordReader,
    RecordReaderDataSetIterator,
)
from .transform import (
    Schema,
    TransformProcess,
    TransformProcessRecordReader,
)

__all__ = [
    "BrightnessTransform",
    "Cifar10DataSetIterator",
    "CollectionRecordReader",
    "CSVRecordReader",
    "CSVSequenceRecordReader",
    "CropImageTransform",
    "DataSet",
    "EmnistDataSetIterator",
    "SvhnDataSetIterator",
    "TinyImageNetDataSetIterator",
    "FlipImageTransform",
    "ImageRecordReader",
    "ImageTransform",
    "PipelineImageTransform",
    "RandomCropTransform",
    "ResizeImageTransform",
    "RotateImageTransform",
    "LineRecordReader",
    "MultiDataSet",
    "RecordReader",
    "RecordReaderDataSetIterator",
    "Schema",
    "TransformProcess",
    "TransformProcessRecordReader",
]
