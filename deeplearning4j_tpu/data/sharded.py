"""Sharded per-host data loading — the MLPerf TPU-pod input design.

Reference: "Scale MLPerf-0.6 models on Google TPU-v3 Pods" (PAPERS.md):
at pod scale every host reads, decodes, and feeds ONLY its own mesh
shard; no host ever materializes (or transfers) another host's rows.
The from-files path here splits into two pieces:

* :func:`shard_paths` — deterministic file partition by
  ``(process_index, process_count)``: every file lands in exactly one
  host shard, shard sizes differ by at most one, and a 1-host run is the
  identity (so sharded loading is bit-exact against the unsharded
  loader).
* :class:`ShardedDataSetIterator` — wraps a per-host iterator (its
  batches are this host's LOCAL rows) and assembles each batch into a
  GLOBAL ``jax.Array`` against a batch-dim :class:`~jax.sharding.
  Sharding`: one ``device_put`` per addressable shard (transfers start
  immediately and overlap each other) stitched with
  ``jax.make_array_from_single_device_arrays``. The result feeds
  :class:`~deeplearning4j_tpu.parallel.trainer.DistributedTrainer`
  directly — the trainer recognizes pre-sharded arrays and skips its own
  full-batch ``device_put`` (previously every host staged the whole
  global batch through one device transfer).

Composes with :class:`~.iterators.AsyncDataSetIterator` (assembly on the
prefetch thread → H2D for step N+1 overlaps compute for step N) and with
:meth:`~deeplearning4j_tpu.obs.step_profiler.StepProfiler.wrap_iterator`.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, TypeVar

import numpy as np

from .dataset import DataSet
from .iterators import DataSetIterator

T = TypeVar("T")


def shard_paths(paths: Sequence[T], index: int, count: int) -> List[T]:
    """Deterministic per-host partition of a file list.

    Round-robin by position: host ``i`` of ``count`` takes
    ``paths[i::count]``. Properties (enforced by tier-1):

    * every path appears in exactly one shard,
    * shard sizes differ by at most 1,
    * ``count=1`` returns the list unchanged (bit-exact single-host run).

    Callers must pass the SAME ``paths`` order on every host (e.g. the
    sorted walk of :class:`~.records.ImageRecordReader`) — the partition
    is positional, so order skew would double-read some files and drop
    others.
    """
    if count < 1:
        raise ValueError(f"count must be >= 1, got {count}")
    if not 0 <= index < count:
        raise ValueError(f"index must be in [0, {count}), got {index}")
    return list(paths[index::count])


class ShardedDataSetIterator(DataSetIterator):
    """Per-host batches → globally-sharded device batches.

    ``underlying`` yields this host's LOCAL rows of each global batch
    (typically a :class:`~.records.RecordReaderDataSetIterator` over an
    :class:`~.records.ImageRecordReader` built from
    ``shard_paths(all_paths, process_index, process_count)``).
    ``sharding`` is the batch-dim sharding the training step consumes —
    pass :attr:`DistributedTrainer.data_sharding`. Each ``next()``:

    1. optionally applies ``feature_fn``/``label_fn`` on host (dtype
       prep — the assembled array feeds the jitted step as-is),
    2. slices the local batch into this process's addressable shards and
       ``device_put``\\ s each slice to its owning device (transfers are
       async and start here, NOT at first use),
    3. stitches the global array with
       ``jax.make_array_from_single_device_arrays``.

    The local batch size must equal the rows this process owns under
    ``sharding`` (global batch = local batch × ``process_count``).
    """

    def __init__(self, underlying: DataSetIterator, sharding, *,
                 process_count: Optional[int] = None,
                 feature_fn: Optional[Callable[[np.ndarray], np.ndarray]] = None,
                 label_fn: Optional[Callable[[np.ndarray], np.ndarray]] = None,
                 ) -> None:
        import jax

        self.underlying = underlying
        self.sharding = sharding
        self.process_count = (int(process_count) if process_count is not None
                              else jax.process_count())
        if self.process_count < 1:
            raise ValueError("process_count must be >= 1")
        self.feature_fn = feature_fn
        self.label_fn = label_fn

    # ----- assembly ---------------------------------------------------
    def _assemble(self, arr: np.ndarray):
        """Local [rows, ...] host array → global jax.Array under
        ``self.sharding`` via one device_put per addressable shard."""
        import jax

        arr = np.asarray(arr)
        local_rows = arr.shape[0]
        global_shape = (local_rows * self.process_count,) + arr.shape[1:]
        idx_map = self.sharding.addressable_devices_indices_map(global_shape)
        spans = []
        for dev, idx in idx_map.items():
            sl = idx[0] if idx else slice(None)
            start = 0 if sl.start is None else int(sl.start)
            stop = global_shape[0] if sl.stop is None else int(sl.stop)
            spans.append((start, stop, dev))
        offset = min(s for s, _, _ in spans)
        owned = {(s, e) for s, e, _ in spans}  # devices may replicate a span
        owned_rows = sum(e - s for s, e in owned)
        if owned_rows != local_rows or any(
                s - offset < 0 or e - offset > local_rows for s, e in owned):
            n_shards = len(owned)
            raise ValueError(
                f"local batch of {local_rows} rows does not cover this "
                f"process's {owned_rows} rows under the sharding "
                f"({n_shards} local shard(s), process_count="
                f"{self.process_count}); local batch must be "
                f"global_batch / process_count and divide the data axis")
        shards = [jax.device_put(arr[s - offset:e - offset], dev)
                  for s, e, dev in spans]
        return jax.make_array_from_single_device_arrays(
            global_shape, self.sharding, shards)

    def _assemble_ds(self, ds: DataSet) -> DataSet:
        feats = np.asarray(ds.features)
        labels = np.asarray(ds.labels)
        if self.feature_fn is not None:
            feats = np.asarray(self.feature_fn(feats))
        if self.label_fn is not None:
            labels = np.asarray(self.label_fn(labels))
        return DataSet(
            self._assemble(feats),
            self._assemble(labels),
            None if ds.features_mask is None
            else self._assemble(np.asarray(ds.features_mask)),
            None if ds.labels_mask is None
            else self._assemble(np.asarray(ds.labels_mask)),
        )

    # ----- DataSetIterator protocol -----------------------------------
    def has_next(self) -> bool:
        return self.underlying.has_next()

    def next(self) -> DataSet:
        return self._assemble_ds(self.underlying.next())

    def reset(self) -> None:
        self.underlying.reset()

    def batch_size(self) -> int:
        """GLOBAL batch size (what the training step sees)."""
        return self.underlying.batch_size() * self.process_count

    def local_batch_size(self) -> int:
        return self.underlying.batch_size()

    def state_dict(self) -> dict:
        """Delegates, plus records the GLOBAL batch size. The sharded
        assembly is stateless per batch, so the consumer position IS the
        per-host underlying's position. Every host checkpoints/restores
        its own shard's cursor — PR 7's deterministic sharding makes the
        union exact.

        ``global_batch`` is the elastic-resize contract: the per-host
        cursor counts *global steps* (one local batch per global step at
        any width), so the state carries across a changed shard layout
        exactly when the restoring pipeline keeps the same global batch
        (per-replica batch recomputed as global/width). A mismatch would
        silently bend the LAMB/warmup trajectory, so ``load_state_dict``
        refuses it."""
        state = dict(self.underlying.state_dict())
        state["global_batch"] = int(self.batch_size())
        return state

    def load_state_dict(self, state: dict) -> None:
        state = dict(state)
        saved_global = state.pop("global_batch", None)
        if saved_global is not None and int(saved_global) != int(
                self.batch_size()):
            raise ValueError(
                f"global batch mismatch on restore: checkpoint was taken "
                f"at global batch {int(saved_global)}, this pipeline "
                f"yields {int(self.batch_size())}; elastic resize is "
                f"width-invariant in the GLOBAL batch — recompute the "
                f"per-replica batch as global_batch / data-axis width")
        self.underlying.load_state_dict(state)

    def reshard(self, underlying: DataSetIterator, sharding=None, *,
                process_count: Optional[int] = None) -> None:
        """Re-point this iterator at a new shard layout WITHOUT a cold
        pipeline restart: carry the current global consumed-batch cursor
        onto ``underlying`` (this host's iterator over its NEW
        ``shard_paths(paths, index', count')`` partition, positioned by
        ``load_state_dict``), swap in the new batch-dim ``sharding``
        (e.g. the rebuilt trainer's ``data_sharding``) and
        ``process_count``. The new layout must preserve the global batch
        size — validated by the ``global_batch`` contract above."""
        if process_count is not None and int(process_count) < 1:
            raise ValueError("process_count must be >= 1")
        state = self.state_dict()
        old = (self.underlying, self.sharding, self.process_count)
        self.underlying = underlying
        if sharding is not None:
            self.sharding = sharding
        if process_count is not None:
            self.process_count = int(process_count)
        try:
            self.load_state_dict(state)
        except Exception:
            self.underlying, self.sharding, self.process_count = old
            raise
        if old[0] is not underlying:
            c = getattr(old[0], "close", None)
            if callable(c):
                c()

    def stats(self) -> dict:
        s = getattr(self.underlying, "stats", None)
        return s() if callable(s) else {}

    def close(self, *a, **kw) -> None:
        c = getattr(self.underlying, "close", None)
        if callable(c):
            c(*a, **kw)
