"""CIFAR-10 / EMNIST-shaped dataset fetchers.

Reference: org.deeplearning4j.datasets.iterator.impl.{Cifar10DataSetIterator,
EmnistDataSetIterator} and the datasets-fetchers family (SURVEY.md §2.2
"Dataset fetchers"). No network exists in this environment (SURVEY.md §7),
so — like data/mnist.py — these produce DETERMINISTIC PROCEDURAL datasets at
the real datasets' exact shapes, learnable and suitable for shape-true
pipeline/throughput work, with provenance recorded. Real data dropped at
``~/.dl4j_tpu/cifar10.npz`` / ``~/.dl4j_tpu/emnist-<split>.npz`` (keras npz
layout) is used instead when present.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from .dataset import DataSet
from .iterators import ListDataSetIterator

CIFAR_PROVENANCE = "procedural-cifar10-v1 (synthetic; no-network environment)"
EMNIST_PROVENANCE = "procedural-emnist-v1 (synthetic; no-network environment)"

# EMNIST split -> class count (reference: EmnistDataSetIterator.Set)
EMNIST_SPLITS = {"mnist": 10, "digits": 10, "letters": 26, "balanced": 47,
                 "byclass": 62, "bymerge": 47}


def _cifar_example(cls: int, rng: np.random.Generator) -> np.ndarray:
    """One 3x32x32 image: class-keyed hue + oriented texture + a class
    shape, noised — separable but not trivial."""
    base = np.zeros((3, 32, 32), np.float32)
    hue = np.asarray([((cls * 3 + c) % 10) / 10.0 for c in range(3)],
                     np.float32)
    base += hue[:, None, None] * rng.uniform(0.4, 0.8)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32)
    angle = cls * np.pi / 10.0
    wave = np.sin((xx * np.cos(angle) + yy * np.sin(angle)) *
                  (0.3 + 0.05 * cls) + rng.uniform(0, 6.28))
    base += 0.2 * wave[None]
    cy, cx = rng.integers(8, 24), rng.integers(8, 24)
    r = 4 + (cls % 5)
    m = ((yy - cy) ** 2 + (xx - cx) ** 2) < r * r
    base[cls % 3, m] = rng.uniform(0.7, 1.0)
    base += rng.normal(0, 0.08, base.shape).astype(np.float32)
    return np.clip(base, 0.0, 1.0)


def _emnist_glyph(cls: int, n_classes: int,
                  rng: np.random.Generator) -> np.ndarray:
    """28x28 glyph: a fixed per-class 7x5 bitmap (class-seeded, so every
    class has one stable shape) placed with random geometry + noise."""
    pattern_rng = np.random.default_rng(10_000 + cls)  # class-stable glyph
    bitmap = (pattern_rng.random((7, 5)) > 0.5).astype(np.float32)
    bitmap[0, :] = 1.0  # guarantee some ink
    scale = rng.integers(2, 4)
    glyph = np.kron(bitmap, np.ones((scale, scale), np.float32))
    gh, gw = glyph.shape
    img = np.zeros((28, 28), np.float32)
    top = rng.integers(0, 28 - gh + 1)
    left = rng.integers(0, 28 - gw + 1)
    img[top: top + gh, left: left + gw] = glyph * rng.uniform(0.6, 1.0)
    img += rng.normal(0, 0.08, img.shape).astype(np.float32)
    return np.clip(img, 0.0, 1.0)


def _load_npz(path: str, flatten: Optional[int], train: bool):
    path = os.path.expanduser(path)
    if not os.path.exists(path):
        return None
    z = np.load(path)
    x = z["x_train" if train else "x_test"].astype(np.float32) / 255.0
    y = z["y_train" if train else "y_test"].astype(np.int64)
    if flatten:
        x = x.reshape(len(x), flatten)
    return x, y


# Cifar10DataSetIterator is defined below as a subclass of the shared
# _ProceduralImageIterator (same npz-override/procedural skeleton as SVHN
# and TinyImageNet).


class EmnistDataSetIterator(ListDataSetIterator):
    """Reference-shaped: EmnistDataSetIterator(split, batch[, train]).
    Features [n, 784] in [0, 1]; labels one-hot over the split's classes."""

    def __init__(self, split: str, batch: int, train: bool = True,
                 seed: int = 123, num_examples: Optional[int] = None,
                 shuffle: bool = True,
                 shard: Optional[Tuple[int, int]] = None) -> None:
        if split not in EMNIST_SPLITS:
            raise ValueError(
                f"unknown EMNIST split {split!r}; one of {sorted(EMNIST_SPLITS)}")
        k = EMNIST_SPLITS[split]
        real = _load_npz(f"~/.dl4j_tpu/emnist-{split}.npz", 784, train)
        if real is not None:
            x, y = real
            self.provenance = f"emnist-{split}.npz (real)"
        else:
            n = num_examples or (8192 if train else 1024)
            rng = np.random.default_rng(seed if train else seed + 999)
            y = rng.integers(0, k, size=n)
            x = np.stack([_emnist_glyph(int(c), k, rng) for c in y])
            x = x.reshape(n, 784)
            self.provenance = EMNIST_PROVENANCE
        if num_examples is not None:
            x, y = x[:num_examples], y[:num_examples]
        x, y = _apply_shard(x, y, shard)
        labels = np.eye(k, dtype=np.float32)[y]
        self.num_classes = k
        super().__init__(DataSet(x, labels), batch, shuffle=shuffle, seed=seed)


def _apply_shard(x, y, shard: Optional[Tuple[int, int]]):
    """Per-host rows for multi-process training (sharded loading,
    data/sharded.py): host ``i`` of ``count`` keeps every count-th
    example — sizes within 1, every example on exactly one host, and
    ``(0, 1)`` is the identity."""
    if shard is None:
        return x, y
    index, count = shard
    if count < 1 or not 0 <= index < count:
        raise ValueError(f"invalid shard {shard!r}; want (index, count)")
    return x[index::count], y[index::count]
SVHN_PROVENANCE = "procedural-svhn-v1 (synthetic; no-network environment)"
TINYIMAGENET_PROVENANCE = \
    "procedural-tinyimagenet-v1 (synthetic; no-network environment)"


def _class_image(cls: int, n_classes: int, rng: np.random.Generator,
                 size: int, channels: int) -> np.ndarray:
    """Class-conditioned procedural image, learnable at any class count:
    class identity is factored into stripe orientation (cls mod 10) and a
    strong localized blob whose grid position encodes cls // 10 — every
    class pair differs in at least one high-amplitude factor."""
    img = rng.normal(0.45, 0.08, (size, size, channels)).astype(np.float32)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
    ang = 2 * np.pi * (cls % 10) / 10.0
    stripe = 0.5 + 0.5 * np.sin(
        8 * np.pi * (xx * np.cos(ang) + yy * np.sin(ang)) + cls)
    block = cls // 10  # blob grid position encodes the coarse class
    grid = max(int(np.ceil(np.sqrt(max(n_classes // 10, 1)))), 1)
    cy = 0.15 + 0.7 * (block % grid) / max(grid - 1, 1) if grid > 1 else 0.5
    cx = 0.15 + 0.7 * (block // grid) / max(grid - 1, 1) if grid > 1 else 0.5
    blob = np.exp(-(((yy - cy) ** 2 + (xx - cx) ** 2) / 0.01))
    for c in range(channels):
        w = 0.5 + 0.5 * np.cos(ang + c)
        img[:, :, c] += 0.35 * w * stripe + 0.5 * blob
    return np.clip(img, 0.0, 1.0).transpose(2, 0, 1)  # NCHW


class _ProceduralImageIterator(ListDataSetIterator):
    """Shared loader for image datasets with an npz-real-data override and
    a class-conditioned procedural fallback (the Cifar10 recipe)."""

    def __init__(self, npz_name: str, num_classes: int, size: int,
                 provenance: str, default_train: int, default_eval: int,
                 batch: int, train: bool, seed: int,
                 num_examples: Optional[int], shuffle: bool,
                 make_example=None,
                 shard: Optional[Tuple[int, int]] = None) -> None:
        real = _load_npz(f"~/.dl4j_tpu/{npz_name}", None, train)
        if real is not None:
            x, y = real
            if x.ndim == 4 and x.shape[-1] == 3:  # NHWC npz -> NCHW
                x = x.transpose(0, 3, 1, 2)
            self.provenance = f"{npz_name} (real)"
        else:
            gen = make_example or (
                lambda c, rng: _class_image(c, num_classes, rng, size, 3))
            n = num_examples or (default_train if train else default_eval)
            rng = np.random.default_rng(seed if train else seed + 999)
            y = rng.integers(0, num_classes, size=n)
            x = np.stack([gen(int(c), rng) for c in y])
            self.provenance = provenance
        if num_examples is not None:
            x, y = x[:num_examples], y[:num_examples]
        x, y = _apply_shard(x, y, shard)
        labels = np.eye(num_classes, dtype=np.float32)[y]
        super().__init__(DataSet(x, labels), batch, shuffle=shuffle,
                         seed=seed)


class Cifar10DataSetIterator(_ProceduralImageIterator):
    """Reference-shaped: Cifar10DataSetIterator(batch[, train, seed]).
    Features [n, 3, 32, 32] (NCHW) in [0, 1]; labels one-hot [n, 10]."""

    NUM_CLASSES = 10

    def __init__(self, batch: int, train: bool = True, seed: int = 123,
                 num_examples: Optional[int] = None,
                 shuffle: bool = True,
                 shard: Optional[Tuple[int, int]] = None) -> None:
        super().__init__("cifar10.npz", 10, 32, CIFAR_PROVENANCE, 8192, 1024,
                         batch, train, seed, num_examples, shuffle,
                         make_example=_cifar_example, shard=shard)


class SvhnDataSetIterator(_ProceduralImageIterator):
    """Reference-shaped: SvhnDataSetIterator(batch[, train]) — Street View
    House Numbers. Features [n, 3, 32, 32] NCHW in [0, 1]; labels one-hot
    [n, 10]. Real data at ``~/.dl4j_tpu/svhn.npz`` is preferred."""

    NUM_CLASSES = 10

    def __init__(self, batch: int, train: bool = True, seed: int = 123,
                 num_examples: Optional[int] = None,
                 shuffle: bool = True,
                 shard: Optional[Tuple[int, int]] = None) -> None:
        super().__init__("svhn.npz", 10, 32, SVHN_PROVENANCE, 8192, 1024,
                         batch, train, seed, num_examples, shuffle,
                         shard=shard)


class TinyImageNetDataSetIterator(_ProceduralImageIterator):
    """Reference-shaped: TinyImageNetDataSetIterator(batch[, train]) —
    200 classes at [3, 64, 64] NCHW. Real data at
    ``~/.dl4j_tpu/tinyimagenet.npz`` is preferred."""

    NUM_CLASSES = 200

    def __init__(self, batch: int, train: bool = True, seed: int = 123,
                 num_examples: Optional[int] = None,
                 shuffle: bool = True,
                 shard: Optional[Tuple[int, int]] = None) -> None:
        super().__init__("tinyimagenet.npz", 200, 64,
                         TINYIMAGENET_PROVENANCE, 4096, 512,
                         batch, train, seed, num_examples, shuffle,
                         shard=shard)
