"""MNIST-shaped dataset.

Reference: org.deeplearning4j.datasets.iterator.impl.MnistDataSetIterator
(the LeNet-MNIST benchmark input, BASELINE.json:7). This environment has no
network access (SURVEY.md §7 env facts), so real MNIST cannot be downloaded;
this module produces a DETERMINISTIC PROCEDURAL dataset at MNIST shape
(28x28 grayscale, 10 classes): seven-segment-style digit glyphs rasterized
with per-example random translation, scaling, stroke noise and background
noise. It is learnable (a LeNet reaches >97% quickly) and serves as the
documented stand-in for throughput benchmarks — provenance is recorded by
``PROVENANCE`` below, per BASELINE.md measurement notes.

If a real ``mnist.npz`` (keras layout) is placed at ``~/.dl4j_tpu/mnist.npz``
it is used instead.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from .dataset import DataSet
from .iterators import DataSetIterator, ListDataSetIterator

PROVENANCE = "procedural-7seg-v1 (synthetic; no-network environment)"

# seven-segment layout:  segments (top, top-left, top-right, middle,
# bottom-left, bottom-right, bottom)
_SEGMENTS = {
    0: (1, 1, 1, 0, 1, 1, 1),
    1: (0, 0, 1, 0, 0, 1, 0),
    2: (1, 0, 1, 1, 1, 0, 1),
    3: (1, 0, 1, 1, 0, 1, 1),
    4: (0, 1, 1, 1, 0, 1, 0),
    5: (1, 1, 0, 1, 0, 1, 1),
    6: (1, 1, 0, 1, 1, 1, 1),
    7: (1, 0, 1, 0, 0, 1, 0),
    8: (1, 1, 1, 1, 1, 1, 1),
    9: (1, 1, 1, 1, 0, 1, 1),
}


def _render_digit(digit: int, rng: np.random.Generator) -> np.ndarray:
    """Rasterize one 28x28 glyph with random geometry."""
    img = np.zeros((28, 28), dtype=np.float32)
    # glyph box with random position/size
    h = rng.integers(16, 22)
    w = rng.integers(8, 13)
    top = rng.integers(2, 28 - h - 1)
    left = rng.integers(2, 28 - w - 1)
    t = rng.integers(2, 4)  # stroke thickness
    mid = top + h // 2
    seg = _SEGMENTS[digit]
    if seg[0]:
        img[top : top + t, left : left + w] = 1.0
    if seg[1]:
        img[top : mid, left : left + t] = 1.0
    if seg[2]:
        img[top : mid, left + w - t : left + w] = 1.0
    if seg[3]:
        img[mid : mid + t, left : left + w] = 1.0
    if seg[4]:
        img[mid : top + h, left : left + t] = 1.0
    if seg[5]:
        img[mid : top + h, left + w - t : left + w] = 1.0
    if seg[6]:
        img[top + h - t : top + h, left : left + w] = 1.0
    # stroke intensity variation + blur-ish noise
    img *= rng.uniform(0.6, 1.0)
    img += rng.normal(0, 0.08, img.shape).astype(np.float32)
    return np.clip(img, 0.0, 1.0)


def _generate(n: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n)
    images = np.stack([_render_digit(int(d), rng) for d in labels])
    return images.reshape(n, 784).astype(np.float32), labels.astype(np.int64)


def _load_real() -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    path = os.path.expanduser("~/.dl4j_tpu/mnist.npz")
    if not os.path.exists(path):
        return None
    z = np.load(path)
    return (
        z["x_train"].reshape(-1, 784).astype(np.float32) / 255.0,
        z["y_train"].astype(np.int64),
        z["x_test"].reshape(-1, 784).astype(np.float32) / 255.0,
        z["y_test"].astype(np.int64),
    )


class MnistDataSetIterator(ListDataSetIterator):
    """Reference-shaped constructor: MnistDataSetIterator(batch, train[, seed]).
    Features [n, 784] in [0,1]; labels one-hot [n, 10]."""

    def __init__(
        self,
        batch: int,
        train: bool = True,
        seed: int = 123,
        num_examples: Optional[int] = None,
        shuffle: bool = True,
    ) -> None:
        real = _load_real()
        if real is not None:
            xtr, ytr, xte, yte = real
            x, y = (xtr, ytr) if train else (xte, yte)
            self.provenance = "mnist.npz (real)"
        else:
            n = num_examples or (12800 if train else 2048)
            x, y = _generate(n, seed if train else seed + 999)
            self.provenance = PROVENANCE
        if num_examples is not None:
            x, y = x[:num_examples], y[:num_examples]
        labels = np.eye(10, dtype=np.float32)[y]
        super().__init__(DataSet(x, labels), batch, shuffle=shuffle, seed=seed)
