"""Schema + TransformProcess — DataVec's declarative ETL.

Reference: org.datavec.api.transform.{schema.Schema, TransformProcess}
(SURVEY.md §2.2 "DataVec API"): a typed column schema and an ordered,
serializable list of column transforms executed over records. The
serializable-pipeline property is preserved — a TransformProcess
round-trips through JSON (to_json/from_json), like every config object in
this framework (config-is-data, SURVEY.md §5.6).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import math
from typing import Any, Callable, Dict, List, Optional, Sequence

from .records import Record, RecordReader


class ColumnType(enum.Enum):
    DOUBLE = "double"
    INTEGER = "integer"
    STRING = "string"
    CATEGORICAL = "categorical"


@dataclasses.dataclass(frozen=True)
class ColumnMeta:
    name: str
    type: ColumnType
    categories: tuple = ()  # for CATEGORICAL


class Schema:
    """Typed column schema (reference: org.datavec.api.transform.schema.Schema)."""

    def __init__(self, columns: Sequence[ColumnMeta]) -> None:
        self.columns = list(columns)
        names = [c.name for c in self.columns]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names: {names}")

    def names(self) -> List[str]:
        return [c.name for c in self.columns]

    def index_of(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(f"no column {name!r}; have {self.names()}")

    def column(self, name: str) -> ColumnMeta:
        return self.columns[self.index_of(name)]

    @staticmethod
    def builder() -> "SchemaBuilder":
        return SchemaBuilder()

    def to_dict(self) -> Dict[str, Any]:
        return {"columns": [
            {"name": c.name, "type": c.type.value,
             "categories": list(c.categories)} for c in self.columns]}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Schema":
        return Schema([ColumnMeta(c["name"], ColumnType(c["type"]),
                                  tuple(c.get("categories", ())))
                       for c in d["columns"]])


class SchemaBuilder:
    def __init__(self) -> None:
        self._cols: List[ColumnMeta] = []

    def add_double_column(self, name: str) -> "SchemaBuilder":
        self._cols.append(ColumnMeta(name, ColumnType.DOUBLE))
        return self

    def add_integer_column(self, name: str) -> "SchemaBuilder":
        self._cols.append(ColumnMeta(name, ColumnType.INTEGER))
        return self

    def add_string_column(self, name: str) -> "SchemaBuilder":
        self._cols.append(ColumnMeta(name, ColumnType.STRING))
        return self

    def add_categorical_column(self, name: str,
                               categories: Sequence[str]) -> "SchemaBuilder":
        self._cols.append(ColumnMeta(name, ColumnType.CATEGORICAL,
                                     tuple(categories)))
        return self

    def build(self) -> Schema:
        return Schema(self._cols)


# ---------------------------------------------------------------------------
# Transform ops. Each op: apply(records, schema) -> (records, new_schema),
# and a dict round-trip for serialization.
# ---------------------------------------------------------------------------

_OP_REGISTRY: Dict[str, type] = {}


def _register(cls):
    _OP_REGISTRY[cls.kind] = cls
    return cls


class TransformOp:
    kind = "base"

    def apply(self, records, schema):
        raise NotImplementedError

    def to_dict(self) -> Dict[str, Any]:
        d = dict(self.__dict__)
        d["kind"] = self.kind
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]):
        d = dict(d)
        d.pop("kind")
        return cls(**d)


@_register
class RemoveColumns(TransformOp):
    kind = "remove_columns"

    def __init__(self, names: Sequence[str]) -> None:
        self.names = list(names)

    def apply(self, records, schema):
        idxs = sorted(schema.index_of(n) for n in self.names)
        keep = [i for i in range(len(schema.columns)) if i not in idxs]
        new_schema = Schema([schema.columns[i] for i in keep])
        return [[r[i] for i in keep] for r in records], new_schema


@_register
class RenameColumn(TransformOp):
    kind = "rename_column"

    def __init__(self, old: str, new: str) -> None:
        self.old, self.new = old, new

    def apply(self, records, schema):
        i = schema.index_of(self.old)
        cols = list(schema.columns)
        cols[i] = dataclasses.replace(cols[i], name=self.new)
        return records, Schema(cols)


@_register
class CategoricalToOneHot(TransformOp):
    kind = "categorical_to_one_hot"

    def __init__(self, name: str) -> None:
        self.name = name

    def apply(self, records, schema):
        i = schema.index_of(self.name)
        col = schema.columns[i]
        if col.type is not ColumnType.CATEGORICAL:
            raise ValueError(f"{self.name} is {col.type}, not categorical")
        cats = list(col.categories)
        cols = list(schema.columns)
        cols[i:i + 1] = [ColumnMeta(f"{self.name}[{c}]", ColumnType.DOUBLE)
                         for c in cats]
        out = []
        for r in records:
            v = r[i]
            if v not in cats:
                raise ValueError(f"unknown category {v!r} for {self.name}")
            onehot = [1.0 if c == v else 0.0 for c in cats]
            out.append(list(r[:i]) + onehot + list(r[i + 1:]))
        return out, Schema(cols)


@_register
class StringToCategorical(TransformOp):
    kind = "string_to_categorical"

    def __init__(self, name: str, categories: Sequence[str]) -> None:
        self.name = name
        self.categories = list(categories)

    def apply(self, records, schema):
        i = schema.index_of(self.name)
        cols = list(schema.columns)
        cols[i] = ColumnMeta(self.name, ColumnType.CATEGORICAL,
                             tuple(self.categories))
        return records, Schema(cols)


@_register
class CategoricalToInteger(TransformOp):
    kind = "categorical_to_integer"

    def __init__(self, name: str) -> None:
        self.name = name

    def apply(self, records, schema):
        i = schema.index_of(self.name)
        col = schema.columns[i]
        if col.type is not ColumnType.CATEGORICAL:
            raise ValueError(f"{self.name} is {col.type}, not categorical")
        cats = list(col.categories)
        cols = list(schema.columns)
        cols[i] = ColumnMeta(self.name, ColumnType.INTEGER)
        out = []
        for r in records:
            out.append(list(r[:i]) + [cats.index(r[i])] + list(r[i + 1:]))
        return out, Schema(cols)


_MATH_OPS: Dict[str, Callable[[float, float], float]] = {
    "add": lambda a, b: a + b,
    "subtract": lambda a, b: a - b,
    "multiply": lambda a, b: a * b,
    "divide": lambda a, b: a / b,
}


@_register
class DoubleMathOp(TransformOp):
    kind = "double_math_op"

    def __init__(self, name: str, op: str, value: float) -> None:
        if op not in _MATH_OPS:
            raise ValueError(f"unknown math op {op!r}")
        self.name, self.op, self.value = name, op, float(value)

    def apply(self, records, schema):
        i = schema.index_of(self.name)
        fn = _MATH_OPS[self.op]
        out = [list(r[:i]) + [fn(float(r[i]), self.value)] + list(r[i + 1:])
               for r in records]
        return out, schema


@_register
class MinMaxNormalize(TransformOp):
    kind = "min_max_normalize"

    def __init__(self, name: str, min_value: float, max_value: float) -> None:
        self.name = name
        self.min_value = float(min_value)
        self.max_value = float(max_value)

    def apply(self, records, schema):
        i = schema.index_of(self.name)
        span = self.max_value - self.min_value
        if span == 0:
            raise ValueError("max_value == min_value")
        out = [list(r[:i]) + [(float(r[i]) - self.min_value) / span]
               + list(r[i + 1:]) for r in records]
        return out, schema


@_register
class FilterInvalid(TransformOp):
    """Drop rows whose named double column is NaN/inf."""

    kind = "filter_invalid"

    def __init__(self, name: str) -> None:
        self.name = name

    def apply(self, records, schema):
        i = schema.index_of(self.name)
        return [r for r in records if math.isfinite(float(r[i]))], schema


@_register
class ConditionalFilter(TransformOp):
    """Drop rows where column <op> value is true (op: lt/gt/eq/ne)."""

    kind = "conditional_filter"
    _CONDS = {"lt": lambda a, b: a < b, "gt": lambda a, b: a > b,
              "eq": lambda a, b: a == b, "ne": lambda a, b: a != b}

    def __init__(self, name: str, op: str, value: float) -> None:
        if op not in self._CONDS:
            raise ValueError(f"unknown condition {op!r}")
        self.name, self.op, self.value = name, op, value

    def apply(self, records, schema):
        i = schema.index_of(self.name)
        cond = self._CONDS[self.op]
        return [r for r in records
                if not cond(float(r[i]), self.value)], schema


class TransformProcess:
    """Ordered, serializable transform pipeline (reference:
    org.datavec.api.transform.TransformProcess)."""

    def __init__(self, initial_schema: Schema,
                 ops: Sequence[TransformOp]) -> None:
        self.initial_schema = initial_schema
        self.ops = list(ops)
        # schema before each op, resolved once — execute() (and the
        # streaming per-record reader) must not rebuild/revalidate the
        # schema chain per call
        self._schemas: List[Schema] = []
        schema = initial_schema
        for op in self.ops:
            self._schemas.append(schema)
            _, schema = op.apply([], schema)
        self._final = schema

    @staticmethod
    def builder(schema: Schema) -> "TransformProcessBuilder":
        return TransformProcessBuilder(schema)

    def final_schema(self) -> Schema:
        return self._final

    def execute(self, records: Sequence[Record]) -> List[Record]:
        out = [list(r) for r in records]
        for op, schema in zip(self.ops, self._schemas):
            out, _ = op.apply(out, schema)
        return out

    def to_json(self) -> str:
        return json.dumps({
            "initial_schema": self.initial_schema.to_dict(),
            "ops": [op.to_dict() for op in self.ops],
        })

    @staticmethod
    def from_json(s: str) -> "TransformProcess":
        d = json.loads(s)
        ops = [_OP_REGISTRY[o["kind"]].from_dict(o) for o in d["ops"]]
        return TransformProcess(Schema.from_dict(d["initial_schema"]), ops)


class TransformProcessBuilder:
    def __init__(self, schema: Schema) -> None:
        self._schema = schema
        self._ops: List[TransformOp] = []

    def _add(self, op: TransformOp) -> "TransformProcessBuilder":
        self._ops.append(op)
        return self

    def remove_columns(self, *names: str):
        return self._add(RemoveColumns(names))

    def rename_column(self, old: str, new: str):
        return self._add(RenameColumn(old, new))

    def categorical_to_one_hot(self, name: str):
        return self._add(CategoricalToOneHot(name))

    def categorical_to_integer(self, name: str):
        return self._add(CategoricalToInteger(name))

    def string_to_categorical(self, name: str, categories: Sequence[str]):
        return self._add(StringToCategorical(name, categories))

    def double_math_op(self, name: str, op: str, value: float):
        return self._add(DoubleMathOp(name, op, value))

    def min_max_normalize(self, name: str, min_value: float,
                          max_value: float):
        return self._add(MinMaxNormalize(name, min_value, max_value))

    def filter_invalid(self, name: str):
        return self._add(FilterInvalid(name))

    def conditional_filter(self, name: str, op: str, value: float):
        return self._add(ConditionalFilter(name, op, value))

    def build(self) -> TransformProcess:
        # validate the chain against the schema now (fail at build, not run)
        tp = TransformProcess(self._schema, self._ops)
        tp.final_schema()
        return tp


class TransformProcessRecordReader(RecordReader):
    """Reader decorator applying a TransformProcess on the fly (reference:
    TransformProcessRecordReader). A real :class:`RecordReader` so the
    base ``iter_records(skip=)`` resume path applies — the skip counts
    POST-transform records, which is the consumer-visible cursor even
    when filters drop rows."""

    def __init__(self, reader, process: TransformProcess) -> None:
        self.reader = reader
        self.process = process

    def __iter__(self):
        for rec in self.reader:
            out = self.process.execute([rec])
            if out:  # filters may drop the row
                yield out[0]

    def reset(self) -> None:
        self.reader.reset()

    def labels(self):
        return self.reader.labels()
