"""Record readers — the DataVec ingestion tier.

Reference: org.datavec.api.records.reader.RecordReader and its zoo
(CSVRecordReader, LineRecordReader, CSVSequenceRecordReader,
ImageRecordReader — SURVEY.md §2.2 "DataVec API"/"DataVec image"). A record
is a list of field values (float or str — the reference's Writable
hierarchy collapses to plain Python values; NDArrayWritable is an ndarray).

Readers are restartable iterables; ``RecordReaderDataSetIterator`` bridges
records to the training tier's :class:`~deeplearning4j_tpu.data.dataset.DataSet`
batches. Hot parse loops (CSV, netpbm decode, resize) go through the native
library (deeplearning4j_tpu.native / libdl4jtpu) when built.
"""

from __future__ import annotations

import os
from typing import Iterable, Iterator, List, Optional, Sequence, Union

import numpy as np

from .. import native
from .dataset import DataSet

Writable = Union[float, int, str, np.ndarray]
Record = List[Writable]

DATA_WORKERS_ENV = "DL4J_TPU_DATA_WORKERS"


def resolve_data_workers(requested: Optional[int] = None) -> int:
    """Decode/augment worker-pool sizing. An explicit ``requested`` wins;
    otherwise the ``DL4J_TPU_DATA_WORKERS`` env var (the operator knob
    for the host input tier); otherwise 1. Always >= 1."""
    if requested is not None:
        return max(1, int(requested))
    env = os.environ.get(DATA_WORKERS_ENV)
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            raise ValueError(
                f"{DATA_WORKERS_ENV}={env!r} is not an integer") from None
    return 1


class RecordReader:
    """SPI: restartable stream of records."""

    def __iter__(self) -> Iterator[Record]:
        raise NotImplementedError

    def iter_records(self, skip: int = 0) -> Iterator[Record]:
        """One pass over the records, skipping the first ``skip`` — the
        mid-epoch resume entry point. The generic fallback produces and
        discards the skipped records (correct for any reader); readers
        with per-record cost (image decode) override with a free skip."""
        it = iter(self)
        for _ in range(skip):
            if next(it, None) is None:
                return
        yield from it

    def reset(self) -> None:
        """Default: readers here re-create their state in __iter__."""

    def labels(self) -> Optional[List[str]]:
        """Label vocabulary, for readers that define one (images)."""
        return None


class CollectionRecordReader(RecordReader):
    """Wraps an in-memory collection of records (reference:
    CollectionRecordReader)."""

    def __init__(self, records: Sequence[Record]) -> None:
        self._records = list(records)

    def __iter__(self) -> Iterator[Record]:
        return iter(self._records)


class LineRecordReader(RecordReader):
    """One record per line: ``[line]`` (reference: LineRecordReader)."""

    def __init__(self, path: str, encoding: str = "utf-8") -> None:
        self.path = path
        self.encoding = encoding

    def __iter__(self) -> Iterator[Record]:
        with open(self.path, "r", encoding=self.encoding) as f:
            for line in f:
                yield [line.rstrip("\n").rstrip("\r")]


def _convert_field(field: str) -> Writable:
    try:
        return float(field)
    except ValueError:
        return field


class CSVRecordReader(RecordReader):
    """Delimited text → records (reference: CSVRecordReader).

    With ``numeric=True`` the whole file is parsed by the native fast path
    into a float32 matrix (raising on non-numeric data); otherwise each
    field falls back from float to str individually.
    """

    def __init__(self, path: str, *, delimiter: str = ",",
                 skip_lines: int = 0, numeric: bool = False,
                 encoding: str = "utf-8") -> None:
        self.path = path
        self.delimiter = delimiter
        self.skip_lines = int(skip_lines)
        self.numeric = bool(numeric)
        self.encoding = encoding

    def __iter__(self) -> Iterator[Record]:
        if self.numeric:
            with open(self.path, "rb") as f:
                matrix = native.parse_csv(f.read(), self.delimiter,
                                          self.skip_lines)
            for row in matrix:
                yield [float(v) for v in row]
            return
        with open(self.path, "r", encoding=self.encoding) as f:
            skipped = 0
            for line in f:
                line = line.rstrip("\n").rstrip("\r")
                if not line.strip():
                    continue
                if skipped < self.skip_lines:
                    skipped += 1
                    continue
                yield [_convert_field(x) for x in line.split(self.delimiter)]


class CSVSequenceRecordReader(RecordReader):
    """Sequence reader: one CSV file per sequence (reference:
    CSVSequenceRecordReader). Each record is a [timesteps, fields] list of
    per-step field lists."""

    def __init__(self, paths: Sequence[str], *, delimiter: str = ",",
                 skip_lines: int = 0, encoding: str = "utf-8") -> None:
        self.paths = list(paths)
        self.delimiter = delimiter
        self.skip_lines = int(skip_lines)
        self.encoding = encoding

    def __iter__(self) -> Iterator[List[Record]]:
        for p in self.paths:
            reader = CSVRecordReader(p, delimiter=self.delimiter,
                                     skip_lines=self.skip_lines,
                                     encoding=self.encoding)
            yield list(reader)


def _pil():
    try:
        from PIL import Image

        return Image
    except ImportError:  # pragma: no cover - env-dependent
        return None


class ImageRecordReader(RecordReader):
    """Image directory reader (reference: ImageRecordReader +
    NativeImageLoader — SURVEY.md §2.2 'the ImageNet input path').

    Walks ``root`` for images, decodes + bilinearly resizes to
    [height, width, channels], and when ``label_from_path`` appends the
    parent-directory label index. Record: ``[ndarray(h, w, c), label_idx]``.

    Decode story: netpbm (P5/P6) through the native C++ codec always;
    PNG/JPEG/BMP/GIF through Pillow when it is importable (it is in this
    environment). ``transform`` applies an
    :class:`~..data.image_transform.ImageTransform` (augmentation pipeline)
    to every decoded image, the reference's ImageRecordReader(transform)
    seam.
    """

    NETPBM_EXTENSIONS = (".ppm", ".pgm", ".pnm")
    PIL_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".gif")

    def __init__(self, height: int, width: int, channels: int = 3, *,
                 root: Optional[str] = None,
                 paths: Optional[Sequence[str]] = None,
                 label_from_path: bool = True,
                 transform=None, seed: int = 0,
                 output_dtype: str = "float32",
                 workers: Optional[int] = None,
                 shuffle: bool = False) -> None:
        """``output_dtype="uint8"`` is the TPU-native fast path: pixels stay
        uint8 on host end to end (decode header parse + crop/flip as numpy
        VIEWS, one small contiguous copy), transfer to HBM at 1 byte/px,
        and the cast to the model's float dtype happens ON DEVICE inside
        the jitted step (core.dtypes.as_input) — the host float conversion
        + [0,1] scaling that dominates the float32 path (~300us/img of its
        ~400us on this host) disappears. Values are raw 0..255; fold the
        1/255 into the model (or BN absorbs it). Only geometric transforms
        (flip/crop) are uint8-safe; value-space transforms raise.

        ``workers > 1`` decodes+augments on a thread pool (the netpbm/PIL
        decode and the resize release the GIL), preserving record order —
        the reference's multi-threaded NativeImageLoader ingestion. The
        default (``workers=None``) resolves through the
        ``DL4J_TPU_DATA_WORKERS`` env var (:func:`resolve_data_workers`),
        so deployments size the host decode tier without code changes;
        record order is identical for every worker count.

        ``shuffle=True`` permutes the path list ONCE at construction with
        ``seed`` — a deterministic epoch order that is independent of
        both ``workers`` and any prefetch depth stacked on top."""
        if (root is None) == (paths is None):
            raise ValueError("provide exactly one of root= or paths=")
        if output_dtype not in ("float32", "uint8"):
            raise ValueError("output_dtype must be float32 or uint8")
        self.height, self.width, self.channels = height, width, channels
        self.label_from_path = label_from_path
        self.transform = transform
        self.output_dtype = output_dtype
        self.workers = resolve_data_workers(workers)
        self._seed = int(seed)
        self._rng = np.random.RandomState(seed)
        self._epochs_started = 0  # passes begun — the rng-stream position
        # resolved once: PIL availability can't change mid-scan, and the
        # walk below tests this per file at ImageNet scale
        self.EXTENSIONS = self.NETPBM_EXTENSIONS + (
            self.PIL_EXTENSIONS if _pil() is not None else ())
        if root is not None:
            found: List[str] = []
            for dirpath, _dirnames, filenames in sorted(os.walk(root)):
                for fn in sorted(filenames):
                    if fn.lower().endswith(self.EXTENSIONS):
                        found.append(os.path.join(dirpath, fn))
            self.paths = found
        else:
            self.paths = list(paths)  # type: ignore[arg-type]
        if shuffle:
            order = np.random.default_rng(seed).permutation(len(self.paths))
            self.paths = [self.paths[i] for i in order]
        self._labels = sorted({os.path.basename(os.path.dirname(p))
                               for p in self.paths}) if label_from_path else []
        label_idx = {n: i for i, n in enumerate(self._labels)}
        # per-path label resolved once — the iter loop is the ImageNet-scale
        # hot path, no per-image string scans there
        self._path_labels = [label_idx[os.path.basename(os.path.dirname(p))]
                             for p in self.paths] if label_from_path else []

    def labels(self) -> Optional[List[str]]:
        return self._labels or None

    def _decode(self, path: str) -> np.ndarray:
        if path.lower().endswith(self.NETPBM_EXTENSIONS):
            with open(path, "rb") as f:
                return native.decode_netpbm(f.read())
        Image = _pil()
        if Image is None:
            raise ValueError(
                f"{path}: only netpbm is decodable without Pillow "
                "(convert with e.g. `mogrify -format ppm`)")
        with Image.open(path) as im:
            if im.mode not in ("RGB", "L"):
                im = im.convert("RGB" if self.channels == 3 else "L")
            arr = np.asarray(im, dtype=np.float32) / 255.0  # match netpbm [0,1]
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr

    def _decode_u8(self, path: str) -> np.ndarray:
        """Decode to uint8 HWC with ZERO per-pixel host math: netpbm is a
        header parse + frombuffer view; PIL hands back uint8 natively."""
        if path.lower().endswith(self.NETPBM_EXTENSIONS):
            with open(path, "rb") as f:
                buf = f.read()
            # shared front-anchored header parse (native.py) — same
            # semantics as the float decoder: '#' comments, exactly one
            # whitespace byte before the raster; back-anchored slicing
            # would silently shift pixels on trailing-byte files
            if buf[:2] not in (b"P5", b"P6"):
                raise ValueError(f"{path}: not a binary netpbm (P5/P6)")
            try:
                w, h, c, maxval, pos = native.parse_netpbm_header(buf)
            except ValueError as e:
                raise ValueError(f"{path}: malformed netpbm header") from e
            if maxval > 255:
                raise ValueError(
                    f"{path}: 16-bit netpbm (maxval {maxval}) unsupported "
                    "on the uint8 fast path")
            data = buf[pos: pos + h * w * c]
            if len(data) != h * w * c:
                raise ValueError(f"{path}: truncated netpbm raster")
            arr = np.frombuffer(data, np.uint8).reshape(h, w, c)
            if maxval != 255:
                # rounded rescale to the full byte range so the uint8 fast
                # path matches the float decoder within rounding (floor
                # division diverged by up to 1 LSB)
                arr = ((arr.astype(np.uint16) * 255 + maxval // 2)
                       // maxval).astype(np.uint8)
            return arr
        Image = _pil()
        if Image is None:
            raise ValueError(f"{path}: only netpbm decodable without Pillow")
        with Image.open(path) as im:
            if im.mode not in ("RGB", "L"):
                im = im.convert("RGB" if self.channels == 3 else "L")
            arr = np.asarray(im)  # uint8
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr

    def _load(self, path: str, rng=None) -> np.ndarray:
        rng = rng if rng is not None else self._rng
        if self.output_dtype == "uint8":
            img = self._decode_u8(path)
            if self.transform is not None:
                if not getattr(self.transform, "uint8_safe", False):
                    raise ValueError(
                        "output_dtype='uint8' supports only geometric "
                        "(uint8_safe) transforms — flip/crop; value-space "
                        "transforms need the float32 path")
                img = np.asarray(self.transform.call(img, rng))
            if img.shape[:2] != (self.height, self.width):
                # resize needs float math; round (not truncate) back so the
                # uint8 output matches the float path within rounding
                img = np.rint(np.clip(native.resize_bilinear(
                    img.astype(np.float32), self.height, self.width),
                    0, 255)).astype(np.uint8)
        else:
            img = self._decode(path)
            if self.transform is not None:
                img = np.asarray(self.transform.call(
                    np.asarray(img, np.float32), rng))
            if img.shape[:2] != (self.height, self.width):
                img = native.resize_bilinear(img, self.height, self.width)
        if img.shape[2] != self.channels:
            if self.channels == 3 and img.shape[2] == 1:
                img = np.repeat(img, 3, axis=2)
            elif self.channels == 1 and img.shape[2] == 3:
                img = img.mean(axis=2, keepdims=True)
                if self.output_dtype == "uint8":
                    img = np.rint(img).astype(np.uint8)
            else:
                raise ValueError(
                    f"cannot adapt {img.shape[2]} channels to "
                    f"{self.channels}: {path}")
        return np.ascontiguousarray(img)

    def __iter__(self) -> Iterator[Record]:
        return self.iter_records(0)

    def iter_records(self, skip: int = 0) -> Iterator[Record]:
        # per-image independent rngs (same derivation for every worker
        # count — the loader-determinism contract, see
        # tests/test_sharded_loader.py) make the skip FREE: the full seed
        # vector is drawn so the pass's rng stream stays identical, but
        # skipped images are never decoded.
        seeds = self._rng.randint(0, 2**31 - 1, size=len(self.paths))
        self._epochs_started += 1
        if self.workers > 1:
            yield from self._iter_parallel(seeds, skip)
            return
        for i in range(skip, len(self.paths)):
            rec: Record = [self._load(
                self.paths[i], rng=np.random.RandomState(seeds[i]))]
            if self.label_from_path:
                rec.append(self._path_labels[i])
            yield rec

    def state_dict(self) -> dict:
        """Reader-level resume state: how many passes have STARTED. Each
        pass draws one per-image seed vector from the reader's stateful
        rng, so the pass index pins the augmentation stream; the record
        cursor within the pass belongs to the dataset iterator above."""
        return {"epoch": self._epochs_started}

    def load_state_dict(self, state: dict) -> None:
        """Repositions the rng stream so the next :meth:`iter_records`
        call RE-ENTERS the snapshotted pass — it draws the exact seed
        vector that pass drew, and the caller skips to its cursor."""
        epoch = max(0, int(state.get("epoch", 0)) - 1)
        self._rng = np.random.RandomState(self._seed)
        for _ in range(epoch):  # replay the completed passes' seed draws
            self._rng.randint(0, 2**31 - 1, size=len(self.paths))
        self._epochs_started = epoch

    def _iter_parallel(self, seeds, skip: int = 0) -> Iterator[Record]:
        """Thread-pool decode+augment, order-preserving, bounded in-flight
        window (the reference's multi-threaded image ingestion; decode and
        resize release the GIL, so workers scale with real cores)."""
        from concurrent.futures import ThreadPoolExecutor

        def load(i: int):
            return self._load(self.paths[i],
                              rng=np.random.RandomState(seeds[i]))

        window = 4 * self.workers
        with ThreadPoolExecutor(max_workers=self.workers) as pool:
            pending = {}
            nxt = skip
            for i in range(skip, len(self.paths)):
                pending[i] = pool.submit(load, i)
                while len(pending) >= window or (
                        nxt in pending and pending[nxt].done()):
                    rec: Record = [pending.pop(nxt).result()]
                    if self.label_from_path:
                        rec.append(self._path_labels[nxt])
                    yield rec
                    nxt += 1
            while nxt in pending:
                rec = [pending.pop(nxt).result()]
                if self.label_from_path:
                    rec.append(self._path_labels[nxt])
                yield rec
                nxt += 1


class RecordReaderDataSetIterator:
    """Records → DataSet batches (reference:
    org.deeplearning4j.datasets.datavec.RecordReaderDataSetIterator).

    ``label_index`` selects the label field (negative indexes allowed);
    classification one-hots it to ``num_classes``, regression keeps the
    raw value(s). ndarray features (image readers) are stacked as-is.
    """

    def __init__(self, reader: RecordReader, batch_size: int, *,
                 label_index: int = -1, num_classes: Optional[int] = None,
                 regression: bool = False) -> None:
        self.reader = reader
        self._batch = int(batch_size)
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        if not regression and num_classes is None:
            raise ValueError("classification needs num_classes")

    # -- DataSetIterator protocol (lookahead over the generator) so this
    # composes with AsyncDataSetIterator / MappedDataSetIterator ----------
    _gen = None
    _lookahead = None
    _epochs_started = 0
    _batches_out = 0

    def batch_size(self) -> int:
        return self._batch

    def _start_generation(self, skip_batches: int = 0):
        self._epochs_started += 1
        self._batches_out = skip_batches
        return self._generate(skip_records=skip_batches * self._batch)

    def has_next(self) -> bool:
        if self._gen is None:
            self._gen = self._start_generation()
        if self._lookahead is None:
            self._lookahead = next(self._gen, None)
        return self._lookahead is not None

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        item, self._lookahead = self._lookahead, None
        self._batches_out += 1
        return item

    def reset(self) -> None:
        self.reader.reset()
        self._gen = None
        self._lookahead = None
        self._batches_out = 0

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        return self._start_generation()

    def state_dict(self) -> dict:
        # the lookahead batch was pulled from the generator but never
        # handed out — _batches_out only counts next() returns, so it is
        # correctly re-produced on resume
        return {"epoch": self._epochs_started, "batches": self._batches_out}

    def load_state_dict(self, state: dict) -> None:
        epoch = int(state["epoch"])
        batches = int(state["batches"])
        loader = getattr(self.reader, "load_state_dict", None)
        if callable(loader):
            self.reader.load_state_dict({"epoch": epoch})
        else:
            self.reader.reset()
        self._epochs_started = max(0, epoch - 1)
        self._lookahead = None
        if epoch > 0:
            self._gen = self._start_generation(skip_batches=batches)
        else:
            self._gen = None
            self._batches_out = 0

    def _generate(self, skip_records: int = 0) -> Iterator[DataSet]:
        feats: List[np.ndarray] = []
        labels: List[np.ndarray] = []
        for rec in self.reader.iter_records(skip_records):
            li = self.label_index if self.label_index >= 0 \
                else len(rec) + self.label_index
            label_val = rec[li]
            fields = [v for i, v in enumerate(rec) if i != li]
            if len(fields) == 1 and isinstance(fields[0], np.ndarray):
                # keep the reader's dtype: uint8 readers ship raw bytes to
                # the device, where as_input does the float cast
                feats.append(fields[0])
            else:
                feats.append(np.asarray([float(v) for v in fields],
                                        np.float32))
            if self.regression:
                labels.append(np.asarray([float(label_val)], np.float32))
            else:
                cls = int(label_val)
                if not 0 <= cls < self.num_classes:
                    # explicit: numpy would silently wrap negative labels
                    raise ValueError(
                        f"label {cls} outside [0, {self.num_classes})")
                onehot = np.zeros(self.num_classes, np.float32)
                onehot[cls] = 1.0
                labels.append(onehot)
            if len(feats) == self._batch:
                yield DataSet(np.stack(feats), np.stack(labels))
                feats, labels = [], []
        if feats:
            yield DataSet(np.stack(feats), np.stack(labels))
