"""DataSet / MultiDataSet containers.

Reference: org.nd4j.linalg.dataset.{DataSet, MultiDataSet} — features + labels
+ optional masks. Host-side numpy until the jitted step device_puts them (the
async prefetch iterator overlaps that transfer; data/iterators.py).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class DataSet:
    features: np.ndarray
    labels: np.ndarray
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def split_test_and_train(self, n_train: int) -> Tuple["DataSet", "DataSet"]:
        tr = DataSet(
            self.features[:n_train], self.labels[:n_train],
            None if self.features_mask is None else self.features_mask[:n_train],
            None if self.labels_mask is None else self.labels_mask[:n_train],
        )
        te = DataSet(
            self.features[n_train:], self.labels[n_train:],
            None if self.features_mask is None else self.features_mask[n_train:],
            None if self.labels_mask is None else self.labels_mask[n_train:],
        )
        return tr, te

    def shuffle(self, seed: Optional[int] = None) -> None:
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self.num_examples())
        self.features = self.features[perm]
        self.labels = self.labels[perm]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[perm]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[perm]

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        n = self.num_examples()
        return [
            DataSet(
                self.features[i : i + batch_size],
                self.labels[i : i + batch_size],
                None if self.features_mask is None else self.features_mask[i : i + batch_size],
                None if self.labels_mask is None else self.labels_mask[i : i + batch_size],
            )
            for i in range(0, n, batch_size)
        ]

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        return DataSet(
            np.concatenate([d.features for d in datasets]),
            np.concatenate([d.labels for d in datasets]),
            None if datasets[0].features_mask is None
            else np.concatenate([d.features_mask for d in datasets]),
            None if datasets[0].labels_mask is None
            else np.concatenate([d.labels_mask for d in datasets]),
        )


@dataclasses.dataclass
class MultiDataSet:
    """Multiple feature/label arrays (reference: MultiDataSet) — the
    ComputationGraph input container."""

    features: Tuple[np.ndarray, ...]
    labels: Tuple[np.ndarray, ...]
    features_masks: Optional[Tuple[Optional[np.ndarray], ...]] = None
    labels_masks: Optional[Tuple[Optional[np.ndarray], ...]] = None

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])
