"""Process-wide metrics registry: Counter / Gauge / Histogram + Span.

The north star is a fleet serving millions of users, and you cannot
operate what you cannot measure (PAPERS.md: the TPU-pod reports attribute
fleet-scale throughput and resilience wins to continuous telemetry over
input pipelines, collectives, and failure/recovery paths). PR 1 left its
signals as ad-hoc per-object ``stats()`` dicts; this module is the single
source of truth those dicts now read from, and
:mod:`~deeplearning4j_tpu.obs.prom` exposes it to scrapers.

Design constraints, in priority order:

* **Hot-path cheap.** A counter increment is one small lock + a float add;
  a :class:`Span` is two ``perf_counter()`` calls and one histogram
  observe. Nothing here touches a device, allocates per call, or formats
  strings on the increment path — label resolution happens ONCE at
  instrumentation-setup time (``family.labels(...)`` returns a child you
  keep), never per event.
* **Thread-safe.** Serving workers, prefetch threads and HTTP handlers all
  hit the same children concurrently; every mutation is lock-protected
  (CPython's ``+=`` on an attribute is not atomic).
* **Hermetic tests.** The default registry is process-global
  (:func:`get_registry`) so one scrape sees serving + training + data, but
  every instrumented component takes ``registry=`` so a test can hand it a
  fresh :class:`MetricsRegistry` and assert exact values. Components that
  can exist many times per process (``ParallelInference``, servers,
  prefetchers) additionally carve out per-instance children via an
  ``instance`` label, so their ``stats()`` views stay exact even on the
  shared global registry.

Naming convention (README "Observability"):
``dl4j_tpu_<area>_<name>_<unit>`` — areas in use: ``serving``,
``inference``, ``resilience``, ``training``, ``data``, ``client``.
Counters end in ``_total``; durations are ``_seconds``.
"""

from __future__ import annotations

import math
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Latency-oriented defaults: serving forwards on TPU are sub-millisecond,
# HTTP round-trips tens of ms, elastic-restart backoffs seconds.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class MetricError(ValueError):
    """Bad metric/label name, or re-registration with a different shape."""


def _check_name(name: str) -> str:
    if not _METRIC_NAME_RE.match(name or ""):
        raise MetricError(f"invalid metric name {name!r}")
    return name


def _check_labelnames(labelnames: Sequence[str]) -> Tuple[str, ...]:
    names = tuple(labelnames)
    for n in names:
        if not _LABEL_NAME_RE.match(n) or n.startswith("__") or n == "le":
            raise MetricError(f"invalid label name {n!r}")
    if len(set(names)) != len(names):
        raise MetricError(f"duplicate label names in {names}")
    return names


# --------------------------------------------------------------------------
# children — the objects instrumentation actually holds and mutates
# --------------------------------------------------------------------------
class CounterValue:
    """Monotonically non-decreasing value. ``inc`` only."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise MetricError("counters can only increase")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class GaugeValue:
    """Point-in-time value: set/inc/dec, plus ``set_max`` for high-water
    marks (queue depth peaks, largest batch seen)."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    def set_max(self, value: float) -> None:
        with self._lock:
            if value > self._value:
                self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class HistogramValue:
    """Fixed-bucket histogram (upper bounds; +Inf implicit)."""

    __slots__ = ("_lock", "_bounds", "_counts", "_sum", "_count")

    def __init__(self, bounds: Tuple[float, ...]) -> None:
        self._lock = threading.Lock()
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        bounds = self._bounds
        i = 0
        n = len(bounds)
        while i < n and v > bounds[i]:
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def time(self) -> "Span":
        return Span(self)

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def buckets(self) -> List[Tuple[float, int]]:
        """Cumulative (le, count) pairs, ending with (+Inf, total)."""
        with self._lock:
            counts = list(self._counts)
        out: List[Tuple[float, int]] = []
        acc = 0
        for le, c in zip(self._bounds + (math.inf,), counts):
            acc += c
            out.append((le, acc))
        return out


# --------------------------------------------------------------------------
# families — registered once per name, hand out label-scoped children
# --------------------------------------------------------------------------
class _Family:
    typ = "untyped"
    _child_cls = CounterValue

    def __init__(self, name: str, help: str,
                 labelnames: Tuple[str, ...]) -> None:
        self.name = _check_name(name)
        self.help = help
        self.labelnames = _check_labelnames(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        return self._child_cls()

    def labels(self, *values, **labelkv):
        """Resolve (and create on first use) the child for a label set.
        Positional values follow ``labelnames`` order; keywords must cover
        every label name. Call once at setup, keep the child."""
        if labelkv:
            if values:
                raise MetricError("pass labels positionally or by keyword, not both")
            try:
                values = tuple(str(labelkv[n]) for n in self.labelnames)
            except KeyError as e:
                raise MetricError(f"missing label {e.args[0]!r} for {self.name}") from None
            if len(labelkv) != len(self.labelnames):
                extra = set(labelkv) - set(self.labelnames)
                raise MetricError(f"unknown labels {sorted(extra)} for {self.name}")
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise MetricError(
                f"{self.name} expects labels {self.labelnames}, got {values}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._make_child()
                self._children[values] = child
        return child

    def items(self) -> List[Tuple[Tuple[str, ...], object]]:
        """Snapshot of (labelvalues, child), sorted for stable exposition."""
        with self._lock:
            return sorted(self._children.items())

    # no-label convenience: the family proxies its single child, so
    # `registry.counter("x_total", "...").inc()` just works.
    def _default(self):
        if self.labelnames:
            raise MetricError(
                f"{self.name} has labels {self.labelnames}; call .labels(...) first")
        return self._children[()]


class Counter(_Family):
    typ = "counter"
    _child_cls = CounterValue

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value


class Gauge(_Family):
    typ = "gauge"
    _child_cls = GaugeValue

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set_max(self, value: float) -> None:
        self._default().set_max(value)

    @property
    def value(self) -> float:
        return self._default().value


class Histogram(_Family):
    typ = "histogram"

    def __init__(self, name: str, help: str, labelnames: Tuple[str, ...],
                 buckets: Optional[Sequence[float]] = None) -> None:
        b = tuple(float(x) for x in (buckets or DEFAULT_BUCKETS))
        if list(b) != sorted(b) or len(set(b)) != len(b):
            raise MetricError(f"buckets must be sorted and unique: {b}")
        if b and math.isinf(b[-1]):
            b = b[:-1]  # +Inf is implicit
        self.bucket_bounds = b
        super().__init__(name, help, labelnames)

    def _make_child(self) -> HistogramValue:
        return HistogramValue(self.bucket_bounds)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def time(self) -> "Span":
        return Span(self._default())

    @property
    def sum(self) -> float:
        return self._default().sum

    @property
    def count(self) -> int:
        return self._default().count


# --------------------------------------------------------------------------
# Span — low-overhead timing context manager
# --------------------------------------------------------------------------
class Span:
    """Times a ``with`` block via ``perf_counter`` and feeds a histogram
    child; optionally appends a structured event to a registry's ring
    buffer. The body of ``__enter__``/``__exit__`` is deliberately tiny —
    the 2%-overhead budget (ISSUE 2) is spent on exactly two clock reads
    and one lock-protected observe."""

    __slots__ = ("_hist", "_registry", "_name", "_fields", "_t0", "elapsed")

    def __init__(self, histogram: Optional[HistogramValue] = None, *,
                 registry: Optional["MetricsRegistry"] = None,
                 name: Optional[str] = None,
                 fields: Optional[dict] = None) -> None:
        self._hist = histogram
        self._registry = registry
        self._name = name
        self._fields = fields
        self._t0 = 0.0
        self.elapsed: Optional[float] = None

    def __enter__(self) -> "Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.elapsed = time.perf_counter() - self._t0
        if self._hist is not None:
            self._hist.observe(self.elapsed)
        if self._registry is not None:
            self._registry.log_event(
                "span", name=self._name, seconds=self.elapsed,
                error=exc_type is not None, **(self._fields or {}))


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------
class MetricsRegistry:
    """Thread-safe family registry + bounded structured event log.

    Registration is idempotent: asking for an existing name with the same
    type/labelnames returns the existing family (so N servers in one
    process share one ``dl4j_tpu_serving_requests_total``); a mismatch
    raises :class:`MetricError` — two subsystems silently writing
    different shapes to one name is a bug, not a merge.
    """

    def __init__(self, max_events: int = 1024) -> None:
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        self._events: deque = deque(maxlen=int(max_events))

    # ---- registration -------------------------------------------------
    def _register(self, cls, name: str, help: str,
                  labelnames: Sequence[str], **kwargs) -> _Family:
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if type(fam) is not cls or fam.labelnames != labelnames:
                    raise MetricError(
                        f"{name} already registered as {fam.typ} with labels "
                        f"{fam.labelnames}; cannot re-register as {cls.typ} "
                        f"with {labelnames}")
                if kwargs.get("buckets") is not None:
                    b = tuple(float(x) for x in kwargs["buckets"])
                    if b and math.isinf(b[-1]):
                        b = b[:-1]
                    if b != fam.bucket_bounds:
                        raise MetricError(
                            f"{name} already registered with buckets "
                            f"{fam.bucket_bounds}")
                return fam
            fam = cls(name, help, labelnames, **kwargs)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._register(Histogram, name, help, labelnames,
                              buckets=buckets)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def collect(self) -> List[_Family]:
        """Stable-ordered snapshot of families for exposition."""
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    # ---- tracing ------------------------------------------------------
    def trace(self, name: str, help: str = "", *,
              labels: Optional[dict] = None,
              buckets: Optional[Sequence[float]] = None,
              log: bool = False, **fields) -> Span:
        """``with registry.trace("dl4j_tpu_area_op_latency_seconds"): ...``
        — registers/reuses the histogram, times the block, and (with
        ``log=True``) appends a structured span event."""
        labels = labels or {}
        hist = self.histogram(name, help, tuple(labels), buckets=buckets)
        child = hist.labels(**labels) if labels else hist._default()
        return Span(child, registry=self if log else None, name=name,
                    fields={**labels, **fields} if (labels or fields) else None)

    # ---- structured event log ----------------------------------------
    def log_event(self, kind: str, **fields) -> None:
        evt = {"kind": kind, "ts": time.time()}
        evt.update(fields)
        with self._lock:
            self._events.append(evt)

    def events(self, kind: Optional[str] = None) -> List[dict]:
        with self._lock:
            evts = list(self._events)
        if kind is None:
            return evts
        return [e for e in evts if e.get("kind") == kind]

    # ---- convenience --------------------------------------------------
    def render(self) -> str:
        from .prom import render_prometheus

        return render_prometheus(self)


# --------------------------------------------------------------------------
# process-global default
# --------------------------------------------------------------------------
_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry: one scrape of any server's ``/metrics``
    sees every instrumented subsystem in this process."""
    return _default_registry


def set_registry(registry: Optional[MetricsRegistry]) -> MetricsRegistry:
    """Install a process-global registry (tests); ``None`` installs a fresh
    empty one. Returns the previous registry so callers can restore it."""
    global _default_registry
    prev = _default_registry
    _default_registry = registry if registry is not None else MetricsRegistry()
    return prev


def trace(name: str, help: str = "", **kwargs) -> Span:
    """Module-level :meth:`MetricsRegistry.trace` on the global registry."""
    return get_registry().trace(name, help, **kwargs)
