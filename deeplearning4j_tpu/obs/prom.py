"""Prometheus text exposition, format version 0.0.4.

Renders a :class:`~deeplearning4j_tpu.obs.metrics.MetricsRegistry` into the
plain-text scrape format every Prometheus-compatible collector understands
(https://prometheus.io/docs/instrumenting/exposition_formats/):

    # HELP dl4j_tpu_serving_requests_total HTTP requests by status code
    # TYPE dl4j_tpu_serving_requests_total counter
    dl4j_tpu_serving_requests_total{code="200",instance="server-0"} 42

Histograms expand into cumulative ``_bucket`` series (``le`` label, last
bucket ``+Inf`` equal to ``_count``), plus ``_sum`` and ``_count``. Label
values escape backslash, double-quote and newline; HELP text escapes
backslash and newline — exactly the 0.0.4 rules, which
``tools/check_metrics_contract.py`` re-validates from the outside on every
tier-1 run.
"""

from __future__ import annotations

import math
from typing import Iterable, Tuple

# What a scraper must be told; version pins the exposition grammar.
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def escape_label_value(text: str) -> str:
    return (text.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def format_value(value: float) -> str:
    v = float(value)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _labels(names: Tuple[str, ...], values: Tuple[str, ...],
            extra: Iterable[Tuple[str, str]] = ()) -> str:
    pairs = [f'{n}="{escape_label_value(v)}"' for n, v in zip(names, values)]
    pairs.extend(f'{n}="{escape_label_value(v)}"' for n, v in extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


def render_prometheus(registry) -> str:
    """Render every family in ``registry`` (sorted by name, children sorted
    by label values) as 0.0.4 text. Ends with a trailing newline, as the
    format requires."""
    lines = []
    for fam in registry.collect():
        if fam.help:
            lines.append(f"# HELP {fam.name} {escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.typ}")
        names = fam.labelnames
        for values, child in fam.items():
            if fam.typ == "histogram":
                for le, cum in child.buckets():
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_labels(names, values, [('le', format_value(le))])}"
                        f" {cum}")
                lines.append(
                    f"{fam.name}_sum{_labels(names, values)}"
                    f" {format_value(child.sum)}")
                lines.append(
                    f"{fam.name}_count{_labels(names, values)} {child.count}")
            else:
                lines.append(
                    f"{fam.name}{_labels(names, values)}"
                    f" {format_value(child.value)}")
    return "\n".join(lines) + "\n"
