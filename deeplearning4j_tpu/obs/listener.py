"""MetricsListener — the training-loop → metrics-registry bridge.

Built on the :class:`~deeplearning4j_tpu.core.listeners.TrainingListener`
SPI (the framework's one metrics bus), so it attaches to anything that
drives a ``ListenerBus``: ``MultiLayerNetwork.fit``,
``DistributedTrainer.fit``, and samediff ``TrainingSession.fit``.

It declares ``requires_score = False``: step latency and examples/sec need
no loss value, so attaching ONLY this listener must not force the per-step
device→host loss fetch the training loops otherwise avoid (measured round
5: ~64 ms per sync through the axon tunnel). Loops that honor
``ListenerBus.requires_score`` pass NaN instead, and the score gauge
simply skips NaN.
"""

from __future__ import annotations

import time
from typing import Any, Mapping, Optional

import numpy as np

from ..core.listeners import TrainingListener
from .metrics import MetricsRegistry, get_registry

# Training steps range from sub-ms (tiny CPU tests) to seconds (pod-scale
# BERT), so the default latency buckets fit; examples/sec is derived by
# the scraper as rate(examples_total)/rate(step_latency_count).
_STEP_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


class MetricsListener(TrainingListener):
    """Feeds ``dl4j_tpu_training_*`` series from iteration callbacks.

    Series: ``iterations_total``, ``examples_total`` (from the model's
    ``last_batch_size``), ``epochs_total``, ``step_latency_seconds``
    (wall time between consecutive ``iteration_done`` calls — the full
    step including data wait, which is the fleet-level signal), and a
    ``score`` gauge updated whenever a real (non-NaN) score arrives.
    """

    requires_score = False

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        self._iterations = reg.counter(
            "dl4j_tpu_training_iterations_total",
            "Completed training iterations (optimizer steps)")
        self._examples = reg.counter(
            "dl4j_tpu_training_examples_total",
            "Training examples consumed (rows across all iterations)")
        self._epochs = reg.counter(
            "dl4j_tpu_training_epochs_total", "Completed training epochs")
        self._step_latency = reg.histogram(
            "dl4j_tpu_training_step_latency_seconds",
            "Wall time between consecutive training iterations",
            buckets=_STEP_BUCKETS)
        self._score = reg.gauge(
            "dl4j_tpu_training_score", "Most recent training score (loss)")
        self._last_t: Optional[float] = None

    def on_epoch_start(self, model: Any) -> None:
        # epoch boundaries include eval/checkpoint time; don't let that
        # masquerade as one huge training step
        self._last_t = None

    def on_epoch_end(self, model: Any) -> None:
        self._epochs.inc()
        self._last_t = None

    def iteration_done(self, model: Any, iteration: int, epoch: int,
                       score: float) -> None:
        now = time.perf_counter()
        if self._last_t is not None:
            self._step_latency.observe(now - self._last_t)
        self._last_t = now
        self._iterations.inc()
        batch = getattr(model, "last_batch_size", None)
        if batch:
            self._examples.inc(batch)
        if score == score:  # skip NaN (loop ran with requires_score=False)
            self._score.set(float(score))


def record_moe_metrics(state: Optional[Mapping[str, Any]],
                       registry: Optional[MetricsRegistry] = None) -> int:
    """Feed MoE routing observability from a model ``state`` pytree
    (``layer_name -> layer state``) into the registry.

    Every :class:`~deeplearning4j_tpu.nn.layers.MixtureOfExpertsLayer`
    refreshes ``state["expert_tokens"]`` ([E] assignments kept per expert),
    ``state["dropped_tokens"]`` (capacity-overflow drops) and
    ``state["capacity_slots"]`` (total buffer slots E·C) per forward;
    this turns the latest per-batch values into

    * ``dl4j_tpu_moe_expert_tokens_total{layer=,expert=}`` (counter)
    * ``dl4j_tpu_moe_dropped_tokens_total{layer=}`` (counter)
    * ``dl4j_tpu_moe_capacity_slots{layer=}`` (gauge — alert when the
      kept-token total approaches it: capacity_factor is too tight)
    * ``dl4j_tpu_moe_drop_share{layer=}`` (gauge — dropped/(kept+dropped)
      for THIS batch; the capacity_factor tuning signal)
    * ``dl4j_tpu_moe_expert_load_cv{layer=}`` (gauge — std/mean of the
      per-expert kept counts; 0 = perfectly balanced router, rising CV
      means the aux loss is losing to expert collapse)

    Call once per completed step (that is what
    :class:`MoEMetricsListener` does). Returns the number of MoE layer
    states seen, so callers can assert wiring.
    """
    reg = registry if registry is not None else get_registry()
    tok = reg.counter(
        "dl4j_tpu_moe_expert_tokens_total",
        "MoE (token, slot) assignments kept per expert (post capacity "
        "drop)", ("layer", "expert"))
    drop = reg.counter(
        "dl4j_tpu_moe_dropped_tokens_total",
        "MoE (token, slot) assignments dropped by capacity overflow",
        ("layer",))
    slots = reg.gauge(
        "dl4j_tpu_moe_capacity_slots",
        "MoE expert-buffer slots (num_experts × capacity) per layer",
        ("layer",))
    share = reg.gauge(
        "dl4j_tpu_moe_drop_share",
        "Share of this batch's MoE assignments dropped by capacity "
        "overflow: dropped / (kept + dropped)", ("layer",))
    load_cv = reg.gauge(
        "dl4j_tpu_moe_expert_load_cv",
        "Coefficient of variation (std/mean) of per-expert kept token "
        "counts this batch — 0 is perfect balance", ("layer",))
    seen = 0
    for lname, lstate in (state or {}).items():
        if not isinstance(lstate, Mapping) or "expert_tokens" not in lstate:
            continue
        seen += 1
        counts = np.asarray(lstate["expert_tokens"], dtype=np.float64)
        for e_idx, c in enumerate(counts.tolist()):
            tok.labels(lname, str(e_idx)).inc(c)
        kept = float(counts.sum())
        mean = counts.mean() if counts.size else 0.0
        load_cv.labels(lname).set(
            float(counts.std() / mean) if mean > 0 else 0.0)
        if "capacity_slots" in lstate:
            slots.labels(lname).set(
                float(np.asarray(lstate["capacity_slots"])))
        if "dropped_tokens" in lstate:
            dropped = float(np.asarray(lstate["dropped_tokens"]))
            drop.labels(lname).inc(dropped)
            total = kept + dropped
            share.labels(lname).set(dropped / total if total > 0 else 0.0)
    return seen


class MoEMetricsListener(TrainingListener):
    """Per-iteration MoE routing telemetry: expert load + capacity drops.

    Reads ``model.state`` after each iteration and feeds
    :func:`record_moe_metrics`. ``MultiLayerNetwork``/``ComputationGraph``
    training loops write the post-step state back onto the model every
    iteration, so the default works there directly. For
    ``DistributedTrainer`` the live state stays on-device unless a
    listener declares ``requires_arrays``; construct with
    ``sync_arrays=True`` to request that (it forces a per-iteration
    device→host sync of params AND state — pay it only when you need
    per-step expert-load curves off a distributed run).
    """

    requires_score = False

    def __init__(self, registry: Optional[MetricsRegistry] = None, *,
                 sync_arrays: bool = False) -> None:
        self.registry = registry if registry is not None else get_registry()
        self.requires_arrays = bool(sync_arrays)

    def iteration_done(self, model: Any, iteration: int, epoch: int,
                       score: float) -> None:
        record_moe_metrics(getattr(model, "state", None), self.registry)
