"""MetricsListener — the training-loop → metrics-registry bridge.

Built on the :class:`~deeplearning4j_tpu.core.listeners.TrainingListener`
SPI (the framework's one metrics bus), so it attaches to anything that
drives a ``ListenerBus``: ``MultiLayerNetwork.fit``,
``DistributedTrainer.fit``, and samediff ``TrainingSession.fit``.

It declares ``requires_score = False``: step latency and examples/sec need
no loss value, so attaching ONLY this listener must not force the per-step
device→host loss fetch the training loops otherwise avoid (measured round
5: ~64 ms per sync through the axon tunnel). Loops that honor
``ListenerBus.requires_score`` pass NaN instead, and the score gauge
simply skips NaN.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from ..core.listeners import TrainingListener
from .metrics import MetricsRegistry, get_registry

# Training steps range from sub-ms (tiny CPU tests) to seconds (pod-scale
# BERT), so the default latency buckets fit; examples/sec is derived by
# the scraper as rate(examples_total)/rate(step_latency_count).
_STEP_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


class MetricsListener(TrainingListener):
    """Feeds ``dl4j_tpu_training_*`` series from iteration callbacks.

    Series: ``iterations_total``, ``examples_total`` (from the model's
    ``last_batch_size``), ``epochs_total``, ``step_latency_seconds``
    (wall time between consecutive ``iteration_done`` calls — the full
    step including data wait, which is the fleet-level signal), and a
    ``score`` gauge updated whenever a real (non-NaN) score arrives.
    """

    requires_score = False

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        self._iterations = reg.counter(
            "dl4j_tpu_training_iterations_total",
            "Completed training iterations (optimizer steps)")
        self._examples = reg.counter(
            "dl4j_tpu_training_examples_total",
            "Training examples consumed (rows across all iterations)")
        self._epochs = reg.counter(
            "dl4j_tpu_training_epochs_total", "Completed training epochs")
        self._step_latency = reg.histogram(
            "dl4j_tpu_training_step_latency_seconds",
            "Wall time between consecutive training iterations",
            buckets=_STEP_BUCKETS)
        self._score = reg.gauge(
            "dl4j_tpu_training_score", "Most recent training score (loss)")
        self._last_t: Optional[float] = None

    def on_epoch_start(self, model: Any) -> None:
        # epoch boundaries include eval/checkpoint time; don't let that
        # masquerade as one huge training step
        self._last_t = None

    def on_epoch_end(self, model: Any) -> None:
        self._epochs.inc()
        self._last_t = None

    def iteration_done(self, model: Any, iteration: int, epoch: int,
                       score: float) -> None:
        now = time.perf_counter()
        if self._last_t is not None:
            self._step_latency.observe(now - self._last_t)
        self._last_t = now
        self._iterations.inc()
        batch = getattr(model, "last_batch_size", None)
        if batch:
            self._examples.inc(batch)
        if score == score:  # skip NaN (loop ran with requires_score=False)
            self._score.set(float(score))
