"""StepProfiler — per-phase training step-time attribution.

The input-pipeline open item (ROADMAP; ``resnet50_e2e_fit`` is
transfer-bound at 0.16× the synthetic step rate) needs *attribution*, not
just totals: a step's wall time splits into

* ``data_wait`` — time the training loop blocked waiting for the next
  batch (an :class:`~deeplearning4j_tpu.data.iterators.
  AsyncDataSetIterator` dequeue, file decode on a sync iterator, …),
* ``h2d`` — host→device transfer of the batch (``device_put`` /
  ``jnp.asarray`` on host memory),
* ``compute`` — device execution of the jitted step,
* ``host`` — host-side bookkeeping after dispatch (param reassignment,
  listeners, score fetch).

JAX dispatch is asynchronous: timing the jitted call measures only
dispatch (~µs) while the device runs in the background, and naively
fencing every step would serialize the pipeline the profiler is supposed
to diagnose. So ``compute`` (and ``h2d``) are **fenced only every
``sync_every`` steps** (``jax.block_until_ready``): sampled steps pay one
synchronization and yield a true device-time measurement; the other
steps run undisturbed and contribute to the cheap phases only. The
breakdown extrapolates the sampled mean across all steps — the MLPerf
TPU-pod input-pipeline methodology (PAPERS.md) of measuring input wait
vs transfer vs device compute before optimizing any of them.

Metrics: phase latencies land in
``dl4j_tpu_training_step_phase_seconds{instance=,phase=}``; the
:meth:`stats` breakdown is the per-instance view (README
"Observability" one-source-of-truth convention).
"""

from __future__ import annotations

import itertools
import time
from typing import Any, Dict, Optional

from .metrics import MetricsRegistry, get_registry

PHASES = ("data_wait", "h2d", "compute", "host")

# sub-ms tiny-model steps up to multi-second pod steps
_PHASE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                  0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_profiler_seq = itertools.count()


class _Phase:
    """Context manager timing one phase occurrence."""

    __slots__ = ("_prof", "_name", "_sampled", "_t0")

    def __init__(self, prof: "StepProfiler", name: str, sampled: bool) -> None:
        self._prof = prof
        self._name = name
        self._sampled = sampled
        self._t0 = 0.0

    def __enter__(self) -> "_Phase":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._prof.record(self._name, time.perf_counter() - self._t0,
                          sampled=self._sampled)


class StepProfiler:
    """Attributes training step wall time to ``data_wait`` / ``h2d`` /
    ``compute`` / ``host`` phases.

    Pass one to ``Solver(model, profiler=...)`` / ``GraphSolver`` and wrap
    the data source with :meth:`wrap_iterator`; every phase both feeds the
    metrics registry and accumulates into the :meth:`stats` breakdown.
    ``sync_every=N`` fences device work on every Nth step (N=0 never
    fences — device phases then measure dispatch only, which is stated in
    ``stats()['fenced']``).
    """

    def __init__(self, *, sync_every: int = 10,
                 registry: Optional[MetricsRegistry] = None,
                 name: Optional[str] = None) -> None:
        if sync_every < 0:
            raise ValueError(f"sync_every must be >= 0, got {sync_every}")
        self.sync_every = int(sync_every)
        self.name = name or f"profiler-{next(_profiler_seq)}"
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        hist = reg.histogram(
            "dl4j_tpu_training_step_phase_seconds",
            "Training step time by phase (data_wait=input pipeline, "
            "h2d=host-to-device transfer, compute=device step [fenced on "
            "sampled steps only], host=post-dispatch bookkeeping)",
            ("instance", "phase"), buckets=_PHASE_BUCKETS)
        self._hist = {p: hist.labels(self.name, p) for p in PHASES}
        self._totals: Dict[str, float] = {p: 0.0 for p in PHASES}
        self._counts: Dict[str, int] = {p: 0 for p in PHASES}
        # phases measured on fenced steps, tracked separately so the
        # extrapolation never mixes dispatch-only and fenced samples.
        # "host" is here too: on unfenced steps the device is still
        # executing in the background, and on a host whose cores the
        # device computation shares (CPU backend, busy TPU hosts) the
        # post-dispatch bookkeeping's WALL time absorbs device time —
        # only the post-fence (idle-device) samples are honest.
        self._sampled_totals = {"h2d": 0.0, "compute": 0.0, "host": 0.0}
        self._sampled_counts = {"h2d": 0, "compute": 0, "host": 0}
        self.steps = 0
        self.sampled_steps = 0
        self._step_open = False
        self._step_sampled = False

    # ---- step lifecycle ----------------------------------------------
    def begin_step(self) -> bool:
        """Start a step; returns True when this step should fence device
        work (``jax.block_until_ready``) so compute/h2d are real."""
        self._step_open = True
        self._step_sampled = (self.sync_every > 0
                              and self.steps % self.sync_every == 0)
        return self._step_sampled

    def end_step(self) -> None:
        if not self._step_open:
            return
        self._step_open = False
        self.steps += 1
        if self._step_sampled:
            self.sampled_steps += 1

    # ---- recording ----------------------------------------------------
    def phase(self, name: str, *, sampled: bool = False) -> _Phase:
        """``with profiler.phase("h2d"): ...`` — times the block into the
        phase. ``sampled=True`` marks a fenced device measurement."""
        if name not in self._totals:
            raise ValueError(f"unknown phase {name!r}; expected one of {PHASES}")
        return _Phase(self, name, sampled)

    def record(self, name: str, seconds: float, *, sampled: bool = False) -> None:
        self._totals[name] += seconds
        self._counts[name] += 1
        if sampled and name in self._sampled_totals:
            self._sampled_totals[name] += seconds
            self._sampled_counts[name] += 1
        self._hist[name].observe(seconds)

    def record_data_wait(self, seconds: float) -> None:
        self.record("data_wait", seconds)

    # ---- iterator instrumentation ------------------------------------
    def wrap_iterator(self, iterator):
        """Wrap a ``DataSetIterator`` (or any iterable) so the time the
        consumer blocks in ``next()`` is attributed to ``data_wait``."""
        return _ProfiledIterator(iterator, self)

    # ---- breakdown ----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """Per-phase breakdown.

        ``per_step_ms`` uses fenced (sampled) means for the device phases
        and all-step means for the host phases; ``share`` normalizes those
        attributed per-step costs — the number that must *explain* an
        e2e/synthetic throughput ratio, not just restate totals.
        """
        steps = max(self.steps, 1)
        per_step_ms: Dict[str, float] = {}
        for p in PHASES:
            if p in self._sampled_totals and self._sampled_counts[p] > 0:
                mean = self._sampled_totals[p] / self._sampled_counts[p]
            else:
                mean = self._totals[p] / steps
            per_step_ms[p] = mean * 1e3
        total_ms = sum(per_step_ms.values())
        share = {p: (v / total_ms if total_ms > 0 else 0.0)
                 for p, v in per_step_ms.items()}
        # the step time were the input pipeline free (data already in
        # HBM): what the bench reports as *_excl_transfer_wall
        excl_input_ms = per_step_ms["compute"] + per_step_ms["host"]
        return {
            "steps": self.steps,
            "sampled_steps": self.sampled_steps,
            "fenced": self.sync_every > 0,
            "seconds_total": {p: self._totals[p] for p in PHASES},
            "per_step_ms": {p: round(v, 4) for p, v in per_step_ms.items()},
            "share": {p: round(v, 4) for p, v in share.items()},
            "step_time_ms_est": round(total_ms, 4),
            "step_time_ms_excl_input": round(excl_input_ms, 4),
            "input_bound_share": round(
                share["data_wait"] + share["h2d"], 4),
        }

    def samples_per_sec_excl_input(self, batch_size: int) -> Optional[float]:
        """Projected throughput with the input pipeline (data_wait + h2d)
        taken out of the step — the bench's
        ``samples_per_sec_excl_transfer_wall``. None until a step with
        nonzero compute/host time has been recorded."""
        excl_ms = self.stats()["step_time_ms_excl_input"]
        if excl_ms <= 0:
            return None
        return batch_size / (excl_ms / 1e3)


class _ProfiledIterator:
    """DataSetIterator/iterable proxy attributing ``next()`` wall time to
    the profiler's ``data_wait`` phase."""

    def __init__(self, underlying, profiler: StepProfiler) -> None:
        self.underlying = underlying
        self.profiler = profiler

    # DataSetIterator protocol --------------------------------------------
    def has_next(self) -> bool:
        return self.underlying.has_next()

    def next(self):
        t0 = time.perf_counter()
        try:
            return self.underlying.next()
        finally:
            self.profiler.record_data_wait(time.perf_counter() - t0)

    def reset(self) -> None:
        self.underlying.reset()

    def batch_size(self) -> int:
        return self.underlying.batch_size()

    def stats(self) -> dict:
        s = getattr(self.underlying, "stats", None)
        return s() if callable(s) else {}

    def close(self, *a, **kw) -> None:
        c = getattr(self.underlying, "close", None)
        if callable(c):
            c(*a, **kw)

    # plain-iterable protocol ---------------------------------------------
    def __iter__(self):
        self._it = iter(self.underlying)
        return self

    def __next__(self):
        t0 = time.perf_counter()
        item = next(self._it)  # StopIteration is not a wait to attribute
        self.profiler.record_data_wait(time.perf_counter() - t0)
        return item
