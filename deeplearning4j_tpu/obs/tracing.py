"""Distributed tracing: trace identity, W3C propagation, span trees.

The metrics registry (obs/metrics.py) answers "how much time was spent";
it cannot answer "where did THIS request's time go" — there is no trace
identity, no parent/child structure, and nothing crosses the
client→server→engine hop. This module adds exactly that, in the shape
production tracing systems share (Dapper lineage; W3C Trace Context for
the wire format):

* :class:`TraceContext` — immutable (128-bit trace id, 64-bit span id,
  parent id, sampled flag) identity, encoded/decoded as a W3C
  ``traceparent`` header (``00-<32 hex>-<16 hex>-<flags>``).
* :class:`TraceSpan` — a timed operation. Spans **nest**: entering a span
  makes it the contextvar-current span, so a child opened anywhere below
  it (same thread or same asyncio task) parents automatically; exiting —
  including via an exception, which marks ``error=True`` — restores the
  previous current span. Cross-thread children (a serving worker
  finishing a request enqueued by an HTTP handler) are parented
  explicitly via :meth:`Tracer.record_span`.
* :class:`TraceStore` — thread-safe, doubly-bounded (traces × spans per
  trace) ring of completed traces, queried by ``/v1/traces``.
* :class:`Tracer` — the factory components hold: sampling decision at
  root creation, no-op spans when disabled. **Disabled tracing is
  byte-identical behavior**: no ids are generated, no headers injected,
  no spans stored (``tools/check_trace_contract.py`` enforces this and
  the <3% enabled overhead bound in bench's ``tracing_overhead`` row).

Timestamps: every span timestamp is ``perf_counter`` anchored to one
process-wide wall-clock epoch, so timestamps are strictly monotonic
across threads (wall-clock steps can never reorder a parent after its
child) while still reading as UNIX time.
"""

from __future__ import annotations

import contextvars
import os
import queue
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Union

__all__ = [
    "DEFAULT_SAMPLE_RATE",
    "TraceContext",
    "TraceSpan",
    "TraceStore",
    "Tracer",
    "current_context",
    "current_span",
    "decode_traceparent",
    "encode_traceparent",
    "get_tracer",
    "set_tracer",
    "trace_now",
]

# one anchor for the whole process: monotonic clock, wall-clock origin
_EPOCH = time.time() - time.perf_counter()


def trace_now() -> float:
    """Monotonic wall-clock-anchored timestamp (seconds since the UNIX
    epoch, advanced by ``perf_counter``)."""
    return _EPOCH + time.perf_counter()


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


class TraceContext:
    """Immutable trace identity: what crosses a process/thread boundary."""

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None,
                 sampled: bool = True) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = bool(sampled)

    def child(self) -> "TraceContext":
        """A fresh span identity under this context (same trace)."""
        return TraceContext(self.trace_id, _new_span_id(),
                            parent_id=self.span_id, sampled=self.sampled)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"TraceContext(trace_id={self.trace_id!r}, "
                f"span_id={self.span_id!r}, parent_id={self.parent_id!r}, "
                f"sampled={self.sampled})")

    def __eq__(self, other: object) -> bool:
        return (isinstance(other, TraceContext)
                and self.trace_id == other.trace_id
                and self.span_id == other.span_id
                and self.sampled == other.sampled)

    def __hash__(self) -> int:
        return hash((self.trace_id, self.span_id, self.sampled))


# ---------------------------------------------------------------------------
# W3C traceparent (https://www.w3.org/TR/trace-context/)
# ---------------------------------------------------------------------------
def encode_traceparent(ctx: TraceContext) -> str:
    """``00-<trace id:32 hex>-<span id:16 hex>-<flags:2 hex>``; flag bit 0
    is "sampled"."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if ctx.sampled else '00'}"


def _is_hex(s: str) -> bool:
    try:
        int(s, 16)
        return True
    except ValueError:
        return False


def decode_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """Parse a ``traceparent`` header into a :class:`TraceContext`, or
    ``None`` for anything malformed (lenient by spec: a bad header means
    "start a new trace", never an error). Accepts future versions except
    the forbidden ``ff``; rejects all-zero ids."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if len(version) != 2 or not _is_hex(version) or version.lower() == "ff":
        return None
    if len(trace_id) != 32 or not _is_hex(trace_id) or set(trace_id) == {"0"}:
        return None
    if len(span_id) != 16 or not _is_hex(span_id) or set(span_id) == {"0"}:
        return None
    if len(flags) != 2 or not _is_hex(flags):
        return None
    sampled = bool(int(flags, 16) & 0x01)
    return TraceContext(trace_id.lower(), span_id.lower(), sampled=sampled)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
_current_span: "contextvars.ContextVar[Optional[TraceSpan]]" = \
    contextvars.ContextVar("dl4j_tpu_current_span", default=None)


def current_span() -> Optional["TraceSpan"]:
    """The innermost open :class:`TraceSpan` in this thread/context."""
    return _current_span.get()


def current_context() -> Optional[TraceContext]:
    """The innermost open span's :class:`TraceContext` (None outside any
    span)."""
    span = _current_span.get()
    return span.context if span is not None else None


class TraceSpan:
    """One timed operation in a trace. Context-manager entry makes it the
    current span (contextvar — per-thread and per-async-task); exit
    restores the previous current span even when the body raises, in
    which case ``error=True`` and the exception type is recorded."""

    __slots__ = ("tracer", "name", "trace_id", "span_id", "parent_id",
                 "sampled", "attributes", "start_time", "end_time", "error",
                 "_token", "_finished")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: str, parent_id: Optional[str], sampled: bool,
                 attrs: Optional[dict] = None) -> None:
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = sampled
        self.attributes: Dict[str, Any] = dict(attrs) if attrs else {}
        self.start_time = trace_now()
        self.end_time: Optional[float] = None
        self.error = False
        self._token = None
        self._finished = False

    @property
    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id,
                            parent_id=self.parent_id, sampled=self.sampled)

    @property
    def duration(self) -> Optional[float]:
        return None if self.end_time is None else self.end_time - self.start_time

    def set_attribute(self, key: str, value: Any) -> "TraceSpan":
        self.attributes[key] = value
        return self

    def record_exception(self, exc: BaseException) -> None:
        self.error = True
        self.attributes.setdefault("exception", type(exc).__name__)

    def __enter__(self) -> "TraceSpan":
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # restore-first: even if export misbehaves, the previous current
        # span must come back (contextvar token reset is exact — nested
        # and concurrent-thread spans cannot cross-restore)
        if self._token is not None:
            _current_span.reset(self._token)
            self._token = None
        if exc is not None:
            self.record_exception(exc)
        elif exc_type is not None:
            self.error = True
            self.attributes.setdefault("exception", exc_type.__name__)
        self.finish()

    def finish(self, end_time: Optional[float] = None) -> None:
        """Close the span and export it to the tracer's store (idempotent;
        unsampled spans keep identity but are never stored)."""
        if self._finished:
            return
        self._finished = True
        self.end_time = end_time if end_time is not None else trace_now()
        if self.sampled:
            self.tracer._export(self._record())

    def _record(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start_time,
            "end": self.end_time,
            "duration_ms": round((self.end_time - self.start_time) * 1e3, 6),
            "error": self.error,
            "attrs": self.attributes,
        }


class _NullSpan:
    """Returned while tracing is disabled/unsampled creation is skipped:
    absorbs the span API at near-zero cost. ``context`` is None, which is
    the signal callers use to skip header injection."""

    __slots__ = ()
    context = None
    trace_id = span_id = parent_id = None
    sampled = False
    name = ""
    start_time = end_time = None
    duration = None
    attributes: Dict[str, Any] = {}

    # writable no-op: callers flag 5xx responses with ``span.error = True``
    # on whatever span they hold — an unsampled request must absorb that
    # write, not kill the handler thread with an AttributeError
    @property
    def error(self) -> bool:
        return False

    @error.setter
    def error(self, value) -> None:
        pass

    def set_attribute(self, key: str, value: Any) -> "_NullSpan":
        return self

    def record_exception(self, exc: BaseException) -> None:
        pass

    def finish(self, end_time: Optional[float] = None) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


NULL_SPAN = _NullSpan()


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------
class TraceStore:
    """Bounded in-memory index of completed spans, grouped by trace.

    Memory is bounded on BOTH axes: at most ``max_traces`` traces are
    retained (oldest-touched evicted first) and at most
    ``max_spans_per_trace`` spans are kept per trace (later spans are
    counted, not stored — a runaway fan-out cannot grow a trace without
    bound). ``tools/check_trace_contract.py`` enforces both bounds.
    """

    def __init__(self, max_traces: int = 256,
                 max_spans_per_trace: int = 256) -> None:
        self.max_traces = int(max_traces)
        self.max_spans_per_trace = int(max_spans_per_trace)
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, dict]" = OrderedDict()
        self.dropped_spans = 0
        self.evicted_traces = 0

    def add(self, span: dict) -> None:
        tid = span["trace_id"]
        with self._lock:
            entry = self._traces.get(tid)
            if entry is None:
                entry = {"spans": [], "dropped": 0}
                self._traces[tid] = entry
            else:
                self._traces.move_to_end(tid)
            if len(entry["spans"]) >= self.max_spans_per_trace:
                entry["dropped"] += 1
                self.dropped_spans += 1
            else:
                entry["spans"].append(span)
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)
                self.evicted_traces += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)

    def span_count(self) -> int:
        with self._lock:
            return sum(len(e["spans"]) for e in self._traces.values())

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            entry = self._traces.get(trace_id)
            if entry is None:
                return None
            spans = list(entry["spans"])
            dropped = entry["dropped"]
        return self._assemble(trace_id, spans, dropped)

    @staticmethod
    def _assemble(trace_id: str, spans: List[dict], dropped: int) -> dict:
        spans = sorted(spans, key=lambda s: s["start"])
        ids = {s["span_id"] for s in spans}
        # root = earliest span whose parent is unknown to this trace
        # (either a true root or the local edge of a remote parent)
        roots = [s for s in spans
                 if s["parent_id"] is None or s["parent_id"] not in ids]
        root = roots[0] if roots else (spans[0] if spans else None)
        start = min((s["start"] for s in spans), default=0.0)
        end = max((s["end"] for s in spans), default=0.0)
        routes = sorted({s["attrs"]["route"] for s in spans
                         if "route" in s["attrs"]})
        return {
            "trace_id": trace_id,
            "root": root["name"] if root else None,
            "start": start,
            "duration_ms": round((end - start) * 1e3, 6),
            "span_count": len(spans),
            "dropped_spans": dropped,
            "error": any(s["error"] for s in spans),
            "routes": routes,
            "spans": spans,
        }

    def traces(self, *, min_duration_ms: Optional[float] = None,
               route: Optional[str] = None,
               limit: int = 50) -> List[dict]:
        """Most-recently-completed first, optionally filtered by total
        trace duration and by a ``route`` attribute present on any span
        (the ``/v1/traces`` query surface)."""
        with self._lock:
            items = [(tid, list(e["spans"]), e["dropped"])
                     for tid, e in self._traces.items()]
        out = []
        for tid, spans, dropped in reversed(items):
            t = self._assemble(tid, spans, dropped)
            if min_duration_ms is not None and t["duration_ms"] < min_duration_ms:
                continue
            if route is not None and route not in t["routes"]:
                continue
            out.append(t)
            if len(out) >= max(int(limit), 1):
                break
        return out


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------
class Tracer:
    """Span factory + sampling policy + the store spans export to.

    ``enabled=False`` (or :meth:`disable`) short-circuits everything to
    :data:`NULL_SPAN` — no ids, no headers, no storage. ``sample_rate``
    decides **per trace, head-based, at root creation**: an unsampled
    trace takes the same near-zero NULL path as disabled tracing (no ids
    generated, no header propagated, no children anywhere downstream), so
    fractional sampling scales tracing cost linearly down — the classic
    Dapper trade: every request keeps its request id, one in N carries a
    full client→server→engine span tree.

    Export is **asynchronous** (the batch-span-processor shape real
    tracers use): a finished span costs the hot thread one C-level
    ``SimpleQueue.put``; a lazy daemon flusher thread moves records into
    the bounded store. Under the GIL this matters more than it looks —
    store writes on a serving worker would otherwise delay the handler
    thread it just woke. :meth:`flush` (FIFO marker) gives readers a
    consistent point; readers that poll work too.
    """

    def __init__(self, store: Optional[TraceStore] = None, *,
                 enabled: bool = True, sample_rate: float = 1.0) -> None:
        if not 0.0 <= float(sample_rate) <= 1.0:
            raise ValueError(f"sample_rate must be in [0, 1], got {sample_rate}")
        self.store = store if store is not None else TraceStore()
        self.enabled = bool(enabled)
        self.sample_rate = float(sample_rate)
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._flusher: Optional[threading.Thread] = None
        self._flusher_lock = threading.Lock()

    def enable(self) -> "Tracer":
        self.enabled = True
        return self

    def disable(self) -> "Tracer":
        self.enabled = False
        return self

    def _sample(self) -> bool:
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        # 53 random bits -> uniform [0, 1); no global random state touched
        return (int.from_bytes(os.urandom(7), "big") >> 3) < \
            self.sample_rate * (1 << 53)

    def span(self, name: str, *,
             parent: Union[TraceContext, TraceSpan, None, str] = "current",
             attrs: Optional[dict] = None):
        """Open a span (use as a context manager, or call ``finish()``).

        ``parent`` defaults to the current contextvar span; pass an
        explicit :class:`TraceContext` (e.g. decoded from ``traceparent``)
        to continue a remote trace, or ``None`` to force a new root. A
        head-unsampled root — and any child of an unsampled context —
        returns :data:`NULL_SPAN`, the zero-cost path.
        """
        if not self.enabled:
            return NULL_SPAN
        if parent == "current":
            parent = current_span()
        if isinstance(parent, TraceSpan):
            parent = parent.context
        if parent is None:
            if not self._sample():
                return NULL_SPAN
            return TraceSpan(self, name, _new_trace_id(), _new_span_id(),
                             None, True, attrs)
        if not parent.sampled:
            return NULL_SPAN
        return TraceSpan(self, name, parent.trace_id, _new_span_id(),
                         parent.span_id, True, attrs)

    @staticmethod
    def make_record(name: str, parent: Union[TraceContext, TraceSpan, None],
                    start_time: float, end_time: float,
                    attrs: Optional[dict] = None,
                    error: bool = False,
                    span_id: Optional[str] = None) -> Optional[dict]:
        """Build a completed-span record for an already-measured operation
        (no TraceSpan allocation — this sits near serving hot paths).
        ``span_id`` pins an identity that was already propagated (e.g. the
        client attempt id sent in ``traceparent``). Returns None when the
        parent is absent/unsampled."""
        if isinstance(parent, TraceSpan):
            parent = parent.context
        if parent is None or not parent.sampled:
            return None
        start_time = float(start_time)
        end_time = float(end_time)
        return {
            "trace_id": parent.trace_id,
            "span_id": span_id if span_id is not None else _new_span_id(),
            "parent_id": parent.span_id,
            "name": name,
            "start": start_time,
            "end": end_time,
            "duration_ms": round((end_time - start_time) * 1e3, 6),
            "error": bool(error),
            "attrs": dict(attrs) if attrs else {},
        }

    def record_span(self, name: str, *, parent: Union[TraceContext, TraceSpan],
                    start_time: float, end_time: float,
                    attrs: Optional[dict] = None,
                    error: bool = False) -> None:
        """Synthesize an already-measured span (cross-thread children: the
        caller measured start/end itself, e.g. a serving worker attributing
        queue wait for a request enqueued by another thread)."""
        if not self.enabled:
            return
        rec = self.make_record(name, parent, start_time, end_time,
                               attrs=attrs, error=error)
        if rec is not None:
            self._export(rec)

    def record_spans(self, records: List[Optional[dict]]) -> None:
        """Bulk export of :meth:`make_record` results — ONE queue put (one
        potential flusher wakeup) for a whole batch of spans."""
        if not self.enabled:
            return
        batch = [r for r in records if r is not None]
        if batch:
            self._q.put(batch)
            if self._flusher is None:
                self._ensure_flusher()

    def _export(self, record: dict) -> None:
        self._q.put(record)
        if self._flusher is None:
            self._ensure_flusher()

    def _ensure_flusher(self) -> None:
        with self._flusher_lock:
            if self._flusher is None:
                t = threading.Thread(target=self._run_flusher,
                                     name="trace-flusher", daemon=True)
                t.start()
                self._flusher = t

    # Debounce between the wakeup and the drain: while the flusher
    # sleeps, no getter is parked on the queue, so hot-thread puts are a
    # pure C append with NO thread wakeup — measured on the loopback
    # serving bench, per-put wakeups (6 spans/request) cost up to ~100us
    # of GIL handoff per request; batched drains make it ~one wakeup per
    # burst. A put may be a single record or a LIST of records (bulk
    # exporters like the engine worker batch per forward).
    _FLUSH_DEBOUNCE_S = 0.01

    def _run_flusher(self) -> None:
        while True:
            item = self._q.get()  # blocks (and parks) only when idle
            time.sleep(self._FLUSH_DEBOUNCE_S)
            while True:
                if isinstance(item, threading.Event):  # flush() marker
                    item.set()
                elif isinstance(item, list):
                    for rec in item:
                        self.store.add(rec)
                else:
                    self.store.add(item)
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    break

    def flush(self, timeout: float = 5.0) -> bool:
        """Wait until every span exported SO FAR is in the store (FIFO
        marker through the export queue). Returns False on timeout."""
        if self._flusher is None:
            return True  # nothing was ever exported
        marker = threading.Event()
        self._q.put(marker)
        return marker.wait(timeout)


# ---------------------------------------------------------------------------
# process-global default
# ---------------------------------------------------------------------------
# Default sampling for the PROCESS-GLOBAL tracer only (explicitly
# constructed Tracers default to 1.0 so tests capture everything). One in
# ten traces is the classic production fraction (Dapper's answer to
# tracing cost): propagation headers and request ids flow on EVERY
# request, span storage costs only the sampled slice — which is what
# keeps default-config overhead under the 3% serving budget on small
# hosts. Raise it per process via ``set_tracer(Tracer(sample_rate=1.0))``
# when diagnosing.
DEFAULT_SAMPLE_RATE = 0.1

_default_tracer = Tracer(sample_rate=DEFAULT_SAMPLE_RATE)


def get_tracer() -> Tracer:
    """The process-wide tracer: serving, training and deploy paths export
    into one store, so ``/v1/traces`` on any server in the process shows
    the whole picture."""
    return _default_tracer


def set_tracer(tracer: Optional[Tracer]) -> Tracer:
    """Install a process-global tracer (tests); ``None`` installs a fresh
    default-sampled one. Returns the previous tracer so callers can
    restore it."""
    global _default_tracer
    prev = _default_tracer
    _default_tracer = tracer if tracer is not None else \
        Tracer(sample_rate=DEFAULT_SAMPLE_RATE)
    return prev
