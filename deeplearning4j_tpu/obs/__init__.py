"""Process-wide observability: metrics registry, Prometheus exposition,
trace spans, and the training-listener bridge.

One registry (default process-global, injectable everywhere) is the single
source of truth for serving (``ParallelInference``, ``JsonModelServer``),
resilience (circuit/admission/retry/elastic_fit), training
(:class:`MetricsListener`), and data (``AsyncDataSetIterator``) signals;
``GET /metrics`` on ``JsonModelServer`` and ``UIServer`` exposes it in
Prometheus text format 0.0.4. See README "Observability" for the metric
naming convention and the ``stats()`` ↔ metrics mapping.
"""

from .listener import MetricsListener, MoEMetricsListener, record_moe_metrics
from .metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricError,
    MetricsRegistry,
    Span,
    get_registry,
    set_registry,
    trace,
)
from .prom import CONTENT_TYPE as PROM_CONTENT_TYPE
from .prom import render_prometheus

__all__ = [
    "Counter",
    "DEFAULT_BUCKETS",
    "Gauge",
    "Histogram",
    "MetricError",
    "MetricsListener",
    "MetricsRegistry",
    "MoEMetricsListener",
    "PROM_CONTENT_TYPE",
    "Span",
    "get_registry",
    "record_moe_metrics",
    "render_prometheus",
    "set_registry",
    "trace",
]
