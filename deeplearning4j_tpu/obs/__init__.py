"""Process-wide observability: metrics registry, Prometheus exposition,
distributed tracing, step-time attribution, and the training-listener
bridge.

One registry (default process-global, injectable everywhere) is the single
source of truth for serving (``ParallelInference``, ``JsonModelServer``),
resilience (circuit/admission/retry/elastic_fit), training
(:class:`MetricsListener`), and data (``AsyncDataSetIterator``) signals;
``GET /metrics`` on ``JsonModelServer`` and ``UIServer`` exposes it in
Prometheus text format 0.0.4. See README "Observability" for the metric
naming convention and the ``stats()`` ↔ metrics mapping.

Tracing (``obs/tracing.py``): :class:`Tracer`/:class:`TraceSpan` give
requests identity (W3C ``traceparent``) and parent/child structure across
the client→server→engine hop, exported to a bounded :class:`TraceStore`
served by ``GET /v1/traces``. :class:`StepProfiler`
(``obs/step_profiler.py``) attributes training step time to
data_wait/h2d/compute/host phases with sampled device fencing. README
"Tracing & step-time attribution".
"""

from .listener import MetricsListener, MoEMetricsListener, record_moe_metrics
from .metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricError,
    MetricsRegistry,
    Span,
    get_registry,
    set_registry,
    trace,
)
from .prom import CONTENT_TYPE as PROM_CONTENT_TYPE
from .prom import render_prometheus
from .step_profiler import PHASES as STEP_PHASES
from .step_profiler import StepProfiler
from .tracing import (
    DEFAULT_SAMPLE_RATE as DEFAULT_TRACE_SAMPLE_RATE,
    TraceContext,
    TraceSpan,
    TraceStore,
    Tracer,
    current_context,
    current_span,
    decode_traceparent,
    encode_traceparent,
    get_tracer,
    set_tracer,
    trace_now,
)

__all__ = [
    "Counter",
    "DEFAULT_BUCKETS",
    "DEFAULT_TRACE_SAMPLE_RATE",
    "Gauge",
    "Histogram",
    "MetricError",
    "MetricsListener",
    "MetricsRegistry",
    "MoEMetricsListener",
    "PROM_CONTENT_TYPE",
    "STEP_PHASES",
    "Span",
    "StepProfiler",
    "TraceContext",
    "TraceSpan",
    "TraceStore",
    "Tracer",
    "current_context",
    "current_span",
    "decode_traceparent",
    "encode_traceparent",
    "get_registry",
    "get_tracer",
    "record_moe_metrics",
    "render_prometheus",
    "set_registry",
    "set_tracer",
    "trace",
    "trace_now",
]
