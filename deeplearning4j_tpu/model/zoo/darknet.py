"""Darknet-19 and TinyYOLO.

Reference: org.deeplearning4j.zoo.model.{Darknet19, TinyYOLO}. Both are
conv-BN-leakyReLU stacks; TinyYOLO's head emits the YOLOv2 grid tensor
[b, B*(5+C), gh, gw] with B anchor boxes.
"""

from __future__ import annotations

from ...nn import Activation, InputType, LossFunction, NeuralNetConfiguration, WeightInit
from ...nn.sequential import MultiLayerNetwork
from ...nn.layers import (
    ActivationLayer,
    BatchNormalizationLayer,
    ConvolutionLayer,
    ConvolutionMode,
    GlobalPoolingLayer,
    LossLayer,
    PoolingType,
    SubsamplingLayer,
)
from ...train.updaters import Adam, Nesterovs


def _conv_block(b, n_out, kernel=(3, 3)):
    b.layer(ConvolutionLayer(
        n_out=n_out, kernel_size=kernel, convolution_mode=ConvolutionMode.SAME,
        has_bias=False, activation=Activation.IDENTITY))
    b.layer(BatchNormalizationLayer())
    b.layer(ActivationLayer(activation=Activation.LEAKYRELU))
    return b


class Darknet19:
    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3,
                 updater=None, dtype: str = "float32") -> None:
        self.num_classes = num_classes
        self.seed = seed
        self.height, self.width, self.channels = height, width, channels
        self.updater = updater or Nesterovs(1e-3, 0.9)
        self.dtype = dtype

    def conf(self):
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed).data_type(self.dtype).updater(self.updater)
             .weight_init(WeightInit.RELU).list())
        _conv_block(b, 32)
        b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        _conv_block(b, 64)
        b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        for f in (128, 256):
            _conv_block(b, f)
            _conv_block(b, f // 2, (1, 1))
            _conv_block(b, f)
            b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        for f in (512, 1024):
            _conv_block(b, f)
            _conv_block(b, f // 2, (1, 1))
            _conv_block(b, f)
            _conv_block(b, f // 2, (1, 1))
            _conv_block(b, f)
            if f == 512:
                b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        b.layer(ConvolutionLayer(n_out=self.num_classes, kernel_size=(1, 1),
                                 convolution_mode=ConvolutionMode.SAME))
        b.layer(GlobalPoolingLayer(pooling_type=PoolingType.AVG))
        b.layer(ActivationLayer(activation=Activation.SOFTMAX))
        b.layer(LossLayer(loss=LossFunction.MCXENT))
        return b.set_input_type(InputType.convolutional(
            self.height, self.width, self.channels)).build()

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


class TinyYOLO:
    """Tiny YOLOv2 backbone + detection head. The head outputs the raw grid
    tensor [b, B*(5+C), gh, gw]; box decoding/NMS is post-processing (as in
    the reference's YOLO utils), not part of the graph."""

    def __init__(self, num_classes: int = 20, num_boxes: int = 5,
                 seed: int = 123, height: int = 416, width: int = 416,
                 channels: int = 3, updater=None,
                 dtype: str = "float32") -> None:
        self.num_classes = num_classes
        self.num_boxes = num_boxes
        self.seed = seed
        self.height, self.width, self.channels = height, width, channels
        self.updater = updater or Adam(1e-3)
        self.dtype = dtype

    def conf(self):
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed).data_type(self.dtype).updater(self.updater)
             .weight_init(WeightInit.RELU).list())
        filters = [16, 32, 64, 128, 256]
        for f in filters:
            _conv_block(b, f)
            b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        _conv_block(b, 512)
        # stride-1 maxpool (same padding) as in tiny-yolo
        b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(1, 1),
                                 convolution_mode=ConvolutionMode.SAME))
        _conv_block(b, 1024)
        _conv_block(b, 1024)
        depth = self.num_boxes * (5 + self.num_classes)
        b.layer(ConvolutionLayer(n_out=depth, kernel_size=(1, 1),
                                 convolution_mode=ConvolutionMode.SAME,
                                 activation=Activation.IDENTITY))
        return b.set_input_type(InputType.convolutional(
            self.height, self.width, self.channels)).build()

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()
