from .lenet import LeNet

__all__ = ["LeNet"]
