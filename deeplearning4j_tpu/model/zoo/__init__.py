"""Model zoo (reference: org.deeplearning4j.zoo.model.* — SURVEY.md §2.2).

No pretrained-weight downloads (zero-egress environment); architectures are
construction-parity with the reference and train from scratch.
"""

from ...generate.sampling import greedy, temperature, top_k, top_p
from .bert import BertEncoder
from .darknet import Darknet19, TinyYOLO
from .inception_resnet import InceptionResNetV1
from .lenet import LeNet
from .misc import FaceNetNN4Small2, SimpleCNN, YOLO2
from .resnet50 import ResNet50
from .squeezenet import SqueezeNet
from .textgen_lstm import TextGenerationLSTM
from .transformer_lm import TransformerLM
from .unet import UNet
from .vgg16 import AlexNet, VGG16, VGG19
from .xception import Xception
from .nasnet import NASNet

__all__ = [
    "AlexNet",
    "BertEncoder",
    "Darknet19",
    "FaceNetNN4Small2",
    "InceptionResNetV1",
    "LeNet",
    "ResNet50",
    "SimpleCNN",
    "SqueezeNet",
    "TextGenerationLSTM",
    "TinyYOLO",
    "TransformerLM",
    "UNet",
    "greedy",
    "temperature",
    "top_k",
    "top_p",
    "VGG16",
    "VGG19",
    "YOLO2",
    "Xception",
    "NASNet",
]
