from .lenet import LeNet
from .resnet50 import ResNet50
from .vgg16 import AlexNet, VGG16

__all__ = ["AlexNet", "LeNet", "ResNet50", "VGG16"]
