"""Xception.

Reference: org.deeplearning4j.zoo.model.Xception — separable-conv blocks
with residual 1x1-conv shortcuts (entry/middle/exit flow).
"""

from __future__ import annotations

from ...nn import Activation, InputType, LossFunction, NeuralNetConfiguration, WeightInit
from ...nn.graph import ComputationGraph
from ...nn.layers import (
    ActivationLayer,
    BatchNormalizationLayer,
    ConvolutionLayer,
    ConvolutionMode,
    GlobalPoolingLayer,
    OutputLayer,
    PoolingType,
    SeparableConvolution2DLayer,
    SubsamplingLayer,
)
from ...nn.vertices import ElementWiseOp, ElementWiseVertex
from ...train.updaters import Adam


class Xception:
    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 299, width: int = 299, channels: int = 3,
                 middle_blocks: int = 8, updater=None,
                 dtype: str = "float32") -> None:
        self.num_classes = num_classes
        self.seed = seed
        self.height, self.width, self.channels = height, width, channels
        self.middle_blocks = middle_blocks
        self.updater = updater or Adam(1e-3)
        self.dtype = dtype

    def _conv_bn(self, g, name, inp, n_out, kernel, stride=(1, 1), relu=True):
        g.add_layer(f"{name}", ConvolutionLayer(
            n_out=n_out, kernel_size=kernel, stride=stride, has_bias=False,
            convolution_mode=ConvolutionMode.SAME,
            activation=Activation.IDENTITY), inp)
        g.add_layer(f"{name}_bn", BatchNormalizationLayer(), name)
        if relu:
            g.add_layer(f"{name}_relu",
                        ActivationLayer(activation=Activation.RELU),
                        f"{name}_bn")
            return f"{name}_relu"
        return f"{name}_bn"

    def _sep_bn(self, g, name, inp, n_out, pre_relu=True):
        x = inp
        if pre_relu:
            g.add_layer(f"{name}_prerelu",
                        ActivationLayer(activation=Activation.RELU), x)
            x = f"{name}_prerelu"
        g.add_layer(name, SeparableConvolution2DLayer(
            n_out=n_out, kernel_size=(3, 3), has_bias=False,
            convolution_mode=ConvolutionMode.SAME,
            activation=Activation.IDENTITY), x)
        g.add_layer(f"{name}_bn", BatchNormalizationLayer(), name)
        return f"{name}_bn"

    def _xception_block(self, g, name, inp, n_out, first_relu=True):
        """Two separable convs + stride-2 pool, with a 1x1/2 conv shortcut."""
        x = self._sep_bn(g, f"{name}_s1", inp, n_out, pre_relu=first_relu)
        x = self._sep_bn(g, f"{name}_s2", x, n_out)
        g.add_layer(f"{name}_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        short = self._conv_bn(g, f"{name}_short", inp, n_out, (1, 1), (2, 2),
                              relu=False)
        g.add_vertex(f"{name}_add", ElementWiseVertex(op=ElementWiseOp.ADD),
                     f"{name}_pool", short)
        return f"{name}_add"

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).data_type(self.dtype).updater(self.updater)
             .weight_init(WeightInit.RELU)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        # entry flow
        x = self._conv_bn(g, "stem1", "input", 32, (3, 3), (2, 2))
        x = self._conv_bn(g, "stem2", x, 64, (3, 3))
        x = self._xception_block(g, "entry1", x, 128, first_relu=False)
        x = self._xception_block(g, "entry2", x, 256)
        x = self._xception_block(g, "entry3", x, 728)
        # middle flow: residual triple separable convs
        for i in range(self.middle_blocks):
            name = f"mid{i}"
            y = self._sep_bn(g, f"{name}_s1", x, 728)
            y = self._sep_bn(g, f"{name}_s2", y, 728)
            y = self._sep_bn(g, f"{name}_s3", y, 728)
            g.add_vertex(f"{name}_add",
                         ElementWiseVertex(op=ElementWiseOp.ADD), y, x)
            x = f"{name}_add"
        # exit flow
        x = self._xception_block(g, "exit1", x, 1024)
        x = self._sep_bn(g, "exit_s1", x, 1536, pre_relu=False)
        g.add_layer("exit_s1_relu", ActivationLayer(
            activation=Activation.RELU), x)
        x = self._sep_bn(g, "exit_s2", "exit_s1_relu", 2048, pre_relu=False)
        g.add_layer("exit_s2_relu", ActivationLayer(
            activation=Activation.RELU), x)
        g.add_layer("gap", GlobalPoolingLayer(
            pooling_type=PoolingType.AVG), "exit_s2_relu")
        g.add_layer("out", OutputLayer(
            n_out=self.num_classes, loss=LossFunction.MCXENT,
            activation=Activation.SOFTMAX), "gap")
        return g.set_outputs("out").build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()
