"""TransformerLM — causal decoder-only language model for autoregressive
generation serving.

The zoo's BERT is bidirectional (MLM) and lives on the ComputationGraph,
which has no transient-state carry — neither can be decoded
incrementally. This model is the KV-cache-native counterpart: a
sequential stack of pre-LN causal :class:`TransformerDecoderBlockLayer`
blocks (residuals internal), so the ``rnn_state`` channel threads one
static-shape KV cache per block through
:class:`~deeplearning4j_tpu.generate.session.GenerationSession`.

GPT-style layout: token embedding + learned positional embedding →
N causal blocks → final LayerNorm → softmax over the vocab (trainable
with SPARSE_MCXENT next-token labels).
"""

from __future__ import annotations

from typing import Optional

from ...nn import Activation, LossFunction, NeuralNetConfiguration, WeightInit
from ...nn.layers import (
    EmbeddingSequenceLayer,
    LayerNormLayer,
    PositionalEmbeddingLayer,
    RnnOutputLayer,
    TransformerDecoderBlockLayer,
)
from ...nn.sequential import MultiLayerNetwork
from ...train.updaters import Adam


class TransformerLM:
    def __init__(
        self,
        vocab_size: int = 1000,
        hidden: int = 256,
        n_layers: int = 4,
        n_heads: int = 4,
        ffn_size: int = 0,
        max_len: int = 256,
        seed: int = 123,
        updater=None,
        dtype: str = "float32",
    ) -> None:
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.n_layers = n_layers
        self.n_heads = n_heads
        self.ffn_size = ffn_size or 4 * hidden
        self.max_len = max_len
        self.seed = seed
        self.updater = updater or Adam(1e-4)
        self.dtype = dtype

    def conf(self):
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed).data_type(self.dtype).updater(self.updater)
             .weight_init(WeightInit.XAVIER).list())
        b.layer(EmbeddingSequenceLayer(n_in=self.vocab_size,
                                       n_out=self.hidden))
        b.layer(PositionalEmbeddingLayer(n_out=self.hidden,
                                         max_len=self.max_len))
        for _ in range(self.n_layers):
            b.layer(TransformerDecoderBlockLayer(
                n_in=self.hidden, n_heads=self.n_heads,
                ffn_size=self.ffn_size))
        b.layer(LayerNormLayer(n_out=self.hidden))
        b.layer(RnnOutputLayer(n_in=self.hidden, n_out=self.vocab_size,
                               loss=LossFunction.SPARSE_MCXENT,
                               activation=Activation.SOFTMAX))
        return b.build()

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()

    @classmethod
    def draft_of(cls, target: "TransformerLM", *, hidden: int = 64,
                 n_layers: int = 1, n_heads: int = 2,
                 seed: Optional[int] = None) -> "TransformerLM":
        """A small draft config paired to ``target`` for speculative
        decoding: same vocab, ``max_len`` and dtype (the acceptance ratio
        needs one shared token space and the paired caches advance in
        lockstep), with a much cheaper stack — the default (1 layer,
        hidden 64) is the zoo's serving draft. Train/distill it on the
        target's data; exact acceptance sampling keeps the output
        distribution regardless of draft quality, the draft only moves
        the acceptance rate."""
        return cls(vocab_size=target.vocab_size, hidden=hidden,
                   n_layers=n_layers, n_heads=n_heads,
                   max_len=target.max_len,
                   seed=target.seed + 1 if seed is None else seed,
                   dtype=target.dtype)
