"""TextGenerationLSTM — the GravesLSTM char-RNN benchmark model.

Reference: org.deeplearning4j.zoo.model.TextGenerationLSTM
(BASELINE.json:9, "GravesLSTM char-RNN"): stacked GravesLSTM (peephole)
layers over one-hot character input with an RnnOutputLayer, trained via
truncated BPTT. :meth:`generate` adds the sampling path the reference
example script hand-rolled: seeded greedy/temperature/top-k/top-p
decoding over the carried (h, c) state — the prompt is consumed once and
each further character costs one single-step forward.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ...nn import Activation, InputType, LossFunction, NeuralNetConfiguration, WeightInit
from ...nn.conf import BackpropType
from ...nn.sequential import MultiLayerNetwork
from ...nn.layers import GravesLSTMLayer, RnnOutputLayer
from ...train.updaters import RmsProp


class TextGenerationLSTM:
    def __init__(self, vocab_size: int = 77, hidden: int = 200,
                 layers: int = 2, tbptt_length: int = 50, seed: int = 123,
                 updater=None, dtype: str = "float32") -> None:
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.layers = layers
        self.tbptt_length = tbptt_length
        self.seed = seed
        self.updater = updater or RmsProp(1e-3)
        self.dtype = dtype

    def conf(self):
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed).data_type(self.dtype).updater(self.updater)
             .weight_init(WeightInit.XAVIER).list())
        for _ in range(self.layers):
            b.layer(GravesLSTMLayer(n_out=self.hidden,
                                    activation=Activation.TANH))
        b.layer(RnnOutputLayer(n_out=self.vocab_size,
                               loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
        return (b.set_input_type(InputType.recurrent(self.vocab_size))
                .backprop_type(BackpropType.TRUNCATED_BPTT)
                .tbptt_fwd_length(self.tbptt_length)
                .tbptt_back_length(self.tbptt_length)
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()

    @staticmethod
    def generate(
        model: MultiLayerNetwork,
        prompts: Sequence[Sequence[int]],
        max_tokens: int,
        *,
        max_len: int = 256,
        greedy: bool = True,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: int = 0,
        eos_id: Optional[int] = None,
    ) -> List[List[int]]:
        """Sample continuations for character-id prompts from a trained
        char-RNN (ids one-hot encoded internally; the recurrent (h, c)
        carry threads through the decode so the prefix never re-runs)."""
        from ...generate import GenerationSession

        session = GenerationSession(model, max_len=max_len)
        return session.generate(
            prompts, max_tokens, greedy=greedy, temperature=temperature,
            top_k=top_k, top_p=top_p, seed=seed, eos_id=eos_id)
