"""TextGenerationLSTM — the GravesLSTM char-RNN benchmark model.

Reference: org.deeplearning4j.zoo.model.TextGenerationLSTM
(BASELINE.json:9, "GravesLSTM char-RNN"): stacked GravesLSTM (peephole)
layers over one-hot character input with an RnnOutputLayer, trained via
truncated BPTT.
"""

from __future__ import annotations

from ...nn import Activation, InputType, LossFunction, NeuralNetConfiguration, WeightInit
from ...nn.conf import BackpropType
from ...nn.sequential import MultiLayerNetwork
from ...nn.layers import GravesLSTMLayer, RnnOutputLayer
from ...train.updaters import RmsProp


class TextGenerationLSTM:
    def __init__(self, vocab_size: int = 77, hidden: int = 200,
                 layers: int = 2, tbptt_length: int = 50, seed: int = 123,
                 updater=None, dtype: str = "float32") -> None:
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.layers = layers
        self.tbptt_length = tbptt_length
        self.seed = seed
        self.updater = updater or RmsProp(1e-3)
        self.dtype = dtype

    def conf(self):
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed).data_type(self.dtype).updater(self.updater)
             .weight_init(WeightInit.XAVIER).list())
        for _ in range(self.layers):
            b.layer(GravesLSTMLayer(n_out=self.hidden,
                                    activation=Activation.TANH))
        b.layer(RnnOutputLayer(n_out=self.vocab_size,
                               loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
        return (b.set_input_type(InputType.recurrent(self.vocab_size))
                .backprop_type(BackpropType.TRUNCATED_BPTT)
                .tbptt_fwd_length(self.tbptt_length)
                .tbptt_back_length(self.tbptt_length)
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()
