"""SqueezeNet v1.1.

Reference: org.deeplearning4j.zoo.model.SqueezeNet. Fire modules: a 1x1
"squeeze" conv followed by parallel 1x1 and 3x3 "expand" convs whose
outputs concatenate on channels (MergeVertex).
"""

from __future__ import annotations

from ...nn import Activation, InputType, LossFunction, NeuralNetConfiguration, WeightInit
from ...nn.graph import ComputationGraph
from ...nn.layers import (
    ActivationLayer,
    ConvolutionLayer,
    ConvolutionMode,
    GlobalPoolingLayer,
    LossLayer,
    PoolingType,
    SubsamplingLayer,
)
from ...nn.vertices import MergeVertex
from ...train.updaters import Adam


class SqueezeNet:
    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3,
                 updater=None, dtype: str = "float32") -> None:
        self.num_classes = num_classes
        self.seed = seed
        self.height, self.width, self.channels = height, width, channels
        self.updater = updater or Adam(1e-3)
        self.dtype = dtype

    def _fire(self, g, name, inp, squeeze, expand):
        g.add_layer(f"{name}_sq", ConvolutionLayer(
            n_out=squeeze, kernel_size=(1, 1),
            convolution_mode=ConvolutionMode.SAME), inp)
        g.add_layer(f"{name}_e1", ConvolutionLayer(
            n_out=expand, kernel_size=(1, 1),
            convolution_mode=ConvolutionMode.SAME), f"{name}_sq")
        g.add_layer(f"{name}_e3", ConvolutionLayer(
            n_out=expand, kernel_size=(3, 3),
            convolution_mode=ConvolutionMode.SAME), f"{name}_sq")
        g.add_vertex(f"{name}_cat", MergeVertex(), f"{name}_e1", f"{name}_e3")
        return f"{name}_cat"

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).data_type(self.dtype).updater(self.updater)
             .weight_init(WeightInit.RELU).activation(Activation.RELU)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        g.add_layer("conv1", ConvolutionLayer(
            n_out=64, kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.TRUNCATE), "input")
        g.add_layer("pool1", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2)), "conv1")
        x = self._fire(g, "fire2", "pool1", 16, 64)
        x = self._fire(g, "fire3", x, 16, 64)
        g.add_layer("pool3", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2)), x)
        x = self._fire(g, "fire4", "pool3", 32, 128)
        x = self._fire(g, "fire5", x, 32, 128)
        g.add_layer("pool5", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2)), x)
        x = self._fire(g, "fire6", "pool5", 48, 192)
        x = self._fire(g, "fire7", x, 48, 192)
        x = self._fire(g, "fire8", x, 64, 256)
        x = self._fire(g, "fire9", x, 64, 256)
        g.add_layer("conv10", ConvolutionLayer(
            n_out=self.num_classes, kernel_size=(1, 1),
            convolution_mode=ConvolutionMode.SAME), x)
        g.add_layer("gap", GlobalPoolingLayer(
            pooling_type=PoolingType.AVG), "conv10")
        g.add_layer("softmax", ActivationLayer(
            activation=Activation.SOFTMAX), "gap")
        g.add_layer("loss", LossLayer(loss=LossFunction.MCXENT), "softmax")
        return g.set_outputs("loss").build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()
