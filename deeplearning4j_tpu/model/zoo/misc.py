"""SimpleCNN, YOLO2, and FaceNetNN4Small2.

Reference: org.deeplearning4j.zoo.model.{SimpleCNN, YOLO2, FaceNetNN4Small2}
— the remaining zoo architectures. YOLO2 is the full Darknet-19 trunk with
the reorg ("passthrough") concat: the conv13 feature map space-to-depths to
the head resolution and merges with conv20 before the detection conv.
FaceNetNN4Small2 is the NN4.small2 inception variant ending in a
128-d L2-normalized embedding (the SameDiffLambdaLayer escape hatch carries
the normalize op — the reference uses a custom L2NormalizeVertex).
"""

from __future__ import annotations

import jax.numpy as jnp

from ...nn import Activation, InputType, LossFunction, NeuralNetConfiguration, WeightInit
from ...nn.graph import ComputationGraph
from ...nn.layers import (
    ActivationLayer,
    BatchNormalizationLayer,
    ConvolutionLayer,
    ConvolutionMode,
    DenseLayer,
    DropoutLayer,
    GlobalPoolingLayer,
    LossLayer,
    OutputLayer,
    PoolingType,
    SameDiffLambdaLayer,
    SubsamplingLayer,
)
from ...nn.sequential import MultiLayerNetwork
from ...nn.vertices import MergeVertex
from ...train.updaters import Adam, Nesterovs


class SimpleCNN:
    """Reference: zoo.model.SimpleCNN — a small conv stack for quick
    experiments (conv-BN-relu blocks, dropout, dense head)."""

    def __init__(self, num_classes: int = 10, seed: int = 123,
                 height: int = 48, width: int = 48, channels: int = 3,
                 updater=None, dtype: str = "float32") -> None:
        self.num_classes = num_classes
        self.seed = seed
        self.height, self.width, self.channels = height, width, channels
        self.updater = updater or Adam(1e-3)
        self.dtype = dtype

    def conf(self):
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed).data_type(self.dtype).updater(self.updater)
             .weight_init(WeightInit.RELU).list())
        for f in (16, 32, 64):
            b.layer(ConvolutionLayer(
                n_out=f, kernel_size=(3, 3),
                convolution_mode=ConvolutionMode.SAME,
                activation=Activation.RELU))
            b.layer(BatchNormalizationLayer())
            b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        b.layer(GlobalPoolingLayer(pooling_type=PoolingType.AVG))
        b.layer(DropoutLayer(dropout=0.5))
        b.layer(OutputLayer(n_out=self.num_classes,
                            loss=LossFunction.MCXENT,
                            activation=Activation.SOFTMAX))
        return b.set_input_type(InputType.convolutional(
            self.height, self.width, self.channels)).build()

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


class YOLO2:
    """Reference: zoo.model.YOLO2 — Darknet-19 trunk + the passthrough
    reorg concat + detection conv emitting [b, B*(5+C), gh, gw]."""

    def __init__(self, num_classes: int = 20, n_boxes: int = 5,
                 seed: int = 123, height: int = 416, width: int = 416,
                 channels: int = 3, updater=None,
                 dtype: str = "float32") -> None:
        self.num_classes = num_classes
        self.n_boxes = n_boxes
        self.seed = seed
        self.height, self.width, self.channels = height, width, channels
        self.updater = updater or Nesterovs(1e-3, 0.9)
        self.dtype = dtype

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).data_type(self.dtype).updater(self.updater)
             .weight_init(WeightInit.RELU)
             .graph_builder().add_inputs("input"))
        prev = "input"
        idx = [0]

        def conv(n_out, kernel=(3, 3), src=None):
            nonlocal prev
            name = f"c{idx[0]}"
            idx[0] += 1
            g.add_layer(name, ConvolutionLayer(
                n_out=n_out, kernel_size=kernel,
                convolution_mode=ConvolutionMode.SAME, has_bias=False,
                activation=Activation.IDENTITY), src or prev)
            g.add_layer(f"{name}_bn", BatchNormalizationLayer(), name)
            g.add_layer(f"{name}_act", ActivationLayer(
                activation=Activation.LEAKYRELU), f"{name}_bn")
            prev = f"{name}_act"
            return prev

        def pool():
            nonlocal prev
            name = f"p{idx[0]}"
            idx[0] += 1
            g.add_layer(name, SubsamplingLayer(kernel_size=(2, 2),
                                               stride=(2, 2)), prev)
            prev = name
            return prev

        # darknet-19 trunk
        conv(32); pool()
        conv(64); pool()
        conv(128); conv(64, (1, 1)); conv(128); pool()
        conv(256); conv(128, (1, 1)); conv(256); pool()
        conv(512); conv(256, (1, 1)); conv(512); conv(256, (1, 1))
        route = conv(512)  # conv13: the passthrough source (26x26x512)
        pool()
        conv(1024); conv(512, (1, 1)); conv(1024); conv(512, (1, 1))
        conv(1024)
        # head
        conv(1024); conv(1024)
        head = prev
        # passthrough: conv 64 1x1 on the route, then reorg 2x (NCHW)
        conv(64, (1, 1), src=route)
        from ...nn.input_type import ConvolutionalType

        g.add_layer("reorg", SameDiffLambdaLayer(
            fn=lambda x: _space_to_depth_nchw(x, 2),
            output_type_fn=lambda t: ConvolutionalType(
                height=t.height // 2, width=t.width // 2,
                channels=t.channels * 4)), prev)
        g.add_vertex("concat", MergeVertex(), "reorg", head)
        conv(1024, src="concat")
        out_ch = self.n_boxes * (5 + self.num_classes)
        g.add_layer("detect", ConvolutionLayer(
            n_out=out_ch, kernel_size=(1, 1),
            convolution_mode=ConvolutionMode.SAME,
            activation=Activation.IDENTITY), prev)
        # training surface: the reference attaches Yolo2OutputLayer with
        # anchor-box loss; here the grid tensor is the output and a loss
        # layer slot accepts a task-specific loss downstream
        g.add_layer("grid", LossLayer(loss=LossFunction.MSE), "detect")
        g.set_outputs("grid")
        g.set_input_types(InputType.convolutional(
            self.height, self.width, self.channels))
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


def _space_to_depth_nchw(x, block):
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // block, block, w // block, block)
    return x.transpose(0, 3, 5, 1, 2, 4).reshape(
        n, c * block * block, h // block, w // block)


class FaceNetNN4Small2:
    """Reference: zoo.model.FaceNetNN4Small2 — the NN4.small2 inception
    face-embedding net: stem convs, inception merge blocks, and a 128-d
    L2-normalized embedding head (train with triplet/center loss upstream)."""

    def __init__(self, embedding_size: int = 128, seed: int = 123,
                 height: int = 96, width: int = 96, channels: int = 3,
                 updater=None, dtype: str = "float32") -> None:
        self.embedding_size = embedding_size
        self.seed = seed
        self.height, self.width, self.channels = height, width, channels
        self.updater = updater or Adam(1e-3)
        self.dtype = dtype

    def _inception(self, g, name, src, b1, b3r, b3, b5r, b5, bp):
        """Four-branch inception merge: 1x1 / 3x3 / 5x5 / pool-proj."""
        branches = []
        if b1:
            g.add_layer(f"{name}_1x1", ConvolutionLayer(
                n_out=b1, kernel_size=(1, 1), activation=Activation.RELU,
                convolution_mode=ConvolutionMode.SAME), src)
            branches.append(f"{name}_1x1")
        g.add_layer(f"{name}_3x3r", ConvolutionLayer(
            n_out=b3r, kernel_size=(1, 1), activation=Activation.RELU,
            convolution_mode=ConvolutionMode.SAME), src)
        g.add_layer(f"{name}_3x3", ConvolutionLayer(
            n_out=b3, kernel_size=(3, 3), activation=Activation.RELU,
            convolution_mode=ConvolutionMode.SAME), f"{name}_3x3r")
        branches.append(f"{name}_3x3")
        if b5:
            g.add_layer(f"{name}_5x5r", ConvolutionLayer(
                n_out=b5r, kernel_size=(1, 1), activation=Activation.RELU,
                convolution_mode=ConvolutionMode.SAME), src)
            g.add_layer(f"{name}_5x5", ConvolutionLayer(
                n_out=b5, kernel_size=(5, 5), activation=Activation.RELU,
                convolution_mode=ConvolutionMode.SAME), f"{name}_5x5r")
            branches.append(f"{name}_5x5")
        g.add_layer(f"{name}_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(1, 1), padding=(1, 1),
            pooling_type=PoolingType.MAX), src)
        g.add_layer(f"{name}_poolp", ConvolutionLayer(
            n_out=bp, kernel_size=(1, 1), activation=Activation.RELU,
            convolution_mode=ConvolutionMode.SAME), f"{name}_pool")
        branches.append(f"{name}_poolp")
        g.add_vertex(name, MergeVertex(), *branches)
        return name

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).data_type(self.dtype).updater(self.updater)
             .weight_init(WeightInit.RELU)
             .graph_builder().add_inputs("input"))
        # stem
        g.add_layer("stem1", ConvolutionLayer(
            n_out=64, kernel_size=(7, 7), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME,
            activation=Activation.RELU), "input")
        g.add_layer("stem1_bn", BatchNormalizationLayer(), "stem1")
        g.add_layer("pool1", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), "stem1_bn")
        g.add_layer("stem2", ConvolutionLayer(
            n_out=64, kernel_size=(1, 1), activation=Activation.RELU,
            convolution_mode=ConvolutionMode.SAME), "pool1")
        g.add_layer("stem3", ConvolutionLayer(
            n_out=192, kernel_size=(3, 3), activation=Activation.RELU,
            convolution_mode=ConvolutionMode.SAME), "stem2")
        g.add_layer("stem3_bn", BatchNormalizationLayer(), "stem3")
        g.add_layer("pool2", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), "stem3_bn")
        # inception blocks (NN4.small2 widths)
        i3a = self._inception(g, "i3a", "pool2", 64, 96, 128, 16, 32, 32)
        i3b = self._inception(g, "i3b", i3a, 64, 96, 128, 32, 64, 64)
        g.add_layer("pool3", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), i3b)
        i4a = self._inception(g, "i4a", "pool3", 256, 96, 192, 32, 64, 128)
        i4e = self._inception(g, "i4e", i4a, 0, 160, 256, 64, 128, 128)
        g.add_layer("pool4", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), i4e)
        i5a = self._inception(g, "i5a", "pool4", 256, 96, 384, 0, 0, 96)
        i5b = self._inception(g, "i5b", i5a, 256, 96, 384, 0, 0, 96)
        # embedding head
        g.add_layer("gap", GlobalPoolingLayer(
            pooling_type=PoolingType.AVG), i5b)
        g.add_layer("bottleneck", DenseLayer(
            n_out=self.embedding_size, activation=Activation.IDENTITY), "gap")
        g.add_layer("embeddings", SameDiffLambdaLayer(
            fn=lambda x: x / jnp.sqrt(jnp.maximum(
                jnp.sum(jnp.square(x), axis=-1, keepdims=True), 1e-12)),
            output_size=self.embedding_size), "bottleneck")
        # trainable surface: embeddings feed a loss slot (triplet pipelines
        # drive loss_pure directly; MSE slot keeps fit() usable for tests)
        g.add_layer("loss", LossLayer(loss=LossFunction.MSE), "embeddings")
        g.set_outputs("loss")
        g.set_input_types(InputType.convolutional(
            self.height, self.width, self.channels))
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()
