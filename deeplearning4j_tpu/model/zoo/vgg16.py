"""VGG-16 and AlexNet.

Reference: org.deeplearning4j.zoo.model.{VGG16, AlexNet}. Sequential stacks,
reference layer dimensions.
"""

from __future__ import annotations

from ...nn import Activation, InputType, LossFunction, MultiLayerNetwork, NeuralNetConfiguration, WeightInit
from ...nn.layers import (
    ConvolutionLayer,
    ConvolutionMode,
    DenseLayer,
    LocalResponseNormalizationLayer,
    OutputLayer,
    SubsamplingLayer,
)
from ...train.updaters import Nesterovs


class VGG16:
    def __init__(self, num_classes: int = 1000, seed: int = 123, height: int = 224,
                 width: int = 224, channels: int = 3, updater=None, dtype: str = "float32") -> None:
        self.num_classes = num_classes
        self.seed = seed
        self.height, self.width, self.channels = height, width, channels
        self.updater = updater or Nesterovs(1e-2, 0.9)
        self.dtype = dtype

    #: (filters, conv repetitions) per stage — VGG19 overrides this
    _PLAN = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]

    def conf(self):
        b = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .data_type(self.dtype)
            .updater(self.updater)
            .weight_init(WeightInit.RELU)
            .activation(Activation.RELU)
            .list()
        )
        for n_out, reps in self._PLAN:
            for _ in range(reps):
                b = b.layer(ConvolutionLayer(
                    n_out=n_out, kernel_size=(3, 3), stride=(1, 1),
                    convolution_mode=ConvolutionMode.SAME,
                ))
            b = b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        b = (
            b.layer(DenseLayer(n_out=4096))
            .layer(DenseLayer(n_out=4096))
            .layer(OutputLayer(n_out=self.num_classes, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
        )
        return b.set_input_type(
            InputType.convolutional(self.height, self.width, self.channels)
        ).build()

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


class AlexNet:
    def __init__(self, num_classes: int = 1000, seed: int = 123, height: int = 224,
                 width: int = 224, channels: int = 3, updater=None, dtype: str = "float32") -> None:
        self.num_classes = num_classes
        self.seed = seed
        self.height, self.width, self.channels = height, width, channels
        self.updater = updater or Nesterovs(1e-2, 0.9)
        self.dtype = dtype

    def conf(self):
        return (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .data_type(self.dtype)
            .updater(self.updater)
            .weight_init(WeightInit.NORMAL)
            .activation(Activation.RELU)
            .list()
            .layer(ConvolutionLayer(n_out=96, kernel_size=(11, 11), stride=(4, 4),
                                    convolution_mode=ConvolutionMode.TRUNCATE))
            .layer(LocalResponseNormalizationLayer())
            .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=256, kernel_size=(5, 5), stride=(1, 1),
                                    convolution_mode=ConvolutionMode.SAME))
            .layer(LocalResponseNormalizationLayer())
            .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                    convolution_mode=ConvolutionMode.SAME))
            .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                    convolution_mode=ConvolutionMode.SAME))
            .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3),
                                    convolution_mode=ConvolutionMode.SAME))
            .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
            .layer(DenseLayer(n_out=4096, dropout=0.5))
            .layer(DenseLayer(n_out=4096, dropout=0.5))
            .layer(OutputLayer(n_out=self.num_classes, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.convolutional(self.height, self.width, self.channels))
            .build()
        )

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


class VGG19(VGG16):
    """Reference: org.deeplearning4j.zoo.model.VGG19 — VGG16 with a fourth
    conv in the last three stages."""

    _PLAN = [(64, 2), (128, 2), (256, 4), (512, 4), (512, 4)]
