"""Inception-ResNet v1 (FaceNet vintage).

Reference: org.deeplearning4j.zoo.model.InceptionResNetV1 — stem, then
residual inception blocks A/B/C with scaled residual adds (ScaleVertex),
reduction blocks between stages.
"""

from __future__ import annotations

from ...nn import Activation, InputType, LossFunction, NeuralNetConfiguration, WeightInit
from ...nn.graph import ComputationGraph
from ...nn.layers import (
    ActivationLayer,
    BatchNormalizationLayer,
    ConvolutionLayer,
    ConvolutionMode,
    GlobalPoolingLayer,
    OutputLayer,
    PoolingType,
    SubsamplingLayer,
)
from ...nn.vertices import ElementWiseOp, ElementWiseVertex, MergeVertex, ScaleVertex
from ...train.updaters import Adam


class InceptionResNetV1:
    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 160, width: int = 160, channels: int = 3,
                 blocks_a: int = 5, blocks_b: int = 10, blocks_c: int = 5,
                 updater=None, dtype: str = "float32") -> None:
        self.num_classes = num_classes
        self.seed = seed
        self.height, self.width, self.channels = height, width, channels
        self.blocks = (blocks_a, blocks_b, blocks_c)
        self.updater = updater or Adam(1e-3)
        self.dtype = dtype

    def _conv(self, g, name, inp, n_out, kernel, stride=(1, 1),
              mode=ConvolutionMode.SAME, relu=True):
        g.add_layer(name, ConvolutionLayer(
            n_out=n_out, kernel_size=kernel, stride=stride, has_bias=False,
            convolution_mode=mode, activation=Activation.IDENTITY), inp)
        g.add_layer(f"{name}_bn", BatchNormalizationLayer(), name)
        if relu:
            g.add_layer(f"{name}_relu",
                        ActivationLayer(activation=Activation.RELU),
                        f"{name}_bn")
            return f"{name}_relu"
        return f"{name}_bn"

    def _residual(self, g, name, inp, branch_ends, n_channels, scale):
        g.add_vertex(f"{name}_cat", MergeVertex(), *branch_ends)
        up = self._conv(g, f"{name}_up", f"{name}_cat", n_channels, (1, 1),
                        relu=False)
        g.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), up)
        g.add_vertex(f"{name}_add", ElementWiseVertex(op=ElementWiseOp.ADD),
                     inp, f"{name}_scale")
        g.add_layer(f"{name}_out", ActivationLayer(
            activation=Activation.RELU), f"{name}_add")
        return f"{name}_out"

    def _block_a(self, g, name, inp):  # 35x35, 256 ch
        b1 = self._conv(g, f"{name}_b1", inp, 32, (1, 1))
        b2 = self._conv(g, f"{name}_b2a", inp, 32, (1, 1))
        b2 = self._conv(g, f"{name}_b2b", b2, 32, (3, 3))
        b3 = self._conv(g, f"{name}_b3a", inp, 32, (1, 1))
        b3 = self._conv(g, f"{name}_b3b", b3, 32, (3, 3))
        b3 = self._conv(g, f"{name}_b3c", b3, 32, (3, 3))
        return self._residual(g, name, inp, [b1, b2, b3], 256, 0.17)

    def _block_b(self, g, name, inp):  # 17x17, 896 ch
        b1 = self._conv(g, f"{name}_b1", inp, 128, (1, 1))
        b2 = self._conv(g, f"{name}_b2a", inp, 128, (1, 1))
        b2 = self._conv(g, f"{name}_b2b", b2, 128, (1, 7))
        b2 = self._conv(g, f"{name}_b2c", b2, 128, (7, 1))
        return self._residual(g, name, inp, [b1, b2], 896, 0.10)

    def _block_c(self, g, name, inp):  # 8x8, 1792 ch
        b1 = self._conv(g, f"{name}_b1", inp, 192, (1, 1))
        b2 = self._conv(g, f"{name}_b2a", inp, 192, (1, 1))
        b2 = self._conv(g, f"{name}_b2b", b2, 192, (1, 3))
        b2 = self._conv(g, f"{name}_b2c", b2, 192, (3, 1))
        return self._residual(g, name, inp, [b1, b2], 1792, 0.20)

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).data_type(self.dtype).updater(self.updater)
             .weight_init(WeightInit.RELU)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        # stem
        x = self._conv(g, "stem1", "input", 32, (3, 3), (2, 2),
                       ConvolutionMode.TRUNCATE)
        x = self._conv(g, "stem2", x, 32, (3, 3), mode=ConvolutionMode.TRUNCATE)
        x = self._conv(g, "stem3", x, 64, (3, 3))
        g.add_layer("stem_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2)), x)
        x = self._conv(g, "stem4", "stem_pool", 80, (1, 1))
        x = self._conv(g, "stem5", x, 192, (3, 3), mode=ConvolutionMode.TRUNCATE)
        x = self._conv(g, "stem6", x, 256, (3, 3), (2, 2))
        na, nb, nc = self.blocks
        for i in range(na):
            x = self._block_a(g, f"a{i}", x)
        # reduction A → 896 channels, /2 spatial
        r1 = self._conv(g, "redA_b1", x, 384, (3, 3), (2, 2),
                        ConvolutionMode.TRUNCATE)
        r2 = self._conv(g, "redA_b2a", x, 192, (1, 1))
        r2 = self._conv(g, "redA_b2b", r2, 192, (3, 3))
        r2 = self._conv(g, "redA_b2c", r2, 256, (3, 3), (2, 2),
                        ConvolutionMode.TRUNCATE)
        g.add_layer("redA_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2)), x)
        g.add_vertex("redA", MergeVertex(), r1, r2, "redA_pool")
        x = "redA"
        for i in range(nb):
            x = self._block_b(g, f"b{i}", x)
        # reduction B → 1792 channels, /2 spatial
        r1 = self._conv(g, "redB_b1a", x, 256, (1, 1))
        r1 = self._conv(g, "redB_b1b", r1, 384, (3, 3), (2, 2),
                        ConvolutionMode.TRUNCATE)
        r2 = self._conv(g, "redB_b2a", x, 256, (1, 1))
        r2 = self._conv(g, "redB_b2b", r2, 256, (3, 3), (2, 2),
                        ConvolutionMode.TRUNCATE)
        r3 = self._conv(g, "redB_b3a", x, 256, (1, 1))
        r3 = self._conv(g, "redB_b3b", r3, 256, (3, 3))
        r3 = self._conv(g, "redB_b3c", r3, 256, (3, 3), (2, 2),
                        ConvolutionMode.TRUNCATE)
        g.add_layer("redB_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2)), x)
        g.add_vertex("redB", MergeVertex(), r1, r2, r3, "redB_pool")
        x = "redB"
        for i in range(nc):
            x = self._block_c(g, f"c{i}", x)
        g.add_layer("gap", GlobalPoolingLayer(
            pooling_type=PoolingType.AVG), x)
        g.add_layer("out", OutputLayer(
            n_out=self.num_classes, loss=LossFunction.MCXENT,
            activation=Activation.SOFTMAX), "gap")
        return g.set_outputs("out").build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()
