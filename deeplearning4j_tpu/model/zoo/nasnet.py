"""NASNet-A (mobile config).

Reference: org.deeplearning4j.zoo.model.NASNet — NASNet-A with
numBlocks normal cells per stack and penultimateFilters=1056 (mobile:
filters = 1056 / 24 = 44). Cell wiring follows Zoph et al. 2018's
NASNet-A search result (the same wiring the reference and
keras.applications share): each cell combines the current hidden state
``h`` and the previous cell's input ``p`` through five add-blocks of
separable convs / 3x3 pools / identities, concatenated on channels;
reduction cells run their branches at stride 2.

TPU notes: separable convs lower to grouped `conv_general_dilated`
(feature_group_count) + 1x1 — both MXU-tileable; the concat/add DAG is
pure XLA fusion food. All shapes static; NCHW here, XLA relayouts for
the TPU conv backend.
"""

from __future__ import annotations

from ...nn import Activation, InputType, LossFunction, NeuralNetConfiguration, WeightInit
from ...nn.graph import ComputationGraph
from ...nn.layers import (
    ActivationLayer,
    ConvolutionLayer,
    ConvolutionMode,
    GlobalPoolingLayer,
    OutputLayer,
    PoolingType,
    SeparableConvolution2DLayer,
    SubsamplingLayer,
)
from ...nn.layers.norm import BatchNormalizationLayer
from ...nn.vertices import ElementWiseVertex, MergeVertex
from ...train.updaters import Adam


class NASNet:
    """NASNet-A mobile. ``num_blocks`` normal cells per stack (reference
    default 4), ``penultimate_filters`` sets the width (1056 -> 44)."""

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3,
                 num_blocks: int = 4, penultimate_filters: int = 1056,
                 stem_filters: int = 32, updater=None,
                 dtype: str = "float32") -> None:
        if penultimate_filters % 24 != 0:
            raise ValueError("penultimate_filters must be divisible by 24 "
                             "(2 reductions x concat of 6 branches)")
        if num_blocks < 1:
            raise ValueError("num_blocks must be >= 1 (the p/h spatial "
                             "alignment happens inside the normal-cell loop)")
        self.num_classes = num_classes
        self.seed = seed
        self.height, self.width, self.channels = height, width, channels
        self.num_blocks = num_blocks
        self.filters = penultimate_filters // 24
        self.stem_filters = stem_filters
        self.updater = updater or Adam(1e-3)
        self.dtype = dtype

    # ---- wiring helpers ----------------------------------------------------
    def _sep(self, g, name, inp, filters, kernel, stride=(1, 1)):
        """relu -> sepconv(k, stride) -> BN -> sepconv(k, 1) -> BN (the
        NASNet twice-applied separable block)."""
        g.add_layer(f"{name}_relu", ActivationLayer(
            activation=Activation.RELU), inp)
        g.add_layer(f"{name}_s1", SeparableConvolution2DLayer(
            n_out=filters, kernel_size=kernel, stride=stride,
            convolution_mode=ConvolutionMode.SAME, has_bias=False),
            f"{name}_relu")
        g.add_layer(f"{name}_bn1", BatchNormalizationLayer(), f"{name}_s1")
        g.add_layer(f"{name}_r2", ActivationLayer(
            activation=Activation.RELU), f"{name}_bn1")
        g.add_layer(f"{name}_s2", SeparableConvolution2DLayer(
            n_out=filters, kernel_size=kernel,
            convolution_mode=ConvolutionMode.SAME, has_bias=False),
            f"{name}_r2")
        g.add_layer(f"{name}_bn2", BatchNormalizationLayer(), f"{name}_s2")
        return f"{name}_bn2"

    def _squeeze(self, g, name, inp, filters, stride=(1, 1)):
        """relu -> 1x1 conv (optionally strided: factorized-reduction
        stand-in for spatial adjust) -> BN."""
        g.add_layer(f"{name}_relu", ActivationLayer(
            activation=Activation.RELU), inp)
        g.add_layer(f"{name}_1x1", ConvolutionLayer(
            n_out=filters, kernel_size=(1, 1), stride=stride,
            convolution_mode=ConvolutionMode.SAME, has_bias=False),
            f"{name}_relu")
        g.add_layer(f"{name}_bn", BatchNormalizationLayer(), f"{name}_1x1")
        return f"{name}_bn"

    def _pool(self, g, name, inp, ptype, stride=(1, 1)):
        g.add_layer(name, SubsamplingLayer(
            pooling_type=ptype, kernel_size=(3, 3), stride=stride,
            convolution_mode=ConvolutionMode.SAME), inp)
        return name

    def _add(self, g, name, a, b):
        g.add_vertex(name, ElementWiseVertex(), a, b)
        return name

    def _normal_cell(self, g, name, h_in, p_in, filters):
        """NASNet-A normal cell: out = concat(p, b1..b5), 6*filters chans."""
        h = self._squeeze(g, f"{name}_hsq", h_in, filters)
        p = self._squeeze(g, f"{name}_psq", p_in, filters)
        b1 = self._add(g, f"{name}_b1",
                       self._sep(g, f"{name}_b1l", h, filters, (3, 3)),
                       self._sep(g, f"{name}_b1r", p, filters, (5, 5)))
        b2 = self._add(g, f"{name}_b2",
                       self._sep(g, f"{name}_b2l", p, filters, (5, 5)),
                       self._sep(g, f"{name}_b2r", p, filters, (3, 3)))
        b3 = self._add(g, f"{name}_b3",
                       self._pool(g, f"{name}_b3l", h, PoolingType.AVG), p)
        b4 = self._add(g, f"{name}_b4",
                       self._pool(g, f"{name}_b4l", p, PoolingType.AVG),
                       self._pool(g, f"{name}_b4r", p, PoolingType.AVG))
        b5 = self._add(g, f"{name}_b5",
                       self._sep(g, f"{name}_b5l", h, filters, (3, 3)), h)
        g.add_vertex(f"{name}_out", MergeVertex(), p, b1, b2, b3, b4, b5)
        return f"{name}_out"

    def _reduction_cell(self, g, name, h_in, p_in, filters):
        """NASNet-A reduction cell: spatial /2, out = concat of 4 combines."""
        h = self._squeeze(g, f"{name}_hsq", h_in, filters)
        p = self._squeeze(g, f"{name}_psq", p_in, filters)
        s2 = (2, 2)
        b1 = self._add(g, f"{name}_b1",
                       self._sep(g, f"{name}_b1l", h, filters, (5, 5), s2),
                       self._sep(g, f"{name}_b1r", p, filters, (7, 7), s2))
        b2 = self._add(g, f"{name}_b2",
                       self._pool(g, f"{name}_b2l", h, PoolingType.MAX, s2),
                       self._sep(g, f"{name}_b2r", p, filters, (7, 7), s2))
        b3 = self._add(g, f"{name}_b3",
                       self._pool(g, f"{name}_b3l", h, PoolingType.AVG, s2),
                       self._sep(g, f"{name}_b3r", p, filters, (5, 5), s2))
        # combines over the stride-2 intermediates (full stride-1 wiring)
        b4 = self._add(g, f"{name}_b4",
                       self._pool(g, f"{name}_b4l", b1, PoolingType.AVG), b2)
        b5 = self._add(g, f"{name}_b5",
                       self._sep(g, f"{name}_b5l", b1, filters, (3, 3)),
                       self._pool(g, f"{name}_b5r", h, PoolingType.MAX, s2))
        g.add_vertex(f"{name}_out", MergeVertex(), b2, b3, b4, b5)
        return f"{name}_out"

    # ---- model -------------------------------------------------------------
    def conf(self):
        f = self.filters
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).data_type(self.dtype).updater(self.updater)
             .weight_init(WeightInit.RELU).activation(Activation.IDENTITY)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        g.add_layer("stem_conv", ConvolutionLayer(
            n_out=self.stem_filters, kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME, has_bias=False), "input")
        g.add_layer("stem_bn", BatchNormalizationLayer(), "stem_conv")

        # stem reductions bring 112 -> 56 -> 28 before the first stack
        p = "stem_bn"
        h = self._reduction_cell(g, "stem_r1", "stem_bn", "stem_bn", f // 4)
        p_spatial_mismatch = True  # p is one reduction behind h
        h2 = self._reduction_cell(g, "stem_r2", h,
                                  self._squeeze(g, "stem_adj1", p, f // 4,
                                                stride=(2, 2)), f // 2)
        p, h = h, h2

        for stack, mult in ((1, 1), (2, 2), (3, 4)):
            for i in range(self.num_blocks):
                # align p spatially with h when a reduction just happened
                if p_spatial_mismatch:
                    p = self._squeeze(g, f"s{stack}_adj{i}", p, f * mult,
                                      stride=(2, 2))
                    p_spatial_mismatch = False
                out = self._normal_cell(g, f"s{stack}_c{i}", h, p, f * mult)
                p, h = h, out
            if stack < 3:
                out = self._reduction_cell(g, f"s{stack}_red", h, p, f * 2 * mult)
                p, h = h, out
                p_spatial_mismatch = True

        g.add_layer("final_relu", ActivationLayer(
            activation=Activation.RELU), h)
        g.add_layer("gap", GlobalPoolingLayer(
            pooling_type=PoolingType.AVG), "final_relu")
        g.add_layer("out", OutputLayer(
            n_out=self.num_classes, activation=Activation.SOFTMAX,
            loss=LossFunction.MCXENT), "gap")
        g.set_outputs("out")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()
