"""LeNet.

Reference: org.deeplearning4j.zoo.model.LeNet — the MNIST benchmark model
(BASELINE.json:7). Same architecture: conv5x5x20 -> maxpool -> conv5x5x50 ->
maxpool -> dense500 relu -> softmax output, identity-activation convs,
SAME-mode convolutions.
"""

from __future__ import annotations

from ...nn import Activation, InputType, LossFunction, MultiLayerNetwork, NeuralNetConfiguration, WeightInit
from ...nn.layers import (
    ConvolutionLayer,
    ConvolutionMode,
    DenseLayer,
    OutputLayer,
    SubsamplingLayer,
)
from ...train.updaters import Adam


class LeNet:
    def __init__(
        self,
        num_classes: int = 10,
        seed: int = 123,
        height: int = 28,
        width: int = 28,
        channels: int = 1,
        updater=None,
        dtype: str = "float32",
    ) -> None:
        self.num_classes = num_classes
        self.seed = seed
        self.height = height
        self.width = width
        self.channels = channels
        self.updater = updater or Adam(1e-3)
        self.dtype = dtype

    def conf(self):
        return (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .data_type(self.dtype)
            .updater(self.updater)
            .weight_init(WeightInit.XAVIER)
            .activation(Activation.RELU)
            .list()
            .layer(ConvolutionLayer(
                n_out=20, kernel_size=(5, 5), stride=(1, 1),
                convolution_mode=ConvolutionMode.SAME, activation=Activation.IDENTITY,
            ))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(
                n_out=50, kernel_size=(5, 5), stride=(1, 1),
                convolution_mode=ConvolutionMode.SAME, activation=Activation.IDENTITY,
            ))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=500))
            .layer(OutputLayer(
                n_out=self.num_classes, loss=LossFunction.MCXENT,
                activation=Activation.SOFTMAX,
            ))
            .set_input_type(InputType.convolutional_flat(self.height, self.width, self.channels))
            .build()
        )

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()
