"""U-Net.

Reference: org.deeplearning4j.zoo.model.UNet — encoder/decoder with skip
concatenations (MergeVertex) and a per-pixel sigmoid head (CnnLossLayer).
"""

from __future__ import annotations

from ...nn import Activation, InputType, LossFunction, NeuralNetConfiguration, WeightInit
from ...nn.graph import ComputationGraph
from ...nn.layers import (
    CnnLossLayer,
    ConvolutionLayer,
    ConvolutionMode,
    SubsamplingLayer,
    Upsampling2DLayer,
)
from ...nn.vertices import MergeVertex
from ...train.updaters import Adam


class UNet:
    def __init__(self, num_classes: int = 1, seed: int = 123,
                 height: int = 128, width: int = 128, channels: int = 3,
                 base_filters: int = 32, depth: int = 3, updater=None,
                 dtype: str = "float32") -> None:
        self.num_classes = num_classes
        self.seed = seed
        self.height, self.width, self.channels = height, width, channels
        self.base_filters = base_filters
        self.depth = depth
        self.updater = updater or Adam(1e-3)
        self.dtype = dtype

    def _double_conv(self, g, name, inp, filters):
        g.add_layer(f"{name}_c1", ConvolutionLayer(
            n_out=filters, kernel_size=(3, 3),
            convolution_mode=ConvolutionMode.SAME), inp)
        g.add_layer(f"{name}_c2", ConvolutionLayer(
            n_out=filters, kernel_size=(3, 3),
            convolution_mode=ConvolutionMode.SAME), f"{name}_c1")
        return f"{name}_c2"

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).data_type(self.dtype).updater(self.updater)
             .weight_init(WeightInit.RELU).activation(Activation.RELU)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        skips = []
        x = "input"
        f = self.base_filters
        for d in range(self.depth):
            x = self._double_conv(g, f"down{d}", x, f * (2 ** d))
            skips.append(x)
            g.add_layer(f"pool{d}", SubsamplingLayer(
                kernel_size=(2, 2), stride=(2, 2)), x)
            x = f"pool{d}"
        x = self._double_conv(g, "bottom", x, f * (2 ** self.depth))
        for d in reversed(range(self.depth)):
            g.add_layer(f"up{d}", Upsampling2DLayer(size=(2, 2)), x)
            g.add_layer(f"upc{d}", ConvolutionLayer(
                n_out=f * (2 ** d), kernel_size=(2, 2),
                convolution_mode=ConvolutionMode.SAME), f"up{d}")
            g.add_vertex(f"cat{d}", MergeVertex(), f"upc{d}", skips[d])
            x = self._double_conv(g, f"dec{d}", f"cat{d}", f * (2 ** d))
        g.add_layer("head", ConvolutionLayer(
            n_out=self.num_classes, kernel_size=(1, 1),
            convolution_mode=ConvolutionMode.SAME,
            activation=Activation.SIGMOID), x)
        g.add_layer("loss", CnnLossLayer(loss=LossFunction.XENT), "head")
        return g.set_outputs("loss").build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()
