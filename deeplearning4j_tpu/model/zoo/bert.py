"""BERT-style transformer encoder as a native ComputationGraph.

Reference: the reference reaches BERT through SameDiff TF import
(SURVEY.md §2.2 "TF import" — the BASELINE.json:10 tokens/sec path); it has
no native-layer BERT. This zoo model is the TPU-native equivalent used for
the headline BERT throughput benchmark: pre-LN transformer blocks built from
the framework's own layers (SelfAttentionLayer, time-distributed DenseLayer
FFN, LayerNorm, ElementWiseVertex residuals), MLM-style sparse softmax loss
over the vocab. bert-base defaults (L=12, H=768, A=12, FFN=3072,
vocab=30522).

Sequence format is the framework's recurrent convention [batch, features,
time]; token ids enter as [batch, time] int32.
"""

from __future__ import annotations

from ...nn import Activation, LossFunction, NeuralNetConfiguration, WeightInit
from ...nn.graph import ComputationGraph
from ...nn.layers import (
    DenseLayer,
    EmbeddingSequenceLayer,
    LayerNormLayer,
    PositionalEmbeddingLayer,
    RnnOutputLayer,
)
from ...nn.vertices import ElementWiseOp, ElementWiseVertex
from ...train.updaters import Adam


class BertEncoder:
    def __init__(
        self,
        vocab_size: int = 30522,
        hidden: int = 768,
        n_layers: int = 12,
        n_heads: int = 12,
        ffn_size: int = 3072,
        max_len: int = 512,
        seed: int = 123,
        updater=None,
        dtype: str = "float32",
        compute_dtype: str = None,
        gradient_checkpointing: bool = False,
    ) -> None:
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.n_layers = n_layers
        self.n_heads = n_heads
        self.ffn_size = ffn_size
        self.max_len = max_len
        self.seed = seed
        self.updater = updater or Adam(1e-4)
        self.dtype = dtype
        self.compute_dtype = compute_dtype
        self.gradient_checkpointing = gradient_checkpointing

    def _block(self, g, name: str, inp: str) -> str:
        """Pre-LN transformer block: x + Attn(LN(x)), then x + FFN(LN(x))."""
        from ...nn.layers import SelfAttentionLayer

        h = self.hidden
        g.add_layer(f"{name}_ln1", LayerNormLayer(n_out=h), inp)
        g.add_layer(f"{name}_attn", SelfAttentionLayer(
            n_in=h, n_out=h, n_heads=self.n_heads,
            activation=Activation.IDENTITY,
        ), f"{name}_ln1")
        g.add_vertex(f"{name}_res1", ElementWiseVertex(op=ElementWiseOp.ADD),
                     inp, f"{name}_attn")
        g.add_layer(f"{name}_ln2", LayerNormLayer(n_out=h), f"{name}_res1")
        g.add_layer(f"{name}_ffn1", DenseLayer(
            n_in=h, n_out=self.ffn_size, activation=Activation.GELU,
        ), f"{name}_ln2")
        g.add_layer(f"{name}_ffn2", DenseLayer(
            n_in=self.ffn_size, n_out=h, activation=Activation.IDENTITY,
        ), f"{name}_ffn1")
        g.add_vertex(f"{name}_res2", ElementWiseVertex(op=ElementWiseOp.ADD),
                     f"{name}_res1", f"{name}_ffn2")
        return f"{name}_res2"

    def conf(self):
        g = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .data_type(self.dtype)
            .compute_dtype(self.compute_dtype)
            .gradient_checkpointing(self.gradient_checkpointing)
            .updater(self.updater)
            .weight_init(WeightInit.XAVIER)
            .graph_builder()
            .add_inputs("ids")
        )
        g.add_layer("tok_emb", EmbeddingSequenceLayer(
            n_in=self.vocab_size, n_out=self.hidden,
        ), "ids")
        g.add_layer("pos_emb", PositionalEmbeddingLayer(
            n_out=self.hidden, max_len=self.max_len,
        ), "tok_emb")
        x = "pos_emb"
        for i in range(self.n_layers):
            x = self._block(g, f"blk{i}", x)
        g.add_layer("final_ln", LayerNormLayer(n_out=self.hidden), x)
        g.add_layer("mlm", RnnOutputLayer(
            n_in=self.hidden, n_out=self.vocab_size,
            loss=LossFunction.SPARSE_MCXENT, activation=Activation.SOFTMAX,
        ), "final_ln")
        g.set_outputs("mlm")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()
