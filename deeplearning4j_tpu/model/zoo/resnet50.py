"""ResNet-50.

Reference: org.deeplearning4j.zoo.model.ResNet50 — the ImageNet benchmark
model (BASELINE.json:8, "ResNet-50 ImageNet via ComputationGraph"). Standard
v1 bottleneck architecture: 7x7/2 stem -> maxpool -> stages [3,4,6,3] ->
global average pool -> softmax. Residual adds are ElementWiseVertex(ADD),
identity vs projection shortcuts per stage, batch norm after every conv.
"""

from __future__ import annotations

from ...nn import Activation, InputType, LossFunction, NeuralNetConfiguration, WeightInit
from ...nn.graph import ComputationGraph
from ...nn.layers import (
    ActivationLayer,
    BatchNormalizationLayer,
    ConvolutionLayer,
    ConvolutionMode,
    GlobalPoolingLayer,
    OutputLayer,
    PoolingType,
    SubsamplingLayer,
    ZeroPaddingLayer,
)
from ...nn.vertices import ElementWiseOp, ElementWiseVertex
from ...train.updaters import Adam


class ResNet50:
    def __init__(
        self,
        num_classes: int = 1000,
        seed: int = 123,
        height: int = 224,
        width: int = 224,
        channels: int = 3,
        updater=None,
        dtype: str = "float32",
        compute_dtype: str = None,
    ) -> None:
        self.num_classes = num_classes
        self.seed = seed
        self.height = height
        self.width = width
        self.channels = channels
        self.updater = updater or Adam(1e-3)
        self.dtype = dtype
        self.compute_dtype = compute_dtype

    # ---- block builders ---------------------------------------------------
    def _conv_bn(self, g, name, n_out, kernel, stride, inp, activation=True, mode=ConvolutionMode.SAME):
        g.add_layer(f"{name}_conv", ConvolutionLayer(
            n_out=n_out, kernel_size=kernel, stride=stride,
            convolution_mode=mode, activation=Activation.IDENTITY, has_bias=False,
        ), inp)
        g.add_layer(f"{name}_bn", BatchNormalizationLayer(), f"{name}_conv")
        if activation:
            g.add_layer(f"{name}_relu", ActivationLayer(activation=Activation.RELU), f"{name}_bn")
            return f"{name}_relu"
        return f"{name}_bn"

    def _bottleneck(self, g, name, inp, filters, stride=(1, 1), project=False):
        f1, f2, f3 = filters
        x = self._conv_bn(g, f"{name}_a", f1, (1, 1), stride, inp)
        x = self._conv_bn(g, f"{name}_b", f2, (3, 3), (1, 1), x)
        x = self._conv_bn(g, f"{name}_c", f3, (1, 1), (1, 1), x, activation=False)
        if project:
            shortcut = self._conv_bn(
                g, f"{name}_proj", f3, (1, 1), stride, inp, activation=False
            )
        else:
            shortcut = inp
        g.add_vertex(f"{name}_add", ElementWiseVertex(op=ElementWiseOp.ADD), x, shortcut)
        g.add_layer(f"{name}_out", ActivationLayer(activation=Activation.RELU), f"{name}_add")
        return f"{name}_out"

    def conf(self):
        g = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .data_type(self.dtype)
            .compute_dtype(self.compute_dtype)
            .updater(self.updater)
            .weight_init(WeightInit.RELU)
            .graph_builder()
            .add_inputs("input")
        )
        # stem
        x = self._conv_bn(g, "stem", 64, (7, 7), (2, 2), "input")
        g.add_layer("stem_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), convolution_mode=ConvolutionMode.SAME,
            pooling_type=PoolingType.MAX,
        ), x)
        x = "stem_pool"
        # stages: (blocks, filters, first-stride)
        stages = [
            (3, (64, 64, 256), (1, 1)),
            (4, (128, 128, 512), (2, 2)),
            (6, (256, 256, 1024), (2, 2)),
            (3, (512, 512, 2048), (2, 2)),
        ]
        for si, (blocks, filters, stride) in enumerate(stages):
            for bi in range(blocks):
                x = self._bottleneck(
                    g, f"s{si}b{bi}", x, filters,
                    stride=stride if bi == 0 else (1, 1),
                    project=(bi == 0),
                )
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type=PoolingType.AVG), x)
        g.add_layer("fc", OutputLayer(
            n_out=self.num_classes, loss=LossFunction.MCXENT, activation=Activation.SOFTMAX,
        ), "avgpool")
        g.set_outputs("fc")
        g.set_input_types(InputType.convolutional(self.height, self.width, self.channels))
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()
