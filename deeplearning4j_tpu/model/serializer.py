"""Model serialization.

Reference: org.deeplearning4j.util.ModelSerializer (SURVEY.md §5.4): a zip
with ``configuration.json`` (the config DSL — "config is data"),
``coefficients.bin`` (single flat param vector, possible because of the
contiguous-params invariant), ``updaterState.bin`` and an optional normalizer
entry. Same layout here:

  configuration.json   — core.config JSON of the MultiLayerConfiguration/
                         ComputationGraphConfiguration
  coefficients.npy     — flat float param vector (ravel_pytree order)
  state.npz            — non-trainable state leaves (BN running stats)
  updaterState.npz     — optax optimizer-state leaves (optional)
  normalizer.npz       — normalizer state (optional)
  meta.json            — model class + framework version

Orbax handles sharded/async checkpoints for the distributed trainer
(parallel/); this serializer is the reference-parity single-file format.
"""

from __future__ import annotations

import io
import json
import os
import tempfile
import warnings
import zipfile
from typing import Any, Optional

import jax
import numpy as np
from jax.flatten_util import ravel_pytree

from .. import __version__
from ..core.config import from_json, to_json

_CONF = "configuration.json"
_COEFF = "coefficients.npy"
_STATE = "state.npz"
_UPDATER = "updaterState.npz"
_NORM = "normalizer.npz"
_META = "meta.json"

_FRAMEWORK = "deeplearning4j_tpu"
_KNOWN_MODEL_CLASSES = ("MultiLayerNetwork", "ComputationGraph")


def _leaves_to_npz(tree: Any) -> bytes:
    leaves = jax.tree_util.tree_leaves(tree)
    buf = io.BytesIO()
    np.savez(buf, **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)})
    return buf.getvalue()


def _npz_to_leaves(data: bytes, template: Any) -> Any:
    z = np.load(io.BytesIO(data))
    leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
    treedef = jax.tree_util.tree_structure(template)
    t_leaves = jax.tree_util.tree_leaves(template)
    if len(leaves) != len(t_leaves):
        raise ValueError(
            f"Checkpoint has {len(leaves)} state leaves, model expects {len(t_leaves)}"
        )
    import jax.numpy as jnp

    cast = [jnp.asarray(l, np.asarray(t).dtype) for l, t in zip(leaves, t_leaves)]
    return jax.tree_util.tree_unflatten(treedef, cast)


def write_model(model, path: str, save_updater: bool = False, normalizer=None,
                *, class_name: Optional[str] = None) -> None:
    """Reference: ModelSerializer.writeModel(model, file, saveUpdater[, normalizer]).

    Atomic: the zip is assembled in a temp file in the destination
    directory, fsynced, then ``os.replace``d onto ``path`` — a crash
    mid-write never leaves a truncated artifact at ``path`` (an existing
    file there survives untouched).

    ``class_name=`` overrides the recorded model class: the async
    checkpoint writer (train/checkpoint.py) serializes a host-memory
    SNAPSHOT shim instead of the live model, and meta.json must still
    name the real class for :func:`restore_model` dispatch."""
    dirname = os.path.dirname(os.path.abspath(path))
    fd, tmp_path = tempfile.mkstemp(dir=dirname, prefix=".tmp-",
                                    suffix=os.path.basename(path))
    try:
        with os.fdopen(fd, "wb") as f:
            with zipfile.ZipFile(f, "w", zipfile.ZIP_DEFLATED) as zf:
                zf.writestr(_CONF, to_json(model.conf))
                flat, _ = ravel_pytree(model.params)
                buf = io.BytesIO()
                np.save(buf, np.asarray(flat))
                zf.writestr(_COEFF, buf.getvalue())
                zf.writestr(_STATE, _leaves_to_npz(model.state))
                meta = {
                    "model_class": class_name or type(model).__name__,
                    "framework": _FRAMEWORK,
                    "version": __version__,
                }
                zf.writestr(_META, json.dumps(meta))
                if save_updater and model._trainer is not None:
                    zf.writestr(_UPDATER, _leaves_to_npz(model._trainer.opt_state))
                if normalizer is not None:
                    buf = io.BytesIO()
                    np.savez(buf, **normalizer.state_dict())
                    zf.writestr(_NORM, buf.getvalue())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp_path, path)
    except BaseException:
        try:
            os.unlink(tmp_path)
        except OSError:
            pass
        raise


def restore_multi_layer_network(path: str, load_updater: bool = False):
    """Reference: ModelSerializer.restoreMultiLayerNetwork."""
    from ..nn.sequential import MultiLayerNetwork

    return _restore(path, MultiLayerNetwork, load_updater)


def restore_computation_graph(path: str, load_updater: bool = False):
    """Reference: ModelSerializer.restoreComputationGraph."""
    from ..nn.graph import ComputationGraph

    return _restore(path, ComputationGraph, load_updater)


def _check_meta(meta: dict, path: str) -> None:
    """Fail loudly on artifacts this framework cannot interpret (hard
    error on unknown model class / foreign framework) and warn on a
    framework-version skew — round-5 style checkpoint incompatibilities
    (CHANGES.md) should surface at load, not as silent mis-loads."""
    cls_name = meta.get("model_class")
    if cls_name not in _KNOWN_MODEL_CLASSES:
        raise ValueError(
            f"{path}: unknown model_class {cls_name!r} in meta.json "
            f"(expected one of {_KNOWN_MODEL_CLASSES})")
    framework = meta.get("framework")
    if framework is not None and framework != _FRAMEWORK:
        raise ValueError(
            f"{path}: artifact written by framework {framework!r}, "
            f"not {_FRAMEWORK!r}")
    version = meta.get("version")
    if version is not None and version != __version__:
        warnings.warn(
            f"{path}: artifact written by {_FRAMEWORK} {version}, loading "
            f"with {__version__} — layer semantics may have changed "
            f"(see CHANGES.md); verify outputs or re-export",
            stacklevel=3)


def restore_model(path: str, load_updater: bool = False):
    with zipfile.ZipFile(path) as zf:
        meta = json.loads(zf.read(_META))
    _check_meta(meta, path)
    if meta["model_class"] == "ComputationGraph":
        return restore_computation_graph(path, load_updater)
    return restore_multi_layer_network(path, load_updater)


def _restore(path: str, cls, load_updater: bool):
    with zipfile.ZipFile(path) as zf:
        if _META in zf.namelist():
            _check_meta(json.loads(zf.read(_META)), path)
        conf = from_json(zf.read(_CONF).decode())
        model = cls(conf).init()
        flat = np.load(io.BytesIO(zf.read(_COEFF)))
        n_expected = model.num_params()
        if int(flat.size) != n_expected:
            raise ValueError(
                f"{path}: coefficient vector has {int(flat.size)} values but "
                f"{cls.__name__} built from the stored configuration expects "
                f"{n_expected} params — the artifact does not match its own "
                f"configuration (corrupt, or written by an incompatible "
                f"framework version)")
        _, unravel = ravel_pytree(model.params)
        model.params = unravel(jax.numpy.asarray(flat))
        if _STATE in zf.namelist():
            model.state = _npz_to_leaves(zf.read(_STATE), model.state)
        if load_updater:
            if _UPDATER not in zf.namelist():
                raise ValueError(
                    f"{path}: load_updater=True but the artifact has no "
                    f"updater state — it was saved with save_updater=False; "
                    f"re-save with write_model(..., save_updater=True) or "
                    f"load with load_updater=False")
            from ..train.solver import Solver

            model._trainer = Solver(model)
            model._trainer.opt_state = _npz_to_leaves(
                zf.read(_UPDATER), model._trainer.opt_state
            )
    return model


def read_normalizer(path: str):
    from ..data.normalizers import (
        ImagePreProcessingScaler,
        NormalizerMinMaxScaler,
        NormalizerStandardize,
        VGG16ImagePreProcessor,
    )

    kinds = {
        "standardize": NormalizerStandardize,
        "minmax": NormalizerMinMaxScaler,
        "image": ImagePreProcessingScaler,
        "vgg16": VGG16ImagePreProcessor,
    }
    with zipfile.ZipFile(path) as zf:
        if _NORM not in zf.namelist():
            return None
        z = np.load(io.BytesIO(zf.read(_NORM)))
        d = {k: z[k] for k in z.files}
    norm = kinds[str(d["kind"])]()
    norm.load_state_dict(d)
    return norm
