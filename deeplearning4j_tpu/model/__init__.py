from . import zoo
from .serializer import (
    read_normalizer,
    restore_computation_graph,
    restore_model,
    restore_multi_layer_network,
    write_model,
)

__all__ = [
    "read_normalizer",
    "restore_computation_graph",
    "restore_model",
    "restore_multi_layer_network",
    "write_model",
    "zoo",
]
