"""TPU kernel ops.

The reference accelerates hot layers through per-layer "platform helpers"
(cuDNN/oneDNN consulted before generic impls — SURVEY.md §2.1). Here XLA is
the default platform and Pallas kernels are the optional accelerated helper,
selected through :func:`set_attention_impl` — the same pluggable-seam shape
as the reference's ``LayerHelper`` SPI, so ValidateCuDNN-style parity tests
(helper vs builtin) carry over (SURVEY.md §4).
"""

from . import helpers
from .helpers import (
    available_helpers,
    get_helper,
    helper_name,
    register_helper,
    set_helper,
)
from .flash_attention import (
    attention_impl,
    decode_attention,
    decode_attention_reference,
    flash_attention,
    flash_decode_attention,
    mha_attention,
    mha_attention_reference,
    set_attention_impl,
)
from .grouped_matmul import (
    grouped_matmul,
    grouped_matmul_impl,
    grouped_matmul_reference,
    set_grouped_matmul_impl,
)
from .moe_dispatch import (
    DispatchPlan,
    combine_rows,
    gather_dispatch,
    make_dispatch_plan,
    scatter_combine,
    top_k_routing,
)
from .paged_attention import (
    pack_row_blocks,
    paged_cache_write,
    paged_decode_attention,
    paged_gather,
)

__all__ = [
    "attention_impl",
    "decode_attention",
    "decode_attention_reference",
    "flash_decode_attention",
    "available_helpers",
    "get_helper",
    "helper_name",
    "helpers",
    "register_helper",
    "set_helper",
    "flash_attention",
    "mha_attention",
    "mha_attention_reference",
    "set_attention_impl",
    "DispatchPlan",
    "combine_rows",
    "gather_dispatch",
    "grouped_matmul",
    "grouped_matmul_impl",
    "grouped_matmul_reference",
    "set_grouped_matmul_impl",
    "make_dispatch_plan",
    "pack_row_blocks",
    "paged_cache_write",
    "paged_decode_attention",
    "paged_gather",
    "scatter_combine",
    "top_k_routing",
]
