"""Sorted grouped (ragged) expert matmul — the MoE fast path.

``grouped_matmul(lhs [N, d], group_sizes [E], rhs [E, d, h]) -> [N, h]``
contracts each row of ``lhs`` against the weight slab of the group it
belongs to. Rows are PRE-SORTED by group: group ``e`` owns the contiguous
row range ``[offsets[e], offsets[e] + group_sizes[e])`` where ``offsets``
is the exclusive cumsum of ``group_sizes``. Rows at or past the global
frontier ``sum(group_sizes)`` belong to no group and produce zeros —
that is how MoE dispatch parks dropped assignments.

One kernel covers all experts — no per-expert host loop. Internally rows
are viewed as zero-padded per-group tiles ``[E, m_pad, d]`` (``m_pad`` =
``max_group_size`` rounded to the m-block); the Pallas kernel reads the
per-group row count from SMEM and m-tiles past a group's frontier skip
their matmul entirely — the same skip-past-the-frontier trick as
``flash_decode_attention`` — so MXU time is proportional to *actual*
per-group load, not to the capacity bound. The masked XLA spelling
(:func:`grouped_matmul_reference`) is the same gather→batched-einsum→
scatter with zero-filled padding, and is the parity/fallback reference.

The op carries a custom VJP: dgrad is a grouped matmul against ``rhs``
transposed, wgrad is the per-group accumulation
``drhs[e] = lhs_e^T @ g_e`` spelled over the zero-padded group tiles.

``set_grouped_matmul_impl`` is the helper-impl seam, mirroring
``ops/flash_attention.set_attention_impl`` (reference: LayerHelper SPI).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU memory spaces — absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

# ---------------------------------------------------------------------------
# helper-impl seam
# ---------------------------------------------------------------------------

_IMPL = "auto"  # "auto" | "pallas" | "xla"


def set_grouped_matmul_impl(impl: str) -> None:
    """Select the grouped-matmul implementation: "xla" (masked reference
    spelling), "pallas" (TPU kernel; interpreted off-TPU), or "auto"
    (pallas on TPU, xla elsewhere). Read at trace time; jit caches are
    cleared on change so the toggle takes effect everywhere."""
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(f"unknown grouped_matmul impl {impl!r}")
    global _IMPL
    if impl != _IMPL:
        _IMPL = impl
        jax.clear_caches()


def grouped_matmul_impl() -> str:
    return _IMPL


# ---------------------------------------------------------------------------
# sorted-rows <-> zero-padded group tiles
# ---------------------------------------------------------------------------


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _to_groups(x: jax.Array, group_sizes: jax.Array, m_pad: int) -> jax.Array:
    """Gather sorted rows ``x [N, c]`` into ``[E, m_pad, c]`` group tiles;
    slots past a group's size (and rows past the global frontier) are 0."""
    e = group_sizes.shape[0]
    sizes = group_sizes.astype(jnp.int32)
    starts = jnp.cumsum(sizes) - sizes  # exclusive cumsum [E]
    m_idx = jax.lax.broadcasted_iota(jnp.int32, (e, m_pad), 1)
    row = starts[:, None] + m_idx
    row = jnp.where(m_idx < sizes[:, None], row, x.shape[0])  # OOB -> fill
    return jnp.take(x, row.reshape(-1), axis=0, mode="fill",
                    fill_value=0).reshape(e, m_pad, x.shape[1])


def _from_groups(buf: jax.Array, group_sizes: jax.Array, n: int) -> jax.Array:
    """Scatter ``[E, m_pad, h]`` group tiles back to sorted rows ``[n, h]``;
    rows past ``sum(group_sizes)`` come back as zeros."""
    e, m_pad, h = buf.shape
    sizes = group_sizes.astype(jnp.int32)
    ends = jnp.cumsum(sizes)
    rid = jnp.arange(n, dtype=jnp.int32)
    gid = jnp.searchsorted(ends, rid, side="right").astype(jnp.int32)
    safe = jnp.minimum(gid, e - 1)
    local = rid - (ends[safe] - sizes[safe])
    pos = safe * m_pad + local
    pos = jnp.where((gid < e) & (local < m_pad), pos, e * m_pad)  # OOB -> 0
    return jnp.take(buf.reshape(e * m_pad, h), pos, axis=0, mode="fill",
                    fill_value=0)


# ---------------------------------------------------------------------------
# masked XLA reference spelling
# ---------------------------------------------------------------------------


def _gmm_xla(lhs, rhs, group_sizes, m_pad):
    buf = _to_groups(lhs, group_sizes, m_pad)  # [E, m_pad, d], zero-masked
    out_dtype = jnp.promote_types(lhs.dtype, rhs.dtype)
    if out_dtype in (jnp.bfloat16, jnp.float16):
        out = jnp.einsum("emd,edh->emh", buf, rhs.astype(buf.dtype),
                         preferred_element_type=jnp.float32)
    else:
        out = jnp.einsum("emd,edh->emh", buf, rhs)
    return _from_groups(out.astype(out_dtype), group_sizes, lhs.shape[0])


def grouped_matmul_reference(
    lhs: jax.Array,
    group_sizes: jax.Array,
    rhs: jax.Array,
    max_group_size: Optional[int] = None,
) -> jax.Array:
    """Masked XLA spelling of :func:`grouped_matmul` (plain autodiff, no
    custom VJP) — the parity reference for the Pallas kernel and for the
    custom VJP's gradients."""
    _check_shapes(lhs, group_sizes, rhs)
    m_pad, _ = _tiling(lhs.shape[0], max_group_size, 128)
    return _gmm_xla(lhs, rhs, group_sizes.astype(jnp.int32), m_pad)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------


def _gmm_kernel(size_ref, lhs_ref, rhs_ref, out_ref, *, block_m):
    """One (group, m-tile) grid step. The group's row count arrives as an
    SMEM scalar; tiles wholly past the group frontier skip the matmul and
    just zero their output block (padded input rows are already zero, so
    partially-filled tiles need no extra masking)."""
    j = pl.program_id(1)
    size = size_ref[0, 0]

    @pl.when(j * block_m >= size)
    def _():
        out_ref[0] = jnp.zeros_like(out_ref[0])

    @pl.when(j * block_m < size)
    def _():
        out_ref[0] = jax.lax.dot_general(
            lhs_ref[0], rhs_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(out_ref.dtype)


def _gmm_pallas(lhs, rhs, group_sizes, m_pad, block_m, interpret):
    e, d, h = rhs.shape
    out_dtype = jnp.promote_types(lhs.dtype, rhs.dtype)
    buf = _to_groups(lhs, group_sizes, m_pad).astype(out_dtype)
    sizes = group_sizes.astype(jnp.int32).reshape(e, 1)
    kern = functools.partial(_gmm_kernel, block_m=block_m)
    kw = dict(memory_space=_VMEM)
    out = pl.pallas_call(
        kern,
        grid=(e, m_pad // block_m),
        in_specs=[
            pl.BlockSpec((1, 1), lambda ge, j: (ge, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_m, d), lambda ge, j: (ge, j, 0), **kw),
            pl.BlockSpec((1, d, h), lambda ge, j: (ge, 0, 0), **kw),
        ],
        out_specs=pl.BlockSpec((1, block_m, h), lambda ge, j: (ge, j, 0),
                               **kw),
        out_shape=jax.ShapeDtypeStruct((e, m_pad, h), out_dtype),
        interpret=interpret,
    )(sizes, buf, rhs.astype(out_dtype))
    return _from_groups(out, group_sizes, lhs.shape[0])


# ---------------------------------------------------------------------------
# custom VJP
# ---------------------------------------------------------------------------


def _gmm_any(lhs, rhs, group_sizes, m_pad, block_m, use_pallas, interpret):
    if use_pallas and _VMEM is not None:
        return _gmm_pallas(lhs, rhs, group_sizes, m_pad, block_m, interpret)
    return _gmm_xla(lhs, rhs, group_sizes, m_pad)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _gmm(lhs, rhs, group_sizes, m_pad, block_m, use_pallas, interpret):
    return _gmm_any(lhs, rhs, group_sizes, m_pad, block_m, use_pallas,
                    interpret)


def _gmm_fwd(lhs, rhs, group_sizes, m_pad, block_m, use_pallas, interpret):
    out = _gmm_any(lhs, rhs, group_sizes, m_pad, block_m, use_pallas,
                   interpret)
    return out, (lhs, rhs, group_sizes)


def _gmm_bwd(m_pad, block_m, use_pallas, interpret, res, g):
    lhs, rhs, group_sizes = res
    # dgrad: grouped matmul against rhs transposed — rows past the frontier
    # had zero output, so they correctly get zero cotangent back.
    dlhs = _gmm_any(g, jnp.swapaxes(rhs, 1, 2), group_sizes, m_pad, block_m,
                    use_pallas, interpret).astype(lhs.dtype)
    # wgrad: per-group accumulation drhs[e] = lhs_e^T @ g_e over the
    # zero-padded group tiles (padding rows contribute nothing).
    lhs_buf = _to_groups(lhs, group_sizes, m_pad)
    g_buf = _to_groups(g, group_sizes, m_pad)
    if jnp.promote_types(lhs.dtype, g.dtype) in (jnp.bfloat16, jnp.float16):
        drhs = jnp.einsum("emd,emh->edh", lhs_buf, g_buf,
                          preferred_element_type=jnp.float32)
    else:
        drhs = jnp.einsum("emd,emh->edh", lhs_buf, g_buf)
    dgs = np.zeros(group_sizes.shape, dtype=jax.dtypes.float0)
    return dlhs, drhs.astype(rhs.dtype), dgs


_gmm.defvjp(_gmm_fwd, _gmm_bwd)


# ---------------------------------------------------------------------------
# public entry point
# ---------------------------------------------------------------------------


def _check_shapes(lhs, group_sizes, rhs):
    if lhs.ndim != 2 or rhs.ndim != 3 or group_sizes.ndim != 1:
        raise ValueError(
            f"grouped_matmul expects lhs [N, d], group_sizes [E], "
            f"rhs [E, d, h]; got {lhs.shape}, {group_sizes.shape}, "
            f"{rhs.shape}")
    if rhs.shape[0] != group_sizes.shape[0] or rhs.shape[1] != lhs.shape[1]:
        raise ValueError(
            f"grouped_matmul shape mismatch: lhs {lhs.shape}, "
            f"group_sizes {group_sizes.shape}, rhs {rhs.shape}")


def _tiling(n: int, max_group_size: Optional[int], block_m: int):
    m = n if max_group_size is None else int(max_group_size)
    m = max(1, min(m, max(n, 1)))
    bm = min(block_m, _round_up(m, 8))
    return _round_up(m, bm), bm


def grouped_matmul(
    lhs: jax.Array,
    group_sizes: jax.Array,
    rhs: jax.Array,
    max_group_size: Optional[int] = None,
    block_m: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Ragged grouped matmul over rows pre-sorted by group (see module
    docstring for the row-layout contract).

    ``max_group_size`` is a static upper bound on any single group's row
    count (e.g. the MoE capacity); it bounds the padded per-group tile so
    compute stays proportional to the bound instead of ``N``. Groups
    exceeding the bound have their overflow rows zeroed — callers must
    guarantee the bound. Defaults to ``N`` (always safe)."""
    _check_shapes(lhs, group_sizes, rhs)
    m_pad, bm = _tiling(lhs.shape[0], max_group_size, block_m)
    impl = _IMPL
    if impl == "auto":
        use_pallas = jax.default_backend() == "tpu" and _VMEM is not None
    else:
        use_pallas = impl == "pallas"
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not jnp.issubdtype(rhs.dtype, jnp.inexact):  # e.g. int8 expert slabs
        rhs = rhs.astype(lhs.dtype)
    return _gmm(lhs, rhs, group_sizes.astype(jnp.int32), m_pad, bm,
                use_pallas, bool(interpret))
