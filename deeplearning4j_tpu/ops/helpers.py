"""Layer-helper SPI: per-op pluggable implementations.

Reference: org.deeplearning4j.nn.layers.LayerHelper and the cuDNN/oneDNN
helper classes consulted before builtin math (SURVEY.md §2.1 "platform
helpers", §2.2 "Helper SPI"). The attention seam (flash_attention.py) was
the first instance; this generalizes it: any hot op can register named
implementations and be switched globally — the hook where Pallas kernels,
experimental lowerings, or debug paths plug in without touching layers.

Built-in registrations:
  conv2d: "xla" (conv_general_dilated — the fast path; XLA's conv emitter
          tiles the MXU directly) and "im2col" (patch-extraction + one big
          matmul — the reference's builtin strategy, kept as a genuinely
          different lowering for A/B parity checks and odd shapes where
          explicit GEMM wins).
  lstm:   "scan" (lax.scan — one compiled loop, the sequence-length-
          agnostic default) and "unrolled" (python-unrolled steps — larger
          program, no loop overhead; can win for short static sequences).

Switching clears jit caches (choices are read at trace time), same
contract as set_attention_impl.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
from jax import lax

_IMPLS: Dict[str, Dict[str, Callable]] = {}
_ACTIVE: Dict[str, str] = {}


def register_helper(op: str, name: str, fn: Callable,
                    default: bool = False) -> None:
    """Register an implementation; the first registration for ``op`` (or a
    later one passing ``default=True``) becomes the active choice."""
    _IMPLS.setdefault(op, {})[name] = fn
    if default or op not in _ACTIVE:
        _ACTIVE[op] = name


def set_helper(op: str, name: str) -> None:
    """Select the implementation for ``op`` ("xla"/"im2col"/...). Clears
    jit caches so already-compiled programs re-trace with the new choice."""
    if op not in _IMPLS:
        raise ValueError(f"no helpers registered for op {op!r}")
    if name not in _IMPLS[op]:
        raise ValueError(
            f"unknown helper {name!r} for {op!r}; have {sorted(_IMPLS[op])}")
    if _ACTIVE.get(op) != name:
        _ACTIVE[op] = name
        jax.clear_caches()


def get_helper(op: str) -> Callable:
    return _IMPLS[op][_ACTIVE[op]]


def helper_name(op: str) -> str:
    return _ACTIVE[op]


def available_helpers(op: str):
    return sorted(_IMPLS.get(op, {}))


# ---------------------------------------------------------------------------
# conv2d helpers — signature: (x, w, strides, padding, dilation, dn) -> y
# where w layout + dimension numbers come from the calling layer
# ---------------------------------------------------------------------------

def _conv2d_xla(x, w, strides, padding, dilation, dn):
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding, rhs_dilation=dilation,
        dimension_numbers=lax.conv_dimension_numbers(x.shape, w.shape, dn))


def _conv2d_im2col(x, w, strides, padding, dilation, dn):
    """Patch extraction + one [b*oh*ow, k*k*ci] @ [k*k*ci, co] matmul —
    the explicit-GEMM lowering (reference: the builtin im2col path)."""
    in_spec, w_spec, out_spec = dn
    if in_spec != "NCHW" or w_spec != "OIHW":
        # normalize to NCHW/OIHW, recurse, convert back
        x_n = jnp.transpose(x, [in_spec.index(c) for c in "NCHW"])
        w_n = jnp.transpose(w, [w_spec.index(c) for c in "OIHW"])
        y = _conv2d_im2col(x_n, w_n, strides, padding, dilation,
                           ("NCHW", "OIHW", "NCHW"))
        return jnp.transpose(y, ["NCHW".index(c) for c in out_spec])
    n, ci, h, wdt = x.shape
    co, _, kh, kw = w.shape
    if isinstance(padding, str):
        # resolve SAME/VALID to explicit pads the same way lax does
        eff_kh = (kh - 1) * dilation[0] + 1
        eff_kw = (kw - 1) * dilation[1] + 1
        if padding.upper() == "SAME":
            oh = -(-h // strides[0])
            ow = -(-wdt // strides[1])
            ph = max(0, (oh - 1) * strides[0] + eff_kh - h)
            pw = max(0, (ow - 1) * strides[1] + eff_kw - wdt)
            pads = [(ph // 2, ph - ph // 2), (pw // 2, pw - pw // 2)]
        else:
            pads = [(0, 0), (0, 0)]
    else:
        pads = [tuple(p) for p in padding]
    x = jnp.pad(x, [(0, 0), (0, 0), pads[0], pads[1]])
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), strides, [(0, 0), (0, 0)], rhs_dilation=dilation,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))  # [n, ci*kh*kw, oh, ow]
    _, f, oh, ow = patches.shape
    cols = patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, f)
    y = cols @ w.reshape(co, f).T  # one MXU-shaped GEMM
    return y.reshape(n, oh, ow, co).transpose(0, 3, 1, 2)


register_helper("conv2d", "xla", _conv2d_xla, default=True)
register_helper("conv2d", "im2col", _conv2d_im2col)


def conv2d(x, w, strides, padding, dilation, dn):
    """Layer entry point: dispatch through the active conv2d helper."""
    return get_helper("conv2d")(x, w, strides, padding, dilation, dn)


# ---------------------------------------------------------------------------
# recurrent sequence helpers — signature: (inputs, step_fn, carry) ->
# (carry_final, stacked_outputs). ``inputs`` is a time-major pytree; the
# cell math (gates, masking, peepholes) stays with the layer's step_fn.
# ---------------------------------------------------------------------------

def _rnn_scan(inputs, step_fn, carry):
    return lax.scan(step_fn, carry, inputs)


def _rnn_unrolled(inputs, step_fn, carry):
    n_steps = jax.tree_util.tree_leaves(inputs)[0].shape[0]
    outs = []
    for t in range(n_steps):
        inp_t = jax.tree_util.tree_map(lambda a: a[t], inputs)
        carry, out = step_fn(carry, inp_t)
        outs.append(out)
    return carry, jnp.stack(outs, axis=0)


register_helper("lstm", "scan", _rnn_scan, default=True)
register_helper("lstm", "unrolled", _rnn_unrolled)


def rnn_sequence(inputs, step_fn, carry):
    """Layer entry point: dispatch through the active lstm helper."""
    return get_helper("lstm")(inputs, step_fn, carry)
