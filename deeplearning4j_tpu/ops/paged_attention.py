"""Paged KV-cache primitives (block pools + per-row block tables).

The static decode cache (``[b, h, max_len, d]`` per layer) charges every
resident sequence for ``max_len`` positions it may never use. The paged
layout (the vLLM idea) splits each layer's cache into a shared pool of
fixed-size blocks ``[num_blocks, h, block_size, d]`` plus one int32 block
table per row ``[b, max_len // block_size]``: a sequence only holds the
blocks that cover its *used* positions, so the same HBM pool multiplies
the concurrent sequences and a cache handoff becomes a block-list
transfer (serving/disagg.py).

Block id 0 is reserved as the TRASH block: unallocated table entries are
0, and engine-side write redirection points inactive rows there, so a
fused batch step can keep its static shape — stray writes land in trash
and are never read, because reads are masked to ``[0, pos]`` by
:func:`~deeplearning4j_tpu.ops.flash_attention.decode_attention` and the
positions a live row reads are always backed by its own blocks.

``paged_decode_attention`` is XLA-level: it gathers the row's blocks
into the contiguous ``[b, h, L, d]`` view and delegates to the existing
``decode_attention`` dispatch (flash kernel / int8 dequant reference
path). A Pallas kernel that walks the block table in-kernel (no
transient gather) is the obvious next seam; the contract here is the
reference semantics it would have to match.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def pack_row_blocks(x: jax.Array, block_size: int) -> jax.Array:
    """Reshape one row's contiguous cache plane ``[h, L, ...]`` into its
    per-block form ``[L // block_size, h, block_size, ...]`` — the layout
    a scatter into the shared pool (one slice per block id) expects."""
    h, L = x.shape[0], x.shape[1]
    if L % block_size:
        raise ValueError(f"cache length {L} not divisible by "
                         f"block_size {block_size}")
    blocked = x.reshape((h, L // block_size, block_size) + x.shape[2:])
    return jnp.moveaxis(blocked, 1, 0)


def paged_gather(pool: jax.Array, block_table: jax.Array) -> jax.Array:
    """Gather each row's blocks into the contiguous cache view: pool
    ``[num_blocks, h, block_size, ...]`` + table ``[b, nbr]`` ->
    ``[b, h, nbr * block_size, ...]`` (K/V pools are 4-D, int8 scale
    pools 3-D — both layouts share this)."""
    g = pool[block_table]                       # [b, nbr, h, bs, ...]
    g = jnp.moveaxis(g, 2, 1)                   # [b, h, nbr, bs, ...]
    b, h, nbr, bs = g.shape[:4]
    return g.reshape((b, h, nbr * bs) + g.shape[4:])


def paged_cache_write(pool: jax.Array, new: jax.Array,
                      block_table: jax.Array, pos: jax.Array) -> jax.Array:
    """Write ``new`` (``[b, h, t, d]`` K/V or ``[b, h, t]`` scales) into
    the shared pool at each row's positions ``pos + [0, t)``, routed
    through its block table — the paged counterpart of the static cache's
    ``dynamic_update_slice`` write. Positions past the table's capacity
    clamp to the last slot (the engine retires rows before that happens;
    the clamp only keeps indices in range for frozen/done rows)."""
    b, t = new.shape[0], new.shape[2]
    bs = pool.shape[2]
    cap = block_table.shape[1] * bs
    p = pos.astype(jnp.int32)[:, None] + jnp.arange(t, dtype=jnp.int32)[None]
    p = jnp.minimum(p, cap - 1)                 # [b, t]
    blk = jnp.take_along_axis(block_table, p // bs, axis=1)  # [b, t]
    off = p % bs
    # advanced-index axes move to the front: values must be [b*t, h, ...]
    vals = jnp.moveaxis(new, 2, 1).reshape((b * t, pool.shape[1])
                                           + pool.shape[3:])
    return pool.at[blk.reshape(-1), :, off.reshape(-1)].set(
        vals.astype(pool.dtype))


def paged_decode_attention(
    q: jax.Array,                 # [b, h, tq, d]
    pool_k: jax.Array,            # [num_blocks, h, block_size, d]
    pool_v: jax.Array,            # [num_blocks, h, block_size, dv]
    block_table: jax.Array,       # [b, nbr] int32
    start_pos: jax.Array,         # [b] int32
    scale: Optional[float] = None,
    k_scale: Optional[jax.Array] = None,   # [num_blocks, h, block_size] f32
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Decode attention against a paged cache: gather the row's blocks,
    then run the standard masked decode attention (which also handles the
    int8 dequant when scale pools ride along). Entries past ``pos`` —
    including anything a trash-redirected write left in block 0 — are
    masked out exactly as the static cache's pad garbage is."""
    k = paged_gather(pool_k, block_table)
    v = paged_gather(pool_v, block_table)
    from .flash_attention import decode_attention

    return decode_attention(
        q, k, v, start_pos, scale=scale,
        k_scale=None if k_scale is None else paged_gather(k_scale,
                                                          block_table),
        v_scale=None if v_scale is None else paged_gather(v_scale,
                                                          block_table))
