"""Pallas TPU flash attention.

Blockwise online-softmax attention (Dao et al. flash attention, computed the
TPU way): the q×k score matrix is never materialised in HBM — each q block
streams over k/v blocks held in VMEM, carrying running max/denominator, so
HBM traffic is O(t·d) instead of O(t²). Matmuls hit the MXU via
``dot_general`` with ``preferred_element_type=float32``.

This is the accelerated "helper" implementation for the attention layers
(deeplearning4j_tpu.nn.layers.attention); the reference's analogous seam is
the cuDNN attention/mha helper consulted before the builtin math
(SURVEY.md §2.1 "platform helpers", §2.2 "Helper SPI").

The backward pass is blockwise too (_mea_bwd_single — Dao et al. alg. 4 as
nested lax.scan): score blocks are recomputed per (q-chunk, k-chunk) with
the row logsumexp rebuilt on the fly, so TRAINING memory is O(t·d) like
the forward — long-context backprop never materialises the t² matrix.
Inputs [batch, heads, time, head_dim].
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU memory spaces — absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG = -1e30  # finite "-inf": keeps exp/max well-defined for fully-masked rows

# ---------------------------------------------------------------------------
# helper-impl seam (reference: LayerHelper SPI — cuDNN vs builtin)
# ---------------------------------------------------------------------------

_IMPL = "auto"  # "auto" | "flash" | "xla"


def set_attention_impl(impl: str) -> None:
    """Select the attention implementation: "xla" (builtin einsum path),
    "flash" (Pallas kernel), or "auto" (flash on TPU for long sequences).

    The choice is read at trace time, so already-compiled functions would
    keep their traced impl; jit caches are cleared here so the toggle takes
    effect everywhere (recompilation on next call)."""
    if impl not in ("auto", "flash", "xla"):
        raise ValueError(f"unknown attention impl {impl!r}")
    global _IMPL
    if impl != _IMPL:
        _IMPL = impl
        jax.clear_caches()


def attention_impl() -> str:
    return _IMPL


# ---------------------------------------------------------------------------
# reference (builtin) implementation — also the backward path for flash
# ---------------------------------------------------------------------------


def mha_attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Plain XLA attention: softmax(q·kᵀ·scale + bias)·v. Masks are additive
    large-negative biases so shapes stay static for the compiler."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    neg = jnp.asarray(_NEG, scores.dtype)
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :] > 0, scores, neg)
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        qi = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0) + (tk - tq)
        ki = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        scores = jnp.where(qi >= ki, scores, neg)
    weights = jax.nn.softmax(scores, axis=-1)
    if mask is not None or causal:
        # Rows with no valid key output 0 (matching the flash kernel) rather
        # than softmax-of-constant uniform weights.
        any_valid = jnp.any(scores > _NEG * 0.5, axis=-1, keepdims=True)
        weights = jnp.where(any_valid, weights, 0.0)
    return jnp.einsum("bhqk,bhkv->bhqv", weights, v)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, m_scr,
                  l_scr, acc_scr, *, scale, block_q, block_k, causal,
                  tk_offset):
    """One (batch·head, q-block, k-block) grid step.

    The k dimension is the innermost grid axis; TPU grids execute
    sequentially, so the VMEM scratch accumulators (running max /
    denominator / weighted sum) carry across k steps for a fixed q block.
    Only (block, d) tiles are ever resident in VMEM — Pallas pipelines the
    HBM→VMEM tile loads — so sequence length is bounded by HBM, not VMEM.
    """
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def body():
        q = q_ref[0].astype(jnp.float32) * scale  # [block_q, d]
        ks = k_ref[0].astype(jnp.float32)  # [block_k, d]
        vs = v_ref[0].astype(jnp.float32)  # [block_k, dv]
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [block_q, block_k]
        mk = mask_ref[0, 0]  # [block_k]
        s = jnp.where(mk[None, :] > 0, s, _NEG)
        if causal:
            q_ids = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + tk_offset
            k_ids = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, _NEG)

        m, l, acc = m_scr[...], l_scr[...], acc_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        # Zero masked entries explicitly: when a row is ENTIRELY masked,
        # m_new == _NEG and exp(s - m_new) == 1, which would weight masked
        # keys uniformly. Zeroing keeps l == 0 so the row output is 0 —
        # the defined semantics for fully-masked rows on both impls.
        p = jnp.where(s > _NEG * 0.5, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, vs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...], l_scr[...], acc_scr[...] = m_new, l_new, acc_new

    if causal:
        # Skip k-blocks strictly above the causal frontier (every entry
        # masked): max q_id in the block < min k_id in the block. Halves
        # the causal FLOPs — the flash-attention point, at block level.
        @pl.when(qi * block_q + tk_offset + block_q - 1 >= ki * block_k)
        def _():
            body()
    else:
        body()

    @pl.when(ki == pl.num_programs(2) - 1)
    def _():
        l_fin = l_scr[...]
        acc_fin = acc_scr[...]
        out = acc_fin / jnp.maximum(l_fin, 1e-30)  # fully-masked rows → 0
        o_ref[0] = out.astype(o_ref.dtype)
        # row logsumexp for the backward (saves its recompute pass there);
        # fully-masked rows get +big so exp(s - lse) -> 0 downstream
        lse_ref[0] = jnp.where(
            l_fin > 0, m_scr[...] + jnp.log(jnp.maximum(l_fin, 1e-30)),
            -_NEG)


def _pad_to(x: jax.Array, axis: int, multiple: int, value=0.0) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _flash_forward(q, k, v, mask, causal, scale, block_q, block_k, interpret,
                   with_lse: bool = False):
    if _VMEM is None:  # jaxlib without pallas TPU support: same math via XLA
        out = mha_attention_reference(q, k, v, mask=mask, causal=causal,
                                      scale=scale)
        return (out, None) if with_lse else out
    b, h, tq, d = q.shape
    tk, dv = k.shape[2], v.shape[3]
    block_q = min(block_q, max(tq, 1))
    block_k = min(block_k, max(tk, 1))

    if mask is None:
        mask = jnp.ones((b, tk), jnp.float32)
    # [b, 1, tk]: a leading singleton keeps the block's trailing two dims
    # equal to the array dims, satisfying the mosaic tiling constraint.
    mask = _pad_to(mask.astype(jnp.float32), 1, block_k, 0.0)[:, None, :]
    qp = _pad_to(q, 2, block_q)
    kp = _pad_to(k, 2, block_k)
    vp = _pad_to(v, 2, block_k)
    tq_p, tk_p = qp.shape[2], kp.shape[2]

    qp = qp.reshape(b * h, tq_p, d)
    kp = kp.reshape(b * h, tk_p, d)
    vp = vp.reshape(b * h, tk_p, dv)

    grid = (b * h, tq_p // block_q, tk_p // block_k)
    kern = functools.partial(
        _flash_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, tk_offset=tk - tq)
    kwargs = dict(memory_space=_VMEM)
    scratch = [
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, dv), jnp.float32),
    ]
    out, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0),
                         **kwargs),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0),
                         **kwargs),
            pl.BlockSpec((1, block_k, dv), lambda bh, qi, ki: (bh, ki, 0),
                         **kwargs),
            pl.BlockSpec((1, 1, block_k), lambda bh, qi, ki: (bh // h, 0, ki),
                         **kwargs),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dv),
                         lambda bh, qi, ki: (bh, qi, 0), **kwargs),
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, qi, ki: (bh, qi, 0), **kwargs),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq_p, dv), q.dtype),
            jax.ShapeDtypeStruct((b * h, tq_p, 1), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(qp, kp, vp, mask)
    out = out.reshape(b, h, tq_p, dv)[:, :, :tq, :]
    if not with_lse:
        return out
    return out, lse.reshape(b, h, tq_p)[:, :, :tq]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _flash(q, k, v, mask, causal, scale, block_q, block_k, bwd_block_q,
           bwd_block_k, interpret):
    return _flash_forward(q, k, v, mask, causal, scale, block_q, block_k,
                          interpret)


def _flash_fwd(q, k, v, mask, causal, scale, block_q, block_k, bwd_block_q,
               bwd_block_k, interpret):
    out, lse = _flash_forward(q, k, v, mask, causal, scale, block_q, block_k,
                              interpret, with_lse=True)
    return out, (q, k, v, mask, out, lse)


def _mea_bwd_single(q, k, v, mask_k, g, out, lse_rows, *, causal, scale,
                    tk_off, bq, bk, have_lse):
    """Memory-efficient attention backward for ONE head (Dao et al. alg. 4,
    the XLA spelling): two-level ``lax.scan`` over (q-chunk, k-chunk)
    recomputes score blocks instead of materializing the [tq, tk] matrix —
    backward memory is O(t·d) like the flash forward, so long-context
    TRAINING fits, not just inference. Returns (dq, dk, dv).

    MXU discipline (round-5 backward tuning): operands stay in the INPUT
    dtype (bf16 on TPU) and every matmul accumulates in f32 via
    ``preferred_element_type`` — the same policy as the forward kernel.
    The softmax/statistics math (exp, lse, delta, ds scaling) runs in f32;
    only the 5 big dot_generals see bf16 operands, which doubles their MXU
    rate vs the previous cast-everything-to-f32 spelling."""
    tq, d = q.shape
    tk, dv = v.shape
    nq, nk = tq // bq, tk // bk
    op_dtype = q.dtype  # matmul operand dtype (bf16 on the TPU path)
    qc = q.reshape(nq, bq, d)
    gc = g.reshape(nq, bq, dv)
    oc = out.reshape(nq, bq, dv)
    lc = lse_rows.reshape(nq, bq, 1)
    kc = k.reshape(nk, bk, d)
    vc = v.reshape(nk, bk, dv)
    mc = mask_k.reshape(nk, bk)
    neg = jnp.float32(_NEG)

    def dotf32(a, b, dims):
        return lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)

    def scores(qch, kch, mch, qi, ki):
        s = dotf32(qch, kch, ((1,), (1,))) * scale  # [bq, bk] f32
        s = jnp.where(mch[None, :] > 0, s, neg)
        if causal:
            q_ids = (qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
                     + tk_off)
            k_ids = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_ids >= k_ids, s, neg)
        return s

    def outer(carry, xs):
        dk_acc, dv_acc = carry
        qi, qch, gch, och, lch = xs

        if have_lse:
            lse = lch  # saved by the forward kernel: no recompute pass
        else:
            # XLA-fallback forward saved no lse: rebuild it blockwise
            def p1(c, ys):
                m, l = c
                ki, kch, mch = ys
                s = scores(qch, kch, mch, qi, ki)
                m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
                p = jnp.where(s > neg * 0.5, jnp.exp(s - m_new), 0.0)
                l = l * jnp.exp(m - m_new) + jnp.sum(p, axis=-1,
                                                     keepdims=True)
                return (m_new, l), None

            (m, l), _ = lax.scan(
                p1, (jnp.full((bq, 1), neg), jnp.zeros((bq, 1), jnp.float32)),
                (jnp.arange(nk), kc, mc))
            # fully-masked rows: force P = 0 downstream, not exp(s+inf)
            lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)),
                            jnp.float32(-_NEG))
        delta = jnp.sum(gch.astype(jnp.float32) * och.astype(jnp.float32),
                        axis=-1, keepdims=True)  # D_i

        # pass 2: dq for this q-chunk; per-k-chunk dk/dv contributions
        def p2(dq, ys):
            ki, kch, vch, mch = ys
            s = scores(qch, kch, mch, qi, ki)
            p = jnp.where(s > neg * 0.5, jnp.exp(s - lse), 0.0)  # [bq, bk]
            dp = dotf32(gch, vch, ((1,), (1,)))                  # [bq, bk]
            ds = (p * (dp - delta)).astype(op_dtype)
            p_c = p.astype(op_dtype)
            dq = dq + dotf32(ds, kch, ((1,), (0,))) * scale
            return dq, (dotf32(ds, qch, ((0,), (0,))) * scale,
                        dotf32(p_c, gch, ((0,), (0,))))

        dq, (dks, dvs) = lax.scan(
            p2, jnp.zeros((bq, d), jnp.float32),
            (jnp.arange(nk), kc, vc, mc))
        return (dk_acc + dks, dv_acc + dvs), dq

    (dk_out, dv_out), dqs = lax.scan(
        outer,
        (jnp.zeros((nk, bk, d), jnp.float32),
         jnp.zeros((nk, bk, dv), jnp.float32)),
        (jnp.arange(nq), qc, gc, oc, lc))
    return dqs.reshape(tq, d), dk_out.reshape(tk, d), dv_out.reshape(tk, dv)


def _dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, mask_ref,
               dq_ref, dq_scr, *, scale, block_q, block_k, causal, tk_offset):
    """Pallas backward kernel 1: dq. Grid (bh, q-block, k-block), k
    innermost; dq accumulates in VMEM scratch across the sequential k
    steps (same carry discipline as the forward kernel's online softmax).
    Per step: recompute the score block from q/k (bf16 operands, f32
    accumulation), p = exp(s - lse), ds = p * (g·vᵀ - delta),
    dq += ds·k · scale."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def body():
        q = q_ref[0]                       # [bq, d] bf16
        ks = k_ref[0]                      # [bk, d]
        vs = v_ref[0]                      # [bk, dv]
        gs = g_ref[0]                      # [bq, dv]
        lse = lse_ref[0]                   # [bq, 1] f32
        delta = delta_ref[0]               # [bq, 1] f32
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        mk = mask_ref[0, 0]
        s = jnp.where(mk[None, :] > 0, s, _NEG)
        if causal:
            q_ids = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + tk_offset
            k_ids = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, _NEG)
        p = jnp.where(s > _NEG * 0.5, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            gs, vs, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dq_scr[...] += jax.lax.dot_general(
            ds, ks, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        @pl.when(qi * block_q + tk_offset + block_q - 1 >= ki * block_k)
        def _():
            body()
    else:
        body()

    @pl.when(ki == pl.num_programs(2) - 1)
    def _():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, mask_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale, block_q, block_k,
                causal, tk_offset):
    """Pallas backward kernel 2: dk and dv. Grid (bh, k-block, q-block),
    q innermost; dk/dv accumulate in VMEM scratch across q steps.

    Everything is computed in TRANSPOSED orientation — sᵀ = k·qᵀ [bk, bq],
    pᵀ, dsᵀ — so the two accumulating contractions are natural
    ([bk, bq]·[bq, d]) with no Mosaic tile transposes; lse/delta arrive as
    ROW vectors (tile (1, bq)) for the same reason."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def body():
        q = q_ref[0]                        # [bq, d]
        ks = k_ref[0]                       # [bk, d]
        vs = v_ref[0]                       # [bk, dv]
        gs = g_ref[0]                       # [bq, dv]
        lse_row = lse_ref[0]                # [1, bq] f32
        delta_row = delta_ref[0]            # [1, bq] f32
        s_t = jax.lax.dot_general(
            ks, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bk, bq]
        mk = mask_ref[0, 0]                 # [bk]
        s_t = jnp.where(mk[:, None] > 0, s_t, _NEG)
        if causal:
            k_ids = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 0)
            q_ids = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 1) + tk_offset
            s_t = jnp.where(q_ids >= k_ids, s_t, _NEG)
        p_t = jnp.where(s_t > _NEG * 0.5, jnp.exp(s_t - lse_row), 0.0)
        dp_t = jax.lax.dot_general(
            vs, gs, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bk, bq]
        ds_t = (p_t * (dp_t - delta_row)).astype(q.dtype)
        dk_scr[...] += jax.lax.dot_general(
            ds_t, q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bk, d]
        dv_scr[...] += jax.lax.dot_general(
            p_t.astype(q.dtype), gs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bk, dv]

    if causal:
        # skip q-blocks entirely ABOVE the diagonal for this k block
        @pl.when(qi * block_q + tk_offset + block_q - 1 >= ki * block_k)
        def _():
            body()
    else:
        body()

    @pl.when(qi == pl.num_programs(2) - 1)
    def _():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, mask, out, lse, g, causal, scale, bq, bk):
    """Pallas two-kernel backward (dq pass + dkv pass). Requires the lse
    saved by the Pallas forward. Inputs [b, h, t, d]."""
    b, h, tq, d = q.shape
    tk, dv = k.shape[2], v.shape[3]
    bq = min(bq, max(tq, 1))
    bk = min(bk, max(tk, 1))
    # halve blocks while padding waste exceeds 25% (t=1100 with bq=1024
    # would pad to 2048 — every padded tile still runs all five matmuls)
    while bq > 128 and -(-tq // bq) * bq > 1.25 * tq:
        bq //= 2
    while bk > 128 and -(-tk // bk) * bk > 1.25 * tk:
        bk //= 2

    mask_k = jnp.ones((b, tk), jnp.float32) if mask is None \
        else mask.astype(jnp.float32)
    mp = _pad_to(mask_k, 1, bk, 0.0)[:, None, :]
    qp = _pad_to(q, 2, bq)
    gp = _pad_to(g.astype(q.dtype), 2, bq)
    # delta precomputed in XLA (cheap elementwise+reduce, fuses upstream)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    dp_ = _pad_to(delta[..., None], 2, bq, 0.0)
    lp = _pad_to(lse.astype(jnp.float32)[..., None], 2, bq, -_NEG)
    kp = _pad_to(k, 2, bk)
    vp = _pad_to(v, 2, bk)
    tq_p, tk_p = qp.shape[2], kp.shape[2]

    qp = qp.reshape(b * h, tq_p, d)
    kp = kp.reshape(b * h, tk_p, d)
    vp = vp.reshape(b * h, tk_p, dv)
    gp = gp.reshape(b * h, tq_p, dv)
    lp = lp.reshape(b * h, tq_p, 1)
    dp_ = dp_.reshape(b * h, tq_p, 1)

    kw = dict(memory_space=_VMEM)
    kern_q = functools.partial(
        _dq_kernel, scale=scale, block_q=bq, block_k=bk, causal=causal,
        tk_offset=tk - tq)
    dq = pl.pallas_call(
        kern_q,
        grid=(b * h, tq_p // bq, tk_p // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0), **kw),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0), **kw),
            pl.BlockSpec((1, bk, dv), lambda bh, qi, ki: (bh, ki, 0), **kw),
            pl.BlockSpec((1, bq, dv), lambda bh, qi, ki: (bh, qi, 0), **kw),
            pl.BlockSpec((1, bq, 1), lambda bh, qi, ki: (bh, qi, 0), **kw),
            pl.BlockSpec((1, bq, 1), lambda bh, qi, ki: (bh, qi, 0), **kw),
            pl.BlockSpec((1, 1, bk), lambda bh, qi, ki: (bh // h, 0, ki),
                         **kw),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0),
                               **kw),
        out_shape=jax.ShapeDtypeStruct((b * h, tq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
    )(qp, kp, vp, gp, lp, dp_, mp)

    kern_kv = functools.partial(
        _dkv_kernel, scale=scale, block_q=bq, block_k=bk, causal=causal,
        tk_offset=tk - tq)
    # row-vector stats for the transposed dkv kernel
    lp_row = jnp.transpose(lp, (0, 2, 1))     # [bh, 1, tq_p]
    dp_row = jnp.transpose(dp_, (0, 2, 1))
    dk, dv_out = pl.pallas_call(
        kern_kv,
        grid=(b * h, tk_p // bk, tq_p // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, ki, qi: (bh, qi, 0), **kw),
            pl.BlockSpec((1, bk, d), lambda bh, ki, qi: (bh, ki, 0), **kw),
            pl.BlockSpec((1, bk, dv), lambda bh, ki, qi: (bh, ki, 0), **kw),
            pl.BlockSpec((1, bq, dv), lambda bh, ki, qi: (bh, qi, 0), **kw),
            pl.BlockSpec((1, 1, bq), lambda bh, ki, qi: (bh, 0, qi), **kw),
            pl.BlockSpec((1, 1, bq), lambda bh, ki, qi: (bh, 0, qi), **kw),
            pl.BlockSpec((1, 1, bk), lambda bh, ki, qi: (bh // h, 0, ki),
                         **kw),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda bh, ki, qi: (bh, ki, 0), **kw),
            pl.BlockSpec((1, bk, dv), lambda bh, ki, qi: (bh, ki, 0), **kw),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk_p, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk_p, dv), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, dv), jnp.float32)],
    )(qp, kp, vp, gp, lp_row, dp_row, mp)

    dq = dq.reshape(b, h, tq_p, d)[:, :, :tq].astype(q.dtype)
    dk = dk.reshape(b, h, tk_p, d)[:, :, :tk].astype(k.dtype)
    dv_out = dv_out.reshape(b, h, tk_p, dv)[:, :, :tk].astype(v.dtype)
    return dq, dk, dv_out


def _flash_bwd(causal, scale, block_q, block_k, bwd_block_q, bwd_block_k,
               interpret, res, g):
    q, k, v, mask, out, lse = res
    b, h, tq, d = q.shape
    tk, dv = k.shape[2], v.shape[3]
    if _VMEM is not None and not interpret and lse is not None:
        # compiled path: the two-kernel Pallas backward
        dq, dk, dv_g = _flash_bwd_pallas(
            q, k, v, mask, out, lse, g, causal, scale,
            bwd_block_q or block_q, bwd_block_k or block_k)
        dmask = None if mask is None else jnp.zeros_like(mask)
        return dq, dk, dv_g, dmask
    # interpreter/CPU fallback: the scan-based memory-efficient backward
    bq = min(bwd_block_q or block_q, max(tq, 1))
    bk = min(bwd_block_k or block_k, max(tk, 1))

    mask_k = jnp.ones((b, tk), jnp.float32) if mask is None \
        else mask.astype(jnp.float32)
    # operands stay in the input dtype (bf16 on TPU): every matmul in
    # _mea_bwd_single accumulates f32 via preferred_element_type
    qp = _pad_to(q, 2, bq)
    gp = _pad_to(g.astype(q.dtype), 2, bq)
    op = _pad_to(out.astype(q.dtype), 2, bq)
    kp = _pad_to(k, 2, bk)
    vp = _pad_to(v, 2, bk)
    mp = _pad_to(mask_k, 1, bk, 0.0)
    have_lse = lse is not None
    if have_lse:
        lp = _pad_to(lse.astype(jnp.float32)[..., None], 2, bq, -_NEG)
    else:  # placeholder so the vmap structure stays uniform
        lp = jnp.zeros((b, h, qp.shape[2], 1), jnp.float32)

    single = functools.partial(
        _mea_bwd_single, causal=causal, scale=scale, tk_off=tk - tq,
        bq=bq, bk=bk, have_lse=have_lse)
    # vmap heads (mask is per-batch), then batch
    per_batch = jax.vmap(single, in_axes=(0, 0, 0, None, 0, 0, 0))
    dq, dk, dv = jax.vmap(per_batch)(qp, kp, vp, mp, gp, op, lp)

    dq = dq[:, :, :tq].astype(q.dtype)
    dk = dk[:, :, :tk].astype(k.dtype)
    dv = dv[:, :, :tk].astype(v.dtype)
    dmask = None if mask is None else jnp.zeros_like(mask)
    return dq, dk, dv, dmask


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 256,
    block_k: Optional[int] = None,
    bwd_block_q: Optional[int] = None,
    bwd_block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention over [b, h, t, d] tensors. ``mask`` is a [b, t_k]
    key-padding mask (1 = keep). Runs the Pallas kernel compiled on TPU and
    in interpreter mode elsewhere (the CPU test path).

    Blocks are tuned on TPU v5e (d=64, bf16; forward sweep in
    ROUND4_NOTES.md, backward sweep in ROUND5_NOTES.md): forward
    block_q=256 with block_k adaptive on sequence length — 512 up to 4k
    and 1024 beyond. The scan-based backward defaults to LARGER tiles
    (bwd 1024x1024) because each scan step's five matmuls must fill the
    MXU on their own; operands stay bf16 with f32 accumulation."""
    if block_k is None:
        block_k = 512 if k.shape[2] < 8192 else 1024
    if bwd_block_q is None:
        bwd_block_q = 1024
    if bwd_block_k is None:
        bwd_block_k = 1024
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, mask, causal, float(scale), block_q, block_k,
                  bwd_block_q, bwd_block_k, interpret)


# ---------------------------------------------------------------------------
# KV-cache decode attention (single-query-block flash)
# ---------------------------------------------------------------------------


def decode_attention_reference(
    q: jax.Array,           # [b, h, tq, d] — queries at positions start+i
    k: jax.Array,           # [b, h, L, d]  — static-shape KV cache
    v: jax.Array,           # [b, h, L, dv]
    start_pos: jax.Array,   # [b] int32 — absolute position of q's first row
    scale: Optional[float] = None,
) -> jax.Array:
    """Builtin XLA decode attention against a cached K/V: query ``i`` of row
    ``b`` sits at absolute position ``start_pos[b] + i`` and attends cache
    entries ``[0, start_pos[b] + i]`` inclusive. Cache slots past the
    frontier (pad garbage, not-yet-written zeros) are masked out, so the
    cache can stay a fixed ``[b, h, max_len, d]`` allocation for the whole
    generation — no shape ever depends on how far decoding has advanced."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    tq, L = q.shape[2], k.shape[2]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    q_ids = jax.lax.broadcasted_iota(jnp.int32, (tq, L), 0)
    k_ids = jax.lax.broadcasted_iota(jnp.int32, (tq, L), 1)
    limit = start_pos.astype(jnp.int32)[:, None, None, None] + q_ids[None, None]
    keep = k_ids[None, None] <= limit
    neg = jnp.asarray(_NEG, scores.dtype)
    scores = jnp.where(keep, scores, neg)
    weights = jax.nn.softmax(scores, axis=-1)
    # rows with no valid key (start_pos < 0 — an inactive slot) output 0
    any_valid = jnp.any(scores > _NEG * 0.5, axis=-1, keepdims=True)
    weights = jnp.where(any_valid, weights, 0.0)
    return jnp.einsum("bhqk,bhkv->bhqv", weights, v)


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr,
                   acc_scr, *, scale, block_k):
    """One (batch·head, k-block) grid step of single-query flash decode.

    The k axis is the innermost (sequential) grid dim so the VMEM online-
    softmax accumulators carry across k blocks, exactly like the training
    forward kernel — but the q block is a single row (the token being
    decoded) and the valid cache length arrives as an SMEM scalar, so
    k-blocks entirely past the decode frontier skip their matmuls: the
    per-step work is O(position), not O(max_len)."""
    ki = pl.program_id(1)
    length = len_ref[0, 0]  # valid cache entries = start_pos + 1

    @pl.when(ki == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(ki * block_k < length)
    def _():
        q = q_ref[0].astype(jnp.float32) * scale    # [1, d]
        ks = k_ref[0].astype(jnp.float32)           # [block_k, d]
        vs = v_ref[0].astype(jnp.float32)           # [block_k, dv]
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)     # [1, block_k]
        k_ids = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        s = jnp.where(k_ids < length, s, _NEG)
        m, l, acc = m_scr[...], l_scr[...], acc_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(s > _NEG * 0.5, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        m_scr[...] = m_new
        l_scr[...] = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc * alpha + jax.lax.dot_general(
            p, vs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == pl.num_programs(1) - 1)
    def _():
        o_ref[0] = (acc_scr[...] /
                    jnp.maximum(l_scr[...], 1e-30)).astype(o_ref.dtype)


def flash_decode_attention(
    q: jax.Array,           # [b, h, 1, d]
    k: jax.Array,           # [b, h, L, d]
    v: jax.Array,           # [b, h, L, dv]
    start_pos: jax.Array,   # [b] int32
    scale: Optional[float] = None,
    block_k: int = 512,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Pallas single-query-block decode attention (same contract as
    :func:`decode_attention_reference` with ``tq == 1``)."""
    if q.shape[2] != 1:
        raise ValueError("flash_decode_attention is the tq=1 kernel; use "
                         "decode_attention for multi-row queries")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if _VMEM is None:  # jaxlib without pallas TPU support
        return decode_attention_reference(q, k, v, start_pos, scale=scale)
    b, h, _, d = q.shape
    L, dv = k.shape[2], v.shape[3]
    block_k = min(block_k, max(L, 1))
    kp = _pad_to(k, 2, block_k)
    vp = _pad_to(v, 2, block_k)
    L_p = kp.shape[2]
    qp = q.reshape(b * h, 1, d)
    kp = kp.reshape(b * h, L_p, d)
    vp = vp.reshape(b * h, L_p, dv)
    lengths = (start_pos.astype(jnp.int32) + 1).reshape(b, 1)

    kern = functools.partial(_decode_kernel, scale=float(scale),
                             block_k=block_k)
    kw = dict(memory_space=_VMEM)
    out = pl.pallas_call(
        kern,
        grid=(b * h, L_p // block_k),
        in_specs=[
            pl.BlockSpec((1, 1), lambda bh, ki, _h=h: (bh // _h, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, d), lambda bh, ki: (bh, 0, 0), **kw),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0), **kw),
            pl.BlockSpec((1, block_k, dv), lambda bh, ki: (bh, ki, 0), **kw),
        ],
        out_specs=pl.BlockSpec((1, 1, dv), lambda bh, ki: (bh, 0, 0), **kw),
        out_shape=jax.ShapeDtypeStruct((b * h, 1, dv), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, dv), jnp.float32),
        ],
        interpret=interpret,
    )(lengths, qp, kp, vp)
    return out.reshape(b, h, 1, dv)


def decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    start_pos: jax.Array,
    scale: Optional[float] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Helper-seam dispatch for KV-cache decode attention (mirrors
    :func:`mha_attention`): the Pallas single-query kernel when "flash" is
    selected (or automatically on TPU) and the single-row query fits it,
    the builtin XLA spelling otherwise. ``set_attention_impl`` switches
    every decode step in the process, so flash-vs-reference parity checks
    run the same model code both ways.

    ``k_scale``/``v_scale`` ([b, h, L] f32, per-slot/per-head) mark an
    int8-quantized cache: the dequant (``cache * scale``) happens here,
    inside the reference path, where XLA fuses it into the score/value
    matmuls — the cache itself stays int8 in HBM (the capacity win). The
    Pallas kernel is fp-only, so quantized caches always take the
    reference spelling."""
    if k_scale is not None or v_scale is not None:
        if k_scale is not None:
            k = k.astype(q.dtype) * k_scale[..., None].astype(q.dtype)
        if v_scale is not None:
            v = v.astype(q.dtype) * v_scale[..., None].astype(q.dtype)
        return decode_attention_reference(q, k, v, start_pos, scale=scale)
    impl = _IMPL
    if impl == "auto":
        impl = "flash" if jax.default_backend() == "tpu" else "xla"
    if impl == "flash" and q.shape[2] == 1:
        return flash_decode_attention(q, k, v, start_pos, scale=scale)
    return decode_attention_reference(q, k, v, start_pos, scale=scale)


def mha_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Dispatch through the helper seam: builtin XLA path by default, the
    Pallas flash kernel when selected (or automatically on TPU for sequences
    long enough that materialising q·kᵀ matters)."""
    impl = _IMPL
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        # Gate on the larger of tq/tk: the materialised score matrix is
        # tq×tk, so long keys with few queries (LearnedSelfAttention) also
        # benefit from k/v streaming.
        impl = ("flash" if (on_tpu and max(q.shape[2], k.shape[2]) >= 512)
                else "xla")
    if impl == "flash":
        return flash_attention(q, k, v, mask=mask, causal=causal, scale=scale)
    return mha_attention_reference(q, k, v, mask=mask, causal=causal,
                                   scale=scale)
