"""Pallas TPU flash attention.

Blockwise online-softmax attention (Dao et al. flash attention, computed the
TPU way): the q×k score matrix is never materialised in HBM — each q block
streams over k/v blocks held in VMEM, carrying running max/denominator, so
HBM traffic is O(t·d) instead of O(t²). Matmuls hit the MXU via
``dot_general`` with ``preferred_element_type=float32``.

This is the accelerated "helper" implementation for the attention layers
(deeplearning4j_tpu.nn.layers.attention); the reference's analogous seam is
the cuDNN attention/mha helper consulted before the builtin math
(SURVEY.md §2.1 "platform helpers", §2.2 "Helper SPI").

The backward pass recomputes attention with the reference XLA einsum path
(flash forward + rematerialised backward): forward memory is what flash
buys; XLA fuses the backward fine at the sequence lengths the layer zoo
uses. Inputs [batch, heads, time, head_dim].
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU memory spaces — absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG = -1e30  # finite "-inf": keeps exp/max well-defined for fully-masked rows

# ---------------------------------------------------------------------------
# helper-impl seam (reference: LayerHelper SPI — cuDNN vs builtin)
# ---------------------------------------------------------------------------

_IMPL = "auto"  # "auto" | "flash" | "xla"


def set_attention_impl(impl: str) -> None:
    """Select the attention implementation: "xla" (builtin einsum path),
    "flash" (Pallas kernel), or "auto" (flash on TPU for long sequences).

    The choice is read at trace time, so already-compiled functions would
    keep their traced impl; jit caches are cleared here so the toggle takes
    effect everywhere (recompilation on next call)."""
    if impl not in ("auto", "flash", "xla"):
        raise ValueError(f"unknown attention impl {impl!r}")
    global _IMPL
    if impl != _IMPL:
        _IMPL = impl
        jax.clear_caches()


def attention_impl() -> str:
    return _IMPL


# ---------------------------------------------------------------------------
# reference (builtin) implementation — also the backward path for flash
# ---------------------------------------------------------------------------


def mha_attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Plain XLA attention: softmax(q·kᵀ·scale + bias)·v. Masks are additive
    large-negative biases so shapes stay static for the compiler."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    neg = jnp.asarray(_NEG, scores.dtype)
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :] > 0, scores, neg)
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        qi = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0) + (tk - tq)
        ki = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        scores = jnp.where(qi >= ki, scores, neg)
    weights = jax.nn.softmax(scores, axis=-1)
    if mask is not None or causal:
        # Rows with no valid key output 0 (matching the flash kernel) rather
        # than softmax-of-constant uniform weights.
        any_valid = jnp.any(scores > _NEG * 0.5, axis=-1, keepdims=True)
        weights = jnp.where(any_valid, weights, 0.0)
    return jnp.einsum("bhqk,bhkv->bhqv", weights, v)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, m_scr, l_scr,
                  acc_scr, *, scale, block_q, block_k, causal, tk_offset):
    """One (batch·head, q-block, k-block) grid step.

    The k dimension is the innermost grid axis; TPU grids execute
    sequentially, so the VMEM scratch accumulators (running max /
    denominator / weighted sum) carry across k steps for a fixed q block.
    Only (block, d) tiles are ever resident in VMEM — Pallas pipelines the
    HBM→VMEM tile loads — so sequence length is bounded by HBM, not VMEM.
    """
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, d]
    ks = k_ref[0].astype(jnp.float32)  # [block_k, d]
    vs = v_ref[0].astype(jnp.float32)  # [block_k, dv]
    s = jax.lax.dot_general(
        q, ks, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)  # [block_q, block_k]
    mk = mask_ref[0, 0]  # [block_k]
    s = jnp.where(mk[None, :] > 0, s, _NEG)
    if causal:
        q_ids = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0) + tk_offset
        k_ids = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_ids >= k_ids, s, _NEG)

    m, l, acc = m_scr[...], l_scr[...], acc_scr[...]
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    # Zero masked entries explicitly: when a row is ENTIRELY masked,
    # m_new == _NEG and exp(s - m_new) == 1, which would weight masked
    # keys uniformly. Zeroing keeps l == 0 so the row output is 0 —
    # the defined semantics for fully-masked rows on both impls.
    p = jnp.where(s > _NEG * 0.5, p, 0.0)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jax.lax.dot_general(
        p, vs, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    m_scr[...], l_scr[...], acc_scr[...] = m_new, l_new, acc_new

    @pl.when(ki == pl.num_programs(2) - 1)
    def _():
        out = acc_new / jnp.maximum(l_new, 1e-30)  # fully-masked rows → 0
        o_ref[0] = out.astype(o_ref.dtype)


def _pad_to(x: jax.Array, axis: int, multiple: int, value=0.0) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _flash_forward(q, k, v, mask, causal, scale, block_q, block_k, interpret):
    if _VMEM is None:  # jaxlib without pallas TPU support: same math via XLA
        return mha_attention_reference(q, k, v, mask=mask, causal=causal,
                                       scale=scale)
    b, h, tq, d = q.shape
    tk, dv = k.shape[2], v.shape[3]
    block_q = min(block_q, max(tq, 1))
    block_k = min(block_k, max(tk, 1))

    if mask is None:
        mask = jnp.ones((b, tk), jnp.float32)
    # [b, 1, tk]: a leading singleton keeps the block's trailing two dims
    # equal to the array dims, satisfying the mosaic tiling constraint.
    mask = _pad_to(mask.astype(jnp.float32), 1, block_k, 0.0)[:, None, :]
    qp = _pad_to(q, 2, block_q)
    kp = _pad_to(k, 2, block_k)
    vp = _pad_to(v, 2, block_k)
    tq_p, tk_p = qp.shape[2], kp.shape[2]

    qp = qp.reshape(b * h, tq_p, d)
    kp = kp.reshape(b * h, tk_p, d)
    vp = vp.reshape(b * h, tk_p, dv)

    grid = (b * h, tq_p // block_q, tk_p // block_k)
    kern = functools.partial(
        _flash_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, tk_offset=tk - tq)
    kwargs = dict(memory_space=_VMEM)
    scratch = [
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, dv), jnp.float32),
    ]
    out = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0),
                         **kwargs),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0),
                         **kwargs),
            pl.BlockSpec((1, block_k, dv), lambda bh, qi, ki: (bh, ki, 0),
                         **kwargs),
            pl.BlockSpec((1, 1, block_k), lambda bh, qi, ki: (bh // h, 0, ki),
                         **kwargs),
        ],
        out_specs=pl.BlockSpec((1, block_q, dv),
                               lambda bh, qi, ki: (bh, qi, 0), **kwargs),
        out_shape=jax.ShapeDtypeStruct((b * h, tq_p, dv), q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(qp, kp, vp, mask)
    return out.reshape(b, h, tq_p, dv)[:, :, :tq, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, mask, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, mask, causal, scale, block_q, block_k,
                          interpret)


def _flash_fwd(q, k, v, mask, causal, scale, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, mask, causal, scale, block_q, block_k,
                         interpret)
    return out, (q, k, v, mask)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, mask = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: mha_attention_reference(
            q_, k_, v_, mask=mask, causal=causal, scale=scale), q, k, v)
    dq, dk, dv = vjp(g)
    dmask = None if mask is None else jnp.zeros_like(mask)
    return dq, dk, dv, dmask


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention over [b, h, t, d] tensors. ``mask`` is a [b, t_k]
    key-padding mask (1 = keep). Runs the Pallas kernel compiled on TPU and
    in interpreter mode elsewhere (the CPU test path)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, mask, causal, float(scale), block_q, block_k,
                  interpret)


def mha_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Dispatch through the helper seam: builtin XLA path by default, the
    Pallas flash kernel when selected (or automatically on TPU for sequences
    long enough that materialising q·kᵀ matters)."""
    impl = _IMPL
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        # Gate on the larger of tq/tk: the materialised score matrix is
        # tq×tk, so long keys with few queries (LearnedSelfAttention) also
        # benefit from k/v streaming.
        impl = ("flash" if (on_tpu and max(q.shape[2], k.shape[2]) >= 512)
                else "xla")
    if impl == "flash":
        return flash_attention(q, k, v, mask=mask, causal=causal, scale=scale)
    return mha_attention_reference(q, k, v, mask=mask, causal=causal,
                                   scale=scale)
