"""Sort-based MoE token dispatch — gather/scatter instead of one-hot einsums.

The GShard/Mesh-TF dense formulation (nn/layers/moe.py ``dispatch_mode=
"einsum"``) turns routing into two ``[tokens, E, capacity]`` one-hot
contractions. That keeps every shape static, but the dispatch einsum is
O(tokens · E · capacity · d) with capacity ≈ top_k·tokens·cf/E — quadratic
in the token count — and almost all of that "MXU work" multiplies zeros
(BENCH: 2.84× the grad-step cost of an equal-FLOPs dense FFN at
tokens=8192, E=8, top_k=2). GShard's successors (PAPERS.md: the MLPerf
TPU-pod scaling and cross-replica sharding reports) moved to gather/
scatter dispatch for exactly this reason.

This module keeps every shape static while replacing the contractions with
index arithmetic:

1. route with ONE ``jax.lax.top_k`` (``top_k_routing``);
2. assign capacity slots with a per-expert cumsum over the flat
   (round, token) assignment list (``make_dispatch_plan``) — round-major
   order reproduces the einsum path's first-come-first-served capacity
   contract bit-for-bit (round 0 of every token claims slots before
   round 1 of any token, tokens in batch order within a round);
3. permute tokens into the ``[E, C, d]`` expert buffer with one
   ``jnp.take`` (``gather_dispatch``) — the leading ``E`` dim is the same
   expert-parallel sharding axis the einsum path exposes, so
   ``DistributedTrainer`` expert sharding rules carry over unchanged;
4. combine expert outputs back to token order with a gate-weighted gather
   (``scatter_combine``; the name is the backward view — its transpose is
   the scatter).

Overflowing (token, round) assignments map to an out-of-range sentinel
slot, so the scatter drops them (``mode="drop"``) and the gathers fill
zeros (``mode="fill"``) — the exact GShard drop semantics: a dropped
assignment contributes nothing and the residual path carries the token.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


def top_k_routing(gates: jax.Array, top_k: int) -> Tuple[jax.Array, jax.Array]:
    """Route with a single ``jax.lax.top_k``.

    Returns ``(gate_vals [n, k], expert_idx [n, k])``, descending by gate
    with ties to the lower expert index — the same selection sequence as
    the legacy k-round argmax-and-mask loop, in one HLO op (and top_k's
    VJP scatters the gate gradient to the selected entries, matching the
    ``sum(gates * one_hot)`` gradient of the loop formulation).
    """
    return jax.lax.top_k(gates, top_k)


class DispatchPlan(NamedTuple):
    """Static-shape routing plan for one batch of ``n`` tokens.

    Flat ``[k*n]`` arrays index the round-major flattened assignment list:
    row ``r*n + t`` is round ``r``'s expert choice for token ``t``. ``E*C``
    in ``buffer_idx`` (resp. ``n`` in ``slot_token``) is the out-of-range
    sentinel for dropped assignments (resp. unfilled slots).
    """

    buffer_idx: jax.Array     # [k*n] int32: expert*C + slot; E*C = dropped
    keep: jax.Array           # [k*n] bool: assignment claimed a slot
    slot_token: jax.Array     # [E*C] int32: source token per slot; n = empty
    expert_tokens: jax.Array  # [E] int32: assignments kept per expert
    dropped_tokens: jax.Array  # [] int32: assignments dropped (overflow)


def make_dispatch_plan(
    expert_idx: jax.Array,
    num_experts: int,
    capacity: int,
    token_mask: Optional[jax.Array] = None,
) -> DispatchPlan:
    """Assign capacity slots: per-expert cumsum over the flat assignment
    list, first-come-first-served in (round, token) order.

    ``expert_idx`` is ``[n, k]`` int (from :func:`top_k_routing`).
    ``token_mask`` ``[n]`` (nonzero = real) excludes padding tokens
    entirely: they claim no capacity slot and appear in no expert buffer.
    """
    n, k = expert_idx.shape
    flat_expert = expert_idx.T.reshape(-1)  # [k*n], round-major
    onehot = (flat_expert[:, None]
              == jnp.arange(num_experts, dtype=flat_expert.dtype)[None, :]
              ).astype(jnp.int32)                              # [k*n, E]
    if token_mask is not None:
        valid = jnp.tile(token_mask > 0, k)                    # [k*n]
        onehot = onehot * valid[:, None].astype(jnp.int32)
    # running per-expert fill count at each flat row; invalid rows (masked
    # tokens) have an all-zero onehot row and land at -1 => never kept
    within = jnp.sum(jnp.cumsum(onehot, axis=0) * onehot, axis=1) - 1
    keep = (within >= 0) & (within < capacity)
    sentinel = num_experts * capacity
    buffer_idx = jnp.where(
        keep, flat_expert.astype(jnp.int32) * capacity + within.astype(jnp.int32),
        sentinel).astype(jnp.int32)
    flat_token = jnp.tile(jnp.arange(n, dtype=jnp.int32), k)
    # int scatter only — the inverse permutation; out-of-range (dropped)
    # rows vanish, kept rows hit distinct slots by construction
    slot_token = jnp.full((sentinel,), n, jnp.int32).at[buffer_idx].set(
        flat_token, mode="drop")
    kept = onehot * keep[:, None].astype(jnp.int32)
    expert_tokens = jnp.sum(kept, axis=0)
    dropped_tokens = jnp.sum(onehot) - jnp.sum(kept)
    return DispatchPlan(buffer_idx, keep, slot_token, expert_tokens,
                        dropped_tokens)


def gather_dispatch(x: jax.Array, plan: DispatchPlan, num_experts: int,
                    capacity: int) -> jax.Array:
    """Permute tokens ``[n, d]`` into the expert buffer ``[E, C, d]`` with
    one gather; unfilled slots read zeros (their combine weight is zero, so
    like the einsum path's zero rows they only feed the bias path, which
    the combine then discards)."""
    buf = jnp.take(x, plan.slot_token, axis=0, mode="fill", fill_value=0)
    return buf.reshape(num_experts, capacity, x.shape[-1])


def scatter_combine(out_e: jax.Array, gate_vals: jax.Array,
                    plan: DispatchPlan, *, renormalize: bool = True,
                    eps: float = 1e-9) -> jax.Array:
    """Combine expert outputs ``[E, C, o]`` back to token order ``[n, o]``.

    Each kept (round, token) assignment gathers its expert-buffer row and
    weights it by the (renormalized) gate; dropped assignments contribute
    zero. ``renormalize=True`` divides by the sum of KEPT gates per token,
    matching the einsum path: a token whose assignments all dropped gets
    exactly zero output (the residual path carries it).
    """
    e, c, o = out_e.shape
    rows = jnp.take(out_e.reshape(e * c, o), plan.buffer_idx, axis=0,
                    mode="fill", fill_value=0)                 # [k*n, o]
    return combine_rows(rows, gate_vals, plan.keep,
                        renormalize=renormalize, eps=eps)


def combine_rows(rows: jax.Array, gate_vals: jax.Array, keep: jax.Array,
                 *, renormalize: bool = True, eps: float = 1e-9) -> jax.Array:
    """Gate-weight per-assignment output rows ``[k*n, o]`` (round-major
    flat order) down to token order ``[n, o]`` — the combine arithmetic
    shared by every dispatch mode, factored out so ``"grouped"`` (which
    sources rows from the sorted grouped matmul instead of the ``[E, C]``
    buffer) is gate-math-identical to ``"sort"`` by construction."""
    n, k = gate_vals.shape
    o = rows.shape[-1]
    gate_flat = gate_vals.T.reshape(-1)                        # [k*n]
    kept_gate = jnp.where(keep, gate_flat, 0)
    if renormalize:
        denom = jnp.sum(kept_gate.reshape(k, n), axis=0)       # [n]
        kept_gate = kept_gate / jnp.tile(jnp.maximum(denom, eps), k)
    return jnp.sum((rows * kept_gate[:, None]).reshape(k, n, o), axis=0)
