"""Tier-1 wiring for tools/check_disagg_contract.py: the disaggregated
prefill/decode pipeline chaos contract (README.md "Disaggregated
serving") — a 2-host prefill→decode pipeline over real HTTP, prefill
host killed mid-burst, zero high-priority loss via queued decodes +
unified fallback, breaker-open within one window, role itemization and
disagg metric series — is enforced on every test run, not just when
someone remembers to run the tool."""

import os
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


def test_disagg_contract_smoke():
    sys.path.insert(0, _TOOLS)
    try:
        import check_disagg_contract
    finally:
        sys.path.remove(_TOOLS)
    assert check_disagg_contract.main(log=lambda m: None) == 0
