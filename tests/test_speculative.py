"""Speculative decoding (ISSUE 11).

The load-bearing contract is DISTRIBUTION EXACTNESS: exact acceptance
sampling (accept-or-resample against the target/draft probability ratio)
must keep the output law byte-identical to plain sampling under the same
``(seed, step)`` keying — greedy streams token-for-token identical to
non-speculative decode for every k, every prompt bucket, all the way to
the cache limit (where the k+1 window no longer fits and the boundary
fallback takes over) — plus cache rewind under rejection, the engine's
per-request ``speculative_k``, the decode-side AIMD controller, and the
slot-release regression for cancelled/expired bursts.
"""

import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.generate import (
    GenerationSession,
    SpeculativeGenerationSession,
    sample_tokens,
    speculative_accept,
)
from deeplearning4j_tpu.generate.sampling import _warped_probs
from deeplearning4j_tpu.model.zoo import TextGenerationLSTM, TransformerLM
from deeplearning4j_tpu.obs.metrics import MetricsRegistry
from deeplearning4j_tpu.parallel import DecodeAIMD, DecodeEngine


MAX_LEN = 16
VOCAB = 23


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(vocab_size=VOCAB, hidden=32, n_layers=2,
                         n_heads=4, max_len=MAX_LEN).init()


@pytest.fixture(scope="module")
def draft_lm():
    # deliberately uncorrelated with the target (different arch + seed):
    # acceptance is near-chance, so the rejection/rewind path dominates
    return TransformerLM(vocab_size=VOCAB, hidden=16, n_layers=1,
                         n_heads=2, max_len=MAX_LEN, seed=99).init()


# ---------------------------------------------------------------------------
# the acceptance primitive
# ---------------------------------------------------------------------------


class TestAcceptPrimitive:
    def test_closed_form_exactness(self):
        """The accept-or-resample law emits exactly the target
        distribution: q(x)·min(1, p/q)(x) + P(reject)·residual == p,
        for arbitrary draft/target pairs (the algorithm's defining
        identity, checked in float64)."""
        rng = np.random.RandomState(0)
        for _ in range(20):
            p = rng.dirichlet(np.ones(11))
            q = rng.dirichlet(np.ones(11))
            accept = q * np.minimum(1.0, p / np.maximum(q, 1e-300))
            p_reject = 1.0 - accept.sum()
            resid = np.maximum(p - q, 0.0)
            resid = resid / resid.sum() if resid.sum() > 0 else p
            emitted = accept + p_reject * resid
            np.testing.assert_allclose(emitted, p, atol=1e-12)

    def test_monte_carlo_marginal_matches_target(self):
        """The jitted primitive's first-emitted-token marginal equals the
        warped target distribution (deterministic: fixed seed ensemble),
        under temperature + top-p warping."""
        rng = np.random.RandomState(1)
        V, B = 8, 4000
        zt = rng.randn(V).astype(np.float32)
        zd = rng.randn(V).astype(np.float32)
        seeds = jnp.arange(B, dtype=jnp.uint32)
        steps = jnp.zeros((B,), jnp.int32)
        gmask = jnp.zeros((B,), bool)
        temps = jnp.full((B,), 0.9, jnp.float32)
        ks = jnp.zeros((B,), jnp.int32)
        ps = jnp.full((B,), 0.95, jnp.float32)
        d_logits = jnp.broadcast_to(jnp.asarray(zd), (B, 1, V))
        d_toks = sample_tokens(d_logits[:, 0], seeds, steps, gmask, temps,
                               ks, ps)[:, None]
        t_logits = jnp.broadcast_to(jnp.asarray(zt), (B, 2, V))
        toks, n_acc, n_emit = speculative_accept(
            d_toks, d_logits, t_logits, seeds, steps,
            jnp.ones((B,), jnp.int32), gmask, temps, ks, ps)
        assert np.array_equal(np.asarray(n_emit), np.asarray(n_acc) + 1)
        emp = np.bincount(np.asarray(toks[:, 0]), minlength=V) / B
        pt = np.asarray(_warped_probs(
            jnp.asarray(zt), jnp.asarray(False), jnp.asarray(0.9),
            jnp.asarray(0), jnp.asarray(0.95)))
        assert 0.5 * np.abs(emp - pt).sum() < 0.05

    def test_greedy_rows_accept_iff_argmax_matches(self):
        rng = np.random.RandomState(2)
        V, K = 9, 3
        t_logits = jnp.asarray(rng.randn(2, K + 1, V), jnp.float32)
        d_logits = jnp.asarray(rng.randn(2, K, V), jnp.float32)
        t_argmax = np.asarray(jnp.argmax(t_logits, axis=-1))
        # row 0 proposes the target's argmax everywhere -> full accept +
        # bonus; row 1 mismatches at position 0 -> correction emitted
        d_toks = np.stack([t_argmax[0, :K],
                           (t_argmax[1, :K] + 1) % V]).astype(np.int32)
        toks, n_acc, n_emit = speculative_accept(
            jnp.asarray(d_toks), d_logits, t_logits,
            jnp.asarray([5, 5], jnp.uint32), jnp.zeros((2,), jnp.int32),
            jnp.full((2,), K, jnp.int32), jnp.ones((2,), bool),
            jnp.ones((2,), jnp.float32), jnp.zeros((2,), jnp.int32),
            jnp.ones((2,), jnp.float32))
        assert int(n_acc[0]) == K and int(n_emit[0]) == K + 1
        assert np.asarray(toks[0]).tolist() == t_argmax[0].tolist()
        assert int(n_acc[1]) == 0 and int(n_emit[1]) == 1
        assert int(toks[1, 0]) == int(t_argmax[1, 0])

    def test_k0_row_reproduces_plain_sampler(self):
        """spec_ks == 0 degenerates to plain sampling with the SAME
        (seed, step) key — a non-speculative request inside a speculative
        batch keeps its exact stream."""
        rng = np.random.RandomState(3)
        V = 12
        t_logits = jnp.asarray(rng.randn(6, 2, V), jnp.float32)
        d_logits = jnp.asarray(rng.randn(6, 1, V), jnp.float32)
        seeds = jnp.arange(6, dtype=jnp.uint32)
        steps = jnp.full((6,), 4, jnp.int32)
        gmask = jnp.zeros((6,), bool)
        temps = jnp.full((6,), 0.8, jnp.float32)
        ks = jnp.full((6,), 5, jnp.int32)
        ps = jnp.ones((6,), jnp.float32)
        toks, n_acc, n_emit = speculative_accept(
            jnp.zeros((6, 1), jnp.int32), d_logits, t_logits, seeds, steps,
            jnp.zeros((6,), jnp.int32), gmask, temps, ks, ps)
        plain = sample_tokens(t_logits[:, 0], seeds, steps, gmask, temps,
                              ks, ps)
        assert np.asarray(n_acc).tolist() == [0] * 6
        assert np.asarray(toks[:, 0]).tolist() == np.asarray(plain).tolist()


# ---------------------------------------------------------------------------
# SpeculativeGenerationSession
# ---------------------------------------------------------------------------


class TestSpeculativeSession:
    def test_greedy_identity_across_buckets_and_k(self, lm, draft_lm):
        """Greedy speculative == plain greedy token-for-token, for k in
        {1, 2, 4}, prompts straddling bucket boundaries, run to the cache
        limit (exercising the boundary fallback AND heavy rejection /
        cache rewind — the draft is uncorrelated with the target)."""
        plain = GenerationSession(lm, max_len=MAX_LEN)
        prompts = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 3, 1, 4, 1, 5, 9, 2]]
        ref = plain.generate(prompts, MAX_LEN, greedy=True)
        for k in (1, 2, 4):
            spec = SpeculativeGenerationSession(lm, draft_lm,
                                                max_len=MAX_LEN, k=k)
            got = spec.generate(prompts, MAX_LEN, greedy=True)
            assert got == ref, f"k={k}: {got} != {ref}"
            st = spec.last_stats
            assert st["spec_steps"] > 0 and st["proposed"] > 0

    def test_greedy_identity_full_acceptance(self, lm):
        """Draft == target: every proposal accepted (the bonus-token
        path), stream still identical and accepted/step == k + 1."""
        plain = GenerationSession(lm, max_len=MAX_LEN)
        spec = SpeculativeGenerationSession(lm, lm, max_len=MAX_LEN, k=2)
        prompts = [[1, 2, 3]]
        n = 9  # stays clear of max_len so every step is a full window
        assert spec.generate(prompts, n, greedy=True) \
            == plain.generate(prompts, n, greedy=True)
        st = spec.last_stats
        assert st["acceptance_rate"] == 1.0
        assert st["accepted_per_step"] == 3.0

    def test_sampled_deterministic_and_batch_independent(self, lm, draft_lm):
        spec = SpeculativeGenerationSession(lm, draft_lm, max_len=MAX_LEN,
                                            k=2)
        kw = dict(greedy=False, temperature=0.9, top_k=8, seed=42)
        a = spec.generate([[1, 2, 3]], 6, **kw)
        b = spec.generate([[1, 2, 3]], 6, **kw)
        assert a == b
        # the same row, batched with a neighbor, keeps its exact stream
        both = spec.generate([[1, 2, 3], [4, 5]], 6, **kw)
        assert both[0] == a[0]

    DIST_B = 512
    DIST_KW = dict(greedy=False, temperature=0.8, top_k=8, top_p=0.95,
                   seed=0)

    @pytest.fixture(scope="class")
    def dist_ref(self, lm):
        plain = GenerationSession(lm, max_len=MAX_LEN)
        return plain.generate([[1, 2, 3]] * self.DIST_B, 2, **self.DIST_KW)

    @pytest.mark.parametrize("k", [1, 2, 4])
    def test_sampled_distribution_equivalence(self, lm, draft_lm, dist_ref,
                                              k):
        """temperature/top-p speculative sampling matches plain sampling
        in distribution under the same (seed, step) keys: over a fixed
        512-seed ensemble (one batched call, rows = seeds), the
        first-speculative-token empirical distribution matches plain
        decode's. Deterministic — fixed seeds, no flake."""
        B = self.DIST_B
        prompts = [[1, 2, 3]] * B
        ref = dist_ref
        spec = SpeculativeGenerationSession(lm, draft_lm, max_len=MAX_LEN,
                                            k=k)
        got = spec.generate(prompts, 2, **self.DIST_KW)
        # token 0 comes from the (shared) prefill sampler: exact equality
        assert [r[0] for r in got] == [r[0] for r in ref]
        emp_ref = np.bincount([r[1] for r in ref], minlength=VOCAB) / B
        emp_got = np.bincount([r[1] for r in got], minlength=VOCAB) / B
        tv = 0.5 * np.abs(emp_ref - emp_got).sum()
        assert tv < 0.15, f"k={k}: TV {tv}"

    def test_recurrent_models_rejected(self, lm):
        lstm = TextGenerationLSTM(vocab_size=VOCAB, hidden=16,
                                  layers=1).init()
        with pytest.raises(ValueError, match="position-indexed"):
            SpeculativeGenerationSession(lstm, lstm, max_len=MAX_LEN)
        with pytest.raises(ValueError, match="position-indexed"):
            SpeculativeGenerationSession(lm, lstm, max_len=MAX_LEN)

    def test_vocab_mismatch_rejected(self, lm):
        other = TransformerLM(vocab_size=VOCAB + 1, hidden=16, n_layers=1,
                              n_heads=2, max_len=MAX_LEN).init()
        with pytest.raises(ValueError, match="vocab"):
            SpeculativeGenerationSession(lm, other, max_len=MAX_LEN)


# ---------------------------------------------------------------------------
# DecodeEngine with a draft model
# ---------------------------------------------------------------------------


class TestSpeculativeEngine:
    def _engine(self, lm, draft, **kw):
        reg = kw.pop("registry", MetricsRegistry())
        return DecodeEngine(lm, draft_model=draft, max_len=MAX_LEN,
                            registry=reg, **kw), reg

    def test_matches_plain_engine_mixed_k(self, lm, draft_lm):
        """Speculative engine greedy output == plain session, with
        per-request speculative_k (0 = plain decode) mixed in one batch
        and one request running to the cache limit."""
        eng, _ = self._engine(lm, draft_lm, speculative_k=3, slots=4,
                              name="spec-eq")
        try:
            handles = [eng.submit([1, 2, 3], max_tokens=6),
                       eng.submit([4, 5, 6, 7, 8], max_tokens=6,
                                  speculative_k=1),
                       eng.submit([2, 2], max_tokens=6, speculative_k=0),
                       eng.submit([9, 3, 1], max_tokens=MAX_LEN)]
            got = [h.result(timeout=180) for h in handles]
        finally:
            eng.shutdown()
        sess = GenerationSession(lm, max_len=MAX_LEN)
        full = sess.generate([[1, 2, 3], [4, 5, 6, 7, 8], [2, 2],
                              [9, 3, 1]], MAX_LEN, greedy=True)
        exp = [full[0][:6], full[1][:6], full[2][:6], full[3]]
        assert got == exp

    def test_staggered_arrival(self, lm, draft_lm):
        eng, _ = self._engine(lm, draft_lm, speculative_k=2, slots=4,
                              name="spec-stagger")
        try:
            h1 = eng.submit([1, 2, 3], max_tokens=10)
            ev = iter(h1.events(timeout=60))
            for _ in range(3):
                next(ev)
            h2 = eng.submit([4, 5, 6, 7, 8], max_tokens=6)
            got1 = h1.result(timeout=180)
            got2 = h2.result(timeout=180)
        finally:
            eng.shutdown()
        sess = GenerationSession(lm, max_len=MAX_LEN)
        assert got1 == sess.generate([[1, 2, 3]], 10, greedy=True)[0]
        assert got2 == sess.generate([[4, 5, 6, 7, 8]], 6, greedy=True)[0]

    def test_slot_release_regression(self, lm, draft_lm):
        """ISSUE 11 small fix: a burst of cancelled/expired requests —
        mid-speculation AND still queued — releases every draft/target
        cache slot and admission slot; full capacity serves afterwards."""
        gate = {"delay": 0.05}
        eng, _ = self._engine(lm, draft_lm, speculative_k=2, slots=2,
                              queue_limit=6, name="spec-leak",
                              step_hook=lambda: time.sleep(gate["delay"]))
        try:
            long = [eng.submit([1, 2, 3], max_tokens=MAX_LEN - 4)
                    for _ in range(2)]
            queued = [eng.submit([4, 5], max_tokens=4, timeout=0.2)
                      for _ in range(4)]
            # both slots decoding, four waiting
            deadline = time.monotonic() + 30
            while eng.stats()["active_slots"] < 2:
                assert time.monotonic() < deadline
                time.sleep(0.01)
            for h in long:
                h.cancel()
            # queued requests expire in place (0.2s deadline) without
            # ever reaching a slot; cancelled actives free mid-window
            deadline = time.monotonic() + 60
            while eng.stats()["in_flight"] > 0:
                assert time.monotonic() < deadline, eng.stats()
                time.sleep(0.02)
            gate["delay"] = 0.0
            s = eng.stats()
            assert s["active_slots"] == 0 and s["in_flight"] == 0
            assert s["cancelled"] >= 2
            for h in queued:
                h.result(timeout=10)  # all terminal (deadline/cancel)
            # recovered: full capacity (slots + queue) completes
            again = [eng.submit([6, 7], max_tokens=3) for _ in range(6)]
            for h in again:
                assert len(h.result(timeout=180)) == 3
            assert eng.stats()["in_flight"] == 0
        finally:
            eng.shutdown()

    def test_stats_zero_guarded_and_metrics(self, lm, draft_lm):
        reg = MetricsRegistry()
        eng, _ = self._engine(lm, draft_lm, speculative_k=2, slots=2,
                              name="spec-stats", registry=reg)
        try:
            s = eng.stats()
            assert s["speculative"]["enabled"] is True
            assert s["speculative"]["current_k"] == 2
            assert s["speculative"]["acceptance_rate"] is None
            assert s["speculative"]["accepted_tokens_per_step"] is None
            assert s["per_token_p95_s"] is None
            assert s["slot_target"] == 2
            eng.submit([1, 2, 3], max_tokens=5).result(timeout=180)
            s = eng.stats()
            assert s["speculative"]["proposed"] > 0
            assert s["speculative"]["acceptance_rate"] is not None
            assert s["per_token_p95_s"] is not None
        finally:
            eng.shutdown()
        from deeplearning4j_tpu.obs.prom import render_prometheus

        text = render_prometheus(reg)
        for series in ("dl4j_tpu_generate_spec_proposed_total",
                       "dl4j_tpu_generate_spec_accepted_total",
                       "dl4j_tpu_generate_spec_steps_total",
                       "dl4j_tpu_generate_speculative_k",
                       "dl4j_tpu_generate_slot_target",
                       "dl4j_tpu_generate_token_latency_seconds"):
            assert series in text, f"missing {series}"

    def test_plain_engine_unchanged(self, lm):
        """No draft model: speculative surface reports disabled and the
        engine path is the PR-9 one."""
        eng = DecodeEngine(lm, max_len=MAX_LEN, slots=2,
                           registry=MetricsRegistry(), name="no-spec")
        try:
            s = eng.stats()
            assert s["speculative"]["enabled"] is False
            assert s["speculative"]["current_k"] == 0
            assert eng.speculative_k == 0
        finally:
            eng.shutdown()


# ---------------------------------------------------------------------------
# decode-side AIMD
# ---------------------------------------------------------------------------


class TestDecodeAIMD:
    @pytest.fixture()
    def eng(self, lm, draft_lm):
        e = DecodeEngine(lm, draft_model=draft_lm, speculative_k=4,
                         max_len=MAX_LEN, slots=8,
                         registry=MetricsRegistry(), name="aimd")
        yield e
        e.shutdown(drain=False)

    def test_no_traffic_no_action(self, eng):
        assert eng.adjust() is None

    def test_breach_shrinks_k_and_slots(self, eng):
        ctl = DecodeAIMD(eng, target_p95_s=0.05)
        for _ in range(20):
            eng._h_token.observe(0.2)  # way over budget
        obs = ctl.tick()
        assert obs["action"] == "shrink"
        assert eng.speculative_k == 2 and eng.slot_target == 4
        for _ in range(20):
            eng._h_token.observe(0.2)
        ctl.tick()
        ctl_obs = ctl.tick()  # no new traffic between ticks -> None
        assert ctl_obs is None
        assert eng.speculative_k == 1 and eng.slot_target == 2

    def test_under_budget_grows_slots_then_k(self, eng):
        ctl = DecodeAIMD(eng, target_p95_s=0.05)
        eng.set_decode_control(2, 4)
        # fake queued demand: admitted-but-unplaced requests
        eng._admission.max_pending = 100
        for _ in range(3):
            eng._admission.admit()
        for _ in range(20):
            eng._h_token.observe(0.001)
        obs = ctl.tick()
        assert obs["action"] == "grow_slots"
        assert eng.slot_target == 5 and eng.speculative_k == 2
        for _ in range(3):
            eng._admission.release()
        for _ in range(20):
            eng._h_token.observe(0.001)
        obs = ctl.tick()
        assert obs["action"] == "grow_k"
        assert eng.speculative_k == 3 and eng.slot_target == 5

    def test_hold_at_max(self, eng):
        ctl = DecodeAIMD(eng, target_p95_s=0.05)
        eng.set_decode_control(4, 8)
        for _ in range(20):
            eng._h_token.observe(0.001)
        assert ctl.tick()["action"] == "hold"
        assert eng.speculative_k == 4 and eng.slot_target == 8

    def test_control_clamps(self, eng):
        assert eng.set_decode_control(99, 99) == (4, 8)
        assert eng.set_decode_control(0, 0) == (1, 1)

    def test_adaptive_loop_ticks(self, lm, draft_lm):
        """adaptive=True: the engine loop itself ticks the controller
        (observable as a k shrink under an artificially slow step)."""
        eng = DecodeEngine(lm, draft_model=draft_lm, speculative_k=4,
                           max_len=MAX_LEN, slots=2,
                           adaptive=True, target_p95_s=1e-4,
                           adjust_interval=0.05,
                           registry=MetricsRegistry(), name="aimd-loop")
        try:
            eng.submit([1, 2, 3], max_tokens=MAX_LEN - 4).result(timeout=180)
            deadline = time.monotonic() + 30
            while eng.speculative_k == 4:
                if time.monotonic() > deadline:
                    break
                eng.submit([1, 2], max_tokens=4).result(timeout=180)
            assert eng.speculative_k < 4
        finally:
            eng.shutdown()
