"""ONNX import golden tests (VERDICT.md round 3 ask 4).

No ``onnx`` package exists in this environment (and torch.onnx.export
requires it), so fixtures are genuine ONNX ModelProtos built directly with
the vendored protoc schema — byte-identical to what a serializer would
produce — and golden outputs come from an INDEPENDENT implementation of the
same math (torch CPU functional ops on the same weights). Two golden
models: a ResNet-style residual CNN and a BERT-style transformer encoder
block (the two families SURVEY.md:119 names for the reference importer).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

from deeplearning4j_tpu.modelimport.onnx import OnnxGraphMapper, tensor_to_numpy  # noqa: E402
from deeplearning4j_tpu.modelimport.onnx_proto import onnx_pb2 as P  # noqa: E402


# ---------------------------------------------------------------------------
# ModelProto builder (the serializer side of the fixture)
# ---------------------------------------------------------------------------

_NP_TO_ONNX = {np.dtype(np.float32): 1, np.dtype(np.int64): 7,
               np.dtype(np.int32): 6, np.dtype(np.float64): 11,
               np.dtype(np.bool_): 9, np.dtype(np.int8): 3,
               np.dtype(np.uint8): 2}


def make_tensor(name: str, arr: np.ndarray) -> P.TensorProto:
    t = P.TensorProto()
    t.name = name
    t.data_type = _NP_TO_ONNX[np.dtype(arr.dtype)]
    t.dims.extend(arr.shape)
    t.raw_data = np.ascontiguousarray(arr).tobytes()
    return t


def make_attr(name: str, value) -> P.AttributeProto:
    a = P.AttributeProto()
    a.name = name
    if isinstance(value, bool):
        a.type, a.i = P.AttributeProto.INT, int(value)
    elif isinstance(value, int):
        a.type, a.i = P.AttributeProto.INT, value
    elif isinstance(value, float):
        a.type, a.f = P.AttributeProto.FLOAT, value
    elif isinstance(value, str):
        a.type, a.s = P.AttributeProto.STRING, value.encode()
    elif isinstance(value, np.ndarray):
        a.type = P.AttributeProto.TENSOR
        a.t.CopyFrom(make_tensor("", value))
    elif isinstance(value, (list, tuple)) and all(isinstance(v, int) for v in value):
        a.type = P.AttributeProto.INTS
        a.ints.extend(value)
    elif isinstance(value, (list, tuple)):
        a.type = P.AttributeProto.FLOATS
        a.floats.extend(float(v) for v in value)
    else:
        raise TypeError(f"attr {name}: {type(value)}")
    return a


def make_node(op: str, inputs, outputs, **attrs) -> P.NodeProto:
    n = P.NodeProto()
    n.op_type = op
    n.name = outputs[0]
    n.input.extend(inputs)
    n.output.extend(outputs)
    for k, v in attrs.items():
        n.attribute.append(make_attr(k, v))
    return n


def make_vi(name: str, dtype: np.dtype, shape) -> P.ValueInfoProto:
    vi = P.ValueInfoProto()
    vi.name = name
    tt = vi.type.tensor_type
    tt.elem_type = _NP_TO_ONNX[np.dtype(dtype)]
    for d in shape:
        dim = tt.shape.dim.add()
        dim.dim_value = d
    return vi


def make_model(nodes, inputs, outputs, initializers, opset: int = 17) -> bytes:
    m = P.ModelProto()
    m.ir_version = 8
    m.producer_name = "dl4j-tpu-test"
    op = m.opset_import.add()
    op.domain = ""
    op.version = opset
    g = m.graph
    g.name = "g"
    g.node.extend(nodes)
    g.input.extend(inputs)
    g.output.extend(outputs)
    g.initializer.extend(initializers)
    return m.SerializeToString()


def test_tensor_roundtrip():
    arr = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    np.testing.assert_array_equal(tensor_to_numpy(make_tensor("x", arr)), arr)


# ---------------------------------------------------------------------------
# golden model 1: ResNet-style residual CNN
# ---------------------------------------------------------------------------

def _resnet_style_fixture(rng):
    """Conv-BN-Relu-MaxPool stem, one residual block, GAP-Flatten-Gemm head."""
    p = {
        "w0": rng.randn(8, 3, 3, 3).astype(np.float32) * 0.2,
        "b0": rng.randn(8).astype(np.float32) * 0.1,
        "bn0_s": rng.rand(8).astype(np.float32) + 0.5,
        "bn0_b": rng.randn(8).astype(np.float32) * 0.1,
        "bn0_m": rng.randn(8).astype(np.float32) * 0.1,
        "bn0_v": rng.rand(8).astype(np.float32) + 0.5,
        "w1": rng.randn(8, 8, 3, 3).astype(np.float32) * 0.2,
        "bn1_s": rng.rand(8).astype(np.float32) + 0.5,
        "bn1_b": rng.randn(8).astype(np.float32) * 0.1,
        "bn1_m": rng.randn(8).astype(np.float32) * 0.1,
        "bn1_v": rng.rand(8).astype(np.float32) + 0.5,
        "w2": rng.randn(8, 8, 3, 3).astype(np.float32) * 0.2,
        "bn2_s": rng.rand(8).astype(np.float32) + 0.5,
        "bn2_b": rng.randn(8).astype(np.float32) * 0.1,
        "bn2_m": rng.randn(8).astype(np.float32) * 0.1,
        "bn2_v": rng.rand(8).astype(np.float32) + 0.5,
        "wfc": rng.randn(8, 5).astype(np.float32) * 0.3,
        "bfc": rng.randn(5).astype(np.float32) * 0.1,
    }
    nodes = [
        make_node("Conv", ["x", "w0", "b0"], ["c0"], kernel_shape=[3, 3],
                  pads=[1, 1, 1, 1], strides=[1, 1]),
        make_node("BatchNormalization",
                  ["c0", "bn0_s", "bn0_b", "bn0_m", "bn0_v"], ["n0"],
                  epsilon=1e-5),
        make_node("Relu", ["n0"], ["r0"]),
        make_node("MaxPool", ["r0"], ["p0"], kernel_shape=[2, 2], strides=[2, 2]),
        # residual block
        make_node("Conv", ["p0", "w1"], ["c1"], kernel_shape=[3, 3],
                  pads=[1, 1, 1, 1]),
        make_node("BatchNormalization",
                  ["c1", "bn1_s", "bn1_b", "bn1_m", "bn1_v"], ["n1"],
                  epsilon=1e-5),
        make_node("Relu", ["n1"], ["r1"]),
        make_node("Conv", ["r1", "w2"], ["c2"], kernel_shape=[3, 3],
                  pads=[1, 1, 1, 1]),
        make_node("BatchNormalization",
                  ["c2", "bn2_s", "bn2_b", "bn2_m", "bn2_v"], ["n2"],
                  epsilon=1e-5),
        make_node("Add", ["p0", "n2"], ["res"]),
        make_node("Relu", ["res"], ["r2"]),
        # head
        make_node("GlobalAveragePool", ["r2"], ["gap"]),
        make_node("Flatten", ["gap"], ["flat"], axis=1),
        make_node("Gemm", ["flat", "wfc", "bfc"], ["y"], alpha=1.0, beta=1.0),
    ]
    model = make_model(
        nodes,
        inputs=[make_vi("x", np.float32, (2, 3, 16, 16))],
        outputs=[make_vi("y", np.float32, (2, 5))],
        initializers=[make_tensor(k, v) for k, v in p.items()],
    )
    return model, p


def _torch_resnet_style(p, x):
    """Independent reference implementation of the fixture graph."""
    t = {k: torch.from_numpy(v) for k, v in p.items()}
    h = F.conv2d(torch.from_numpy(x), t["w0"], t["b0"], padding=1)
    h = F.batch_norm(h, t["bn0_m"], t["bn0_v"], t["bn0_s"], t["bn0_b"], eps=1e-5)
    h = F.relu(h)
    h = F.max_pool2d(h, 2, 2)
    r = F.conv2d(h, t["w1"], padding=1)
    r = F.batch_norm(r, t["bn1_m"], t["bn1_v"], t["bn1_s"], t["bn1_b"], eps=1e-5)
    r = F.relu(r)
    r = F.conv2d(r, t["w2"], padding=1)
    r = F.batch_norm(r, t["bn2_m"], t["bn2_v"], t["bn2_s"], t["bn2_b"], eps=1e-5)
    h = F.relu(h + r)
    h = h.mean(dim=(2, 3))
    return (h @ t["wfc"] + t["bfc"]).numpy()


def test_onnx_resnet_style_golden():
    rng = np.random.RandomState(0)
    model_bytes, params = _resnet_style_fixture(rng)
    x = rng.randn(2, 3, 16, 16).astype(np.float32)
    expected = _torch_resnet_style(params, x)

    sd = OnnxGraphMapper.import_model(model_bytes, outputs=["y"])
    got = np.asarray(sd.output({"x": x}, ["y"])["y"])
    assert got.shape == expected.shape
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-4)


def test_onnx_resnet_style_full_graph_compiles():
    rng = np.random.RandomState(1)
    model_bytes, params = _resnet_style_fixture(rng)
    x = rng.randn(2, 3, 16, 16).astype(np.float32)
    sd = OnnxGraphMapper.import_model(model_bytes, outputs=["y"])
    compiled = sd.compile({"x": x}, ["y"])
    out = compiled(dict(sd._values), {"x": x})
    np.testing.assert_allclose(
        np.asarray(out["y"]), _torch_resnet_style(params, x), rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# golden model 2: BERT-style transformer encoder block
# ---------------------------------------------------------------------------

def _bert_style_fixture(rng, vocab=100, hidden=16, heads=2, seq=8, batch=2, ffn=32):
    hd = hidden // heads
    p = {
        "emb": rng.randn(vocab, hidden).astype(np.float32) * 0.2,
        "wq": rng.randn(hidden, hidden).astype(np.float32) * 0.2,
        "wk": rng.randn(hidden, hidden).astype(np.float32) * 0.2,
        "wv": rng.randn(hidden, hidden).astype(np.float32) * 0.2,
        "wo": rng.randn(hidden, hidden).astype(np.float32) * 0.2,
        "bq": rng.randn(hidden).astype(np.float32) * 0.1,
        "bk": rng.randn(hidden).astype(np.float32) * 0.1,
        "bv": rng.randn(hidden).astype(np.float32) * 0.1,
        "bo": rng.randn(hidden).astype(np.float32) * 0.1,
        "ln1_g": rng.rand(hidden).astype(np.float32) + 0.5,
        "ln1_b": rng.randn(hidden).astype(np.float32) * 0.1,
        "wf1": rng.randn(hidden, ffn).astype(np.float32) * 0.2,
        "bf1": rng.randn(ffn).astype(np.float32) * 0.1,
        "wf2": rng.randn(ffn, hidden).astype(np.float32) * 0.2,
        "bf2": rng.randn(hidden).astype(np.float32) * 0.1,
        "ln2_g": rng.rand(hidden).astype(np.float32) + 0.5,
        "ln2_b": rng.randn(hidden).astype(np.float32) * 0.1,
        # shape/scale constants the exporters emit as initializers
        "split_shape": np.asarray([batch, seq, heads, hd], np.int64),
        "merge_shape": np.asarray([batch, seq, hidden], np.int64),
        "scale": np.asarray(1.0 / np.sqrt(hd), np.float32),
        "half": np.asarray(0.5, np.float32),
        "one": np.asarray(1.0, np.float32),
        "inv_sqrt2": np.asarray(1.0 / np.sqrt(2.0), np.float32),
    }

    def proj(x, w, b, out):
        return [make_node("MatMul", [x, w], [f"{out}_mm"]),
                make_node("Add", [f"{out}_mm", b], [out])]

    def heads_split(x, out):  # [b,s,h] -> [b,heads,s,hd]
        return [make_node("Reshape", [x, "split_shape"], [f"{out}_r"]),
                make_node("Transpose", [f"{out}_r"], [out], perm=[0, 2, 1, 3])]

    nodes = [
        make_node("Gather", ["emb", "ids"], ["x0"], axis=0),
        *proj("x0", "wq", "bq", "q"), *heads_split("q", "qh"),
        *proj("x0", "wk", "bk", "k"), *heads_split("k", "kh"),
        *proj("x0", "wv", "bv", "v"), *heads_split("v", "vh"),
        make_node("Transpose", ["kh"], ["kt"], perm=[0, 1, 3, 2]),
        make_node("MatMul", ["qh", "kt"], ["scores_raw"]),
        make_node("Mul", ["scores_raw", "scale"], ["scores"]),
        make_node("Softmax", ["scores"], ["probs"], axis=-1),
        make_node("MatMul", ["probs", "vh"], ["ctx_h"]),
        make_node("Transpose", ["ctx_h"], ["ctx_t"], perm=[0, 2, 1, 3]),
        make_node("Reshape", ["ctx_t", "merge_shape"], ["ctx"]),
        *proj("ctx", "wo", "bo", "attn_out"),
        make_node("Add", ["x0", "attn_out"], ["res1"]),
        make_node("LayerNormalization", ["res1", "ln1_g", "ln1_b"], ["ln1"],
                  axis=-1, epsilon=1e-5),
        # FFN with exact erf-GELU, spelled out the way exporters decompose it
        *proj("ln1", "wf1", "bf1", "f1"),
        make_node("Mul", ["f1", "inv_sqrt2"], ["f1_s"]),
        make_node("Erf", ["f1_s"], ["f1_erf"]),
        make_node("Add", ["f1_erf", "one"], ["f1_e1"]),
        make_node("Mul", ["f1", "f1_e1"], ["f1_xe"]),
        make_node("Mul", ["f1_xe", "half"], ["gelu"]),
        *proj("gelu", "wf2", "bf2", "f2"),
        make_node("Add", ["ln1", "f2"], ["res2"]),
        make_node("LayerNormalization", ["res2", "ln2_g", "ln2_b"], ["out"],
                  axis=-1, epsilon=1e-5),
    ]
    model = make_model(
        nodes,
        inputs=[make_vi("ids", np.int64, (batch, seq))],
        outputs=[make_vi("out", np.float32, (batch, seq, hidden))],
        initializers=[make_tensor(k, v) for k, v in p.items()],
    )
    return model, p


def _torch_bert_style(p, ids, heads=2):
    t = {k: torch.from_numpy(np.asarray(v)) for k, v in p.items()}
    x0 = t["emb"][torch.from_numpy(ids)]
    b, s, h = x0.shape
    hd = h // heads

    def split(x):
        return x.reshape(b, s, heads, hd).permute(0, 2, 1, 3)

    q = split(x0 @ t["wq"] + t["bq"])
    k = split(x0 @ t["wk"] + t["bk"])
    v = split(x0 @ t["wv"] + t["bv"])
    probs = torch.softmax(q @ k.transpose(-1, -2) / np.sqrt(hd), dim=-1)
    ctx = (probs @ v).permute(0, 2, 1, 3).reshape(b, s, h)
    res1 = x0 + ctx @ t["wo"] + t["bo"]
    ln1 = F.layer_norm(res1, (h,), t["ln1_g"], t["ln1_b"], eps=1e-5)
    f1 = ln1 @ t["wf1"] + t["bf1"]
    gelu = 0.5 * f1 * (1.0 + torch.erf(f1 / np.sqrt(2.0)))
    res2 = ln1 + gelu @ t["wf2"] + t["bf2"]
    return F.layer_norm(res2, (h,), t["ln2_g"], t["ln2_b"], eps=1e-5).numpy()


def test_onnx_bert_style_golden():
    rng = np.random.RandomState(2)
    model_bytes, params = _bert_style_fixture(rng)
    ids = rng.randint(0, 100, (2, 8)).astype(np.int64)
    expected = _torch_bert_style(params, ids)

    sd = OnnxGraphMapper.import_model(model_bytes, outputs=["out"])
    got = np.asarray(sd.output({"ids": ids}, ["out"])["out"])
    assert got.shape == expected.shape
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-4)


def test_onnx_bert_style_full_graph_compiles():
    rng = np.random.RandomState(3)
    model_bytes, params = _bert_style_fixture(rng)
    ids = rng.randint(0, 100, (2, 8)).astype(np.int64)
    sd = OnnxGraphMapper.import_model(model_bytes, outputs=["out"])
    compiled = sd.compile({"ids": ids}, ["out"])
    out = compiled(dict(sd._values), {"ids": ids})
    np.testing.assert_allclose(
        np.asarray(out["out"]), _torch_bert_style(params, ids),
        rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# op-level coverage beyond the two golden models
# ---------------------------------------------------------------------------

def _run_single(op, inputs, outputs=("y",), input_arrays=None, opset=17, **attrs):
    arrays = dict(input_arrays or {})
    inits = [make_tensor(k, v) for k, v in arrays.items() if k not in ("x",)]
    vis = [make_vi("x", arrays["x"].dtype, arrays["x"].shape)]
    model = make_model([make_node(op, list(inputs), list(outputs), **attrs)],
                       inputs=vis, outputs=[], initializers=inits, opset=opset)
    sd = OnnxGraphMapper.import_model(model)
    return {o: np.asarray(v) for o, v in
            sd.output({"x": arrays["x"]}, list(outputs)).items()}


def test_onnx_gemm_transB():
    rng = np.random.RandomState(4)
    x = rng.randn(3, 6).astype(np.float32)
    w = rng.randn(4, 6).astype(np.float32)
    b = rng.randn(4).astype(np.float32)
    out = _run_single("Gemm", ["x", "w", "b"], input_arrays={"x": x, "w": w, "b": b},
                      alpha=1.0, beta=1.0, transB=1)["y"]
    np.testing.assert_allclose(out, x @ w.T + b, rtol=1e-5, atol=1e-5)


def test_onnx_slice_and_reduce():
    rng = np.random.RandomState(5)
    x = rng.randn(4, 10, 6).astype(np.float32)
    arrays = {"x": x, "starts": np.asarray([2], np.int64),
              "ends": np.asarray([9], np.int64),
              "axes": np.asarray([1], np.int64),
              "steps": np.asarray([2], np.int64)}
    model = make_model(
        [make_node("Slice", ["x", "starts", "ends", "axes", "steps"], ["s"]),
         make_node("ReduceMean", ["s"], ["y"], axes=[2], keepdims=0)],
        inputs=[make_vi("x", np.float32, x.shape)], outputs=[],
        initializers=[make_tensor(k, v) for k, v in arrays.items() if k != "x"])
    sd = OnnxGraphMapper.import_model(model)
    out = np.asarray(sd.output({"x": x}, ["y"])["y"])
    np.testing.assert_allclose(out, x[:, 2:9:2].mean(axis=2), rtol=1e-5, atol=1e-6)


def test_onnx_grouped_conv():
    rng = np.random.RandomState(6)
    x = rng.randn(1, 4, 8, 8).astype(np.float32)
    w = rng.randn(4, 2, 3, 3).astype(np.float32)  # groups=2
    out = _run_single("Conv", ["x", "w"], input_arrays={"x": x, "w": w},
                      kernel_shape=[3, 3], pads=[1, 1, 1, 1], group=2)["y"]
    expected = F.conv2d(torch.from_numpy(x), torch.from_numpy(w),
                        padding=1, groups=2).numpy()
    np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)


def test_onnx_maxpool_explicit_pads():
    """ResNet-stem pattern: MaxPool with pads=[1,1,1,1] (explicit, nonzero)."""
    rng = np.random.RandomState(7)
    x = rng.randn(1, 3, 9, 9).astype(np.float32)
    out = _run_single("MaxPool", ["x"], input_arrays={"x": x},
                      kernel_shape=[3, 3], strides=[2, 2], pads=[1, 1, 1, 1])["y"]
    expected = F.max_pool2d(torch.from_numpy(x), 3, 2, padding=1).numpy()
    np.testing.assert_allclose(out, expected, rtol=1e-6)


@pytest.mark.parametrize("include_pad", [0, 1])
def test_onnx_avgpool_explicit_pads(include_pad):
    rng = np.random.RandomState(8)
    x = rng.randn(1, 2, 8, 8).astype(np.float32)
    out = _run_single("AveragePool", ["x"], input_arrays={"x": x},
                      kernel_shape=[3, 3], strides=[2, 2], pads=[1, 1, 1, 1],
                      count_include_pad=include_pad)["y"]
    expected = F.avg_pool2d(torch.from_numpy(x), 3, 2, padding=1,
                            count_include_pad=bool(include_pad)).numpy()
    np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)


def test_onnx_float_range():
    model = make_model(
        [make_node("Range", ["r_start", "r_limit", "r_delta"], ["y"])],
        inputs=[make_vi("x", np.float32, (1,))], outputs=[],
        initializers=[make_tensor("r_start", np.asarray(0.0, np.float32)),
                      make_tensor("r_limit", np.asarray(1.0, np.float32)),
                      make_tensor("r_delta", np.asarray(0.25, np.float32))])
    sd = OnnxGraphMapper.import_model(model)
    out = np.asarray(sd.output({"x": np.zeros(1, np.float32)}, ["y"])["y"])
    np.testing.assert_allclose(out, np.arange(0.0, 1.0, 0.25, dtype=np.float32))


def test_onnx_unknown_op_message():
    model = make_model([make_node("TotallyMadeUpOp", ["x"], ["y"])],
                       inputs=[make_vi("x", np.float32, (2,))], outputs=[],
                       initializers=[])
    with pytest.raises(NotImplementedError, match="TotallyMadeUpOp"):
        OnnxGraphMapper.import_model(model)


class TestTranche3OnnxRules:
    """Golden checks for the widened ONNX ruleset vs torch/np math."""

    def test_reduce_family(self):
        rng = np.random.RandomState(10)
        x = rng.randn(3, 5).astype(np.float32)
        got = _run_single("ReduceL2", ["x"], input_arrays={"x": x},
                          axes=[1], keepdims=0)["y"]
        np.testing.assert_allclose(got, np.linalg.norm(x, axis=1),
                                   rtol=1e-5)
        got = _run_single("ReduceL1", ["x"], input_arrays={"x": x},
                          axes=[1], keepdims=0)["y"]
        np.testing.assert_allclose(got, np.abs(x).sum(1), rtol=1e-5)
        got = _run_single("ReduceLogSumExp", ["x"], input_arrays={"x": x},
                          axes=[1], keepdims=0)["y"]
        np.testing.assert_allclose(got, np.log(np.exp(x).sum(1)), rtol=1e-5)
        got = _run_single("ReduceSumSquare", ["x"], input_arrays={"x": x},
                          axes=[1], keepdims=0)["y"]
        np.testing.assert_allclose(got, (x ** 2).sum(1), rtol=1e-5)

    def test_conv_transpose_vs_torch(self):
        rng = np.random.RandomState(11)
        x = rng.randn(1, 3, 5, 5).astype(np.float32) * 0.5
        w = rng.randn(3, 4, 3, 3).astype(np.float32) * 0.2  # [C, M, kH, kW]
        got = _run_single("ConvTranspose", ["x", "w"],
                          input_arrays={"x": x, "w": w},
                          strides=[2, 2], kernel_shape=[3, 3])["y"]
        ref = F.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                 stride=2).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_instance_and_group_norm_vs_torch(self):
        rng = np.random.RandomState(12)
        x = rng.randn(2, 6, 4, 4).astype(np.float32)
        g = rng.rand(6).astype(np.float32) + 0.5
        b = rng.randn(6).astype(np.float32) * 0.1
        got = _run_single("InstanceNormalization", ["x", "g", "b"],
                          input_arrays={"x": x, "g": g, "b": b},
                          epsilon=1e-5)["y"]
        ref = F.instance_norm(torch.tensor(x), weight=torch.tensor(g),
                              bias=torch.tensor(b)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

        got = _run_single("GroupNormalization", ["x", "g", "b"],
                          input_arrays={"x": x, "g": g, "b": b},
                          num_groups=3, epsilon=1e-5)["y"]
        ref = F.group_norm(torch.tensor(x), 3, weight=torch.tensor(g),
                           bias=torch.tensor(b)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    def test_lrn_vs_torch(self):
        rng = np.random.RandomState(13)
        x = rng.randn(1, 8, 4, 4).astype(np.float32)
        got = _run_single("LRN", ["x"], input_arrays={"x": x}, size=3,
                          alpha=3e-4, beta=0.75, bias=1.0)["y"]
        ref = F.local_response_norm(torch.tensor(x), 3, alpha=3e-4,
                                    beta=0.75, k=1.0).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_topk_onehot_cumsum_trilu(self):
        rng = np.random.RandomState(14)
        x = rng.randn(3, 7).astype(np.float32)
        got = _run_single("TopK", ["x", "k"], outputs=("y", "yi"),
                          input_arrays={"x": x,
                                        "k": np.asarray([4], np.int64)})
        ref_v = np.sort(x, axis=1)[:, ::-1][:, :4]
        np.testing.assert_allclose(got["y"], ref_v, rtol=1e-6)
        np.testing.assert_array_equal(got["yi"],
                                      np.argsort(-x, axis=1)[:, :4])

        ids = np.asarray([0, 2, 1], np.int64)
        got = _run_single(
            "OneHot", ["x", "d", "v"],
            input_arrays={"x": ids, "d": np.asarray([4], np.int64),
                          "v": np.asarray([0.0, 1.0], np.float32)})["y"]
        np.testing.assert_allclose(got, np.eye(4, dtype=np.float32)[ids])

        x2 = rng.randn(2, 5).astype(np.float32)
        got = _run_single("CumSum", ["x", "ax"],
                          input_arrays={"x": x2,
                                        "ax": np.asarray([1], np.int64)})["y"]
        np.testing.assert_allclose(got, np.cumsum(x2, axis=1), rtol=1e-5)

        m = rng.randn(4, 4).astype(np.float32)
        got = _run_single("Trilu", ["x"], input_arrays={"x": m}, upper=0)["y"]
        np.testing.assert_allclose(got, np.tril(m))

    def test_scatter_gather_elements(self):
        data = np.zeros((4, 3), np.float32)
        idx = np.asarray([[0], [2]], np.int64)
        upd = np.asarray([[9.0, 8.0, 7.0], [1.0, 2.0, 3.0]], np.float32)
        got = _run_single("ScatterND", ["x", "i", "u"],
                          input_arrays={"x": data, "i": idx, "u": upd})["y"]
        ref = data.copy(); ref[0] = upd[0]; ref[2] = upd[1]
        np.testing.assert_allclose(got, ref)

        x = np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32)
        gidx = np.asarray([[0, 0], [1, 0]], np.int64)
        got = _run_single("GatherElements", ["x", "i"],
                          input_arrays={"x": x, "i": gidx}, axis=1)["y"]
        np.testing.assert_allclose(got, [[1.0, 1.0], [4.0, 3.0]])

    def test_quantize_dequantize_and_space_depth(self):
        # non-negative values: the default uint8 range clips negatives to 0
        x = np.asarray([[0.31, 0.12], [0.7, 0.05]], np.float32)
        scale = np.asarray([0.1], np.float32)
        zp = np.asarray([0], np.int32)
        q = _run_single("QuantizeLinear", ["x", "s", "z"],
                        input_arrays={"x": x, "s": scale, "z": zp})["y"]
        dq = _run_single("DequantizeLinear", ["x", "s", "z"],
                         input_arrays={"x": q.astype(np.int32), "s": scale,
                                       "z": zp})["y"]
        np.testing.assert_allclose(dq, x, atol=0.06)

        rng = np.random.RandomState(15)
        img = rng.randn(1, 8, 2, 2).astype(np.float32)
        got = _run_single("DepthToSpace", ["x"], input_arrays={"x": img},
                          blocksize=2)["y"]
        ref = torch.pixel_shuffle(torch.tensor(img), 2).numpy()
        # ONNX DCR == torch pixel_shuffle? torch uses CRD; verify DCR manually
        n, c, h, w = img.shape
        t = img.reshape(n, 2, 2, c // 4, h, w).transpose(0, 3, 4, 1, 5, 2)
        ref_dcr = t.reshape(n, c // 4, h * 2, w * 2)
        np.testing.assert_allclose(got, ref_dcr, rtol=1e-6)

    def test_mean_shrink_mvn(self):
        rng = np.random.RandomState(16)
        a = rng.randn(3, 4).astype(np.float32)
        b = rng.randn(3, 4).astype(np.float32)
        model = make_model(
            [make_node("Mean", ["x", "b"], ["y"])],
            inputs=[make_vi("x", np.float32, a.shape)], outputs=[],
            initializers=[make_tensor("b", b)])
        sd = OnnxGraphMapper.import_model(model)
        got = np.asarray(sd.output({"x": a}, ["y"])["y"])
        np.testing.assert_allclose(got, (a + b) / 2, rtol=1e-6)

        x = np.asarray([-1.0, -0.3, 0.0, 0.4, 2.0], np.float32)
        got = _run_single("Shrink", ["x"], input_arrays={"x": x},
                          lambd=0.5, bias=0.0)["y"]
        ref = F.hardshrink(torch.tensor(x), 0.5).numpy()
        np.testing.assert_allclose(got, ref)
        got = _run_single("Shrink", ["x"], input_arrays={"x": x},
                          lambd=0.5, bias=0.2)["y"]
        ref = np.where(x < -0.5, x + 0.2, np.where(x > 0.5, x - 0.2, 0.0))
        np.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_new_simple_activations_vs_torch(self):
        rng = np.random.RandomState(17)
        x = rng.randn(2, 6).astype(np.float32)
        for op, ref_fn in [("Celu", F.celu), ("HardSwish", F.hardswish),
                           ("Mish", F.mish)]:
            got = _run_single(op, ["x"], input_arrays={"x": x.copy()})["y"]
            np.testing.assert_allclose(got, ref_fn(torch.tensor(x)).numpy(),
                                       rtol=1e-4, atol=1e-5, err_msg=op)

    def test_mod_fmod_and_reverse_sequence(self):
        x = np.asarray([-3.5, 3.5], np.float32)
        y = np.asarray([2.0, -2.0], np.float32)
        got = _run_single("Mod", ["x", "m"],
                          input_arrays={"x": x, "m": y}, fmod=1)["y"]
        np.testing.assert_allclose(got, np.fmod(x, y))  # sign of dividend
        got = _run_single("Mod", ["x", "m"],
                          input_arrays={"x": x, "m": y})["y"]
        np.testing.assert_allclose(got, np.mod(x, y))

        # spec-default time-major ReverseSequence [T, B, ...]
        rng = np.random.RandomState(18)
        seq = rng.randn(5, 2, 3).astype(np.float32)
        lens = np.asarray([3, 5], np.int64)
        got = _run_single("ReverseSequence", ["x", "l"],
                          input_arrays={"x": seq, "l": lens})["y"]
        ref = seq.copy()
        for b, n in enumerate(lens):
            ref[:n, b] = seq[:n, b][::-1]
        np.testing.assert_allclose(got, ref)

    def test_conv_transpose_rejects_ambiguous_pads(self):
        rng = np.random.RandomState(19)
        x = rng.randn(1, 2, 4, 4).astype(np.float32)
        w = rng.randn(2, 3, 3, 3).astype(np.float32)
        with pytest.raises(NotImplementedError, match="pads"):
            _run_single("ConvTranspose", ["x", "w"],
                        input_arrays={"x": x, "w": w}, strides=[2, 2],
                        kernel_shape=[3, 3], pads=[1, 1, 1, 1])

    def test_quantize_signed_int8(self):
        x = np.asarray([[-1.0, 0.5]], np.float32)
        q = _run_single("QuantizeLinear", ["x", "s", "z"],
                        input_arrays={"x": x,
                                      "s": np.asarray([0.1], np.float32),
                                      "z": np.asarray([0], np.int8)})["y"]
        np.testing.assert_array_equal(q, [[-10, 5]])
