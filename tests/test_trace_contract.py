"""Tier-1 wiring for tools/check_trace_contract.py: the end-to-end trace
propagation contract (README.md "Tracing" — one trace id client -> server
-> engine over real HTTP, correct nesting, monotonic timestamps, bounded
store, byte-identical off behavior) is enforced on every test run,
mirroring test_serving_contract.py / test_metrics_contract.py."""

import os
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


def test_trace_contract_smoke():
    sys.path.insert(0, _TOOLS)
    try:
        import check_trace_contract
    finally:
        sys.path.remove(_TOOLS)
    assert check_trace_contract.main(log=lambda m: None) == 0
