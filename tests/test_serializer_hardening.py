"""Serializer hardening: atomic writes, loud load-time validation
(model/serializer.py — ISSUE 4 satellites)."""

import io
import json
import os
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.model import serializer
from deeplearning4j_tpu.model.serializer import (
    restore_model,
    restore_multi_layer_network,
    write_model,
)
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer


def _model(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    return MultiLayerNetwork(conf).init()


def _rewrite_entry(path, name, data: bytes) -> None:
    """Rewrite one zip entry (zips are append-only; rebuild)."""
    with zipfile.ZipFile(path) as zf:
        entries = {n: zf.read(n) for n in zf.namelist()}
    entries[name] = data
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        for n, d in entries.items():
            zf.writestr(n, d)


def test_crashed_write_never_clobbers_existing_artifact(tmp_path, monkeypatch):
    path = str(tmp_path / "model.zip")
    m1 = _model(1)
    write_model(m1, path)
    x = np.ones((2, 4), np.float32)
    expected = np.asarray(m1.output(x))

    def boom(tree):
        raise RuntimeError("crash mid-serialize")

    monkeypatch.setattr(serializer, "_leaves_to_npz", boom)
    with pytest.raises(RuntimeError):
        write_model(_model(2), path)
    monkeypatch.undo()
    # the original artifact survives byte-identical in behavior and no
    # temp debris remains in the directory
    assert [f for f in os.listdir(tmp_path) if f.startswith(".tmp-")] == []
    restored = restore_multi_layer_network(path)
    np.testing.assert_allclose(np.asarray(restored.output(x)), expected,
                               atol=1e-6)


def test_write_model_to_fresh_path_is_complete(tmp_path):
    path = str(tmp_path / "sub" / "model.zip")
    os.makedirs(os.path.dirname(path))
    write_model(_model(1), path)
    assert zipfile.is_zipfile(path)


def test_coefficient_length_mismatch_is_loud(tmp_path):
    path = str(tmp_path / "model.zip")
    write_model(_model(1), path)
    buf = io.BytesIO()
    np.save(buf, np.zeros(7, np.float32))  # wrong size on purpose
    _rewrite_entry(path, "coefficients.npy", buf.getvalue())
    with pytest.raises(ValueError, match="coefficient vector has 7"):
        restore_multi_layer_network(path)


def test_load_updater_without_updater_state_raises(tmp_path):
    path = str(tmp_path / "model.zip")
    write_model(_model(1), path, save_updater=False)
    with pytest.raises(ValueError, match="save_updater"):
        restore_multi_layer_network(path, load_updater=True)
    # explicit opt-out still loads
    assert restore_multi_layer_network(path, load_updater=False) is not None


def test_unknown_model_class_hard_errors(tmp_path):
    path = str(tmp_path / "model.zip")
    write_model(_model(1), path)
    with zipfile.ZipFile(path) as zf:
        meta = json.loads(zf.read("meta.json"))
    meta["model_class"] = "FancyFutureNetwork"
    _rewrite_entry(path, "meta.json", json.dumps(meta).encode())
    with pytest.raises(ValueError, match="unknown model_class"):
        restore_model(path)


def test_foreign_framework_hard_errors(tmp_path):
    path = str(tmp_path / "model.zip")
    write_model(_model(1), path)
    with zipfile.ZipFile(path) as zf:
        meta = json.loads(zf.read("meta.json"))
    meta["framework"] = "someone_elses_dl"
    _rewrite_entry(path, "meta.json", json.dumps(meta).encode())
    with pytest.raises(ValueError, match="framework"):
        restore_model(path)


def test_framework_version_skew_warns_but_loads(tmp_path):
    path = str(tmp_path / "model.zip")
    write_model(_model(1), path)
    with zipfile.ZipFile(path) as zf:
        meta = json.loads(zf.read("meta.json"))
    meta["version"] = "0.0.0-ancient"
    _rewrite_entry(path, "meta.json", json.dumps(meta).encode())
    with pytest.warns(UserWarning, match="0.0.0-ancient"):
        model = restore_model(path)
    assert model is not None
