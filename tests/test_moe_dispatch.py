"""Sort-based MoE dispatch (ops/moe_dispatch.py, ISSUE 3 + 18).

Tier-1 contract: ``dispatch_mode="sort"`` (gather/scatter), ``"einsum"``
(legacy dense one-hot) and ``"grouped"`` (sorted grouped expert matmul,
ops.grouped_matmul) implement the SAME GShard routing — identical slot
assignment (first-come-first-served in (round, token) order), identical
capacity drops, matching outputs and gradients across the full
{mode} × {top_k} × {capacity_factor} × {mask} × {dtype} matrix — plus
the routing-observability state and the micro-bench tool smoke.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import (
    Activation, InputType, LossFunction, NeuralNetConfiguration, WeightInit,
)
from deeplearning4j_tpu.nn.layers import MixtureOfExpertsLayer, OutputLayer
from deeplearning4j_tpu.nn.layers.base import LayerContext
from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
from deeplearning4j_tpu.ops import (
    gather_dispatch, make_dispatch_plan, scatter_combine, top_k_routing,
)
from deeplearning4j_tpu.train.updaters import Sgd
from deeplearning4j_tpu.utils import check_gradients


def _pair(e=4, d=8, h=16, o=8, k=2, cap=1.5, seed=0, dtype=jnp.float32):
    """(sort layer, einsum layer, shared params)."""
    mk = lambda mode: MixtureOfExpertsLayer(
        n_in=d, n_out=o, num_experts=e, hidden=h, top_k=k,
        capacity_factor=cap, activation=Activation.RELU, dispatch_mode=mode)
    sort, einsum = mk("sort"), mk("einsum")
    params = sort.init(jax.random.PRNGKey(seed), dtype)
    return sort, einsum, params


def _apply(lay, params, x, mask=None):
    return lay.apply(params, lay.init_state(jnp.float32), x,
                     LayerContext(mask=mask))


# ---- plan unit tests ------------------------------------------------------


def test_plan_fcfs_slot_assignment():
    """Deterministic 3-token example: slots are granted per expert in
    (round, token) order and overflow drops exactly the late arrivals."""
    # round-major flat list with capacity 2: expert 0 sees token0(r0),
    # token2(r0), token1(r1) -> token1's round-1 assignment overflows
    expert_idx = jnp.asarray([[0, 1], [1, 0], [0, 1]], jnp.int32)
    plan = make_dispatch_plan(expert_idx, num_experts=2, capacity=2)
    # expert buffers: e0 = [t0, t2], e1 = [t1, t0]
    np.testing.assert_array_equal(np.asarray(plan.slot_token), [0, 2, 1, 0])
    np.testing.assert_array_equal(np.asarray(plan.expert_tokens), [2, 2])
    assert int(plan.dropped_tokens) == 2  # t1->e0 and t2->e1 overflow
    # kept flags, round-major: [t0r0, t1r0, t2r0, t0r1, t1r1, t2r1]
    np.testing.assert_array_equal(
        np.asarray(plan.keep), [True, True, True, True, False, False])


def test_plan_masked_tokens_claim_no_slot():
    expert_idx = jnp.zeros((4, 1), jnp.int32)  # all want expert 0
    mask = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    plan = make_dispatch_plan(expert_idx, num_experts=2, capacity=4,
                              token_mask=mask)
    # masked tokens 1 and 3 appear in no buffer and count nowhere
    np.testing.assert_array_equal(np.asarray(plan.slot_token),
                                  [0, 2, 4, 4, 4, 4, 4, 4])
    np.testing.assert_array_equal(np.asarray(plan.expert_tokens), [2, 0])
    assert int(plan.dropped_tokens) == 0


def test_gather_scatter_roundtrip_identity():
    """With capacity >= tokens and top-1 routing, dispatch->combine of the
    identity expert returns each token times its (renormalized=1) gate."""
    x = jnp.asarray(np.random.RandomState(0).rand(6, 3), jnp.float32)
    gates = jax.nn.softmax(jnp.asarray(
        np.random.RandomState(1).randn(6, 2), jnp.float32))
    gate_vals, idx = top_k_routing(gates, 1)
    plan = make_dispatch_plan(idx, num_experts=2, capacity=6)
    buf = gather_dispatch(x, plan, 2, 6)
    y = scatter_combine(buf, gate_vals, plan)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x),
                               rtol=1e-6, atol=1e-6)


# ---- mode equivalence -----------------------------------------------------


@pytest.mark.parametrize("k,cap", [(1, 100.0), (2, 1.5), (2, 0.3),
                                   (4, 0.26)])
def test_modes_agree_outputs_and_state(k, cap):
    """sort == einsum on outputs, per-expert loads, drops and the aux
    balance term — including under heavy capacity overflow."""
    sort, einsum, params = _pair(k=k, cap=cap)
    x = jnp.asarray(np.random.RandomState(3).rand(12, 8), jnp.float32)
    ys, ss = _apply(sort, params, x)
    ye, se = _apply(einsum, params, x)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(ye),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(ss["expert_tokens"]),
                                  np.asarray(se["expert_tokens"]))
    assert float(ss["dropped_tokens"]) == float(se["dropped_tokens"])
    np.testing.assert_allclose(float(ss["aux_load_balance"]),
                               float(se["aux_load_balance"]), rtol=1e-5)


def test_modes_agree_gradients():
    sort, einsum, params = _pair(k=2, cap=0.8)
    x = jnp.asarray(np.random.RandomState(4).rand(10, 8), jnp.float32)

    def loss(lay):
        def f(p):
            y, _ = _apply(lay, p, x)
            return jnp.sum(jnp.square(y))
        return jax.grad(f)

    gs, ge = loss(sort)(params), loss(einsum)(params)
    for name in gs:
        np.testing.assert_allclose(np.asarray(gs[name]),
                                   np.asarray(ge[name]),
                                   rtol=1e-4, atol=1e-6, err_msg=name)


def test_modes_agree_recurrent_token_mask():
    """Masked recurrent tokens claim no capacity slot in either mode, and
    padding CONTENT is irrelevant (adversarial values in masked steps)."""
    sort, einsum, params = _pair(k=1, cap=0.5)
    rs = np.random.RandomState(6)
    b, d, t = 2, 8, 6
    x = np.asarray(rs.rand(b, d, t), np.float32)
    mask = np.ones((b, t), np.float32)
    mask[:, t // 2:] = 0.0
    x_adv = x.copy()
    x_adv[:, :, t // 2:] = 50.0  # would win every router argmax unmasked

    ys, ss = _apply(sort, params, jnp.asarray(x), jnp.asarray(mask))
    ys_adv, _ = _apply(sort, params, jnp.asarray(x_adv), jnp.asarray(mask))
    ye, se = _apply(einsum, params, jnp.asarray(x), jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(ys), np.asarray(ye),
                               rtol=1e-5, atol=1e-6)
    # adversarial padding changes nothing: no slot stolen, no output drift
    np.testing.assert_allclose(np.asarray(ys), np.asarray(ys_adv),
                               rtol=1e-5, atol=1e-6)
    # padding rows produce exactly zero (residual carries them)
    np.testing.assert_allclose(np.asarray(ys)[:, :, t // 2:], 0.0)
    np.testing.assert_array_equal(np.asarray(ss["expert_tokens"]),
                                  np.asarray(se["expert_tokens"]))
    # only real tokens were routed at all
    assert float(np.sum(np.asarray(ss["expert_tokens"]))) \
        + float(ss["dropped_tokens"]) == b * (t // 2)


def test_capacity_overflow_drops_sort_mode():
    """Tight capacity drops most tokens in sort mode exactly as the
    einsum contract: dropped rows get zero output."""
    sort, _, params = _pair(k=1, cap=0.26)  # capacity = 1 per expert
    x = jnp.asarray(np.random.RandomState(3).rand(12, 8), jnp.float32)
    y, state = _apply(sort, params, x)
    zero_rows = int(np.sum(np.all(np.asarray(y) == 0.0, axis=-1)))
    assert zero_rows >= 8  # at most one token per expert survives
    assert float(state["dropped_tokens"]) == 12 - float(
        np.sum(np.asarray(state["expert_tokens"])))
    assert np.asarray(state["expert_tokens"]).max() <= 1


# ---- full mode-equivalence matrix (ISSUE 18) ------------------------------


def _moe(mode, k, cap, dtype, e=4, d=6, h=8, o=6, seed=0):
    lay = MixtureOfExpertsLayer(
        n_in=d, n_out=o, num_experts=e, hidden=h, top_k=k,
        capacity_factor=cap, activation=Activation.RELU,
        dispatch_mode=mode)
    params = lay.init(jax.random.PRNGKey(seed), dtype)
    return lay, params


# Curated slice of the mode × top_k × capacity × mask × dtype cross:
# "grouped" (the bit-identical claim) gets the full k × cap cross in
# f32 plus masked/bf16 spot checks; "einsum" (float-tolerance
# reference) gets one spot check per varied dimension. The full
# 48-case cross costs ~1 min of tier-1 budget for no extra coverage.
_MATRIX = [
    ("grouped", 1, 1.0, False, "float32"),
    ("grouped", 2, 1.0, False, "float32"),
    ("grouped", 4, 1.0, False, "float32"),
    ("grouped", 1, 1.5, False, "float32"),
    ("grouped", 2, 1.5, False, "float32"),
    ("grouped", 4, 1.5, False, "float32"),
    ("grouped", 2, 1.5, True, "float32"),
    ("grouped", 2, 1.0, False, "bfloat16"),
    ("grouped", 4, 1.5, True, "bfloat16"),
    ("einsum", 1, 1.0, False, "float32"),
    ("einsum", 2, 1.5, False, "float32"),
    ("einsum", 4, 1.0, False, "float32"),
    ("einsum", 2, 1.0, True, "float32"),
    ("einsum", 2, 1.5, False, "bfloat16"),
]


@pytest.mark.parametrize(
    "mode,k,cap,masked,dtype", _MATRIX,
    ids=[f"{m}-{k}-{c}-{'masked' if mk else 'flat'}-{d}"
         for m, k, c, mk, d in _MATRIX])
def test_mode_equivalence_matrix(mode, k, cap, masked, dtype):
    """Every non-default dispatch mode matches "sort" on outputs AND
    parameter gradients across top_k × capacity_factor × mask × dtype.
    "grouped" shares the sort plan and combine arithmetic, so its
    outputs must be exact in f32; "einsum" reassociates reductions, so
    it gets float tolerance."""
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    base, params = _moe("sort", k, cap, dt)
    other, _ = _moe(mode, k, cap, dt)
    rs = np.random.RandomState(11)
    if masked:
        b, t = 2, 5
        x = jnp.asarray(rs.rand(b, 6, t), dt)
        mask = jnp.asarray((np.arange(t) < 3)[None, :].repeat(b, 0)
                           .astype(np.float32))
    else:
        x = jnp.asarray(rs.rand(10, 6), dt)
        mask = None

    def run(lay):
        def loss(p):
            y, state = lay.apply(p, lay.init_state(dt), x,
                                 LayerContext(mask=mask))
            return jnp.sum(jnp.square(y.astype(jnp.float32))), (y, state)
        (l, (y, state)), grads = jax.value_and_grad(
            loss, has_aux=True)(params)
        return np.asarray(y, np.float32), state, grads

    ys, ss, gs = run(base)
    yo, so, go = run(other)
    scale = max(float(np.abs(ys).max()), 1e-6)
    if mode == "grouped" and dtype == "float32":
        out_tol = dict(rtol=0, atol=1e-6 * scale)
    elif dtype == "float32":
        out_tol = dict(rtol=1e-5, atol=1e-6)
    else:  # bf16: accumulation order differs between spellings
        out_tol = dict(rtol=0, atol=3e-2 * scale)
    np.testing.assert_allclose(yo, ys, err_msg="outputs", **out_tol)
    np.testing.assert_array_equal(np.asarray(ss["expert_tokens"]),
                                  np.asarray(so["expert_tokens"]))
    assert float(ss["dropped_tokens"]) == float(so["dropped_tokens"])
    assert float(ss["capacity_slots"]) == float(so["capacity_slots"]) > 0
    # tolerance scaled by the GLOBAL gradient magnitude: with k=1 the
    # renormalized gate makes the true router gradient exactly zero and
    # both spellings produce only roundoff noise there — a per-param
    # scale would compare noise against noise
    gscale = max(max(np.abs(np.asarray(g, np.float32)).max()
                     for g in gs.values()), 1e-6)
    gtol = 1e-5 if dtype == "float32" else 6e-2
    for name in gs:
        a = np.asarray(gs[name], np.float32)
        b = np.asarray(go[name], np.float32)
        np.testing.assert_allclose(b, a, rtol=0, atol=gtol * gscale,
                                   err_msg=f"grad {name}")


# ---- gradcheck (float64, reference GradCheckUtil harness) -----------------


def test_gradcheck_sort_dispatch():
    conf = (NeuralNetConfiguration.builder().seed(7).data_type("float64")
            .updater(Sgd(0.1)).weight_init(WeightInit.XAVIER).list()
            .layer(MixtureOfExpertsLayer(n_out=6, num_experts=3, hidden=8,
                                         top_k=2, capacity_factor=4.0,
                                         activation=Activation.TANH,
                                         dispatch_mode="sort"))
            .layer(OutputLayer(n_out=2, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(5)).build())
    model = MultiLayerNetwork(conf).init()
    rs = np.random.default_rng(8)
    x = rs.normal(size=(6, 5))
    y = np.eye(2)[np.arange(6) % 2]
    assert check_gradients(model, x, y, subset=60, print_results=True)


def test_gradcheck_modes_agree_with_balance_loss():
    """Analytic grads of the full score (incl. aux balance loss) match
    between modes in float64."""
    def build(mode):
        conf = (NeuralNetConfiguration.builder().seed(9)
                .data_type("float64").updater(Sgd(0.1))
                .weight_init(WeightInit.XAVIER).list()
                .layer(MixtureOfExpertsLayer(
                    n_out=6, num_experts=3, hidden=8, top_k=2,
                    capacity_factor=1.0, balance_loss_weight=0.5,
                    activation=Activation.TANH, dispatch_mode=mode))
                .layer(OutputLayer(n_out=2, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(5)).build())
        return MultiLayerNetwork(conf).init()

    rs = np.random.default_rng(10)
    x = rs.normal(size=(9, 5))
    y = np.eye(2)[np.arange(9) % 2]
    ms = build("sort")
    gs = ms.calculate_gradients(x, y)
    flat_s = jax.tree_util.tree_leaves(gs)
    for mode in ("einsum", "grouped"):
        mo = build(mode)
        mo.params = jax.tree_util.tree_map(lambda a: a, ms.params)
        go = mo.calculate_gradients(x, y)
        for a, b in zip(flat_s, jax.tree_util.tree_leaves(go)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-8, atol=1e-10,
                                       err_msg=mode)


# ---- observability --------------------------------------------------------


def test_record_moe_metrics_counters():
    from deeplearning4j_tpu.obs import MetricsRegistry, record_moe_metrics

    sort, _, params = _pair(k=2, cap=0.5)
    x = jnp.asarray(np.random.RandomState(5).rand(12, 8), jnp.float32)
    _, state = _apply(sort, params, x)

    reg = MetricsRegistry()
    seen = record_moe_metrics({"layer_0": state}, reg)
    assert seen == 1
    tok = reg.get("dl4j_tpu_moe_expert_tokens_total")
    drop = reg.get("dl4j_tpu_moe_dropped_tokens_total")
    per_expert = np.asarray(state["expert_tokens"])
    for e_idx, expect in enumerate(per_expert.tolist()):
        assert tok.labels("layer_0", str(e_idx)).value == expect
    assert drop.labels("layer_0").value == float(state["dropped_tokens"])
    # counters are cumulative across steps
    record_moe_metrics({"layer_0": state}, reg)
    assert tok.labels("layer_0", "0").value == 2 * per_expert[0]
    # conservation: kept + dropped == top_k * tokens
    assert float(per_expert.sum()) + float(state["dropped_tokens"]) == 24


def test_moe_metrics_listener_end_to_end():
    from deeplearning4j_tpu.obs import MetricsRegistry, MoEMetricsListener

    conf = (NeuralNetConfiguration.builder().seed(5).updater(Sgd(0.3))
            .weight_init(WeightInit.XAVIER).list()
            .layer(MixtureOfExpertsLayer(n_out=8, num_experts=4, hidden=16,
                                         top_k=2, capacity_factor=2.0))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    reg = MetricsRegistry()
    net.set_listeners(MoEMetricsListener(reg))
    rs = np.random.RandomState(0)
    x = rs.rand(16, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 16)]
    net.fit(x, y, epochs=2)
    tok = reg.get("dl4j_tpu_moe_expert_tokens_total")
    total = sum(child.value for _, child in tok.items())
    drop = reg.get("dl4j_tpu_moe_dropped_tokens_total")
    dropped = sum(child.value for _, child in drop.items())
    # 2 iterations (one full batch per epoch) x 16 tokens x top_k=2
    # assignments, kept + dropped
    assert total + dropped == 2 * 16 * 2


# ---- serialization + tooling ---------------------------------------------


def test_dispatch_mode_json_roundtrip():
    from deeplearning4j_tpu.core.config import from_json, to_json

    lay = MixtureOfExpertsLayer(n_in=8, n_out=4, num_experts=4,
                                dispatch_mode="einsum")
    back = from_json(to_json(lay))
    assert back.dispatch_mode == "einsum"
    assert from_json(to_json(MixtureOfExpertsLayer(
        n_in=8, n_out=4))).dispatch_mode == "sort"
    with pytest.raises(ValueError):
        MixtureOfExpertsLayer(n_in=8, n_out=4, dispatch_mode="scatter")


def test_bench_tool_smoke(capsys):
    """tools/bench_moe_dispatch.py runs on tiny shapes and reports the
    modes numerically agreeing."""
    import json as _json
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "tools"))
    import bench_moe_dispatch

    rc = bench_moe_dispatch.main(["--tokens", "64", "--d", "8",
                                  "--hidden", "16", "--iters", "1"])
    row = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert row["modes_agree"]
    assert row["sort_grad_step_ms"] > 0
    assert row["einsum_grad_step_ms"] > 0
    assert row["grouped_grad_step_ms"] > 0
    assert row["grouped_max_abs_output_diff"] == 0.0
