"""Tier-1 wiring for tools/check_fabric_contract.py: the cross-host
serving fabric chaos contract (README.md "Cross-host serving fabric") —
two real HTTP hosts behind one EnginePool of RemoteReplica adapters,
kill one host under mixed-priority load and assert zero high-priority
loss, breaker-open within one window, re-balance onto the survivor, and
half-open rejoin after revival — is enforced on every test run, not
just when someone remembers to run the tool."""

import os
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


def test_fabric_contract_smoke():
    sys.path.insert(0, _TOOLS)
    try:
        import check_fabric_contract
    finally:
        sys.path.remove(_TOOLS)
    assert check_fabric_contract.main(log=lambda m: None) == 0
