"""Sharded per-host loading + loader determinism (ISSUE 7).

Covers the input-tier contract of data/sharded.py and the worker-pool
paths of data/records.py:

* shard-partition completeness: every file in exactly one host shard,
  shard sizes within 1, single-host partition is the identity;
* loader determinism: identical epoch order and batch contents for
  worker counts {1, 4} × prefetch depths {1, 4} under a fixed shuffle
  seed (augmentation included — per-image rng derivation makes the
  stream independent of worker scheduling);
* numerical transparency: a 1-host ShardedDataSetIterator reproduces
  the plain loader's batches bit-exactly;
* multi-shard assembly: batches assembled over the 8-device CPU mesh
  equal the host batch, carry the trainer's data sharding, and train
  to the same score as the unsharded path;
* donated input buffers are numerically transparent;
* DL4J_TPU_DATA_WORKERS sizes the decode pool.
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.data import (
    AsyncDataSetIterator,
    DataSet,
    ListDataSetIterator,
    ShardedDataSetIterator,
    shard_paths,
)
from deeplearning4j_tpu.data.records import (
    ImageRecordReader,
    RecordReaderDataSetIterator,
    resolve_data_workers,
)
from deeplearning4j_tpu.obs.metrics import MetricsRegistry


def _write_ppm(path, arr):
    h, w, _ = arr.shape
    with open(path, "wb") as f:
        f.write(f"P6 {w} {h} 255\n".encode() + arr.tobytes())


def _make_tree(tmp_path, n=32, size=16, classes=4):
    rng = np.random.RandomState(7)
    for c in range(classes):
        os.makedirs(tmp_path / f"c{c}", exist_ok=True)
    for i in range(n):
        _write_ppm(str(tmp_path / f"c{i % classes}" / f"{i:03d}.ppm"),
                   rng.randint(0, 256, (size, size, 3), np.uint8))
    return str(tmp_path)


# ---------------------------------------------------------------------------
# shard_paths
# ---------------------------------------------------------------------------

class TestShardPaths:
    def test_completeness_and_balance(self):
        paths = [f"f{i:04d}" for i in range(103)]
        for count in (1, 2, 4, 8, 5):
            shards = [shard_paths(paths, i, count) for i in range(count)]
            flat = [p for s in shards for p in s]
            # every file in exactly one shard
            assert sorted(flat) == sorted(paths)
            assert len(set(flat)) == len(paths)
            sizes = [len(s) for s in shards]
            assert max(sizes) - min(sizes) <= 1, (count, sizes)

    def test_single_host_is_identity(self):
        paths = [f"f{i}" for i in range(17)]
        assert shard_paths(paths, 0, 1) == paths

    def test_deterministic(self):
        paths = [f"f{i}" for i in range(40)]
        assert shard_paths(paths, 2, 4) == shard_paths(paths, 2, 4)

    def test_invalid_args(self):
        with pytest.raises(ValueError):
            shard_paths([], 0, 0)
        with pytest.raises(ValueError):
            shard_paths([], 3, 2)


# ---------------------------------------------------------------------------
# loader determinism: workers x prefetch depth x fixed shuffle seed
# ---------------------------------------------------------------------------

def _collect_epoch(root, *, workers, queue_size, seed=5, batch=8):
    from deeplearning4j_tpu.data.image_transform import (
        FlipImageTransform, PipelineImageTransform, RandomCropTransform,
    )

    aug = PipelineImageTransform(
        (FlipImageTransform(mode=1), 0.5),
        RandomCropTransform(height=12, width=12))
    reader = ImageRecordReader(12, 12, 3, root=root, transform=aug,
                               seed=seed, shuffle=True, workers=workers)
    base = RecordReaderDataSetIterator(reader, batch_size=batch,
                                       label_index=1, num_classes=4)
    it = AsyncDataSetIterator(base, queue_size=queue_size,
                              registry=MetricsRegistry())
    try:
        return [(np.asarray(ds.features), np.asarray(ds.labels))
                for ds in it]
    finally:
        it.close()


def test_epoch_identical_across_workers_and_depths(tmp_path):
    root = _make_tree(tmp_path)
    ref = _collect_epoch(root, workers=1, queue_size=1)
    assert len(ref) == 4  # 32 images / batch 8
    for workers in (1, 4):
        for depth in (1, 4):
            got = _collect_epoch(root, workers=workers, queue_size=depth)
            assert len(got) == len(ref), (workers, depth)
            for (fa, la), (fb, lb) in zip(ref, got):
                np.testing.assert_array_equal(fa, fb)
                np.testing.assert_array_equal(la, lb)


def test_shuffle_seed_changes_order_deterministically(tmp_path):
    root = _make_tree(tmp_path)
    a = _collect_epoch(root, workers=1, queue_size=2, seed=5)
    b = _collect_epoch(root, workers=1, queue_size=2, seed=6)
    c = _collect_epoch(root, workers=4, queue_size=4, seed=6)
    assert not all(
        np.array_equal(fa, fb) for (fa, _), (fb, _) in zip(a, b))
    for (fb, lb), (fc, lc) in zip(b, c):
        np.testing.assert_array_equal(fb, fc)
        np.testing.assert_array_equal(lb, lc)


# ---------------------------------------------------------------------------
# sharded assembly
# ---------------------------------------------------------------------------

def _data_sharding(n=8):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.parallel.mesh import make_mesh

    return NamedSharding(make_mesh(data=n), P("data"))


def test_one_host_sharded_is_bit_exact(tmp_path):
    """Sharded loading is numerically transparent: with one host and one
    device shard, batches equal the plain loader's bit for bit."""
    import jax

    root = _make_tree(tmp_path)
    device = jax.devices()[0]
    from jax.sharding import SingleDeviceSharding

    def make_base():
        reader = ImageRecordReader(12, 12, 3, root=root, seed=3,
                                   output_dtype="uint8")
        return RecordReaderDataSetIterator(reader, batch_size=8,
                                           label_index=1, num_classes=4)

    plain = [(np.asarray(ds.features), np.asarray(ds.labels))
             for ds in make_base()]
    sharded = ShardedDataSetIterator(
        make_base(), SingleDeviceSharding(device), process_count=1)
    got = [(np.asarray(ds.features), np.asarray(ds.labels))
           for ds in sharded]
    assert len(got) == len(plain) > 0
    for (fa, la), (fb, lb) in zip(plain, got):
        np.testing.assert_array_equal(fa, fb)
        np.testing.assert_array_equal(la, lb)


def test_multi_shard_assembly_roundtrip():
    """Assembly over the 8-device CPU mesh: the global array equals the
    host batch, is laid out on the target sharding, and each device
    holds exactly its slice."""
    import jax

    sh = _data_sharding(8)
    x = np.arange(16 * 6, dtype=np.float32).reshape(16, 6)
    y = np.eye(4, dtype=np.float32)[np.arange(16) % 4]
    it = ShardedDataSetIterator(
        ListDataSetIterator(DataSet(x, y), 16), sh, process_count=1)
    ds = it.next()
    assert isinstance(ds.features, jax.Array)
    assert ds.features.sharding.is_equivalent_to(sh, ds.features.ndim)
    np.testing.assert_array_equal(np.asarray(ds.features), x)
    np.testing.assert_array_equal(np.asarray(ds.labels), y)
    for s in ds.features.addressable_shards:
        np.testing.assert_array_equal(np.asarray(s.data), x[s.index])


def test_assembly_rejects_wrong_local_rows():
    sh = _data_sharding(8)
    x = np.zeros((16, 4), np.float32)
    y = np.zeros((16, 2), np.float32)
    it = ShardedDataSetIterator(
        ListDataSetIterator(DataSet(x, y), 16), sh, process_count=4)
    with pytest.raises(ValueError, match="local batch"):
        it.next()


def test_feature_fn_preps_dtype(tmp_path):
    sh = _data_sharding(8)
    x = (np.arange(16 * 4).reshape(16, 4) % 255).astype(np.uint8)
    y = np.eye(2, dtype=np.float32)[np.arange(16) % 2]
    it = ShardedDataSetIterator(
        ListDataSetIterator(DataSet(x, y), 16), sh,
        feature_fn=lambda a: a.astype(np.float32) / 255.0)
    ds = it.next()
    assert str(ds.features.dtype) == "float32"
    np.testing.assert_allclose(np.asarray(ds.features),
                               x.astype(np.float32) / 255.0)


# ---------------------------------------------------------------------------
# trainer integration: sharded batches skip host prep/put; same numbers
# ---------------------------------------------------------------------------

def test_trainer_fit_iterator_sharded_matches_unsharded():
    from deeplearning4j_tpu.model.zoo import LeNet
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.trainer import DistributedTrainer

    rng = np.random.RandomState(0)
    x = rng.rand(32, 1, 28, 28).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 32)]

    m1 = LeNet(seed=42).init()
    t1 = DistributedTrainer(m1, mesh=make_mesh(data=8))
    s1 = [float(t1.fit_batch(x[:16], y[:16])),
          float(t1.fit_batch(x[16:], y[16:]))]
    t1.sync_to_model()

    m2 = LeNet(seed=42).init()
    t2 = DistributedTrainer(m2, mesh=make_mesh(data=8), donate_inputs=True)
    it = ShardedDataSetIterator(
        ListDataSetIterator(DataSet(x, y), 16), t2.data_sharding)
    assert it.batch_size() == 16
    t2.fit_iterator(it, epochs=1)

    # same data, same seed -> identical training trajectory
    assert np.isfinite(s1).all()
    for (ln, lp), (ln2, lp2) in zip(sorted(m1.params.items()),
                                    sorted(m2.params.items())):
        assert ln == ln2
        for k in lp:
            np.testing.assert_allclose(np.asarray(lp[k]),
                                       np.asarray(lp2[k]),
                                       rtol=1e-6, atol=1e-6)


def test_trainer_presharded_detection():
    import jax

    from deeplearning4j_tpu.model.zoo import LeNet
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.trainer import DistributedTrainer

    model = LeNet(seed=1).init()
    tr = DistributedTrainer(model, mesh=make_mesh(data=8))
    x = np.zeros((16, 1, 28, 28), np.float32)
    gx = jax.device_put(x, tr.data_sharding)
    assert tr._is_presharded(gx)
    assert not tr._is_presharded(x)
    assert not tr._is_presharded(jax.device_put(x))  # single-device array
    # passthrough: _put_data must return the SAME array, not re-transfer
    assert tr._put_data(gx) is gx


# ---------------------------------------------------------------------------
# donated inputs are numerically transparent
# ---------------------------------------------------------------------------

def test_solver_donate_inputs_same_scores():
    from deeplearning4j_tpu.model.zoo import LeNet
    from deeplearning4j_tpu.train.solver import Solver

    rng = np.random.RandomState(3)
    x = rng.rand(8, 1, 28, 28).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    def run(donate):
        model = LeNet(seed=9).init()
        solver = Solver(model, donate_inputs=donate)
        return [float(solver.fit_batch(x.copy(), y.copy())[0])
                for _ in range(3)]

    np.testing.assert_allclose(run(False), run(True), rtol=1e-6)


def test_graph_solver_donate_inputs_same_scores():
    from deeplearning4j_tpu.nn.conf import (
        Activation, DenseLayer, InputType, NeuralNetConfiguration,
        OutputLayer,
    )
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.train import Adam
    from deeplearning4j_tpu.train.graph_solver import GraphSolver

    def make_conf():
        return (
            NeuralNetConfiguration.builder()
            .seed(9)
            .updater(Adam(1e-2))
            .graph_builder()
            .add_inputs("in")
            .add_layer("d1", DenseLayer(n_out=16, activation=Activation.TANH),
                       "in")
            .add_layer("out", OutputLayer(n_out=4), "d1")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(6))
            .build()
        )

    rng = np.random.RandomState(3)
    x = rng.rand(8, 6).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 8)]

    def run(donate):
        model = ComputationGraph(make_conf()).init()
        solver = GraphSolver(model, donate_inputs=donate)
        return [float(solver.fit_batch((x.copy(),), (y.copy(),)))
                for _ in range(3)]

    np.testing.assert_allclose(run(False), run(True), rtol=1e-6)


# ---------------------------------------------------------------------------
# worker-pool sizing
# ---------------------------------------------------------------------------

class TestDataWorkersEnv:
    def test_explicit_wins(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_DATA_WORKERS", "7")
        assert resolve_data_workers(3) == 3

    def test_env_overrides_default(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_DATA_WORKERS", "7")
        assert resolve_data_workers() == 7

    def test_default_is_one(self, monkeypatch):
        monkeypatch.delenv("DL4J_TPU_DATA_WORKERS", raising=False)
        assert resolve_data_workers() == 1

    def test_floor_is_one(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_DATA_WORKERS", "0")
        assert resolve_data_workers() == 1

    def test_garbage_env_raises(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_DATA_WORKERS", "many")
        with pytest.raises(ValueError, match="DL4J_TPU_DATA_WORKERS"):
            resolve_data_workers()

    def test_reader_uses_env(self, tmp_path, monkeypatch):
        root = _make_tree(tmp_path, n=4)
        monkeypatch.setenv("DL4J_TPU_DATA_WORKERS", "2")
        reader = ImageRecordReader(12, 12, 3, root=root)
        assert reader.workers == 2


# ---------------------------------------------------------------------------
# ISSUE 16: iterator resume across a CHANGED shard layout (elastic resize)
# ---------------------------------------------------------------------------

def _make_indexed_tree(tmp_path, n=32, size=8):
    """n constant-valued images (image i is all-i): a decoded row's mean
    names its source file, so consumed-set proofs read off the batches."""
    os.makedirs(tmp_path / "c0", exist_ok=True)
    paths = []
    for i in range(n):
        p = str(tmp_path / "c0" / f"{i:03d}.ppm")
        _write_ppm(p, np.full((size, size, 3), i, np.uint8))
        paths.append(p)
    return paths


def _host_iter(paths, index, count, local_batch):
    reader = ImageRecordReader(8, 8, 3, paths=shard_paths(paths, index, count),
                               output_dtype="uint8")
    return RecordReaderDataSetIterator(reader, batch_size=local_batch,
                                       label_index=1, num_classes=1)


def _ids(ds):
    feats = np.asarray(ds.features)
    return [int(round(float(r.mean()))) for r in feats]


class TestResumeAcrossShardLayout:
    """The tentpole's data half: a cursor saved at shard=(i, N) restores
    at (j, N/2) with the GLOBAL consumed-batch sequence non-overlapping
    and non-skipping. Rides two invariants: shard_paths is round-robin
    (equal per-host consumption == a global file prefix), and the
    per-host cursor counts GLOBAL steps — 'batches' is the same number
    on every host at every width, so per-host skip = batches × the NEW
    local batch repositions exactly."""

    def test_round_robin_equal_consumption_is_global_prefix(self):
        paths = list(range(40))
        for count in (2, 4, 8):
            for k in (1, 3):  # k files consumed per host
                consumed = set()
                for i in range(count):
                    consumed.update(shard_paths(paths, i, count)[:k])
                assert consumed == set(range(k * count))

    def test_state_saved_at_width4_restores_at_width2(self, tmp_path):
        paths = _make_indexed_tree(tmp_path)  # 32 files
        global_batch, steps = 8, 2

        # width 4: local batch 2; every host consumes `steps` global steps
        consumed = []
        states = []
        for i in range(4):
            it = _host_iter(paths, i, 4, global_batch // 4)
            for _ in range(steps):
                consumed += _ids(it.next())
            states.append(it.state_dict())
        # equal per-host consumption == the global prefix, and the cursor
        # is host-independent (it counts global steps, not host rows)
        assert sorted(consumed) == list(range(steps * global_batch))
        assert all(s == states[0] for s in states)

        # width 2: local batch 4; ANY old host's state repositions host j
        remaining = []
        for j in range(2):
            it = _host_iter(paths, j, 2, global_batch // 2)
            it.load_state_dict(states[j % 4])
            while it.has_next():
                remaining += _ids(it.next())
        # non-overlapping, non-skipping: the union is exactly the files
        # the width-4 run never consumed
        assert sorted(remaining) == list(range(steps * global_batch, 32))
        assert not set(consumed) & set(remaining)

    def test_grow_path_width2_to_width4(self, tmp_path):
        paths = _make_indexed_tree(tmp_path)
        global_batch, steps = 8, 3
        it0 = _host_iter(paths, 0, 2, global_batch // 2)
        consumed = []
        for _ in range(steps):
            consumed += _ids(it0.next())
        state = it0.state_dict()

        remaining = []
        for j in range(4):
            it = _host_iter(paths, j, 4, global_batch // 4)
            it.load_state_dict(state)
            while it.has_next():
                remaining += _ids(it.next())
        all_consumed = set()
        for i in range(2):
            all_consumed.update(
                [int(p.split(os.sep)[-1].split(".")[0]) for p in
                 shard_paths(paths, i, 2)[:steps * global_batch // 2]])
        assert sorted(remaining) == sorted(set(range(32)) - all_consumed)


class TestShardedIteratorGlobalBatchContract:
    """ISSUE 16: ShardedDataSetIterator's state carries the GLOBAL batch
    and refuses a restore that would change it (width-invariant global
    batch keeps the LAMB/warmup trajectory intact), plus reshard() —
    carrying the live cursor onto a new shard layout without a cold
    pipeline restart."""

    def _rows(self, n=32):
        x = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
        y = np.eye(2, dtype=np.float32)[np.arange(n) % 2]
        return x, y

    def test_state_dict_carries_global_batch(self):
        sh = _data_sharding(8)
        x, y = self._rows()
        it = ShardedDataSetIterator(
            ListDataSetIterator(DataSet(x, y), 8), sh, process_count=1)
        st = it.state_dict()
        assert st["global_batch"] == 8 == it.batch_size()
        it.load_state_dict(st)  # round-trips through the validation

    def test_load_refuses_changed_global_batch(self):
        sh = _data_sharding(8)
        x, y = self._rows()
        it8 = ShardedDataSetIterator(
            ListDataSetIterator(DataSet(x, y), 8), sh, process_count=1)
        st = it8.state_dict()
        it4 = ShardedDataSetIterator(
            ListDataSetIterator(DataSet(x, y), 4), sh, process_count=1)
        with pytest.raises(ValueError, match="global batch"):
            it4.load_state_dict(st)

    def test_legacy_state_without_global_batch_still_loads(self):
        sh = _data_sharding(8)
        x, y = self._rows()
        it = ShardedDataSetIterator(
            ListDataSetIterator(DataSet(x, y), 8), sh, process_count=1)
        it.load_state_dict(it.underlying.state_dict())  # pre-16 sidecar

    def test_reshard_carries_cursor(self):
        sh = _data_sharding(8)
        x, y = self._rows()
        it = ShardedDataSetIterator(
            ListDataSetIterator(DataSet(x, y), 8, shuffle=False), sh,
            process_count=1)
        first = [np.asarray(it.next().features) for _ in range(2)]
        closed = []
        it.underlying.close = lambda *a, **kw: closed.append(True)
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from deeplearning4j_tpu.parallel.mesh import make_mesh

        half = NamedSharding(  # the shrunk fleet's 4-device data axis
            make_mesh(devices=jax.devices()[:4], data=4), P("data"))
        new_under = ListDataSetIterator(DataSet(x, y), 8, shuffle=False)
        it.reshard(new_under, half)
        assert it.underlying is new_under and closed == [True]
        rest = []
        while it.has_next():
            rest.append(np.asarray(it.next().features))
        got = np.concatenate(first + rest)
        np.testing.assert_array_equal(got, x)  # nothing twice, none skipped
        assert rest[0].shape[0] == 8  # global batch preserved

    def test_reshard_refuses_global_batch_change_and_rolls_back(self):
        sh = _data_sharding(8)
        x, y = self._rows()
        it = ShardedDataSetIterator(
            ListDataSetIterator(DataSet(x, y), 8), sh, process_count=1)
        old = it.underlying
        with pytest.raises(ValueError, match="global batch"):
            it.reshard(ListDataSetIterator(DataSet(x, y), 4))
        assert it.underlying is old  # swap rolled back, pipeline intact
        assert it.next().features.shape[0] == 8
