"""Distributed training tests on the 8-virtual-device CPU mesh.

Mirrors the reference's "distributed without a cluster" strategy
(SURVEY.md §4): ParallelWrapper/SharedTraining semantics validated
in-process. Key correctness claim: distributed training with the default
sync strategy must match single-device training bit-for-bit-ish (same
global batch, same seed ⇒ same loss trajectory), because mean-loss over a
sharded batch IS the all-reduced gradient.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.nn import (
    Activation,
    InputType,
    LossFunction,
    MultiLayerNetwork,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.train import Sgd
from deeplearning4j_tpu.parallel import (
    DistributedTrainer,
    InferenceMode,
    MeshSpec,
    ParallelInference,
    ParameterAveragingSync,
    SyncAllReduce,
    ThresholdCompressedSync,
    make_mesh,
)


def _mlp(seed=7, nin=12, nout=3):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Sgd(0.1))
        .list()
        .layer(DenseLayer(n_out=16, activation=Activation.TANH))
        .layer(OutputLayer(n_out=nout, loss=LossFunction.MCXENT))
        .set_input_type(InputType.feed_forward(nin))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _data(n=64, nin=12, nout=3, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, nin).astype(np.float32)
    y = np.eye(nout, dtype=np.float32)[rng.randint(0, nout, n)]
    return x, y


class TestMesh:
    def test_make_mesh_default_all_devices(self):
        mesh = make_mesh()
        assert mesh.devices.size == len(jax.devices())
        assert mesh.axis_names == ("data",)

    def test_mesh_spec_wildcard(self):
        sizes = MeshSpec(data=-1, model=2).resolve(8)
        assert sizes == {"data": 4, "model": 2}

    def test_mesh_spec_mismatch_raises(self):
        with pytest.raises(ValueError):
            MeshSpec(data=3).resolve(8)

    def test_2d_mesh(self):
        mesh = make_mesh(data=4, model=2)
        assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2


class TestDistributedTrainer:
    def test_matches_single_device(self):
        """DP training == single-device training on the same global batch."""
        x, y = _data(64)
        m_single = _mlp(seed=3)
        m_dist = _mlp(seed=3)

        from deeplearning4j_tpu.train.solver import Solver

        solver = Solver(m_single)
        trainer = DistributedTrainer(m_dist, mesh=make_mesh(data=8))

        for _ in range(5):
            s_single, _ = solver.fit_batch(x, y)
            s_dist = trainer.fit_batch(x, y)
        trainer.sync_to_model()
        assert np.allclose(float(s_single), float(s_dist), rtol=1e-4)
        for lname in m_single.params:
            for pname in m_single.params[lname]:
                np.testing.assert_allclose(
                    np.asarray(m_single.params[lname][pname]),
                    np.asarray(m_dist.params[lname][pname]),
                    rtol=2e-4, atol=2e-5,
                )

    def test_fit_reduces_loss(self):
        x, y = _data(64)
        model = _mlp()
        trainer = DistributedTrainer(model, mesh=make_mesh(data=8))
        first = float(trainer.fit_batch(x, y))
        for _ in range(30):
            last = float(trainer.fit_batch(x, y))
        assert last < first

    def test_threshold_compressed_strategy_trains(self):
        x, y = _data(64)
        model = _mlp()
        trainer = DistributedTrainer(
            model,
            mesh=make_mesh(data=8),
            strategy=ThresholdCompressedSync(threshold=1e-3, target_density=0.2),
        )
        first = float(trainer.fit_batch(x, y))
        for _ in range(60):
            last = float(trainer.fit_batch(x, y))
        assert last < first
        # adaptive threshold moved off its initial value
        assert trainer.threshold_value() is not None
        assert trainer.threshold_value() != pytest.approx(1e-3)

    def test_parameter_averaging_strategy(self):
        x, y = _data(64)
        model = _mlp()
        trainer = DistributedTrainer(
            model, mesh=make_mesh(data=8), strategy=ParameterAveragingSync(frequency=4)
        )
        first = float(trainer.fit_batch(x, y))
        for _ in range(40):
            last = float(trainer.fit_batch(x, y))
        assert last < first
        trainer.sync_to_model()
        # after sync replicas must agree -> params finite and consistent
        for lp in model.params.values():
            for p in lp.values():
                assert np.all(np.isfinite(np.asarray(p)))
        # exported (averaged) params and the trainer's sharded forward must
        # agree: sync_to_model performed the final average, not a device-0 dump
        np.testing.assert_allclose(
            np.asarray(trainer.output(x)), np.asarray(model.output(x)),
            rtol=1e-5, atol=1e-6,
        )

    def test_tensor_parallel_rules(self):
        """DP×TP mesh: dense kernels sharded over the model axis; forward
        and training still match the replicated result."""
        x, y = _data(32)
        m_ref = _mlp(seed=11)
        m_tp = _mlp(seed=11)

        mesh = make_mesh(data=4, model=2)
        rules = [
            (r"layer_0/W", P(None, "model")),  # column-parallel
            (r"layer_1/W", P("model", None)),  # row-parallel
        ]
        trainer = DistributedTrainer(m_tp, mesh=mesh, param_sharding_rules=rules)

        from deeplearning4j_tpu.train.solver import Solver

        solver = Solver(m_ref)
        for _ in range(3):
            s_ref, _ = solver.fit_batch(x, y)
            s_tp = trainer.fit_batch(x, y)
        assert np.allclose(float(s_ref), float(s_tp), rtol=1e-4)
        out_ref = np.asarray(m_ref.output(x))
        out_tp = np.asarray(trainer.output(x))
        np.testing.assert_allclose(out_ref, out_tp, rtol=2e-4, atol=2e-5)

    def test_explicit_rejects_tp_rules(self):
        with pytest.raises(ValueError):
            DistributedTrainer(
                _mlp(),
                mesh=make_mesh(data=8),
                strategy=ThresholdCompressedSync(),
                param_sharding_rules=[("layer_0/W", P(None, "model"))],
            )

    def test_fit_iterator_api(self):
        x, y = _data(64)
        model = _mlp()
        trainer = DistributedTrainer(model, mesh=make_mesh(data=8))
        trainer.fit(x, y, epochs=3)
        assert model.score_value is not None and np.isfinite(model.score_value)


class TestFitRechunking:
    """Non-divisible batches are re-chunked, not silently dropped
    (VERDICT.md round-1 weak item 6; reference repartitioned instead)."""

    def test_all_rows_train_with_carry(self):
        model = _mlp()
        trainer = DistributedTrainer(model, mesh=make_mesh(
            data=4, devices=jax.devices()[:4]))
        x, y = _data(18)  # 3 batches of 6 against a 4-wide data axis
        batches = [(x[i:i + 6], y[i:i + 6]) for i in (0, 6, 12)]

        class _It:
            def __iter__(self):
                from deeplearning4j_tpu.data.dataset import DataSet
                return iter([DataSet(f, l) for f, l in batches])

        with pytest.warns(UserWarning, match="tail row"):
            trainer.fit(_It())
        # emit chunk = 4; 18 rows -> 4 chunks of 4 trained, 2 dropped+warned
        assert model.iteration_count == 4
        assert trainer.dropped_rows == 2

    def test_divisible_batches_no_warning_no_drop(self):
        import warnings as _w

        model = _mlp()
        trainer = DistributedTrainer(model, mesh=make_mesh(
            data=4, devices=jax.devices()[:4]))
        x, y = _data(16)
        with _w.catch_warnings():
            _w.simplefilter("error")
            trainer.fit(x, y)
        assert trainer.dropped_rows == 0


class TestParallelInferenceResilience:
    """Overload + failure paths (core/resilience.py), all deterministic:
    the worker is parked on an Event via injected latency, the breaker
    runs on a fake clock, and faults come from a seeded FaultInjector."""

    def _pi(self, **kw):
        from deeplearning4j_tpu.core.resilience import FaultInjector
        import threading

        entered = threading.Event()   # worker reached the forward site
        release = threading.Event()   # test lets the worker proceed

        def gate_sleep(_seconds):
            entered.set()
            assert release.wait(timeout=10), "test never released the worker"

        inj = FaultInjector(sleep=gate_sleep)
        kw.setdefault("workers", 1)
        kw.setdefault("batch_limit", 1)
        pi = ParallelInference(_mlp(), fault_injector=inj, **kw)
        return pi, inj, entered, release

    def test_queue_full_sheds_fail_fast(self):
        from deeplearning4j_tpu.core.resilience import AdmissionRejectedError
        from deeplearning4j_tpu.parallel.inference import FORWARD_SITE

        pi, inj, entered, release = self._pi(queue_limit=2)
        inj.inject_latency(FORWARD_SITE, 1.0, times=1)
        x, _ = _data(4)
        try:
            f1 = pi.output_async(x[0])          # worker parks on this one
            assert entered.wait(timeout=10)
            f2 = pi.output_async(x[1])          # fills the pending window
            with pytest.raises(AdmissionRejectedError):
                pi.output_async(x[2])           # shed NOW, no blocking
        finally:
            release.set()
        f1.result(timeout=10)
        f2.result(timeout=10)
        s = pi.stats()
        assert s["accepted"] == 2 and s["shed"] == 1
        assert s["completed"] == 2
        pi.shutdown()

    def test_deadline_expiry_in_queue_skips_forward(self):
        from deeplearning4j_tpu.core.resilience import (
            Deadline, DeadlineExceededError)
        from deeplearning4j_tpu.parallel.inference import FORWARD_SITE

        clk_t = [0.0]
        pi, inj, entered, release = self._pi(
            queue_limit=8, clock=lambda: clk_t[0])
        inj.inject_latency(FORWARD_SITE, 1.0, times=1)
        x, _ = _data(4)
        try:
            f1 = pi.output_async(x[0])
            assert entered.wait(timeout=10)
            f2 = pi.output_async(x[1], timeout=0.5)  # waits behind f1
            clk_t[0] += 1.0                          # expires f2 in-queue
        finally:
            release.set()
        f1.result(timeout=10)
        with pytest.raises(DeadlineExceededError):
            f2.result(timeout=10)
        s = pi.stats()
        assert s["timed_out"] == 1
        assert s["batches"] == 1  # the expired request never cost a forward
        pi.shutdown()

    def test_circuit_opens_on_poisoned_forward_then_recovers(self):
        from deeplearning4j_tpu.core.resilience import (
            CircuitBreaker, CircuitOpenError, CircuitState, FaultInjector)
        from deeplearning4j_tpu.parallel.inference import FORWARD_SITE

        clk_t = [0.0]
        clock = lambda: clk_t[0]  # noqa: E731
        inj = FaultInjector()
        inj.inject_error(FORWARD_SITE, lambda: RuntimeError("poisoned jit"),
                         times=3)
        breaker = CircuitBreaker(failure_threshold=1.0, min_calls=3,
                                 window=8, open_timeout=5.0, clock=clock)
        pi = ParallelInference(_mlp(), workers=1, batch_limit=1,
                               circuit_breaker=breaker, clock=clock,
                               fault_injector=inj)
        x, _ = _data(4)
        # three poisoned forwards trip the breaker at the threshold
        for i in range(3):
            with pytest.raises(RuntimeError, match="poisoned"):
                pi.output(x[i])
        assert pi.circuit_state is CircuitState.OPEN
        with pytest.raises(CircuitOpenError) as ei:
            pi.output_async(x[0])  # rejected at the door, nothing queued
        assert ei.value.retry_after > 0
        assert pi.stats()["circuit_rejected"] == 1
        # after the open timeout one probe goes through and closes it
        clk_t[0] += 5.0
        assert pi.circuit_state is CircuitState.HALF_OPEN
        out = pi.output(x[0])
        assert np.all(np.isfinite(np.asarray(out)))
        assert pi.circuit_state is CircuitState.CLOSED
        assert pi.stats()["failed"] == 3
        pi.shutdown()

    def test_graceful_drain(self):
        pi = ParallelInference(_mlp(), workers=2, batch_limit=4)
        x, _ = _data(8)
        futs = [pi.output_async(x[i]) for i in range(8)]
        assert pi.drain(timeout=30)
        assert all(f.done() for f in futs)
        with pytest.raises(RuntimeError, match="draining"):
            pi.output_async(x[0])
        assert pi.stats()["draining"]
        pi.shutdown()

    def test_stats_snapshot_shape(self):
        pi = ParallelInference(_mlp(), workers=1, batch_limit=8)
        x, _ = _data(4)
        pi.output(x)
        s = pi.stats()
        assert s["accepted"] == s["completed"] == 1
        assert s["shed"] == s["timed_out"] == s["failed"] == 0
        assert s["batches"] == 1 and s["max_batch_size"] == 4
        assert s["mean_batch_size"] == pytest.approx(4.0)
        assert s["circuit_state"] == "closed"
        assert s["queue_depth"] == 0
        assert s["padded_rows"] == 0  # 4 rows hit the 4-bucket exactly
        pi.shutdown()

    def test_padded_rows_counted(self):
        """Bucketing pads 3 rows up to the 4-bucket: the wasted row shows
        up in stats() and the dl4j_tpu_inference_padded_rows_total series,
        and real rows are never counted as padding."""
        from deeplearning4j_tpu.obs import MetricsRegistry

        reg = MetricsRegistry()
        pi = ParallelInference(_mlp(), workers=1, batch_limit=8,
                               registry=reg, name="pad-test")
        x, _ = _data(3)
        pi.output(x)
        s = pi.stats()
        assert s["padded_rows"] == 1
        assert s["batches"] == 1 and s["max_batch_size"] == 3
        fam = reg.get("dl4j_tpu_inference_padded_rows_total")
        assert fam.labels("pad-test").value == 1
        # an exact power-of-two batch adds no padding
        x4, _ = _data(4)
        pi.output(x4)
        assert pi.stats()["padded_rows"] == 1
        pi.shutdown()


class TestParallelInference:
    def test_batched_matches_direct(self):
        model = _mlp()
        x, _ = _data(16)
        pi = ParallelInference(model, inference_mode=InferenceMode.BATCHED, batch_limit=8)
        try:
            futures = [pi.output_async(x[i]) for i in range(16)]
            outs = np.stack([f.result(timeout=30) for f in futures])
        finally:
            pi.shutdown()
        direct = np.asarray(model.output(x))
        np.testing.assert_allclose(outs, direct, rtol=1e-5, atol=1e-6)

    def test_sequential_mode_and_batch_requests(self):
        model = _mlp()
        x, _ = _data(8)
        pi = ParallelInference(model, inference_mode=InferenceMode.SEQUENTIAL, workers=1)
        try:
            out = pi.output(x)  # a whole batch as one request
        finally:
            pi.shutdown()
        np.testing.assert_allclose(out, np.asarray(model.output(x)), rtol=1e-5, atol=1e-6)


def test_distributed_trainer_computation_graph():
    """DistributedTrainer drives ComputationGraph models (the ResNet-50
    path): DP training converges and matches GraphSolver single-device
    losses; output() serves the graph's network output sharded."""
    import numpy as np

    from deeplearning4j_tpu.model.zoo import SqueezeNet
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.trainer import DistributedTrainer
    from deeplearning4j_tpu.train.graph_solver import GraphSolver

    rs = np.random.RandomState(0)
    x = rs.rand(16, 3, 48, 48).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 16)]

    def build():
        return SqueezeNet(num_classes=4, height=48, width=48, seed=5).init()

    trainer = DistributedTrainer(build(), mesh=make_mesh(data=8))
    dist = [float(trainer.fit_batch(x, y)) for _ in range(4)]

    solver = GraphSolver(build())
    ref = [float(solver.fit_batch((x,), (y,))) for _ in range(4)]
    np.testing.assert_allclose(dist, ref, rtol=1e-4)
    assert dist[-1] < dist[0]

    out = np.asarray(trainer.output(x))
    assert out.shape == (16, 4)
    np.testing.assert_allclose(out.sum(1), 1.0, atol=1e-4)


class TestTransformerTensorParallel:
    """VERDICT r4 ask 5: TP proven on a transformer, not LeNet's Dense
    layers — BertEncoder QKV/FFN kernels sharded over 'model' with
    Megatron column/row rules, loss-equal to the unsharded run."""

    BERT_KW = dict(vocab_size=50, hidden=32, n_layers=2, n_heads=4,
                   ffn_size=64, max_len=16, seed=7)

    # Megatron layout: QKV and FFN-in are column-parallel (activations
    # split over heads/ffn), attention-out and FFN-out are row-parallel
    # (XLA inserts the psum). Biases of column-parallel layers shard too.
    TP_RULES = [
        (r".*_attn/W[qkv]$", P(None, "model")),
        (r".*_attn/Wo$", P("model", None)),
        (r".*_ffn1/W$", P(None, "model")),
        (r".*_ffn1/b$", P("model")),
        (r".*_ffn2/W$", P("model", None)),
    ]

    def _data(self, batch=8):
        rs = np.random.RandomState(0)
        ids = rs.randint(0, 50, (batch, 16)).astype(np.int32)
        labels = rs.randint(0, 50, (batch, 16)).astype(np.int32)
        return ids, labels

    def test_bert_tp_loss_equals_unsharded(self):
        from deeplearning4j_tpu.model.zoo import BertEncoder
        from deeplearning4j_tpu.train.graph_solver import GraphSolver

        m_ref = BertEncoder(**self.BERT_KW).init()
        m_tp = BertEncoder(**self.BERT_KW).init()
        mesh = make_mesh(data=2, model=4)
        trainer = DistributedTrainer(m_tp, mesh=mesh,
                                     param_sharding_rules=self.TP_RULES)
        ids, labels = self._data()
        solver = GraphSolver(m_ref)
        for _ in range(3):
            s_ref = solver.fit_batch((ids,), (labels,))
            s_tp = trainer.fit_batch(ids, labels)
        s_ref = s_ref[0] if isinstance(s_ref, tuple) else s_ref
        assert np.allclose(float(s_ref), float(s_tp), rtol=1e-4), \
            (float(s_ref), float(s_tp))
        trainer.sync_to_model()
        for lname in m_ref.params:
            for pname in m_ref.params[lname]:
                np.testing.assert_allclose(
                    np.asarray(jax.device_get(m_ref.params[lname][pname])),
                    np.asarray(jax.device_get(m_tp.params[lname][pname])),
                    rtol=5e-3, atol=5e-5, err_msg=f"{lname}/{pname}")

    def test_bert_tp_kernels_actually_sharded(self):
        """The rules must HIT: each block's Wq/Wk/Wv/Wo/ffn kernels live
        sharded over the model axis, not replicated."""
        from deeplearning4j_tpu.model.zoo import BertEncoder

        m_tp = BertEncoder(**self.BERT_KW).init()
        mesh = make_mesh(data=2, model=4)
        trainer = DistributedTrainer(m_tp, mesh=mesh,
                                     param_sharding_rules=self.TP_RULES)
        ids, labels = self._data()
        trainer.fit_batch(ids, labels)
        hit = []
        for lname, lparams in trainer.params.items():
            for pname, arr in lparams.items():
                spec = getattr(arr.sharding, "spec", None)
                if spec is not None and "model" in str(spec):
                    hit.append(f"{lname}/{pname}")
        for blk in ("blk0", "blk1"):
            for suffix in ("_attn/Wq", "_attn/Wk", "_attn/Wv", "_attn/Wo",
                           "_ffn1/W", "_ffn2/W"):
                assert any(h == blk + suffix for h in hit), (blk + suffix, hit)
