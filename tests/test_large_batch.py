"""Pod-scale large-batch training stack (ISSUE 14): LARS/LAMB trust-ratio
updaters, distributed batch norm, and bucketed backward-overlapped gradient
exchange — unit + equivalence coverage on the 8-virtual-device CPU mesh.

The three MLPerf-0.6 TPU-pods walls (PAPERS.md, arxiv 1909.09756) and the
contracts enforced here:

* plain SGD/Adam stops converging at huge global batch → Lars/Lamb with
  the layer-wise trust ratio; their norms are the only cross-element
  coupling, spelled slice-local + psum under ZeRO-1 (zero1==replicated is
  auto-discovered per updater in tests/test_zero1.py).
* per-replica BN statistics degrade as the per-chip batch shrinks →
  ``BatchNormalizationLayer(stats_axis_group=)`` /
  ``DistributedTrainer(bn_group_size=)`` — grouped moments agree between
  the explicit (psum over replica groups) and implicit (sharded reshape)
  spellings, and running-stat state keeps its shape.
* serial gradient exchange idles the DCN during backprop →
  ``BucketedAllReduceSync`` — per-bucket psums in reverse layer order,
  trajectory EXACTLY the unbucketed all-reduce.

Plus the ISSUE 14 audit: ``GradientNormalization`` CLIP/RENORM per-layer
norms must act on POST-SYNC global gradients on both trainer paths.
"""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.core.config import from_json, to_json
from deeplearning4j_tpu.nn import (
    Activation,
    GradientNormalization,
    InputType,
    LossFunction,
    MultiLayerNetwork,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import (
    BatchNormalizationLayer,
    DenseLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.layers.base import DistContext, LayerContext
from deeplearning4j_tpu.obs import MetricsRegistry
from deeplearning4j_tpu.parallel import (
    BucketedAllReduceSync,
    DistributedTrainer,
    TopKCompressedSync,
    make_mesh,
)
from deeplearning4j_tpu.train import (
    Adam,
    ExponentialSchedule,
    Lamb,
    Lars,
    Sgd,
    WarmupSchedule,
)


def _mlp(seed=7, updater=None, bn=False, bn_group=None, grad_norm=None,
         nin=16, hidden=64, nout=8):
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(updater or Adam(0.01)))
    if grad_norm is not None:
        b = b.gradient_normalization(grad_norm)
        b = b.gradient_normalization_threshold(0.5)
    b = b.list().layer(DenseLayer(n_out=hidden, activation=Activation.TANH))
    if bn:
        b = b.layer(BatchNormalizationLayer(stats_axis_group=bn_group))
    conf = (b.layer(OutputLayer(n_out=nout, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(nin)).build())
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0, nin=16, nout=8):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, nin).astype(np.float32)
    y = np.eye(nout, dtype=np.float32)[rng.randint(0, nout, n)]
    return x, y


def _params_close(a, b, rtol=3e-5, atol=3e-6):
    for ln in a:
        for pn in a[ln]:
            np.testing.assert_allclose(
                np.asarray(a[ln][pn]), np.asarray(b[ln][pn]),
                rtol=rtol, atol=atol, err_msg=f"{ln}/{pn}")


# ---------------------------------------------------------------- updaters
class TestTrustRatioUpdaters:
    def test_lamb_trains_and_exposes_trust(self):
        x, y = _data()
        t = DistributedTrainer(_mlp(3, Lamb(0.02)), mesh=make_mesh(data=8))
        first = float(t.fit_batch(x, y))
        for _ in range(30):
            last = float(t.fit_batch(x, y))
        assert last < first
        stats = t.trust_ratio_stats()
        assert "layer_0/W" in stats and "layer_1/b" in stats
        for entry in stats.values():
            assert entry["trust_ratio"] > 0.0
            assert entry["update_norm"] >= 0.0

    def test_lars_trains(self):
        x, y = _data()
        t = DistributedTrainer(_mlp(5, Lars(0.5, trust_coefficient=1e-2)),
                               mesh=make_mesh(data=8))
        first = float(t.fit_batch(x, y))
        for _ in range(30):
            last = float(t.fit_batch(x, y))
        assert last < first

    def test_trust_ratio_zero_norm_falls_back_to_one(self):
        """A zero-initialized param (bias) must take a plain (ratio-1)
        step, not a 0/0 one."""
        import jax.numpy as jnp

        tx = Lamb(0.01).to_optax()
        params = {"b": jnp.zeros((4,))}
        st = tx.init(params)
        upd, st = tx.update({"b": jnp.full((4,), 0.5)}, st, params)
        assert np.all(np.isfinite(np.asarray(upd["b"])))
        assert float(st["trust"]["b"]) == pytest.approx(1.0)

    def test_zero1_explicit_path_psum_norms(self):
        """The hand-spelled shard_map ZeRO-1 schedule with a trust-ratio
        updater: slice-local + psum'd norms keep the 1/N-slice update
        exactly the replicated one (losses AND params), under the
        bucketed exchange too."""
        x, y = _data()
        mesh = make_mesh(data=8)
        for updater in (Lamb(0.01), Lars(0.1)):
            t_rep = DistributedTrainer(_mlp(5, updater), mesh=mesh,
                                       strategy=BucketedAllReduceSync())
            t_z = DistributedTrainer(_mlp(5, updater), mesh=mesh,
                                     strategy=BucketedAllReduceSync(),
                                     zero1=True)
            for _ in range(5):
                s_rep = float(t_rep.fit_batch(x, y))
                s_z = float(t_z.fit_batch(x, y))
            assert np.isclose(s_rep, s_z, rtol=1e-5), (updater, s_rep, s_z)
            t_rep.sync_to_model()
            t_z.sync_to_model()
            _params_close(t_rep.model.params, t_z.model.params)

    def test_trust_metrics_land_in_registry(self):
        x, y = _data()
        reg = MetricsRegistry()
        t = DistributedTrainer(_mlp(3, Lamb(0.01)), mesh=make_mesh(data=8),
                               registry=reg, metrics_every=2)
        for _ in range(4):
            t.fit_batch(x, y)
        g = reg.get("dl4j_tpu_training_trust_ratio")
        assert g is not None and g.labels("layer_0/W").value > 0
        gn = reg.get("dl4j_tpu_training_grad_norm")
        assert gn is not None and gn.labels("layer_0/W").value > 0

    def test_non_trust_updater_has_no_trust_series(self):
        x, y = _data()
        reg = MetricsRegistry()
        t = DistributedTrainer(_mlp(3, Adam(0.01)), mesh=make_mesh(data=8),
                               registry=reg)
        t.fit_batch(x, y)
        assert t.trust_ratio_stats() == {}
        assert reg.get("dl4j_tpu_training_trust_ratio") is None

    def test_updater_json_round_trip(self):
        for u in (Lars(0.1, momentum=0.8, weight_decay=1e-4),
                  Lamb(0.01, weight_decay=0.01, trust_coefficient=0.9)):
            assert from_json(to_json(u)) == u


# ---------------------------------------------------------------- schedule
class TestWarmupSchedule:
    def test_linear_warmup_then_base(self):
        s = WarmupSchedule(base=None, warmup_iterations=10, base_value=2.0)
        assert float(s(0)) == pytest.approx(0.2)
        assert float(s(4)) == pytest.approx(1.0)
        assert float(s(9)) == pytest.approx(2.0)
        assert float(s(100)) == pytest.approx(2.0)

    def test_composes_with_any_base(self):
        base = ExponentialSchedule(initial_value=1.0, gamma=0.5)
        s = WarmupSchedule(base=base, warmup_iterations=2)
        # warmup factor 0.5 at it=0, then the base value unmodified
        assert float(s(0)) == pytest.approx(0.5 * float(base(0)))
        assert float(s(3)) == pytest.approx(float(base(3)))

    def test_zero_warmup_is_identity(self):
        s = WarmupSchedule(base=None, warmup_iterations=0, base_value=3.0)
        assert float(s(0)) == pytest.approx(3.0)

    def test_json_round_trip_nested(self):
        s = WarmupSchedule(base=ExponentialSchedule(initial_value=0.01),
                           warmup_iterations=50)
        s2 = from_json(to_json(s))
        assert s2 == s
        assert float(s2(25)) == pytest.approx(float(s(25)))

    def test_drives_an_updater_inside_jit(self):
        x, y = _data()
        sched = WarmupSchedule(warmup_iterations=3, base_value=0.02)
        t = DistributedTrainer(_mlp(3, Lamb(sched)), mesh=make_mesh(data=8),
                               zero1=True)
        scores = [float(t.fit_batch(x, y)) for _ in range(5)]
        assert all(np.isfinite(s) for s in scores)


# ------------------------------------------------------- distributed BN
class TestDistributedBatchNorm:
    def test_explicit_matches_implicit_grouped(self):
        """Grouped moments agree between the two spellings: psum over
        replica groups (shard_map) vs the sharded reshape (GSPMD) —
        trajectory AND running stats."""
        x, y = _data()
        mesh = make_mesh(data=8)
        t_imp = DistributedTrainer(_mlp(9, bn=True), mesh=mesh,
                                   bn_group_size=2)
        t_exp = DistributedTrainer(_mlp(9, bn=True), mesh=mesh,
                                   bn_group_size=2,
                                   strategy=BucketedAllReduceSync())
        for _ in range(4):
            s_i = float(t_imp.fit_batch(x, y))
            s_e = float(t_exp.fit_batch(x, y))
        assert np.isclose(s_i, s_e, rtol=1e-4), (s_i, s_e)
        t_imp.sync_to_model()
        t_exp.sync_to_model()
        for k in ("mean", "var"):
            np.testing.assert_allclose(
                np.asarray(t_imp.model.state["layer_1"][k]),
                np.asarray(t_exp.model.state["layer_1"][k]),
                rtol=1e-4, atol=1e-6, err_msg=k)

    def test_full_axis_group_equals_global_stats(self):
        """group == data axis width: the explicit path's grouped stats
        ARE the global batch stats — i.e. the implicit path's historical
        (ungrouped) spelling."""
        x, y = _data()
        mesh = make_mesh(data=8)
        t_global = DistributedTrainer(_mlp(9, bn=True), mesh=mesh)  # implicit
        t_exp = DistributedTrainer(_mlp(9, bn=True), mesh=mesh,
                                   bn_group_size=8,
                                   strategy=BucketedAllReduceSync())
        for _ in range(3):
            s_g = float(t_global.fit_batch(x, y))
            s_e = float(t_exp.fit_batch(x, y))
        assert np.isclose(s_g, s_e, rtol=1e-4), (s_g, s_e)

    def test_cnn_4d_activations_grouped(self):
        """Per-channel grouped moments on [b, c, h, w] conv activations:
        both spellings reduce rows+spatial per group and agree."""
        from deeplearning4j_tpu.nn.layers import ConvolutionLayer

        conf = (NeuralNetConfiguration.builder().seed(3)
                .updater(Lamb(0.01)).list()
                .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                        stride=(1, 1)))
                .layer(BatchNormalizationLayer())
                .layer(OutputLayer(n_out=4, loss=LossFunction.MCXENT))
                .set_input_type(InputType.convolutional(8, 8, 1)).build())

        def build():
            return MultiLayerNetwork(conf).init()

        rng = np.random.RandomState(0)
        x = rng.randn(32, 1, 8, 8).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 32)]
        mesh = make_mesh(data=8)
        t_i = DistributedTrainer(build(), mesh=mesh, bn_group_size=4)
        t_e = DistributedTrainer(build(), mesh=mesh, bn_group_size=4,
                                 strategy=BucketedAllReduceSync())
        for _ in range(3):
            s_i = float(t_i.fit_batch(x, y))
            s_e = float(t_e.fit_batch(x, y))
        assert np.isclose(s_i, s_e, rtol=1e-4), (s_i, s_e)

    def test_group_size_changes_training_statistics(self):
        """bn_group_size=1 (per-replica stats) vs the global batch: the
        moments genuinely differ, so the trajectories must diverge —
        grouping is not a no-op."""
        x, y = _data()
        mesh = make_mesh(data=8)
        t_local = DistributedTrainer(_mlp(9, bn=True), mesh=mesh,
                                     bn_group_size=1)
        t_global = DistributedTrainer(_mlp(9, bn=True), mesh=mesh)
        for _ in range(3):
            s_l = float(t_local.fit_batch(x, y))
            s_g = float(t_global.fit_batch(x, y))
        assert not np.isclose(s_l, s_g, rtol=1e-6), (s_l, s_g)

    def test_layer_field_overrides_trainer_default(self):
        x, y = _data()
        mesh = make_mesh(data=8)
        # layer pins group 4; trainer default 2 must not apply to it
        t_a = DistributedTrainer(_mlp(9, bn=True, bn_group=4), mesh=mesh,
                                 bn_group_size=2)
        t_b = DistributedTrainer(_mlp(9, bn=True, bn_group=4), mesh=mesh,
                                 bn_group_size=4)
        for _ in range(3):
            s_a = float(t_a.fit_batch(x, y))
            s_b = float(t_b.fit_batch(x, y))
        assert np.isclose(s_a, s_b, rtol=1e-6), (s_a, s_b)

    def test_state_shape_and_checkpoint_compat(self):
        """Running-stat state keeps its [n_out] shape under grouping —
        group-size independent, so checkpoints stay compatible."""
        x, y = _data()
        t = DistributedTrainer(_mlp(9, bn=True), mesh=make_mesh(data=8),
                               bn_group_size=4)
        t.fit_batch(x, y)
        t.sync_to_model()
        st = t.model.state["layer_1"]
        assert np.shape(st["mean"]) == (64,)
        assert np.shape(st["var"]) == (64,)
        assert t.stats()["bn_group_size"] == 4

    def test_invalid_group_rejected(self):
        with pytest.raises(ValueError, match="divide the data"):
            DistributedTrainer(_mlp(9, bn=True), mesh=make_mesh(data=8),
                               bn_group_size=3)
        x, y = _data()
        t = DistributedTrainer(_mlp(9, bn=True, bn_group=5),
                               mesh=make_mesh(data=8))
        with pytest.raises(ValueError, match="stats_axis_group"):
            t.fit_batch(x, y)

    def test_no_dist_context_is_classic_local(self):
        """Outside a DistributedTrainer (Solver path, ctx.dist None) the
        layer ignores stats_axis_group and normalizes locally."""
        import jax.numpy as jnp

        layer = BatchNormalizationLayer(n_out=4, stats_axis_group=4)
        params = layer.init(jax.random.PRNGKey(0), jnp.float32)
        state = layer.init_state(jnp.float32)
        x = jnp.asarray(np.random.RandomState(0).randn(8, 4), jnp.float32)
        y, _ = layer.apply(params, state, x, LayerContext(train=True))
        ref, _ = BatchNormalizationLayer(n_out=4).apply(
            params, state, x, LayerContext(train=True))
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-6)

    def test_config_json_round_trip(self):
        layer = BatchNormalizationLayer(n_out=32, stats_axis_group=4)
        assert from_json(to_json(layer)) == layer


# -------------------------------------------------- bucketed all-reduce
class TestBucketedAllReduceSync:
    def test_exact_trajectory_vs_implicit_all_reduce(self):
        """psum of a concatenation == concatenation of psums: the
        bucketed exchange follows the unbucketed trajectory exactly
        (losses and params), across bucket granularities."""
        x, y = _data()
        mesh = make_mesh(data=8)
        t_ref = DistributedTrainer(_mlp(7), mesh=mesh)
        trainers = [DistributedTrainer(
            _mlp(7), mesh=mesh,
            strategy=BucketedAllReduceSync(bucket_bytes=bb))
            for bb in (1 << 8, 1 << 12, 4 << 20)]
        for _ in range(4):
            s_ref = float(t_ref.fit_batch(x, y))
            for t in trainers:
                assert np.isclose(s_ref, float(t.fit_batch(x, y)),
                                  rtol=1e-5)
        t_ref.sync_to_model()
        for t in trainers:
            t.sync_to_model()
            _params_close(t_ref.model.params, t.model.params)

    def test_bucket_layout_reverse_layer_order(self):
        strat = BucketedAllReduceSync(bucket_bytes=1 << 8)  # 256B: splits
        params = {
            "layer_0": {"W": np.zeros((16, 64), np.float32),
                        "b": np.zeros((64,), np.float32)},
            "layer_1": {"W": np.zeros((64, 8), np.float32),
                        "b": np.zeros((8,), np.float32)},
        }
        strat.init_state(params)
        order = [(ln, pn) for _, bucket in strat._buckets
                 for ln, pn, _, _ in bucket]
        # reverse layer order: the output layer's grads exist first
        assert order[0][0] == "layer_1"
        assert order.index(("layer_1", "W")) < order.index(("layer_0", "W"))
        stats = strat.compression_stats(())
        assert stats["buckets"] == len(strat._buckets) > 1
        total = sum(p.size * 4 for lp in params.values() for p in lp.values())
        assert stats["total_exchanged_bytes"] == total
        assert sum(stats["bucket_volume_bytes"]) == total

    def test_composes_with_zero1(self):
        x, y = _data()
        mesh = make_mesh(data=8)
        t = DistributedTrainer(_mlp(5), mesh=mesh, zero1=True,
                               strategy=BucketedAllReduceSync())
        t_ref = DistributedTrainer(_mlp(5), mesh=mesh)
        for _ in range(4):
            s = float(t.fit_batch(x, y))
            s_ref = float(t_ref.fit_batch(x, y))
        assert np.isclose(s, s_ref, rtol=1e-5), (s, s_ref)
        # zero1 actually sharded the moments
        assert t.updater_state_bytes() < t.updater_state_bytes(
            per_replica=False) / 5

    def test_no_compression_metrics_but_stats_visible(self):
        x, y = _data()
        reg = MetricsRegistry()
        t = DistributedTrainer(_mlp(5), mesh=make_mesh(data=8), registry=reg,
                               strategy=BucketedAllReduceSync())
        t.fit_batch(x, y)
        comp = t.compression_stats()
        assert comp["buckets"] >= 1
        assert comp["total_exchanged_bytes"] > 0
        assert t.threshold_value() is None
        # not a compressed strategy: no compression-ratio histogram
        assert reg.get("dl4j_tpu_training_grad_compression_ratio") is None

    def test_invalid_bucket_bytes_rejected(self):
        with pytest.raises(ValueError):
            BucketedAllReduceSync(bucket_bytes=0)


# ------------------------------------------- gradient-normalization audit
class TestGradNormPostSync:
    """ISSUE 14 audit: per-layer CLIP/RENORM must act on the POST-SYNC
    global gradients on BOTH paths. The implicit path's grads are global
    by construction; the explicit path syncs FIRST then normalizes — if
    it ever clipped pre-sync local grads the per-layer norms (computed
    from a 1/N batch slice) would differ and these trajectories would
    silently diverge."""

    @pytest.mark.parametrize("mode", [
        GradientNormalization.CLIP_L2_PER_LAYER,
        GradientNormalization.RENORMALIZE_L2_PER_LAYER,
        GradientNormalization.CLIP_L2_PER_PARAM_TYPE,
    ], ids=["clip-layer", "renorm-layer", "clip-param"])
    def test_explicit_matches_implicit(self, mode):
        x, y = _data()
        mesh = make_mesh(data=8)
        # Sgd: stateless, so ANY divergence is the normalization's
        t_imp = DistributedTrainer(_mlp(3, Sgd(0.5), grad_norm=mode),
                                   mesh=mesh)
        t_exp = DistributedTrainer(_mlp(3, Sgd(0.5), grad_norm=mode),
                                   mesh=mesh,
                                   strategy=BucketedAllReduceSync())
        for _ in range(5):
            s_i = float(t_imp.fit_batch(x, y))
            s_e = float(t_exp.fit_batch(x, y))
        assert np.isclose(s_i, s_e, rtol=1e-5), (mode, s_i, s_e)
        t_imp.sync_to_model()
        t_exp.sync_to_model()
        _params_close(t_imp.model.params, t_exp.model.params)

    def test_clip_actually_engages(self):
        """The threshold (0.5) genuinely clips on this task — the
        equivalence above is not vacuous."""
        x, y = _data()
        mesh = make_mesh(data=8)
        t_clip = DistributedTrainer(
            _mlp(3, Sgd(0.5), grad_norm=GradientNormalization.CLIP_L2_PER_LAYER),
            mesh=mesh)
        t_none = DistributedTrainer(_mlp(3, Sgd(0.5)), mesh=mesh)
        for _ in range(3):
            s_c = float(t_clip.fit_batch(x, y))
            s_n = float(t_none.fit_batch(x, y))
        assert not np.isclose(s_c, s_n, rtol=1e-6), (s_c, s_n)
