"""Evaluation-suite tests: calibration, multi-class ROC, top-N
(VERDICT.md round 3 ask 9)."""

import numpy as np


# ---------------------------------------------------------------------------
# calibration / multi-class ROC / top-N (VERDICT.md round 3 ask 9)
# ---------------------------------------------------------------------------

def test_evaluation_calibration_perfectly_calibrated():
    from deeplearning4j_tpu.train.evaluation import EvaluationCalibration

    rng = np.random.RandomState(0)
    n = 20000
    p1 = rng.rand(n)
    labels_idx = (rng.rand(n) < p1).astype(np.int64)  # P(y=1) == p1: calibrated
    probs = np.stack([1 - p1, p1], axis=1)
    onehot = np.eye(2)[labels_idx]
    ec = EvaluationCalibration(reliability_bins=10)
    ec.eval(onehot, probs)
    mean_p, freq, counts = ec.get_reliability_info(cls=1)
    valid = counts > 100
    np.testing.assert_allclose(mean_p[valid], freq[valid], atol=0.06)
    assert ec.expected_calibration_error(cls=1) < 0.03
    assert "ECE" in ec.stats()


def test_evaluation_calibration_miscalibrated_detected():
    from deeplearning4j_tpu.train.evaluation import EvaluationCalibration

    rng = np.random.RandomState(1)
    n = 5000
    labels_idx = rng.randint(0, 2, n)           # truth is a fair coin...
    p1 = np.where(labels_idx == 1, 0.95, 0.9)   # ...but we always say ~0.9
    probs = np.stack([1 - p1, p1], axis=1)
    ec = EvaluationCalibration()
    ec.eval(np.eye(2)[labels_idx], probs)
    assert ec.expected_calibration_error(cls=1) > 0.3


def test_evaluation_calibration_histograms():
    from deeplearning4j_tpu.train.evaluation import EvaluationCalibration

    ec = EvaluationCalibration(histogram_bins=10)
    probs = np.asarray([[0.05, 0.95], [0.95, 0.05], [0.45, 0.55]])
    ec.eval(np.asarray([[0, 1], [1, 0], [0, 1]], np.float64), probs)
    edges, counts = ec.get_probability_histogram(cls=1)
    assert counts.sum() == 3 and len(edges) == 11
    _, res_counts = ec.get_residual_plot()
    assert res_counts.sum() == 6  # both columns pooled


def test_roc_multiclass_auc():
    from deeplearning4j_tpu.train.evaluation import ROCMultiClass

    rng = np.random.RandomState(2)
    n, k = 3000, 3
    truth = rng.randint(0, k, n)
    # logits favoring the true class -> per-class AUC well above 0.5
    logits = rng.randn(n, k)
    logits[np.arange(n), truth] += 2.0
    probs = np.exp(logits) / np.exp(logits).sum(axis=1, keepdims=True)
    roc = ROCMultiClass()
    roc.eval(np.eye(k)[truth], probs)
    for c in range(k):
        assert roc.calculate_auc(c) > 0.85
    assert 0.85 < roc.calculate_average_auc() <= 1.0
    # sanity: random scores give ~0.5
    roc2 = ROCMultiClass()
    roc2.eval(np.eye(k)[truth], np.full((n, k), 1.0 / k) + rng.rand(n, k) * 1e-6)
    assert abs(roc2.calculate_average_auc() - 0.5) < 0.05


def test_roc_binary_per_output():
    from deeplearning4j_tpu.train.evaluation import ROCBinary

    rng = np.random.RandomState(3)
    n = 2000
    y = rng.randint(0, 2, (n, 2)).astype(np.float64)
    scores = np.stack([
        np.clip(y[:, 0] * 0.6 + rng.rand(n) * 0.4, 0, 1),  # informative
        rng.rand(n),                                        # random
    ], axis=1)
    rb = ROCBinary()
    rb.eval(y, scores)
    assert rb.calculate_auc(0) > 0.8
    assert abs(rb.calculate_auc(1) - 0.5) < 0.06


def test_evaluation_top_n_accuracy():
    from deeplearning4j_tpu.train.evaluation import Evaluation

    probs = np.asarray([
        [0.5, 0.3, 0.2],   # truth 1: top-1 wrong, top-2 right
        [0.1, 0.7, 0.2],   # truth 1: right
        [0.2, 0.3, 0.5],   # truth 0: top-1 wrong, top-2 wrong
        [0.6, 0.3, 0.1],   # truth 0: right
    ])
    truth = np.eye(3)[[1, 1, 0, 0]]
    e = Evaluation(top_n=2)
    e.eval(truth, probs)
    assert e.accuracy() == 0.5
    assert e.top_n_accuracy() == 0.75


def test_evaluation_calibration_binary_sigmoid_1d():
    """Regression: 1-D sigmoid outputs (the simplest calibration case)."""
    from deeplearning4j_tpu.train.evaluation import EvaluationCalibration

    rng = np.random.RandomState(4)
    p = rng.rand(5000)
    y = (rng.rand(5000) < p).astype(np.float64)
    ec = EvaluationCalibration(reliability_bins=10)
    ec.eval(y, p)
    assert ec.expected_calibration_error() < 0.05
    mean_p, freq, counts = ec.get_reliability_info(cls=0)
    assert counts.sum() == 5000


def test_roc_binary_per_example_mask():
    from deeplearning4j_tpu.train.evaluation import ROCBinary

    rng = np.random.RandomState(5)
    y = rng.randint(0, 2, 100).astype(np.float64)
    s = np.clip(y * 0.8 + rng.rand(100) * 0.2, 0, 1)
    m = (rng.rand(100) > 0.3).astype(np.float64)
    rb = ROCBinary()
    rb.eval(y, s, mask=m)  # 1-D labels + 1-D per-example mask
    assert rb.calculate_auc(0) > 0.9
