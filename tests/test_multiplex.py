"""ModelMultiplexer residency accounting and paging semantics
(serving/multiplex.py, ISSUE 19): byte-budget math against the model's
actual leaf bytes (quantized deploys resident at their int8 size), LRU
eviction with the request-rate EWMA as tie-break, park/unpark
idempotence, byte-identical quantized page-in replay, bounded
cold-start queueing, and the server's register/unregister race with
in-flight traffic. All CPU, fake clocks where ordering matters."""

import json
import threading
import urllib.request
from urllib.error import HTTPError

import numpy as np
import pytest

from deeplearning4j_tpu.core.resilience import AdmissionRejectedError, Deadline
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.obs import MetricsRegistry
from deeplearning4j_tpu.serving import (
    ModelManager,
    ModelMultiplexer,
    ModelParkedError,
    ModelStore,
    model_bytes,
)

X = np.linspace(-1.0, 1.0, 4, dtype=np.float32).reshape(1, 4)


def _model(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    return MultiLayerNetwork(conf).init()


@pytest.fixture
def store(tmp_path):
    s = ModelStore(str(tmp_path / "registry"))
    for i in range(4):
        s.publish(f"m{i}", _model(i + 1))
    return s


def _mux(store, budget, **kw):
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("manager_defaults",
                  dict(workers=1, batch_limit=4, probation_seconds=0.0,
                       warmup_example=X))
    return ModelMultiplexer(store, budget_bytes=budget, **kw)


# ----- byte accounting -------------------------------------------------
def test_model_bytes_is_leaf_bytes_and_quantized_is_smaller(store):
    """The budget's unit of account: size × itemsize over every
    params/state leaf — the cache_bytes arithmetic applied to weights —
    and an int8 rewrite pages in smaller than its f32 source."""
    import jax

    model, _ = store.load("m0", 1)
    expect = sum(int(l.size) * l.dtype.itemsize for l in
                 jax.tree_util.tree_leaves((model.params, model.state)))
    assert model_bytes(model) == expect > 0

    from deeplearning4j_tpu.nn.rewrite import rewrite_model

    q, applied = rewrite_model(model, "inference:int8",
                               context="inference")
    assert any(p.startswith("quantize_weights_") for p in applied)
    assert model_bytes(q) < model_bytes(model)


def test_resident_bytes_tracks_manager_measurements(store):
    mux = _mux(store, 10**9)
    try:
        mux.register("m0")
        mux.register("m1", optimize="inference:int8")
        assert mux.resident_bytes() == 0  # nothing loaded at register
        m0 = mux.ensure_resident("m0")
        assert mux.resident_bytes() == m0.resident_bytes() > 0
        f32_total = mux.resident_bytes()
        m1 = mux.ensure_resident("m1")
        assert mux.resident_bytes() == \
            m0.resident_bytes() + m1.resident_bytes()
        # the quantized model's residency cost is its int8 size
        assert m1.resident_bytes() < m0.resident_bytes()
        assert mux.describe()["models"]["m1"]["bytes"] == \
            m1.resident_bytes()
        mux.park("m0")
        assert mux.resident_bytes() == m1.resident_bytes()  # warm only
        assert mux.describe()["models"]["m0"]["bytes"] == 0
        assert f32_total > m1.resident_bytes()
    finally:
        mux.shutdown(drain=False)


def test_budget_enforced_and_single_model_overcommit_serves(store):
    """Eviction keeps resident bytes under budget; a budget too small
    for even ONE model overcommits (logged) instead of refusing."""
    probe = _mux(store, 10**9)
    probe.register("m0")
    probe.ensure_resident("m0")
    per = probe.resident_bytes()
    probe.shutdown(drain=False)

    mux = _mux(store, int(per * 1.5))  # room for exactly one
    try:
        for i in range(3):
            mux.register(f"m{i}")
        for i in range(3):
            np.asarray(mux.output(f"m{i}", X))
            assert mux.resident_bytes() <= int(per * 1.5)
        assert mux.describe()["resident_models"] == 1
    finally:
        mux.shutdown(drain=False)

    tiny = _mux(store, max(1, per // 2))  # smaller than any model
    try:
        tiny.register("m0")
        out = np.asarray(tiny.output("m0", X))  # still serves
        assert out.shape == (1, 3)
        assert tiny.resident_bytes() > tiny.budget_bytes  # overcommitted
    finally:
        tiny.shutdown(drain=False)


# ----- eviction policy -------------------------------------------------
def test_eviction_is_lru_with_ewma_tiebreak(store):
    clk = [100.0]
    mux = _mux(store, 10**9, clock=lambda: clk[0])
    try:
        for i in range(4):
            mux.register(f"m{i}")
        # warm m0..m2 at distinct times: m0 oldest
        for i, t in ((0, 100.0), (1, 200.0), (2, 300.0)):
            clk[0] = t
            mux.output(f"m{i}", X)
        per = mux.resident_bytes() // 3
        mux.budget_bytes = per * 3 + per // 2  # room for 3, not 4
        # pin m3's page-in estimate to its true resident size (a
        # never-loaded model estimates from the store artifact, which is
        # larger and would over-evict — correct, but not what this test
        # pins down)
        mux._slots["m3"].bytes = per
        clk[0] = 400.0
        mux.output("m3", X)  # forces one eviction
        assert mux.state("m0") == "parked", "LRU victim must be m0"
        assert all(mux.state(m) == "warm" for m in ("m1", "m2", "m3"))

        # tie on last_used -> lower request-rate EWMA loses
        clk[0] = 500.0
        mux.output("m0", X)  # m0 back in; someone else was evicted
        warm = [m for m in mux.models() if mux.state(m) == "warm"]
        clk[0] = 600.0
        for m in warm:  # equalize recency across all warm models
            mux._slots[m].last_used = 600.0
        others = [m for m in warm if m != "m0"]
        mux._slots["m0"].ewma = 0.001  # coldest trend
        for m in others:
            mux._slots[m].ewma = 5.0
        cold = next(m for m in mux.models() if mux.state(m) == "parked")
        mux.ensure_resident(cold)
        assert mux.state("m0") == "parked", \
            "EWMA tie-break must evict the coldest trend"
        assert all(mux.state(m) == "warm" for m in others)
    finally:
        mux.shutdown(drain=False)


def test_prefetch_fills_headroom_by_ewma_without_evicting(store):
    mux = _mux(store, 10**9)
    try:
        for i in range(3):
            mux.register(f"m{i}")
        mux.output("m0", X)
        per = mux.resident_bytes()
        mux.budget_bytes = per * 2 + per // 2  # headroom for ONE more
        # pin estimates to true resident size (see the LRU test)
        mux._slots["m1"].bytes = mux._slots["m2"].bytes = per
        mux._slots["m1"].ewma = 1.0
        mux._slots["m2"].ewma = 9.0  # hottest parked trend
        fetched = mux.prefetch(limit=2)
        assert fetched == ["m2"], fetched  # m1 would need an eviction
        assert mux.state("m2") == "warm"
        assert mux.state("m0") == "warm", "prefetch must never evict"
        assert mux.state("m1") == "parked"
    finally:
        mux.shutdown(drain=False)


# ----- park / unpark ---------------------------------------------------
def test_manager_park_unpark_idempotent_and_exact_replay(store):
    reg = MetricsRegistry()
    mgr = ModelManager(store, "m0", registry=reg, workers=1,
                       batch_limit=4, probation_seconds=0.0)
    try:
        before = np.asarray(mgr.output(X))
        assert mgr.park() is True
        assert mgr.park() is False  # idempotent
        assert mgr.parked and mgr.engine is None
        with pytest.raises(ModelParkedError):
            mgr.submit(X)
        entry = mgr.unpark()
        assert str(entry.version) == mgr.live_version
        assert mgr.unpark().version == entry.version  # idempotent
        assert np.array_equal(np.asarray(mgr.output(X)), before)
    finally:
        mgr.shutdown(drain=False)


def test_unpark_replays_quantized_deploy_byte_identically(store):
    reg = MetricsRegistry()
    mgr = ModelManager(store, "m0", registry=reg, workers=1,
                       batch_limit=4, probation_seconds=0.0,
                       optimize="inference:int8")
    try:
        from deeplearning4j_tpu.nn.rewrite import count_quantized_layers

        before = np.asarray(mgr.output(X))
        assert count_quantized_layers(mgr.engine.model) > 0
        qbytes = mgr.resident_bytes()
        mgr.park()
        assert mgr.resident_bytes() == 0
        mgr.unpark()
        assert count_quantized_layers(mgr.engine.model) > 0, \
            "page-in must replay the int8 rewrite pipeline"
        assert mgr.resident_bytes() == qbytes
        assert np.array_equal(np.asarray(mgr.output(X)), before), \
            "quantized unpark must serve the exact pre-park outputs"
    finally:
        mgr.shutdown(drain=False)


def test_coldstart_queues_and_bounded_deadline_sheds(store):
    """A miss on a cold model queues behind the page-in; a queued waiter
    whose deadline exhausts sheds with AdmissionRejectedError (503 +
    Retry-After at the HTTP edge), never a silent hang."""
    reg = MetricsRegistry()
    mux = _mux(store, 10**9, registry=reg)
    try:
        mux.register("m0")
        fut, _ = mux.submit("m0", X)  # cold miss pages in, then serves
        assert np.asarray(fut.result(timeout=60)).shape == (1, 3)
        c = reg.get("dl4j_tpu_serving_coldstart_misses_total")
        assert c.labels("mux", "m0").value == 1.0
        h = reg.get("dl4j_tpu_serving_pagein_seconds")
        assert h.labels("mux").count == 1

        # a waiter behind a stuck page-in gives up at its deadline
        mux._slots["m0"].state = "paging"  # simulate a wedged pager
        with pytest.raises(AdmissionRejectedError):
            mux.ensure_resident(
                "m0", deadline=Deadline.after(0.2, clock=mux._clock))
        mux._slots["m0"].state = "warm"
    finally:
        mux.shutdown(drain=False)


def test_eviction_mid_flight_completes_and_resubmits(store):
    """A model evicted between residency check and engine submit costs a
    retry, never a lost request: park drains first, and submit() pages
    the model back in transparently."""
    mux = _mux(store, 10**9)
    try:
        mux.register("m0")
        before = np.asarray(mux.output("m0", X))
        stop = threading.Event()
        errors, served = [], [0]

        def client():
            while not stop.is_set():
                try:
                    out = np.asarray(mux.output("m0", X, timeout=30.0))
                    # tolerance, not bytes: concurrent clients batch
                    # together and the padded batch forward is not
                    # bit-identical to a single-row one (exact replay is
                    # pinned by the single-request park/unpark tests)
                    assert np.allclose(out, before, atol=1e-4)
                    served[0] += 1
                except Exception as e:
                    errors.append(e)

        threads = [threading.Thread(target=client) for _ in range(3)]
        for t in threads:
            t.start()
        for _ in range(3):  # evict under fire
            mux.park("m0")
        stop.set()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
        assert served[0] > 0
    finally:
        mux.shutdown(drain=False)


# ----- server integration ---------------------------------------------
def _post(port, path, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        json.dumps({"data": X.tolist()}).encode(),
        {"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read())


def test_server_reports_residency_and_tenant_header(store):
    from deeplearning4j_tpu.remote.server import JsonModelServer

    reg = MetricsRegistry()
    mux = _mux(store, 10**9, registry=reg,
               tenants={"gold": {"priority": "high",
                                 "pagein_deadline_s": 30.0}},
               priorities={"high": 1.0, "low": 0.5})
    mux.register("m0")
    mux.register("m1")
    srv = JsonModelServer(registry=reg, multiplexer=mux,
                          name="mux-srv").start()
    try:
        code, body = _post(srv.port, "/v1/models/m0",
                           {"X-Tenant": "gold"})
        assert code == 200 and "output" in body
        mux.park("m1")  # never served: stays parked
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/health", timeout=15) as r:
            h = json.loads(r.read())
        assert h["multiplex"]["models"] == {"m0": "warm", "m1": "parked"}
        assert h["multiplex"]["budget_bytes"] == mux.budget_bytes
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/v1/models",
                timeout=15) as r:
            m = json.loads(r.read())
        assert m["multiplex"]["models"]["m0"]["residency"] == "warm"
        t = reg.get("dl4j_tpu_serving_tenant_requests_total")
        assert t.labels("mux", "gold").value == 1.0
        with pytest.raises(HTTPError) as ei:
            _post(srv.port, "/v1/models/nope")
        assert ei.value.code == 404
    finally:
        srv.stop(drain=False)
        mux.shutdown(drain=False)


def test_register_unregister_race_with_inflight_traffic(store):
    """ISSUE 19 satellite: add_model/remove_model are copy-on-write, so
    churning registrations while handler threads serve and scrape
    health/stats never drops a request or trips concurrent mutation."""
    from deeplearning4j_tpu.remote.server import JsonModelServer

    reg = MetricsRegistry()
    mgr = ModelManager(store, "m0", registry=reg, workers=1,
                       batch_limit=4, probation_seconds=0.0)
    extra = ModelManager(store, "m1", registry=reg, workers=1,
                         batch_limit=4, probation_seconds=0.0)
    srv = JsonModelServer(registry=reg, managers={"m0": mgr},
                          name="race-srv").start()
    stop = threading.Event()
    errors = []
    try:
        def client():
            while not stop.is_set():
                try:
                    code, _ = _post(srv.port, "/v1/models/m0")
                    assert code == 200
                except Exception as e:
                    errors.append(e)

        def scraper():
            while not stop.is_set():
                try:
                    srv.health()
                    srv.stats()
                except Exception as e:
                    errors.append(e)

        threads = [threading.Thread(target=client) for _ in range(2)]
        threads.append(threading.Thread(target=scraper))
        for t in threads:
            t.start()
        for _ in range(50):  # churn registrations under fire
            srv.add_model("m1", extra)
            srv.remove_model("m1")
        stop.set()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
    finally:
        stop.set()
        srv.stop(drain=False)
        mgr.shutdown(drain=False)
        extra.shutdown(drain=False)
