"""StepProfiler: per-phase training step attribution (obs/step_profiler.py)
and its Solver/GraphSolver wiring — phases land in the registry, the
breakdown sums to 1, the scan fast path is bypassed (per-step boundaries
required), and sampled fencing controls which steps pay a device sync."""

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.obs import MetricsRegistry, StepProfiler
from deeplearning4j_tpu.obs.step_profiler import PHASES
from deeplearning4j_tpu.train.solver import Solver


def _model(seed=5):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return x, y


def test_phase_recording_and_stats():
    reg = MetricsRegistry()
    prof = StepProfiler(sync_every=1, registry=reg, name="p")
    prof.begin_step()
    prof.record("data_wait", 0.010)
    prof.record("h2d", 0.005, sampled=True)
    prof.record("compute", 0.080, sampled=True)
    prof.record("host", 0.005)
    prof.end_step()
    s = prof.stats()
    assert s["steps"] == 1 and s["sampled_steps"] == 1
    assert s["per_step_ms"]["compute"] == pytest.approx(80.0)
    assert s["share"]["compute"] == pytest.approx(0.8, abs=1e-3)
    assert s["input_bound_share"] == pytest.approx(0.15, abs=1e-3)
    assert sum(s["share"].values()) == pytest.approx(1.0, abs=1e-3)
    # histogram children exist per phase
    fam = reg.get("dl4j_tpu_training_step_phase_seconds")
    assert fam is not None
    for p in PHASES:
        assert fam.labels("p", p).count == 1


def test_unknown_phase_and_bad_sync_every():
    prof = StepProfiler(registry=MetricsRegistry())
    with pytest.raises(ValueError):
        prof.phase("gpu")
    with pytest.raises(ValueError):
        StepProfiler(sync_every=-1, registry=MetricsRegistry())


def test_sampling_schedule():
    prof = StepProfiler(sync_every=3, registry=MetricsRegistry())
    fenced = []
    for _ in range(9):
        fenced.append(prof.begin_step())
        prof.end_step()
    assert fenced == [True, False, False] * 3
    assert prof.sampled_steps == 3
    # sync_every=0 never fences
    prof0 = StepProfiler(sync_every=0, registry=MetricsRegistry())
    assert prof0.begin_step() is False
    assert prof0.stats()["fenced"] is False


def test_wrap_iterator_attributes_data_wait():
    reg = MetricsRegistry()
    prof = StepProfiler(registry=reg)
    x, y = _data(32)
    it = prof.wrap_iterator(ListDataSetIterator(DataSet(x, y), 8))
    seen = 0
    while it.has_next():
        it.next()
        seen += 1
    assert seen == 4
    assert prof._counts["data_wait"] == 4
    assert it.batch_size() == 8
    it.reset()
    assert it.has_next()


def test_wrap_plain_iterable():
    prof = StepProfiler(registry=MetricsRegistry())
    out = list(prof.wrap_iterator([1, 2, 3]))
    assert out == [1, 2, 3]
    assert prof._counts["data_wait"] == 3  # StopIteration not attributed


def test_solver_fit_with_profiler_per_step_attribution():
    reg = MetricsRegistry()
    prof = StepProfiler(sync_every=2, registry=reg)
    solver = Solver(_model(), profiler=prof)
    x, y = _data(64)
    it = prof.wrap_iterator(ListDataSetIterator(DataSet(x, y), 8))
    solver.fit(it, epochs=2)
    s = prof.stats()
    # the scan fast path would leave steps == 0; the profiler must force
    # per-step boundaries (8 batches x 2 epochs)
    assert s["steps"] == 16
    assert s["sampled_steps"] == 8
    assert s["seconds_total"]["data_wait"] > 0
    assert s["seconds_total"]["compute"] > 0
    assert s["seconds_total"]["host"] > 0
    assert s["step_time_ms_est"] > 0
    assert sum(s["share"].values()) == pytest.approx(1.0, abs=1e-3)


def test_solver_without_profiler_unchanged():
    solver = Solver(_model())
    assert solver.profiler is None
    x, y = _data(32)
    solver.fit(ListDataSetIterator(DataSet(x, y), 8), epochs=1)
    assert solver.model.iteration_count == 4  # scan fast path still taken


def test_graph_solver_with_profiler():
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.train.graph_solver import GraphSolver

    conf = (NeuralNetConfiguration.builder().seed(7)
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=4, n_out=8), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=3), "d")
            .set_outputs("out")
            .build())
    model = ComputationGraph(conf).init()
    reg = MetricsRegistry()
    prof = StepProfiler(sync_every=1, registry=reg)
    solver = GraphSolver(model, profiler=prof)
    x, y = _data(32)
    batches = [DataSet(x[i:i + 8], y[i:i + 8]) for i in range(0, 32, 8)]
    solver.fit(batches, epochs=1)
    s = prof.stats()
    assert s["steps"] == 4
    assert s["sampled_steps"] == 4
    assert s["seconds_total"]["compute"] > 0


def test_async_iterator_fetch_wait_metrics():
    """Satellite: AsyncDataSetIterator stats on /metrics — capacity gauge
    and per-dequeue wait histogram next to the existing depth/starvation
    series."""
    from deeplearning4j_tpu.data.iterators import AsyncDataSetIterator

    reg = MetricsRegistry()
    x, y = _data(32)
    it = AsyncDataSetIterator(ListDataSetIterator(DataSet(x, y), 8),
                              queue_size=2, registry=reg, name="adsi")
    n = 0
    while it.has_next():
        it.next()
        n += 1
    it.close()
    assert n == 4
    s = it.stats()
    assert s["queue_capacity"] == 2
    assert s["fetches"] >= 4
    assert "mean_fetch_wait_s" in s
    text = reg.render()
    assert 'dl4j_tpu_data_prefetch_queue_capacity{instance="adsi"} 2' in text
    assert "dl4j_tpu_data_fetch_wait_seconds_bucket" in text
    assert "dl4j_tpu_data_consumer_starvation_seconds_total" in text
