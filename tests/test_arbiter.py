"""Arbiter tests: spaces, generators, and a real search over a tiny net
(SURVEY.md §2.2 "Arbiter")."""

import numpy as np
import pytest

from deeplearning4j_tpu.arbiter import (
    ContinuousParameterSpace,
    DiscreteParameterSpace,
    FixedValue,
    GridSearchGenerator,
    IntegerParameterSpace,
    LocalOptimizationRunner,
    OptimizationConfiguration,
    RandomSearchGenerator,
)


def test_spaces_sample_and_grid():
    rng = np.random.RandomState(0)
    c = ContinuousParameterSpace(0.1, 1.0)
    assert all(0.1 <= c.sample(rng) <= 1.0 for _ in range(20))
    assert len(c.grid(5)) == 5
    logc = ContinuousParameterSpace(1e-4, 1e-1, log_scale=True)
    vals = [logc.sample(rng) for _ in range(50)]
    assert min(vals) < 1e-3 and max(vals) > 1e-2  # spans decades
    i = IntegerParameterSpace(2, 5)
    assert set(i.grid(10)) == {2, 3, 4, 5}
    d = DiscreteParameterSpace(["a", "b"])
    assert d.grid(99) == ["a", "b"]
    assert FixedValue(7).sample(rng) == 7
    with pytest.raises(ValueError):
        ContinuousParameterSpace(1.0, 0.1)
    with pytest.raises(ValueError):
        ContinuousParameterSpace(-1.0, 1.0, log_scale=True)


def test_grid_generator_cartesian():
    gen = GridSearchGenerator({
        "a": DiscreteParameterSpace([1, 2]),
        "b": DiscreteParameterSpace(["x", "y", "z"]),
    })
    combos = list(gen)
    assert len(combos) == 6
    assert {"a": 1, "b": "z"} in combos


def test_random_generator_deterministic():
    spaces = {"lr": ContinuousParameterSpace(1e-4, 1e-1, log_scale=True)}
    a = list(RandomSearchGenerator(spaces, 5, seed=1))
    b = list(RandomSearchGenerator(spaces, 5, seed=1))
    assert a == b and len(a) == 5


def test_search_finds_better_hyperparameters():
    """Search lr × hidden for a tiny classifier; best beats worst clearly."""
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.train.updaters import Adam

    rng = np.random.RandomState(0)
    x = rng.randn(128, 6).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] * x[:, 1] > 0).astype(int)]

    def factory(hp):
        conf = (NeuralNetConfiguration.builder().seed(11)
                .updater(Adam(learning_rate=hp["lr"])).list()
                .layer(DenseLayer(n_in=6, n_out=hp["hidden"]))
                .layer(OutputLayer(n_in=hp["hidden"], n_out=2))
                .build())
        m = MultiLayerNetwork(conf).init()
        m.fit(x, y, epochs=60)
        return m

    def score(model, hp):
        return model.score(x, y)  # training loss (minimize)

    runner = LocalOptimizationRunner(OptimizationConfiguration(
        candidate_generator=RandomSearchGenerator({
            "lr": ContinuousParameterSpace(1e-5, 1e-1, log_scale=True),
            "hidden": IntegerParameterSpace(4, 32),
        }, num_candidates=6, seed=4),
        model_factory=factory,
        score_function=score,
        minimize=True,
    ))
    best = runner.execute()
    scores = [r.score for r in runner.results]
    assert runner.num_candidates_completed() == 6
    assert best.score == min(scores)
    assert best.score < max(scores) * 0.8  # search actually discriminates
    assert best.error is None


def test_failed_candidate_does_not_stop_search():
    def factory(hp):
        if hp["x"] == 2:
            raise RuntimeError("boom")
        return hp["x"]

    runner = LocalOptimizationRunner(OptimizationConfiguration(
        candidate_generator=GridSearchGenerator(
            {"x": DiscreteParameterSpace([1, 2, 3])}),
        model_factory=factory,
        score_function=lambda m, hp: float(m),
        minimize=True,
    ))
    best = runner.execute()
    assert runner.num_candidates_completed() == 3
    assert best.score == 1.0
    failed = [r for r in runner.results if r.error]
    assert len(failed) == 1 and "boom" in failed[0].error
