"""Stage-3 tests: iterators, normalizers, serializer, checkpoints,
early stopping, scan fast path, MNIST, LeNet."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import (
    AsyncDataSetIterator,
    ListDataSetIterator,
    MultipleEpochsIterator,
)
from deeplearning4j_tpu.data.mnist import MnistDataSetIterator
from deeplearning4j_tpu.data.normalizers import (
    ImagePreProcessingScaler,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
)
from deeplearning4j_tpu.model.serializer import (
    restore_multi_layer_network,
    write_model,
)
from deeplearning4j_tpu.model.zoo import LeNet
from deeplearning4j_tpu.nn import (
    Activation,
    InputType,
    LossFunction,
    MultiLayerNetwork,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.train import Adam
from deeplearning4j_tpu.train.checkpoint import CheckpointListener
from deeplearning4j_tpu.train.early_stopping import (
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    MaxEpochsTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)


def tiny_model(seed=1):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Adam(1e-2))
        .list()
        .layer(DenseLayer(n_out=8, activation=Activation.TANH))
        .layer(OutputLayer(n_out=2))
        .set_input_type(InputType.feed_forward(4))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def tiny_data(n=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(axis=1) > 0).astype(int)]
    return x, y


class TestIterators:
    def test_list_iterator_batches(self):
        x, y = tiny_data(10)
        it = ListDataSetIterator(DataSet(x, y), batch=4)
        sizes = [ds.num_examples() for ds in it]
        assert sizes == [4, 4, 2]

    def test_list_iterator_reset_and_shuffle(self):
        x, y = tiny_data(8)
        it = ListDataSetIterator(DataSet(x, y), batch=8, shuffle=True, seed=1)
        first = next(iter(it)).features.copy()
        second = next(iter(it)).features.copy()
        assert first.shape == second.shape
        assert not np.array_equal(first, second)  # different epoch order

    def test_async_iterator_equivalence(self):
        x, y = tiny_data(20)
        plain = list(ListDataSetIterator(DataSet(x, y), batch=6))
        async_it = AsyncDataSetIterator(ListDataSetIterator(DataSet(x, y), batch=6))
        got = list(async_it)
        assert len(got) == len(plain)
        for a, b in zip(got, plain):
            np.testing.assert_array_equal(a.features, b.features)

    def test_async_iterator_reset(self):
        x, y = tiny_data(12)
        it = AsyncDataSetIterator(ListDataSetIterator(DataSet(x, y), batch=4))
        assert len(list(it)) == 3
        assert len(list(it)) == 3  # again after implicit reset

    def test_multiple_epochs(self):
        x, y = tiny_data(8)
        it = MultipleEpochsIterator(ListDataSetIterator(DataSet(x, y), batch=4), epochs=3)
        assert len(list(it)) == 6


class TestNormalizers:
    def test_standardize_round_trip(self):
        x, y = tiny_data(50)
        ds = DataSet(x.copy(), y)
        norm = NormalizerStandardize()
        norm.fit(ds)
        norm.transform(ds)
        assert abs(ds.features.mean()) < 0.1
        norm.revert(ds)
        np.testing.assert_allclose(ds.features, x, atol=1e-4)

    def test_minmax(self):
        x, y = tiny_data(50)
        ds = DataSet(x.copy(), y)
        norm = NormalizerMinMaxScaler()
        norm.fit(ds)
        norm.transform(ds)
        assert ds.features.min() >= -1e-6 and ds.features.max() <= 1 + 1e-6

    def test_image_scaler(self):
        ds = DataSet(np.full((2, 3), 255.0, np.float32), np.zeros((2, 1)))
        ImagePreProcessingScaler().transform(ds)
        np.testing.assert_allclose(ds.features, 1.0)


class TestSerializer:
    def test_round_trip(self, tmp_path):
        model = tiny_model()
        x, y = tiny_data()
        model.fit(x, y, epochs=3)
        out_before = np.asarray(model.output(x))
        path = str(tmp_path / "model.zip")
        write_model(model, path, save_updater=True)
        restored = restore_multi_layer_network(path, load_updater=True)
        out_after = np.asarray(restored.output(x))
        np.testing.assert_allclose(out_before, out_after, rtol=1e-6)
        assert restored.conf == model.conf

    def test_training_resumes_identically(self, tmp_path):
        x, y = tiny_data()
        m1 = tiny_model()
        m1.fit(x, y, epochs=2)
        path = str(tmp_path / "m.zip")
        write_model(m1, path, save_updater=True)
        m2 = restore_multi_layer_network(path, load_updater=True)
        # restored updater state means continued training matches
        m1._rng = type(m1._rng)(99)
        m2._rng = type(m2._rng)(99)
        m1.fit(x, y, epochs=1)
        m2.fit(x, y, epochs=1)
        np.testing.assert_allclose(
            np.asarray(m1.params["layer_0"]["W"]),
            np.asarray(m2.params["layer_0"]["W"]), rtol=1e-5,
        )


class TestCheckpoint:
    def test_checkpoint_and_keep_last(self, tmp_path):
        model = tiny_model()
        model.add_listeners(CheckpointListener(str(tmp_path), save_every_n_iterations=2, keep_last=2))
        x, y = tiny_data()
        for _ in range(6):
            model.fit(x, y)
        zips = [f for f in os.listdir(tmp_path) if f.endswith(".zip")]
        assert len(zips) == 2
        last = CheckpointListener.last_checkpoint(str(tmp_path))
        assert last is not None
        restored = restore_multi_layer_network(last)
        assert restored.num_params() == model.num_params()


class TestEarlyStopping:
    def test_stops_and_returns_best(self):
        x, y = tiny_data(64)
        train_ds = DataSet(x, y)
        config = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(ListDataSetIterator(train_ds, 32)),
            epoch_termination_conditions=[
                MaxEpochsTerminationCondition(8),
                ScoreImprovementEpochTerminationCondition(3),
            ],
        )
        trainer = EarlyStoppingTrainer(config, tiny_model(), ListDataSetIterator(train_ds, 32))
        result = trainer.fit()
        assert result.total_epochs <= 8
        assert result.best_model is not None
        assert np.isfinite(result.best_model_score)


class TestScanFastPath:
    def test_scan_matches_loop(self):
        x, y = tiny_data(32)
        m_scan = tiny_model(seed=5)
        m_loop = tiny_model(seed=5)
        from deeplearning4j_tpu.core import CollectScoresListener

        # listener forces the per-batch loop path
        m_loop.add_listeners(CollectScoresListener())
        it1 = ListDataSetIterator(DataSet(x, y), batch=8)
        it2 = ListDataSetIterator(DataSet(x, y), batch=8)
        m_scan.fit(it1, epochs=2)
        m_loop.fit(it2, epochs=2)
        np.testing.assert_allclose(
            np.asarray(m_scan.params["layer_0"]["W"]),
            np.asarray(m_loop.params["layer_0"]["W"]),
            rtol=1e-5, atol=1e-6,
        )


class TestMnistLeNet:
    def test_mnist_shapes(self):
        it = MnistDataSetIterator(32, train=True, num_examples=64)
        ds = next(iter(it))
        assert ds.features.shape == (32, 784)
        assert ds.labels.shape == (32, 10)
        assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0

    def test_lenet_learns_mnist(self):
        model = LeNet(seed=1).init()
        it = MnistDataSetIterator(64, train=True, num_examples=512, seed=7)
        model.fit(it, epochs=5)
        test_it = MnistDataSetIterator(64, train=False, num_examples=256, seed=7)
        ev = model.evaluate(test_it)
        assert ev.accuracy() > 0.6, f"LeNet accuracy too low: {ev.accuracy()}"
