"""DataVec-equivalent tests: record readers, schema transforms, and the
record→DataSet bridge feeding a real fit() (SURVEY.md §2.2 DataVec rows)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu import native
from deeplearning4j_tpu.data.records import (
    CollectionRecordReader,
    CSVRecordReader,
    CSVSequenceRecordReader,
    ImageRecordReader,
    LineRecordReader,
    RecordReaderDataSetIterator,
)
from deeplearning4j_tpu.data.transform import (
    Schema,
    TransformProcess,
    TransformProcessRecordReader,
)


@pytest.fixture
def csv_file(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("f1,f2,label\n1.0,2.0,0\n3.0,4.0,1\n5.0,6.0,2\n7.0,8.0,0\n")
    return str(p)


def test_csv_record_reader(csv_file):
    recs = list(CSVRecordReader(csv_file, skip_lines=1))
    assert recs == [[1.0, 2.0, 0.0], [3.0, 4.0, 1.0], [5.0, 6.0, 2.0],
                    [7.0, 8.0, 0.0]]
    # header row read as strings without skip
    recs0 = list(CSVRecordReader(csv_file))
    assert recs0[0] == ["f1", "f2", "label"]
    # numeric fast path (native CSV parser)
    recs_n = list(CSVRecordReader(csv_file, skip_lines=1, numeric=True))
    assert recs_n == recs


def test_line_and_collection_readers(tmp_path):
    p = tmp_path / "lines.txt"
    p.write_text("hello\nworld\n")
    assert list(LineRecordReader(str(p))) == [["hello"], ["world"]]
    cr = CollectionRecordReader([[1, 2], [3, 4]])
    assert list(cr) == [[1, 2], [3, 4]]
    assert list(cr) == [[1, 2], [3, 4]]  # restartable


def test_csv_sequence_reader(tmp_path):
    for i, content in enumerate(["1,2\n3,4\n", "5,6\n"]):
        (tmp_path / f"seq{i}.csv").write_text(content)
    reader = CSVSequenceRecordReader(
        [str(tmp_path / "seq0.csv"), str(tmp_path / "seq1.csv")])
    seqs = list(reader)
    assert seqs == [[[1.0, 2.0], [3.0, 4.0]], [[5.0, 6.0]]]


def _write_ppm(path, h, w, value):
    data = bytes([value]) * (h * w * 3)
    path.write_bytes(b"P6\n%d %d\n255\n" % (w, h) + data)


def test_image_record_reader(tmp_path):
    for label, value in [("cat", 10), ("dog", 200)]:
        d = tmp_path / label
        d.mkdir()
        for i in range(2):
            _write_ppm(d / f"{i}.ppm", 6, 8, value)
    reader = ImageRecordReader(4, 4, 3, root=str(tmp_path))
    assert reader.labels() == ["cat", "dog"]
    recs = list(reader)
    assert len(recs) == 4
    img, label = recs[0]
    assert img.shape == (4, 4, 3)
    np.testing.assert_allclose(img, 10 / 255.0, atol=1e-6)
    assert label == 0
    assert recs[-1][1] == 1


def test_record_reader_dataset_iterator(csv_file):
    reader = CSVRecordReader(csv_file, skip_lines=1)
    it = RecordReaderDataSetIterator(reader, batch_size=3, label_index=-1,
                                     num_classes=3)
    batches = list(it)
    assert [b.num_examples() for b in batches] == [3, 1]
    np.testing.assert_allclose(batches[0].features,
                               [[1, 2], [3, 4], [5, 6]])
    np.testing.assert_allclose(batches[0].labels,
                               [[1, 0, 0], [0, 1, 0], [0, 0, 1]])


def test_regression_iterator(csv_file):
    reader = CSVRecordReader(csv_file, skip_lines=1)
    it = RecordReaderDataSetIterator(reader, batch_size=4, label_index=0,
                                     regression=True)
    (batch,) = list(it)
    np.testing.assert_allclose(batch.features, [[2, 0], [4, 1], [6, 2],
                                                [8, 0]])
    np.testing.assert_allclose(batch.labels, [[1], [3], [5], [7]])


def test_schema_and_transform_process():
    schema = (Schema.builder()
              .add_double_column("x")
              .add_categorical_column("color", ["red", "green"])
              .add_string_column("junk")
              .build())
    tp = (TransformProcess.builder(schema)
          .remove_columns("junk")
          .double_math_op("x", "multiply", 2.0)
          .min_max_normalize("x", 0.0, 10.0)
          .categorical_to_one_hot("color")
          .build())
    final = tp.final_schema()
    assert final.names() == ["x", "color[red]", "color[green]"]
    out = tp.execute([[1.0, "red", "a"], [5.0, "green", "b"]])
    np.testing.assert_allclose(out, [[0.2, 1, 0], [1.0, 0, 1]])


def test_transform_process_json_roundtrip():
    schema = (Schema.builder().add_double_column("x")
              .add_categorical_column("c", ["a", "b"]).build())
    tp = (TransformProcess.builder(schema)
          .double_math_op("x", "add", 1.0)
          .categorical_to_integer("c")
          .conditional_filter("x", "gt", 100.0)
          .build())
    tp2 = TransformProcess.from_json(tp.to_json())
    recs = [[1.0, "b"], [200.0, "a"]]
    assert tp2.execute(recs) == tp.execute(recs) == [[2.0, 1]]


def test_filters():
    schema = Schema.builder().add_double_column("x").build()
    tp = (TransformProcess.builder(schema).filter_invalid("x").build())
    assert tp.execute([[1.0], [float("nan")], [2.0]]) == [[1.0], [2.0]]


def test_build_validates_schema():
    schema = Schema.builder().add_double_column("x").build()
    with pytest.raises(KeyError):
        TransformProcess.builder(schema).remove_columns("nope").build()


def test_transform_reader_feeds_fit(csv_file):
    """End-to-end DataVec path: CSV → transform → iterator → fit()."""
    import jax

    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer

    schema = (Schema.builder().add_double_column("f1")
              .add_double_column("f2").add_integer_column("label").build())
    tp = (TransformProcess.builder(schema)
          .min_max_normalize("f1", 0.0, 8.0)
          .min_max_normalize("f2", 0.0, 8.0)
          .build())
    reader = TransformProcessRecordReader(
        CSVRecordReader(csv_file, skip_lines=1), tp)
    it = RecordReaderDataSetIterator(reader, batch_size=4, num_classes=3)

    conf = (NeuralNetConfiguration.builder().seed(1).list()
            .layer(DenseLayer(n_in=2, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    model = MultiLayerNetwork(conf).init()
    model.fit(it, epochs=3)
    out = model.output(np.array([[0.125, 0.25]], np.float32))
    assert out.shape == (1, 3)
    assert np.isfinite(np.asarray(out)).all()


def test_negative_label_rejected():
    reader = CollectionRecordReader([[1.0, -1]])
    it = RecordReaderDataSetIterator(reader, batch_size=1, num_classes=3)
    with pytest.raises(ValueError, match="label -1"):
        list(it)


def test_svhn_and_tinyimagenet_fetchers():
    from deeplearning4j_tpu.data import (SvhnDataSetIterator,
                                         TinyImageNetDataSetIterator)

    it = SvhnDataSetIterator(16, num_examples=48, shuffle=False)
    batches = list(it)
    assert batches[0].features.shape == (16, 3, 32, 32)
    assert batches[0].labels.shape == (16, 10)
    assert 0.0 <= batches[0].features.min() and batches[0].features.max() <= 1.0
    # deterministic given the seed
    it2 = SvhnDataSetIterator(16, num_examples=48, shuffle=False)
    np.testing.assert_array_equal(batches[0].features,
                                  next(iter(it2)).features)

    it3 = TinyImageNetDataSetIterator(8, num_examples=16, shuffle=False)
    ds = next(iter(it3))
    assert ds.features.shape == (8, 3, 64, 64)
    assert ds.labels.shape == (8, 200)
