"""Unit coverage for the graph-rewrite pass framework (nn/rewrite):
float64 gradchecks per pass, stem-rewrite shape/parity on the zoo
ResNet block, fold-then-serialize round trips, solver/manager knobs.
The cross-cutting equivalence contract (forward/backward parity, no-op
byte-identity, deploy-serves-folded) lives in
tools/check_rewrite_equivalence.py -> test_rewrite_contract.py."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.nn import (
    Activation,
    InputType,
    LossFunction,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    BatchNormalizationLayer,
    ConvolutionLayer,
    ConvolutionMode,
    DenseLayer,
    OutputLayer,
    SpaceToDepthLayer,
)
from deeplearning4j_tpu.nn.rewrite import (
    BatchNormAffinePass,
    ConvBatchNormFoldPass,
    SpaceToDepthStemPass,
    resolve_passes,
    rewrite_model,
)
from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
from deeplearning4j_tpu.train.solver import Solver
from deeplearning4j_tpu.utils.gradcheck import check_gradients


def _stem_net(dtype="float64", n_out=2, classes=3, hw=8, extra_bn=False,
              seed=12):
    b = NeuralNetConfiguration.builder().seed(seed).data_type(dtype).list()
    b.layer(ConvolutionLayer(
        name="stem_conv", n_out=n_out, kernel_size=(7, 7), stride=(2, 2),
        convolution_mode=ConvolutionMode.SAME,
        activation=Activation.IDENTITY, has_bias=True))
    if extra_bn:
        b.layer(BatchNormalizationLayer(name="stem_bn"))
        b.layer(ActivationLayer(name="stem_relu",
                                activation=Activation.RELU))
    else:
        b.layer(ActivationLayer(name="stem_act",
                                activation=Activation.TANH))
    b.layer(OutputLayer(name="out", n_out=classes, loss=LossFunction.MCXENT,
                        activation=Activation.SOFTMAX))
    b.set_input_type(InputType.convolutional(hw, hw, 3))
    return MultiLayerNetwork(b.build()).init()


def _batch(model, hw=8, n=3, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 3, hw, hw).astype(np.float64)
    y = np.eye(classes)[rng.randint(0, classes, n)].astype(np.float64)
    return x, y


# ---------------------------------------------------------------------------
# float64 gradchecks per pass
# ---------------------------------------------------------------------------

def test_gradcheck_stem_rewrite():
    model = _stem_net()
    x, y = _batch(model)
    m2, applied = rewrite_model(model, [SpaceToDepthStemPass()],
                                context="training")
    assert applied == ["space_to_depth_stem"]
    np.testing.assert_allclose(np.asarray(m2.output(x)),
                               np.asarray(model.output(x)), atol=1e-12)
    assert check_gradients(m2, x, y, subset=60)


def test_gradcheck_conv_bn_fold():
    model = _stem_net(extra_bn=True)
    x, y = _batch(model)
    model.fit(x, y, epochs=2)  # move BN stats off the init values
    m2, applied = rewrite_model(model, [ConvBatchNormFoldPass()],
                                context="inference")
    assert applied == ["conv_bn_fold"]
    np.testing.assert_allclose(np.asarray(m2.output(x)),
                               np.asarray(model.output(x)), atol=1e-10)
    # the folded graph is a plain trainable net in its own right
    assert check_gradients(m2, x, y, subset=60)


def test_gradcheck_bn_affine():
    b = (NeuralNetConfiguration.builder().seed(5).data_type("float64").list()
         .layer(DenseLayer(name="d", n_out=6, activation=Activation.TANH))
         .layer(BatchNormalizationLayer(name="bn"))
         .layer(OutputLayer(name="out", n_out=3, loss=LossFunction.MCXENT,
                            activation=Activation.SOFTMAX))
         .set_input_type(InputType.feed_forward(4)))
    model = MultiLayerNetwork(b.build()).init()
    rng = np.random.RandomState(1)
    x = rng.rand(4, 4)
    y = np.eye(3)[rng.randint(0, 3, 4)].astype(np.float64)
    model.fit(x, y, epochs=2)
    m2, applied = rewrite_model(model, [BatchNormAffinePass()],
                                context="training")
    assert applied == ["bn_affine_precompute"]
    assert m2.conf.layers[1].fused
    # same params/state objects: config-only rewrite
    assert m2.params["bn"] is model.params["bn"]
    np.testing.assert_allclose(np.asarray(m2.output(x)),
                               np.asarray(model.output(x)), atol=1e-12)
    assert check_gradients(m2, x, y, subset=60)


# ---------------------------------------------------------------------------
# stem rewrite: shapes and exact kernel transform
# ---------------------------------------------------------------------------

def test_stem_rewrite_shapes_and_kernel_layout():
    model = _stem_net(dtype="float32", n_out=4, hw=16)
    m2, _ = rewrite_model(model, [SpaceToDepthStemPass()],
                          context="training")
    s2d, conv = m2.conf.layers[0], m2.conf.layers[1]
    assert isinstance(s2d, SpaceToDepthLayer) and s2d.block_size == 2
    assert conv.n_in == 12 and conv.kernel_size == (4, 4)
    assert conv.stride == (1, 1)
    assert conv.convolution_mode is ConvolutionMode.SAME
    w2 = np.asarray(m2.params[m2.conf.layer_name(1)]["W"])
    assert w2.shape == (4, 12, 4, 4)
    # exact pad+reshape: every original weight appears once, untouched
    w = np.asarray(model.params[model.conf.layer_name(0)]["W"])
    for o in range(4):
        for c in range(3):
            for dh in range(7):
                for dw in range(7):
                    m_, u = dh // 2, dh % 2
                    n_, v = dw // 2, dw % 2
                    assert w2[o, (u * 2 + v) * 3 + c, m_, n_] == w[o, c, dh, dw]
    # zero-padded taps (dh==7 or dw==7) are exactly zero
    assert np.count_nonzero(w2) <= np.count_nonzero(w)
    # spatial output identical
    out = np.asarray(m2.output(np.random.RandomState(0)
                               .rand(2, 3, 16, 16).astype(np.float32)))
    assert out.shape == (2, 3)


def test_stem_rewrite_skips_odd_input():
    b = (NeuralNetConfiguration.builder().seed(3).list()
         .layer(ConvolutionLayer(n_out=4, kernel_size=(7, 7), stride=(2, 2),
                                 convolution_mode=ConvolutionMode.SAME,
                                 activation=Activation.IDENTITY))
         .layer(OutputLayer(n_out=2, loss=LossFunction.MCXENT,
                            activation=Activation.SOFTMAX))
         .set_input_type(InputType.convolutional(15, 15, 3)))
    model = MultiLayerNetwork(b.build()).init()
    m2, applied = rewrite_model(model, [SpaceToDepthStemPass()],
                                context="training")
    assert m2 is model and applied == []


# ---------------------------------------------------------------------------
# zoo ResNet block parity (the real zoo builders, both rewrite sets)
# ---------------------------------------------------------------------------

def _zoo_resnet_block():
    from deeplearning4j_tpu.model.zoo.resnet50 import ResNet50
    from deeplearning4j_tpu.nn import WeightInit
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.layers import (
        GlobalPoolingLayer, PoolingType, SubsamplingLayer,
    )

    rn = ResNet50(num_classes=4, height=32, width=32)
    g = (NeuralNetConfiguration.builder().seed(9).updater(rn.updater)
         .weight_init(WeightInit.RELU).graph_builder().add_inputs("input"))
    x = rn._conv_bn(g, "stem", 16, (7, 7), (2, 2), "input")
    g.add_layer("stem_pool", SubsamplingLayer(
        kernel_size=(3, 3), stride=(2, 2),
        convolution_mode=ConvolutionMode.SAME,
        pooling_type=PoolingType.MAX), x)
    x = rn._bottleneck(g, "s0b0", "stem_pool", (8, 8, 32), project=True)
    g.add_layer("avgpool", GlobalPoolingLayer(pooling_type=PoolingType.AVG), x)
    g.add_layer("fc", OutputLayer(n_out=4, loss=LossFunction.MCXENT,
                                  activation=Activation.SOFTMAX), "avgpool")
    g.set_outputs("fc")
    g.set_input_types(InputType.convolutional(32, 32, 3))
    return ComputationGraph(g.build()).init()


def test_zoo_resnet_block_stem_parity():
    from deeplearning4j_tpu.train.graph_solver import GraphSolver

    model = _zoo_resnet_block()
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 32, 32).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 2)]
    solver = GraphSolver(model)
    for _ in range(2):
        solver.fit_batch((x,), (y,))
    base = np.asarray(model.output(x))

    m2, applied = rewrite_model(model, [SpaceToDepthStemPass()],
                                context="training")
    assert applied == ["space_to_depth_stem"]
    # the s2d vertex feeds the rewritten stem conv
    names = [v.name for v in m2.conf.vertices]
    assert "stem_conv_s2d" in names
    spec = m2.conf.spec("stem_conv")
    assert spec.inputs == ("stem_conv_s2d",)
    assert spec.layer.n_in == 12
    np.testing.assert_allclose(np.asarray(m2.output(x)), base, atol=2e-5)

    # full inference set: no BN vertices remain, outputs still match
    m3, applied3 = rewrite_model(model, "inference")
    assert "conv_bn_fold" in applied3
    assert not any(isinstance(v.layer, BatchNormalizationLayer)
                   for v in m3.conf.vertices)
    np.testing.assert_allclose(np.asarray(m3.output(x)), base, atol=2e-5)
    # training through the stem-rewritten graph still works
    s2 = GraphSolver(m2)
    s2.fit_batch((x,), (y,))


# ---------------------------------------------------------------------------
# fold-then-serialize round trip: artifacts store the UN-rewritten model
# ---------------------------------------------------------------------------

def test_fold_then_serialize_round_trip(tmp_path):
    from deeplearning4j_tpu.core.config import to_json
    from deeplearning4j_tpu.model.serializer import restore_model, write_model

    model = _stem_net(dtype="float32", extra_bn=True, hw=16)
    x, _ = _batch(model, hw=16)
    x = x.astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.random.RandomState(2).randint(0, 3, 3)]
    model.fit(x, y, epochs=2)
    expected = np.asarray(model.output(x))

    # serialize the ORIGINAL, restore, rewrite the restored copy
    path = os.path.join(tmp_path, "m.zip")
    write_model(model, path)
    restored = restore_model(path)
    assert to_json(restored.conf) == to_json(model.conf)
    folded, applied = rewrite_model(restored, "inference")
    assert "conv_bn_fold" in applied
    np.testing.assert_allclose(np.asarray(folded.output(x)), expected,
                               atol=2e-5)
    # re-serializing the restored (un-rewritten) model keeps the artifact
    # checkpoint-compatible: same config, same param count
    path2 = os.path.join(tmp_path, "m2.zip")
    write_model(restored, path2)
    again = restore_model(path2)
    assert to_json(again.conf) == to_json(model.conf)
    assert again.num_params() == model.num_params()
    np.testing.assert_allclose(np.asarray(again.output(x)), expected,
                               atol=1e-6)


# ---------------------------------------------------------------------------
# solver knob
# ---------------------------------------------------------------------------

def test_solver_optimize_knob_rewrites_in_place():
    model = _stem_net(dtype="float32", extra_bn=True, hw=16)
    x, _ = _batch(model, hw=16)
    x = x.astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.random.RandomState(4).randint(0, 3, 3)]
    before = np.asarray(model.output(x))
    solver = Solver(model, optimize="training")
    assert set(solver.applied_rewrites) == {"space_to_depth_stem",
                                            "bn_affine_precompute"}
    assert isinstance(model.layers[0], SpaceToDepthLayer)
    assert any(getattr(l, "fused", False) for l in model.layers)
    np.testing.assert_allclose(np.asarray(model.output(x)), before,
                               atol=2e-5)
    for _ in range(3):
        solver.fit_batch(x, y)
    assert np.isfinite(float(solver.fit_batch(x, y)[0]))


def test_solver_rejects_inference_only_pass():
    model = _stem_net(dtype="float32", extra_bn=True, hw=16)
    with pytest.raises(ValueError, match="inference-only"):
        Solver(model, optimize=[ConvBatchNormFoldPass()])
    with pytest.raises(ValueError):
        resolve_passes("inference", context="training")


def test_manager_optimize_none_serves_original(tmp_path):
    from deeplearning4j_tpu.obs import MetricsRegistry
    from deeplearning4j_tpu.serving import ModelManager, ModelStore

    model = _stem_net(dtype="float32", extra_bn=True, hw=16)
    x = np.random.RandomState(0).rand(2, 3, 16, 16).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.random.RandomState(1).randint(0, 3, 2)]
    model.fit(x, y, epochs=1)
    store = ModelStore(str(tmp_path))
    store.publish("m", model)
    mgr = ModelManager(store, "m", registry=MetricsRegistry(),
                       warmup_example=x, workers=1, optimize=None)
    try:
        assert any(isinstance(l, BatchNormalizationLayer)
                   for l in mgr.engine.model.conf.layers)
        np.testing.assert_allclose(np.asarray(mgr.output(x)),
                                   np.asarray(model.output(x)), atol=1e-6)
    finally:
        mgr.shutdown(drain=False)


# ---------------------------------------------------------------------------
# auto-discovered no-op property (ISSUE 13 satellite): EVERY pass either
# pipeline can emit — including passes added in the future — must be
# byte-identical on a model without its pattern, so new passes inherit
# the PR-5 no-op contract without hand-written cases.
# ---------------------------------------------------------------------------

def _discovered_passes():
    """Every pass the pipelines can emit, deduped by pass name — future
    passes land here automatically via training_passes()/
    inference_passes() (including the quantization variants)."""
    from deeplearning4j_tpu.nn.rewrite import (inference_passes,
                                               training_passes)

    candidates = list(training_passes()) + list(inference_passes())
    for quant in ("int8", "fp8"):
        try:
            candidates += inference_passes(quantize=quant)
        except ValueError:
            pass  # jaxlib without fp8 support: int8 still covered
    out = {}
    for p in candidates:
        out.setdefault(p.name, p)
    return sorted(out.items())


def _patternless_model():
    """A model none of the discovered passes can match: LSTM stack (no
    conv/BN/stem for the structural passes, no Dense/Conv/attention
    matmul weights for the quantization passes; the output layer is
    excluded from quantization by design)."""
    from deeplearning4j_tpu.nn import InputType, LossFunction
    from deeplearning4j_tpu.nn.layers import LSTMLayer, RnnOutputLayer

    b = NeuralNetConfiguration.builder().seed(17).list()
    b.layer(LSTMLayer(n_out=8))
    b.layer(LSTMLayer(n_out=8))
    b.layer(RnnOutputLayer(n_out=4, loss=LossFunction.MCXENT,
                           activation=Activation.SOFTMAX))
    b.set_input_type(InputType.recurrent(5, 6))
    return MultiLayerNetwork(b.build()).init()


def test_every_discovered_pass_is_noop_on_patternless_model():
    from deeplearning4j_tpu.core.config import to_json

    passes = _discovered_passes()
    assert len(passes) >= 4  # 3 structural + at least int8 quantization
    assert any(n.startswith("quantize_weights") for n, _ in passes)
    model = _patternless_model()
    before_json = to_json(model.conf)
    for name, p in passes:
        conf2, params2, state2, changed = p.apply(
            model.conf, model.params, model.state)
        assert not changed, f"{name} claimed a match on a patternless model"
        assert conf2 is model.conf, f"{name} rebuilt the config object"
        assert params2 is model.params, f"{name} rebuilt the params pytree"
        assert state2 is model.state, f"{name} rebuilt the state pytree"
        assert to_json(conf2) == before_json, f"{name} mutated the config"
