"""Two-process compressed-gradient exchange over a real transport
(VERDICT.md round 3 weak 6: "the claimed compressed-DCN path has no
multi-process demonstration"). Two worker processes each hold a gradient
shard, threshold-encode it with the native codec (libdl4jtpu), exchange the
COMPRESSED buffers over a localhost TCP socket (the DCN stand-in), decode
the peer's, and average — the SharedTrainingMaster gradient-sharing wire
pattern (SURVEY.md:322)."""

import json
import socket
import struct
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from deeplearning4j_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native libdl4jtpu not built")


def _worker_code() -> str:
    # full worker script; _recv helper inlined (sized reads over TCP)
    return textwrap.dedent("""
        import json, socket, struct, sys
        import numpy as np
        from deeplearning4j_tpu import native

        def recv_exact(conn, n):
            out = b""
            while len(out) < n:
                chunk = conn.recv(n - len(out))
                if not chunk:
                    raise ConnectionError("peer closed")
                out += chunk
            return out

        rank = int(sys.argv[1]); port = int(sys.argv[2]); threshold = 1e-3
        rng = np.random.RandomState(100 + rank)
        grad = (rng.randn(4096).astype(np.float32) * 5e-4)

        encoded = native.threshold_encode(grad, threshold)  # grad keeps residual
        payload = encoded.tobytes()

        if rank == 0:
            srv = socket.socket()
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind(("127.0.0.1", port)); srv.listen(1)
            srv.settimeout(30)
            conn, _ = srv.accept()
        else:
            conn = socket.socket()
            import time
            for _ in range(200):
                try:
                    conn.connect(("127.0.0.1", port)); break
                except OSError:
                    time.sleep(0.05)

        conn.sendall(struct.pack("<I", len(payload)) + payload)
        (n_bytes,) = struct.unpack("<I", recv_exact(conn, 4))
        peer_encoded = np.frombuffer(recv_exact(conn, n_bytes), np.int32)
        conn.close()

        mine = np.zeros(grad.size, np.float32)
        native.threshold_decode(encoded, threshold, mine)
        theirs = np.zeros(grad.size, np.float32)
        native.threshold_decode(peer_encoded, threshold, theirs)
        averaged = 0.5 * (mine + theirs)
        print(json.dumps({
            "rank": rank,
            "wire_bytes": len(payload),
            "dense_bytes": int(grad.nbytes),
            "sum": float(averaged.sum()),
            "nonzero": int(np.count_nonzero(averaged)),
            "checksum": float(np.abs(averaged).sum()),
        }))
    """)


def test_two_process_compressed_gradient_exchange(tmp_path):
    port = 29517
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _worker_code(), str(rank), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
        )
        for rank in (0, 1)
    ]
    results = {}
    for p in procs:
        out, err = p.communicate(timeout=120)
        assert p.returncode == 0, f"worker failed: {err[-800:]}"
        r = json.loads(out.strip().splitlines()[-1])
        results[r["rank"]] = r

    # both workers computed the SAME average (the all-reduce contract)
    assert results[0]["checksum"] == pytest.approx(results[1]["checksum"])
    assert results[0]["sum"] == pytest.approx(results[1]["sum"])
    # the wire carried compressed data, much smaller than dense f32
    for r in results.values():
        assert r["wire_bytes"] < r["dense_bytes"] / 4, (
            f"no compression: {r['wire_bytes']} vs dense {r['dense_bytes']}")
    # and the decoded average reproduces the host-side reference math
    t = 1e-3
    expect = np.zeros(4096, np.float32)
    for k in (0, 1):
        g = np.random.RandomState(100 + k).randn(4096).astype(np.float32) * 5e-4
        dec = np.zeros(4096, np.float32)
        native.threshold_decode(native.threshold_encode(g, t), t, dec)
        expect += 0.5 * dec
    assert results[0]["checksum"] == pytest.approx(float(np.abs(expect).sum()),
                                                   rel=1e-6)
