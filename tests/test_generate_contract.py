"""Tier-1 wiring for tools/check_generate_contract.py: the streaming
generation-serving contract (README.md "Generation serving" — ordered
token events over real HTTP, mid-stream deadline with partial output,
admission shed -> 503 + Retry-After, disconnect frees the cache slot,
metric/trace surfaces, and the ISSUE-11 pooled route: /v1/generate via
EnginePool.submit_generate over speculative decode replicas with
X-Request-Id echo, per-request speculative_k, and acceptance-rate
stats) is enforced on every test run, mirroring
test_serving_contract.py / test_trace_contract.py."""

import os
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


def test_generate_contract_smoke():
    sys.path.insert(0, _TOOLS)
    try:
        import check_generate_contract
    finally:
        sys.path.remove(_TOOLS)
    assert check_generate_contract.main(log=lambda m: None) == 0
