"""PipelineParallelTrainer: stage partitioning, trajectory equality
against the single-device Solver (the tier-1 PP gate), composition with
data parallelism + ZeRO-1, checkpoint interchange with non-PP trainers,
and the over-one-chip memory proof (stage_param_bytes)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import (
    Activation,
    InputType,
    LossFunction,
    MultiLayerNetwork,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.train import Adam, Sgd
from deeplearning4j_tpu.train.solver import Solver
from deeplearning4j_tpu.parallel import PipelineParallelTrainer
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.pipeline import partition_stages

NIN, H, NOUT = 6, 12, 3


def _chain(seed=7, n_blocks=4, h=H, updater=None, l2=0.0):
    """pre-dense + n_blocks identical dense blocks + output head: the
    canonical periodic chain partition_stages understands."""
    b = (NeuralNetConfiguration.builder()
         .seed(seed)
         .updater(updater if updater is not None else Sgd(0.2)))
    if l2:
        b = b.l2(l2)
    b = b.list().layer(DenseLayer(n_out=h, activation=Activation.TANH))
    for _ in range(n_blocks):
        b = b.layer(DenseLayer(n_out=h, activation=Activation.TANH))
    conf = (b.layer(OutputLayer(n_out=NOUT, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(NIN))
            .build())
    return MultiLayerNetwork(conf).init()


def _batch(n=32, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, NIN).astype(np.float32)
    y = np.eye(NOUT, dtype=np.float32)[rs.randint(0, NOUT, n)]
    return x, y


# ---------------------------------------------------------------------------
# Stage partitioning
# ---------------------------------------------------------------------------


def test_partition_stages_layout():
    m = _chain(n_blocks=6)
    part = partition_stages(m, 4)
    assert part.n_stages == 4
    assert part.prelude == (0,)            # input dense pinned to stage 0
    assert part.head == (7,)               # output layer pinned to last
    assert part.n_blocks == 6
    assert sum(part.blocks_per_stage) == 6
    assert all(c >= 1 for c in part.blocks_per_stage)
    # stage_units covers every layer exactly once, in order
    flat = [i for units in part.stage_units for i in units]
    assert flat == list(range(8))


def test_partition_balances_parameter_cost():
    m = _chain(n_blocks=8)
    part = partition_stages(m, 4)
    # 8 identical blocks over 4 stages: no stage may be starved, and the
    # max/mean stage-cost ratio should stay close to even
    assert min(part.blocks_per_stage) >= 1
    assert 1.0 <= part.balance < 1.5


def test_partition_rejects_aperiodic_chain():
    m = _chain(n_blocks=1)  # pre + 1 block + head: no period covers S=4
    with pytest.raises(ValueError):
        partition_stages(m, 4)


def test_partition_rejects_single_stage():
    m = _chain(n_blocks=4)
    with pytest.raises(ValueError):
        partition_stages(m, 1)


def test_graph_linear_chain_rejects_branching():
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    b = (NeuralNetConfiguration.builder().seed(1).updater(Sgd(0.1))
         .graph_builder().add_inputs("in"))
    b = b.add_layer("d1", DenseLayer(n_out=H, activation=Activation.TANH),
                    "in")
    b = b.add_layer("d2", DenseLayer(n_out=H, activation=Activation.TANH),
                    "in")  # second consumer of "in": a branch
    b = b.add_layer("out", OutputLayer(n_out=NOUT, loss=LossFunction.MCXENT),
                    "d1")
    conf = (b.set_outputs("out")
            .set_input_types(InputType.feed_forward(NIN)).build())
    g = ComputationGraph(conf).init()
    with pytest.raises(ValueError):
        g.linear_chain()


def test_forward_pure_start_folds_suffix():
    # fold layers [0, 3) via upto=, then resume from the boundary with
    # start=3: together they must equal the full forward
    m = _chain(n_blocks=4)
    x, _ = _batch(8)
    full = m.forward_pure(m.params, m.state, jnp.asarray(x),
                          train=False, rng=None)[0]
    h = m.forward_pure(m.params, m.state, jnp.asarray(x),
                       train=False, rng=None, upto=3)[0]
    resumed = m.forward_pure(m.params, m.state, h,
                             train=False, rng=None, start=3)[0]
    np.testing.assert_allclose(np.asarray(full), np.asarray(resumed),
                               rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# Trajectory equality: pipelined training == single-device Solver
# (the tier-1 PP gate) — both schedules
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_trainer_matches_solver(schedule):
    mesh = make_mesh(devices=jax.devices()[:4], pipe=4)
    m = _chain(n_blocks=4)
    tr = PipelineParallelTrainer(m, mesh, n_micro=8, schedule=schedule,
                                 stage_time_probe=False)
    ref = Solver(_chain(n_blocks=4))
    x, y = _batch(32)
    for i in range(3):
        lp = float(tr.fit_batch(x, y))
        ls, _ = ref.fit_batch(x, y)
        np.testing.assert_allclose(lp, float(ls), rtol=1e-5,
                                   err_msg=f"step {i}")
    tr.sync_to_model()
    for name, group in ref.model.params.items():
        for pname, pv in group.items():
            np.testing.assert_allclose(
                np.asarray(jax.device_get(m.params[name][pname])),
                np.asarray(jax.device_get(pv)),
                rtol=2e-4, atol=2e-5, err_msg=f"{name}/{pname}")


@pytest.mark.parametrize("n_micro", [5, 2])
def test_trainer_degenerate_microbatching(n_micro):
    # M not a multiple of S, and M < S (fill/drain dominated): still exact
    mesh = make_mesh(devices=jax.devices()[:4], pipe=4)
    m = _chain(n_blocks=4, seed=11)
    tr = PipelineParallelTrainer(m, mesh, n_micro=n_micro,
                                 stage_time_probe=False)
    ref = Solver(_chain(n_blocks=4, seed=11))
    x, y = _batch(n_micro * 4, seed=2)
    for _ in range(2):
        lp = float(tr.fit_batch(x, y))
        ls, _ = ref.fit_batch(x, y)
        np.testing.assert_allclose(lp, float(ls), rtol=1e-5)


def test_trainer_resident_microbatches_bound():
    # acceptance: 1F1B resident activations ≤ S microbatches, and the
    # trainer reports it (GPipe pays M for the same bubble share)
    mesh = make_mesh(devices=jax.devices()[:4], pipe=4)
    m = _chain(n_blocks=4)
    tr = PipelineParallelTrainer(m, mesh, n_micro=8, schedule="1f1b",
                                 stage_time_probe=False)
    st = tr.stats()
    assert st["resident_microbatches"] <= tr.n_stages
    assert st["bubble_share"] < 0.35
    m2 = _chain(n_blocks=4)
    gp = PipelineParallelTrainer(m2, mesh, n_micro=8, schedule="gpipe",
                                 stage_time_probe=False)
    assert gp.stats()["resident_microbatches"] == 8
    assert gp.stats()["bubble_share"] == st["bubble_share"]


# ---------------------------------------------------------------------------
# Composition: pipe × data mesh, ZeRO-1 inside stages
# ---------------------------------------------------------------------------


def test_pp_dp_zero1_matches_replicated_and_solver():
    x, y = _batch(32, seed=5)
    mk = lambda: _chain(n_blocks=4, seed=13, updater=Adam(0.01), l2=0.01)

    mesh = make_mesh(pipe=4, data=2)
    trz = PipelineParallelTrainer(mk(), mesh, n_micro=4, zero1=True,
                                  stage_time_probe=False)
    assert trz.n_data_shards == 2 and trz.zero1
    trr = PipelineParallelTrainer(mk(), mesh, n_micro=4, zero1=False,
                                  stage_time_probe=False)
    ref = Solver(mk())
    for i in range(3):
        lz = float(trz.fit_batch(x, y))
        lr = float(trr.fit_batch(x, y))
        ls, _ = ref.fit_batch(x, y)
        np.testing.assert_allclose(lz, float(ls), rtol=2e-4,
                                   err_msg=f"zero1 step {i}")
        np.testing.assert_allclose(lr, float(ls), rtol=2e-4,
                                   err_msg=f"replicated step {i}")
    # final params agree across all three trainings
    trz.sync_to_model()
    trr.sync_to_model()
    for name, group in ref.model.params.items():
        for pname, pv in group.items():
            ref_a = np.asarray(jax.device_get(pv))
            np.testing.assert_allclose(
                np.asarray(jax.device_get(trz.model.params[name][pname])),
                ref_a, rtol=2e-4, atol=2e-5, err_msg=f"z {name}/{pname}")
            np.testing.assert_allclose(
                np.asarray(jax.device_get(trr.model.params[name][pname])),
                ref_a, rtol=2e-4, atol=2e-5, err_msg=f"r {name}/{pname}")


# ---------------------------------------------------------------------------
# Checkpoint interchange: PP ↔ non-PP via global-shape opt_state/params
# ---------------------------------------------------------------------------


def test_opt_state_speaks_global_shapes():
    mesh = make_mesh(devices=jax.devices()[:4], pipe=4)
    m = _chain(n_blocks=4, updater=Adam(0.01))
    tr = PipelineParallelTrainer(m, mesh, n_micro=4,
                                 stage_time_probe=False)
    ref = Solver(_chain(n_blocks=4, updater=Adam(0.01)))
    got = jax.tree_util.tree_structure(tr.opt_state)
    want = jax.tree_util.tree_structure(ref.opt_state)
    assert got == want
    for a, b in zip(jax.tree_util.tree_leaves(tr.opt_state),
                    jax.tree_util.tree_leaves(ref.opt_state)):
        assert np.shape(a) == np.shape(b)


def test_orbax_interchange_pp_and_dp(tmp_path):
    from deeplearning4j_tpu.parallel import DistributedTrainer
    from deeplearning4j_tpu.train.orbax_checkpoint import OrbaxCheckpointer

    x, y = _batch(32, seed=9)
    mk = lambda: _chain(n_blocks=4, seed=17, updater=Adam(0.01))

    # train 2 steps pipelined, checkpoint, restore into a data-parallel
    # trainer, and train one more step — must equal 3 pipelined steps
    tr = PipelineParallelTrainer(mk(), make_mesh(devices=jax.devices()[:4], pipe=4),
                                 n_micro=4, schedule="1f1b",
                                 stage_time_probe=False)
    tr.fit_batch(x, y)
    tr.fit_batch(x, y)
    ck = OrbaxCheckpointer(str(tmp_path / "pp"), async_save=False)
    ck.save(2, tr)
    ck.wait()

    ref = PipelineParallelTrainer(mk(), make_mesh(devices=jax.devices()[:4], pipe=4),
                                  n_micro=4, schedule="gpipe",
                                  stage_time_probe=False)
    meta = ck.restore(ref)  # PP(1f1b) -> PP(gpipe): global shapes reshard
    assert meta.get("pipeline_stages") == 4
    l_ref = float(ref.fit_batch(x, y))

    dp = DistributedTrainer(mk(), make_mesh(data=8), zero1=True)
    ck.restore(dp)  # PP -> DP: same global tree, zero1 resharding
    l_dp = float(dp.fit_batch(x, y))
    np.testing.assert_allclose(l_dp, l_ref, rtol=1e-4)

    # and back: checkpoint the DP trainer, restore into PP, step again
    ck2 = OrbaxCheckpointer(str(tmp_path / "dp"), async_save=False)
    ck2.save(3, dp)
    ck2.wait()
    tr2 = PipelineParallelTrainer(mk(), make_mesh(devices=jax.devices()[:4], pipe=4),
                                  n_micro=4, stage_time_probe=False)
    ck2.restore(tr2)
    l_pp = float(tr2.fit_batch(x, y))
    l_dp2 = float(dp.fit_batch(x, y))
    np.testing.assert_allclose(l_pp, l_dp2, rtol=1e-4)


def test_load_updater_state_rejects_mismatched_tree():
    mesh = make_mesh(devices=jax.devices()[:4], pipe=4)
    m = _chain(n_blocks=4, updater=Adam(0.01))
    tr = PipelineParallelTrainer(m, mesh, n_micro=4,
                                 stage_time_probe=False)
    bad = Solver(_chain(n_blocks=4, updater=Sgd(0.1))).opt_state
    with pytest.raises(ValueError):
        tr.load_updater_state(bad)


# ---------------------------------------------------------------------------
# Over-one-chip proof: global params exceed a per-device budget, the
# per-stage share fits, and the model still trains on the 8-device mesh
# ---------------------------------------------------------------------------


def test_over_budget_model_trains():
    # 8 blocks of 96x96 dense on an 8-stage pipe: ~75 KiB of block params
    # per stage vs ~600 KiB global. Budget set between the two: no single
    # device could hold the full model under it, each stage's share fits.
    m = _chain(n_blocks=8, h=96)
    mesh = make_mesh(pipe=8)
    tr = PipelineParallelTrainer(m, mesh, n_micro=8,
                                 stage_time_probe=False)
    per_dev = tr.stage_param_bytes()
    total = tr.stage_param_bytes(per_device=False)
    budget = 2 * per_dev
    assert per_dev <= budget < total, (per_dev, budget, total)
    x, y = _batch(32, seed=3)
    l0 = float(tr.fit_batch(x, y))
    l1 = l0
    for _ in range(4):
        l1 = float(tr.fit_batch(x, y))
    assert np.isfinite(l1) and l1 < l0


# ---------------------------------------------------------------------------
# Scope errors: clear failures instead of silent wrong math
# ---------------------------------------------------------------------------


def test_rejects_trust_ratio_body_updater():
    from deeplearning4j_tpu.train import Lars

    m = _chain(n_blocks=4, updater=Lars(0.1))
    mesh = make_mesh(devices=jax.devices()[:4], pipe=4)
    with pytest.raises(ValueError, match="elementwise"):
        PipelineParallelTrainer(m, mesh, n_micro=4,
                                stage_time_probe=False)


def test_rejects_batch_size_not_divisible():
    mesh = make_mesh(devices=jax.devices()[:4], pipe=4)
    m = _chain(n_blocks=4)
    tr = PipelineParallelTrainer(m, mesh, n_micro=8,
                                 stage_time_probe=False)
    x, y = _batch(30)  # 30 % 8 != 0
    with pytest.raises(ValueError):
        tr.fit_batch(x, y)
