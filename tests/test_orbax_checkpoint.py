"""Orbax async/sharded checkpointing (SURVEY §5.4's named TPU design):
save a sharded DistributedTrainer mid-training, keep training, restore
into a FRESH trainer on the same mesh, and resume to identical losses."""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.nn import (
    Activation, InputType, LossFunction, NeuralNetConfiguration, WeightInit,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.trainer import DistributedTrainer
from deeplearning4j_tpu.train.orbax_checkpoint import OrbaxCheckpointer
from deeplearning4j_tpu.train.updaters import Adam


def _net():
    conf = (NeuralNetConfiguration.builder().seed(21).updater(Adam(0.01))
            .weight_init(WeightInit.XAVIER).list()
            .layer(DenseLayer(n_out=16, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


def _data():
    rs = np.random.RandomState(0)
    x = rs.rand(16, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 16)]
    return x, y


def test_sharded_save_restore_resume_identical(tmp_path):
    x, y = _data()
    mesh = make_mesh(data=4, model=2)
    rules = [(r"layer_0/W", __import__("jax").sharding.PartitionSpec(
        None, "model"))]

    t1 = DistributedTrainer(_net(), mesh=mesh, param_sharding_rules=rules)
    for _ in range(3):
        t1.fit_batch(x, y)
    ckpt = OrbaxCheckpointer(str(tmp_path / "ck"), async_save=False)
    ckpt.save(3, t1)
    ckpt.wait()
    # reference trajectory: continue the original trainer
    ref = [float(t1.fit_batch(x, y)) for _ in range(3)]

    # fresh trainer on the same mesh, restored from disk
    t2 = DistributedTrainer(_net(), mesh=mesh, param_sharding_rules=rules)
    meta = ckpt.restore(t2)
    assert meta["iteration"] == 3
    # restore preserved the TP sharding (leaf is sharded, not replicated)
    w = t2.params["layer_0"]["W"]
    assert not w.sharding.is_fully_replicated
    got = [float(t2.fit_batch(x, y)) for _ in range(3)]
    np.testing.assert_allclose(got, ref, rtol=1e-6)
    ckpt.close()


def test_async_save_overlaps_and_keeps_k(tmp_path):
    x, y = _data()
    t = DistributedTrainer(_net(), mesh=make_mesh(data=8))
    ckpt = OrbaxCheckpointer(str(tmp_path / "ck"), max_to_keep=2,
                             async_save=True)
    for step in range(4):
        t.fit_batch(x, y)
        ckpt.save(step, t)  # returns without blocking on serialization
    ckpt.wait()
    assert ckpt.latest_step() == 3
    # keep-last-K pruning (CheckpointListener parity)
    t2 = DistributedTrainer(_net(), mesh=make_mesh(data=8))
    ckpt.restore(t2, step=3)
    with pytest.raises(Exception):
        ckpt.restore(t2, step=0)  # pruned
    # config sidecar preserved ("config is data")
    import os
    assert os.path.exists(str(tmp_path / "ck" / "configuration.json"))
    ckpt.close()
