"""ZeRO-1 cross-replica sharded weight update — equivalence suite on the
8-virtual-device CPU mesh.

The correctness claim ("Automatic Cross-Replica Sharding of Weight Update
in Data-Parallel Training", PAPERS.md): partitioning updater state 1/N
over the data axis and updating only per-replica parameter slices
followed by an all-gather is EXACTLY the replicated update — same loss
trajectory, same params, for every elementwise updater — while the
per-replica optimizer memory drops ~1/N. The end-to-end sweep (both
trainer paths, checkpoint layout independence, metric series) lives in
tools/check_dp_update_contract.py via test_dp_update_contract.py; this
file covers the per-updater trajectories and the seams.
"""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.nn import (
    Activation,
    InputType,
    LossFunction,
    MultiLayerNetwork,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel import (
    DistributedTrainer,
    ParameterAveragingSync,
    ThresholdCompressedSync,
    TopKCompressedSync,
    make_mesh,
    zero1_partition_spec,
)
from deeplearning4j_tpu.train import Adam, Sgd, registered_updaters


def _mlp(seed=7, updater=None, nin=16, hidden=64, nout=8):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater or Adam(0.01))
        .list()
        .layer(DenseLayer(n_out=hidden, activation=Activation.TANH))
        .layer(OutputLayer(n_out=nout, loss=LossFunction.MCXENT))
        .set_input_type(InputType.feed_forward(nin))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0, nin=16, nout=8):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, nin).astype(np.float32)
    y = np.eye(nout, dtype=np.float32)[rng.randint(0, nout, n)]
    return x, y


def _assert_params_match(a, b, rtol=2e-5, atol=2e-6):
    for ln in a:
        for pn in a[ln]:
            np.testing.assert_allclose(
                np.asarray(a[ln][pn]), np.asarray(b[ln][pn]),
                rtol=rtol, atol=atol, err_msg=f"{ln}/{pn}")


class TestZero1Equivalence:
    # AUTO-DISCOVERED: every @register_config'd IUpdater — incl. the
    # trust-ratio pair (Lars/Lamb, whose layer norms must be psum-spelled
    # on the explicit path) and any future updater — inherits the
    # zero1==replicated trajectory contract without being hand-listed.
    @pytest.mark.parametrize("updater_cls", registered_updaters(),
                             ids=lambda c: c.__name__.lower())
    def test_matches_replicated_trajectory(self, updater_cls):
        """zero1 == replicated to float tolerance, per registered updater
        (default-constructed; equality of the two trajectories is the
        claim, not convergence)."""
        updater = updater_cls()
        x, y = _data()
        mesh = make_mesh(data=8)
        t_rep = DistributedTrainer(_mlp(3, updater), mesh=mesh)
        t_z = DistributedTrainer(_mlp(3, updater), mesh=mesh, zero1=True)
        for _ in range(5):
            s_rep = float(t_rep.fit_batch(x, y))
            s_z = float(t_z.fit_batch(x, y))
        assert np.isfinite(s_rep), updater
        assert np.isclose(s_rep, s_z, rtol=1e-5), (s_rep, s_z)
        t_rep.sync_to_model()
        t_z.sync_to_model()
        _assert_params_match(t_rep.model.params, t_z.model.params)

    def test_explicit_path_matches_under_threshold_compression(self):
        """Same equivalence on the shard_map path: zero1 with a compressed
        strategy follows the strategy's own (compressed) trajectory."""
        x, y = _data()
        mesh = make_mesh(data=8)
        mk = lambda: ThresholdCompressedSync(  # noqa: E731
            threshold=1e-3, target_density=0.2)
        t_rep = DistributedTrainer(_mlp(5), mesh=mesh, strategy=mk())
        t_z = DistributedTrainer(_mlp(5), mesh=mesh, strategy=mk(),
                                 zero1=True)
        for _ in range(5):
            s_rep = float(t_rep.fit_batch(x, y))
            s_z = float(t_z.fit_batch(x, y))
        assert np.isclose(s_rep, s_z, rtol=1e-5), (s_rep, s_z)
        t_rep.sync_to_model()
        t_z.sync_to_model()
        _assert_params_match(t_rep.model.params, t_z.model.params)
        # the adaptive threshold trajectory agrees too
        assert t_rep.threshold_value() == pytest.approx(
            t_z.threshold_value(), rel=1e-6)

    def test_updater_state_actually_sharded(self):
        """The dominant (param-shaped) Adam moments live at 1/8 per
        replica; step-count scalars stay replicated."""
        x, y = _data()
        t = DistributedTrainer(_mlp(), mesh=make_mesh(data=8), zero1=True)
        t.fit_batch(x, y)
        specs = {str(l.sharding.spec): l.shape
                 for l in jax.tree_util.tree_leaves(t.opt_state)}
        assert "PartitionSpec('data',)" in specs, specs
        per = t.updater_state_bytes()
        glob = t.updater_state_bytes(per_replica=False)
        assert per < glob / 5  # ~1/8 + replicated scalars
        s = t.stats()
        assert s["zero1"] and s["updater_state_bytes"] == per
        assert s["updater_state_bytes_global"] == glob

    def test_param_averaging_rejected(self):
        with pytest.raises(ValueError, match="identical on every replica"):
            DistributedTrainer(_mlp(), mesh=make_mesh(data=8), zero1=True,
                               strategy=ParameterAveragingSync(frequency=4))

    def test_non_divisible_dims_stay_replicated_and_train(self):
        """nout=5: output-layer bias (5,) is not divisible by 8 — it must
        replicate while the rest shards, with the trajectory unchanged."""
        x, y = _data(nout=5)
        mesh = make_mesh(data=8)
        t_rep = DistributedTrainer(_mlp(3, nout=5), mesh=mesh)
        t_z = DistributedTrainer(_mlp(3, nout=5), mesh=mesh, zero1=True)
        for _ in range(3):
            s_rep = float(t_rep.fit_batch(x, y))
            s_z = float(t_z.fit_batch(x, y))
        assert np.isclose(s_rep, s_z, rtol=1e-5)


class TestTopKCompressedSync:
    def test_trains_and_reports_density(self):
        x, y = _data()
        t = DistributedTrainer(_mlp(9), mesh=make_mesh(data=8), zero1=True,
                               strategy=TopKCompressedSync(density=0.05))
        first = float(t.fit_batch(x, y))
        for _ in range(40):
            last = float(t.fit_batch(x, y))
        assert last < first
        comp = t.compression_stats()
        assert comp["target_density"] == pytest.approx(0.05)
        # ties can push the realized density slightly over target
        assert 0.0 < comp["density"] < 0.15
        assert comp["compression_ratio"] > 5
        assert t.threshold_value() is None  # no threshold: must not crash

    def test_invalid_density_rejected(self):
        with pytest.raises(ValueError):
            TopKCompressedSync(density=0.0)
        with pytest.raises(ValueError):
            TopKCompressedSync(density=1.5)

    def test_zero_accumulator_selects_nothing(self):
        """All-zero grads+residual must exchange nothing (the >=kth mask
        alone would select everything when the k-th magnitude is 0)."""
        from jax.sharding import PartitionSpec as P

        from deeplearning4j_tpu.parallel.mesh import shmap

        topk = TopKCompressedSync(density=0.1)
        g = {"l": {"W": np.zeros((8, 8), np.float32)}}
        st = topk.init_state(g)
        synced, new_st = jax.jit(shmap(
            lambda gg, ss: topk.sync(gg, ss, "data"), make_mesh(data=8),
            in_specs=(P(), {"residual": P(), "density": P()}),
            out_specs=(P(), {"residual": P(), "density": P()}),
        ))(g, st)
        assert not np.any(np.asarray(synced["l"]["W"]))
        assert float(new_st["density"]) == 0.0


class TestZero1PartitionSpec:
    def test_rules(self):
        from jax.sharding import PartitionSpec as P

        assert zero1_partition_spec((16, 8), 8) == P("data")
        assert zero1_partition_spec((16, 8), 8, base=P(None, "model")) == \
            P("data", "model")
        # dim 0 already TP-sharded: never double-shard
        assert zero1_partition_spec((16, 8), 8, base=P("model", None)) == \
            P("model", None)
        assert zero1_partition_spec((6,), 4) == P()   # not divisible
        assert zero1_partition_spec((), 4) == P()     # scalar
        assert zero1_partition_spec((16,), 1) == P()  # single shard


class TestZero1Checkpoint:
    def test_replicated_save_restores_into_sharded_trainer(self, tmp_path):
        """The reverse direction of the contract tool's round trip: a
        replicated checkpoint reshards onto the zero1 layout on read."""
        from deeplearning4j_tpu.train.orbax_checkpoint import OrbaxCheckpointer

        x, y = _data()
        mesh = make_mesh(data=8)
        t_rep = DistributedTrainer(_mlp(5), mesh=mesh)
        for _ in range(3):
            t_rep.fit_batch(x, y)
        ck = OrbaxCheckpointer(str(tmp_path / "ck"), async_save=False)
        ck.save(3, t_rep)
        ck.wait()
        ref = [float(t_rep.fit_batch(x, y)) for _ in range(3)]

        t_z = DistributedTrainer(_mlp(5), mesh=mesh, zero1=True)
        meta = ck.restore(t_z)
        assert meta["zero1"] is False
        mu = [l for l in jax.tree_util.tree_leaves(t_z.opt_state)
              if l.ndim == 2][0]
        assert "data" in str(mu.sharding.spec)  # resharded on restore
        got = [float(t_z.fit_batch(x, y)) for _ in range(3)]
        np.testing.assert_allclose(got, ref, rtol=1e-6)
        ck.close()

    def test_pre_density_strat_state_migrates(self, tmp_path):
        """Checkpoints from before the compression-density key restore:
        saved keys come back, the new key keeps its fresh value."""
        from deeplearning4j_tpu.train.orbax_checkpoint import OrbaxCheckpointer

        x, y = _data()
        mesh = make_mesh(data=8)
        mk = lambda: ThresholdCompressedSync(target_density=0.2)  # noqa: E731
        t = DistributedTrainer(_mlp(7), mesh=mesh, strategy=mk(), zero1=True)
        for _ in range(3):
            t.fit_batch(x, y)
        saved_threshold = t.threshold_value()
        # simulate the pre-zero1 writer: no "density" key in strat_state
        t.strat_state = {k: v for k, v in t.strat_state.items()
                         if k != "density"}
        ck = OrbaxCheckpointer(str(tmp_path / "ck"), async_save=False)
        ck.save(3, t)
        ck.wait()

        t2 = DistributedTrainer(_mlp(7), mesh=mesh, strategy=mk(), zero1=True)
        ck.restore(t2)
        assert set(t2.strat_state.keys()) == {"residual", "threshold",
                                              "density"}
        assert t2.threshold_value() == pytest.approx(saved_threshold)
        assert float(t2.strat_state["density"]) == 0.0  # fresh value
        assert np.isfinite(float(t2.fit_batch(x, y)))  # resumes cleanly
        ck.close()

    def test_incompatible_updater_clear_error(self, tmp_path):
        from deeplearning4j_tpu.train.orbax_checkpoint import OrbaxCheckpointer

        x, y = _data()
        mesh = make_mesh(data=8)
        t = DistributedTrainer(_mlp(3), mesh=mesh, zero1=True)
        t.fit_batch(x, y)
        ck = OrbaxCheckpointer(str(tmp_path / "ck"), async_save=False)
        ck.save(1, t)
        ck.wait()
        wrong = DistributedTrainer(_mlp(3, updater=Sgd(0.1)), mesh=mesh)
        with pytest.raises(ValueError, match="incompatible.*opt_state"):
            ck.restore(wrong)
        ck.close()


class TestCheckpointListenerTrainerSync:
    def test_listener_saves_live_params(self, tmp_path):
        """CheckpointListener(trainer=) writes the LIVE device params, not
        the stale pre-fit model copy (the trainer only syncs back at
        fit() end)."""
        from deeplearning4j_tpu.model.serializer import restore_model
        from deeplearning4j_tpu.train.checkpoint import CheckpointListener

        x, y = _data()
        model = _mlp(11)
        stale = {ln: {pn: np.array(p) for pn, p in lp.items()}
                 for ln, lp in model.params.items()}
        trainer = DistributedTrainer(model, mesh=make_mesh(data=8))
        listener = CheckpointListener(
            str(tmp_path), save_every_n_iterations=1, save_updater=False,
            trainer=trainer)
        model.listeners.add(listener)
        trainer.fit(x, y, epochs=1)
        path = CheckpointListener.last_checkpoint(str(tmp_path))
        assert path is not None
        saved = restore_model(path)
        # saved params moved away from initialization == live at save time
        w_saved = np.asarray(saved.params["layer_0"]["W"])
        assert not np.allclose(w_saved, stale["layer_0"]["W"])
        np.testing.assert_allclose(
            w_saved, np.asarray(trainer.model.params["layer_0"]["W"]),
            rtol=1e-6)
