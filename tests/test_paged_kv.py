"""Paged KV cache unit tests (ISSUE 17): block allocator semantics,
paged-vs-static greedy token identity through the DecodeEngine (fp and
int8, plain and speculative), live resident-bytes accounting, and
out-of-blocks admission/preemption behavior.

Engines compile real jit programs, so the static/paged fp pair is
module-scoped and shared across the identity + accounting tests — each
extra DecodeEngine costs seconds of compile time on the tier-1 clock."""

import numpy as np
import pytest

from deeplearning4j_tpu.generate import (BlockAllocator, OutOfBlocksError,
                                         block_bytes, blocks_needed,
                                         paged_decode_state)
from deeplearning4j_tpu.generate.session import GenerationSession
from deeplearning4j_tpu.model.zoo import TextGenerationLSTM, TransformerLM
from deeplearning4j_tpu.obs.metrics import MetricsRegistry
from deeplearning4j_tpu.parallel.decode import DecodeEngine

MAX_LEN = 24
PROMPTS = [[1, 2, 3], [4, 5, 6, 7, 8], [2, 2]]


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(vocab_size=23, hidden=32, n_layers=2,
                         n_heads=4, max_len=MAX_LEN).init()


@pytest.fixture(scope="module")
def draft():
    return TransformerLM(vocab_size=23, hidden=16, n_layers=1,
                         n_heads=2, max_len=MAX_LEN).init()


def _engine(lm, **kw):
    kw.setdefault("registry", MetricsRegistry())
    return DecodeEngine(lm, max_len=MAX_LEN, **kw)


@pytest.fixture(scope="module")
def static_eng(lm):
    eng = _engine(lm, slots=4)
    yield eng
    eng.shutdown()


@pytest.fixture(scope="module")
def paged_eng(lm):
    reg = MetricsRegistry()
    eng = _engine(lm, slots=4, block_size=4, registry=reg)
    eng._test_registry = reg
    yield eng
    eng.shutdown()


def _collect(eng, prompts, **kw):
    hs = [eng.submit(p, max_tokens=6, **kw) for p in prompts]
    return [h.result(timeout=120) for h in hs]


class TestBlockAllocator:
    def test_block_zero_reserved_and_all_or_nothing(self):
        a = BlockAllocator(5)  # 4 usable, block 0 is trash
        assert a.total_blocks == 4
        assert a.free_blocks == 4
        ids = a.alloc(3)
        assert len(ids) == 3 and 0 not in ids
        assert a.free_blocks == 1
        # all-or-nothing: asking for 2 with 1 free changes nothing
        with pytest.raises(OutOfBlocksError):
            a.alloc(2)
        assert a.free_blocks == 1
        a.free(ids)
        assert a.free_blocks == 4

    def test_free_validates_ids(self):
        a = BlockAllocator(4)
        with pytest.raises(ValueError):
            a.free([0])  # the trash block is never allocated
        with pytest.raises(ValueError):
            a.free([4])

    def test_blocks_needed(self):
        assert blocks_needed(0, 4) == 0
        assert blocks_needed(1, 4) == 1
        assert blocks_needed(4, 4) == 1
        assert blocks_needed(5, 4) == 2


class TestPagedState:
    def test_pools_and_tables_shape(self, lm):
        sess = GenerationSession(lm, max_len=MAX_LEN)
        carry = paged_decode_state(sess, 3, block_size=4, num_blocks=10)
        paged = [st for st in carry.values() if "block_table" in st]
        assert paged, "attention layers must be paged"
        for st in paged:
            assert st["block_table"].shape == (3, MAX_LEN // 4)
            assert st["cache_k"].shape[0] == 10  # pool-indexed
            assert st["cache_k"].shape[2] == 4   # block-sized
        assert block_bytes(sess, 4) > 0

    def test_recurrent_carry_rejected(self):
        lstm = TextGenerationLSTM(vocab_size=11, hidden=16).init()
        sess = GenerationSession(lstm, max_len=MAX_LEN)
        with pytest.raises(ValueError, match="not\\s+pageable"):
            paged_decode_state(sess, 2, block_size=4, num_blocks=10)

    def test_max_len_divisibility_enforced(self, lm):
        with pytest.raises(ValueError, match="divisible"):
            _engine(lm, block_size=5)


class TestPagedDecodeIdentity:
    def test_greedy_identity_fp(self, static_eng, paged_eng):
        assert _collect(paged_eng, PROMPTS) == _collect(static_eng,
                                                       PROMPTS)

    def test_sampled_identity(self, static_eng, paged_eng):
        kw = dict(greedy=False, temperature=0.9, top_k=5, seed=13)
        assert (_collect(paged_eng, PROMPTS, **kw)
                == _collect(static_eng, PROMPTS, **kw))

    def test_greedy_identity_int8(self, lm):
        exp_eng = _engine(lm, slots=4, cache_dtype="int8")
        got_eng = _engine(lm, slots=4, cache_dtype="int8", block_size=4)
        try:
            assert _collect(got_eng, PROMPTS) == _collect(exp_eng,
                                                          PROMPTS)
        finally:
            exp_eng.shutdown()
            got_eng.shutdown()

    def test_speculative_identity(self, lm, draft, static_eng):
        """Greedy speculative streams are token-identical to plain
        greedy (tier-1 in test_speculative), so the static greedy
        baseline doubles as the speculative-over-paged-blocks oracle —
        one draft engine instead of two."""
        got_eng = _engine(lm, slots=4, draft_model=draft, speculative_k=3,
                          block_size=4)
        try:
            assert _collect(got_eng, PROMPTS) == _collect(static_eng,
                                                          PROMPTS)
        finally:
            got_eng.shutdown()

    def test_tight_pool_identity(self, lm, static_eng):
        """A pool far below static capacity still decodes correctly when
        rows fit (blocks recycle across sequential requests)."""
        exp = _collect(static_eng, PROMPTS)
        eng = _engine(lm, slots=4, block_size=4, num_kv_blocks=9)
        try:
            assert _collect(eng, PROMPTS) == exp
        finally:
            eng.shutdown()


class TestLiveKvBytes:
    def test_gauge_tracks_resident_blocks(self, paged_eng):
        eng, reg = paged_eng, paged_eng._test_registry
        st = eng.stats()
        assert st["kv_cache_bytes"] == 0
        assert st["kv_block_size"] == 4
        assert st["kv_blocks_total"] == 4 * (MAX_LEN // 4)
        assert st["kv_blocks_free"] == st["kv_blocks_total"]
        per_block = block_bytes(eng.session, 4)

        seen = []
        eng._step_hook = lambda: seen.append(
            (eng.stats()["kv_blocks_free"],
             eng.stats()["kv_cache_bytes"]))
        try:
            h = eng.submit([1, 2, 3, 4, 5], max_tokens=4)
            h.result(timeout=120)
        finally:
            eng._step_hook = None
        assert seen, "decode steps must have run"
        free_mid, bytes_mid = seen[0]
        used_mid = eng.stats()["kv_blocks_total"] - free_mid
        assert used_mid >= blocks_needed(5, 4)
        assert bytes_mid == used_mid * per_block
        # gauge mirrors stats
        fam = reg.get("dl4j_tpu_generate_kv_cache_bytes")
        assert fam is not None
        # retire returns every block
        done = eng.stats()
        assert done["kv_blocks_free"] == done["kv_blocks_total"]
        assert done["kv_cache_bytes"] == 0

    def test_static_engine_reports_fixed_bytes(self, static_eng):
        st = static_eng.stats()
        assert st["kv_blocks_total"] is None
        assert st["kv_block_size"] is None
        assert st["kv_cache_bytes"] > 0  # preallocated carry


class TestOutOfBlocks:
    def test_admit_requeues_until_blocks_free(self, lm):
        """With the pool sized for one long row (5 usable blocks, each
        row peaking at 5), a second concurrent request waits for blocks
        instead of failing, then completes when the first retires."""
        eng = _engine(lm, slots=4, block_size=4, num_kv_blocks=6)
        try:
            h1 = eng.submit([1, 2, 3, 4, 5, 6, 7, 8, 9, 10], max_tokens=8)
            h2 = eng.submit([4, 5, 6, 7, 8, 9, 10, 11, 12, 13],
                            max_tokens=8)
            r1 = h1.result(timeout=120)
            r2 = h2.result(timeout=120)
            assert len(r1) == 8 and len(r2) == 8
            assert h1.reason == "completed" and h2.reason == "completed"

            # a prompt needing more blocks than the whole pool holds
            # fails with a clear error once the batch is idle — never
            # hangs (5 usable blocks * 4 = 20 positions < 21 needed)
            h = eng.submit(list(range(1, 22)), max_tokens=2)
            term = list(h.events(timeout=60))[-1]
            assert term["reason"] == "failed"
            assert "blocks" in term.get("error", "")
        finally:
            eng.shutdown()
