"""RL tier tests: CartPole dynamics, replay, policies, and DQN learning
(SURVEY.md §2.2 "RL4J")."""

import numpy as np
import pytest

from deeplearning4j_tpu.rl import (
    CartPole,
    EpsGreedyPolicy,
    ExpReplay,
    QLearningConfiguration,
    QLearningDiscreteDense,
    Transition,
)


def test_cartpole_contract():
    env = CartPole(max_steps=50, seed=1)
    obs = env.reset()
    assert obs.shape == (4,) and not env.is_done()
    steps = 0
    while not env.is_done():
        reply = env.step(steps % 2)
        steps += 1
        assert reply.reward == 1.0
    assert 1 <= steps <= 50
    # reset restarts
    env.reset()
    assert not env.is_done()


def test_exp_replay_ring():
    rep = ExpReplay(max_size=5, batch_size=3, seed=0)
    for i in range(8):
        rep.store(Transition(np.full(2, i, np.float32), i % 2, float(i),
                             np.zeros(2, np.float32), False))
    assert len(rep) == 5
    obs, actions, rewards, next_obs, dones = rep.sample()
    assert obs.shape == (3, 2) and rewards.min() >= 3  # 0..2 overwritten


def test_eps_greedy_anneals():
    calls = []
    pol = EpsGreedyPolicy(lambda x: np.array([[0.0, 1.0]]), 2,
                          eps_start=1.0, eps_min=0.1, decay_steps=10, seed=0)
    assert pol.epsilon == 1.0
    for _ in range(10):
        calls.append(pol.next_action(np.zeros(4, np.float32)))
    assert abs(pol.epsilon - 0.1) < 1e-9
    # greedy action is 1 once epsilon decayed
    assert pol.next_action(np.zeros(4, np.float32)) in (0, 1)


def test_dqn_learns_cartpole():
    conf = QLearningConfiguration(
        seed=7, max_step=3000, max_epoch_step=200, exp_replay_size=5000,
        batch_size=64, target_dqn_update_freq=200, update_start=200,
        epsilon_nb_step=1500, hidden=(32, 32), learning_rate=2e-3)
    dqn = QLearningDiscreteDense(CartPole(max_steps=200, seed=3), conf)
    rewards = dqn.train()
    assert len(rewards) >= 5
    first = np.mean(rewards[:5])
    last = np.mean(rewards[-5:])
    assert last > first * 1.5, (first, last)
    # trained greedy policy holds the pole notably longer than random
    policy = dqn.get_policy()
    env = CartPole(max_steps=200, seed=11)
    obs = env.reset()
    steps = 0
    while not env.is_done():
        obs = env.step(policy.next_action(obs)).observation
        steps += 1
    assert steps > 50, steps
