"""KV-cached autoregressive generation (ISSUE 9).

The load-bearing contract is prefill/decode EQUIVALENCE: incremental
KV-cached decode must be token-for-token identical (greedy) to a full
re-forward at every position, for the attention and LSTM paths, across
prompt-bucket boundaries — plus seeded-sampling semantics, the flash
decode kernel vs the reference impl, and the continuous-batching
DecodeEngine (admission, deadlines, slot reuse, metrics, spans).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.generate import (
    GenerationSession,
    bucket_length,
    sample_tokens,
)
from deeplearning4j_tpu.generate import sampling as S
from deeplearning4j_tpu.model.zoo import TextGenerationLSTM, TransformerLM
from deeplearning4j_tpu.obs.metrics import MetricsRegistry
from deeplearning4j_tpu.ops import (
    decode_attention_reference,
    flash_decode_attention,
)
from deeplearning4j_tpu.parallel import DecodeEngine


def _one_hot(toks, vocab):
    oh = np.zeros((1, vocab, len(toks)), np.float32)
    for i, t in enumerate(toks):
        oh[0, t, i] = 1.0
    return oh


def _full_greedy(model, prompt, n, vocab, max_len, one_hot=False):
    """The re-forward oracle: rebuild the whole sequence every step and
    argmax the last position's distribution."""
    toks = list(prompt)
    out_toks = []
    for _ in range(n):
        if len(toks) >= max_len:
            break
        x = (_one_hot(toks, vocab) if one_hot
             else jnp.asarray([toks], jnp.int32))
        out = model.output(x)
        nxt = int(jnp.argmax(out[0, :, -1]))
        out_toks.append(nxt)
        toks.append(nxt)
    return out_toks


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------


class TestSampling:
    def test_greedy_is_argmax(self):
        logits = jnp.asarray([[0.1, 3.0, -1.0], [5.0, 0.0, 4.9]])
        assert S.greedy(logits).tolist() == [1, 0]

    def test_temperature_seeded_deterministic(self):
        key = jax.random.PRNGKey(7)
        logits = jnp.asarray(np.random.RandomState(0).randn(4, 16), jnp.float32)
        a = S.temperature(logits, key, 0.8)
        b = S.temperature(logits, key, 0.8)
        assert a.tolist() == b.tolist()
        c = S.temperature(logits, jax.random.PRNGKey(8), 0.8)
        assert a.tolist() != c.tolist() or True  # different key may differ

    def test_top_k_restricts_support(self):
        logits = jnp.asarray(np.random.RandomState(1).randn(64), jnp.float32)
        top3 = set(np.argsort(np.asarray(logits))[-3:].tolist())
        draws = {int(S.top_k(logits, jax.random.PRNGKey(i), 3))
                 for i in range(50)}
        assert draws <= top3

    def test_top_p_restricts_support(self):
        # one dominant token: p=0.5 must always return it
        logits = jnp.asarray([10.0, 0.0, 0.0, 0.0], jnp.float32)
        draws = {int(S.top_p(logits, jax.random.PRNGKey(i), 0.5))
                 for i in range(20)}
        assert draws == {0}

    def test_temperature_equivalence_on_log_probs(self):
        # sampling from log(softmax(z))/T must equal sampling from z/T —
        # the invariance the decode path relies on for softmax outputs
        key = jax.random.PRNGKey(3)
        z = jnp.asarray(np.random.RandomState(2).randn(8, 32), jnp.float32)
        lp = jnp.log(jax.nn.softmax(z, axis=-1))
        assert (S.temperature(z, key, 0.7).tolist()
                == S.temperature(lp, key, 0.7).tolist())

    def test_batched_sampler_per_row_specs(self):
        rng = np.random.RandomState(3)
        logits = jnp.asarray(rng.randn(3, 32), jnp.float32)
        seeds = jnp.asarray([1, 2, 3], jnp.uint32)
        steps = jnp.zeros((3,), jnp.int32)
        toks = sample_tokens(
            logits, seeds, steps,
            jnp.asarray([True, False, False]),
            jnp.asarray([1.0, 0.9, 0.9], jnp.float32),
            jnp.asarray([0, 5, 0], jnp.int32),
            jnp.asarray([1.0, 1.0, 0.9], jnp.float32))
        # row 0 greedy == argmax
        assert int(toks[0]) == int(jnp.argmax(logits[0]))
        # row 1 top-k: inside the top-5 set
        top5 = set(np.argsort(np.asarray(logits[1]))[-5:].tolist())
        assert int(toks[1]) in top5

    def test_batched_sampler_seed_independent_of_batch(self):
        # the (seed, step) keying makes a row's draw independent of which
        # other rows share the batch — continuous batching determinism
        rng = np.random.RandomState(4)
        row = jnp.asarray(rng.randn(1, 32), jnp.float32)
        other = jnp.asarray(rng.randn(1, 32), jnp.float32)
        args = (jnp.asarray([9], jnp.uint32), jnp.asarray([2], jnp.int32),
                jnp.asarray([False]), jnp.asarray([0.8], jnp.float32),
                jnp.asarray([0], jnp.int32), jnp.asarray([1.0], jnp.float32))
        solo = sample_tokens(row, *args)
        both = sample_tokens(
            jnp.concatenate([row, other]),
            jnp.asarray([9, 1], jnp.uint32), jnp.asarray([2, 0], jnp.int32),
            jnp.asarray([False, False]), jnp.asarray([0.8, 1.0], jnp.float32),
            jnp.asarray([0, 0], jnp.int32), jnp.asarray([1.0, 1.0], jnp.float32))
        assert int(solo[0]) == int(both[0])


# ---------------------------------------------------------------------------
# decode attention kernel
# ---------------------------------------------------------------------------


class TestDecodeAttention:
    def test_flash_matches_reference(self):
        rng = np.random.RandomState(0)
        b, h, L, d = 3, 4, 40, 16
        q = jnp.asarray(rng.randn(b, h, 1, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, h, L, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, h, L, d), jnp.float32)
        for pos in ([0, 5, 39], [1, 1, 1], [38, 0, 20]):
            sp = jnp.asarray(pos, jnp.int32)
            ref = decode_attention_reference(q, k, v, sp)
            fl = flash_decode_attention(q, k, v, sp, block_k=8)
            np.testing.assert_allclose(np.asarray(fl), np.asarray(ref),
                                       atol=1e-5, rtol=1e-5)

    def test_reference_masks_future(self):
        # entries past the frontier must not influence the output
        rng = np.random.RandomState(1)
        b, h, L, d = 1, 2, 16, 8
        q = jnp.asarray(rng.randn(b, h, 1, d), jnp.float32)
        k = np.asarray(rng.randn(b, h, L, d), np.float32)
        v = np.asarray(rng.randn(b, h, L, d), np.float32)
        pos = jnp.asarray([4], jnp.int32)
        base = decode_attention_reference(q, jnp.asarray(k), jnp.asarray(v), pos)
        k2, v2 = k.copy(), v.copy()
        k2[:, :, 5:] = 99.0
        v2[:, :, 5:] = -99.0
        pert = decode_attention_reference(q, jnp.asarray(k2), jnp.asarray(v2), pos)
        np.testing.assert_allclose(np.asarray(pert), np.asarray(base),
                                   atol=1e-6)

    def test_chunk_queries_causal(self):
        # tq > 1: query i attends [0, start+i] — matches per-step calls
        rng = np.random.RandomState(2)
        b, h, L, d, tq = 2, 2, 12, 8, 3
        q = jnp.asarray(rng.randn(b, h, tq, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, h, L, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, h, L, d), jnp.float32)
        start = jnp.asarray([0, 4], jnp.int32)
        chunk = decode_attention_reference(q, k, v, start)
        for i in range(tq):
            single = decode_attention_reference(q[:, :, i:i + 1], k, v,
                                                start + i)
            np.testing.assert_allclose(np.asarray(chunk[:, :, i:i + 1]),
                                       np.asarray(single), atol=1e-5)


# ---------------------------------------------------------------------------
# prefill/decode equivalence (the acceptance contract)
# ---------------------------------------------------------------------------


class TestEquivalence:
    MAX_LEN = 16

    @pytest.fixture(scope="class")
    def lm(self):
        return TransformerLM(vocab_size=29, hidden=32, n_layers=2,
                             n_heads=4, max_len=self.MAX_LEN).init()

    def test_attention_path_across_buckets(self, lm):
        """Greedy incremental decode == full re-forward at every position,
        for prompt lengths straddling bucket boundaries (3 -> bucket 4,
        5 -> bucket 8, 8 -> bucket 8) and generations crossing them."""
        sess = GenerationSession(lm, max_len=self.MAX_LEN)
        prompts = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 3, 1, 4, 1, 5, 9, 2]]
        n = self.MAX_LEN  # run to the cache limit -> crosses buckets
        inc = sess.generate(prompts, n, greedy=True)
        for p, got in zip(prompts, inc):
            ref = _full_greedy(lm, p, n, 29, self.MAX_LEN)
            assert got == ref, f"prompt {p}: {got} != {ref}"

    def test_lstm_path(self):
        tg = TextGenerationLSTM(vocab_size=13, hidden=16, layers=2)
        model = tg.init()
        prompts = [[1, 2, 3], [4, 5, 6, 7, 8]]
        inc = TextGenerationLSTM.generate(model, prompts, 8, max_len=32,
                                          greedy=True)
        for p, got in zip(prompts, inc):
            ref = _full_greedy(model, p, 8, 13, 32, one_hot=True)
            assert got == ref

    def test_recurrent_attention_path(self):
        from deeplearning4j_tpu.nn import (
            Activation, InputType, LossFunction, NeuralNetConfiguration,
            WeightInit)
        from deeplearning4j_tpu.nn.layers import (
            RecurrentAttentionLayer, RnnOutputLayer)
        from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork

        conf = (NeuralNetConfiguration.builder().seed(5)
                .weight_init(WeightInit.XAVIER).list()
                .layer(RecurrentAttentionLayer(n_in=11, n_out=16, causal=True))
                .layer(RnnOutputLayer(n_out=11, loss=LossFunction.MCXENT,
                                      activation=Activation.SOFTMAX))
                .set_input_type(InputType.recurrent(11)).build())
        model = MultiLayerNetwork(conf).init()
        sess = GenerationSession(model, max_len=16)
        prompts = [[1, 2, 3], [4, 5]]
        inc = sess.generate(prompts, 6, greedy=True)
        for p, got in zip(prompts, inc):
            ref = _full_greedy(model, p, 6, 11, 16, one_hot=True)
            assert got == ref

    def test_seeded_sampling_reproducible(self, lm):
        sess = GenerationSession(lm, max_len=self.MAX_LEN)
        a = sess.generate([[1, 2, 3]], 6, greedy=False, temperature=0.9,
                          top_k=8, seed=42)
        b = sess.generate([[1, 2, 3]], 6, greedy=False, temperature=0.9,
                          top_k=8, seed=42)
        assert a == b

    def test_bidirectional_model_rejected(self):
        from deeplearning4j_tpu.nn import (
            Activation, InputType, LossFunction, NeuralNetConfiguration,
            WeightInit)
        from deeplearning4j_tpu.nn.layers import (
            RnnOutputLayer, SelfAttentionLayer)
        from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork

        conf = (NeuralNetConfiguration.builder().seed(5)
                .weight_init(WeightInit.XAVIER).list()
                .layer(SelfAttentionLayer(n_in=8, n_out=8, n_heads=2))
                .layer(RnnOutputLayer(n_out=8, loss=LossFunction.MCXENT,
                                      activation=Activation.SOFTMAX))
                .set_input_type(InputType.recurrent(8)).build())
        model = MultiLayerNetwork(conf).init()
        with pytest.raises(ValueError, match="decode"):
            GenerationSession(model, max_len=8)

    def test_causal_self_attention_matches_masked_reference(self):
        """causal=True on SelfAttentionLayer == explicit future-masked
        softmax attention (training-path spot check)."""
        from deeplearning4j_tpu.nn.layers import SelfAttentionLayer
        from deeplearning4j_tpu.nn.layers.base import LayerContext

        rng = np.random.RandomState(0)
        lay = SelfAttentionLayer(n_in=8, n_out=8, n_heads=2, causal=True)
        params = lay.init(jax.random.PRNGKey(0), jnp.float32)
        x = jnp.asarray(rng.randn(2, 8, 5), jnp.float32)
        y, _ = lay.apply(params, {}, x, LayerContext())
        # manual: per-position prefix attention
        for t in range(5):
            lay_nc = SelfAttentionLayer(n_in=8, n_out=8, n_heads=2)
            y_pref, _ = lay_nc.apply(params, {}, x[:, :, : t + 1],
                                     LayerContext())
            np.testing.assert_allclose(np.asarray(y[:, :, t]),
                                       np.asarray(y_pref[:, :, t]),
                                       atol=1e-5)

    def test_bucket_length(self):
        assert [bucket_length(n, 16) for n in (1, 2, 3, 5, 8, 9, 16, 99)] \
            == [1, 2, 4, 8, 8, 16, 16, 16]


# ---------------------------------------------------------------------------
# DecodeEngine
# ---------------------------------------------------------------------------


class TestDecodeEngine:
    MAX_LEN = 24

    @pytest.fixture()
    def lm(self):
        return TransformerLM(vocab_size=23, hidden=32, n_layers=2,
                             n_heads=4, max_len=self.MAX_LEN).init()

    def _engine(self, lm, **kw):
        reg = kw.pop("registry", MetricsRegistry())
        return DecodeEngine(lm, max_len=self.MAX_LEN, registry=reg, **kw), reg

    def test_matches_session_and_batches_mixed_positions(self, lm):
        """Requests submitted together at different prompt lengths decode
        in one cache and still match the single-sequence session."""
        eng, reg = self._engine(lm, slots=4, name="eng-eq")
        try:
            handles = [eng.submit([1, 2, 3], max_tokens=6),
                       eng.submit([4, 5, 6, 7, 8], max_tokens=6),
                       eng.submit([2, 2], max_tokens=6)]
            got = [h.result(timeout=120) for h in handles]
        finally:
            eng.shutdown()
        sess = GenerationSession(lm, max_len=self.MAX_LEN)
        exp = sess.generate([[1, 2, 3], [4, 5, 6, 7, 8], [2, 2]], 6,
                            greedy=True)
        assert got == exp

    def test_staggered_arrival_continuous_batching(self, lm):
        """A request arriving while another is mid-decode joins the same
        cache (different position) without corrupting either stream."""
        eng, reg = self._engine(lm, slots=4, name="eng-stagger")
        try:
            h1 = eng.submit([1, 2, 3], max_tokens=10)
            # wait for a few tokens before the second arrives
            ev = iter(h1.events(timeout=60))
            for _ in range(3):
                next(ev)
            h2 = eng.submit([4, 5, 6, 7, 8], max_tokens=6)
            got1 = h1.result(timeout=120)
            got2 = h2.result(timeout=120)
        finally:
            eng.shutdown()
        sess = GenerationSession(lm, max_len=self.MAX_LEN)
        assert got1 == sess.generate([[1, 2, 3]], 10, greedy=True)[0]
        assert got2 == sess.generate([[4, 5, 6, 7, 8]], 6, greedy=True)[0]

    def test_admission_shed_and_metrics(self, lm):
        import threading

        from deeplearning4j_tpu.core.resilience import AdmissionRejectedError

        gate = threading.Event()
        eng, reg = self._engine(lm, slots=1, queue_limit=2, name="eng-shed",
                                step_hook=lambda: gate.wait(0.02))
        try:
            h1 = eng.submit([1, 2, 3], max_tokens=self.MAX_LEN)
            h2 = eng.submit([1, 2], max_tokens=4)  # queued behind the slot
            with pytest.raises(AdmissionRejectedError) as ei:
                eng.submit([1], max_tokens=2)
            assert ei.value.retry_after is not None
            gate.set()
            h1.result(timeout=120)
            h2.result(timeout=120)
            s = eng.stats()
            assert s["shed"] == 1 and s["completed"] == 2
            assert s["in_flight"] == 0
            assert int(eng._c_tokens.value) > 0
        finally:
            eng.shutdown()

    def test_deadline_mid_stream_partial_output(self, lm):
        import time as _t

        eng, reg = self._engine(lm, slots=2, name="eng-dl",
                                step_hook=lambda: _t.sleep(0.05))
        try:
            h = eng.submit([1, 2, 3], max_tokens=self.MAX_LEN, timeout=0.4)
            evs = list(h.events(timeout=60))
        finally:
            eng.shutdown()
        assert evs[-1]["done"] and evs[-1]["reason"] == "deadline"
        assert 1 <= evs[-1]["count"] < self.MAX_LEN - 3
        # ordered partial output
        assert [e["index"] for e in evs[:-1]] == list(range(evs[-1]["count"]))

    def test_cancel_frees_slot(self, lm):
        import time as _t

        eng, reg = self._engine(lm, slots=1, name="eng-cancel",
                                step_hook=lambda: _t.sleep(0.01))
        try:
            h = eng.submit([1, 2, 3], max_tokens=self.MAX_LEN)
            next(iter(h.events(timeout=60)))  # it is decoding
            h.cancel()
            for _ in range(200):
                if eng.stats()["active_slots"] == 0:
                    break
                _t.sleep(0.02)
            s = eng.stats()
            assert s["active_slots"] == 0 and s["in_flight"] == 0
            assert s["cancelled"] == 1
            # the freed slot serves a new request
            assert eng.submit([4, 5], max_tokens=3).result(timeout=120)
        finally:
            eng.shutdown()

    def test_gauge_and_histogram_series(self, lm):
        eng, reg = self._engine(lm, slots=2, name="eng-obs")
        try:
            eng.submit([1, 2, 3], max_tokens=4).result(timeout=120)
        finally:
            eng.shutdown()
        # read back through the engine's held children (the registry is
        # the single source of truth; exposition is covered by the
        # generate contract tool)
        assert int(eng._c_tokens.value) == 4
        assert eng._g_inflight.value == 0
        assert eng._h_prefill.count >= 1
        assert eng._h_decode.count >= 1

    def test_prompt_too_long_rejected(self, lm):
        eng, _ = self._engine(lm, slots=1, name="eng-long")
        try:
            with pytest.raises(ValueError, match="max_len"):
                eng.submit(list(range(1, self.MAX_LEN + 2)), max_tokens=2)
        finally:
            eng.shutdown()
