"""MultiLayerNetwork end-to-end tests: build, fit, output, serde of config."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.core import from_json, to_json
from deeplearning4j_tpu.nn import (
    Activation,
    InputType,
    LossFunction,
    MultiLayerNetwork,
    NeuralNetConfiguration,
    WeightInit,
)
from deeplearning4j_tpu.nn.layers import (
    BatchNormalizationLayer,
    ConvolutionLayer,
    DenseLayer,
    GravesLSTMLayer,
    LSTMLayer,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.train import Adam, Sgd


def small_mlp_conf(seed=12345):
    return (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Adam(1e-2))
        .weight_init(WeightInit.XAVIER)
        .list()
        .layer(DenseLayer(n_out=16, activation=Activation.RELU))
        .layer(DenseLayer(n_out=8, activation=Activation.TANH))
        .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT))
        .set_input_type(InputType.feed_forward(10))
        .build()
    )


def make_xor_like(n=64, d=10, k=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    cls = (np.abs(x[:, 0] * 3).astype(np.int64) + (x[:, 1] > 0)) % k
    y = np.eye(k, dtype=np.float32)[cls]
    return x, y


class TestBuild:
    def test_n_in_inference(self):
        conf = small_mlp_conf()
        assert conf.layers[0].n_in == 10
        assert conf.layers[1].n_in == 16
        assert conf.layers[2].n_in == 8

    def test_global_defaults_applied(self):
        conf = small_mlp_conf()
        assert conf.layers[0].weight_init is WeightInit.XAVIER
        assert conf.layers[0].updater == Adam(1e-2)

    def test_config_json_round_trip(self):
        conf = small_mlp_conf()
        back = from_json(to_json(conf))
        assert back == conf

    def test_cnn_preprocessor_insertion(self):
        conf = (
            NeuralNetConfiguration.builder()
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3)))
            .layer(SubsamplingLayer())
            .layer(DenseLayer(n_out=10))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.convolutional_flat(12, 12, 1))
            .build()
        )
        names = [type(l).__name__ for l in conf.layers]
        assert names[0] == "FeedForwardToCnnPreProcessor"
        assert "CnnToFeedForwardPreProcessor" in names
        # conv 12x12 -(3x3)-> 10x10 -(2x2 pool)-> 5x5 * 4ch = 100
        assert conf.layers[names.index("DenseLayer")].n_in == 100

    def test_init_params_shapes(self):
        model = MultiLayerNetwork(small_mlp_conf()).init()
        assert model.params["layer_0"]["W"].shape == (10, 16)
        assert model.params["layer_2"]["b"].shape == (3,)
        assert model.num_params() == 10 * 16 + 16 + 16 * 8 + 8 + 8 * 3 + 3

    def test_init_deterministic(self):
        m1 = MultiLayerNetwork(small_mlp_conf()).init()
        m2 = MultiLayerNetwork(small_mlp_conf()).init()
        np.testing.assert_array_equal(
            np.asarray(m1.params["layer_0"]["W"]), np.asarray(m2.params["layer_0"]["W"])
        )

    def test_summary(self):
        model = MultiLayerNetwork(small_mlp_conf()).init()
        s = model.summary()
        assert "DenseLayer" in s and "Total params" in s


class TestFit:
    def test_mlp_learns(self):
        x, y = make_xor_like()
        model = MultiLayerNetwork(small_mlp_conf()).init()
        s0 = model.score(x, y)
        model.fit(x, y, epochs=60)
        s1 = model.score(x, y)
        assert s1 < s0 * 0.7, f"loss did not decrease: {s0} -> {s1}"

    def test_output_shape_and_softmax(self):
        x, y = make_xor_like()
        model = MultiLayerNetwork(small_mlp_conf()).init()
        out = np.asarray(model.output(x))
        assert out.shape == (64, 3)
        np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)

    def test_evaluate(self):
        x, y = make_xor_like()
        model = MultiLayerNetwork(small_mlp_conf()).init()
        model.fit(x, y, epochs=30)
        ev = model.evaluate(x, y)
        assert ev.accuracy() > 0.5

    def test_listeners_called(self):
        from deeplearning4j_tpu.core import CollectScoresListener

        x, y = make_xor_like()
        model = MultiLayerNetwork(small_mlp_conf()).init()
        listener = CollectScoresListener()
        model.add_listeners(listener)
        model.fit(x, y, epochs=3)
        assert len(listener.scores) == 3
        assert all(np.isfinite(s) for s in listener.scores)


class TestCnn:
    def test_lenet_style_fit(self):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(7)
            .updater(Adam(1e-2))
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3), activation=Activation.RELU))
            .layer(SubsamplingLayer())
            .layer(BatchNormalizationLayer())
            .layer(DenseLayer(n_out=16, activation=Activation.RELU))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.convolutional_flat(10, 10, 1))
            .build()
        )
        model = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 100)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x[:, :50].sum(axis=1) > 0).astype(np.int64)]
        s0 = model.score(x, y)
        model.fit(x, y, epochs=30)
        assert model.score(x, y) < s0

    def test_bn_running_stats_update(self):
        conf = (
            NeuralNetConfiguration.builder()
            .updater(Sgd(0.1))
            .list()
            .layer(BatchNormalizationLayer())
            .layer(OutputLayer(n_out=2, loss=LossFunction.MSE, activation=Activation.IDENTITY))
            .set_input_type(InputType.feed_forward(4))
            .build()
        )
        model = MultiLayerNetwork(conf).init()
        before = np.asarray(model.state["layer_0"]["mean"]).copy()
        x = np.random.default_rng(1).normal(5.0, size=(16, 4)).astype(np.float32)
        y = np.zeros((16, 2), dtype=np.float32)
        model.fit(x, y, epochs=2)
        after = np.asarray(model.state["layer_0"]["mean"])
        assert not np.allclose(before, after)
        assert after.mean() > 0.5  # moved toward the batch mean of ~5


class TestRnn:
    def test_lstm_shapes_and_fit(self):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(3)
            .updater(Adam(1e-2))
            .list()
            .layer(LSTMLayer(n_out=8, activation=Activation.TANH))
            .layer(RnnOutputLayer(n_out=2, loss=LossFunction.MCXENT))
            .set_input_type(InputType.recurrent(5))
            .build()
        )
        model = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 5, 12)).astype(np.float32)
        labels_cls = (x.sum(axis=1) > 0).astype(np.int64)  # [8, 12]
        y = np.eye(2, dtype=np.float32)[labels_cls].transpose(0, 2, 1)  # [8, 2, 12]
        out = np.asarray(model.output(x))
        assert out.shape == (8, 2, 12)
        s0 = model.score(x, y)
        model.fit(x, y, epochs=25)
        assert model.score(x, y) < s0

    def test_graves_lstm_has_peepholes(self):
        conf = (
            NeuralNetConfiguration.builder()
            .list()
            .layer(GravesLSTMLayer(n_out=4))
            .layer(RnnOutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(3))
            .build()
        )
        model = MultiLayerNetwork(conf).init()
        assert "P" in model.params["layer_0"]
        assert model.params["layer_0"]["P"].shape == (3, 4)

    def test_rnn_time_step_stateful(self):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(11)
            .list()
            .layer(LSTMLayer(n_out=6))
            .layer(RnnOutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(4))
            .build()
        )
        model = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(2).normal(size=(2, 4, 10)).astype(np.float32)
        full = np.asarray(model.output(x))
        # streaming: two chunks of 5 steps must reproduce the full output
        model.rnn_clear_previous_state()
        o1 = np.asarray(model.rnn_time_step(x[:, :, :5]))
        o2 = np.asarray(model.rnn_time_step(x[:, :, 5:]))
        streamed = np.concatenate([o1, o2], axis=2)
        np.testing.assert_allclose(full, streamed, rtol=1e-4, atol=1e-5)

    def test_masking_changes_loss(self):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(5)
            .list()
            .layer(LSTMLayer(n_out=4))
            .layer(RnnOutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(3))
            .build()
        )
        model = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(3).normal(size=(4, 3, 6)).astype(np.float32)
        y = np.zeros((4, 2, 6), dtype=np.float32)
        y[:, 0, :] = 1.0
        mask = np.ones((4, 6), dtype=np.float32)
        mask[:, 3:] = 0.0
        s_full = model.score(x, y)
        s_masked = model.score(x, y, mask=mask, label_mask=mask)
        assert not np.isclose(s_full, s_masked)
