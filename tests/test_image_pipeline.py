"""Image input pipeline: decode (netpbm native + PNG/JPEG via Pillow),
augmentation transforms, and the input-vs-compute throughput statement
(VERDICT.md round 3 ask 8; SURVEY.md:124 'the ImageNet input path')."""

import os
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data.image_transform import (
    BrightnessTransform,
    CropImageTransform,
    FlipImageTransform,
    PipelineImageTransform,
    RandomCropTransform,
    ResizeImageTransform,
    RotateImageTransform,
)
from deeplearning4j_tpu.data.records import (
    ImageRecordReader,
    RecordReaderDataSetIterator,
)


def _img(h=8, w=10, c=3, seed=0):
    return np.random.RandomState(seed).rand(h, w, c).astype(np.float32) * 255


# ---------------------------------------------------------------------------
# transforms
# ---------------------------------------------------------------------------

def test_flip_modes():
    x = _img()
    assert np.array_equal(FlipImageTransform(mode=1)(x), x[:, ::-1])
    assert np.array_equal(FlipImageTransform(mode=0)(x), x[::-1])
    assert np.array_equal(FlipImageTransform(mode=-1)(x), x[::-1, ::-1])


def test_crop_and_random_crop():
    x = _img(12, 12)
    out = CropImageTransform(top=2, left=1, bottom=3, right=2)(x)
    assert out.shape == (7, 9, 3)
    np.testing.assert_array_equal(out, x[2:9, 1:10])

    rc = RandomCropTransform(height=5, width=6)
    rng = np.random.RandomState(0)
    for _ in range(5):
        out = rc.call(x, rng)
        assert out.shape == (5, 6, 3)
    with pytest.raises(ValueError):
        RandomCropTransform(height=20, width=5)(x)


def test_rotate_right_angle_exact_and_arbitrary():
    x = _img(6, 6)
    assert np.array_equal(RotateImageTransform(angle=90)(x), np.rot90(x))
    assert np.array_equal(RotateImageTransform(angle=180)(x), np.rot90(x, 2))
    out = RotateImageTransform(angle=30)(x)  # PIL bilinear path
    assert out.shape == x.shape
    assert np.isfinite(out).all()


def test_pipeline_probability_and_order():
    x = _img()
    always = PipelineImageTransform(
        FlipImageTransform(mode=1), FlipImageTransform(mode=1))
    np.testing.assert_array_equal(always(x), x)  # double flip = identity
    never = PipelineImageTransform((BrightnessTransform(delta=100.0), 0.0))
    np.testing.assert_array_equal(never(x), x)


def test_device_batch_augmentation():
    import jax

    from deeplearning4j_tpu.data.image_transform import (
        batch_random_crop, batch_random_flip,
    )

    x = np.random.RandomState(0).rand(4, 3, 12, 12).astype(np.float32)
    key = jax.random.PRNGKey(0)
    flipped = np.asarray(jax.jit(batch_random_flip)(x, key))
    for i in range(4):
        ok_same = np.array_equal(flipped[i], x[i])
        ok_flip = np.array_equal(flipped[i], x[i][..., ::-1])
        assert ok_same or ok_flip
    cropped = jax.jit(
        lambda a, k: batch_random_crop(a, k, 8, 8))(x, key)
    assert cropped.shape == (4, 3, 8, 8)


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------

def _write_ppm(path, arr):
    h, w, _ = arr.shape
    with open(path, "wb") as f:
        f.write(f"P6 {w} {h} 255\n".encode())
        f.write(arr.astype(np.uint8).tobytes())


def test_png_and_jpeg_decode(tmp_path):
    PIL = pytest.importorskip("PIL.Image")
    rng = np.random.RandomState(0)
    arr = rng.randint(0, 256, (10, 12, 3), np.uint8)
    for cls in ("a", "b"):
        os.makedirs(tmp_path / cls, exist_ok=True)
    PIL.fromarray(arr).save(str(tmp_path / "a" / "x.png"))
    PIL.fromarray(arr).save(str(tmp_path / "b" / "y.jpg"), quality=95)
    _write_ppm(str(tmp_path / "a" / "z.ppm"), arr)

    reader = ImageRecordReader(10, 12, 3, root=str(tmp_path))
    recs = list(reader)
    assert len(recs) == 3
    assert reader.labels() == ["a", "b"]
    png_rec = recs[0][0]  # a/x.png sorts first
    # all decoders normalize to [0, 1] (the native netpbm convention)
    np.testing.assert_allclose(png_rec, arr.astype(np.float32) / 255.0,
                               atol=0.5 / 255.0)


def test_reader_applies_augmentation(tmp_path):
    rng = np.random.RandomState(0)
    arr = rng.randint(0, 256, (10, 10, 3), np.uint8)
    os.makedirs(tmp_path / "a", exist_ok=True)
    _write_ppm(str(tmp_path / "a" / "x.ppm"), arr)
    reader = ImageRecordReader(
        10, 10, 3, root=str(tmp_path),
        transform=FlipImageTransform(mode=1))
    rec = next(iter(reader))[0]
    np.testing.assert_allclose(rec, arr[:, ::-1].astype(np.float32) / 255.0,
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# throughput: input path vs compute step
# ---------------------------------------------------------------------------

def test_input_pipeline_throughput_vs_resnet_step(tmp_path, capsys):
    """The honest input-bound-vs-compute-bound statement: measure the
    augmented 224x224 input path (decode+flip+crop+batch) and compare to
    the last TPU-measured ResNet-50 step rate. Asserts a conservative
    host-throughput floor; prints the ratio for the record."""
    rng = np.random.RandomState(0)
    os.makedirs(tmp_path / "a", exist_ok=True)
    n = 48
    for i in range(n):
        _write_ppm(str(tmp_path / "a" / f"{i}.ppm"),
                   rng.randint(0, 256, (256, 256, 3), np.uint8))
    aug = PipelineImageTransform(
        (FlipImageTransform(mode=1), 0.5),
        RandomCropTransform(height=224, width=224))
    reader = ImageRecordReader(224, 224, 3, root=str(tmp_path), transform=aug)
    it = RecordReaderDataSetIterator(reader, batch_size=16, label_index=1,
                                     num_classes=1)
    start = time.perf_counter()
    seen = sum(ds.features.shape[0] for ds in it)
    rate = seen / (time.perf_counter() - start)
    assert seen == n
    assert rate > 30  # single slow core; TPU feeding needs parallel workers
    resnet_tpu_sps = 1794.89  # BENCH_latest.json, round 4
    with capsys.disabled():
        print(f"\n[input-pipeline] {rate:.0f} img/s host vs "
              f"{resnet_tpu_sps:.0f} samples/s ResNet-50/TPU -> "
              f"need ~{resnet_tpu_sps / rate:.1f} input workers")
