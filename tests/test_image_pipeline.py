"""Image input pipeline: decode (netpbm native + PNG/JPEG via Pillow),
augmentation transforms, and the input-vs-compute throughput statement
(VERDICT.md round 3 ask 8; SURVEY.md:124 'the ImageNet input path')."""

import os
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data.image_transform import (
    BrightnessTransform,
    CropImageTransform,
    FlipImageTransform,
    PipelineImageTransform,
    RandomCropTransform,
    ResizeImageTransform,
    RotateImageTransform,
)
from deeplearning4j_tpu.data.records import (
    ImageRecordReader,
    RecordReaderDataSetIterator,
)


def _img(h=8, w=10, c=3, seed=0):
    return np.random.RandomState(seed).rand(h, w, c).astype(np.float32) * 255


# ---------------------------------------------------------------------------
# transforms
# ---------------------------------------------------------------------------

def test_flip_modes():
    x = _img()
    assert np.array_equal(FlipImageTransform(mode=1)(x), x[:, ::-1])
    assert np.array_equal(FlipImageTransform(mode=0)(x), x[::-1])
    assert np.array_equal(FlipImageTransform(mode=-1)(x), x[::-1, ::-1])


def test_crop_and_random_crop():
    x = _img(12, 12)
    out = CropImageTransform(top=2, left=1, bottom=3, right=2)(x)
    assert out.shape == (7, 9, 3)
    np.testing.assert_array_equal(out, x[2:9, 1:10])

    rc = RandomCropTransform(height=5, width=6)
    rng = np.random.RandomState(0)
    for _ in range(5):
        out = rc.call(x, rng)
        assert out.shape == (5, 6, 3)
    with pytest.raises(ValueError):
        RandomCropTransform(height=20, width=5)(x)


def test_rotate_right_angle_exact_and_arbitrary():
    x = _img(6, 6)
    assert np.array_equal(RotateImageTransform(angle=90)(x), np.rot90(x))
    assert np.array_equal(RotateImageTransform(angle=180)(x), np.rot90(x, 2))
    out = RotateImageTransform(angle=30)(x)  # PIL bilinear path
    assert out.shape == x.shape
    assert np.isfinite(out).all()


def test_pipeline_probability_and_order():
    x = _img()
    always = PipelineImageTransform(
        FlipImageTransform(mode=1), FlipImageTransform(mode=1))
    np.testing.assert_array_equal(always(x), x)  # double flip = identity
    never = PipelineImageTransform((BrightnessTransform(delta=100.0), 0.0))
    np.testing.assert_array_equal(never(x), x)


def test_device_batch_augmentation():
    import jax

    from deeplearning4j_tpu.data.image_transform import (
        batch_random_crop, batch_random_flip,
    )

    x = np.random.RandomState(0).rand(4, 3, 12, 12).astype(np.float32)
    key = jax.random.PRNGKey(0)
    flipped = np.asarray(jax.jit(batch_random_flip)(x, key))
    for i in range(4):
        ok_same = np.array_equal(flipped[i], x[i])
        ok_flip = np.array_equal(flipped[i], x[i][..., ::-1])
        assert ok_same or ok_flip
    cropped = jax.jit(
        lambda a, k: batch_random_crop(a, k, 8, 8))(x, key)
    assert cropped.shape == (4, 3, 8, 8)


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------

def _write_ppm(path, arr):
    h, w, _ = arr.shape
    with open(path, "wb") as f:
        f.write(f"P6 {w} {h} 255\n".encode())
        f.write(arr.astype(np.uint8).tobytes())


def test_png_and_jpeg_decode(tmp_path):
    PIL = pytest.importorskip("PIL.Image")
    rng = np.random.RandomState(0)
    arr = rng.randint(0, 256, (10, 12, 3), np.uint8)
    for cls in ("a", "b"):
        os.makedirs(tmp_path / cls, exist_ok=True)
    PIL.fromarray(arr).save(str(tmp_path / "a" / "x.png"))
    PIL.fromarray(arr).save(str(tmp_path / "b" / "y.jpg"), quality=95)
    _write_ppm(str(tmp_path / "a" / "z.ppm"), arr)

    reader = ImageRecordReader(10, 12, 3, root=str(tmp_path))
    recs = list(reader)
    assert len(recs) == 3
    assert reader.labels() == ["a", "b"]
    png_rec = recs[0][0]  # a/x.png sorts first
    # all decoders normalize to [0, 1] (the native netpbm convention)
    np.testing.assert_allclose(png_rec, arr.astype(np.float32) / 255.0,
                               atol=0.5 / 255.0)


def test_reader_applies_augmentation(tmp_path):
    rng = np.random.RandomState(0)
    arr = rng.randint(0, 256, (10, 10, 3), np.uint8)
    os.makedirs(tmp_path / "a", exist_ok=True)
    _write_ppm(str(tmp_path / "a" / "x.ppm"), arr)
    reader = ImageRecordReader(
        10, 10, 3, root=str(tmp_path),
        transform=FlipImageTransform(mode=1))
    rec = next(iter(reader))[0]
    np.testing.assert_allclose(rec, arr[:, ::-1].astype(np.float32) / 255.0,
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# throughput: input path vs compute step
# ---------------------------------------------------------------------------

def test_input_pipeline_throughput_vs_resnet_step(tmp_path, capsys):
    """The honest input-bound-vs-compute-bound statement: measure the
    augmented 224x224 input path (decode+flip+crop+batch) and compare to
    the last TPU-measured ResNet-50 step rate. Asserts a conservative
    host-throughput floor; prints the ratio for the record."""
    rng = np.random.RandomState(0)
    os.makedirs(tmp_path / "a", exist_ok=True)
    n = 48
    for i in range(n):
        _write_ppm(str(tmp_path / "a" / f"{i}.ppm"),
                   rng.randint(0, 256, (256, 256, 3), np.uint8))
    aug = PipelineImageTransform(
        (FlipImageTransform(mode=1), 0.5),
        RandomCropTransform(height=224, width=224))
    reader = ImageRecordReader(224, 224, 3, root=str(tmp_path), transform=aug)
    it = RecordReaderDataSetIterator(reader, batch_size=16, label_index=1,
                                     num_classes=1)
    start = time.perf_counter()
    seen = sum(ds.features.shape[0] for ds in it)
    rate = seen / (time.perf_counter() - start)
    assert seen == n
    assert rate > 30  # single slow core; TPU feeding needs parallel workers
    resnet_tpu_sps = 1794.89  # BENCH_latest.json, round 4
    with capsys.disabled():
        print(f"\n[input-pipeline] {rate:.0f} img/s host vs "
              f"{resnet_tpu_sps:.0f} samples/s ResNet-50/TPU -> "
              f"need ~{resnet_tpu_sps / rate:.1f} input workers")


# ---- round-5 input-pipeline (VERDICT r4 ask 2) ----------------------------


def _make_ppm_tree(tmp_path, n=12, size=32):
    rng = np.random.RandomState(0)
    header = f"P6 {size} {size} 255\n".encode()
    for cls in ("a", "b"):
        (tmp_path / cls).mkdir(exist_ok=True)
    for i in range(n):
        body = rng.randint(0, 256, (size, size, 3), np.uint8).tobytes()
        (tmp_path / "ab"[i % 2] / f"{i}.ppm").write_bytes(header + body)
    return str(tmp_path)


def test_uint8_reader_matches_float_reader(tmp_path):
    from deeplearning4j_tpu.data.image_transform import CropImageTransform
    from deeplearning4j_tpu.data.records import ImageRecordReader

    root = _make_ppm_tree(tmp_path, n=6)
    crop = CropImageTransform(top=4, left=4, bottom=4, right=4)
    u8 = list(ImageRecordReader(24, 24, 3, root=root, transform=crop,
                                output_dtype="uint8"))
    f32 = list(ImageRecordReader(24, 24, 3, root=root, transform=crop))
    assert len(u8) == len(f32) == 6
    for (a, la), (b, lb) in zip(u8, f32):
        assert a.dtype == np.uint8 and b.dtype == np.float32
        assert la == lb
        np.testing.assert_allclose(a.astype(np.float32) / 255.0, b,
                                   atol=1e-6)


def test_uint8_reader_rejects_value_transforms(tmp_path):
    from deeplearning4j_tpu.data.image_transform import BrightnessTransform
    from deeplearning4j_tpu.data.records import ImageRecordReader

    root = _make_ppm_tree(tmp_path, n=2)
    reader = ImageRecordReader(32, 32, 3, root=root,
                               transform=BrightnessTransform(delta=0.1),
                               output_dtype="uint8")
    with pytest.raises(ValueError, match="uint8"):
        next(iter(reader))


def test_parallel_reader_preserves_order_and_content(tmp_path):
    from deeplearning4j_tpu.data.records import ImageRecordReader

    root = _make_ppm_tree(tmp_path, n=16)
    serial = list(ImageRecordReader(32, 32, 3, root=root,
                                    output_dtype="uint8"))
    parallel = list(ImageRecordReader(32, 32, 3, root=root,
                                      output_dtype="uint8", workers=4))
    assert len(serial) == len(parallel) == 16
    for (a, la), (b, lb) in zip(serial, parallel):
        np.testing.assert_array_equal(a, b)
        assert la == lb


def test_uint8_batches_flow_to_device_augment_and_fit(tmp_path):
    """End-to-end: u8 files -> RecordReader -> async prefetch+device_put ->
    jitted on-device augment (crop+cast+scale) -> train step. The host
    never touches a float pixel (SURVEY.md §3.1 I/O-overlap boundary)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.data.image_transform import batch_random_crop
    from deeplearning4j_tpu.data.iterators import (
        AsyncDataSetIterator, MappedDataSetIterator, device_put_dataset,
    )
    from deeplearning4j_tpu.data.records import (
        ImageRecordReader, RecordReaderDataSetIterator,
    )
    from deeplearning4j_tpu.nn import (
        Activation, InputType, LossFunction, NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.layers import (
        ConvolutionLayer, GlobalPoolingLayer, OutputLayer, PoolingType,
    )
    from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.train.solver import Solver

    root = _make_ppm_tree(tmp_path, n=8, size=32)
    reader = ImageRecordReader(32, 32, 3, root=root, output_dtype="uint8")
    base = RecordReaderDataSetIterator(reader, batch_size=4, label_index=1,
                                       num_classes=2)
    key = jax.random.PRNGKey(0)

    def prep(features):  # [b, h, w, c] u8 -> [b, c, 24, 24] f32 in [0,1]
        x = jnp.transpose(jnp.asarray(features), (0, 3, 1, 2))
        x = x.astype(jnp.float32) / 255.0
        return batch_random_crop(x, key, 24, 24)

    it = MappedDataSetIterator(
        AsyncDataSetIterator(base, device_put_fn=device_put_dataset),
        feature_fn=jax.jit(prep))

    lb = (NeuralNetConfiguration.builder().seed(3).list()
          .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3)))
          .layer(GlobalPoolingLayer(pooling_type=PoolingType.AVG))
          .layer(OutputLayer(n_out=2, loss=LossFunction.MCXENT,
                             activation=Activation.SOFTMAX)))
    lb.set_input_type(InputType.convolutional(24, 24, 3))
    net = MultiLayerNetwork(lb.build()).init()
    solver = Solver(net)
    n = 0
    for ds in it:
        assert ds.features.dtype == jnp.float32
        score = float(solver.fit_batch(ds.features, ds.labels)[0])
        assert np.isfinite(score)
        n += ds.features.shape[0]
    assert n == 8


def test_record_iterator_multi_epoch_reset(tmp_path):
    """Regression: reset() must clear the protocol lookahead so wrappers
    like MultipleEpochsIterator see every epoch, not just the first."""
    from deeplearning4j_tpu.data.iterators import MultipleEpochsIterator
    from deeplearning4j_tpu.data.records import (
        ImageRecordReader, RecordReaderDataSetIterator,
    )

    root = _make_ppm_tree(tmp_path, n=8)
    reader = ImageRecordReader(32, 32, 3, root=root, output_dtype="uint8")
    base = RecordReaderDataSetIterator(reader, batch_size=4, label_index=1,
                                       num_classes=2)
    assert base.batch_size() == 4
    it = MultipleEpochsIterator(base, epochs=3)
    it.reset()
    n = 0
    while it.has_next():
        n += it.next().features.shape[0]
    assert n == 24  # 8 images x 3 epochs
    assert it.batch_size() == 4


def test_uint8_netpbm_parser_comments_maxval_trailing(tmp_path):
    """The u8 fast-path netpbm parser must match the native float parser's
    front-anchored semantics: '#' comments, maxval rescale, and files with
    trailing bytes after the raster."""
    from deeplearning4j_tpu.data.records import ImageRecordReader

    rng = np.random.RandomState(0)
    px = rng.randint(0, 256, (8, 8, 3), np.uint8)
    (tmp_path / "a").mkdir()
    # comment line + trailing newline after raster
    body = b"P6\n# a comment\n8 8\n255\n" + px.tobytes() + b"\n"
    (tmp_path / "a" / "x.ppm").write_bytes(body)
    r = ImageRecordReader(8, 8, 3, root=str(tmp_path), output_dtype="uint8")
    got = next(iter(r))[0]
    np.testing.assert_array_equal(got, px)
    # maxval 127 rescales to the full byte range
    px7 = (px // 2).astype(np.uint8)
    (tmp_path / "a" / "x.ppm").write_bytes(
        b"P6 8 8 127\n" + px7.tobytes())
    r2 = ImageRecordReader(8, 8, 3, root=str(tmp_path), output_dtype="uint8")
    got2 = next(iter(r2))[0]
    assert got2.max() > 200  # rescaled toward 255
    # ROUNDED rescale: the uint8 fast path must match the float decoder
    # within rounding (ADVICE round-5 item 2 — floor division diverged
    # by up to 1 LSB)
    rf = ImageRecordReader(8, 8, 3, root=str(tmp_path),
                           output_dtype="float32")
    fgot = next(iter(rf))[0]  # [0,1] floats
    np.testing.assert_array_equal(got2, np.rint(fgot * 255).astype(np.uint8))
    # 16-bit rejected loudly
    (tmp_path / "a" / "x.ppm").write_bytes(
        b"P6 8 8 65535\n" + (b"\0" * (8 * 8 * 3 * 2)))
    r3 = ImageRecordReader(8, 8, 3, root=str(tmp_path), output_dtype="uint8")
    with pytest.raises(ValueError, match="16-bit"):
        next(iter(r3))
