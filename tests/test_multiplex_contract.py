"""Tier-1 wiring for tools/check_multiplex_contract.py: the multi-tenant
multiplexing chaos contract (README.md "Multi-tenant multiplexing") —
8 models behind one server on a budget sized for ~4 warm over real
HTTP, hot tenants in-SLO during cold-tenant page-in churn, zero
requests lost to eviction, byte-identical unpark replay (quantized
included), kill-during-page-in recovery — is enforced on every test
run, not just when someone remembers to run the tool. Honors
``DL4J_CHAOS_SEED`` like every chaos harness."""

import os
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


def test_multiplex_contract_smoke():
    sys.path.insert(0, _TOOLS)
    try:
        import check_multiplex_contract
    finally:
        sys.path.remove(_TOOLS)
    assert check_multiplex_contract.main(log=lambda m: None) == 0
