"""Unit coverage for post-training quantization (nn/rewrite/quantize.py)
and the int8 KV cache (generate/session.py + attention/_cached_attention):
per-channel scale exactness, pass semantics on both config families,
calibration, quantized decode, engine wiring — ISSUE 13."""

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.core.config import from_json, to_json
from deeplearning4j_tpu.nn import (
    Activation,
    InputType,
    LossFunction,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer,
    ConvolutionMode,
    DenseLayer,
    OutputLayer,
    RnnOutputLayer,
    SelfAttentionLayer,
)
from deeplearning4j_tpu.nn.rewrite import (
    QuantizedConvolutionLayer,
    QuantizedDenseLayer,
    QuantizedMixtureOfExpertsLayer,
    QuantizedSelfAttentionLayer,
    QuantizedTransformerDecoderBlockLayer,
    QuantizeWeightsPass,
    calibrate,
    count_quantized_layers,
    quantize_weight,
    resolve_passes,
    rewrite_model,
)
from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork


def _mlp(seed=5, n_in=8, hidden=32, classes=4):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=n_in, n_out=hidden,
                              activation=Activation.RELU))
            .layer(DenseLayer(n_out=hidden, activation=Activation.RELU))
            .layer(OutputLayer(n_out=classes, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(n_in))
            .build())
    return MultiLayerNetwork(conf).init()


def _conv_net(seed=6):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(ConvolutionLayer(n_out=6, kernel_size=(3, 3),
                                    convolution_mode=ConvolutionMode.SAME,
                                    activation=Activation.RELU))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.convolutional(8, 8, 3))
            .build())
    return MultiLayerNetwork(conf).init()


# ---------------------------------------------------------------------------
# the quantizer primitive
# ---------------------------------------------------------------------------

def test_quantize_weight_per_channel_roundtrip():
    rng = np.random.RandomState(0)
    # mixed-magnitude columns: per-channel scales must track each column
    w = rng.randn(16, 8) * np.logspace(-2, 1, 8)[None, :]
    q, s = quantize_weight(w, "int8", channel_axis=1)
    assert q.dtype == jnp.int8 and s.shape == (8,)
    deq = np.asarray(q, np.float64) * np.asarray(s, np.float64)[None, :]
    # absmax int8: error bounded by scale/2 per element, per channel
    err = np.abs(deq - w)
    bound = np.asarray(s)[None, :] * 0.5 + 1e-12
    assert np.all(err <= bound)
    # exact multiples of the scale survive the round trip bit-exactly
    w2 = np.outer(np.arange(-127, 128), np.ones(3)) * np.asarray([1, 2, 4.0])
    w2 = w2 / 127.0
    q2, s2 = quantize_weight(w2, "int8", channel_axis=1)
    deq2 = np.asarray(q2, np.float64) * np.asarray(s2)[None, :]
    # exact up to the f32 storage precision of the scale itself
    np.testing.assert_allclose(deq2, w2, rtol=1e-6, atol=1e-7)


def test_quantize_weight_conv_axis_and_zero_channel():
    rng = np.random.RandomState(1)
    w = rng.randn(4, 3, 3, 3)
    w[2] = 0.0  # an all-zero output channel must not divide by zero
    q, s = quantize_weight(w, "int8", channel_axis=0)
    assert s.shape == (4,)
    assert np.all(np.asarray(q)[2] == 0)
    assert np.all(np.isfinite(np.asarray(s)))


def test_quantize_weight_rejects_unknown_dtype():
    with pytest.raises(ValueError, match="quant dtype"):
        quantize_weight(np.ones((2, 2)), "int4")
    with pytest.raises(ValueError, match="quant dtype"):
        QuantizeWeightsPass("int4")


# ---------------------------------------------------------------------------
# the pass: sequential configs
# ---------------------------------------------------------------------------

def test_int8_pass_rewrites_dense_and_bounds_error():
    model = _mlp()
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    base = np.asarray(model.output(x))
    q, applied = rewrite_model(model, [QuantizeWeightsPass("int8")])
    assert applied == ["quantize_weights_int8"]
    assert q is not model  # the original is never mutated
    assert count_quantized_layers(q) == 2
    assert count_quantized_layers(model) == 0
    # the final output/loss layer keeps full precision
    assert not isinstance(q.conf.layers[-1], QuantizedDenseLayer)
    # params replaced by storage + scale; weight-only error stays small
    lname = q.conf.layer_name(0)
    assert q.params[lname]["W_q"].dtype == jnp.int8
    assert q.params[lname]["W_scale"].dtype == jnp.float32
    assert "W" not in q.params[lname]
    out = np.asarray(q.output(x))
    assert np.abs(out - base).max() < 5e-2
    assert np.mean((out - base) ** 2) < 1e-4


@pytest.mark.parametrize("mode", ["einsum", "sort", "grouped"])
def test_int8_pass_rewrites_moe_experts(mode):
    """MoE expert slabs quantize with per-expert per-output-channel
    scales; the router Wg and biases stay full precision; all dispatch
    modes serve the quantized experts (ISSUE 18)."""
    from deeplearning4j_tpu.nn.layers import MixtureOfExpertsLayer

    conf = (NeuralNetConfiguration.builder().seed(11).list()
            .layer(MixtureOfExpertsLayer(n_out=8, num_experts=4, hidden=16,
                                         top_k=2, dispatch_mode=mode))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8)).build())
    model = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    x = rng.rand(16, 8).astype(np.float32)
    base = np.asarray(model.output(x))
    q, applied = rewrite_model(model, [QuantizeWeightsPass("int8")])
    assert applied == ["quantize_weights_int8"]
    lay = q.conf.layers[0]
    assert type(lay) is QuantizedMixtureOfExpertsLayer
    assert lay.dispatch_mode == mode
    assert lay.trainable_param_names() == ()
    assert count_quantized_layers(q) == 1
    lname = q.conf.layer_name(0)
    lp = q.params[lname]
    assert set(lp) == {"Wg", "We1_q", "We1_scale", "We2_q", "We2_scale",
                       "be1", "be2"}
    assert lp["We1_q"].dtype == jnp.int8
    assert lp["We1_scale"].shape == (4, 16)  # per-expert × per-channel
    assert lp["We2_scale"].shape == (4, 8)
    assert lp["Wg"].dtype == jnp.float32  # router untouched
    out = np.asarray(q.output(x))
    assert np.abs(out - base).max() < 5e-2
    # idempotent: re-running the pass is a no-op
    q2, ap2 = rewrite_model(q, [QuantizeWeightsPass("int8")])
    assert ap2 == [] and q2 is q
    with pytest.raises(RuntimeError, match="rewrite product"):
        lay.init(None, jnp.float32)


def test_quantize_weight_tuple_axis_per_expert():
    """Tuple channel_axis keeps several axes at full granularity — the
    per-expert expert-slab scheme. Per-expert scales must beat one
    shared-absmax scale when expert magnitudes differ wildly."""
    rng = np.random.RandomState(3)
    w = rng.randn(4, 8, 16)
    w[0] *= 100.0  # an outlier expert would crush a shared absmax
    q, s = quantize_weight(w, "int8", channel_axis=(0, 2))
    assert q.shape == w.shape and s.shape == (4, 16)
    deq = np.asarray(q, np.float64) * np.asarray(s, np.float64)[:, None, :]
    per_expert_err = np.abs(deq - w)[1:].max()
    q1, s1 = quantize_weight(w, "int8", channel_axis=2)
    deq1 = np.asarray(q1, np.float64) * np.asarray(s1, np.float64)
    shared_err = np.abs(deq1 - w)[1:].max()
    assert per_expert_err < shared_err / 10


def test_int8_pass_rewrites_conv():
    model = _conv_net()
    rng = np.random.RandomState(2)
    x = rng.randn(4, 3, 8, 8).astype(np.float32)
    base = np.asarray(model.output(x))
    q, applied = rewrite_model(model, [QuantizeWeightsPass("int8")])
    assert applied and count_quantized_layers(q) == 1
    assert isinstance(q.conf.layers[0], QuantizedConvolutionLayer)
    out = np.asarray(q.output(x))
    assert np.abs(out - base).max() < 5e-2


def test_fp8_pass_when_supported():
    if not hasattr(jnp, "float8_e4m3fn"):
        with pytest.raises(ValueError, match="fp8"):
            QuantizeWeightsPass("fp8")
        return
    model = _mlp(seed=9)
    rng = np.random.RandomState(3)
    x = rng.randn(8, 8).astype(np.float32)
    base = np.asarray(model.output(x))
    q, applied = rewrite_model(model, [QuantizeWeightsPass("fp8")])
    assert applied == ["quantize_weights_fp8"]
    lname = q.conf.layer_name(0)
    assert q.params[lname]["W_q"].dtype == jnp.float8_e4m3fn
    out = np.asarray(q.output(x))
    assert np.abs(out - base).max() < 5e-2


def test_pass_idempotent_and_noop_objects():
    model = _mlp()
    q, _ = rewrite_model(model, [QuantizeWeightsPass("int8")])
    p = QuantizeWeightsPass("int8")
    conf2, params2, state2, changed = p.apply(q.conf, q.params, q.state)
    assert not changed
    assert conf2 is q.conf and params2 is q.params and state2 is q.state


def test_attention_projection_quantization():
    conf = (NeuralNetConfiguration.builder().seed(4).list()
            .layer(SelfAttentionLayer(n_out=16, n_heads=2,
                                      project_input=True))
            .layer(RnnOutputLayer(n_out=4, loss=LossFunction.MCXENT,
                                  activation=Activation.SOFTMAX))
            .set_input_type(InputType.recurrent(8, 6))
            .build())
    model = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(5)
    x = rng.randn(2, 8, 6).astype(np.float32)
    base = np.asarray(model.output(x))
    q, applied = rewrite_model(model, [QuantizeWeightsPass("int8")])
    assert applied and isinstance(q.conf.layers[0],
                                  QuantizedSelfAttentionLayer)
    lname = q.conf.layer_name(0)
    assert {"Wq_q", "Wq_scale", "Wk_q", "Wk_scale", "Wv_q", "Wv_scale",
            "Wo_q", "Wo_scale"} <= set(q.params[lname])
    out = np.asarray(q.output(x))
    assert np.abs(out - base).max() < 5e-2


def test_transformer_lm_quantized_decode_matches_full_forward():
    """A quantized LM must still decode through the KV-cache path — and
    its incremental stream must agree with its OWN full re-forward (the
    PR-9 prefill/decode equivalence, now on the quantized graph)."""
    from deeplearning4j_tpu.generate import GenerationSession
    from deeplearning4j_tpu.model.zoo import TransformerLM

    model = TransformerLM(vocab_size=12, hidden=32, n_layers=2, n_heads=2,
                          max_len=32).init()
    q, applied = rewrite_model(model, [QuantizeWeightsPass("int8")])
    assert applied and count_quantized_layers(q) == 2
    assert isinstance(q.conf.layers[2],
                      QuantizedTransformerDecoderBlockLayer)
    sess = GenerationSession(q, max_len=32)
    out = sess.generate([[1, 2, 3]], 8, greedy=True)[0]
    assert len(out) == 8
    # greedy stream == argmax chain of the quantized model's full forward
    ids = [1, 2, 3]
    for tok in out:
        full = np.asarray(q.output(np.asarray([ids], np.int32)))
        assert int(np.argmax(full[0, :, len(ids) - 1])) == tok
        ids.append(tok)


# ---------------------------------------------------------------------------
# graph configs
# ---------------------------------------------------------------------------

def test_graph_config_quantization():
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    g = (NeuralNetConfiguration.builder().seed(8).graph_builder()
         .add_inputs("in")
         .add_layer("d1", DenseLayer(n_out=16, activation=Activation.RELU),
                    "in")
         .add_layer("out", OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                                       activation=Activation.SOFTMAX), "d1"))
    g.set_outputs("out")
    g.set_input_types(InputType.feed_forward(6))
    model = ComputationGraph(g.build()).init()
    rng = np.random.RandomState(6)
    x = rng.randn(4, 6).astype(np.float32)
    base = np.asarray(model.output(x)[0])
    q, applied = rewrite_model(model, [QuantizeWeightsPass("int8")])
    assert applied == ["quantize_weights_int8"]
    assert count_quantized_layers(q) == 1
    assert "W_q" in q.params["d1"] and "W" not in q.params["d1"]
    out = np.asarray(q.output(x)[0])
    assert np.abs(out - base).max() < 5e-2


# ---------------------------------------------------------------------------
# calibration + activation quantization
# ---------------------------------------------------------------------------

def test_calibrate_records_dense_input_ranges():
    model = _mlp()
    rng = np.random.RandomState(7)
    batches = [rng.randn(8, 8).astype(np.float32) * s for s in (1.0, 3.0)]
    ranges = calibrate(model, batches)
    names = model.layer_names()
    assert set(ranges) == {names[0], names[1]}  # Dense layers only
    # the recorded range is the max over ALL batches
    assert ranges[names[0]] >= float(np.abs(batches[1]).max()) - 1e-6
    with pytest.raises(ValueError, match="MultiLayerNetwork"):
        calibrate(object(), batches)


def test_activation_quantization_close_and_carried_in_pass_config():
    model = _mlp()
    rng = np.random.RandomState(8)
    x = rng.randn(16, 8).astype(np.float32)
    ranges = calibrate(model, [x])
    p = QuantizeWeightsPass("int8", act_ranges=ranges)
    assert p.act_ranges == ranges  # ranges live in the pass config
    base = np.asarray(model.output(x))
    q, applied = rewrite_model(model, [p])
    assert applied
    l0 = q.conf.layers[0]
    assert isinstance(l0, QuantizedDenseLayer)
    assert l0.act_absmax is not None and l0.act_absmax > 0
    out = np.asarray(q.output(x))
    assert np.abs(out - base).max() < 5e-2
    # model params carry no range — only storage + scale + bias
    lname = q.conf.layer_name(0)
    assert set(q.params[lname]) == {"W_q", "W_scale", "b"}


def test_resolve_passes_quantized_specs():
    names = [p.name for p in resolve_passes("inference:int8")]
    assert names[-1] == "quantize_weights_int8"
    assert names[:3] == ["space_to_depth_stem", "conv_bn_fold",
                        "bn_affine_precompute"]
    with pytest.raises(ValueError):
        resolve_passes("inference:int4")
    with pytest.raises(ValueError, match="inference-only"):
        resolve_passes("inference:int8", context="training")


def test_quantized_layers_never_trained_or_inited():
    model = _mlp()
    q, _ = rewrite_model(model, [QuantizeWeightsPass("int8")])
    layer = q.conf.layers[0]
    assert layer.trainable_param_names() == ()
    with pytest.raises(RuntimeError, match="rewrite product"):
        layer.init(None, jnp.float32)


def test_quantized_config_json_round_trip():
    # rewrites are in-memory only, but the rewritten CONFIG must stay a
    # first-class registered config (repr/describe/json surfaces)
    model = _mlp()
    q, _ = rewrite_model(model, [QuantizeWeightsPass("int8",
                                                     act_ranges=None)])
    j = to_json(q.conf)
    back = from_json(j)
    assert isinstance(back.layers[0], QuantizedDenseLayer)
    assert back.layers[0].quant_dtype == "int8"


# ---------------------------------------------------------------------------
# int8 KV cache
# ---------------------------------------------------------------------------

def _tiny_lm(**kw):
    from deeplearning4j_tpu.model.zoo import TransformerLM

    args = dict(vocab_size=16, hidden=32, n_layers=2, n_heads=2, max_len=32)
    args.update(kw)
    return TransformerLM(**args).init()


def test_decode_attention_scales_match_explicit_dequant():
    from deeplearning4j_tpu.ops import (decode_attention,
                                        decode_attention_reference)

    rng = np.random.RandomState(0)
    b, h, L, d = 2, 2, 16, 8
    q = jnp.asarray(rng.randn(b, h, 1, d), jnp.float32)
    kq = jnp.asarray(rng.randint(-127, 128, (b, h, L, d)), jnp.int8)
    vq = jnp.asarray(rng.randint(-127, 128, (b, h, L, d)), jnp.int8)
    ks = jnp.asarray(rng.rand(b, h, L) * 0.1, jnp.float32)
    vs = jnp.asarray(rng.rand(b, h, L) * 0.1, jnp.float32)
    pos = jnp.asarray([5, 11], jnp.int32)
    out = decode_attention(q, kq, vq, pos, k_scale=ks, v_scale=vs)
    ref = decode_attention_reference(
        q, kq.astype(jnp.float32) * ks[..., None],
        vq.astype(jnp.float32) * vs[..., None], pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_int8_cache_session_layout_and_bytes():
    from deeplearning4j_tpu.generate import GenerationSession

    model = _tiny_lm()
    fp = GenerationSession(model, max_len=32)
    qi = GenerationSession(model, max_len=32, cache_dtype="int8")
    with pytest.raises(ValueError, match="cache_dtype"):
        GenerationSession(model, max_len=32, cache_dtype="int4")
    st = qi.decode_state(2)
    block = next(v for k, v in st.items() if "cache_k" in v)
    assert block["cache_k"].dtype == jnp.int8
    assert block["cache_k_scale"].dtype == jnp.float32
    assert block["cache_k_scale"].shape == block["cache_k"].shape[:-1]
    # the int8 cache beats HALF the f32 bytes (i.e. an fp16 cache): the
    # ISSUE's capacity claim, byte-accounted on the real carry
    assert qi.cache_bytes(1) < fp.cache_bytes(1) / 2 + 256


def test_int8_cache_greedy_stream_matches_fp_cache():
    from deeplearning4j_tpu.generate import GenerationSession
    from deeplearning4j_tpu.train.solver import Solver

    model = _tiny_lm()
    rng = np.random.RandomState(0)
    sol = Solver(model)
    for _ in range(60):  # separate the logits so argmax is stable
        s = rng.randint(0, 16, (16, 1))
        x = (s + np.arange(8)) % 16
        sol.fit_batch(jnp.asarray(x, jnp.int32),
                      jnp.asarray((x + 1) % 16, jnp.int32))
    prompts = [((rng.randint(0, 16) + np.arange(4)) % 16).tolist()
               for _ in range(3)]
    fp = GenerationSession(model, max_len=32).generate(
        prompts, 16, greedy=True)
    qi = GenerationSession(model, max_len=32, cache_dtype="int8").generate(
        prompts, 16, greedy=True)
    pairs = [(a, b) for ra, rb in zip(fp, qi) for a, b in zip(ra, rb)]
    match = np.mean([a == b for a, b in pairs])
    assert match >= 0.95, f"greedy token match rate {match}"


def test_decode_engine_int8_cache_and_gauge():
    from deeplearning4j_tpu.obs import MetricsRegistry
    from deeplearning4j_tpu.parallel.decode import DecodeEngine

    model = _tiny_lm()
    reg_fp, reg_q = MetricsRegistry(), MetricsRegistry()
    fp = DecodeEngine(model, max_len=32, slots=2, registry=reg_fp,
                      name="kv-fp")
    qi = DecodeEngine(model, max_len=32, slots=2, cache_dtype="int8",
                      registry=reg_q, name="kv-q")
    try:
        t_fp = fp.generate([1, 2, 3], max_tokens=6, greedy=True)
        t_qi = qi.generate([1, 2, 3], max_tokens=6, greedy=True)
        assert len(t_fp) == len(t_qi) == 6
        s_fp, s_qi = fp.stats(), qi.stats()
        assert s_qi["cache_dtype"] == "int8"
        assert s_qi["kv_cache_bytes"] < s_fp["kv_cache_bytes"] / 2 + 512
        g = reg_q.get("dl4j_tpu_generate_kv_cache_bytes").labels("kv-q")
        assert g.value == s_qi["kv_cache_bytes"] > 0
    finally:
        fp.shutdown(drain=False)
        qi.shutdown(drain=False)


def test_speculative_engine_int8_cache_greedy_identity():
    """Speculative decoding composes with the int8 cache: the rewind
    contract covers the scale planes, and greedy streams stay identical
    to the plain int8-cache decode of the same model."""
    from deeplearning4j_tpu.model.zoo import TransformerLM
    from deeplearning4j_tpu.obs import MetricsRegistry
    from deeplearning4j_tpu.parallel.decode import DecodeEngine

    target_cfg = TransformerLM(vocab_size=16, hidden=32, n_layers=2,
                               n_heads=2, max_len=32)
    model = target_cfg.init()
    draft = TransformerLM.draft_of(target_cfg, hidden=16, n_layers=1,
                                   n_heads=2).init()
    spec = DecodeEngine(model, draft_model=draft, speculative_k=3,
                        max_len=32, slots=2, cache_dtype="int8",
                        registry=MetricsRegistry(), name="spec-q")
    plain = DecodeEngine(model, max_len=32, slots=2, cache_dtype="int8",
                         registry=MetricsRegistry(), name="plain-q")
    try:
        a = spec.generate([1, 2, 3, 4], max_tokens=10, greedy=True)
        b = plain.generate([1, 2, 3, 4], max_tokens=10, greedy=True)
        assert a == b
    finally:
        spec.shutdown(drain=False)
        plain.shutdown(drain=False)


# ---------------------------------------------------------------------------
# manager integration (the contract tool covers the full lifecycle; these
# pin the per-deploy optimize override semantics)
# ---------------------------------------------------------------------------

def test_manager_redeploy_same_version_different_pipeline(tmp_path):
    from deeplearning4j_tpu.obs import MetricsRegistry
    from deeplearning4j_tpu.serving import ModelManager, ModelStore

    model = _mlp()
    store = ModelStore(str(tmp_path / "reg"))
    store.publish("m", model)
    x = np.ones((2, 8), np.float32)
    mgr = ModelManager(store, "m", registry=MetricsRegistry(),
                       warmup_example=x, workers=1)
    try:
        assert count_quantized_layers(mgr.engine.model) == 0
        # same version, different pipeline: a REAL swap, not a no-op
        mgr.deploy(1, optimize="inference:int8")
        assert count_quantized_layers(mgr.engine.model) == 2
        # and back: optimize=None disables rewrites for one deploy
        mgr.deploy(1, optimize=None)
        assert count_quantized_layers(mgr.engine.model) == 0
        # same version + same pipeline IS the existing no-op
        before = mgr.engine._servable
        mgr.deploy(1, optimize=None)
        assert mgr.engine._servable is before
    finally:
        mgr.shutdown(drain=False)
