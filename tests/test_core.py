"""Core infrastructure tests: config round-trip, registry, rng, env, listeners."""

import dataclasses
import enum

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.core import (
    DataType,
    ListenerBus,
    OpRegistry,
    RngState,
    ScoreIterationListener,
    from_json,
    get_environment,
    get_op,
    register_config,
    register_op,
    to_json,
)


class Activation(enum.Enum):
    RELU = "relu"
    TANH = "tanh"


@register_config
@dataclasses.dataclass(frozen=True)
class _InnerCfg:
    units: int = 8
    act: Activation = Activation.RELU


@register_config
@dataclasses.dataclass(frozen=True)
class _OuterCfg:
    name: str = "net"
    layers: tuple = ()
    lr: float = 1e-3
    extra: dict = dataclasses.field(default_factory=dict)


class TestConfig:
    def test_round_trip_nested_polymorphic(self):
        cfg = _OuterCfg(
            name="m",
            layers=(_InnerCfg(4, Activation.TANH), _InnerCfg(2)),
            lr=0.01,
            extra={"k": [1, 2, 3]},
        )
        s = to_json(cfg)
        back = from_json(s)
        assert back == cfg
        assert isinstance(back.layers, tuple)
        assert back.layers[0].act is Activation.TANH

    def test_forward_compatible_extra_keys(self):
        s = to_json(_InnerCfg())
        import json

        d = json.loads(s)
        d["future_field"] = 42
        back = from_json(json.dumps(d))
        assert back == _InnerCfg()

    def test_ndarray_round_trip(self):
        @register_config
        @dataclasses.dataclass(frozen=True)
        class _ArrCfg:
            w: np.ndarray = None

            def __eq__(self, other):
                return np.array_equal(self.w, other.w)

        cfg = _ArrCfg(w=np.arange(6, dtype=np.float32).reshape(2, 3))
        back = from_json(to_json(cfg))
        assert np.array_equal(back.w, cfg.w)
        assert back.w.dtype == np.float32


class TestRegistry:
    def test_register_and_call(self):
        @register_op("test_double")
        def _double(x):
            return x * 2.0

        op = get_op("test_double")
        out = op(jnp.ones((3,)))
        np.testing.assert_allclose(np.asarray(out), 2.0)

    def test_abstract_eval(self):
        @register_op("test_matmul")
        def _mm(a, b):
            return a @ b

        shape = get_op("test_matmul").abstract_eval(
            jax.ShapeDtypeStruct((4, 8), jnp.float32),
            jax.ShapeDtypeStruct((8, 16), jnp.float32),
        )
        assert shape.shape == (4, 16)

    def test_helper_toggle(self):
        calls = []

        def helper(x):
            calls.append("helper")
            return x + 1

        @register_op("test_helper_op", helper=helper)
        def _base(x):
            calls.append("base")
            return x + 1

        op = get_op("test_helper_op")
        op(1.0)
        assert calls == ["helper"]
        get_environment().allow_helpers = False
        op(1.0)
        assert calls == ["helper", "base"]

    def test_duplicate_rejected(self):
        @register_op("test_dup")
        def _a(x):
            return x

        with pytest.raises(ValueError):
            @register_op("test_dup")
            def _b(x):
                return x


class TestRng:
    def test_determinism(self):
        a, b = RngState(7), RngState(7)
        ka, kb = a.next_key(), b.next_key()
        assert jax.random.uniform(ka, (3,)).tolist() == jax.random.uniform(kb, (3,)).tolist()

    def test_stream_advances(self):
        r = RngState(7)
        k1, k2 = r.next_key(), r.next_key()
        assert jax.random.uniform(k1, ()).item() != jax.random.uniform(k2, ()).item()

    def test_split(self):
        r = RngState(3)
        keys = r.split(4)
        assert keys.shape[0] == 4


class TestDtypes:
    def test_mapping(self):
        assert DataType.FLOAT.jnp == jnp.float32
        assert DataType.BFLOAT16.jnp == jnp.bfloat16
        assert DataType.from_any("float32") is DataType.FLOAT
        assert DataType.from_any(np.float64) is DataType.DOUBLE
        assert DataType.FLOAT.is_floating and not DataType.INT.is_floating


class TestListeners:
    def test_bus_dispatch(self):
        logged = []
        bus = ListenerBus([ScoreIterationListener(print_every=2, log_fn=logged.append)])
        for i in range(5):
            bus.iteration_done(None, i, 0, 0.5)
        assert len(logged) == 3  # iterations 0, 2, 4


def test_multi_device_cpu_mesh_available():
    # conftest forces 8 virtual CPU devices; sharding tests depend on this.
    assert len(jax.devices()) == 8
