"""Tier-1 wiring for tools/check_input_pipeline_contract.py: the prefetch
tier's lifecycle + overlap contract (README.md "Input pipeline" — no leaked
prefetch/worker threads after close()/reset() in any race, the starvation
gauge fires when the consumer outruns the producer, and the double buffer
keeps the data_wait share negligible on a fast-producer run), mirroring
test_serving_contract.py / test_trace_contract.py."""

import os
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


def test_input_pipeline_contract_smoke():
    sys.path.insert(0, _TOOLS)
    try:
        import check_input_pipeline_contract
    finally:
        sys.path.remove(_TOOLS)
    assert check_input_pipeline_contract.main(log=lambda m: None) == 0
