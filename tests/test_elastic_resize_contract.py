"""Tier-1 wiring for tools/check_elastic_resize_contract.py: the elastic
mesh-resize chaos contract (README.md "Elastic resize") — SIGKILL a real
ZeRO-1 child trainer twice while shrinking then growing the device count
between boots (N -> N/2 -> N), and prove the run comes back each time
with re-sharded updater state on the new width, a provably
non-overlapping / non-skipping global consumed-batch sequence, a final
eval loss inside the quality gate vs the fixed-width reference, and a
goodput ledger that itemizes the outage — enforced on every test run,
not just when someone remembers to run the tool."""

import os
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


def test_elastic_resize_contract_smoke():
    sys.path.insert(0, _TOOLS)
    try:
        import check_elastic_resize_contract
    finally:
        sys.path.remove(_TOOLS)
    assert check_elastic_resize_contract.main(log=lambda m: None) == 0
