"""Tier-1 wiring for tools/check_dp_update_contract.py: the ZeRO-1
sharded-weight-update + compressed-gradient-exchange contract (README.md
"Distributed training" — zero1 trajectory equals the replicated one on
both trainer paths, per-replica updater bytes shrink ~1/N, top-k residual
feedback conserves mass, checkpoints are layout-independent with clear
incompatibility errors, and the updater-bytes/compression-ratio series
export), mirroring test_metrics_contract.py / test_trace_contract.py."""

import os
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


def test_dp_update_contract_smoke():
    sys.path.insert(0, _TOOLS)
    try:
        import check_dp_update_contract
    finally:
        sys.path.remove(_TOOLS)
    assert check_dp_update_contract.main(log=lambda m: None) == 0
