"""Cross-host serving fabric unit tests (ISSUE 12): RemoteReplica's
replica protocol over HTTP, pool failover semantics (connection error /
503 fail over, 400 never does), health-prober breaker feed, load-score
piggyback + staleness fallback, and remote deploy fan-out with rollback
on partial failure. The full kill-a-host chaos story lives in
tools/check_fabric_contract.py (tier-1 via test_fabric_contract.py)."""

import json
import socket
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.core.resilience import (CircuitBreaker,
                                                CircuitState,
                                                ReplicaUnavailableError)
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.obs.metrics import MetricsRegistry
from deeplearning4j_tpu.parallel import EnginePool
from deeplearning4j_tpu.remote import (JsonModelServer, RemoteDeployError,
                                       RemoteReplica)


def _small_model(seed=5):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    return MultiLayerNetwork(conf).init()


def _wait_for(cond, timeout=10.0, what="condition"):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _replica(port, name, *, registry=None, breaker=None, prober=False,
             **kw):
    return RemoteReplica(
        f"http://127.0.0.1:{port}/v1/serving", name=name,
        registry=registry or MetricsRegistry(),
        circuit_breaker=breaker, start_prober=prober,
        probe_interval=0.05, **kw)


class _RawServer:
    """Minimal raw-socket HTTP server for protocol-level failure shapes
    (fixed status codes, truncated bodies) that a well-behaved
    JsonModelServer never produces."""

    def __init__(self, respond):
        self._respond = respond
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            with conn:
                try:
                    conn.settimeout(5)
                    data = b""
                    while b"\r\n\r\n" not in data:
                        data += conn.recv(65536)
                    head = data.split(b"\r\n\r\n", 1)[0].decode()
                    length = 0
                    for line in head.split("\r\n"):
                        if line.lower().startswith("content-length:"):
                            length = int(line.split(":", 1)[1])
                    body = data.split(b"\r\n\r\n", 1)[1]
                    while len(body) < length:
                        body += conn.recv(65536)
                    conn.sendall(self._respond(head, body))
                except Exception:
                    pass

    def close(self):
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass


@pytest.fixture(scope="module")
def backend():
    model = _small_model()
    srv = JsonModelServer(model, port=0, workers=1,
                          registry=MetricsRegistry(), name="fab-be").start()
    yield srv, model
    srv.stop(drain=False)


def test_remote_replica_serves_through_pool(backend):
    srv, model = backend
    reg = MetricsRegistry()
    rep = _replica(srv.port, "solo", registry=reg)
    pool = EnginePool(engines=[rep], registry=reg, name="fab-p1")
    try:
        x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        out = pool.output(x, timeout=15)
        np.testing.assert_allclose(out, np.asarray(model.output(x)),
                                   atol=1e-5)
        assert pool.stats()["dispatched"]["solo"] == 1
        assert pool.stats()["fabric"]["healthy"] == {"solo": True}
    finally:
        pool.shutdown(drain=False)


def test_load_score_piggybacks_and_falls_back_to_stats_poll(backend):
    srv, _ = backend
    rep = _replica(srv.port, "score", load_score_max_age=60.0)
    try:
        assert rep._remote_score is None
        rep.output(np.ones((1, 4), np.float32), timeout=15)
        # every POST response carries X-Load-Score
        assert rep._remote_score is not None
        # the /stats poll fallback refreshes score AND identity
        rep._remote_score = None
        s = rep.poll_stats()
        assert rep._remote_score is not None
        assert s["replica"]["name"] == "fab-be"
        assert rep.stats()["remote"]["pid"] == s["replica"]["pid"]
        assert rep.load_score() >= 0.0
    finally:
        rep.shutdown(drain=False)


def test_connection_error_fails_over_to_survivor(backend):
    """A dead host surfaces as ReplicaUnavailableError on the dispatched
    future; the pool fails the request over to the next candidate and
    the caller sees only the answer."""
    srv, model = backend
    reg = MetricsRegistry()
    dead_port = _free_port()
    dead = _replica(dead_port, "dead", registry=reg,
                    breaker=CircuitBreaker(min_calls=2, window=4,
                                           open_timeout=60.0))
    live = _replica(srv.port, "live", registry=reg)
    pool = EnginePool(engines=[dead, live], registry=reg, seed=0,
                      name="fab-fo")
    try:
        x = np.ones((1, 4), np.float32)
        for _ in range(8):  # p2c will pick the dead one sometimes
            out = pool.output(x, timeout=15)
        np.testing.assert_allclose(out, np.asarray(model.output(x)),
                                   atol=1e-5)
        st = pool.stats()
        assert st["fabric"]["failovers"]["dead"] >= 1
        # the dead host's breaker accumulated the failures and opened,
        # taking it out of rotation entirely
        assert dead.circuit_state is CircuitState.OPEN
        assert st["fabric"]["healthy"]["dead"] is False
    finally:
        pool.shutdown(drain=False)


def test_400_never_fails_over():
    """A host answering 400 is telling the CALLER the input is bad —
    retrying it on another host cannot help and must not happen."""
    def bad_request(_head, _body):
        body = json.dumps({"error": "malformed request: nope"}).encode()
        return (b"HTTP/1.0 400 Bad Request\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: " + str(len(body)).encode()
                + b"\r\n\r\n" + body)

    raw = _RawServer(bad_request)
    reg = MetricsRegistry()
    r400 = _replica(raw.port, "r400", registry=reg)
    other = _replica(_free_port(), "other", registry=reg)
    pool = EnginePool(engines=[r400, other], registry=reg, seed=1,
                      name="fab-400")
    try:
        # force dispatch onto the 400 replica: the other one is open
        for _ in range(5):
            other._breaker.record_failure()
        assert other.circuit_state is CircuitState.OPEN
        with pytest.raises(ValueError):
            pool.output(np.ones((1, 4), np.float32), timeout=10)
        assert pool.stats()["fabric"]["failovers"]["r400"] == 0
        # a 400 is the caller's fault: the replica stays healthy
        assert r400.circuit_state is CircuitState.CLOSED
    finally:
        pool.shutdown(drain=False)
        raw.close()


def test_truncated_body_is_host_failure():
    def truncated(_head, _body):
        return (b"HTTP/1.0 200 OK\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: 500\r\n\r\n"
                b'{"output": [[0.1')  # dies mid-body

    raw = _RawServer(truncated)
    rep = _replica(raw.port, "trunc")
    try:
        with pytest.raises(ReplicaUnavailableError):
            rep.output(np.ones((1, 4), np.float32), timeout=10)
    finally:
        rep.shutdown(drain=False)
        raw.close()


def test_caller_error_in_half_open_slot_does_not_wedge_breaker():
    """Regression: a 400 landing in the single half-open trial slot is a
    NEUTRAL outcome (the host is fine, the input was bad) — the slot
    must be released so the next probe can still run its trial. Without
    the release the breaker wedges in HALF_OPEN forever: allow() keeps
    rejecting and every probe() reports 'probe_inflight', permanently
    removing the replica from rotation."""
    def respond(head, _body):
        if head.startswith("GET /health"):
            body = json.dumps({"status": "ok", "queue_depth": 0}).encode()
            code = b"200 OK"
        else:
            body = json.dumps({"error": "malformed"}).encode()
            code = b"400 Bad Request"
        return (b"HTTP/1.0 " + code + b"\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: " + str(len(body)).encode()
                + b"\r\n\r\n" + body)

    raw = _RawServer(respond)
    breaker = CircuitBreaker(min_calls=2, window=4, open_timeout=0.05)
    rep = _replica(raw.port, "wedge", breaker=breaker)
    try:
        for _ in range(2):
            breaker.record_failure()
        assert rep.circuit_state is CircuitState.OPEN
        time.sleep(0.06)
        assert rep.circuit_state is CircuitState.HALF_OPEN
        # request traffic wins the trial slot over the prober and ends
        # with a caller error...
        with pytest.raises(ValueError):
            rep.output(np.ones((1, 4), np.float32), timeout=10)
        # ...which must have given the slot back: the next health probe
        # takes the trial and closes the breaker
        assert rep.circuit_state is CircuitState.HALF_OPEN
        assert rep.probe() == "ok"
        assert rep.circuit_state is CircuitState.CLOSED
    finally:
        rep.shutdown(drain=False)
        raw.close()


def test_model_version_fetch_failure_is_not_cached():
    """Regression: a transient /v1/models fetch failure answers '0' but
    must NOT cache it — a later swap() would record old_version='0' and
    the pool's partial-failure rollback would re-deploy a version that
    never existed. The next call retries and caches the real version."""
    calls = []

    def respond(head, _body):
        if head.startswith("GET /v1/models"):
            calls.append(1)
            if len(calls) == 1:
                return b""  # connection dies: transient fetch failure
            body = json.dumps(
                {"models": {"m": {"live_version": "7"}}}).encode()
        else:
            body = json.dumps({"status": "ok"}).encode()
        return (b"HTTP/1.0 200 OK\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: " + str(len(body)).encode()
                + b"\r\n\r\n" + body)

    raw = _RawServer(respond)
    rep = _replica(raw.port, "mv", model_name="m")
    try:
        assert rep.model_version == "0"    # transient-failure answer...
        assert rep._model_version is None  # ...is not cached
        assert rep.model_version == "7"    # retry succeeds and caches
        assert rep._model_version == "7"
    finally:
        rep.shutdown(drain=False)
        raw.close()


def test_retry_after_http_date_is_still_host_unavailable():
    """Retry-After may be an HTTP-date (RFC 7231) — an unparseable hint
    must not turn the 503 into a caller error (it is still a
    host-unavailable signal and must still fail over)."""
    def respond(_head, _body):
        body = json.dumps({"error": "overloaded"}).encode()
        return (b"HTTP/1.0 503 Service Unavailable\r\n"
                b"Retry-After: Wed, 05 Aug 2026 09:00:00 GMT\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: " + str(len(body)).encode()
                + b"\r\n\r\n" + body)

    raw = _RawServer(respond)
    rep = _replica(raw.port, "ra-date")
    try:
        with pytest.raises(ReplicaUnavailableError) as ei:
            rep.output(np.ones((1, 4), np.float32), timeout=10)
        assert ei.value.retry_after is None
    finally:
        rep.shutdown(drain=False)
        raw.close()


def test_auto_generated_names_are_unique():
    """Two adapters to the same netloc must not share a name — same-name
    replicas collide in metric label children and in the pool's
    per-name failover bookkeeping."""
    a = RemoteReplica("http://127.0.0.1:9/v1/serving", start_prober=False,
                      registry=MetricsRegistry())
    b = RemoteReplica("http://127.0.0.1:9/v1/serving", start_prober=False,
                      registry=MetricsRegistry())
    try:
        assert a.name != b.name
        assert a.name.startswith("remote-127.0.0.1:9")
    finally:
        a.shutdown(drain=False)
        b.shutdown(drain=False)


def test_prober_opens_breaker_without_traffic_and_rejoins(backend):
    """The health prober feeds the dispatch breaker: a dead endpoint is
    marked unhealthy with ZERO request traffic; once something answers
    /health there again, the half-open probe closes the breaker — no
    operator action, no request needed."""
    srv, _ = backend
    port = _free_port()
    rep = RemoteReplica(
        f"http://127.0.0.1:{port}/v1/serving", name="probed",
        registry=MetricsRegistry(), probe_interval=0.05,
        connect_timeout=0.5,
        circuit_breaker=CircuitBreaker(min_calls=2, window=4,
                                       open_timeout=0.3))
    try:
        _wait_for(lambda: rep.circuit_state is CircuitState.OPEN,
                  what="prober to open the breaker")
        assert rep.stats()["probes"]["error"] >= 2
        # something starts answering on that port
        revived = JsonModelServer(_small_model(), port=port, workers=1,
                                  registry=MetricsRegistry(),
                                  name="revived").start()
        try:
            _wait_for(lambda: rep.circuit_state is CircuitState.CLOSED,
                      what="half-open probe to close the breaker")
            assert rep.stats()["probes"]["ok"] >= 1
            # identity came along with the healthy probe
            assert rep.stats()["remote"]["name"] == "revived"
        finally:
            revived.stop(drain=False)
    finally:
        rep.shutdown(drain=False)


def test_degraded_health_counts_as_probe_failure():
    def degraded(head, _body):
        if head.startswith("GET /health"):
            body = json.dumps({"status": "degraded",
                               "queue_depth": 0}).encode()
            code = b"503 Service Unavailable"
        else:
            body = json.dumps({"status": "ok"}).encode()
            code = b"200 OK"
        return (b"HTTP/1.0 " + code + b"\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: " + str(len(body)).encode()
                + b"\r\n\r\n" + body)

    raw = _RawServer(degraded)
    rep = _replica(raw.port, "deg",
                   breaker=CircuitBreaker(min_calls=2, window=4,
                                          open_timeout=60.0))
    try:
        assert rep.probe() == "degraded"
        assert rep.probe() == "degraded"
        assert rep.circuit_state is CircuitState.OPEN
    finally:
        rep.shutdown(drain=False)
        raw.close()


def test_remote_deploy_fanout_rolls_back_on_partial_failure(tmp_path):
    """ModelManager over a pool of RemoteReplicas rolls each host
    atomically: host0 deploys, host1 fails -> host0 is rolled back to
    the prior version before the error reaches the caller, so the fleet
    never serves two versions."""
    from deeplearning4j_tpu.core.resilience import FaultInjector
    from deeplearning4j_tpu.serving import ModelManager, ModelStore

    store = ModelStore(str(tmp_path))
    store.publish("m", _small_model(1))
    store.publish("m", _small_model(2))

    hosts = []
    for i in range(2):
        reg = MetricsRegistry()
        mgr = ModelManager(store, "m", version=1, registry=reg,
                           probation_seconds=0.0,
                           warmup_example=np.zeros((1, 4), np.float32))
        srv = JsonModelServer(port=0, managers={"m": mgr}, registry=reg,
                              name=f"dh{i}").start()
        hosts.append((srv, mgr))
    front_reg = MetricsRegistry()
    reps = [RemoteReplica(f"http://127.0.0.1:{srv.port}/v1/models/m",
                          name=f"drr{i}", model_name="m",
                          registry=front_reg, start_prober=False)
            for i, (srv, _) in enumerate(hosts)]
    pool = EnginePool(engines=reps, registry=front_reg, name="dfab")
    front = ModelManager(store, "m", engine=pool, registry=front_reg,
                         probation_seconds=0.0)
    try:
        assert front.live_version == "1"
        front.deploy(2)
        assert [m.live_version for _, m in hosts] == ["2", "2"]
        assert front.live_version == "2"
        # requests flow through the pool onto the managed route
        out = pool.output(np.ones((1, 4), np.float32), timeout=15)
        assert out.shape == (1, 3)

        # partial failure: host1's store load dies mid-fan-out
        inj = FaultInjector()
        inj.inject_error("model_manager.load",
                         lambda: RuntimeError("disk gone"), times=1)
        hosts[1][1]._fault_injector = inj
        with pytest.raises(RemoteDeployError):
            front.deploy(1)
        # host0 was deployed to v1, then rolled back to v2
        assert [m.live_version for _, m in hosts] == ["2", "2"], \
            "partial deploy must leave every host on the prior version"
    finally:
        pool.shutdown(drain=False)
        for srv, _ in hosts:
            srv.stop(drain=False)


def test_local_pool_has_no_fabric_surface():
    """No remote replicas configured -> no failover dispatch path, no
    fabric stats section, no fabric series in the registry (local pools
    are unaffected by the fabric feature)."""
    reg = MetricsRegistry()
    pool = EnginePool(model=_small_model(), replicas=2, workers=1,
                      registry=reg, name="local-only")
    try:
        assert pool._has_remote is False
        assert "fabric" not in pool.stats()
        from deeplearning4j_tpu.obs.prom import render_prometheus
        assert "dl4j_tpu_fabric" not in render_prometheus(reg)
    finally:
        pool.shutdown(drain=False)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_pool_generate_stats_fold_remote_speculative():
    """ISSUE 13 satellite: a front pool's ``stats()["generate"]`` must
    aggregate speculative acceptance counters from REMOTE decode hosts
    too — the adapter surfaces the host's `/stats` `speculative` section
    and the pool folds it next to any local decode replicas'."""
    from deeplearning4j_tpu.model.zoo import TransformerLM
    from deeplearning4j_tpu.parallel.decode import DecodeEngine

    cfg = TransformerLM(vocab_size=16, hidden=32, n_layers=1, n_heads=2,
                        max_len=32)
    target = cfg.init()
    draft = TransformerLM.draft_of(cfg, hidden=16, n_layers=1,
                                   n_heads=2).init()
    gen = DecodeEngine(target, draft_model=draft, speculative_k=2,
                       max_len=32, slots=2, registry=MetricsRegistry(),
                       name="rem-gen")
    srv = JsonModelServer(_small_model(), port=0, workers=1,
                          generator=gen, registry=MetricsRegistry()).start()
    reg = MetricsRegistry()
    rep = _replica(srv.port, "spec-host", registry=reg)
    pool = EnginePool(engines=[rep], registry=reg, name="spec-pool")
    try:
        # drive speculative traffic THROUGH the remote host
        gen.generate([1, 2, 3], max_tokens=8, greedy=True)
        host_spec = gen.stats()["speculative"]
        assert host_spec["proposed"] > 0
        rep.poll_stats()  # the staleness-bounded refresh the pool rides
        assert rep.stats()["speculative"] == {
            "proposed": host_spec["proposed"],
            "accepted": host_spec["accepted"],
            "steps": host_spec["steps"]}
        s = pool.stats()
        assert "generate" in s, "remote speculative host must feed the block"
        g = s["generate"]
        assert g["remote_replicas"] == ["spec-host"]
        assert g["replicas"] == ["spec-host"]
        assert g["proposed"] == host_spec["proposed"]
        assert g["accepted"] == host_spec["accepted"]
        assert g["steps"] == host_spec["steps"]
        assert g["acceptance_rate"] == pytest.approx(
            host_spec["accepted"] / host_spec["proposed"])
    finally:
        pool.shutdown(drain=False)
        srv.stop(drain=False)
        gen.shutdown(drain=False)


def test_pool_generate_stats_without_remote_generation_unchanged():
    """A remote host that serves NO generation contributes no speculative
    section, and a pool of such replicas emits no generate block — the
    PR-11 local shape is untouched."""
    srv = JsonModelServer(_small_model(), port=0, workers=1,
                          registry=MetricsRegistry()).start()
    reg = MetricsRegistry()
    rep = _replica(srv.port, "plain-host", registry=reg)
    pool = EnginePool(engines=[rep], registry=reg, name="plain-pool")
    try:
        rep.poll_stats()
        assert "speculative" not in rep.stats()
        assert "generate" not in pool.stats()
    finally:
        pool.shutdown(drain=False)
        srv.stop(drain=False)
