"""Sharded embedding tables over the 8-device mesh (SURVEY.md §2.3
"Param-server sharding (W2V)"): the PS get/push verbs as sharded state +
XLA collectives, and Word2Vec training with row-sharded tables."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.parallel import ShardedEmbeddingTable, make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(model=8)


def test_table_is_actually_sharded(mesh):
    t = ShardedEmbeddingTable(64, 16, mesh, seed=0)
    assert t.table.shape == (64, 16)
    # 8 shards of 8 rows each
    shard_shapes = {s.data.shape for s in t.table.addressable_shards}
    assert shard_shapes == {(8, 16)}


def test_lookup_and_sparse_update_parity(mesh):
    t = ShardedEmbeddingTable(30, 8, mesh, seed=1)  # 30 pads to 32
    dense = t.to_numpy().copy()
    ids = np.asarray([0, 7, 29, 7], np.int32)
    got = np.asarray(t.lookup(ids))
    np.testing.assert_allclose(got, dense[ids], rtol=1e-6)

    deltas = np.random.RandomState(2).randn(4, 8).astype(np.float32)
    t.add_sparse(ids, deltas)
    expect = dense.copy()
    np.add.at(expect, ids, deltas)  # duplicate id 7 accumulates
    np.testing.assert_allclose(t.to_numpy(), expect, rtol=1e-5, atol=1e-6)


def test_word2vec_with_sharded_tables(mesh):
    from deeplearning4j_tpu.nlp import Word2Vec

    rng = np.random.RandomState(0)
    animals = ["cat", "dog", "horse", "sheep", "goat"]
    tech = ["cpu", "gpu", "tpu", "ram", "disk"]
    sents = []
    for _ in range(200):
        pool = animals if rng.rand() < 0.5 else tech
        sents.append([pool[rng.randint(5)] for _ in range(rng.randint(4, 9))])

    w2v = Word2Vec(vector_size=16, window=3, min_count=1, epochs=3,
                   batch_size=256, seed=3, mesh=mesh)
    w2v.fit(sents)
    # trained vectors come back whole and topic-clustered
    assert w2v.get_word_vector("cat").shape == (16,)
    within = np.mean([w2v.similarity("cat", w) for w in animals if w != "cat"])
    across = np.mean([w2v.similarity("cat", w) for w in tech])
    assert within > across, f"within={within:.3f} across={across:.3f}"


def test_sharded_matches_unsharded_w2v(mesh):
    """Same seed, same data: sharded placement must not change the math
    (GSPMD is a layout, not an algorithm change)."""
    from deeplearning4j_tpu.nlp import Word2Vec

    rng = np.random.RandomState(1)
    words = [f"w{i}" for i in range(12)]
    sents = [[words[rng.randint(12)] for _ in range(6)] for _ in range(60)]

    a = Word2Vec(vector_size=8, min_count=1, epochs=2, batch_size=64, seed=5)
    a.fit([list(s) for s in sents])
    b = Word2Vec(vector_size=8, min_count=1, epochs=2, batch_size=64, seed=5,
                 mesh=mesh)
    b.fit([list(s) for s in sents])
    np.testing.assert_allclose(a.syn0, b.syn0, rtol=1e-4, atol=1e-5)
