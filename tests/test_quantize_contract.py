"""Tier-1 wiring for tools/check_quantize_contract.py: the int8
weight-only pass must deploy through ModelManager → start_canary →
promote_canary end-to-end (hash-split routing inside the accuracy gate),
the ModelStore artifact must stay byte-identical (un-rewritten), rollback
must restore exact full-precision serving, and the remote admin deploy
route must roll a quantized build across fabric hosts — enforced on
every test run, not just when someone runs the tool."""

import os
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


def test_quantize_serving_contract():
    sys.path.insert(0, _TOOLS)
    try:
        import check_quantize_contract
    finally:
        sys.path.remove(_TOOLS)
    assert check_quantize_contract.main(log=lambda m: None) == 0
