"""Control-flow import + native structured loop tests (VERDICT.md round 3
ask 5 — "THE thing XLA while replaces", SURVEY.md §2.2/§7).

Covers the native SameDiff while_loop/ifCond API, the functional TF2
encoding (StatelessWhile/StatelessIf from tf.function), and the legacy V1
dataflow encoding (Enter/Merge/Switch/Exit/NextIteration/LoopCond frames
from tf.compat.v1.while_loop, frameless Switch/Merge from
tf.compat.v1.cond). Golden outputs come from TF CPU execution.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.samediff.samediff import SameDiff


# ---------------------------------------------------------------------------
# native structured API
# ---------------------------------------------------------------------------

def test_native_while_loop():
    sd = SameDiff.create()
    x = sd.placeholder("x")
    outs = sd.while_loop(
        [sd.constant(np.int32(0)), x],
        lambda s, i, acc: s._op("lt", i, s.constant(np.int32(5))),
        lambda s, i, acc: [s._op("add", i, s.constant(np.int32(1))),
                           s._op("mul", acc, s.constant(np.float32(2.0)))],
    )
    res = sd.output({"x": np.float32(3.0)}, [outs[0].name, outs[1].name])
    assert int(res[outs[0].name]) == 5
    assert float(res[outs[1].name]) == pytest.approx(96.0)  # 3 * 2^5


def test_native_if_cond_both_branches():
    sd = SameDiff.create()
    p = sd.placeholder("p", dtype="bool")
    a = sd.placeholder("a")
    outs = sd.ifCond(
        p, [a],
        lambda s, x: s._op("mul", x, s.constant(np.float32(10.0))),
        lambda s, x: s._op("neg", x),
    )
    name = outs[0].name
    assert float(sd.output({"p": True, "a": np.float32(2.0)}, [name])[name]) == 20.0
    assert float(sd.output({"p": False, "a": np.float32(2.0)}, [name])[name]) == -2.0


def test_native_while_save_load_roundtrip(tmp_path):
    sd = SameDiff.create()
    x = sd.placeholder("x")
    outs = sd.while_loop(
        [sd.constant(np.int32(0)), x],
        lambda s, i, acc: s._op("lt", i, s.constant(np.int32(4))),
        lambda s, i, acc: [s._op("add", i, s.constant(np.int32(1))),
                           s._op("add", acc, acc)],
    )
    path = str(tmp_path / "loop.sdz")
    sd.save(path)
    loaded = SameDiff.load(path)
    got = loaded.output({"x": np.float32(1.5)}, [outs[1].name])[outs[1].name]
    assert float(got) == pytest.approx(1.5 * 16)


def test_native_while_under_full_graph_compile():
    """The loop must live INSIDE the single compiled XLA program."""
    sd = SameDiff.create()
    x = sd.placeholder("x")
    outs = sd.while_loop(
        [sd.constant(np.int32(0)), x],
        lambda s, i, acc: s._op("lt", i, s.constant(np.int32(3))),
        lambda s, i, acc: [s._op("add", i, s.constant(np.int32(1))),
                           s._op("mul", acc, acc)],
    )
    compiled = sd.compile({"x": np.float32(1.1)}, [outs[1].name])
    got = compiled(dict(sd._values), {"x": np.float32(1.1)})[outs[1].name]
    assert float(got) == pytest.approx(1.1 ** 8, rel=1e-5)


# ---------------------------------------------------------------------------
# TF import — functional encoding (tf.function)
# ---------------------------------------------------------------------------

tf = pytest.importorskip("tensorflow")


def _frozen(fn, *specs):
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    cf = tf.function(fn).get_concrete_function(*specs)
    return convert_variables_to_constants_v2(cf)


def _tf_run(frozen, *args):
    out = frozen(*(tf.constant(a) for a in args))
    if isinstance(out, (list, tuple)):
        out = out[0]
    return out.numpy()


def _import_and_run(frozen, feeds):
    from deeplearning4j_tpu.samediff.tf_import import TFGraphMapper

    gd = frozen.graph.as_graph_def()
    out_name = frozen.outputs[0].name.split(":")[0]
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    sd = TFGraphMapper.import_graph(gd, outputs=[out_name])
    res = sd.output(dict(zip(in_names, feeds)), [out_name])
    return np.asarray(res[out_name])


def test_tf2_while_loop_import_matches_tf():
    def fn(x):
        i = tf.constant(0)

        def cond(i, acc):
            return i < 7

        def body(i, acc):
            return i + 1, acc * 1.5 + 0.25

        _, out = tf.while_loop(cond, body, [i, x])
        return out

    frozen = _frozen(fn, tf.TensorSpec((3,), tf.float32))
    x = np.asarray([1.0, -2.0, 0.5], np.float32)
    expected = _tf_run(frozen, x)
    got = _import_and_run(frozen, [x])
    np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_tf2_cond_import_matches_tf():
    def fn(x):
        return tf.cond(
            tf.reduce_sum(x) > 0.0,
            lambda: x * 2.0 + 1.0,
            lambda: -x,
        )

    frozen = _frozen(fn, tf.TensorSpec((4,), tf.float32))
    for x in (np.asarray([1, 2, 3, 4], np.float32),
              np.asarray([-1, -2, -3, -4], np.float32)):
        expected = _tf_run(frozen, x)
        got = _import_and_run(frozen, [x])
        np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_tf2_while_with_matmul_state():
    """Loop carrying a matrix through matmuls — the RNN-shaped case."""
    w = np.random.RandomState(0).randn(4, 4).astype(np.float32) * 0.3

    def fn(x):
        def cond(i, h):
            return i < 5

        def body(i, h):
            return i + 1, tf.tanh(h @ tf.constant(w))

        _, out = tf.while_loop(cond, body, [tf.constant(0), x])
        return out

    frozen = _frozen(fn, tf.TensorSpec((2, 4), tf.float32))
    x = np.random.RandomState(1).randn(2, 4).astype(np.float32)
    expected = _tf_run(frozen, x)
    got = _import_and_run(frozen, [x])
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# TF import — legacy V1 dataflow encoding
# ---------------------------------------------------------------------------

def test_v1_while_loop_frames_import_matches_tf():
    """tf.compat.v1.while_loop emits raw Enter/Merge/Switch/Exit/
    NextIteration/LoopCond nodes; the importer rewrites the frame into a
    functional While and compiles it to one lax.while_loop."""
    from deeplearning4j_tpu.samediff.tf_import import TFGraphMapper

    tf.compat.v1.disable_control_flow_v2()  # force the Enter/Merge encoding
    try:
        with tf.Graph().as_default() as g:
            x = tf.compat.v1.placeholder(tf.float32, (3,), name="x")
            i0 = tf.constant(0, name="i0")

            def cond(i, acc):
                return i < 6

            def body(i, acc):
                return i + 1, acc * 2.0

            _, out = tf.compat.v1.while_loop(cond, body, [i0, x], name="loop")
            out = tf.identity(out, name="result")
            with tf.compat.v1.Session(graph=g) as sess:
                xv = np.asarray([1.0, -0.5, 3.0], np.float32)
                expected = sess.run(out, {x: xv})
            gd = g.as_graph_def()
    finally:
        tf.compat.v1.enable_control_flow_v2()

    assert any(n.op == "Enter" for n in gd.node)  # really the V1 encoding
    sd = TFGraphMapper.import_graph(gd, outputs=["result"])
    got = np.asarray(sd.output({"x": xv}, ["result"])["result"])
    np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_v1_cond_switch_merge_import_matches_tf():
    """tf.compat.v1.cond emits frameless Switch/Merge; the importer lowers
    Merge to where(pred, true, false)."""
    from deeplearning4j_tpu.samediff.tf_import import TFGraphMapper

    tf.compat.v1.disable_control_flow_v2()  # force the Switch/Merge encoding
    try:
        with tf.Graph().as_default() as g:
            x = tf.compat.v1.placeholder(tf.float32, (4,), name="x")
            pred = tf.reduce_sum(x) > 0.0
            out = tf.compat.v1.cond(pred, lambda: x * 3.0, lambda: x - 1.0)
            out = tf.identity(out, name="result")
            gd = g.as_graph_def()
            with tf.compat.v1.Session(graph=g) as sess:
                xs = [np.asarray([1, 1, 1, 1], np.float32),
                      np.asarray([-1, -1, -1, -1], np.float32)]
                expecteds = [sess.run(out, {x: xv}) for xv in xs]
    finally:
        tf.compat.v1.enable_control_flow_v2()

    assert any(n.op == "Switch" for n in gd.node)
    sd = TFGraphMapper.import_graph(gd, outputs=["result"])
    for xv, expected in zip(xs, expecteds):
        got = np.asarray(sd.output({"x": xv}, ["result"])["result"])
        np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_bounded_while_is_differentiable():
    """max_iters lowers the loop to lax.scan: same forward values, and
    reverse-mode gradients flow (lax.while_loop cannot do this — training
    through loops needs the bounded form)."""
    import jax
    import numpy as np

    from deeplearning4j_tpu.samediff import SameDiff

    def build(max_iters):
        sd = SameDiff.create()
        x = sd.var("x", np.asarray([2.0], np.float32))
        i0 = sd.constant(np.asarray(0, np.int32), name="i0")
        outs = sd.while_loop(
            [i0, x],
            lambda s, i, a: s.math.lt(
                i, s.constant(np.asarray(3, np.int32))),
            lambda s, i, a: [
                s.math.add(i, s.constant(np.asarray(1, np.int32))),
                s.math.mul(a, a)],
            max_iters=max_iters)
        loss = sd.math.reduce_sum(outs[1])
        sd.set_loss_variables(loss.name)
        return sd, outs[1]

    # forward parity: bounded == unbounded (x^(2^3) = 256)
    sd_b, y_b = build(max_iters=8)
    sd_u, y_u = build(max_iters=None)
    vb = float(np.asarray(sd_b.output({}, [y_b.name])[y_b.name])[0])
    vu = float(np.asarray(sd_u.output({}, [y_u.name])[y_u.name])[0])
    assert vb == vu == 256.0

    # gradient flows through the bounded form: d(x^8)/dx = 8 x^7 = 1024
    grads = sd_b.calculate_gradients({}, ["x"])
    g = float(np.asarray(list(grads.values())[0])[0])
    np.testing.assert_allclose(g, 8 * 2.0 ** 7, rtol=1e-5)

    # the unbounded form still fails loudly (jax's documented limitation)
    import pytest as _pytest
    with _pytest.raises(ValueError, match="Reverse-mode"):
        sd_u.calculate_gradients({}, ["x"])


def test_bounded_while_gradient_safe_past_exit():
    """The bounded lowering must not evaluate the body on the frozen carry
    after exit: a sqrt whose domain the loop condition guards would turn
    gradients NaN under a where-select lowering (0 * inf in the dead
    branch's VJP); the lax.cond lowering keeps them finite."""
    import numpy as np

    from deeplearning4j_tpu.samediff import SameDiff

    sd = SameDiff.create()
    x = sd.var("x", np.asarray([9.0], np.float32))
    i0 = sd.constant(np.asarray(0, np.int32), name="i0")
    # body: a <- sqrt(a); cond: a > 1.1  (sqrt repeatedly -> exits at ~1.07;
    # more iterations would drive d/da sqrt toward the steep region)
    outs = sd.while_loop(
        [i0, x],
        lambda s, i, a: s.math.gt(
            s.math.reduce_sum(a), s.constant(np.asarray(1.1, np.float32))),
        lambda s, i, a: [
            s.math.add(i, s.constant(np.asarray(1, np.int32))),
            s.math.sqrt(a)],
        max_iters=50)  # far beyond the ~5 real iterations
    loss = sd.math.reduce_sum(outs[1])
    sd.set_loss_variables(loss.name)
    grads = sd.calculate_gradients({}, ["x"])
    g = np.asarray(list(grads.values())[0])
    assert np.all(np.isfinite(g)), f"NaN/inf gradient through bounded loop: {g}"


def test_unbounded_while_greedy_decode_import_matches_tf():
    """The serving use-case (VERDICT r4 ask 8, SURVEY.md:243-245): a
    DATA-DEPENDENT tf.while_loop — greedy decode until EOS with a
    max-length guard — imports to an unbounded ``lax.while_loop`` and runs
    forward-only, matching TF CPU exactly. No max_iters lowering: the trip
    count depends on the decoded tokens."""
    V, L, EOS = 13, 16, 0
    rng = np.random.RandomState(42)
    w = (rng.randn(V, V) * 2.0).astype(np.float32)
    w[:, EOS] -= 1.0  # make EOS reachable but not immediate

    def fn(start):
        def cond(i, tok, buf):
            return tf.logical_and(i < L, tok[0] != EOS)

        def body(i, tok, buf):
            logits = tf.one_hot(tok, V) @ tf.constant(w)          # [1, V]
            nxt = tf.cast(tf.argmax(logits, axis=-1), tf.int32)   # [1]
            buf = buf + tf.one_hot(i, L, dtype=tf.int32)[None, :] * nxt[:, None]
            return i + 1, nxt, buf

        i, tok, buf = tf.while_loop(
            cond, body,
            [tf.constant(0), start, tf.zeros([1, L], tf.int32)])
        return buf

    frozen = _frozen(fn, tf.TensorSpec((1,), tf.int32))
    decoded = {}
    for start in range(1, V):
        x = np.asarray([start], np.int32)
        expected = _tf_run(frozen, x)
        got = _import_and_run(frozen, [x])
        np.testing.assert_array_equal(got, expected)
        decoded[start] = expected
    # the loop must actually be data-dependent: different starts produce
    # different-length outputs, and at least one stops early via EOS
    lens = {s: int((d != 0).sum()) for s, d in decoded.items()}
    assert len(set(lens.values())) > 1, lens
