"""ComputationGraph, vertices, zoo, transfer-learning tests."""

import numpy as np
import pytest

from deeplearning4j_tpu.core import from_json, to_json
from deeplearning4j_tpu.nn import (
    Activation,
    InputType,
    LossFunction,
    NeuralNetConfiguration,
    WeightInit,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    ConvolutionLayer,
    DenseLayer,
    GlobalPoolingLayer,
    LSTMLayer,
    OutputLayer,
    PoolingType,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration,
    TransferLearning,
)
from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
from deeplearning4j_tpu.nn.vertices import (
    ElementWiseOp,
    ElementWiseVertex,
    L2NormalizeVertex,
    MergeVertex,
    SubsetVertex,
)
from deeplearning4j_tpu.train import Adam
from deeplearning4j_tpu.utils import check_gradients


def two_input_graph(seed=1):
    return (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Adam(1e-2))
        .graph_builder()
        .add_inputs("in1", "in2")
        .add_layer("d1", DenseLayer(n_out=8, activation=Activation.TANH), "in1")
        .add_layer("d2", DenseLayer(n_out=8, activation=Activation.TANH), "in2")
        .add_vertex("merge", MergeVertex(), "d1", "d2")
        .add_layer("out", OutputLayer(n_out=2), "merge")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(4), InputType.feed_forward(3))
        .build()
    )


def residual_graph(seed=2, dtype="float32"):
    return (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .data_type(dtype)
        .updater(Adam(1e-2))
        .graph_builder()
        .add_inputs("input")
        .add_layer("d1", DenseLayer(n_out=6, activation=Activation.TANH), "input")
        .add_layer("d2", DenseLayer(n_out=6, activation=Activation.IDENTITY), "d1")
        .add_vertex("residual", ElementWiseVertex(op=ElementWiseOp.ADD), "d1", "d2")
        .add_layer("relu", ActivationLayer(activation=Activation.RELU), "residual")
        .add_layer("out", OutputLayer(n_out=2), "relu")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(5))
        .build()
    )


class TestGraphBuild:
    def test_topology_and_shapes(self):
        conf = two_input_graph()
        assert conf.spec("d1").layer.n_in == 4
        assert conf.spec("d2").layer.n_in == 3
        assert conf.spec("out").layer.n_in == 16

    def test_json_round_trip(self):
        conf = two_input_graph()
        assert from_json(to_json(conf)) == conf

    def test_cycle_detection(self):
        g = (
            NeuralNetConfiguration.builder().graph_builder()
            .add_inputs("in")
            .add_layer("a", DenseLayer(n_in=4, n_out=4), "b")
            .add_layer("b", DenseLayer(n_in=4, n_out=4), "a")
            .set_outputs("b")
        )
        with pytest.raises(ValueError, match="cycle"):
            g.build()

    def test_resnet50_builds(self):
        from deeplearning4j_tpu.model.zoo import ResNet50

        m = ResNet50(num_classes=10, height=32, width=32, channels=3).init()
        # reference ResNet-50 is ~23.5M params at 10 classes
        assert 23_000_000 < m.num_params() < 24_000_000

    def test_vgg16_param_count(self):
        from deeplearning4j_tpu.model.zoo import VGG16

        conf = VGG16(num_classes=10, height=32, width=32).conf()
        # VGG16 at 32x32: conv stack 14.7M + fc (512*4096 + 4096^2 + ...)
        from deeplearning4j_tpu.nn import MultiLayerNetwork

        m = MultiLayerNetwork(conf).init()
        assert m.num_params() > 30_000_000


class TestGraphTraining:
    def test_multi_input_learns(self):
        m = ComputationGraph(two_input_graph()).init()
        rng = np.random.default_rng(0)
        x1 = rng.normal(size=(32, 4)).astype(np.float32)
        x2 = rng.normal(size=(32, 3)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x1.sum(1) > 0).astype(int)]
        s0 = m.score((x1, x2), y)
        m.fit((x1, x2), y, epochs=40)
        assert m.score((x1, x2), y) < s0 * 0.5

    def test_residual_learns(self):
        m = ComputationGraph(residual_graph()).init()
        rng = np.random.default_rng(1)
        x = rng.normal(size=(32, 5)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
        s0 = m.score(x, y)
        m.fit(x, y, epochs=40)
        assert m.score(x, y) < s0 * 0.5

    def test_multi_output(self):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(3)
            .updater(Adam(1e-2))
            .graph_builder()
            .add_inputs("in")
            .add_layer("trunk", DenseLayer(n_out=8, activation=Activation.TANH), "in")
            .add_layer("out1", OutputLayer(n_out=2), "trunk")
            .add_layer("out2", OutputLayer(n_out=3, loss=LossFunction.MSE,
                                           activation=Activation.IDENTITY), "trunk")
            .set_outputs("out1", "out2")
            .set_input_types(InputType.feed_forward(4))
            .build()
        )
        m = ComputationGraph(conf).init()
        rng = np.random.default_rng(2)
        x = rng.normal(size=(16, 4)).astype(np.float32)
        y1 = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
        y2 = rng.normal(size=(16, 3)).astype(np.float32)
        s0 = m.score(x, (y1, y2))
        m.fit(x, (y1, y2), epochs=30)
        assert m.score(x, (y1, y2)) < s0
        o1, o2 = m.output(x)
        assert o1.shape == (16, 2) and o2.shape == (16, 3)

    def test_graph_gradients(self):
        conf = residual_graph(dtype="float64")
        m = ComputationGraph(conf).init()
        x = np.random.default_rng(3).normal(size=(4, 5))
        y = np.eye(2)[np.arange(4) % 2]

        class Shim:
            """Adapter so check_gradients drives the graph."""

            def __init__(self, g):
                self.g = g
                self.dtype = g.dtype
                self.params = g.params
                self.state = g.state

            def calculate_gradients(self, f, l, mask=None, label_mask=None):
                return self.g.calculate_gradients(f, l)

            def loss_pure(self, p, s, f, l, rng=None, mask=None, label_mask=None, train=True):
                loss, st = self.g.loss_pure(p, s, (f,), (l,), rng=rng, train=train)
                return loss, st

        assert check_gradients(Shim(m), x, y)


class TestVertices:
    def test_subset_vertex(self):
        import jax.numpy as jnp

        v = SubsetVertex(range_from=1, range_to=2)
        out = v.apply(jnp.arange(12.0).reshape(3, 4))
        assert out.shape == (3, 2)
        np.testing.assert_allclose(np.asarray(out)[0], [1.0, 2.0])

    def test_l2_normalize(self):
        import jax.numpy as jnp

        v = L2NormalizeVertex()
        out = np.asarray(v.apply(jnp.array([[3.0, 4.0]])))
        np.testing.assert_allclose(out, [[0.6, 0.8]], rtol=1e-6)

    def test_elementwise_ops(self):
        import jax.numpy as jnp

        a, b = jnp.ones((2, 3)), 2 * jnp.ones((2, 3))
        assert np.asarray(ElementWiseVertex(op=ElementWiseOp.ADD).apply(a, b))[0, 0] == 3
        assert np.asarray(ElementWiseVertex(op=ElementWiseOp.PRODUCT).apply(a, b))[0, 0] == 2
        assert np.asarray(ElementWiseVertex(op=ElementWiseOp.MAX).apply(a, b))[0, 0] == 2


class TestTransferLearning:
    def _base_model(self):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(5)
            .updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(DenseLayer(n_out=6, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(4))
            .build()
        )
        m = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[np.arange(16) % 3]
        m.fit(x, y, epochs=5)
        return m

    def test_freeze_and_replace_output(self):
        base = self._base_model()
        w0_before = np.asarray(base.params["layer_0"]["W"]).copy()
        new = (
            TransferLearning.Builder(base)
            .fine_tune_configuration(FineTuneConfiguration(updater=Adam(1e-3)))
            .set_feature_extractor(1)
            .n_out_replace(2, 5)
            .build()
        )
        assert new.conf.layers[0].frozen and new.conf.layers[1].frozen
        assert new.conf.layers[2].n_out == 5
        # pretrained weights carried over
        np.testing.assert_array_equal(np.asarray(new.params["layer_0"]["W"]), w0_before)
        # frozen layers do not move during training
        rng = np.random.default_rng(1)
        x = rng.normal(size=(16, 4)).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[np.arange(16) % 5]
        new.fit(x, y, epochs=3)
        np.testing.assert_array_equal(np.asarray(new.params["layer_0"]["W"]), w0_before)
        assert new.output(x).shape == (16, 5)

    def test_add_layer(self):
        base = self._base_model()
        new = (
            TransferLearning.Builder(base)
            .remove_output_layer()
            .add_layer(DenseLayer(n_out=4, activation=Activation.RELU))
            .add_layer(OutputLayer(n_out=2))
            .build()
        )
        assert len(new.conf.layers) == 4
        x = np.random.default_rng(2).normal(size=(8, 4)).astype(np.float32)
        assert new.output(x).shape == (8, 2)
