"""Layer-helper SPI (reference: LayerHelper/cuDNN seam, SURVEY.md §2.2
"Helper SPI"): pluggable conv2d and LSTM implementations must agree with
the builtin path — the ValidateCuDNN parity pattern — and be switchable."""

import numpy as np
import pytest

from deeplearning4j_tpu.ops import (
    available_helpers,
    helper_name,
    set_helper,
)


@pytest.fixture(autouse=True)
def _restore_helpers():
    yield
    set_helper("conv2d", "xla")
    set_helper("lstm", "scan")


def _conv_net():
    from deeplearning4j_tpu.nn import (
        Activation, InputType, LossFunction, NeuralNetConfiguration, WeightInit,
    )
    from deeplearning4j_tpu.nn.layers import (
        ConvolutionLayer, ConvolutionMode, OutputLayer,
    )
    from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(11)
            .weight_init(WeightInit.XAVIER).list()
            .layer(ConvolutionLayer(n_out=6, kernel_size=(3, 3), stride=(2, 2),
                                    convolution_mode=ConvolutionMode.SAME,
                                    activation=Activation.RELU))
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    dilation=(2, 2),
                                    activation=Activation.IDENTITY))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.convolutional(12, 12, 2)).build())
    return MultiLayerNetwork(conf).init()


def test_conv_helpers_registered():
    assert set(available_helpers("conv2d")) >= {"xla", "im2col"}
    assert set(available_helpers("lstm")) >= {"scan", "unrolled"}
    assert helper_name("conv2d") == "xla"


def test_conv2d_im2col_matches_xla():
    net = _conv_net()
    x = np.random.RandomState(0).rand(3, 2, 12, 12).astype(np.float32)
    set_helper("conv2d", "xla")
    y_xla = np.asarray(net.output(x))
    set_helper("conv2d", "im2col")
    y_gemm = np.asarray(net.output(x))
    np.testing.assert_allclose(y_gemm, y_xla, rtol=1e-5, atol=1e-6)


def test_lstm_unrolled_matches_scan():
    from deeplearning4j_tpu.nn import (
        Activation, InputType, LossFunction, NeuralNetConfiguration, WeightInit,
    )
    from deeplearning4j_tpu.nn.layers import LSTMLayer, RnnOutputLayer
    from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(12)
            .weight_init(WeightInit.XAVIER).list()
            .layer(LSTMLayer(n_out=5))
            .layer(RnnOutputLayer(n_out=2, loss=LossFunction.MCXENT,
                                  activation=Activation.SOFTMAX))
            .set_input_type(InputType.recurrent(3, 6)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.RandomState(1).rand(2, 3, 6).astype(np.float32)
    mask = np.asarray([[1, 1, 1, 1, 0, 0], [1, 1, 1, 1, 1, 1]], np.float32)

    set_helper("lstm", "scan")
    y_scan = np.asarray(net.output(x, mask=mask))
    set_helper("lstm", "unrolled")
    y_unrolled = np.asarray(net.output(x, mask=mask))
    np.testing.assert_allclose(y_unrolled, y_scan, rtol=1e-5, atol=1e-6)


def test_unknown_helper_rejected():
    with pytest.raises(ValueError, match="unknown helper"):
        set_helper("conv2d", "nope")
    with pytest.raises(ValueError, match="no helpers registered"):
        set_helper("nothere", "x")
