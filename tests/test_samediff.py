"""SameDiff graph engine tests: build, execute, autodiff, train, save/load,
AOT compile."""

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.samediff import SameDiff, TrainingConfig
from deeplearning4j_tpu.train.updaters import Adam


class TestBasic:
    def test_arith_and_eval(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (None, 3))
        w = sd.var("w", np.ones((3, 2), np.float32))
        b = sd.var("b", np.zeros((2,), np.float32))
        y = (x @ w + b).rename("y")
        out = y.eval({"x": np.ones((4, 3), np.float32)})
        np.testing.assert_allclose(out, 3.0)

    def test_namespaced_ops(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (2, 4))
        sm = sd.nn.softmax(x).rename("sm")
        out = sm.eval({"x": np.zeros((2, 4), np.float32)})
        np.testing.assert_allclose(out, 0.25)

    def test_reductions_and_chaining(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (2, 3))
        total = x.mul(2.0).sum().rename("total")
        assert total.eval({"x": np.ones((2, 3), np.float32)}) == pytest.approx(12.0)

    def test_multi_output_reuse(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (2, 2))
        a = (x + 1.0).rename("a")
        b = (a * a).rename("b")
        res = sd.output({"x": np.zeros((2, 2), np.float32)}, ["a", "b"])
        np.testing.assert_allclose(np.asarray(res["a"]), 1.0)
        np.testing.assert_allclose(np.asarray(res["b"]), 1.0)


class TestGradients:
    def test_simple_grad(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (3,))
        w = sd.var("w", np.array([1.0, 2.0, 3.0], np.float32))
        loss = (x * w).sum().rename("loss")
        sd.set_loss_variables("loss")
        g = sd.calculate_gradients({"x": np.array([1.0, 1.0, 2.0], np.float32)}, ["w"])
        np.testing.assert_allclose(np.asarray(g["w"]), [1.0, 1.0, 2.0])

    def test_matmul_grad_matches_numeric(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (4, 3))
        w = sd.var("w", np.random.default_rng(0).normal(size=(3, 2)).astype(np.float64))
        loss = sd.nn.softmax(x @ w).sum().rename("loss")
        sd.set_loss_variables("loss")
        feeds = {"x": np.random.default_rng(1).normal(size=(4, 3))}
        g = np.asarray(sd.calculate_gradients(feeds, ["w"])["w"])
        # numeric check
        w0 = np.asarray(sd._values[sd._names["w"]]).copy()
        eps = 1e-6
        num = np.zeros_like(w0)
        for i in range(w0.shape[0]):
            for j in range(w0.shape[1]):
                for sgn in (1, -1):
                    w0[i, j] += sgn * eps
                    sd._values[sd._names["w"]] = w0.copy()
                    val = float(sd.output(feeds, ["loss"])["loss"].sum())
                    num[i, j] += sgn * val / (2 * eps)
                    w0[i, j] -= sgn * eps
        sd._values[sd._names["w"]] = w0
        np.testing.assert_allclose(g, num, rtol=1e-4, atol=1e-6)


class TestTraining:
    def test_linear_regression_converges(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(128, 3)).astype(np.float32)
        true_w = np.array([[1.5], [-2.0], [0.5]], np.float32)
        Y = X @ true_w + 0.01 * rng.normal(size=(128, 1)).astype(np.float32)

        sd = SameDiff.create()
        x = sd.placeholder("x", (None, 3))
        label = sd.placeholder("label", (None, 1))
        w = sd.var("w", np.zeros((3, 1), np.float32))
        b = sd.var("b", np.zeros((1,), np.float32))
        pred = (x @ w + b).rename("pred")
        loss = sd.loss.mean_squared_error(label, pred).rename("loss")
        sd.set_loss_variables("loss")

        cfg = TrainingConfig(
            updater=Adam(1e-1),
            data_set_feature_mapping=("x",),
            data_set_label_mapping=("label",),
        )
        it = ListDataSetIterator(DataSet(X, Y), batch=32)
        hist = sd.fit(it, cfg, epochs=50)
        assert hist.loss_curve[-1] < 0.01
        np.testing.assert_allclose(
            np.asarray(sd._values[sd._names["w"]]), true_w, atol=0.1
        )

    def test_loss_curve_survives_midfit_exception(self):
        """An exception mid-fit must not lose the loss curve recorded so
        far (ADVICE round-5 item 3): losses flush per epoch and in a
        finally, and the session keeps the partial History."""
        rng = np.random.default_rng(0)
        X = rng.normal(size=(8, 3)).astype(np.float32)
        Y = rng.normal(size=(8, 1)).astype(np.float32)

        sd = SameDiff.create()
        x = sd.placeholder("x", (None, 3))
        label = sd.placeholder("label", (None, 1))
        w = sd.var("w", np.zeros((3, 1), np.float32))
        pred = (x @ w).rename("pred")
        sd.loss.mean_squared_error(label, pred).rename("loss")
        sd.set_loss_variables("loss")
        cfg = TrainingConfig(
            updater=Adam(1e-2),
            data_set_feature_mapping=("x",),
            data_set_label_mapping=("label",),
        )

        class ExplodingIterator:
            """Yields 3 good batches, then simulates a data-source death."""

            def __iter__(self):
                def gen():
                    for i in range(3):
                        yield DataSet(X, Y)
                    raise KeyboardInterrupt("data source died")

                return gen()

        with pytest.raises(KeyboardInterrupt):
            sd.fit(ExplodingIterator(), cfg, epochs=1)
        hist = sd._training.last_history
        assert hist is not None
        assert len(hist.loss_curve) == 3  # the 3 completed steps survived
        assert all(np.isfinite(v) for v in hist.loss_curve)


class TestSerde:
    def test_save_load_round_trip(self, tmp_path):
        sd = SameDiff.create()
        x = sd.placeholder("x", (None, 3))
        w = sd.var("w", np.random.default_rng(2).normal(size=(3, 2)).astype(np.float32))
        y = sd.nn.softmax(x @ w).rename("y")
        feeds = {"x": np.random.default_rng(3).normal(size=(5, 3)).astype(np.float32)}
        before = y.eval(feeds)

        path = str(tmp_path / "graph.sdz")
        sd.save(path)
        sd2 = SameDiff.load(path)
        after = sd2.get_variable("y").eval(feeds)
        np.testing.assert_allclose(before, after, rtol=1e-6)

    def test_aot_compile(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (4, 3))
        w = sd.var("w", np.ones((3, 2), np.float32))
        (x @ w).rename("y")
        feeds = {"x": np.ones((4, 3), np.float32)}
        compiled = sd.compile(feeds, ["y"])
        out = compiled(dict(sd._values), feeds)
        np.testing.assert_allclose(np.asarray(out["y"]), 3.0)


class TestOpsCoverage:
    def test_shape_ops(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (2, 6))
        r = sd.math.reshape(x, shape=[2, 2, 3]).rename("r")
        t = sd.math.transpose(r, perm=[0, 2, 1]).rename("t")
        out = sd.output({"x": np.arange(12, dtype=np.float32).reshape(2, 6)}, ["t"])
        assert out["t"].shape == (2, 3, 2)

    def test_gather_onehot(self):
        sd = SameDiff.create()
        idx = sd.placeholder("idx", (3,), dtype="int32")
        table = sd.var("table", np.arange(12, dtype=np.float32).reshape(4, 3))
        g = sd.math.gather(table, idx, axis=0).rename("g")
        oh = sd.math.one_hot(idx, depth=4).rename("oh")
        out = sd.output({"idx": np.array([0, 2, 3], np.int32)}, ["g", "oh"])
        np.testing.assert_allclose(np.asarray(out["g"])[1], [6, 7, 8])
        assert np.asarray(out["oh"]).shape == (3, 4)

    def test_strided_slice(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (4, 5))
        s = sd.math.strided_slice(x, begin=[1, 0], end=[3, 4], strides=[1, 2]).rename("s")
        out = s.eval({"x": np.arange(20, dtype=np.float32).reshape(4, 5)})
        assert out.shape == (2, 2)
        np.testing.assert_allclose(out, [[5, 7], [10, 12]])

    def test_layer_norm_and_erf(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (2, 8))
        ln = sd.nn.layer_norm(x).rename("ln")
        e = sd.math.erf(x).rename("e")
        out = sd.output({"x": np.random.default_rng(4).normal(size=(2, 8)).astype(np.float32)}, ["ln", "e"])
        assert abs(float(np.asarray(out["ln"]).mean())) < 1e-5
