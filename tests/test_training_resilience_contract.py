"""Tier-1 wiring for tools/check_training_resilience_contract.py: the
fault-tolerant-training chaos contract (README.md "Fault-tolerant
training") — SIGKILL a real child trainer at a random mid-epoch
iteration and resume bit-identically with a provably non-overlapping /
non-skipping consumed-batch sequence, SIGTERM checkpoints and exits
PREEMPTED_EXIT_CODE with zero lost iterations, and an injected stall
takes the watchdog path — is enforced on every test run, not just when
someone remembers to run the tool."""

import os
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


def test_training_resilience_contract_smoke():
    sys.path.insert(0, _TOOLS)
    try:
        import check_training_resilience_contract
    finally:
        sys.path.remove(_TOOLS)
    assert check_training_resilience_contract.main(log=lambda m: None) == 0
