"""Extended SameDiff op families vs independent references
(SURVEY.md §2.1 op breadth). One representative per family plus the
tricky-semantics ops (segment, space/batch, cells, color, CTC)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.samediff.ops import SD_OPS, get_sd_op


def op(name, *args, **kw):
    return np.asarray(get_sd_op(name)(*[jnp.asarray(a) for a in args], **kw))


def test_registry_breadth():
    assert len(SD_OPS) >= 290, f"op registry shrank: {len(SD_OPS)}"


def test_special_functions():
    # identities (no scipy in the image): erfinv(erf(x)) == x, lgamma vs
    # factorial, xlogy zero handling
    x = np.asarray([0.1, 0.5, 0.9])
    np.testing.assert_allclose(op("erfinv", op("erf", x)), x, rtol=1e-4)
    np.testing.assert_allclose(op("lgamma", np.asarray([5.0])),
                               [np.log(24.0)], rtol=1e-6)
    np.testing.assert_allclose(
        op("xlogy", np.asarray([0.0, 2.0]), np.asarray([5.0, 3.0])),
        [0.0, 2.0 * np.log(3.0)], rtol=1e-6)
    np.testing.assert_allclose(op("frac", np.asarray([1.75, -1.75])),
                               [0.75, -0.75], rtol=1e-6)


def test_reductions_and_index():
    x = np.asarray([[1.0, -5.0, 3.0], [2.0, 0.5, -0.1]])
    np.testing.assert_allclose(op("logsumexp", x, axis=1),
                               np.log(np.exp(x).sum(axis=1)), rtol=1e-6)
    assert op("iamax", x, axis=1).tolist() == [1, 0]
    np.testing.assert_allclose(op("amean", x, axis=1),
                               np.abs(x).mean(axis=1))
    np.testing.assert_allclose(op("reduce_median", x, axis=1),
                               np.median(x, axis=1))
    m, v = get_sd_op("moments")(jnp.asarray(x), axis=1)
    np.testing.assert_allclose(np.asarray(m), x.mean(axis=1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(v), x.var(axis=1), rtol=1e-6)


def test_confusion_matrix_op():
    got = op("confusion_matrix", np.asarray([0, 1, 2, 1]),
             np.asarray([0, 2, 2, 1]), num_classes=3)
    expect = np.zeros((3, 3))
    for t, p in [(0, 0), (1, 2), (2, 2), (1, 1)]:
        expect[t, p] += 1
    np.testing.assert_array_equal(got, expect)


def test_segment_ops():
    data = np.asarray([1.0, 2.0, 3.0, 4.0, 5.0])
    ids = np.asarray([0, 0, 1, 2, 2])
    np.testing.assert_allclose(
        op("segment_sum", data, ids, num_segments=3), [3.0, 3.0, 9.0])
    np.testing.assert_allclose(
        op("segment_mean", data, ids, num_segments=3), [1.5, 3.0, 4.5])
    np.testing.assert_allclose(
        op("segment_max", data, ids, num_segments=3), [2.0, 3.0, 5.0])


def test_sort_topk():
    x = np.asarray([[3.0, 1.0, 4.0, 1.5]])
    np.testing.assert_allclose(op("sort", x, descending=True),
                               [[4.0, 3.0, 1.5, 1.0]])
    vals, idx = get_sd_op("top_k")(jnp.asarray(x), k=2)
    np.testing.assert_allclose(np.asarray(vals), [[4.0, 3.0]])
    assert np.asarray(idx).tolist() == [[2, 0]]
    hit = op("in_top_k", x, np.asarray([2]), k=1)
    assert hit.tolist() == [True]


def test_space_depth_batch_roundtrips():
    rng = np.random.RandomState(0)
    x = rng.rand(2, 4, 6, 3).astype(np.float32)  # NHWC
    sd = op("space_to_depth", x, block_size=2, data_format="NHWC")
    assert sd.shape == (2, 2, 3, 12)
    back = op("depth_to_space", sd, block_size=2, data_format="NHWC")
    np.testing.assert_allclose(back, x)

    import tensorflow as tf
    expect = tf.nn.space_to_depth(x, 2).numpy()
    np.testing.assert_allclose(sd, expect)

    s2b = op("space_to_batch", x, block_shape=[2, 2], paddings=[(0, 0), (0, 0)])
    expect2 = tf.space_to_batch(x, [2, 2], [[0, 0], [0, 0]]).numpy()
    np.testing.assert_allclose(s2b, expect2)
    b2s = op("batch_to_space", s2b, block_shape=[2, 2], crops=[(0, 0), (0, 0)])
    np.testing.assert_allclose(b2s, x)


def test_conv_variants_vs_torch():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    rng = np.random.RandomState(1)
    # conv1d NWC vs torch NCW
    x = rng.randn(2, 10, 3).astype(np.float32)
    w = rng.randn(3, 3, 5).astype(np.float32)  # [kW, in, out]
    got = op("conv1d", x, w, stride=1, padding="VALID")
    expect = F.conv1d(torch.from_numpy(x.transpose(0, 2, 1)),
                      torch.from_numpy(w.transpose(2, 1, 0))).numpy()
    np.testing.assert_allclose(got, expect.transpose(0, 2, 1), rtol=1e-4,
                               atol=1e-5)

    # deconv2d NHWC vs torch conv_transpose2d NCHW; ours takes the
    # forward-conv kernel [kH, kW, out, in], torch takes [in, out, kH, kW]
    x2 = rng.randn(1, 5, 5, 4).astype(np.float32)
    w2 = rng.randn(3, 3, 6, 4).astype(np.float32)
    got2 = op("deconv2d", x2, w2, strides=(2, 2), padding="VALID")
    expect2 = F.conv_transpose2d(
        torch.from_numpy(x2.transpose(0, 3, 1, 2)),
        torch.from_numpy(w2.transpose(3, 2, 0, 1)), stride=2).numpy()
    np.testing.assert_allclose(got2, expect2.transpose(0, 2, 3, 1), rtol=1e-4,
                               atol=1e-5)


def test_pool_variants():
    rng = np.random.RandomState(2)
    x = rng.randn(1, 8, 3).astype(np.float32)
    got = op("max_pool1d", x, kernel=2, strides=2)
    np.testing.assert_allclose(got, x.reshape(1, 4, 2, 3).max(axis=2))
    got_a = op("avg_pool1d", x, kernel=2, strides=2)
    np.testing.assert_allclose(got_a, x.reshape(1, 4, 2, 3).mean(axis=2),
                               rtol=1e-6)
    x3 = rng.randn(1, 4, 4, 4, 2).astype(np.float32)
    got3 = op("max_pool3d", x3, kernel=(2, 2, 2), strides=(2, 2, 2))
    assert got3.shape == (1, 2, 2, 2, 2)


def test_lstm_gru_cells_vs_torch():
    torch = pytest.importorskip("torch")

    rng = np.random.RandomState(3)
    B, I, U = 2, 4, 3
    x = rng.randn(B, I).astype(np.float32)
    h = rng.randn(B, U).astype(np.float32)
    c = rng.randn(B, U).astype(np.float32)
    # ours: [i, f, o, g]; torch LSTMCell: [i, f, g, o]
    Wi = rng.randn(I, 4 * U).astype(np.float32)
    Wh = rng.randn(U, 4 * U).astype(np.float32)
    b = rng.randn(4 * U).astype(np.float32)

    h2, c2 = get_sd_op("lstm_cell")(jnp.asarray(x), jnp.asarray(h),
                                    jnp.asarray(c), jnp.asarray(Wi),
                                    jnp.asarray(Wh), jnp.asarray(b))
    cell = torch.nn.LSTMCell(I, U)
    perm = np.concatenate([np.arange(U), np.arange(U, 2 * U),
                           np.arange(3 * U, 4 * U), np.arange(2 * U, 3 * U)])
    with torch.no_grad():
        cell.weight_ih.copy_(torch.from_numpy(Wi.T[perm]))
        cell.weight_hh.copy_(torch.from_numpy(Wh.T[perm]))
        cell.bias_ih.copy_(torch.from_numpy(b[perm]))
        cell.bias_hh.zero_()
        th, tc = cell(torch.from_numpy(x),
                      (torch.from_numpy(h), torch.from_numpy(c)))
    np.testing.assert_allclose(np.asarray(h2), th.numpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(c2), tc.numpy(), rtol=1e-5, atol=1e-6)


def test_color_space_roundtrip():
    rng = np.random.RandomState(4)
    x = rng.rand(5, 5, 3).astype(np.float32)
    hsv = op("rgb_to_hsv", x)
    back = op("hsv_to_rgb", hsv)
    np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-5)

    import tensorflow as tf
    expect = tf.image.rgb_to_hsv(x).numpy()
    np.testing.assert_allclose(hsv, expect, rtol=1e-4, atol=1e-5)


def test_loss_family():
    labels = np.asarray([1.0, 0.0, 1.0])
    logits = np.asarray([2.0, -1.0, 0.5])
    got = op("hinge_loss", labels, logits)
    expect = np.mean(np.maximum(0, 1 - (2 * labels - 1) * logits))
    np.testing.assert_allclose(got, expect, rtol=1e-6)
    p = np.asarray([[0.7, 0.3], [0.2, 0.8]])
    q = np.asarray([[0.6, 0.4], [0.3, 0.7]])
    np.testing.assert_allclose(op("kl_divergence", p, q),
                               (p * np.log(p / q)).sum(axis=-1), rtol=1e-6)


def test_ctc_loss_finite_and_positive():
    rng = np.random.RandomState(5)
    B, T, C, L = 2, 10, 5, 4
    logp = jax.nn.log_softmax(jnp.asarray(rng.randn(B, T, C), jnp.float32))
    labels = jnp.asarray(rng.randint(1, C, (B, L)), jnp.int32)
    loss = op("ctc_loss", logp, labels,
              np.asarray([10, 8]), np.asarray([4, 3]))
    assert loss.shape == (2,)
    assert np.isfinite(loss).all() and (loss > 0).all()


def test_clip_family():
    x = np.asarray([3.0, 4.0])  # norm 5
    np.testing.assert_allclose(op("clip_by_norm", x, clip_norm=1.0),
                               x / 5.0, rtol=1e-6)
    a, b = get_sd_op("clip_by_global_norm")(
        jnp.asarray([3.0]), jnp.asarray([4.0]), clip_norm=1.0)
    g = np.sqrt(9 + 16)
    np.testing.assert_allclose(np.asarray(a), [3.0 / g], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(b), [4.0 / g], rtol=1e-6)


def test_cells_and_misc_shapes():
    rng = np.random.RandomState(6)
    h = get_sd_op("gru_cell")(
        jnp.asarray(rng.randn(2, 3), jnp.float32),
        jnp.asarray(rng.randn(2, 4), jnp.float32),
        jnp.asarray(rng.randn(3, 12), jnp.float32),
        jnp.asarray(rng.randn(4, 12), jnp.float32))
    assert np.asarray(h).shape == (2, 4)
    np.testing.assert_allclose(op("l2_normalize", np.asarray([[3.0, 4.0]])),
                               [[0.6, 0.8]], rtol=1e-6)
    lrn = op("local_response_normalization", rng.rand(1, 2, 2, 8).astype(np.float32))
    assert lrn.shape == (1, 2, 2, 8)
    up = op("upsampling2d", rng.rand(1, 2, 3, 3).astype(np.float32), scale=2)
    assert up.shape == (1, 2, 6, 6)


# ---------------------------------------------------------------------------
# tranche 2
# ---------------------------------------------------------------------------

def test_sequence_mask():
    got = op("sequence_mask", np.asarray([1, 3, 0]), maxlen=4)
    np.testing.assert_array_equal(
        got, [[1, 0, 0, 0], [1, 1, 1, 0], [0, 0, 0, 0]])


def test_extract_image_patches_vs_tf():
    import tensorflow as tf

    rng = np.random.RandomState(0)
    x = rng.rand(1, 6, 6, 2).astype(np.float32)
    got = op("extract_image_patches", x, ksizes=(3, 3), strides=(2, 2))
    expect = tf.image.extract_patches(
        x, [1, 3, 3, 1], [1, 2, 2, 1], [1, 1, 1, 1], "VALID").numpy()
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_crop_and_resize_vs_tf():
    import tensorflow as tf

    rng = np.random.RandomState(1)
    img = rng.rand(2, 10, 10, 3).astype(np.float32)
    boxes = np.asarray([[0.1, 0.1, 0.8, 0.9], [0.0, 0.0, 1.0, 1.0]], np.float32)
    idx = np.asarray([0, 1], np.int32)
    got = op("crop_and_resize", img, boxes, idx, crop_size=(5, 5))
    expect = tf.image.crop_and_resize(img, boxes, idx, [5, 5]).numpy()
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)


def test_nms_padded():
    boxes = np.asarray([
        [0, 0, 1, 1], [0, 0, 1.05, 1.05], [2, 2, 3, 3], [0, 0, 0.5, 0.5],
    ], np.float32)
    scores = np.asarray([0.9, 0.8, 0.7, 0.6], np.float32)
    idx, valid = get_sd_op("non_max_suppression_padded")(
        jnp.asarray(boxes), jnp.asarray(scores), max_output_size=3,
        iou_threshold=0.5)
    kept = [int(i) for i, v in zip(np.asarray(idx), np.asarray(valid)) if v]
    assert kept[0] == 0          # highest score survives
    assert 1 not in kept         # suppressed by IoU with box 0
    assert 2 in kept             # disjoint box survives


def test_norm_variants():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 4, 5, 5).astype(np.float32)
    inn = op("instance_norm", x)
    np.testing.assert_allclose(inn.mean(axis=(2, 3)), 0.0, atol=1e-5)
    np.testing.assert_allclose(inn.std(axis=(2, 3)), 1.0, atol=1e-3)
    gn = op("group_norm", x, groups=2)
    g = gn.reshape(2, 2, 2, 5, 5)
    np.testing.assert_allclose(g.mean(axis=(2, 3, 4)), 0.0, atol=1e-5)


def test_embedding_and_index_utils():
    table = np.arange(12, dtype=np.float32).reshape(4, 3)
    got = op("embedding_lookup", table, np.asarray([2, 0]))
    np.testing.assert_array_equal(got, table[[2, 0]])
    d = op("matrix_diag", np.asarray([1.0, 2.0, 3.0]))
    np.testing.assert_array_equal(d, np.diag([1.0, 2.0, 3.0]))
    got2 = op("interp", np.asarray([0.5]), np.asarray([0.0, 1.0]),
              np.asarray([10.0, 20.0]))
    np.testing.assert_allclose(got2, [15.0])


def test_crop_and_resize_tf_edge_semantics():
    """TF parity for the edge cases: out-of-image boxes extrapolate to 0,
    crop dim 1 samples the box center."""
    import tensorflow as tf

    rng = np.random.RandomState(3)
    img = rng.rand(1, 10, 10, 2).astype(np.float32)
    boxes = np.asarray([[-0.2, -0.2, 1.2, 1.2]], np.float32)
    idx = np.asarray([0], np.int32)
    got = op("crop_and_resize", img, boxes, idx, crop_size=(4, 4))
    expect = tf.image.crop_and_resize(img, boxes, idx, [4, 4]).numpy()
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)

    boxes2 = np.asarray([[0.2, 0.2, 0.8, 0.8]], np.float32)
    got2 = op("crop_and_resize", img, boxes2, idx, crop_size=(1, 1))
    expect2 = tf.image.crop_and_resize(img, boxes2, idx, [1, 1]).numpy()
    np.testing.assert_allclose(got2, expect2, rtol=1e-4, atol=1e-5)
