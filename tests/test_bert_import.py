"""BERT TF-import golden test.

The reference's flagship import scenario (BASELINE.json:10: "BERT-base via
SameDiff TF import, full-graph HLO compile"). No network: a random-initialized
TFBertModel (transformers) is frozen in-process and imported; outputs compared
against TF execution. A small config keeps CI fast; bench.py measures the
full-size variant on TPU.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
pytest.importorskip("transformers")


def make_frozen_bert(batch=2, seq=16, hidden=64, layers=2, heads=2, vocab=500):
    from transformers import BertConfig, TFBertModel
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    cfg = BertConfig(
        vocab_size=vocab, hidden_size=hidden, num_hidden_layers=layers,
        num_attention_heads=heads, intermediate_size=hidden * 4,
        max_position_embeddings=64,
    )
    model = TFBertModel(cfg)

    @tf.function
    def fwd(input_ids):
        return model(input_ids, training=False).last_hidden_state

    cf = fwd.get_concrete_function(tf.TensorSpec((batch, seq), tf.int32))
    frozen = convert_variables_to_constants_v2(cf)
    return frozen


class TestBertImport:
    def test_bert_import_matches_tf(self):
        from deeplearning4j_tpu.samediff.tf_import import TFGraphMapper

        frozen = make_frozen_bert()
        gd = frozen.graph.as_graph_def()
        ids = np.random.default_rng(0).integers(0, 500, size=(2, 16)).astype(np.int32)
        tf_out = frozen(tf.constant(ids))
        if isinstance(tf_out, (list, tuple)):
            tf_out = tf_out[0]
        tf_out = tf_out.numpy()

        in_name = frozen.inputs[0].name.split(":")[0]
        out_name = frozen.outputs[0].name.split(":")[0]
        sd = TFGraphMapper.import_graph(gd, outputs=[out_name])
        ours = np.asarray(sd.output({in_name: ids}, [out_name])[out_name])
        assert ours.shape == tf_out.shape
        np.testing.assert_allclose(ours, tf_out, rtol=1e-4, atol=1e-4)

    def test_bert_full_graph_jit_compiles(self):
        """The north-star property: the imported graph compiles to ONE XLA
        program (full-graph HLO compile)."""
        import jax

        from deeplearning4j_tpu.samediff.tf_import import TFGraphMapper

        frozen = make_frozen_bert()
        gd = frozen.graph.as_graph_def()
        out_name = frozen.outputs[0].name.split(":")[0]
        in_name = frozen.inputs[0].name.split(":")[0]
        sd = TFGraphMapper.import_graph(gd, outputs=[out_name])
        ids = np.random.default_rng(1).integers(0, 500, size=(2, 16)).astype(np.int32)
        compiled = sd.compile({in_name: ids}, [out_name])
        out = compiled(dict(sd._values), {in_name: ids})
        assert np.asarray(out[out_name]).shape == (2, 16, 64)


class TestImportedFineTune:
    def test_imported_bert_fine_tunes(self):
        """THE reference headline workflow beyond inference: import a
        frozen TF model, convert its constants to variables, attach a new
        head with SameDiff ops, and fit — loss must decrease through the
        IMPORTED weights."""
        import numpy as np

        from deeplearning4j_tpu.samediff import SameDiff, TrainingConfig
        from deeplearning4j_tpu.samediff.tf_import import TFGraphMapper
        from deeplearning4j_tpu.train.updaters import Adam

        frozen = make_frozen_bert(batch=4, seq=8, hidden=32, layers=1,
                                  heads=2, vocab=100)
        gd = frozen.graph.as_graph_def()
        in_name = frozen.inputs[0].name.split(":")[0]
        out_name = frozen.outputs[0].name.split(":")[0]
        sd = TFGraphMapper.import_graph(gd, outputs=[out_name])

        converted = sd.convert_to_variables()
        assert len(converted) > 5  # encoder weights became trainable

        # new classification head in SameDiff ops over the imported output
        hidden = sd.get_variable(out_name)            # [b, t, h]
        pooled = sd._op("reduce_mean", hidden, axis=[1])
        w = sd.var("cls_W", shape=(32, 2))
        logits = sd._op("matmul", pooled, w, name="logits")
        labels = sd.placeholder("labels", dtype="float32")
        loss = sd._op("softmax_cross_entropy", labels, logits)
        loss = sd._op("reduce_mean", loss, name="loss")
        sd.set_loss_variables("loss")

        rng = np.random.default_rng(0)
        ids = rng.integers(0, 100, (4, 8)).astype(np.int32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)]
        cfg = TrainingConfig(
            updater=Adam(5e-3),
            data_set_feature_mapping=[in_name],
            data_set_label_mapping=["labels"],
        )
        probe_name = max(converted,
                         key=lambda n: sd._values[sd._names[n]].size)
        before = np.asarray(sd._values[sd._names[probe_name]]).copy()
        hist = sd.fit([(ids, y)] * 8, cfg, epochs=6)
        losses = hist.loss_curve
        assert np.isfinite(losses[-1])
        assert losses[-1] < losses[0] * 0.7, f"{losses[0]} -> {losses[-1]}"

        # the IMPORTED weights moved, not just the new head
        after = np.asarray(sd._values[sd._names[probe_name]])
        assert not np.allclose(before, after), f"{probe_name} never updated"
