"""Distributed tracing unit coverage (obs/tracing.py): W3C traceparent
codec, contextvar span nesting incl. exception paths and thread isolation,
TraceStore bounds/filters, sampling, and the ModelManager deploy/rollback
span instrumentation. The cross-process propagation contract lives in
tools/check_trace_contract.py (tier-1 via test_trace_contract.py)."""

import threading

import numpy as np
import pytest

from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.obs import MetricsRegistry
from deeplearning4j_tpu.obs.tracing import (
    NULL_SPAN,
    TraceContext,
    TraceStore,
    Tracer,
    current_context,
    current_span,
    decode_traceparent,
    encode_traceparent,
    get_tracer,
    set_tracer,
    trace_now,
)
from deeplearning4j_tpu.serving import ModelManager, ModelStore


# ---------------------------------------------------------------------------
# traceparent codec
# ---------------------------------------------------------------------------
def test_traceparent_roundtrip():
    ctx = TraceContext("0af7651916cd43dd8448eb211c80319c",
                       "b7ad6b7169203331", sampled=True)
    hdr = encode_traceparent(ctx)
    assert hdr == ("00-0af7651916cd43dd8448eb211c80319c-"
                   "b7ad6b7169203331-01")
    back = decode_traceparent(hdr)
    assert back == ctx
    # unsampled flag survives
    off = TraceContext(ctx.trace_id, ctx.span_id, sampled=False)
    assert decode_traceparent(encode_traceparent(off)).sampled is False


@pytest.mark.parametrize("bad", [
    None,
    "",
    "garbage",
    "00-short-b7ad6b7169203331-01",
    "00-0af7651916cd43dd8448eb211c80319c-short-01",
    "ff-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01",  # ff version
    "00-00000000000000000000000000000000-b7ad6b7169203331-01",  # zero trace
    "00-0af7651916cd43dd8448eb211c80319c-0000000000000000-01",  # zero span
    "00-0af7651916cd43dd8448eb211c80319g-b7ad6b7169203331-01",  # non-hex
])
def test_traceparent_malformed_is_none(bad):
    assert decode_traceparent(bad) is None


def test_traceparent_future_version_accepted():
    hdr = "01-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01-extra"
    ctx = decode_traceparent(hdr)
    assert ctx is not None and ctx.sampled


# ---------------------------------------------------------------------------
# span nesting / exception paths (satellite: thread- and contextvar-safety)
# ---------------------------------------------------------------------------
def test_span_nesting_and_restore():
    t = Tracer(TraceStore())
    assert current_span() is None
    with t.span("outer") as outer:
        assert current_span() is outer
        with t.span("inner") as inner:
            assert current_span() is inner
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
        assert current_span() is outer
    assert current_span() is None
    assert t.flush()
    trace = t.store.traces()[0]
    assert trace["span_count"] == 2
    by_name = {s["name"]: s for s in trace["spans"]}
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["outer"]["parent_id"] is None


def test_span_body_raises_still_closes_records_error_restores_current():
    t = Tracer(TraceStore())
    with t.span("outer") as outer:
        with pytest.raises(ValueError):
            with t.span("boom") as boom:
                raise ValueError("nope")
        # previous current-span restored even though the body raised
        assert current_span() is outer
        assert boom.error is True
        assert boom.end_time is not None
        assert boom.attributes["exception"] == "ValueError"
    assert current_span() is None
    assert t.flush()
    spans = {s["name"]: s for s in t.store.traces()[0]["spans"]}
    assert spans["boom"]["error"] is True
    assert spans["outer"]["error"] is False


def test_span_threads_do_not_interfere():
    """Contextvars are per-thread: concurrent spans in different threads
    each see their own current-span stack, and exceptions in one thread
    never corrupt another's."""
    t = Tracer(TraceStore())
    barrier = threading.Barrier(4)
    errors = []

    def worker(i):
        try:
            assert current_span() is None
            with t.span(f"root-{i}") as root:
                barrier.wait(timeout=10)
                assert current_span() is root
                try:
                    with t.span(f"child-{i}"):
                        raise RuntimeError("thread-local failure")
                except RuntimeError:
                    pass
                assert current_span() is root
            assert current_span() is None
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=10)
    assert not errors
    assert t.flush()
    traces = t.store.traces()
    assert len(traces) == 4  # one independent trace per thread
    for tr in traces:
        assert tr["span_count"] == 2
        root = [s for s in tr["spans"] if s["parent_id"] is None]
        assert len(root) == 1


def test_span_finish_idempotent_and_attrs():
    t = Tracer(TraceStore())
    span = t.span("manual", attrs={"a": 1})
    span.set_attribute("b", "two")
    span.finish()
    span.finish()  # second finish is a no-op, not a duplicate export
    assert t.flush()
    assert t.store.span_count() == 1
    rec = t.store.traces()[0]["spans"][0]
    assert rec["attrs"] == {"a": 1, "b": "two"}
    assert rec["end"] >= rec["start"]


def test_record_span_cross_thread_parenting():
    t = Tracer(TraceStore())
    with t.span("handler") as handler:
        ctx = handler.context
    t0 = trace_now()
    t.record_span("worker.op", parent=ctx, start_time=t0,
                  end_time=t0 + 0.25, attrs={"k": "v"}, error=True)
    assert t.flush()
    trace = t.store.traces()[0]
    by_name = {s["name"]: s for s in trace["spans"]}
    rec = by_name["worker.op"]
    assert rec["parent_id"] == ctx.span_id
    assert rec["trace_id"] == ctx.trace_id
    assert rec["error"] is True
    assert abs(rec["duration_ms"] - 250.0) < 1e-6


# ---------------------------------------------------------------------------
# tracer policy: disabled / sampling
# ---------------------------------------------------------------------------
def test_disabled_tracer_is_null_and_stores_nothing():
    t = Tracer(TraceStore(), enabled=False)
    span = t.span("x")
    assert span is NULL_SPAN
    assert span.context is None
    with span:
        assert current_span() is None  # null spans never become current
        span.set_attribute("ignored", 1)
    t.record_span("y", parent=TraceContext("a" * 32, "b" * 16),
                  start_time=0.0, end_time=1.0)
    assert len(t.store) == 0


def test_unsampled_trace_takes_the_null_path():
    """Head-based sampling: an unsampled root is the SAME zero-cost null
    span as disabled tracing — no ids, no header to inject, no children
    recorded anywhere downstream."""
    t = Tracer(TraceStore(), sample_rate=0.0)
    with t.span("root") as root:
        assert root is NULL_SPAN
        assert root.context is None  # nothing to inject into traceparent
        with t.span("child") as child:
            assert child is NULL_SPAN
    assert len(t.store) == 0
    # an explicitly-unsampled REMOTE parent (traceparent flag 00) is
    # honored: no local recording either
    off_ctx = decode_traceparent("00-" + "a" * 32 + "-" + "b" * 16 + "-00")
    assert t.span("server", parent=off_ctx) is NULL_SPAN


def test_sample_rate_validation():
    with pytest.raises(ValueError):
        Tracer(sample_rate=1.5)


def test_set_tracer_roundtrip():
    mine = Tracer(TraceStore())
    prev = set_tracer(mine)
    try:
        assert get_tracer() is mine
    finally:
        set_tracer(prev)
    assert get_tracer() is prev


# ---------------------------------------------------------------------------
# store bounds / filters
# ---------------------------------------------------------------------------
def test_trace_store_bounds_and_eviction():
    store = TraceStore(max_traces=3, max_spans_per_trace=2)
    t = Tracer(store)
    for i in range(5):
        with t.span(f"root-{i}"):
            with t.span("c1"):
                pass
            with t.span("c2"):  # third span exceeds the per-trace cap
                pass
    assert t.flush()
    assert len(store) == 3
    assert store.evicted_traces == 2
    assert store.span_count() <= 3 * 2
    assert store.dropped_spans >= 1
    for tr in store.traces():
        assert tr["span_count"] <= 2


def test_trace_store_filters():
    store = TraceStore()
    t = Tracer(store)
    with t.span("slow", attrs={"route": "/a"}) as s:
        pass
    # synthesize a known-long trace (not sleep-based)
    t.record_span("long", parent=s.context, start_time=s.start_time,
                  end_time=s.start_time + 2.0)
    with t.span("fast", attrs={"route": "/b"}):
        pass
    assert t.flush()
    all_traces = store.traces()
    assert len(all_traces) == 2
    assert all_traces[0]["root"] == "fast"  # newest first
    long_only = store.traces(min_duration_ms=1000.0)
    assert len(long_only) == 1 and long_only[0]["routes"] == ["/a"]
    route_b = store.traces(route="/b")
    assert len(route_b) == 1 and route_b[0]["root"] == "fast"
    assert store.traces(route="/nope") == []
    assert len(store.traces(limit=1)) == 1


def test_trace_store_get_and_clear():
    store = TraceStore()
    t = Tracer(store)
    with t.span("a") as a:
        pass
    assert t.flush()
    assert store.get(a.trace_id)["root"] == "a"
    assert store.get("f" * 32) is None
    store.clear()
    assert len(store) == 0


# ---------------------------------------------------------------------------
# ModelManager deploy/rollback spans
# ---------------------------------------------------------------------------
def _model(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    return MultiLayerNetwork(conf).init()


def test_manager_deploy_and_rollback_traced(tmp_path):
    store = ModelStore(str(tmp_path / "registry"))
    store.publish("m", _model(1))
    store.publish("m", _model(2))
    reg = MetricsRegistry()
    tstore = TraceStore()
    tracer = Tracer(tstore)
    mgr = ModelManager(store, "m", version=1, registry=reg, tracer=tracer,
                       probation_seconds=0.0, workers=1)
    # serve once so a warmup shape is known (deploy then warms the model)
    x = np.random.RandomState(0).randn(1, 4).astype(np.float32)
    mgr.output(x)
    assert tracer.flush()
    tstore.clear()

    mgr.deploy(2)
    assert tracer.flush()
    deploy_traces = [t for t in tstore.traces() if t["root"] == "manager.deploy"]
    assert deploy_traces, [t["root"] for t in tstore.traces()]
    spans = {s["name"]: s for s in deploy_traces[0]["spans"]}
    deploy = spans["manager.deploy"]
    assert deploy["attrs"]["model"] == "m"
    assert deploy["attrs"]["version"] == "2"
    assert deploy["attrs"]["outcome"] == "completed"
    # load/warmup/swap nest under the deploy span (a slow deploy is
    # diagnosable stage by stage after the fact)
    for child in ("manager.load", "manager.warmup", "manager.swap"):
        assert spans[child]["parent_id"] == deploy["span_id"], child
        assert spans[child]["start"] >= deploy["start"]

    mgr.rollback()
    assert tracer.flush()
    rb = [t for t in tstore.traces() if t["root"] == "manager.rollback"]
    assert rb and rb[0]["spans"][0]["attrs"]["rolled_back_from"] == "2"
    mgr.shutdown(drain=False)


def test_ui_server_traces_endpoint():
    """UIServer serves GET /v1/traces from its tracer (same query surface
    as JsonModelServer), so training-process deploy/step traces are
    browsable next to /metrics."""
    import json
    from urllib import request as urllib_request

    from deeplearning4j_tpu.ui.server import UIServer

    tracer = Tracer(TraceStore())
    with tracer.span("manager.deploy", attrs={"route": "/deploy"}):
        pass
    assert tracer.flush()
    ui = UIServer(port=0, tracer=tracer).start()
    try:
        with urllib_request.urlopen(
                f"http://127.0.0.1:{ui.port}/v1/traces?route=/deploy",
                timeout=10) as r:
            body = json.loads(r.read())
        assert body["enabled"] is True
        assert body["trace_count"] == 1
        assert body["traces"][0]["root"] == "manager.deploy"
        with urllib_request.urlopen(
                f"http://127.0.0.1:{ui.port}/v1/traces?route=/nope",
                timeout=10) as r:
            assert json.loads(r.read())["traces"] == []
    finally:
        ui.stop()


def test_engine_spans_only_for_traced_requests():
    """Direct output_async callers with no open span store nothing; a
    traced caller gets queue_wait/batch/forward children."""
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    reg = MetricsRegistry()
    tstore = TraceStore()
    tracer = Tracer(tstore)
    pi = ParallelInference(_model(1), registry=reg, tracer=tracer, workers=1)
    x = np.random.RandomState(0).randn(1, 4).astype(np.float32)
    try:
        pi.output(x)  # untraced: no current span at enqueue
        assert tracer.flush() and len(tstore) == 0
        with tracer.span("request") as req:
            fut = pi.output_async(x)
        fut.result(timeout=30)
        pi.drain(timeout=10)
        assert tracer.flush()
        trace = tstore.get(req.trace_id)
        names = {s["name"] for s in trace["spans"]}
        assert {"engine.queue_wait", "engine.batch",
                "engine.forward"} <= names
        fwd = next(s for s in trace["spans"] if s["name"] == "engine.forward")
        assert fwd["parent_id"] == req.span_id
        assert fwd["attrs"]["model_version"] == "0"
    finally:
        pi.shutdown(drain=False)
