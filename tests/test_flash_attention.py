"""Flash-attention helper vs builtin parity — the ValidateCuDNN pattern
(SURVEY.md §4: helper enabled vs disabled, compare outputs/grads within eps).
Runs the Pallas kernel in interpreter mode on the CPU test platform."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops import (
    flash_attention,
    mha_attention,
    mha_attention_reference,
    set_attention_impl,
)


def _rand(key, *shape):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


@pytest.mark.parametrize("tq,tk", [(64, 64), (96, 128), (40, 72)])
def test_flash_matches_reference(tq, tk):
    q = _rand(0, 2, 2, tq, 16)
    k = _rand(1, 2, 2, tk, 16)
    v = _rand(2, 2, 2, tk, 16)
    ref = mha_attention_reference(q, k, v)
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_with_padding_mask():
    q = _rand(0, 2, 2, 48, 16)
    k = _rand(1, 2, 2, 48, 16)
    v = _rand(2, 2, 2, 48, 16)
    mask = jnp.asarray(np.random.RandomState(0).rand(2, 48) > 0.3,
                       jnp.float32)
    ref = mha_attention_reference(q, k, v, mask=mask)
    out = flash_attention(q, k, v, mask=mask, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_causal():
    q = _rand(0, 1, 2, 64, 16)
    k = _rand(1, 1, 2, 64, 16)
    v = _rand(2, 1, 2, 64, 16)
    ref = mha_attention_reference(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_gradients_match():
    q = _rand(0, 1, 1, 32, 8)
    k = _rand(1, 1, 1, 32, 8)
    v = _rand(2, 1, 1, 32, 8)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=16, block_k=16) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_attention_reference(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_impl_seam_dispatch():
    q = _rand(0, 1, 1, 32, 8)
    try:
        set_attention_impl("flash")
        out_flash = mha_attention(q, q, q)
        set_attention_impl("xla")
        out_xla = mha_attention(q, q, q)
    finally:
        set_attention_impl("auto")
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_xla),
                               atol=2e-5)
    with pytest.raises(ValueError):
        set_attention_impl("bogus")


def test_attention_layer_with_flash_helper():
    """Layer-level helper-vs-builtin parity (ValidateCuDNN shape)."""
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer
    from deeplearning4j_tpu.nn.layers.base import LayerContext

    layer = SelfAttentionLayer(n_in=16, n_out=16, n_heads=2).with_input(
        __import__("deeplearning4j_tpu.nn.input_type",
                   fromlist=["RecurrentType"]).RecurrentType(size=16,
                                                             timesteps=32))
    params = layer.init(jax.random.PRNGKey(0), jnp.float32)
    x = _rand(5, 3, 16, 32)
    ctx = LayerContext(train=False, rng=None, mask=None)
    try:
        set_attention_impl("xla")
        ref, _ = layer.apply(params, {}, x, ctx)
        set_attention_impl("flash")
        out, _ = layer.apply(params, {}, x, ctx)
    finally:
        set_attention_impl("auto")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_fully_masked_rows_zero_on_both_impls():
    q = _rand(0, 1, 1, 16, 8)
    k = _rand(1, 1, 1, 10, 8)
    v = _rand(2, 1, 1, 10, 8)
    mask = jnp.zeros((1, 10), jnp.float32)
    ref = mha_attention_reference(q, k, v, mask=mask)
    out = flash_attention(q, k, v, mask=mask, block_q=8, block_k=4)
    np.testing.assert_allclose(np.asarray(ref), 0.0, atol=1e-7)
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-7)


# ---------------------------------------------------------------------------
# memory-efficient backward (round 4): blockwise recompute, gradient parity
# ---------------------------------------------------------------------------

def _grads(fn, *args):
    loss = lambda *a: jnp.sum(jnp.square(fn(*a)))
    return jax.grad(loss, argnums=tuple(range(len(args))))(*args)


@pytest.mark.parametrize("tq,tk", [(64, 64), (96, 128), (40, 72)])
def test_flash_backward_matches_reference(tq, tk):
    q = _rand(10, 2, 2, tq, 16)
    k = _rand(11, 2, 2, tk, 16)
    v = _rand(12, 2, 2, tk, 16)
    ref = _grads(lambda a, b, c: mha_attention_reference(a, b, c), q, k, v)
    got = _grads(lambda a, b, c: flash_attention(a, b, c, block_q=32,
                                                 block_k=32), q, k, v)
    for g, r, name in zip(got, ref, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=5e-4,
                                   rtol=1e-4, err_msg=f"d{name}")


def test_flash_backward_causal_and_masked():
    q = _rand(13, 1, 2, 64, 16)
    k = _rand(14, 1, 2, 64, 16)
    v = _rand(15, 1, 2, 64, 16)
    mask = jnp.asarray(np.random.RandomState(9).rand(1, 64) > 0.3, jnp.float32)

    ref = _grads(lambda a, b, c: mha_attention_reference(
        a, b, c, mask=mask, causal=True), q, k, v)
    got = _grads(lambda a, b, c: flash_attention(
        a, b, c, mask=mask, causal=True, block_q=32, block_k=32), q, k, v)
    for g, r, name in zip(got, ref, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=5e-4,
                                   rtol=1e-4, err_msg=f"d{name}")


def test_flash_backward_ragged_blocks():
    """Sequence lengths that do NOT divide the block size (padding path)."""
    q = _rand(16, 1, 1, 50, 8)
    k = _rand(17, 1, 1, 70, 8)
    v = _rand(18, 1, 1, 70, 8)
    ref = _grads(lambda a, b, c: mha_attention_reference(a, b, c), q, k, v)
    got = _grads(lambda a, b, c: flash_attention(a, b, c, block_q=32,
                                                 block_k=32), q, k, v)
    for g, r, name in zip(got, ref, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=5e-4,
                                   rtol=1e-4, err_msg=f"d{name}")
