"""Observability subsystem tests (obs/): registry semantics, Prometheus
exposition correctness (label escaping, bucket cumulativity, _sum/_count),
thread-safety under concurrent increments, spans, the resilience observer
hooks, MetricsListener, and the AsyncDataSetIterator stats/shutdown fix."""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.core.resilience import (
    AdmissionController,
    CircuitBreaker,
    CircuitState,
    RetryPolicy,
)
from deeplearning4j_tpu.obs import (
    MetricError,
    MetricsListener,
    MetricsRegistry,
    Span,
    get_registry,
    render_prometheus,
    set_registry,
)


# --------------------------------------------------------------------------
# registry semantics
# --------------------------------------------------------------------------
class TestRegistry:
    def test_counter_gauge_histogram_basics(self):
        reg = MetricsRegistry()
        c = reg.counter("dl4j_tpu_test_events_total", "events")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(MetricError):
            c.inc(-1)

        g = reg.gauge("dl4j_tpu_test_depth", "depth")
        g.set(4)
        g.inc()
        g.dec(2)
        assert g.value == 3
        g.set_max(10)
        g.set_max(5)  # lower than current max: no-op
        assert g.value == 10

        h = reg.histogram("dl4j_tpu_test_latency_seconds", "lat",
                          buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        assert h.count == 3
        assert h.sum == pytest.approx(5.55)

    def test_labels_positional_and_keyword(self):
        reg = MetricsRegistry()
        fam = reg.counter("dl4j_tpu_test_reqs_total", "reqs",
                          ("instance", "code"))
        fam.labels("a", "200").inc()
        fam.labels(instance="a", code="200").inc()
        fam.labels(code="500", instance="a").inc()
        assert fam.labels("a", "200").value == 2
        assert fam.labels("a", "500").value == 1
        with pytest.raises(MetricError):
            fam.inc()  # labeled family has no default child
        with pytest.raises(MetricError):
            fam.labels("a")  # wrong arity
        with pytest.raises(MetricError):
            fam.labels(instance="a")  # missing label

    def test_registration_idempotent_and_shape_checked(self):
        reg = MetricsRegistry()
        a = reg.counter("dl4j_tpu_test_x_total", "x", ("l",))
        b = reg.counter("dl4j_tpu_test_x_total", "x", ("l",))
        assert a is b
        with pytest.raises(MetricError):
            reg.gauge("dl4j_tpu_test_x_total", "x", ("l",))  # type mismatch
        with pytest.raises(MetricError):
            reg.counter("dl4j_tpu_test_x_total", "x", ("other",))  # labels
        with pytest.raises(MetricError):
            reg.counter("0bad-name", "x")
        with pytest.raises(MetricError):
            reg.counter("dl4j_tpu_ok_total", "x", ("le",))  # reserved

    def test_concurrent_counter_increments(self):
        reg = MetricsRegistry()
        fam = reg.counter("dl4j_tpu_test_conc_total", "c", ("instance",))
        child = fam.labels("t")
        h = reg.histogram("dl4j_tpu_test_conc_seconds", "h", buckets=(0.5,))
        n_threads, per_thread = 8, 5000

        def worker():
            for _ in range(per_thread):
                child.inc()
                h.observe(0.25)

        threads = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert child.value == n_threads * per_thread
        assert h.count == n_threads * per_thread
        # every observation landed in the 0.5 bucket, cumulatively
        buckets = h._default().buckets()
        assert buckets[0][1] == n_threads * per_thread
        assert buckets[-1][1] == n_threads * per_thread

    def test_global_registry_injectable(self):
        prev = set_registry(None)
        try:
            reg = get_registry()
            reg.counter("dl4j_tpu_test_global_total", "g").inc()
            assert reg.get("dl4j_tpu_test_global_total").value == 1
        finally:
            set_registry(prev)
        assert get_registry() is prev


# --------------------------------------------------------------------------
# spans + event log
# --------------------------------------------------------------------------
class TestSpans:
    def test_span_feeds_histogram(self):
        reg = MetricsRegistry()
        h = reg.histogram("dl4j_tpu_test_span_seconds", "s")
        with Span(h._default()) as sp:
            pass
        assert sp.elapsed is not None and sp.elapsed >= 0
        assert h.count == 1

    def test_trace_registers_and_logs(self):
        reg = MetricsRegistry()
        with reg.trace("dl4j_tpu_test_op_seconds", labels={"op": "fwd"},
                       log=True):
            pass
        fam = reg.get("dl4j_tpu_test_op_seconds")
        assert fam.labels(op="fwd").count == 1
        evts = reg.events("span")
        assert len(evts) == 1
        assert evts[0]["name"] == "dl4j_tpu_test_op_seconds"
        assert evts[0]["op"] == "fwd" and evts[0]["error"] is False

    def test_span_records_on_exception(self):
        reg = MetricsRegistry()
        with pytest.raises(RuntimeError):
            with reg.trace("dl4j_tpu_test_err_seconds", log=True):
                raise RuntimeError("boom")
        assert reg.get("dl4j_tpu_test_err_seconds").count == 1
        assert reg.events("span")[0]["error"] is True

    def test_event_log_bounded(self):
        reg = MetricsRegistry(max_events=4)
        for i in range(10):
            reg.log_event("e", i=i)
        evts = reg.events("e")
        assert len(evts) == 4 and evts[0]["i"] == 6


# --------------------------------------------------------------------------
# Prometheus exposition
# --------------------------------------------------------------------------
class TestExposition:
    def test_label_escaping(self):
        reg = MetricsRegistry()
        fam = reg.counter("dl4j_tpu_test_esc_total", 'has "quotes"\nand \\',
                          ("path",))
        fam.labels('va"l\\ue\nx').inc()
        text = render_prometheus(reg)
        assert ('# HELP dl4j_tpu_test_esc_total '
                'has "quotes"\\nand \\\\') in text
        assert 'path="va\\"l\\\\ue\\nx"' in text
        # round-trips through the external parser
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        os.pardir, "tools"))
        try:
            from check_metrics_contract import parse_exposition
        finally:
            sys.path.pop(0)
        fams = parse_exposition(text)
        (_, labels, value), = fams["dl4j_tpu_test_esc_total"]["samples"]
        assert labels["path"] == 'va"l\\ue\nx' and value == 1

    def test_histogram_exposition_cumulative(self):
        reg = MetricsRegistry()
        h = reg.histogram("dl4j_tpu_test_h_seconds", "h", buckets=(0.1, 1.0))
        for v in (0.05, 0.05, 0.5, 5.0):
            h.observe(v)
        text = render_prometheus(reg)
        lines = [l for l in text.splitlines() if l.startswith("dl4j_tpu_test_h")]
        assert lines == [
            'dl4j_tpu_test_h_seconds_bucket{le="0.1"} 2',
            'dl4j_tpu_test_h_seconds_bucket{le="1"} 3',
            'dl4j_tpu_test_h_seconds_bucket{le="+Inf"} 4',
            "dl4j_tpu_test_h_seconds_sum 5.6",
            "dl4j_tpu_test_h_seconds_count 4",
        ]

    def test_type_lines_and_ordering(self):
        reg = MetricsRegistry()
        reg.gauge("dl4j_tpu_test_b", "b").set(1)
        reg.counter("dl4j_tpu_test_a_total", "a").inc()
        text = render_prometheus(reg)
        # families sorted by name; TYPE precedes samples
        a = text.index("# TYPE dl4j_tpu_test_a_total counter")
        b = text.index("# TYPE dl4j_tpu_test_b gauge")
        assert a < text.index("dl4j_tpu_test_a_total 1") < b
        assert text.endswith("\n")


# --------------------------------------------------------------------------
# resilience observer hooks (standalone — satellite 2)
# --------------------------------------------------------------------------
class TestObserverHooks:
    def test_circuit_breaker_observer_sees_transitions(self):
        t = [0.0]
        cb = CircuitBreaker(failure_threshold=0.5, min_calls=2, window=4,
                            open_timeout=10.0, clock=lambda: t[0])
        seen = []
        cb.add_observer(lambda old, new: seen.append((old.value, new.value)))
        cb.record_failure()
        cb.record_failure()  # trips
        assert seen == [("closed", "open")]
        t[0] += 10.0
        assert cb.allow()  # open -> half_open, probe admitted
        cb.record_success()  # half_open -> closed
        assert seen == [("closed", "open"), ("open", "half_open"),
                        ("half_open", "closed")]

    def test_circuit_observer_may_reenter_breaker(self):
        cb = CircuitBreaker(failure_threshold=0.5, min_calls=1)
        ra = []
        cb.add_observer(lambda old, new: ra.append(cb.retry_after()))
        cb.record_failure()  # observer calls back in; must not deadlock
        assert len(ra) == 1 and ra[0] > 0

    def test_admission_observer_decisions(self):
        ac = AdmissionController(max_pending=1)
        seen = []
        ac.add_observer(lambda d, pending: seen.append((d, pending)))
        assert ac.try_admit()
        assert not ac.try_admit()
        ac.release()
        assert seen == [("admitted", 1), ("shed", 1)]
        ac.remove_observer(seen)  # unknown fn: tolerated
        assert ac.stats()["shed"] == 1  # behavior unchanged by observer

    def test_retry_policy_observer_counts_attempts(self):
        policy = RetryPolicy(max_retries=3, initial_backoff=0.001, seed=1)
        attempts = []
        policy.observer = lambda attempt, exc, delay: attempts.append(attempt)
        calls = [0]

        def flaky():
            calls[0] += 1
            if calls[0] < 3:
                raise ValueError("flaky")
            return "ok"

        assert policy.execute(flaky, retry_on=(ValueError,),
                              sleep=lambda s: None) == "ok"
        assert attempts == [0, 1]


# --------------------------------------------------------------------------
# MetricsListener
# --------------------------------------------------------------------------
class _FakeModel:
    last_batch_size = 32


class TestMetricsListener:
    def test_series_from_iterations(self):
        reg = MetricsRegistry()
        lis = MetricsListener(registry=reg)
        assert lis.requires_score is False
        model = _FakeModel()
        lis.on_epoch_start(model)
        for i in range(1, 4):
            lis.iteration_done(model, i, 0, 0.5 / i)
        lis.on_epoch_end(model)
        assert reg.get("dl4j_tpu_training_iterations_total").value == 3
        assert reg.get("dl4j_tpu_training_examples_total").value == 96
        assert reg.get("dl4j_tpu_training_epochs_total").value == 1
        # first iteration has no predecessor: 2 latency observations
        assert reg.get("dl4j_tpu_training_step_latency_seconds").count == 2
        assert reg.get("dl4j_tpu_training_score").value == pytest.approx(0.5 / 3)

    def test_nan_score_skipped(self):
        reg = MetricsRegistry()
        lis = MetricsListener(registry=reg)
        lis.iteration_done(_FakeModel(), 1, 0, 0.25)
        lis.iteration_done(_FakeModel(), 2, 0, float("nan"))
        assert reg.get("dl4j_tpu_training_score").value == 0.25

    def test_attaches_to_samediff_training_session(self):
        from deeplearning4j_tpu.samediff import SameDiff
        from deeplearning4j_tpu.samediff.training import TrainingConfig
        from deeplearning4j_tpu.train.updaters import Adam

        reg = MetricsRegistry()
        sd = SameDiff.create()
        x = sd.placeholder("x", (None, 2))
        label = sd.placeholder("label", (None, 1))
        w = sd.var("w", np.zeros((2, 1), np.float32))
        pred = (x @ w).rename("pred")
        sd.loss.mean_squared_error(label, pred).rename("loss")
        sd.set_loss_variables("loss")
        cfg = TrainingConfig(updater=Adam(0.1),
                             data_set_feature_mapping=("x",),
                             data_set_label_mapping=("label",))
        xs = np.random.RandomState(0).randn(8, 2).astype(np.float32)
        ys = (xs @ np.array([[1.0], [2.0]], np.float32)).astype(np.float32)
        sd.fit([(xs, ys)] * 3, cfg, epochs=2,
               listeners=[MetricsListener(registry=reg)])
        assert reg.get("dl4j_tpu_training_iterations_total").value == 6
        assert reg.get("dl4j_tpu_training_examples_total").value == 48
        assert reg.get("dl4j_tpu_training_epochs_total").value == 2

    def test_distributed_trainer_no_score_sync(self):
        from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.parallel import DistributedTrainer

        reg = MetricsRegistry()
        conf = (NeuralNetConfiguration.builder().seed(7).list()
                .layer(DenseLayer(n_in=4, n_out=8))
                .layer(OutputLayer(n_in=8, n_out=2))
                .build())
        model = MultiLayerNetwork(conf).init()
        model.listeners.add(MetricsListener(registry=reg))
        trainer = DistributedTrainer(model)
        rng = np.random.RandomState(3)
        x = rng.randn(32, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 32)]
        trainer.fit(x, y, epochs=1)
        assert reg.get("dl4j_tpu_training_iterations_total").value >= 1
        assert reg.get("dl4j_tpu_training_examples_total").value == 32


# --------------------------------------------------------------------------
# AsyncDataSetIterator stats + shutdown (satellite 1)
# --------------------------------------------------------------------------
class TestAsyncIterator:
    def _iterator(self, reg, n=96, batch=8, queue_size=4):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.iterators import (AsyncDataSetIterator,
                                                       ListDataSetIterator)

        x = np.arange(n * 2, dtype=np.float32).reshape(n, 2)
        y = np.zeros((n, 1), np.float32)
        base = ListDataSetIterator(DataSet(x, y), batch=batch)
        return AsyncDataSetIterator(base, queue_size=queue_size, registry=reg)

    def test_stats_exposed(self):
        reg = MetricsRegistry()
        it = self._iterator(reg)
        batches = sum(1 for _ in it)
        assert batches == 12
        s = it.stats()
        assert s["batches"] == 12
        assert s["queue_high_water"] >= 1
        assert s["producer_blocked_s"] >= 0.0
        assert s["consumer_starvation_s"] >= 0.0
        assert reg.get("dl4j_tpu_data_prefetch_batches_total") is not None
        it.close()

    def test_abandon_mid_epoch_joins_thread(self):
        reg = MetricsRegistry()
        it = self._iterator(reg, n=400, batch=4, queue_size=2)
        consumed = 0
        for _ in it:
            consumed += 1
            if consumed == 3:
                break  # abandon with the producer parked on a full queue
        thread = it._thread
        assert thread is not None and thread.is_alive()
        it.close()
        assert not thread.is_alive(), "prefetch thread leaked after close()"
        assert it._thread is None
        # the whole epoch was NOT forced: producer stopped early
        assert it.stats()["batches"] < 100

    def test_reset_mid_epoch_restarts_cleanly(self):
        reg = MetricsRegistry()
        it = self._iterator(reg, n=64, batch=4, queue_size=2)
        it.next()
        it.next()
        thread = it._thread
        it.reset()
        assert thread is None or not thread.is_alive()
        batches = sum(1 for _ in it)
        assert batches == 16  # full epoch after reset

    def test_error_propagates_after_rework(self):
        from deeplearning4j_tpu.data.iterators import AsyncDataSetIterator

        class Exploding:
            def __init__(self):
                self.n = 0

            def has_next(self):
                return True

            def next(self):
                self.n += 1
                if self.n > 2:
                    raise RuntimeError("reader died")
                return self.n

            def reset(self):
                self.n = 0

            def batch_size(self):
                return 1

        it = AsyncDataSetIterator(Exploding(), queue_size=2,
                                  registry=MetricsRegistry())
        with pytest.raises(RuntimeError, match="reader died"):
            while it.has_next():
                it.next()
        it.close()


# --------------------------------------------------------------------------
# serving integration: stats() is a view over the injected registry
# --------------------------------------------------------------------------
class TestServingIntegration:
    def test_stats_view_matches_registry(self):
        from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.parallel import ParallelInference

        reg = MetricsRegistry()
        conf = (NeuralNetConfiguration.builder().seed(5).list()
                .layer(DenseLayer(n_in=4, n_out=8))
                .layer(OutputLayer(n_in=8, n_out=3))
                .build())
        model = MultiLayerNetwork(conf).init()
        pi = ParallelInference(model, workers=1, registry=reg, name="t")
        try:
            x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
            pi.output(x)
            s = pi.stats()
            assert s["accepted"] == 1 and s["completed"] == 1
            fam = reg.get("dl4j_tpu_inference_requests_total")
            assert fam.labels("t", "accepted").value == 1
            assert fam.labels("t", "completed").value == 1
            assert reg.get(
                "dl4j_tpu_inference_forward_latency_seconds").labels("t").count == 1
            assert reg.get("dl4j_tpu_inference_queue_depth").labels("t").value == 0
            assert reg.get("dl4j_tpu_resilience_circuit_state").labels("t").value == 0
        finally:
            pi.shutdown()

    def test_circuit_transition_series(self):
        from deeplearning4j_tpu.core.resilience import FaultInjector
        from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.parallel import ParallelInference

        reg = MetricsRegistry()
        conf = (NeuralNetConfiguration.builder().seed(5).list()
                .layer(DenseLayer(n_in=4, n_out=8))
                .layer(OutputLayer(n_in=8, n_out=3))
                .build())
        model = MultiLayerNetwork(conf).init()
        inj = FaultInjector()
        from deeplearning4j_tpu.parallel.inference import FORWARD_SITE
        inj.inject_error(FORWARD_SITE, lambda: RuntimeError("poisoned"),
                         times=3)
        cb = CircuitBreaker(failure_threshold=0.5, min_calls=3, window=4,
                            open_timeout=60.0)
        pi = ParallelInference(model, workers=1, circuit_breaker=cb,
                               fault_injector=inj, registry=reg, name="cb")
        try:
            x = np.ones((1, 4), np.float32)
            for _ in range(3):
                with pytest.raises(RuntimeError):
                    pi.output(x)
            assert reg.get("dl4j_tpu_resilience_circuit_state").labels("cb").value == 1
            fam = reg.get("dl4j_tpu_resilience_circuit_transitions_total")
            assert fam.labels("cb", "closed", "open").value == 1
        finally:
            pi.shutdown(drain=False)
