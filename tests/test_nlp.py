"""NLP tier tests: wordpiece tokenization, BertIterator data prep, and
Word2Vec learning co-occurrence structure (SURVEY.md §2.2 NLP row)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    BasicTokenizer,
    BertIterator,
    BertTask,
    BertWordPieceTokenizer,
    Vocabulary,
    Word2Vec,
)

_VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
          "the", "quick", "brown", "fox", "jump", "##ed", "##s",
          "over", "lazy", "dog", "un", "##want"]


@pytest.fixture
def tokenizer():
    return BertWordPieceTokenizer(Vocabulary(_VOCAB))


def test_basic_tokenizer():
    t = BasicTokenizer()
    assert t.tokenize("Hello, World!") == ["hello", ",", "world", "!"]
    assert t.tokenize("  a\tb\nc ") == ["a", "b", "c"]
    # accents stripped under lowercasing
    assert t.tokenize("Café") == ["cafe"]


def test_wordpiece_greedy_longest_match(tokenizer):
    assert tokenizer.tokenize("jumped") == ["jump", "##ed"]
    assert tokenizer.tokenize("unwanted") == ["un", "##want", "##ed"]
    assert tokenizer.tokenize("The quick fox") == ["the", "quick", "fox"]
    # unknown word → [UNK]
    assert tokenizer.tokenize("zebra") == ["[UNK]"]


def test_encode(tokenizer):
    ids = tokenizer.encode("jumped")
    assert ids == [_VOCAB.index("jump"), _VOCAB.index("##ed")]


def test_vocab_from_file(tmp_path):
    p = tmp_path / "vocab.txt"
    p.write_text("\n".join(_VOCAB) + "\n")
    v = Vocabulary.from_file(str(p))
    assert len(v) == len(_VOCAB)
    assert v.id_of("fox") == _VOCAB.index("fox")


def test_bert_iterator_classification(tokenizer):
    sents = ["the quick brown fox", "the lazy dog", "jumped over"]
    it = BertIterator(tokenizer, task=BertTask.SEQ_CLASSIFICATION,
                      sentences=sents, labels=[0, 1, 0], num_classes=2,
                      max_length=8, batch_size=2)
    batches = list(it)
    assert len(batches) == 2 and len(it) == 2
    ids, mask = batches[0].features
    assert ids.shape == (2, 8) and mask.shape == (2, 8)
    assert ids[0, 0] == tokenizer.vocab.id_of("[CLS]")
    # [SEP] closes each sequence at the last unmasked position
    last = int(mask[0].sum()) - 1
    assert ids[0, last] == tokenizer.vocab.id_of("[SEP]")
    assert ids[0, last + 1] == tokenizer.vocab.id_of("[PAD]")
    np.testing.assert_allclose(batches[0].labels[0][0], [1, 0])


def test_bert_iterator_mlm(tokenizer):
    sents = ["the quick brown fox jumped over the lazy dog"] * 20
    it = BertIterator(tokenizer, task=BertTask.UNSUPERVISED,
                      sentences=sents, max_length=16, batch_size=10,
                      mask_prob=0.3, seed=7)
    (b1, b2) = list(it)
    ids, mask = b1.features
    labels = b1.labels[0]
    lmask = b1.labels_masks[0]
    assert ids.shape == labels.shape == lmask.shape == (10, 16)
    # masked positions: corrupted ids differ from labels at ~80% of picks
    picked = lmask > 0
    assert picked.any()
    # labels hold the ORIGINAL ids everywhere
    orig, _ = it._encode(sents[0])
    np.testing.assert_array_equal(labels[0], orig)
    # CLS/SEP are never masked
    cls_id = tokenizer.vocab.id_of("[CLS]")
    sep_id = tokenizer.vocab.id_of("[SEP]")
    assert not ((labels == cls_id) & picked).any()
    assert not ((labels == sep_id) & picked).any()
    # most masked positions become [MASK]
    mask_id = tokenizer.vocab.id_of("[MASK]")
    frac_masked = ((ids == mask_id) & picked).sum() / picked.sum()
    assert 0.5 < frac_masked <= 1.0


def test_bert_iterator_validation(tokenizer):
    with pytest.raises(ValueError):
        BertIterator(tokenizer, task=BertTask.SEQ_CLASSIFICATION,
                     sentences=["a"], num_classes=2)  # no labels
    with pytest.raises(ValueError):
        BertIterator(tokenizer, task=BertTask.SEQ_CLASSIFICATION,
                     sentences=["a", "b"], labels=[0], num_classes=2)


def test_word2vec_learns_cooccurrence():
    # two disjoint topic clusters; words within a cluster co-occur
    rng = np.random.RandomState(0)
    animals = ["cat", "dog", "fox", "wolf"]
    tools = ["hammer", "wrench", "drill", "saw"]
    sentences = []
    for _ in range(400):
        group = animals if rng.rand() < 0.5 else tools
        sentences.append([group[rng.randint(4)] for _ in range(8)])
    w2v = Word2Vec(vector_size=16, window=3, min_count=1, negative=4,
                   epochs=5, batch_size=256, seed=3,
                   learning_rate=5.0, subsample=0)
    w2v.fit(sentences)
    assert w2v.has_word("cat") and not w2v.has_word("zebra")
    assert w2v.get_word_vector("cat").shape == (16,)
    # within-cluster similarity should beat cross-cluster
    within = w2v.similarity("cat", "dog")
    across = w2v.similarity("cat", "hammer")
    assert within > across, (within, across)
    nearest = w2v.words_nearest("cat", 3)
    assert set(nearest) <= set(animals) - {"cat"} | set(), nearest


def test_word2vec_min_count():
    sents = [["a", "b"], ["a", "c"], ["a", "b"]]
    w2v = Word2Vec(vector_size=4, min_count=2, window=2, epochs=1,
                   batch_size=8, subsample=0)
    w2v.fit(sents)
    assert w2v.has_word("a") and w2v.has_word("b")
    assert not w2v.has_word("c")  # below min_count
    with pytest.raises(ValueError):
        Word2Vec(min_count=10).fit([["x", "y"]])


def test_word2vec_hierarchical_softmax_parity():
    """HS and NS modes learn the same toy cluster structure (VERDICT r4
    ask 9; reference: useHierarchicSoftmax — SURVEY.md:139)."""
    rng = np.random.RandomState(0)
    animals = ["cat", "dog", "fox", "wolf"]
    tools = ["hammer", "wrench", "drill", "saw"]
    sentences = []
    for _ in range(400):
        group = animals if rng.rand() < 0.5 else tools
        sentences.append([group[rng.randint(4)] for _ in range(8)])
    w2v = Word2Vec(vector_size=16, window=3, min_count=1, hs=True,
                   epochs=5, batch_size=256, seed=3,
                   learning_rate=5.0, subsample=0)
    w2v.fit(sentences)
    # Huffman tables: V leaves, V-1 inner nodes, mask rows all non-empty
    v = len(w2v.vocab)
    assert w2v.syn1.shape[0] == v - 1
    assert w2v.hs_points.shape == w2v.hs_codes.shape == w2v.hs_mask.shape
    assert (w2v.hs_mask.sum(axis=1) >= 1).all()
    # same qualitative structure as the NS-mode test
    assert w2v.similarity("cat", "dog") > w2v.similarity("cat", "hammer")
    nearest = w2v.words_nearest("cat", 3)
    assert set(nearest) <= set(animals) - {"cat"}, nearest


def test_word2vec_huffman_codes_prefix_free():
    """Huffman invariants: shorter codes for frequent words, prefix-free."""
    sents = [["the"] * 50, ["quick"] * 20, ["brown"] * 10, ["fox"] * 5,
             ["jumps"] * 2, ["over"] * 2]
    w2v = Word2Vec(vector_size=4, min_count=1, hs=True, epochs=1,
                   batch_size=8, subsample=0)
    w2v.fit(sents)
    lens = w2v.hs_mask.sum(axis=1).astype(int)
    # vocab is sorted by descending count: code lengths must be
    # nondecreasing
    assert all(lens[i] <= lens[i + 1] for i in range(len(lens) - 1)), lens
    codes = ["".join(str(int(b)) for b in w2v.hs_codes[i][: lens[i]])
             for i in range(len(w2v.vocab))]
    assert len(set(codes)) == len(codes)
    for i, a in enumerate(codes):
        for j, b in enumerate(codes):
            if i != j:
                assert not b.startswith(a), (a, b)
