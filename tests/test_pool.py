"""EnginePool unit suite (ISSUE 10): power-of-two-choices dispatch,
circuit skip, least-loaded fallback, priority-aware admission, the
content-hash response cache, AIMD adaptive batching, and pool-wide hot
swap with per-replica rollback.

Dispatch-distribution tests run against lightweight fake replicas (the
pool's replica protocol: ``name``, ``output_async``, ``load_score``,
``circuit_state``, ``_breaker``) so the arrival pattern and load decay
are fully deterministic under the pool's seeded RNG; swap/manager tests
use real engines over a tiny model.
"""

import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from deeplearning4j_tpu.core.resilience import (
    AdmissionController,
    AdmissionRejectedError,
    CircuitBreaker,
    CircuitOpenError,
    CircuitState,
    Deadline,
)
from deeplearning4j_tpu.obs.metrics import MetricsRegistry
from deeplearning4j_tpu.parallel import EnginePool, ParallelInference
from deeplearning4j_tpu.parallel.pool import (
    SWAP_SITE,
    AdaptiveBatcher,
    PoolServable,
    ResponseCache,
)


def _tiny_model(seed=5):
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    return MultiLayerNetwork(conf).init()


class FakeReplica:
    """Replica protocol stub: backlog-driven load score, optional breaker
    on a fake clock, scripted shed behavior."""

    def __init__(self, name, clock=None):
        self.name = name
        self.backlog = 0.0
        self.calls = 0
        self.shed_next = False
        self._breaker = CircuitBreaker(clock=clock or time.monotonic)

    @property
    def circuit_state(self):
        return self._breaker.state

    def load_score(self):
        return float(self.backlog)

    def output_async(self, x, *, timeout=None, deadline=None, priority=None):
        if self.shed_next:
            raise AdmissionRejectedError("replica full")
        self.calls += 1
        self.backlog += 1
        fut = Future()
        fut.set_result(np.asarray(x))
        return fut


def _fake_pool(n=3, seed=7, clock=None, **kw):
    reg = MetricsRegistry()
    replicas = [FakeReplica(f"f{i}", clock=clock) for i in range(n)]
    kw.setdefault("max_pending", 100_000)
    pool = EnginePool(engines=replicas, registry=reg, seed=seed,
                      name="tp", **kw)
    return pool, replicas, reg


# --------------------------------------------------------------------------
# dispatch
# --------------------------------------------------------------------------
class TestPowerOfTwoChoices:
    def test_balance_within_2x_on_skewed_arrivals(self):
        """ISSUE 10 satellite: deterministic seed, bursty (skewed) arrival
        pattern, per-replica drain between bursts — max/min per-replica
        dispatch counts stay within 2x and every replica serves."""
        pool, replicas, _ = _fake_pool(n=3, seed=7)
        rng = np.random.RandomState(42)
        bursts = rng.randint(1, 13, size=60)  # skewed: bursts of 1..12
        for burst in bursts:
            for _ in range(int(burst)):
                pool.output_async(np.ones((1, 4), np.float32)).result()
            for r in replicas:  # constant drain between bursts
                r.backlog = max(0.0, r.backlog - 3.0)
        counts = [r.calls for r in replicas]
        assert sum(counts) == int(bursts.sum())
        assert min(counts) > 0, counts
        assert max(counts) <= 2 * min(counts), counts
        s = pool.stats()
        assert s["dispatched"] == {r.name: r.calls for r in replicas}
        pool.shutdown(drain=False)

    def test_open_circuit_replica_gets_zero_dispatches_until_half_open(self):
        """ISSUE 10 satellite: a tripped replica receives nothing while
        hard-open; once the open timeout elapses (half-open) it re-enters
        the candidate set."""
        t = [0.0]
        pool, replicas, _ = _fake_pool(n=2, seed=3, clock=lambda: t[0])
        bad = replicas[1]
        for _ in range(5):  # trip: 5/5 failures over the window
            bad._breaker.record_failure()
        assert bad.circuit_state is CircuitState.OPEN
        for _ in range(50):
            pool.output_async(np.ones((1, 4), np.float32)).result()
            for r in replicas:
                r.backlog = 0.0
        assert bad.calls == 0
        assert replicas[0].calls == 50
        t[0] += 31.0  # default open_timeout=30 elapses -> half-open
        for _ in range(20):
            pool.output_async(np.ones((1, 4), np.float32)).result()
            for r in replicas:
                r.backlog = 0.0
        assert bad.calls > 0  # probes flow again
        pool.shutdown(drain=False)

    def test_least_loaded_fallback_when_chosen_replica_sheds(self):
        pool, replicas, _ = _fake_pool(n=2, seed=0)
        a, b = replicas
        a.shed_next = True      # the attractive replica refuses
        a.backlog, b.backlog = 0.0, 5.0  # p2c must pick a first
        fut = pool.output_async(np.ones((1, 4), np.float32))
        assert fut.result() is not None
        assert b.calls == 1 and a.calls == 0
        assert pool.stats()["dispatch_errors"].get("f0") == 1
        pool.shutdown(drain=False)

    def test_all_circuits_open_raises_circuit_open(self):
        pool, replicas, _ = _fake_pool(n=2, seed=0)
        for r in replicas:
            for _ in range(5):
                r._breaker.record_failure()
        with pytest.raises(CircuitOpenError) as ei:
            pool.output_async(np.ones((1, 4), np.float32))
        assert ei.value.retry_after > 0
        assert pool._admission.pending == 0  # the slot was released
        pool.shutdown(drain=False)

    def test_injected_dispatch_fault_charges_the_target_replica(self):
        """The per-replica engine_pool.dispatch.<name> site: the fault is
        recorded as that replica's failure (its breaker accumulates) and
        the request falls over to another replica."""
        from deeplearning4j_tpu.core.resilience import FaultInjector
        from deeplearning4j_tpu.parallel.pool import DISPATCH_SITE

        inj = FaultInjector()
        pool, replicas, _ = _fake_pool(n=2, seed=0,
                                       fault_injector=inj)
        a, b = replicas
        a.backlog, b.backlog = 0.0, 5.0  # force choice of a
        inj.inject_error(f"{DISPATCH_SITE}.f0",
                         lambda: RuntimeError("link down"), times=1)
        fut = pool.output_async(np.ones((1, 4), np.float32))
        assert fut.result() is not None
        assert b.calls == 1 and a.calls == 0
        assert pool.stats()["dispatch_errors"]["f0"] == 1
        pool.shutdown(drain=False)


# --------------------------------------------------------------------------
# priority admission
# --------------------------------------------------------------------------
class TestPriorityAdmission:
    def test_shed_order_low_first(self):
        ac = AdmissionController(max_pending=10,
                                 priorities={"high": 1.0, "low": 0.5})
        for _ in range(5):
            ac.admit("low")  # low's window: 5 of 10
        with pytest.raises(AdmissionRejectedError):
            ac.admit("low")
        for _ in range(5):
            ac.admit("high")  # high still fits up to the full window
        with pytest.raises(AdmissionRejectedError):
            ac.admit("high")
        by = ac.stats()["by_priority"]
        assert by["low"]["admitted"] == 5 and by["low"]["shed"] == 1
        assert by["high"]["admitted"] == 5 and by["high"]["shed"] == 1

    def test_weighted_token_buckets(self):
        t = [0.0]
        ac = AdmissionController(max_pending=100, rate=10.0, burst=10.0,
                                 priorities={"high": 1.0, "low": 0.25},
                                 clock=lambda: t[0])
        # shares: high 0.8, low 0.2 -> bursts of 8 and 2 tokens
        assert sum(ac.try_admit("low") for _ in range(5)) == 2
        assert sum(ac.try_admit("high") for _ in range(10)) == 8
        t[0] += 1.0  # +10 tokens split 8/2
        assert ac.try_admit("low")
        assert ac.try_admit("high")

    def test_unknown_priority_is_strictest(self):
        ac = AdmissionController(max_pending=10,
                                 priorities={"high": 1.0, "low": 0.5})
        for _ in range(5):
            ac.admit("high")
        with pytest.raises(AdmissionRejectedError):
            ac.admit("???")  # resolves to the lowest class: window 5
        assert ac.stats()["by_priority"]["low"]["shed"] == 1

    def test_default_and_no_priorities_unchanged(self):
        ac = AdmissionController(max_pending=2)
        ac.admit()
        ac.admit("anything")  # no classes configured: plain window
        with pytest.raises(AdmissionRejectedError):
            ac.admit()
        assert "by_priority" not in ac.stats()

    def test_observer_arity_both_supported(self):
        ac = AdmissionController(max_pending=1,
                                 priorities={"high": 1.0, "low": 0.5})
        two, three = [], []
        ac.add_observer(lambda decision, pending: two.append(decision))
        ac.add_observer(
            lambda decision, pending, priority: three.append(priority))
        ac.admit("high")
        assert not ac.try_admit("low")
        assert two == ["admitted", "shed"]
        assert three == ["high", "low"]

    def test_pool_sheds_low_priority_first(self):
        # hold slots open: futures that never resolve
        class Pending(FakeReplica):
            def output_async(self, x, **kw):
                self.calls += 1
                return Future()  # never resolves -> pool slot stays held

        reg = MetricsRegistry()
        pool = EnginePool(engines=[Pending("p0"), Pending("p1")],
                          registry=reg, seed=1, max_pending=8,
                          priorities={"high": 1.0, "low": 0.5}, name="tp")
        for _ in range(4):
            pool.output_async(np.ones((1, 4), np.float32), priority="low")
        with pytest.raises(AdmissionRejectedError):
            pool.output_async(np.ones((1, 4), np.float32), priority="low")
        pool.output_async(np.ones((1, 4), np.float32), priority="high")
        s = pool.stats()
        assert s["shed_by_priority"]["low"] == 1
        assert s["shed_by_priority"].get("high", 0) == 0
        shed = reg.get("dl4j_tpu_pool_shed_total")
        assert shed.labels("tp", "low").value == 1
        pool.shutdown(drain=False)


# --------------------------------------------------------------------------
# response cache
# --------------------------------------------------------------------------
class TestResponseCache:
    def test_ttl_and_lru_bounds(self):
        t = [0.0]
        c = ResponseCache(max_entries=2, ttl_seconds=10.0, clock=lambda: t[0])
        x = np.ones((1, 4), np.float32)
        k1 = ResponseCache.key("1", x)
        c.put(k1, np.zeros(3))
        assert c.get(k1) is not None
        t[0] += 10.0  # expired exactly at ttl
        assert c.get(k1) is None
        c.put(k1, np.zeros(3))
        k2 = ResponseCache.key("1", x * 2)
        k3 = ResponseCache.key("1", x * 3)
        c.put(k2, np.ones(3))
        c.get(k1)  # renew k1's recency
        c.put(k3, np.ones(3))  # evicts k2 (LRU), not k1
        assert c.get(k1) is not None and c.get(k2) is None
        assert len(c) == 2

    def test_key_binds_version_dtype_shape(self):
        x = np.ones((2, 2), np.float32)
        assert ResponseCache.key("1", x) != ResponseCache.key("2", x)
        assert ResponseCache.key("1", x) != ResponseCache.key(
            "1", x.astype(np.float64))
        assert ResponseCache.key("1", x) != ResponseCache.key(
            "1", x.reshape(1, 4))

    def test_pool_cache_hit_bypasses_dispatch(self):
        pool, replicas, _ = _fake_pool(n=2, seed=0, cache_entries=8,
                                       cache_ttl=60.0)
        x = np.ones((1, 4), np.float32)
        f1 = pool.output_async(x)
        f1.result()
        assert f1._dl4j_cache == "miss"
        total = sum(r.calls for r in replicas)
        f2 = pool.output_async(x)
        assert f2._dl4j_cache == "hit"
        assert sum(r.calls for r in replicas) == total  # no dispatch
        f3 = pool.output_async(x, use_cache=False)
        f3.result()
        assert f3._dl4j_cache == "bypass"
        assert sum(r.calls for r in replicas) == total + 1
        cs = pool.stats()["cache"]
        assert cs == {"hits": 1, "misses": 1, "bypass": 1, "entries": 1,
                      "hit_rate": 0.5}
        pool.shutdown(drain=False)

    def test_zero_lookup_hit_rate_is_none(self):
        pool, _, _ = _fake_pool(n=2, seed=0, cache_entries=8)
        assert pool.stats()["cache"]["hit_rate"] is None
        pool.shutdown(drain=False)


# --------------------------------------------------------------------------
# adaptive batching
# --------------------------------------------------------------------------
class TestAdaptiveBatching:
    def _engine(self):
        reg = MetricsRegistry()
        return ParallelInference(_tiny_model(), batch_limit=32, workers=1,
                                 registry=reg, name="ab")

    def test_aimd_grow_and_shrink(self):
        pi = self._engine()
        try:
            b = AdaptiveBatcher(pi, target_p95_s=0.05, grow_step=2,
                                max_flush_timeout=0.01, flush_step=0.002)
            assert b.tick() is None  # no traffic -> no action
            # fast forwards + deep queue -> additive batch growth
            for _ in range(20):
                pi._h_forward.observe(0.001)
            for _ in range(40):
                pi._admission.admit()
            obs = b.tick()
            assert obs["action"] == "grow_batch"
            assert pi.effective_batch_limit == 32 + 2 - 2  # clamped at 32
            # fast forwards + shallow queue -> flush timeout grows
            for _ in range(40):
                pi._admission.release()
            pi.set_batching(8, 0.0)
            for _ in range(20):
                pi._h_forward.observe(0.001)
            obs = b.tick()
            assert obs["action"] == "grow_flush"
            assert pi.flush_timeout == pytest.approx(0.002)
            # p95 breach -> multiplicative decrease of both
            for _ in range(20):
                pi._h_forward.observe(0.2)
            obs = b.tick()
            assert obs["action"] == "shrink"
            assert pi.effective_batch_limit == 4
            assert pi.flush_timeout == pytest.approx(0.001)
        finally:
            pi.shutdown(drain=False)

    def test_set_batching_clamps(self):
        pi = self._engine()
        try:
            assert pi.set_batching(10_000, -3.0) == (32, 0.0)
            assert pi.set_batching(0, None) == (1, 0.0)
            s = pi.stats()
            assert s["effective_batch_limit"] == 1
            assert s["flush_timeout_s"] == 0.0
            # zero-request derived ratios are None, not 0-division
            assert s["padded_row_share"] is None
            assert s["batch_fill"] is None
        finally:
            pi.shutdown(drain=False)

    def test_flush_timeout_coalesces_requests(self):
        reg = MetricsRegistry()
        pi = ParallelInference(_tiny_model(), batch_limit=8, workers=1,
                               flush_timeout=0.5, registry=reg, name="ft")
        try:
            pi.output(np.ones((1, 4), np.float32))  # warm the jit
            base = pi.stats()["batches"]
            futs = [pi.output_async(np.ones((1, 4), np.float32))
                    for _ in range(4)]
            for f in futs:
                f.result(timeout=10)
            # without the flush wait the warm worker would fire ~4
            # one-row batches; the wait coalesces them into 1-2
            assert pi.stats()["batches"] - base <= 2
        finally:
            pi.shutdown(drain=False)


# --------------------------------------------------------------------------
# pool-wide hot swap
# --------------------------------------------------------------------------
class TestPoolSwap:
    def test_swap_all_replicas_and_rollback_on_partial_failure(self):
        class NthFire:
            """Raises on the n-th firing of one site (lets the swap
            succeed on replica 0 and fail on replica 1)."""

            def __init__(self, site, n):
                self.site, self.n, self.count = site, n, 0

            def fire(self, site):
                if site == self.site:
                    self.count += 1
                    if self.count == self.n:
                        raise RuntimeError("swap wire cut")

        reg = MetricsRegistry()
        inj = NthFire(SWAP_SITE, 2)
        pool = EnginePool(model=_tiny_model(1), replicas=2, workers=1,
                          registry=reg, name="sw", fault_injector=inj)
        try:
            x = np.ones((2, 4), np.float32)
            pool.output(x)
            with pytest.raises(RuntimeError, match="swap wire cut"):
                pool.swap_model(_tiny_model(2), version="2")
            # replica 0 was swapped then rolled back: every replica still
            # serves the original version
            assert [e.model_version for e in pool.replicas] == ["0", "0"]
            pool.output(x)
            # injector exhausted: the next swap lands everywhere
            retired = pool.swap_model(_tiny_model(2), version="2")
            assert [e.model_version for e in pool.replicas] == ["2", "2"]
            assert retired.version == "0"
            pool.output(x)
        finally:
            pool.shutdown(drain=False)

    def test_model_manager_drives_a_pool(self, tmp_path):
        from deeplearning4j_tpu.serving import ModelManager, ModelStore

        store = ModelStore(str(tmp_path / "registry"))
        store.publish("m", _tiny_model(1))
        store.publish("m", _tiny_model(2))
        reg = MetricsRegistry()
        pool = EnginePool(model=store.load("m", 1)[0], replicas=2,
                          workers=1, registry=reg, name="mg",
                          model_version="1")
        mgr = ModelManager(store, "m", engine=pool, registry=reg,
                           warmup_example=np.ones((1, 4), np.float32),
                           probation_seconds=0.0)
        try:
            x = np.ones((2, 4), np.float32)
            np.asarray(mgr.output(x))
            entry = mgr.deploy(2)
            assert str(entry.version) == "2"
            # deploy swapped EVERY replica
            assert [e.model_version for e in pool.replicas] == ["2", "2"]
            np.asarray(mgr.output(x))
            mgr.rollback()
            assert [e.model_version for e in pool.replicas] == ["1", "1"]
            np.asarray(mgr.output(x))
        finally:
            mgr.shutdown(drain=False)

    def test_swap_replica_count_mismatch_rejected(self):
        reg = MetricsRegistry()
        pool = EnginePool(model=_tiny_model(1), replicas=2, workers=1,
                          registry=reg, name="mm")
        try:
            sv = PoolServable([pool.replicas[0]._servable], pool.model, "9")
            with pytest.raises(ValueError, match="replicas"):
                pool.swap(sv)
        finally:
            pool.shutdown(drain=False)


# --------------------------------------------------------------------------
# decode replicas
# --------------------------------------------------------------------------
class TestDecodeDispatch:
    def test_submit_generate_p2c_and_slot_release(self):
        from deeplearning4j_tpu.parallel.decode import GenerationHandle

        class FakeDecode:
            def __init__(self, name):
                self.name = name
                self.calls = 0
                self.backlog = 0.0
                self.handles = []
                self._breaker = CircuitBreaker()

            @property
            def circuit_state(self):
                return self._breaker.state

            def load_score(self):
                return self.backlog

            def submit(self, prompt, *, priority=None, **kw):
                self.calls += 1
                h = GenerationHandle(f"{self.name}-req", Deadline.never())
                self.handles.append(h)
                return h

        reg = MetricsRegistry()
        reps = [FakeDecode("d0"), FakeDecode("d1")]
        pool = EnginePool(engines=reps, registry=reg, seed=5,
                          max_pending=16, name="dp")
        assert pool.decode_replicas == reps and pool.replicas == []
        handles = []
        for i in range(6):
            reps[0].backlog, reps[1].backlog = i % 2, (i + 1) % 2
            handles.append(pool.submit_generate([1, 2, 3]))
        assert reps[0].calls + reps[1].calls == 6
        assert reps[0].calls > 0 and reps[1].calls > 0
        assert pool._admission.pending == 6
        for h in handles:
            h._finish("completed")
        assert pool._admission.pending == 0
        # double-finish never over-releases
        handles[0]._finish("completed")
        assert pool._admission.pending == 0
        with pytest.raises(RuntimeError, match="no inference replicas"):
            pool.output_async(np.ones((1, 4), np.float32))
        pool.shutdown(drain=False)


# --------------------------------------------------------------------------
# concurrency smoke
# --------------------------------------------------------------------------
class TestPoolConcurrency:
    def test_concurrent_submitters_real_engines(self):
        reg = MetricsRegistry()
        pool = EnginePool(model=_tiny_model(), replicas=3, workers=1,
                          registry=reg, name="cc", cache_entries=4,
                          seed=11)
        try:
            errs = []

            def worker(i):
                x = np.full((1, 4), float(i % 5), np.float32)
                try:
                    for _ in range(10):
                        np.asarray(pool.output(x))
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not errs
            s = pool.stats()
            served = sum(s["dispatched"].values()) + s["cache"]["hits"]
            assert served == 80
            assert s["queue_depth"] == 0  # every pool slot released
        finally:
            pool.shutdown(drain=False)
