"""Tier-1 wiring for tools/check_serving_contract.py: the serving
status-code contract (README.md "Serving resilience") is enforced on
every test run, not just when someone remembers to run the tool."""

import os
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


def test_serving_contract_smoke():
    sys.path.insert(0, _TOOLS)
    try:
        import check_serving_contract
    finally:
        sys.path.remove(_TOOLS)
    assert check_serving_contract.main(log=lambda m: None) == 0
