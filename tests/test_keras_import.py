"""Keras h5 import golden tests (SURVEY.md §4 "Keras import": golden
outputs from Keras for each saved model). Models are built and saved with
the local TF/Keras, imported, and forward outputs compared on random data."""

import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
keras = tf.keras

from deeplearning4j_tpu.modelimport import KerasModelImport  # noqa: E402
from deeplearning4j_tpu.modelimport.keras import KerasImportError  # noqa: E402


def _import_and_compare(tmp_path, kmodel, x_keras, to_ours, atol=1e-4):
    path = str(tmp_path / "model.h5")
    kmodel.save(path)
    expected = np.asarray(kmodel(x_keras))
    ours = KerasModelImport.import_keras_model_and_weights(path)
    got = np.asarray(ours.output(to_ours(x_keras)))
    np.testing.assert_allclose(got, expected, atol=atol, rtol=1e-3)
    return ours


def test_mlp_import(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((12,)),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(8, activation="tanh"),
        keras.layers.Dense(3, activation="softmax"),
    ])
    x = np.random.RandomState(0).randn(4, 12).astype(np.float32)
    _import_and_compare(tmp_path, m, x, lambda a: a)


def test_cnn_import_with_flatten_permutation(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((10, 8, 3)),
        keras.layers.Conv2D(6, 3, padding="same", activation="relu"),
        keras.layers.MaxPooling2D(2),
        keras.layers.Conv2D(4, 3, padding="valid", strides=2,
                            activation="linear"),
        keras.layers.Flatten(),
        keras.layers.Dense(5, activation="softmax"),
    ])
    x = np.random.RandomState(1).rand(2, 10, 8, 3).astype(np.float32)
    # ours takes NCHW
    _import_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 3, 1, 2))


def test_cnn_batchnorm_dropout_global_pool(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((8, 8, 3)),
        keras.layers.Conv2D(5, 3, padding="same", use_bias=False),
        keras.layers.BatchNormalization(),
        keras.layers.Activation("relu"),
        keras.layers.Dropout(0.25),
        keras.layers.GlobalAveragePooling2D(),
        keras.layers.Dense(4),
    ])
    # fit one batch so BN moving stats are non-trivial
    m.compile(optimizer="sgd", loss="mse")
    rng = np.random.RandomState(2)
    m.fit(rng.rand(8, 8, 8, 3).astype(np.float32),
          rng.rand(8, 4).astype(np.float32), epochs=1, verbose=0)
    x = rng.rand(3, 8, 8, 3).astype(np.float32)
    # inference mode: dropout inactive, BN uses moving stats
    _import_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 3, 1, 2))


def test_lstm_import(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((7, 5)),  # [t, f]
        keras.layers.LSTM(6, return_sequences=False),
        keras.layers.Dense(3, activation="softmax"),
    ])
    x = np.random.RandomState(3).randn(4, 7, 5).astype(np.float32)
    # ours takes [b, f, t]
    _import_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 2, 1))


def test_lstm_return_sequences(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 4)),
        keras.layers.LSTM(5, return_sequences=True),
    ])
    x = np.random.RandomState(4).randn(2, 6, 4).astype(np.float32)
    path = str(tmp_path / "model.h5")
    m.save(path)
    expected = np.asarray(m(x))  # [b, t, u]
    ours = KerasModelImport.import_keras_model_and_weights(path)
    got = np.asarray(ours.output(x.transpose(0, 2, 1)))  # [b, u, t]
    np.testing.assert_allclose(got.transpose(0, 2, 1), expected, atol=1e-4,
                               rtol=1e-3)


def test_unsupported_layer_raises(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((4, 4, 1)),
        keras.layers.GaussianNoise(0.1),  # train-time noise: no silent map
        keras.layers.Flatten(),
        keras.layers.Dense(2),
    ])
    path = str(tmp_path / "model.h5")
    m.save(path)
    with pytest.raises(KerasImportError, match="GaussianNoise"):
        KerasModelImport.import_keras_model_and_weights(path)


def test_not_a_keras_file(tmp_path):
    import h5py

    path = str(tmp_path / "junk.h5")
    with h5py.File(path, "w") as f:
        f.create_dataset("x", data=np.zeros(3))
    with pytest.raises(KerasImportError, match="model_config"):
        KerasModelImport.import_keras_model_and_weights(path)


def test_dilated_conv_import(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((12, 12, 2)),
        keras.layers.Conv2D(3, 3, dilation_rate=2, activation="relu"),
        keras.layers.Flatten(),
        keras.layers.Dense(4),
    ])
    x = np.random.RandomState(5).rand(2, 12, 12, 2).astype(np.float32)
    _import_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 3, 1, 2))


def test_batchnorm_after_flatten_permuted(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 6, 3)),
        keras.layers.Conv2D(4, 3, padding="same"),
        keras.layers.Flatten(),
        keras.layers.BatchNormalization(),
        keras.layers.Dense(5),
    ])
    m.compile(optimizer="sgd", loss="mse")
    rng = np.random.RandomState(6)
    m.fit(rng.rand(16, 6, 6, 3).astype(np.float32),
          rng.rand(16, 5).astype(np.float32), epochs=1, verbose=0)
    x = rng.rand(3, 6, 6, 3).astype(np.float32)
    _import_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 3, 1, 2))


def test_go_backwards_lstm_rejected(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((5, 3)),
        keras.layers.LSTM(4, go_backwards=True),
    ])
    path = str(tmp_path / "model.h5")
    m.save(path)
    with pytest.raises(KerasImportError, match="go_backwards"):
        KerasModelImport.import_keras_model_and_weights(path)


# ---------------------------------------------------------------------------
# functional API -> ComputationGraph (VERDICT.md round 3 ask 6)
# ---------------------------------------------------------------------------

def _import_graph_and_compare(tmp_path, kmodel, x_keras, to_ours, atol=1e-4):
    path = str(tmp_path / "model.h5")
    kmodel.save(path)
    expected = np.asarray(kmodel(x_keras))
    ours = KerasModelImport.import_keras_model_and_weights(path)
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    assert isinstance(ours, ComputationGraph)
    got = np.asarray(ours.output(to_ours(x_keras)))
    np.testing.assert_allclose(got, expected, atol=atol, rtol=1e-3)
    return ours


def test_functional_resnet_style_import(tmp_path):
    """Residual Add + Concatenate branch + SeparableConv2D — the functional
    vertex set the reference maps onto ComputationGraph."""
    inp = keras.layers.Input((12, 12, 3))
    stem = keras.layers.Conv2D(8, 3, padding="same", use_bias=False)(inp)
    stem = keras.layers.BatchNormalization()(stem)
    stem = keras.layers.Activation("relu")(stem)
    # residual block
    r = keras.layers.Conv2D(8, 3, padding="same", activation="relu")(stem)
    r = keras.layers.Conv2D(8, 3, padding="same")(r)
    res = keras.layers.Add()([stem, r])
    res = keras.layers.Activation("relu")(res)
    # parallel branch + concat
    b1 = keras.layers.Conv2D(4, 1, padding="same", activation="relu")(res)
    b2 = keras.layers.SeparableConv2D(6, 3, padding="same",
                                      activation="relu")(res)
    merged = keras.layers.Concatenate()([b1, b2])
    pooled = keras.layers.GlobalAveragePooling2D()(merged)
    out = keras.layers.Dense(5, activation="softmax")(pooled)
    m = keras.Model(inp, out)

    x = np.random.RandomState(3).rand(2, 12, 12, 3).astype(np.float32)
    _import_graph_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 3, 1, 2))


def test_functional_bidirectional_lstm_import(tmp_path):
    inp = keras.layers.Input((7, 5))  # [t, features]
    h = keras.layers.Bidirectional(
        keras.layers.LSTM(6, return_sequences=True), merge_mode="concat")(inp)
    h = keras.layers.GlobalAveragePooling1D()(h)
    out = keras.layers.Dense(3, activation="softmax")(h)
    m = keras.Model(inp, out)
    x = np.random.RandomState(4).rand(2, 7, 5).astype(np.float32)
    # ours takes [batch, features, time]
    _import_graph_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 2, 1),
                              atol=1e-3)


def test_functional_multi_branch_elementwise(tmp_path):
    inp = keras.layers.Input((10,))
    a = keras.layers.Dense(8, activation="tanh")(inp)
    b = keras.layers.Dense(8, activation="relu")(inp)
    avg = keras.layers.Average()([a, b])
    mx = keras.layers.Maximum()([a, b])
    cat = keras.layers.Concatenate()([avg, mx])
    out = keras.layers.Dense(4, activation="softmax")(cat)
    m = keras.Model(inp, out)
    x = np.random.RandomState(5).randn(3, 10).astype(np.float32)
    _import_graph_and_compare(tmp_path, m, x, lambda a: a)


def test_functional_bidirectional_no_return_sequences_rejected(tmp_path):
    inp = keras.layers.Input((7, 5))
    h = keras.layers.Bidirectional(keras.layers.LSTM(6))(inp)
    out = keras.layers.Dense(3)(h)
    m = keras.Model(inp, out)
    path = str(tmp_path / "model.h5")
    m.save(path)
    with pytest.raises(KerasImportError, match="return_sequences"):
        KerasModelImport.import_keras_model_and_weights(path)


def test_functional_noop_flatten_aliases_producer(tmp_path):
    """Regression: a handler that adds no layer (Flatten on flat input)
    must alias the keras tensor to its producer, not to a stale vertex."""
    inp = keras.layers.Input((10,))
    flat = keras.layers.Flatten()(inp)
    out = keras.layers.Dense(4, activation="softmax")(flat)
    m = keras.Model(inp, out)
    x = np.random.RandomState(6).randn(3, 10).astype(np.float32)
    _import_graph_and_compare(tmp_path, m, x, lambda a: a)


def test_functional_concatenate_height_axis_rejected(tmp_path):
    """Concatenate over a spatial axis has no MergeVertex equivalent and
    must fail loudly instead of silently concatenating channels."""
    inp = keras.layers.Input((8, 8, 3))
    a = keras.layers.Conv2D(4, 1)(inp)
    b = keras.layers.Conv2D(4, 1)(inp)
    cat = keras.layers.Concatenate(axis=1)([a, b])  # height concat
    out = keras.layers.Dense(2)(keras.layers.GlobalAveragePooling2D()(cat))
    m = keras.Model(inp, out)
    path = str(tmp_path / "model.h5")
    m.save(path)
    with pytest.raises(KerasImportError, match="Concatenate axis 1"):
        KerasModelImport.import_keras_model_and_weights(path)


def test_embedding_lstm_import(tmp_path):
    """Keras Embedding -> our EmbeddingSequenceLayer: int ids in, parity."""
    m = keras.Sequential([
        keras.layers.Input((6,)),
        keras.layers.Embedding(50, 8),
        keras.layers.LSTM(5),
        keras.layers.Dense(3, activation="softmax"),
    ])
    ids = np.random.RandomState(7).randint(0, 50, (4, 6))
    path = str(tmp_path / "model.h5")
    m.save(path)
    expected = np.asarray(m(ids))
    ours = KerasModelImport.import_keras_model_and_weights(path)
    got = np.asarray(ours.output(ids.astype(np.int32)))
    np.testing.assert_allclose(got, expected, atol=1e-4, rtol=1e-3)


def test_lambda_layer_via_registry(tmp_path):
    """Lambda imports through the pre-registered forward (the reference's
    SameDiffLambdaLayer registration contract); unregistered Lambda fails
    with a clear error."""
    from deeplearning4j_tpu.modelimport.keras import (
        KERAS_LAMBDAS, register_keras_lambda,
    )

    m = keras.Sequential([
        keras.layers.Input((6,)),
        keras.layers.Dense(5, activation="relu"),
        keras.layers.Lambda(lambda t: t * 2.0 + 1.0, name="double_shift"),
        keras.layers.Dense(3, activation="softmax"),
    ])
    x = np.random.RandomState(3).randn(4, 6).astype(np.float32)

    path = str(tmp_path / "lam.h5")
    m.save(path)
    with pytest.raises(KerasImportError, match="register_keras_lambda"):
        KerasModelImport.import_keras_model_and_weights(path)

    register_keras_lambda("double_shift", lambda t: t * 2.0 + 1.0)
    try:
        ours = KerasModelImport.import_keras_model_and_weights(path)
        got = np.asarray(ours.output(x))
        np.testing.assert_allclose(got, np.asarray(m(x)), atol=1e-4,
                                   rtol=1e-3)
    finally:
        KERAS_LAMBDAS.pop("double_shift", None)


def test_custom_layer_registry(tmp_path):
    """A custom Keras class imports through a registered handler
    (reference: KerasLayer.registerCustomLayer)."""
    from deeplearning4j_tpu.modelimport.keras import (
        KERAS_CUSTOM_LAYERS, register_keras_custom_layer,
    )
    from deeplearning4j_tpu.nn.layers import ActivationLayer
    from deeplearning4j_tpu.nn import Activation

    @keras.utils.register_keras_serializable("test")
    class Swish6(keras.layers.Layer):
        def call(self, t):
            return tf.nn.relu6(t)

    m = keras.Sequential([
        keras.layers.Input((4,)),
        keras.layers.Dense(5),
        Swish6(name="r6"),
    ])
    x = np.random.RandomState(4).randn(3, 4).astype(np.float32)
    path = str(tmp_path / "custom.h5")
    m.save(path)

    register_keras_custom_layer(
        "Swish6",
        lambda imp, conf: imp._add(ActivationLayer(
            name=conf["name"], activation=Activation.RELU6)))
    try:
        ours = KerasModelImport.import_keras_model_and_weights(path)
        got = np.asarray(ours.output(x))
        np.testing.assert_allclose(got, np.asarray(m(x)), atol=1e-4,
                                   rtol=1e-3)
    finally:
        KERAS_CUSTOM_LAYERS.pop("Swish6", None)


# ---- round-5 breadth: GRU / SimpleRNN / Conv1D / DepthwiseConv2D /
# TimeDistributed / ZeroPadding2D / UpSampling2D / advanced activations
# (VERDICT r4 ask 7; reference: SURVEY.md:137 '~60 KerasLayer subclasses')


def test_gru_import(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((7, 5)),
        keras.layers.GRU(6, return_sequences=False),  # reset_after default
        keras.layers.Dense(3, activation="softmax"),
    ])
    x = np.random.RandomState(10).randn(4, 7, 5).astype(np.float32)
    _import_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 2, 1))


def test_gru_reset_after_false_import(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 4)),
        keras.layers.GRU(5, reset_after=False, return_sequences=True),
    ])
    x = np.random.RandomState(11).randn(2, 6, 4).astype(np.float32)
    path = str(tmp_path / "model.h5")
    m.save(path)
    expected = np.asarray(m(x))  # [b, t, u]
    ours = KerasModelImport.import_keras_model_and_weights(path)
    got = np.asarray(ours.output(x.transpose(0, 2, 1)))  # [b, u, t]
    np.testing.assert_allclose(got.transpose(0, 2, 1), expected, atol=1e-4,
                               rtol=1e-3)


def test_simple_rnn_import(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((5, 4)),
        keras.layers.SimpleRNN(6, activation="relu", return_sequences=False),
        keras.layers.Dense(2),
    ])
    x = (0.1 * np.random.RandomState(12).randn(3, 5, 4)).astype(np.float32)
    _import_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 2, 1))


def test_conv1d_import(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((12, 5)),
        keras.layers.Conv1D(8, 3, padding="same", activation="relu"),
        keras.layers.Conv1D(6, 3, padding="valid", strides=2),
        keras.layers.GlobalMaxPooling1D(),
        keras.layers.Dense(3),
    ])
    x = np.random.RandomState(13).randn(2, 12, 5).astype(np.float32)
    _import_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 2, 1))


def test_depthwise_conv2d_import(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((9, 9, 4)),
        keras.layers.DepthwiseConv2D(3, padding="same", depth_multiplier=2,
                                     activation="relu"),
        keras.layers.DepthwiseConv2D(3, padding="valid"),
        keras.layers.GlobalAveragePooling2D(),
        keras.layers.Dense(3),
    ])
    x = np.random.RandomState(14).rand(2, 9, 9, 4).astype(np.float32)
    _import_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 3, 1, 2))


def test_time_distributed_dense_import(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 5)),
        keras.layers.TimeDistributed(keras.layers.Dense(7, activation="tanh")),
        keras.layers.LSTM(4, return_sequences=False),
    ])
    x = np.random.RandomState(15).randn(3, 6, 5).astype(np.float32)
    _import_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 2, 1))


def test_zero_padding_and_upsampling_import(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 7, 3)),
        keras.layers.ZeroPadding2D(((1, 2), (0, 3))),
        keras.layers.Conv2D(4, 3, padding="valid", activation="relu"),
        keras.layers.UpSampling2D((2, 3)),
        keras.layers.GlobalAveragePooling2D(),
        keras.layers.Dense(2),
    ])
    x = np.random.RandomState(16).rand(2, 6, 7, 3).astype(np.float32)
    _import_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 3, 1, 2))


def test_advanced_activations_import(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((10,)),
        keras.layers.Dense(8),
        keras.layers.LeakyReLU(negative_slope=0.2)
        if "negative_slope" in
        keras.layers.LeakyReLU.__init__.__code__.co_varnames
        else keras.layers.LeakyReLU(alpha=0.2),
        keras.layers.Dense(6),
        keras.layers.ELU(alpha=0.7),
        keras.layers.Dense(5),
        keras.layers.PReLU(),
        keras.layers.Dense(3),
    ])
    # exercise nonzero PReLU alphas (fresh init is zeros = plain relu)
    weights = m.get_weights()
    rng = np.random.RandomState(17)
    for i, w in enumerate(weights):
        if w.shape == (5,):
            weights[i] = rng.rand(5).astype(np.float32) * 0.5
    m.set_weights(weights)
    x = rng.randn(4, 10).astype(np.float32)
    _import_and_compare(tmp_path, m, x, lambda a: a)


def test_prelu_conv_shared_axes_import(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 6, 3)),
        keras.layers.Conv2D(4, 3, padding="same"),
        keras.layers.PReLU(shared_axes=[1, 2]),  # one alpha per channel
        keras.layers.GlobalAveragePooling2D(),
        keras.layers.Dense(2),
    ])
    weights = m.get_weights()
    rng = np.random.RandomState(18)
    for i, w in enumerate(weights):
        if w.shape == (1, 1, 4):
            weights[i] = (rng.rand(1, 1, 4) * 0.5).astype(np.float32)
    m.set_weights(weights)
    x = rng.rand(2, 6, 6, 3).astype(np.float32)
    _import_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 3, 1, 2))


def test_functional_gru_and_upsampling_import(tmp_path):
    """The VERDICT r4 ask-7 'done' case: a functional model using
    GRU + UpSampling2D imports and matches Keras."""
    img_in = keras.layers.Input((4, 4, 3), name="img")
    a = keras.layers.UpSampling2D(2)(img_in)
    a = keras.layers.Conv2D(5, 3, padding="same", activation="relu")(a)
    a = keras.layers.GlobalAveragePooling2D()(a)
    seq_in = keras.layers.Input((6, 4), name="seq")
    b = keras.layers.GRU(5, return_sequences=True)(seq_in)
    b = keras.layers.GlobalMaxPooling1D()(b)
    out = keras.layers.Concatenate()([a, b])
    out = keras.layers.Dense(3, activation="softmax")(out)
    m = keras.Model([img_in, seq_in], out)

    rng = np.random.RandomState(19)
    xi = rng.rand(2, 4, 4, 3).astype(np.float32)
    xs = rng.randn(2, 6, 4).astype(np.float32)
    path = str(tmp_path / "model.h5")
    m.save(path)
    expected = np.asarray(m([xi, xs]))
    ours = KerasModelImport.import_keras_model_and_weights(path)
    got = np.asarray(ours.output(xi.transpose(0, 3, 1, 2),
                                 xs.transpose(0, 2, 1)))
    np.testing.assert_allclose(got, expected, atol=1e-4, rtol=1e-3)


def test_conv3d_import(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 7, 8, 2)),  # (d, h, w, c)
        keras.layers.Conv3D(4, 3, padding="same", activation="relu"),
        keras.layers.Conv3D(3, (2, 3, 3), padding="valid",
                            strides=(1, 2, 2)),
        keras.layers.GlobalAveragePooling3D(),
        keras.layers.Dense(2),
    ])
    x = np.random.RandomState(20).rand(2, 6, 7, 8, 2).astype(np.float32)
    # ours takes NCDHW
    _import_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 4, 1, 2, 3))


def test_cropping_and_conv2d_transpose_import(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((8, 8, 3)),
        keras.layers.Cropping2D(((1, 1), (2, 0))),
        keras.layers.Conv2DTranspose(5, 3, strides=2, padding="same",
                                     activation="relu"),
        keras.layers.Conv2DTranspose(4, 2, strides=2, padding="valid"),
        keras.layers.GlobalAveragePooling2D(),
        keras.layers.Dense(2),
    ])
    x = np.random.RandomState(21).rand(2, 8, 8, 3).astype(np.float32)
    _import_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 3, 1, 2))


def test_layer_normalization_import(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((6, 5)),
        keras.layers.LayerNormalization(epsilon=1e-4),
        keras.layers.GRU(4, return_sequences=False),
        keras.layers.Dense(8),
        keras.layers.LayerNormalization(),
        keras.layers.Dense(2),
    ])
    # non-trivial gamma/beta
    weights = m.get_weights()
    rng = np.random.RandomState(22)
    m.set_weights([w + 0.1 * rng.rand(*w.shape).astype(np.float32)
                   for w in weights])
    x = rng.randn(3, 6, 5).astype(np.float32)
    _import_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 2, 1))


def test_pooling1d_import(tmp_path):
    m = keras.Sequential([
        keras.layers.Input((12, 6)),
        keras.layers.Conv1D(8, 3, padding="same", activation="relu"),
        keras.layers.MaxPooling1D(2),
        keras.layers.AveragePooling1D(3, strides=2, padding="same"),
        keras.layers.GlobalMaxPooling1D(),
        keras.layers.Dense(3),
    ])
    x = np.random.RandomState(23).randn(2, 12, 6).astype(np.float32)
    _import_and_compare(tmp_path, m, x, lambda a: a.transpose(0, 2, 1))
