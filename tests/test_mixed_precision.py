"""bf16 mixed-precision training path (VERDICT.md round-1 item 3).

Contract: params + updater state stay in the model dtype (f32 master
weights); forward/backward math runs in compute_dtype; BN statistics and
loss math stay >= f32; user-facing outputs come back in the model dtype.
Parity: a bf16 run must track its f32 twin within bf16 tolerance.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.model.zoo import BertEncoder
from deeplearning4j_tpu.nn import (
    Activation,
    InputType,
    LossFunction,
    NeuralNetConfiguration,
    WeightInit,
)
from deeplearning4j_tpu.nn.layers import (
    BatchNormalizationLayer,
    ConvolutionLayer,
    DenseLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
from deeplearning4j_tpu.train.graph_solver import GraphSolver
from deeplearning4j_tpu.train.updaters import Sgd


def _small_net(compute_dtype):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(7)
        .data_type("float32")
        .compute_dtype(compute_dtype)
        .updater(Sgd(0.1))
        .weight_init(WeightInit.XAVIER)
        .list()
        .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3), activation=Activation.RELU))
        .layer(BatchNormalizationLayer())
        .layer(DenseLayer(n_out=16, activation=Activation.RELU))
        .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT, activation=Activation.SOFTMAX))
        .set_input_type(InputType.convolutional(8, 8, 2))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _data():
    rng = np.random.RandomState(0)
    x = rng.rand(8, 2, 8, 8).astype(np.float32)
    y = np.zeros((8, 3), np.float32)
    y[np.arange(8), rng.randint(0, 3, 8)] = 1.0
    return x, y


def test_bf16_params_stay_f32_and_output_dtype():
    net = _small_net("bfloat16")
    x, y = _data()
    net.fit(x, y, epochs=2)
    for lname, lp in net.params.items():
        for k, a in lp.items():
            assert a.dtype == jnp.float32, f"{lname}/{k} master param degraded to {a.dtype}"
    # BN running stats stayed f32
    for lname, st in net.state.items():
        for k, a in st.items():
            assert a.dtype == jnp.float32, f"{lname}/{k} state degraded to {a.dtype}"
    out = net.output(x)
    assert out.dtype == jnp.float32


def test_bf16_tracks_f32_losses():
    x, y = _data()
    net32 = _small_net(None)
    net16 = _small_net("bfloat16")
    # identical init (same seed/config apart from compute_dtype)
    chex_equal = jnp.allclose(
        net32.params["layer_0"]["W"], net16.params["layer_0"]["W"]
    )
    assert chex_equal
    from deeplearning4j_tpu.train.solver import Solver

    s32, s16 = Solver(net32), Solver(net16)
    for _ in range(5):
        l32, _ = s32.fit_batch(x, y)
        l16, _ = s16.fit_batch(x, y)
    # bf16 has ~3 decimal digits; training for 5 steps stays within a few %
    assert float(l16) == pytest.approx(float(l32), rel=0.15)


def test_score_is_f32_under_bf16():
    net = _small_net("bfloat16")
    x, y = _data()
    s = net.score(x, y)
    assert isinstance(s, float) and np.isfinite(s)
    # the f32 twin must agree to bf16 tolerance — score math stays >= f32
    s32 = _small_net(None).score(x, y)
    assert s == pytest.approx(s32, rel=0.1)


def test_bf16_int_ids_not_corrupted():
    """Regression (round-3 ADVICE high): integer token ids must never pass
    through a float cast — bf16 represents integers exactly only up to 256,
    so a float-cast id above that lands on the wrong embedding row. One SGD
    step must touch exactly the embedding rows of the fed ids."""
    from deeplearning4j_tpu.train.updaters import Sgd

    enc = BertEncoder(
        vocab_size=1000, hidden=8, n_layers=1, n_heads=2, ffn_size=16,
        max_len=8, seed=5, compute_dtype="bfloat16", updater=Sgd(1.0),
    )
    model = enc.init()
    solver = GraphSolver(model)
    # odd ids above 512: bf16 spacing there is 4, so every one of these
    # would round to a different (even) row under the old float-cast path
    ids = np.array([[513, 515, 517, 519]], np.int64)
    w_before = np.asarray(model.params["tok_emb"]["W"], np.float32).copy()
    solver.fit_batch((ids,), (np.asarray(ids),))
    w_after = np.asarray(model.params["tok_emb"]["W"], np.float32)
    changed = set(np.where(np.any(w_before != w_after, axis=1))[0].tolist())
    assert changed == {513, 515, 517, 519}, f"wrong embedding rows updated: {sorted(changed)}"


def test_uint8_image_inputs_still_promote_to_float():
    """Regression for the id-preservation fix: integer dtypes are kept ONLY
    for embedding-fed inputs; uint8 image batches must still promote to the
    model float dtype (conv would otherwise reject mixed dtypes)."""
    net = _small_net("bfloat16")
    rng = np.random.RandomState(3)
    x_u8 = (rng.rand(4, 2, 8, 8) * 255).astype(np.uint8)
    out = net.output(x_u8)
    assert out.dtype == jnp.float32
    assert np.isfinite(np.asarray(out)).all()
    y = np.zeros((4, 3), np.float32)
    y[np.arange(4), rng.randint(0, 3, 4)] = 1.0
    net.fit(x_u8, y, epochs=1)  # train path takes the same cast


def test_bf16_output_matches_f32_rows_for_large_ids():
    """output() parity: with ids > 256 the bf16 model must read the SAME
    embedding rows as the f32 model (values differ only by bf16 rounding)."""
    kw = dict(vocab_size=600, hidden=8, n_layers=1, n_heads=2, ffn_size=16,
              max_len=8, seed=9)
    m16 = BertEncoder(compute_dtype="bfloat16", **kw).init()
    m32 = BertEncoder(**kw).init()
    ids = np.array([[257, 301, 511, 599]], np.int32)
    o16 = np.asarray(m16.output(ids), np.float32)
    o32 = np.asarray(m32.output(ids), np.float32)
    # wrong rows produce O(1) softmax differences; rounding stays ~1e-2
    assert np.max(np.abs(o16 - o32)) < 0.05


def test_bert_encoder_zoo_trains_and_loss_decreases():
    from deeplearning4j_tpu.train.updaters import Adam

    enc = BertEncoder(
        vocab_size=50, hidden=16, n_layers=2, n_heads=2, ffn_size=32,
        max_len=16, seed=11, compute_dtype="bfloat16", updater=Adam(1e-2),
    )
    model = enc.init()
    solver = GraphSolver(model)
    rng = np.random.RandomState(1)
    ids = jnp.asarray(rng.randint(0, 50, (4, 8)), jnp.int32)
    labels = ids  # trivially learnable: predict the input token
    losses = [float(solver.fit_batch((ids,), (labels,))) for _ in range(30)]
    assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0] * 0.8, f"no learning: {losses[0]} -> {losses[-1]}"
    out = model.output(ids)
    assert out.shape == (4, 50, 8)
    assert out.dtype == jnp.float32


def test_bert_encoder_f32_graph_shapes():
    enc = BertEncoder(
        vocab_size=40, hidden=8, n_layers=1, n_heads=2, ffn_size=16,
        max_len=8, seed=3,
    )
    model = enc.init()
    n = model.num_params()
    # embeddings 40*8 + pos 8*8 + block(ln1 16 + attn 4*64 + ln2 16 + ffn1
    # 8*16+16 + ffn2 16*8+8) + final_ln 16 + mlm 8*40+40
    assert n > 0
    ids = jnp.zeros((2, 8), jnp.int32)
    out = model.output(ids)
    assert out.shape == (2, 40, 8)


def test_gradient_checkpointing_preserves_values():
    """Per-layer remat (SURVEY §7 jax.checkpoint trade) must not change the
    training math: same seed, same batch -> identical losses with and
    without gradient_checkpointing, on both Sequential and Graph paths."""
    import numpy as np

    from deeplearning4j_tpu.model.zoo import BertEncoder
    from deeplearning4j_tpu.train.graph_solver import GraphSolver

    rs = np.random.RandomState(0)
    ids = rs.randint(0, 300, (2, 16)).astype(np.int32)

    def losses(remat):
        enc = BertEncoder(vocab_size=300, hidden=32, n_layers=2, n_heads=2,
                          ffn_size=64, max_len=32, seed=11,
                          gradient_checkpointing=remat)
        model = enc.init()
        s = GraphSolver(model)
        return [float(s.fit_batch((ids,), (ids,))) for _ in range(3)]

    np.testing.assert_allclose(losses(False), losses(True), rtol=1e-6)


def test_gradient_checkpointing_sequential_with_masks():
    """Sequential path under remat: identical losses with/without, on a
    recurrent net with dropout rng + sequence masks (exercises the
    rng/mask threading through the checkpointed fn)."""
    import numpy as np

    from deeplearning4j_tpu.nn import (Activation, InputType, LossFunction,
                                       NeuralNetConfiguration, WeightInit)
    from deeplearning4j_tpu.nn.layers import LSTMLayer, RnnOutputLayer
    from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.train.solver import Solver
    from deeplearning4j_tpu.train.updaters import Sgd

    rs = np.random.RandomState(1)
    x = rs.rand(2, 4, 6).astype(np.float32)  # [b, f, t]
    y = rs.rand(2, 3, 6).astype(np.float32)
    mask = np.ones((2, 6), np.float32)
    mask[:, 4:] = 0.0

    def losses(remat):
        b = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.1))
             .weight_init(WeightInit.XAVIER))
        if remat:
            b = b.gradient_checkpointing(True)
        conf = (b.list()
                .layer(LSTMLayer(n_out=8, activation=Activation.TANH,
                                 dropout=0.9))
                .layer(RnnOutputLayer(n_out=3, loss=LossFunction.MSE,
                                      activation=Activation.IDENTITY))
                .set_input_type(InputType.recurrent(4, 6)).build())
        net = MultiLayerNetwork(conf).init()
        s = Solver(net)
        return [float(s.fit_batch(x, y, mask=mask)[0]) for _ in range(3)]

    np.testing.assert_allclose(losses(False), losses(True), rtol=1e-6)
