"""Tier-1 wiring for tools/check_rewrite_equivalence.py: every rewrite
pass must stay numerically equivalent on matching graphs (forward AND
backward), a provable no-op on BERT/LSTM/MoE graphs, and the serving path
must fold before warm while the store artifact stays un-rewritten —
enforced on every test run, not just when someone runs the tool."""

import os
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


def test_rewrite_equivalence_contract():
    sys.path.insert(0, _TOOLS)
    try:
        import check_rewrite_equivalence
    finally:
        sys.path.remove(_TOOLS)
    assert check_rewrite_equivalence.main(log=lambda m: None) == 0
