"""Sorted grouped expert matmul (ops/grouped_matmul.py, ISSUE 18).

Tier-1 contract: the masked-XLA reference equals a naive per-group numpy
loop (including empty groups and dropped rows past the frontier), the
Pallas kernel (interpret mode on CPU) equals the reference, the custom
VJP equals ``jax.grad`` of the reference and float64 numerics, and the
impl seam validates its inputs.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import (
    grouped_matmul,
    grouped_matmul_impl,
    grouped_matmul_reference,
    set_grouped_matmul_impl,
)
from deeplearning4j_tpu.ops.grouped_matmul import _gmm_pallas, _tiling


def _case(seed=0, e=4, d=8, h=16, n=40, sizes=(7, 0, 12, 5),
          dtype=np.float32):
    """lhs rows sorted by group; sum(sizes) < n leaves dropped tail rows."""
    assert len(sizes) == e and sum(sizes) <= n
    rs = np.random.RandomState(seed)
    lhs = rs.randn(n, d).astype(dtype)
    rhs = rs.randn(e, d, h).astype(dtype)
    gs = np.asarray(sizes, np.int32)
    return lhs, gs, rhs


def _naive(lhs, group_sizes, rhs):
    n, _ = lhs.shape
    e, _, h = rhs.shape
    out = np.zeros((n, h), np.float64)
    start = 0
    for g in range(e):
        stop = start + int(group_sizes[g])
        out[start:stop] = lhs[start:stop].astype(np.float64) \
            @ rhs[g].astype(np.float64)
        start = stop
    return out  # rows past the frontier stay zero


def test_reference_matches_naive_loop():
    lhs, gs, rhs = _case()
    y = np.asarray(grouped_matmul_reference(jnp.asarray(lhs),
                                            jnp.asarray(gs),
                                            jnp.asarray(rhs)))
    np.testing.assert_allclose(y, _naive(lhs, gs, rhs), rtol=1e-5,
                               atol=1e-5)
    # dropped rows (past sum(group_sizes)) produce exactly zero
    np.testing.assert_array_equal(y[int(gs.sum()):], 0.0)


def test_empty_and_full_groups():
    lhs, gs, rhs = _case(e=3, sizes=(0, 0, 6), n=6)
    y = np.asarray(grouped_matmul(jnp.asarray(lhs), jnp.asarray(gs),
                                  jnp.asarray(rhs)))
    np.testing.assert_allclose(y, _naive(lhs, gs, rhs), rtol=1e-5,
                               atol=1e-5)


def test_pallas_interpret_matches_reference():
    lhs, gs, rhs = _case(seed=2)
    m_pad = _tiling(lhs.shape[0], None, 8)[0]
    y_pl = _gmm_pallas(jnp.asarray(lhs), jnp.asarray(rhs), jnp.asarray(gs),
                       m_pad, 8, interpret=True)
    y_ref = grouped_matmul_reference(jnp.asarray(lhs), jnp.asarray(gs),
                                     jnp.asarray(rhs))
    np.testing.assert_allclose(np.asarray(y_pl), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("max_group", [None, 16])
def test_vjp_matches_reference_grad(max_group):
    lhs, gs, rhs = _case(seed=3, dtype=np.float64)
    g = np.random.RandomState(9).randn(lhs.shape[0],
                                       rhs.shape[-1]).astype(np.float64)

    def f(fn):
        def loss(l, r):
            y = fn(l, jnp.asarray(gs), r, max_group_size=max_group)
            return jnp.sum(y * jnp.asarray(g))
        return jax.grad(loss, argnums=(0, 1))

    dl, dr = f(grouped_matmul)(jnp.asarray(lhs), jnp.asarray(rhs))
    dl_r, dr_r = f(grouped_matmul_reference)(jnp.asarray(lhs),
                                             jnp.asarray(rhs))
    np.testing.assert_allclose(np.asarray(dl), np.asarray(dl_r),
                               rtol=1e-10, atol=1e-10)
    np.testing.assert_allclose(np.asarray(dr), np.asarray(dr_r),
                               rtol=1e-10, atol=1e-10)


def test_vjp_matches_central_difference():
    lhs, gs, rhs = _case(seed=4, e=2, d=3, h=4, n=7, sizes=(3, 2),
                         dtype=np.float64)

    def loss(l, r):
        return jnp.sum(jnp.square(
            grouped_matmul(l, jnp.asarray(gs), r)))

    dl = np.asarray(jax.grad(loss, 0)(jnp.asarray(lhs), jnp.asarray(rhs)))
    eps = 1e-6
    for (i, j) in [(0, 0), (2, 1), (4, 2), (6, 0)]:  # incl. a dropped row
        lp, lm = lhs.copy(), lhs.copy()
        lp[i, j] += eps
        lm[i, j] -= eps
        num = (loss(jnp.asarray(lp), jnp.asarray(rhs))
               - loss(jnp.asarray(lm), jnp.asarray(rhs))) / (2 * eps)
        np.testing.assert_allclose(dl[i, j], float(num), rtol=1e-5,
                                   atol=1e-8)


def test_bf16_uses_f32_accumulation():
    lhs, gs, rhs = _case(seed=5, n=32, sizes=(10, 6, 9, 7))
    y16 = np.asarray(grouped_matmul(
        jnp.asarray(lhs, jnp.bfloat16), jnp.asarray(gs),
        jnp.asarray(rhs, jnp.bfloat16)), np.float32)
    np.testing.assert_allclose(y16, _naive(lhs, gs, rhs), rtol=5e-2,
                               atol=5e-2)


def test_int8_rhs_is_cast_not_rejected():
    """Quantized expert slabs arrive as int8; the op casts to the lhs
    compute dtype (small integers are exact in float)."""
    lhs, gs, _ = _case(seed=6)
    rhs_q = np.random.RandomState(7).randint(-127, 128,
                                             (4, 8, 16)).astype(np.int8)
    y = np.asarray(grouped_matmul(jnp.asarray(lhs), jnp.asarray(gs),
                                  jnp.asarray(rhs_q)))
    np.testing.assert_allclose(
        y, _naive(lhs, gs, rhs_q.astype(np.float32)), rtol=1e-4, atol=1e-3)


def test_impl_seam_validates():
    assert grouped_matmul_impl() in ("auto", "pallas", "xla")
    prev = grouped_matmul_impl()
    try:
        set_grouped_matmul_impl("xla")
        assert grouped_matmul_impl() == "xla"
        with pytest.raises(ValueError, match="unknown grouped_matmul"):
            set_grouped_matmul_impl("cudnn")
    finally:
        set_grouped_matmul_impl(prev)


def test_shape_validation():
    lhs, gs, rhs = _case()
    with pytest.raises(ValueError):
        grouped_matmul(jnp.asarray(lhs), jnp.asarray(gs),
                       jnp.asarray(rhs[:, :5]))  # d mismatch
    with pytest.raises(ValueError):
        grouped_matmul(jnp.asarray(lhs), jnp.asarray(gs[:2]),
                       jnp.asarray(rhs))  # E mismatch
