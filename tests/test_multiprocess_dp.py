"""Multi-NODE data-parallel training without a cluster (SURVEY §4
"Distributed without a cluster"): two separate OS processes, each owning
2 virtual CPU devices, joined by ``jax.distributed.initialize`` over
loopback (Gloo collectives — the DCN stand-in). Each process feeds its
LOCAL half of the global batch to ``DistributedTrainer`` over a 4-device
global mesh; GSPMD emits the cross-process all-reduce. Asserts the loss
decreases and the final params are bit-identical across processes AND
match a single-process run on the concatenated batch — the reference's
TestSparkMultiLayerParameterAveraging convergence contract, tightened to
exact equality (synchronous all-reduce is deterministic, unlike the
reference's async path).
"""

import json
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_WORKER = textwrap.dedent("""
    import json, os, sys
    pid = int(sys.argv[1]); port = sys.argv[2]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(f"localhost:{port}", num_processes=2,
                               process_id=pid)
    import numpy as np
    from deeplearning4j_tpu.nn import (Activation, InputType, LossFunction,
                                       NeuralNetConfiguration, WeightInit)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.trainer import DistributedTrainer
    from deeplearning4j_tpu.train.updaters import Sgd

    def build():
        conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
                .weight_init(WeightInit.XAVIER).list()
                .layer(DenseLayer(n_out=16, activation=Activation.TANH))
                .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(8)).build())
        return MultiLayerNetwork(conf).init()

    net = build()
    trainer = DistributedTrainer(net, mesh=make_mesh(data=4))
    assert trainer._multiprocess, "expected the multi-process path"

    rng = np.random.RandomState(0)
    X = rng.rand(16, 8).astype(np.float32)          # GLOBAL batch
    Y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)]
    lo, hi = (0, 8) if pid == 0 else (8, 16)        # this process's rows

    scores = []
    for _ in range(10):
        scores.append(float(trainer.fit_batch(X[lo:hi], Y[lo:hi])))

    flat = np.concatenate([
        np.asarray(jax.device_get(v)).ravel()
        for ln in sorted(trainer.params)
        for k, v in sorted(trainer.params[ln].items())])
    print("RESULT " + json.dumps({
        "pid": pid, "first": scores[0], "last": scores[-1],
        "param_sum": float(flat.sum()),
        "param_digest": float(np.abs(flat).sum())}), flush=True)
""")


@pytest.mark.slow
def test_two_process_data_parallel_fit():
    port = _free_port()
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, str(i), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)]
    results = {}
    logs = []
    for i, p in enumerate(procs):
        out, _ = p.communicate(timeout=420)
        logs.append(out)
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        for line in out.splitlines():
            if line.startswith("RESULT "):
                r = json.loads(line[len("RESULT "):])
                results[r["pid"]] = r
    assert set(results) == {0, 1}, f"missing results: {logs}"
    r0, r1 = results[0], results[1]
    # replicated params agree exactly across processes
    assert r0["param_sum"] == r1["param_sum"]
    assert r0["param_digest"] == r1["param_digest"]
    # the (global-mean) loss decreases and both processes report the same
    assert r0["last"] < r0["first"]
    assert abs(r0["last"] - r1["last"]) < 1e-9

    # single-process reference on the same GLOBAL batch: same final params
    from deeplearning4j_tpu.nn import (Activation, InputType, LossFunction,
                                       NeuralNetConfiguration, WeightInit)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.trainer import DistributedTrainer
    from deeplearning4j_tpu.train.updaters import Sgd
    import jax

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
            .weight_init(WeightInit.XAVIER).list()
            .layer(DenseLayer(n_out=16, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    # conftest gives this process 8 virtual devices; use 4 to mirror the
    # two-process run's 2x2 global mesh
    trainer = DistributedTrainer(
        net, mesh=make_mesh(devices=jax.devices()[:4], data=4))
    rng = np.random.RandomState(0)
    X = rng.rand(16, 8).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)]
    last = None
    for _ in range(10):
        last = float(trainer.fit_batch(X, Y))
    flat = np.concatenate([
        np.asarray(jax.device_get(v)).ravel()
        for ln in sorted(trainer.params)
        for k, v in sorted(trainer.params[ln].items())])
    np.testing.assert_allclose(float(flat.sum()), r0["param_sum"],
                               rtol=1e-5)
    np.testing.assert_allclose(last, r0["last"], rtol=1e-5)
