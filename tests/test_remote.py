"""JsonModelServer round-trip tests (SURVEY.md §2.2 "Remote inference")."""

import json
import threading
from urllib import request as urllib_request
from urllib.error import HTTPError

import numpy as np
import pytest

from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.remote import JsonModelServer, JsonRemoteInference


@pytest.fixture(scope="module")
def server():
    conf = (NeuralNetConfiguration.builder().seed(5).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    model = MultiLayerNetwork(conf).init()
    srv = JsonModelServer(model, port=0, workers=2).start()
    yield srv, model
    srv.stop()


def test_health(server):
    srv, _ = server
    with urllib_request.urlopen(
            f"http://127.0.0.1:{srv.port}/health") as r:
        assert json.loads(r.read())["status"] == "ok"


def test_predict_matches_local(server):
    srv, model = server
    client = JsonRemoteInference(
        f"http://127.0.0.1:{srv.port}/v1/serving")
    x = np.random.RandomState(0).randn(5, 4).astype(np.float32)
    remote = client.predict(x)
    local = np.asarray(model.output(x))
    np.testing.assert_allclose(remote, local, atol=1e-5)


def test_concurrent_requests_batched(server):
    srv, model = server
    client = JsonRemoteInference(
        f"http://127.0.0.1:{srv.port}/v1/serving")
    rng = np.random.RandomState(1)
    inputs = [rng.randn(2, 4).astype(np.float32) for _ in range(8)]
    results = [None] * 8

    def call(i):
        results[i] = client.predict(inputs[i])

    threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for i in range(8):
        np.testing.assert_allclose(results[i],
                                   np.asarray(model.output(inputs[i])),
                                   atol=1e-5)


def test_bad_request(server):
    srv, _ = server
    req = urllib_request.Request(
        f"http://127.0.0.1:{srv.port}/v1/serving",
        data=b'{"wrong": 1}',
        headers={"Content-Type": "application/json"})
    with pytest.raises(HTTPError) as ei:
        urllib_request.urlopen(req, timeout=10)
    assert ei.value.code == 400


def test_unknown_path(server):
    srv, _ = server
    with pytest.raises(HTTPError) as ei:
        urllib_request.urlopen(
            f"http://127.0.0.1:{srv.port}/nope", timeout=10)
    assert ei.value.code == 404


# ---------------------------------------------------------------------------
# Resilience: the status-code contract under overload, poison and drain
# (README.md "Serving resilience"). All failure timing is deterministic —
# the worker parks on an Event via injected latency, the breaker runs on a
# fake clock.
# ---------------------------------------------------------------------------
def _small_model(seed=5):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    return MultiLayerNetwork(conf).init()


def _gated_injector():
    from deeplearning4j_tpu.core.resilience import FaultInjector

    entered = threading.Event()
    release = threading.Event()

    def gate_sleep(_seconds):
        entered.set()
        assert release.wait(timeout=10), "test never released the worker"

    return FaultInjector(sleep=gate_sleep), entered, release


def _post(port, payload, timeout=10):
    req = urllib_request.Request(
        f"http://127.0.0.1:{port}/v1/serving",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib_request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_overload_sheds_503_with_retry_after():
    from deeplearning4j_tpu.parallel.inference import FORWARD_SITE

    inj, entered, release = _gated_injector()
    inj.inject_latency(FORWARD_SITE, 1.0, times=1)
    srv = JsonModelServer(_small_model(), port=0, workers=1, batch_limit=1,
                          queue_limit=2, fault_injector=inj).start()
    try:
        results = {}

        def call(name):
            try:
                results[name] = _post(srv.port, {"data": [[1, 2, 3, 4]]})
            except HTTPError as e:
                results[name] = (e.code, dict(e.headers))

        t1 = threading.Thread(target=call, args=("a",))
        t1.start()
        assert entered.wait(timeout=10)   # worker parked on request a
        t2 = threading.Thread(target=call, args=("b",))
        t2.start()                        # fills the pending window
        # the window (2) is full: shed instantly, not queued behind a
        import time as _time
        for _ in range(100):              # b must be admitted first
            if srv.stats()["accepted"] >= 2:
                break
            _time.sleep(0.01)
        with pytest.raises(HTTPError) as ei:
            _post(srv.port, {"data": [[1, 2, 3, 4]]})
        assert ei.value.code == 503
        assert float(ei.value.headers["Retry-After"]) > 0
        body = json.loads(ei.value.read())
        assert body["retryable"] is True
        release.set()
        t1.join(timeout=10)
        t2.join(timeout=10)
        assert results["a"][0] == 200 and results["b"][0] == 200
        assert srv.stats()["shed"] == 1
    finally:
        release.set()
        srv.stop()


def test_deadline_exceeded_maps_to_504():
    from deeplearning4j_tpu.parallel.inference import FORWARD_SITE

    inj, entered, release = _gated_injector()
    inj.inject_latency(FORWARD_SITE, 1.0, times=1)
    srv = JsonModelServer(_small_model(), port=0, workers=1, batch_limit=1,
                          fault_injector=inj).start()
    try:
        t = threading.Thread(
            target=lambda: _post(srv.port, {"data": [[1, 2, 3, 4]]}))
        t.start()
        assert entered.wait(timeout=10)
        with pytest.raises(HTTPError) as ei:  # parked behind the first
            _post(srv.port, {"data": [[1, 2, 3, 4]], "deadline_ms": 100})
        assert ei.value.code == 504
        release.set()
        t.join(timeout=10)
    finally:
        release.set()
        srv.stop()


def test_poisoned_forward_opens_circuit_health_degrades_then_recovers():
    from deeplearning4j_tpu.core.resilience import CircuitBreaker, FaultInjector
    from deeplearning4j_tpu.parallel.inference import FORWARD_SITE

    clk_t = [0.0]
    inj = FaultInjector()
    inj.inject_error(FORWARD_SITE, lambda: RuntimeError("poisoned jit"),
                     times=2)
    breaker = CircuitBreaker(failure_threshold=1.0, min_calls=2, window=4,
                             open_timeout=60.0, clock=lambda: clk_t[0])
    srv = JsonModelServer(_small_model(), port=0, workers=1, batch_limit=1,
                          circuit_breaker=breaker, fault_injector=inj).start()
    try:
        # two poisoned forwards -> 500 each, which trips the breaker
        for _ in range(2):
            with pytest.raises(HTTPError) as ei:
                _post(srv.port, {"data": [[1, 2, 3, 4]]})
            assert ei.value.code == 500
        # truthful health: degraded, 503 so load balancers rotate away
        with pytest.raises(HTTPError) as ei:
            urllib_request.urlopen(
                f"http://127.0.0.1:{srv.port}/health", timeout=10)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["status"] == "degraded"
        # requests fail fast with Retry-After while open
        with pytest.raises(HTTPError) as ei:
            _post(srv.port, {"data": [[1, 2, 3, 4]]})
        assert ei.value.code == 503
        assert float(ei.value.headers["Retry-After"]) > 0
        # after the open timeout the next request is the probe and closes it
        clk_t[0] += 60.0
        code, body = _post(srv.port, {"data": [[1, 2, 3, 4]]})
        assert code == 200 and len(body["output"][0]) == 3
        with urllib_request.urlopen(
                f"http://127.0.0.1:{srv.port}/health", timeout=10) as r:
            payload = json.loads(r.read())
        assert r.status == 200 and payload["status"] == "ok"
    finally:
        srv.stop()


def test_client_retries_on_503_then_succeeds():
    from deeplearning4j_tpu.core.resilience import RetryPolicy
    from deeplearning4j_tpu.parallel.inference import FORWARD_SITE

    inj, entered, release = _gated_injector()
    inj.inject_latency(FORWARD_SITE, 1.0, times=1)
    srv = JsonModelServer(_small_model(), port=0, workers=1, batch_limit=1,
                          queue_limit=1, fault_injector=inj).start()
    done = threading.Event()
    try:
        def first():
            try:
                _post(srv.port, {"data": [[1, 2, 3, 4]]})
            finally:
                done.set()

        t = threading.Thread(target=first)
        t.start()
        assert entered.wait(timeout=10)  # window of 1 is now full

        def unblocking_sleep(_seconds):
            release.set()                # the "backoff" frees the server
            assert done.wait(timeout=10)

        client = JsonRemoteInference(
            f"http://127.0.0.1:{srv.port}/v1/serving",
            retry_policy=RetryPolicy(max_retries=3, initial_backoff=0.01,
                                     seed=0),
            sleep=unblocking_sleep)
        out = client.predict(np.ones((1, 4), np.float32))
        assert out.shape == (1, 3)
        assert client.retries >= 1  # first attempt was shed with 503
        t.join(timeout=10)
    finally:
        release.set()
        srv.stop()


def test_client_never_retries_400():
    srv = JsonModelServer(_small_model(), port=0, workers=1).start()
    try:
        client = JsonRemoteInference(
            f"http://127.0.0.1:{srv.port}/v1/serving")
        with pytest.raises(ValueError):
            # a string serializes fine client-side but cannot become a
            # float32 array on the server -> 400, which must not retry
            client.predict("not-a-tensor")
        assert client.retries == 0
    finally:
        srv.stop()


def test_stats_endpoint(server):
    srv, _ = server
    with urllib_request.urlopen(
            f"http://127.0.0.1:{srv.port}/stats", timeout=10) as r:
        s = json.loads(r.read())
    assert s["circuit_state"] == "closed"
    assert {"accepted", "shed", "timed_out", "failed",
            "queue_depth"} <= set(s)


def test_graceful_drain_on_stop():
    from deeplearning4j_tpu.parallel.inference import FORWARD_SITE

    inj, entered, release = _gated_injector()
    inj.inject_latency(FORWARD_SITE, 1.0, times=1)
    srv = JsonModelServer(_small_model(), port=0, workers=1, batch_limit=1,
                          fault_injector=inj).start()
    results = {}

    def call():
        results["inflight"] = _post(srv.port, {"data": [[1, 2, 3, 4]]})

    t = threading.Thread(target=call)
    t.start()
    assert entered.wait(timeout=10)   # request accepted, worker parked
    stopper = threading.Thread(target=srv.stop)
    stopper.start()
    import time as _time
    for _ in range(100):              # wait until stop() flips to draining
        if srv._draining:
            break
        _time.sleep(0.01)
    with pytest.raises(HTTPError) as ei:  # health is truthful mid-drain
        urllib_request.urlopen(
            f"http://127.0.0.1:{srv.port}/health", timeout=10)
    assert ei.value.code == 503
    assert json.loads(ei.value.read())["status"] == "draining"
    release.set()                     # in-flight work finishes, then teardown
    stopper.join(timeout=15)
    t.join(timeout=10)
    assert results["inflight"][0] == 200
    from urllib.error import URLError
    with pytest.raises(URLError):     # fully stopped: connection refused
        urllib_request.urlopen(
            f"http://127.0.0.1:{srv.port}/health", timeout=2)


def test_health_and_stats_carry_replica_identity(server):
    """ISSUE 12 satellite: /health and /stats carry a stable identity
    block (name/uptime_seconds/pid) so pool fan-out failures are
    attributable to a host."""
    import os as _os

    srv, _ = server
    for path in ("/health", "/stats"):
        with urllib_request.urlopen(
                f"http://127.0.0.1:{srv.port}{path}", timeout=10) as r:
            payload = json.loads(r.read())
        ident = payload["replica"]
        assert ident["name"] == srv.name
        assert ident["pid"] == _os.getpid()
        assert ident["uptime_seconds"] >= 0.0
    # uptime advances between reads
    import time as _time

    _time.sleep(0.05)
    with urllib_request.urlopen(
            f"http://127.0.0.1:{srv.port}/health", timeout=10) as r:
        later = json.loads(r.read())["replica"]["uptime_seconds"]
    assert later > ident["uptime_seconds"] - 1e-9


def test_post_responses_carry_load_score(server):
    srv, _ = server
    req = urllib_request.Request(
        f"http://127.0.0.1:{srv.port}/v1/serving",
        data=json.dumps({"data": [[1.0, 2.0, 3.0, 4.0]]}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib_request.urlopen(req, timeout=10) as r:
        assert r.status == 200
        score = r.headers.get("X-Load-Score")
    assert score is not None and float(score) >= 0.0


def test_load_score_dedupes_shared_engine_across_routes():
    """An engine pool that is both the server's direct POST target
    (pool=) and a registered manager's engine must be counted ONCE in
    the aggregated load score — double-counting inflates X-Load-Score
    and skews a front pool's dispatch away from this host."""
    from deeplearning4j_tpu.obs.metrics import MetricsRegistry
    from deeplearning4j_tpu.parallel import EnginePool

    class _FakeManager:
        def __init__(self, engine):
            self.engine = engine

    conf = (NeuralNetConfiguration.builder().seed(5).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    pool = EnginePool(model=MultiLayerNetwork(conf).init(), replicas=1,
                      workers=1, registry=MetricsRegistry(),
                      name="ls-pool")
    srv = None
    try:
        srv = JsonModelServer(port=0, pool=pool,
                              managers={"m": _FakeManager(pool)},
                              registry=MetricsRegistry(), name="ls-srv")
        assert srv.load_score() == pytest.approx(float(pool.load_score()))
    finally:
        if srv is not None:
            srv._httpd.server_close()
        pool.shutdown(drain=False)


def _raw_ndjson_server(chunks, *, then_close=True):
    """One-shot raw HTTP server: answers any POST with an NDJSON body
    built from ``chunks`` and then drops the connection — the shape of a
    host dying mid-generation-stream."""
    import socket

    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind(("127.0.0.1", 0))
    sock.listen(1)
    port = sock.getsockname()[1]

    def serve():
        conn, _ = sock.accept()
        try:
            conn.settimeout(5)
            data = b""
            while b"\r\n\r\n" not in data:
                data += conn.recv(65536)
            conn.sendall(b"HTTP/1.0 200 OK\r\n"
                         b"Content-Type: application/x-ndjson\r\n\r\n")
            for c in chunks:
                conn.sendall(c)
        finally:
            conn.close()   # abrupt: no done event ever arrives
            sock.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    return port


def test_generate_mid_stream_drop_raises_partial_output():
    """ISSUE 12 satellite: a server dying mid-NDJSON-stream surfaces as
    PartialStreamError carrying the tokens received so far — never a
    silent retry that would re-emit them."""
    from deeplearning4j_tpu.remote import PartialStreamError

    port = _raw_ndjson_server([
        b'{"token": 5, "index": 0}\n',
        b'{"token": 7, "index": 1}\n',
    ])
    client = JsonRemoteInference(f"http://127.0.0.1:{port}/v1/serving",
                                 timeout=10)
    events = []
    with pytest.raises(PartialStreamError) as ei:
        for ev in client.generate([1, 2, 3], max_tokens=8):
            events.append(ev)
    # the two emitted tokens were yielded exactly once and ride the error
    assert [e["token"] for e in events] == [5, 7]
    assert ei.value.tokens == [5, 7]
    assert client.retries == 0, "a broken stream must never retry"


def test_generate_truncated_line_raises_partial_output():
    from deeplearning4j_tpu.remote import PartialStreamError

    port = _raw_ndjson_server([
        b'{"token": 3, "index": 0}\n',
        b'{"token": 9, "ind',     # truncated mid-line
    ])
    client = JsonRemoteInference(f"http://127.0.0.1:{port}/v1/serving",
                                 timeout=10)
    with pytest.raises(PartialStreamError) as ei:
        list(client.generate([1], max_tokens=8))
    assert ei.value.tokens == [3]


def test_health_includes_generate_circuit():
    """ISSUE 10 satellite bugfix: health() must cover the DecodeEngine —
    a tripped generate circuit previously still reported ok/200 and its
    queue depth was missing from queue_depth."""
    from deeplearning4j_tpu.core.resilience import CircuitBreaker

    class StubGenerator:
        """DecodeEngine health surface: circuit_state + stats()."""

        def __init__(self):
            self._breaker = CircuitBreaker()

        @property
        def circuit_state(self):
            return self._breaker.state

        def stats(self):
            return {"queue_depth": 2, "in_flight": 2}

        def drain(self, timeout=None):
            return True

    gen = StubGenerator()
    srv = JsonModelServer(generator=gen).start()
    try:
        payload, code = srv.health()
        assert code == 200 and payload["status"] == "ok"
        assert payload["generate"]["circuit"] == "closed"
        assert payload["queue_depth"] == 2  # generator depth counts now
        for _ in range(5):  # trip the generate circuit
            gen._breaker.record_failure()
        payload, code = srv.health()
        assert code == 503 and payload["status"] == "degraded", payload
        assert payload["generate"]["circuit"] == "open"
    finally:
        srv.stop(drain=False)
