"""JsonModelServer round-trip tests (SURVEY.md §2.2 "Remote inference")."""

import json
import threading
from urllib import request as urllib_request
from urllib.error import HTTPError

import numpy as np
import pytest

from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.remote import JsonModelServer, JsonRemoteInference


@pytest.fixture(scope="module")
def server():
    conf = (NeuralNetConfiguration.builder().seed(5).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    model = MultiLayerNetwork(conf).init()
    srv = JsonModelServer(model, port=0, workers=2).start()
    yield srv, model
    srv.stop()


def test_health(server):
    srv, _ = server
    with urllib_request.urlopen(
            f"http://127.0.0.1:{srv.port}/health") as r:
        assert json.loads(r.read())["status"] == "ok"


def test_predict_matches_local(server):
    srv, model = server
    client = JsonRemoteInference(
        f"http://127.0.0.1:{srv.port}/v1/serving")
    x = np.random.RandomState(0).randn(5, 4).astype(np.float32)
    remote = client.predict(x)
    local = np.asarray(model.output(x))
    np.testing.assert_allclose(remote, local, atol=1e-5)


def test_concurrent_requests_batched(server):
    srv, model = server
    client = JsonRemoteInference(
        f"http://127.0.0.1:{srv.port}/v1/serving")
    rng = np.random.RandomState(1)
    inputs = [rng.randn(2, 4).astype(np.float32) for _ in range(8)]
    results = [None] * 8

    def call(i):
        results[i] = client.predict(inputs[i])

    threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for i in range(8):
        np.testing.assert_allclose(results[i],
                                   np.asarray(model.output(inputs[i])),
                                   atol=1e-5)


def test_bad_request(server):
    srv, _ = server
    req = urllib_request.Request(
        f"http://127.0.0.1:{srv.port}/v1/serving",
        data=b'{"wrong": 1}',
        headers={"Content-Type": "application/json"})
    with pytest.raises(HTTPError) as ei:
        urllib_request.urlopen(req, timeout=10)
    assert ei.value.code == 400


def test_unknown_path(server):
    srv, _ = server
    with pytest.raises(HTTPError) as ei:
        urllib_request.urlopen(
            f"http://127.0.0.1:{srv.port}/nope", timeout=10)
    assert ei.value.code == 404
