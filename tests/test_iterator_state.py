"""Iterator-state protocol (ISSUE 15 tentpole): ``state_dict()`` /
``load_state_dict()`` across the iterator family must give EXACT
mid-epoch resume — a freshly built, identically configured pipeline
repositioned from the snapshot yields bit-identical remaining batches,
across epoch boundaries, under async prefetch run-ahead, through the
sharded assembler, and for augmented image readers at any worker count
(leaning on PR 7's loader-determinism contract)."""

import os
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import (
    AsyncDataSetIterator,
    DataSetIterator,
    ListDataSetIterator,
    MappedDataSetIterator,
    MultipleEpochsIterator,
)


def _data(n=20, f=3):
    x = np.arange(n * f).reshape(n, f).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[np.arange(n) % 2]
    return x, y


def _consume(it, n):
    """n batches with fit_iterator's epoch discipline: reset only when
    exhausted."""
    out = []
    for _ in range(n):
        if not it.has_next():
            it.reset()
        out.append(np.asarray(it.next().features))
    return out


def _assert_streams_equal(a, b):
    assert len(a) == len(b)
    for i, (x1, x2) in enumerate(zip(a, b)):
        np.testing.assert_array_equal(x1, x2, err_msg=f"batch {i}")


class TestListIteratorState:
    def _make(self, shuffle=True):
        x, y = _data()
        return ListDataSetIterator(DataSet(x, y), 4, shuffle=shuffle, seed=3)

    @pytest.mark.parametrize("shuffle", [True, False])
    @pytest.mark.parametrize("consumed", [0, 3, 5, 7, 12])
    def test_resume_matches_uninterrupted(self, shuffle, consumed):
        full = _consume(self._make(shuffle), 15)
        it1 = self._make(shuffle)
        _consume(it1, consumed)
        state = it1.state_dict()
        it2 = self._make(shuffle)
        it2.load_state_dict(state)
        _assert_streams_equal(full[consumed:], _consume(it2, 15 - consumed))

    def test_state_at_exact_epoch_boundary(self):
        # 20 rows / batch 4 -> 5 batches per epoch; cursor at exhaustion
        it1 = self._make()
        _consume(it1, 5)
        it2 = self._make()
        it2.load_state_dict(it1.state_dict())
        assert not it2.has_next()  # epoch over; next epoch via reset()
        _assert_streams_equal(_consume(self._make(), 8)[5:], _consume(it2, 3))

    def test_state_is_jsonable(self):
        import json

        it = self._make()
        _consume(it, 3)
        json.loads(json.dumps(it.state_dict()))


class TestAsyncIteratorState:
    def _make(self):
        x, y = _data(32)
        return AsyncDataSetIterator(
            ListDataSetIterator(DataSet(x, y), 4, shuffle=True, seed=9),
            queue_size=6)

    def test_runahead_not_counted(self):
        """The producer prefetches ahead of the consumer; the snapshot
        must record the CONSUMER cursor, not the producer's."""
        full = _consume(self._make(), 16)
        it1 = self._make()
        got = _consume(it1, 3)
        deadline = time.monotonic() + 5.0
        while (it1.stats()["queue_depth"] < 4
               and time.monotonic() < deadline):
            time.sleep(0.01)  # let the producer run well ahead
        state = it1.state_dict()
        assert state["batches"] == 3, state
        it1.close()
        it2 = self._make()
        it2.load_state_dict(state)
        _assert_streams_equal(full[:3], got)
        _assert_streams_equal(full[3:], _consume(it2, 13))
        it2.close()

    def test_resume_across_epoch_boundary(self):
        full = _consume(self._make(), 12)  # 8 per epoch
        it1 = self._make()
        _consume(it1, 9)
        state = it1.state_dict()
        it1.close()
        it2 = self._make()
        it2.load_state_dict(state)
        _assert_streams_equal(full[9:], _consume(it2, 3))
        it2.close()


class TestWrapperDelegation:
    def test_mapped_delegates(self):
        x, y = _data()

        def make():
            return MappedDataSetIterator(
                ListDataSetIterator(DataSet(x, y), 4, shuffle=True, seed=1),
                feature_fn=lambda f: f * 2.0)

        full = _consume(make(), 8)
        it1 = make()
        _consume(it1, 3)
        it2 = make()
        it2.load_state_dict(it1.state_dict())
        _assert_streams_equal(full[3:], _consume(it2, 5))

    def test_multiple_epochs_carries_own_counter(self):
        x, y = _data()

        def make():
            return MultipleEpochsIterator(
                ListDataSetIterator(DataSet(x, y), 4, shuffle=True, seed=1),
                epochs=3)

        it1 = make()
        for _ in range(7):
            it1.next()
        state = it1.state_dict()
        assert state["multi_epoch"] == 1  # crossed one boundary
        it2 = make()
        it2.load_state_dict(state)
        rest1 = [np.asarray(it1.next().features) for _ in range(4)]
        rest2 = [np.asarray(it2.next().features) for _ in range(4)]
        _assert_streams_equal(rest1, rest2)

    def test_sharded_delegates(self):
        from deeplearning4j_tpu.data.sharded import ShardedDataSetIterator
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from deeplearning4j_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(data=len(jax.devices()))
        sh = NamedSharding(mesh._mesh if hasattr(mesh, "_mesh") else mesh,
                           PartitionSpec("data"))
        x, y = _data(32, 4)

        def make():
            return ShardedDataSetIterator(
                ListDataSetIterator(DataSet(x, y), 8, shuffle=True, seed=2),
                sh, process_count=1)

        full = [np.asarray(b.features) for b in
                (lambda it: [it.next() for _ in range(4)])(make())]
        it1 = make()
        it1.next()
        it2 = make()
        it2.load_state_dict(it1.state_dict())
        rest = [np.asarray(it2.next().features) for _ in range(3)]
        _assert_streams_equal(full[1:], rest)

    def test_sharded_state_protocol_pins_global_batch(self):
        """ISSUE 16: the sharded wrapper's sidecar names the GLOBAL batch
        — the width-invariance contract of elastic resize — and a restore
        into a pipeline with a different global batch is refused rather
        than silently bending the trajectory."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from deeplearning4j_tpu.data.sharded import ShardedDataSetIterator
        from deeplearning4j_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(data=len(jax.devices()))
        sh = NamedSharding(mesh._mesh if hasattr(mesh, "_mesh") else mesh,
                           PartitionSpec("data"))
        x, y = _data(32, 4)
        it = ShardedDataSetIterator(
            ListDataSetIterator(DataSet(x, y), 8, shuffle=True, seed=2),
            sh, process_count=1)
        state = it.state_dict()
        assert state["global_batch"] == it.batch_size() == 8
        other = ShardedDataSetIterator(
            ListDataSetIterator(DataSet(x, y), 8, shuffle=True, seed=2),
            sh, process_count=2)  # 8 local x 2 hosts -> global 16
        with pytest.raises(ValueError, match="global batch"):
            other.load_state_dict(state)

    def test_base_raises_clearly(self):
        class Bare(DataSetIterator):
            pass

        with pytest.raises(NotImplementedError, match="Bare"):
            Bare().state_dict()
        with pytest.raises(NotImplementedError, match="Bare"):
            Bare().load_state_dict({})


def _write_ppm(path, arr):
    h, w, _ = arr.shape
    with open(path, "wb") as f:
        f.write(f"P6 {w} {h} 255\n".encode() + arr.tobytes())


class TestImageReaderState:
    """ImageRecordReader-backed pipelines: the per-pass seed draws are
    replayed on restore, so augmented epochs resume bit-identically at
    any worker count — and skipped images are never decoded."""

    def _tree(self, tmp_path, n=10, size=8):
        rng = np.random.RandomState(0)
        for i in range(n):
            d = tmp_path / "ab"[i % 2]
            d.mkdir(exist_ok=True)
            _write_ppm(str(d / f"{i}.ppm"),
                       rng.randint(0, 255, (size, size, 3), dtype=np.uint8))
        return str(tmp_path)

    def _make(self, root, workers=1):
        from deeplearning4j_tpu.data.image_transform import FlipImageTransform
        from deeplearning4j_tpu.data.records import (
            ImageRecordReader, RecordReaderDataSetIterator)

        reader = ImageRecordReader(
            8, 8, 3, root=root, transform=FlipImageTransform(), seed=5,
            workers=workers, shuffle=True)
        return RecordReaderDataSetIterator(reader, 2, num_classes=2)

    @pytest.mark.parametrize("workers", [1, 2])
    def test_mid_second_epoch_resume(self, tmp_path, workers):
        root = self._tree(tmp_path)
        full = _consume(self._make(root), 9)  # 5 batches/epoch
        it1 = self._make(root, workers=workers)
        _consume(it1, 7)  # 2 batches into epoch 2
        state = it1.state_dict()
        assert state == {"epoch": 2, "batches": 2}
        it2 = self._make(root, workers=workers)
        it2.load_state_dict(state)
        _assert_streams_equal(full[7:], _consume(it2, 2))

    def test_skip_does_not_decode(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.data import records as records_mod

        root = self._tree(tmp_path)
        it1 = self._make(root)
        _consume(it1, 3)
        state = it1.state_dict()
        it2 = self._make(root)
        loaded = []
        orig = records_mod.ImageRecordReader._load

        def counting_load(self, path, rng=None):
            loaded.append(path)
            return orig(self, path, rng=rng)

        monkeypatch.setattr(records_mod.ImageRecordReader, "_load",
                            counting_load)
        it2.load_state_dict(state)
        it2.next()
        # 6 records skipped FREE; only the consumed batch (+ lookahead
        # window) decoded
        assert loaded and all("ppm" in p for p in loaded)
        assert len(loaded) <= 4, loaded

    def test_generic_reader_skip_discards(self):
        from deeplearning4j_tpu.data.records import (
            CollectionRecordReader, RecordReaderDataSetIterator)

        recs = [[float(i), float(i % 2)] for i in range(12)]

        def make():
            return RecordReaderDataSetIterator(
                CollectionRecordReader(recs), 3, num_classes=2)

        full = _consume(make(), 4)
        it1 = make()
        _consume(it1, 2)
        it2 = make()
        it2.load_state_dict(it1.state_dict())
        _assert_streams_equal(full[2:], _consume(it2, 2))


class TestFetcherInheritsState:
    def test_cifar_iterator_resumes(self):
        from deeplearning4j_tpu.data.fetchers import Cifar10DataSetIterator

        def make():
            return Cifar10DataSetIterator(8, num_examples=32, seed=4)

        full = _consume(make(), 6)
        it1 = make()
        _consume(it1, 2)
        it2 = make()
        it2.load_state_dict(it1.state_dict())
        _assert_streams_equal(full[2:], _consume(it2, 4))


class TestSolverFitIterator:
    """The resume-aware consumption loops (Solver/GraphSolver/
    DistributedTrainer fit_iterator): start at the iterator's current
    position, reset only on exhaustion, and a mid-epoch-restored
    pipeline reproduces the uninterrupted trajectory bit-exactly
    (the in-process half of the chaos contract)."""

    def _model(self, seed=1):
        from deeplearning4j_tpu.nn import (
            Activation, InputType, LossFunction, NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
        from deeplearning4j_tpu.train.updaters import Adam

        conf = (NeuralNetConfiguration.builder().seed(seed)
                .updater(Adam(0.01)).list()
                .layer(DenseLayer(n_out=6, activation=Activation.TANH))
                .layer(OutputLayer(n_out=2, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(3)).build())
        return MultiLayerNetwork(conf).init()

    def _flat(self, m):
        from jax.flatten_util import ravel_pytree

        f, _ = ravel_pytree(m.params)
        return np.asarray(f)

    def _it(self):
        x, y = _data(16)
        return ListDataSetIterator(DataSet(x, y), 4, shuffle=True, seed=5)

    def test_mid_epoch_resume_bit_exact(self):
        from deeplearning4j_tpu.train.solver import Solver

        m1 = self._model()
        Solver(m1).fit_iterator(self._it(), epochs=3)
        assert m1.iteration_count == 12 and m1.epoch_count == 3

        # interrupted at iteration 6, "resumed" via the state protocol
        m2 = self._model()
        s2 = Solver(m2)
        it = self._it()
        s2.fit_iterator(it, epochs=1)
        if not it.has_next():
            it.reset()
        for _ in range(2):  # 2 batches into epoch 2
            ds = it.next()
            s2.fit_batch(ds.features, ds.labels)
            m2.iteration_count += 1
        it2 = self._it()
        it2.load_state_dict(it.state_dict())
        s2.fit_iterator(it2, epochs=2)  # finish epoch 2 + epoch 3
        assert m2.iteration_count == 12 and m2.epoch_count == 3
        np.testing.assert_array_equal(self._flat(m1), self._flat(m2))

    def test_graph_solver_fit_iterator(self):
        from deeplearning4j_tpu.nn import (
            Activation, InputType, NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.train.graph_solver import GraphSolver
        from deeplearning4j_tpu.train.updaters import Adam

        conf = (NeuralNetConfiguration.builder().seed(2).updater(Adam(1e-2))
                .graph_builder().add_inputs("in")
                .add_layer("d", DenseLayer(n_out=6,
                                           activation=Activation.TANH), "in")
                .add_layer("out", OutputLayer(n_out=2), "d")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(3)).build())
        model = ComputationGraph(conf).init()
        solver = GraphSolver(model)
        score = solver.fit_iterator(self._it(), epochs=2)
        assert np.isfinite(score)
        assert model.iteration_count == 8 and model.epoch_count == 2


class TestRngStateRoundTrip:
    def test_stream_continues_exactly(self):
        import jax

        from deeplearning4j_tpu.core.rng import RngState

        r = RngState(42)
        for _ in range(5):
            r.next_key()
        state = r.state_dict()
        expect = [np.asarray(jax.random.key_data(r.next_key()))
                  for _ in range(3)]
        r2 = RngState(0)
        r2.load_state_dict(state)
        got = [np.asarray(jax.random.key_data(r2.next_key()))
               for _ in range(3)]
        for e, g in zip(expect, got):
            np.testing.assert_array_equal(e, g)
        assert r2.seed == 42
