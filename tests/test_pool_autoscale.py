"""EnginePool dynamic membership + PoolAutoscaler (ISSUE 19):
add_replica/remove_replica are drain-safe under concurrent dispatch,
the replica gauge and stats() track membership live (the PR-19 fix for
the construction-time-only gauge), removal refuses to empty a
partition, and the autoscaler grows/shrinks on load-score EWMA trends
with cooldown. All CPU, fake clocks for the controller."""

import threading
import types

import numpy as np
import pytest

from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.obs import MetricsRegistry
from deeplearning4j_tpu.parallel.pool import EnginePool
from deeplearning4j_tpu.serving import PoolAutoscaler

X = np.linspace(-1.0, 1.0, 4, dtype=np.float32).reshape(1, 4)


def _model(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    return MultiLayerNetwork(conf).init()


def _pool(reg, **kw):
    kw.setdefault("replicas", 2)
    kw.setdefault("workers", 1)
    kw.setdefault("batch_limit", 4)
    return EnginePool(model=_model(), registry=reg, name="dyn", **kw)


def _fake_load(engine, value):
    engine._fake_load = value
    engine.load_score = types.MethodType(
        lambda self: getattr(self, "_fake_load", 0.0), engine)


def test_membership_changes_update_gauge_and_stats_live():
    reg = MetricsRegistry()
    pool = _pool(reg)
    g = reg.get("dl4j_tpu_pool_replicas").labels("dyn")
    try:
        assert g.value == 2.0
        added = pool.add_replica()
        assert added.name == "dyn-r2"
        assert added.model_version == pool.model_version
        assert g.value == 3.0
        pool.output(X)  # dispatchable immediately
        removed = pool.remove_replica("dyn-r0", drain_timeout=10.0)
        assert removed.name == "dyn-r0"
        assert g.value == 2.0
        s = pool.stats()
        assert s["replica_count"] == 2
        # live-membership views: the removed replica drops out of every
        # block even though its counter children survive
        assert set(s["dispatched"]) == {"dyn-r1", "dyn-r2"}
        assert set(s["load_scores"]) == {"dyn-r1", "dyn-r2"}
        assert "dyn-r0" not in s["dispatch_errors"]
        # duplicate names are refused
        with pytest.raises(ValueError, match="already in the pool"):
            pool.add_replica(pool.replicas[0])
    finally:
        pool.shutdown(drain=False)


def test_remove_refuses_last_replica_and_unknown_name():
    reg = MetricsRegistry()
    pool = _pool(reg, replicas=1)
    try:
        with pytest.raises(ValueError, match="last inference replica"):
            pool.remove_replica("dyn-r0")
        with pytest.raises(ValueError, match="no replica named"):
            pool.remove_replica("ghost")
    finally:
        pool.shutdown(drain=False)


def test_membership_churn_under_concurrent_dispatch_loses_nothing():
    """The drain-safety criterion: clients hammer the pool while
    replicas are added and removed; every request succeeds (a dispatch
    racing a removal falls over to the next candidate)."""
    reg = MetricsRegistry()
    pool = _pool(reg)
    stop = threading.Event()
    errors, served = [], [0]
    try:
        def client():
            while not stop.is_set():
                try:
                    pool.output(X, timeout=30.0)
                    served[0] += 1
                except Exception as e:
                    errors.append(e)

        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        for _ in range(3):  # churn membership under fire
            e = pool.add_replica()
            pool.remove_replica(e.name, drain_timeout=10.0)
        victim = pool.replicas[0].name
        pool.add_replica()
        pool.remove_replica(victim, drain_timeout=10.0)
        stop.set()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
        assert served[0] > 0
    finally:
        pool.shutdown(drain=False)


def test_added_replica_serves_current_version_after_swap():
    reg = MetricsRegistry()
    pool = _pool(reg)
    try:
        pool.swap_model(_model(9), version="7")
        added = pool.add_replica()
        assert added.model_version == "7"
        # pool-wide swap still validates against the LIVE count
        sv = pool.make_servable(_model(3), version="8")
        pool.swap(sv)
        assert all(e.model_version == "8" for e in pool.replicas)
    finally:
        pool.shutdown(drain=False)


def test_autoscaler_grows_shrinks_with_cooldown_and_counters():
    clk = [0.0]
    reg = MetricsRegistry()
    pool = _pool(reg)
    sc = PoolAutoscaler(pool, min_replicas=1, max_replicas=3,
                        high_load=1.0, low_load=0.2, halflife_s=0.001,
                        cooldown_s=5.0, clock=lambda: clk[0],
                        registry=reg)
    try:
        for e in pool.replicas:
            _fake_load(e, 4.0)
        clk[0] = 10.0
        obs = sc.tick()
        assert obs["action"] == "grow" and len(pool.replicas) == 3
        clk[0] = 12.0  # inside cooldown: no thrash
        for e in pool.replicas:
            _fake_load(e, 4.0)
        assert sc.tick()["action"] == "cooldown"
        clk[0] = 16.0  # at max: hold even though hot
        assert sc.tick()["action"] == "hold"
        for e in pool.replicas:
            _fake_load(e, 0.0)
        clk[0] = 30.0
        obs = sc.tick()
        assert obs["action"] == "shrink" and len(pool.replicas) == 2
        clk[0] = 40.0
        assert sc.tick()["action"] == "shrink"
        clk[0] = 50.0  # at min: hold
        assert sc.tick()["action"] == "hold"
        assert len(pool.replicas) == 1
        c = reg.get("dl4j_tpu_pool_autoscale_total")
        assert c.labels("dyn", "grow").value == 1.0
        assert c.labels("dyn", "shrink").value == 2.0
        # the pool still serves after scaling down
        assert np.asarray(pool.output(X)).shape == (1, 3)
    finally:
        pool.shutdown(drain=False)


def test_autoscaler_validates_bounds():
    reg = MetricsRegistry()
    pool = _pool(reg)
    try:
        with pytest.raises(ValueError):
            PoolAutoscaler(pool, min_replicas=0)
        with pytest.raises(ValueError):
            PoolAutoscaler(pool, min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError):
            PoolAutoscaler(pool, high_load=1.0, low_load=1.0)
    finally:
        pool.shutdown(drain=False)
