"""Decode-state handoff tests (ISSUE 17 satellite): the serialized
per-request cache slice round-trips through bytes exactly, and a decode
stream restored from a shipped handoff is token-identical to unbroken
local generation — fp and int8 (scale planes on the wire), plain and
speculative (including the speculative-rewind path over paged blocks).

PrefillEngines and the fp decode engines are module-scoped: every
engine pays real jit compiles, and the handoff path exercises the same
compiled programs whichever test runs it."""

import numpy as np
import pytest

from deeplearning4j_tpu.model.zoo import TransformerLM
from deeplearning4j_tpu.obs.metrics import MetricsRegistry
from deeplearning4j_tpu.parallel.decode import DecodeEngine
from deeplearning4j_tpu.serving.disagg import (PrefillEngine,
                                               deserialize_handoff,
                                               serialize_handoff)

MAX_LEN = 24
PROMPTS = [[1, 2, 3], [4, 5, 6, 7, 8]]


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(vocab_size=23, hidden=32, n_layers=2,
                         n_heads=4, max_len=MAX_LEN).init()


@pytest.fixture(scope="module")
def draft():
    return TransformerLM(vocab_size=23, hidden=16, n_layers=1,
                         n_heads=2, max_len=MAX_LEN).init()


def _engine(lm, **kw):
    kw.setdefault("registry", MetricsRegistry())
    return DecodeEngine(lm, max_len=MAX_LEN, **kw)


@pytest.fixture(scope="module")
def pe(lm):
    return PrefillEngine(lm, max_len=MAX_LEN, registry=MetricsRegistry())


@pytest.fixture(scope="module")
def pe8(lm):
    return PrefillEngine(lm, max_len=MAX_LEN, cache_dtype="int8",
                         registry=MetricsRegistry())


@pytest.fixture(scope="module")
def paged_eng(lm):
    eng = _engine(lm, slots=4, block_size=4)
    yield eng
    eng.shutdown()


@pytest.fixture(scope="module")
def static_eng(lm):
    eng = _engine(lm, slots=4)
    yield eng
    eng.shutdown()


def _run_local(eng, prompts, **kw):
    hs = [eng.submit(p, max_tokens=6, **kw) for p in prompts]
    return [h.result(timeout=120) for h in hs]


def _run_handoff(pe, eng, prompts, **kw):
    out = []
    for p in prompts:
        wire = serialize_handoff(pe.prefill(p, max_tokens=6, **kw))
        assert isinstance(wire, bytes)
        h = eng.submit_prefilled(deserialize_handoff(wire))
        out.append(h.result(timeout=120))
    return out


class TestWireFormat:
    def test_round_trip_exact(self, pe):
        ho = pe.prefill([3, 1, 4, 1, 5], max_tokens=6, seed=9,
                        greedy=False, temperature=0.8, top_k=4)
        back = deserialize_handoff(serialize_handoff(ho))
        assert back["prompt"] == ho["prompt"]
        assert back["first_token"] == ho["first_token"]
        assert back["pos"] == 5
        assert back["cache_dtype"] == ho["cache_dtype"]
        assert back["sampling"]["seed"] == 9
        assert back["sampling"]["greedy"] is False
        assert set(back["layers"]) == set(ho["layers"])
        for name, planes in ho["layers"].items():
            for key, arr in planes.items():
                got = back["layers"][name][key]
                assert got.dtype == np.asarray(arr).dtype
                # trimmed to used positions only
                assert got.shape[2] == 5
                np.testing.assert_array_equal(got, np.asarray(arr))

    def test_round_trip_int8_scale_planes(self, pe8):
        ho = pe8.prefill([1, 2, 3, 4], max_tokens=4)
        back = deserialize_handoff(serialize_handoff(ho))
        planes = next(iter(back["layers"].values()))
        assert planes["cache_k"].dtype == np.int8
        assert "cache_k_scale" in planes and "cache_v_scale" in planes
        assert planes["cache_k_scale"].dtype == np.float32
        np.testing.assert_array_equal(
            planes["cache_k"],
            np.asarray(next(iter(ho["layers"].values()))["cache_k"]))

    def test_truncated_payload_rejected(self, pe):
        wire = serialize_handoff(pe.prefill([1, 2], max_tokens=2))
        with pytest.raises(Exception):
            deserialize_handoff(wire[:-10])

    def test_version_gate(self):
        import json

        bad = json.dumps({"version": 99, "tensors": []}).encode() + b"\n"
        with pytest.raises(ValueError, match="version"):
            deserialize_handoff(bad)


class TestHandoffIdentity:
    def test_fp_paged(self, pe, paged_eng):
        exp = _run_local(paged_eng, PROMPTS, seed=7)
        assert _run_handoff(pe, paged_eng, PROMPTS, seed=7) == exp

    def test_fp_static(self, pe, static_eng):
        """Handoffs also restore into a STATIC-layout decode engine."""
        exp = _run_local(static_eng, PROMPTS)
        assert _run_handoff(pe, static_eng, PROMPTS) == exp

    def test_int8_paged(self, lm, pe8):
        eng = _engine(lm, slots=4, cache_dtype="int8", block_size=4)
        try:
            exp = _run_local(eng, PROMPTS)
            assert _run_handoff(pe8, eng, PROMPTS) == exp
        finally:
            eng.shutdown()

    def test_sampled_stream_identity(self, pe, paged_eng):
        kw = dict(greedy=False, temperature=0.9, top_k=5, seed=21)
        exp = _run_local(paged_eng, PROMPTS, **kw)
        assert _run_handoff(pe, paged_eng, PROMPTS, **kw) == exp

    def test_speculative_rewind_over_paged_blocks(self, lm, draft, pe):
        """A speculative decode engine receiving the handoff re-runs the
        draft prefill locally and its rewind path (rejected proposals)
        stays token-identical over paged blocks."""
        eng = _engine(lm, slots=4, draft_model=draft, speculative_k=3,
                      block_size=4)
        try:
            exp = _run_local(eng, PROMPTS, speculative_k=3)
            assert _run_handoff(pe, eng, PROMPTS,
                                speculative_k=3) == exp
        finally:
            eng.shutdown()


class TestHandoffValidation:
    def test_cache_dtype_mismatch_rejected(self, pe8, paged_eng):
        ho = pe8.prefill([1, 2, 3], max_tokens=4)
        with pytest.raises(ValueError, match="cache_dtype"):
            paged_eng.submit_prefilled(ho)  # fp engine

    def test_pos_prompt_mismatch_rejected(self, pe, paged_eng):
        ho = dict(pe.prefill([1, 2, 3], max_tokens=4), pos=2)
        with pytest.raises(ValueError, match="pos"):
            paged_eng.submit_prefilled(ho)

    def test_missing_layer_fails_request(self, pe, paged_eng):
        ho = pe.prefill([1, 2, 3], max_tokens=4)
        name = next(iter(ho["layers"]))
        broken = dict(ho, layers={k: v for k, v in ho["layers"].items()
                                  if k != name})
        term = list(paged_eng.submit_prefilled(broken)
                    .events(timeout=60))[-1]
        assert term["reason"] == "failed"
        assert name in term.get("error", "")
