"""libdl4jtpu native runtime vs pure-NumPy fallback parity.

Mirrors the reference's CPU-vs-GPU kernel cross-checks (SURVEY.md §4): the
same inputs must produce the same outputs through the C++ path and the
fallback path. Native build happens on first use (native/build.sh)."""

import os
import subprocess

import numpy as np
import pytest

from deeplearning4j_tpu import native

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def native_lib():
    if not native.available():
        subprocess.run(["sh", os.path.join(_REPO, "native", "build.sh")],
                       check=True, capture_output=True)
        native._tried = False  # retry load
    if not native.available():
        pytest.skip("native toolchain unavailable")
    return native


def _fallback(fn, *args, **kw):
    """Run a native.py function with the library disabled."""
    saved = native._lib
    native._lib = None
    tried = native._tried
    native._tried = True
    os.environ["DL4J_TPU_DISABLE_NATIVE"] = "1"
    try:
        return fn(*args, **kw)
    finally:
        del os.environ["DL4J_TPU_DISABLE_NATIVE"]
        native._lib = saved
        native._tried = tried


def test_threshold_encode_decode_roundtrip(native_lib):
    rng = np.random.RandomState(0)
    grad = rng.randn(1000).astype(np.float32) * 0.01
    grad_native = grad.copy()
    grad_fb = grad.copy()
    thr = 0.012

    enc_n = native.threshold_encode(grad_native, thr)
    enc_f = _fallback(native.threshold_encode, grad_fb, thr)
    np.testing.assert_array_equal(enc_n, enc_f)
    np.testing.assert_allclose(grad_native, grad_fb, atol=1e-7)  # residuals

    tgt_n = np.zeros(1000, np.float32)
    tgt_f = np.zeros(1000, np.float32)
    native.threshold_decode(enc_n, thr, tgt_n)
    _fallback(native.threshold_decode, enc_f, thr, tgt_f)
    np.testing.assert_allclose(tgt_n, tgt_f, atol=1e-7)
    # encode(x) then decode ≈ clip-to-threshold of original signal
    mask = np.abs(grad) > thr
    np.testing.assert_allclose(tgt_n[mask],
                               np.sign(grad[mask]) * thr, atol=1e-6)
    assert not np.any(tgt_n[~mask])


def test_threshold_encode_overflow_returns_none(native_lib):
    grad = np.ones(100, np.float32)
    assert native.threshold_encode(grad.copy(), 0.5, max_elements=10) is None
    assert _fallback(native.threshold_encode, grad.copy(), 0.5,
                     max_elements=10) is None


def test_bitmap_encode_decode(native_lib):
    rng = np.random.RandomState(1)
    grad = rng.randn(257).astype(np.float32)  # odd size exercises padding
    thr = 0.8
    gn, gf = grad.copy(), grad.copy()
    bm_n, cnt_n = native.bitmap_encode(gn, thr)
    bm_f, cnt_f = _fallback(native.bitmap_encode, gf, thr)
    assert cnt_n == cnt_f
    np.testing.assert_array_equal(bm_n, bm_f)
    np.testing.assert_allclose(gn, gf, atol=1e-7)
    tgt_n = np.zeros(257, np.float32)
    tgt_f = np.zeros(257, np.float32)
    native.bitmap_decode(bm_n, 257, thr, tgt_n)
    _fallback(native.bitmap_decode, bm_f, 257, thr, tgt_f)
    np.testing.assert_allclose(tgt_n, tgt_f, atol=1e-7)


def test_parse_csv(native_lib):
    text = b"a,b,c\n1.5,2,3\n4,-5.25,6e2\n"
    out = native.parse_csv(text, skip_rows=1)
    expect = np.array([[1.5, 2, 3], [4, -5.25, 600]], np.float32)
    np.testing.assert_allclose(out, expect)
    np.testing.assert_allclose(_fallback(native.parse_csv, text,
                                         skip_rows=1), expect)


def test_parse_csv_ragged_raises(native_lib):
    with pytest.raises(ValueError):
        native.parse_csv(b"1,2\n3,4,5\n")
    with pytest.raises(ValueError):
        _fallback(native.parse_csv, b"1,2\n3,4,5\n")


def test_parse_idx(native_lib):
    # rank-3 IDX: 2 images of 3x2
    header = bytes([0, 0, 0x08, 3]) + (2).to_bytes(4, "big") \
        + (3).to_bytes(4, "big") + (2).to_bytes(4, "big")
    data = bytes(range(12))
    buf = header + data
    out = native.parse_idx(buf, scale=1 / 255.0)
    assert out.shape == (2, 3, 2)
    np.testing.assert_allclose(out.reshape(-1),
                               np.arange(12, dtype=np.float32) / 255.0)
    np.testing.assert_allclose(_fallback(native.parse_idx, buf,
                                         scale=1 / 255.0), out)


def test_decode_netpbm(native_lib):
    w, h = 4, 3
    pix = bytes(range(w * h * 3))
    buf = b"P6\n# comment\n4 3\n255\n" + pix
    img = native.decode_netpbm(buf)
    assert img.shape == (3, 4, 3)
    np.testing.assert_allclose(
        img.reshape(-1), np.arange(36, dtype=np.float32) / 255.0, atol=1e-7)
    np.testing.assert_allclose(_fallback(native.decode_netpbm, buf), img)
    gray = b"P5\n2 2\n255\n" + bytes([0, 128, 255, 64])
    g = native.decode_netpbm(gray)
    assert g.shape == (2, 2, 1)
    np.testing.assert_allclose(_fallback(native.decode_netpbm, gray), g)


def test_resize_bilinear(native_lib):
    rng = np.random.RandomState(2)
    img = rng.rand(7, 5, 3).astype(np.float32)
    out_n = native.resize_bilinear(img, 14, 10)
    out_f = _fallback(native.resize_bilinear, img, 14, 10)
    assert out_n.shape == (14, 10, 3)
    np.testing.assert_allclose(out_n, out_f, atol=1e-5)
    # identity resize is exact
    np.testing.assert_allclose(native.resize_bilinear(img, 7, 5), img,
                               atol=1e-6)


def test_normalize_hwc(native_lib):
    rng = np.random.RandomState(3)
    img = rng.rand(4, 4, 3).astype(np.float32)
    mean = [0.485, 0.456, 0.406]
    std = [0.229, 0.224, 0.225]
    out_n = native.normalize_hwc(img.copy(), mean, std)
    out_f = _fallback(native.normalize_hwc, img.copy(), mean, std)
    np.testing.assert_allclose(out_n, out_f, atol=1e-6)
    np.testing.assert_allclose(out_n, (img - mean) / std, atol=1e-6)


def test_version(native_lib):
    assert native._load().dl4j_native_version() == 1


def test_threshold_encode_overflow_leaves_grad_untouched(native_lib):
    grad = np.ones(100, np.float32)
    g = grad.copy()
    assert native.threshold_encode(g, 0.5, max_elements=10) is None
    np.testing.assert_array_equal(g, grad)  # no partial residual subtraction


def test_parse_csv_blank_lines_skipped(native_lib):
    text = b"1,2\n   \n3,4\n"
    expect = np.array([[1, 2], [3, 4]], np.float32)
    np.testing.assert_allclose(native.parse_csv(text), expect)
    np.testing.assert_allclose(_fallback(native.parse_csv, text), expect)


def test_parse_csv_garbage_rejected(native_lib):
    for bad in (b"1.5abc,2\n3,4\n", b"1,,2\n"):
        with pytest.raises(ValueError):
            native.parse_csv(bad)
        with pytest.raises(ValueError):
            _fallback(native.parse_csv, bad)


def test_netpbm_16bit_rejected_both_paths(native_lib):
    buf = b"P5\n2 2\n65535\n" + bytes(8)
    with pytest.raises(ValueError):
        native.decode_netpbm(buf)
    with pytest.raises(ValueError):
        _fallback(native.decode_netpbm, buf)


def test_noncontiguous_inputs_rejected(native_lib):
    grad = np.ones((10, 10), np.float32)
    with pytest.raises(ValueError):
        native.threshold_encode(grad[:, ::2], 0.5)
    with pytest.raises(ValueError):
        native.threshold_decode(np.array([1], np.int32), 0.5, grad.T)
    with pytest.raises(ValueError):
        native.bitmap_encode(grad[::2, ::2], 0.5)


def test_threshold_decode_skips_corrupt_entries(native_lib):
    tgt_n = np.zeros(4, np.float32)
    tgt_f = np.zeros(4, np.float32)
    enc = np.array([0, 2, 99, -99999], np.int32)  # 0 and out-of-range corrupt
    native.threshold_decode(enc, 0.5, tgt_n)
    _fallback(native.threshold_decode, enc, 0.5, tgt_f)
    np.testing.assert_allclose(tgt_n, [0, 0.5, 0, 0])
    np.testing.assert_allclose(tgt_f, tgt_n)


def test_parse_csv_whitespace_field_rejected(native_lib):
    with pytest.raises(ValueError):
        native.parse_csv(b"1, ,3\n")
    with pytest.raises(ValueError):
        _fallback(native.parse_csv, b"1, ,3\n")


def test_parse_idx_truncated_rejected_both_paths(native_lib):
    for bad in (bytes([0, 0, 0x08, 3]),
                bytes([0, 0, 0x08, 1]) + (10).to_bytes(4, "big") + bytes(3)):
        with pytest.raises(ValueError):
            native.parse_idx(bad)
        with pytest.raises(ValueError):
            _fallback(native.parse_idx, bad)
